"""Flush-time program verifier (the ``RAMBA_VERIFY`` entry point).

Modes (read from the environment on every flush, so tests can toggle):

* unset / ``0`` / ``off``      — verifier disabled (zero cost).
* ``1`` / ``strict``           — error findings raise
  :class:`~ramba_tpu.analyze.findings.ProgramVerificationError` before the
  program is compiled.  This is the CI mode.
* any other value (``warn``)   — findings are emitted but nothing raises;
  error findings route the flush down the degradation ladder instead
  (``fuser._execute_resilient(skip_fused=True)``: no monolithic compile,
  no leaf donation).

Rule selection: ``RAMBA_VERIFY_RULES`` (comma whitelist) and
``RAMBA_VERIFY_SKIP`` (comma blacklist) filter :data:`rules.RULES`.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, List, MutableMapping, Optional, Sequence, Tuple

from ramba_tpu.analyze import rules as _rules
from ramba_tpu.analyze.findings import Finding
from ramba_tpu.observe import events as _events
from ramba_tpu.observe import registry as _registry

_OFF = ("", "0", "off", "false", "no")
_STRICT = ("1", "strict", "error", "errors")


def mode() -> str:
    """Current verifier mode: ``"off"``, ``"warn"``, or ``"strict"``."""
    v = (os.environ.get("RAMBA_VERIFY") or "").strip().lower()
    if v in _OFF:
        return "off"
    if v in _STRICT:
        return "strict"
    return "warn"


def enabled_rules() -> List[str]:
    """Rule names to run, after RAMBA_VERIFY_RULES/_SKIP filtering."""
    names = list(_rules.RULES)
    only = os.environ.get("RAMBA_VERIFY_RULES")
    if only:
        want = {s.strip() for s in only.split(",") if s.strip()}
        names = [n for n in names if n in want]
    skip = os.environ.get("RAMBA_VERIFY_SKIP")
    if skip:
        drop = {s.strip() for s in skip.split(",")}
        names = [n for n in names if n not in drop]
    return names


@dataclasses.dataclass
class ProgramView:
    """Everything a rule may inspect about one program.

    Offline lint supplies only ``program``/``donate``/``owners`` (rules
    requiring the live expression graph see empty ``exprs`` and no-op);
    the flush-time verifier supplies all fields.  The ``key_fn`` /
    ``fingerprint`` / ``key_registry`` overrides parameterize the
    cache-collision check for tests and recorded traces; None means
    "use the live fuser's".
    """

    program: Any = None
    leaves: Sequence[Any] = ()
    exprs: Sequence[Any] = ()
    donate: Tuple[int, ...] = ()
    owners: Sequence[int] = ()
    seg_size: int = 0
    key_fn: Optional[Callable[[Any, tuple], Any]] = None
    fingerprint: Optional[Any] = None
    key_registry: Optional[MutableMapping[Any, Any]] = None
    # result-memoization audit surface: the flush's core/memo.py plan
    # (memo-safety rule input) and the canonical-hash collision registry
    # override (None means the process-wide one in rules.py)
    memo_plan: Any = None
    canon_registry: Optional[MutableMapping[str, str]] = None
    # compile-class audit surface: the flush's compile/classes.py bucket
    # plan (compile-class rule input); None = exact-shape compile
    class_plan: Any = None


def verify_program(
    view: ProgramView, rule_names: Optional[Sequence[str]] = None
) -> List[Finding]:
    """Run the selected rules over ``view``.  A crashing rule yields an
    ``internal-error`` warning finding rather than taking the flush down —
    the verifier must never be less reliable than the code it checks."""
    names = enabled_rules() if rule_names is None else list(rule_names)
    out: List[Finding] = []
    for name in names:
        fn = _rules.RULES.get(name)
        if fn is None:
            continue
        try:
            out.extend(fn(view))
        except Exception as e:  # pragma: no cover - defensive
            out.append(Finding(
                "internal-error", "warning", name,
                f"rule crashed: {type(e).__name__}: {e}",
            ))
    return out


def verify_flush(
    program: Any,
    leaves: Sequence[Any],
    exprs: Sequence[Any],
    donate: Sequence[int],
    label: Optional[str] = None,
    memo_plan: Any = None,
    class_plan: Any = None,
) -> List[Finding]:
    """Verify the program a flush is about to execute, emitting each
    finding through ``observe/events.py`` (so ``trace_report.py`` renders
    them) and counting per-severity registry metrics.  ``memo_plan`` is
    the flush's result-memoization plan, audited by the memo-safety
    rule; ``class_plan`` its compile-class bucket plan, audited by the
    compile-class rule."""
    from ramba_tpu import common as _common
    from ramba_tpu.core import fuser as _fuser

    view = ProgramView(
        program=program,
        leaves=leaves,
        exprs=exprs,
        donate=tuple(donate),
        owners=_fuser._leaf_owner_counts(leaves),
        seg_size=_common.max_program_instrs,
        memo_plan=memo_plan,
        class_plan=class_plan,
    )
    findings = verify_program(view)
    for f in findings:
        _registry.inc("analyze.findings")
        _registry.inc(f"analyze.findings.{f.severity}")
        _events.emit(f.as_event(label))
    return findings


def analyze_exprs(
    exprs: Sequence[Any],
    donate: Sequence[int] = (),
    rule_names: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Verify a list of expression roots exactly as the next flush would
    (rewrite + linearize + all rules), without executing anything.  The
    public hook for tests and interactive debugging."""
    from ramba_tpu import common as _common
    from ramba_tpu.core import fuser as _fuser

    program, leaves, rexprs = _fuser._prepare_program(list(exprs))
    view = ProgramView(
        program=program,
        leaves=leaves,
        exprs=rexprs,
        donate=tuple(donate),
        owners=_fuser._leaf_owner_counts(leaves),
        seg_size=_common.max_program_instrs,
    )
    return verify_program(view, rule_names)
