"""Device-side resharding with bounded peak memory and a coherence fence.

Arrays used to be pinned to their bring-up sharding: the only layout
change was a host round-trip (gather → re-``device_put``), and elastic
mesh reshape had to go through drain→checkpoint→resume.  This module
implements ``reshard(arr, new_spec)`` as a *schedule of device
collectives* — the peak-memory-aware redistribution discipline of
"Memory-efficient array redistribution through portable collective
communication" (arXiv:2112.01075) applied to the GSPMD substrate:

* **Plan** — :func:`plan_reshard` cuts the transfer into stages along
  the array's longest axis so that no stage moves more than
  ``max_stage_bytes`` (``RAMBA_RESHARD_STAGE_BYTES``, else the
  governor's chunk target).  A single-stage plan is one jitted identity
  with ``out_shardings`` — XLA lowers it to the all-to-all /
  collective-permute pattern for the (src, dst) layout pair.  A staged
  plan streams slabs: slice from the source layout, update into a
  destination-layout accumulator (donated every stage, so there is
  never more than one accumulator alive).  Peak live is bounded by
  ``src + dst + one stage slab`` — never a full host gather.
* **Fence** — under multi-controller execution the plan hash is agreed
  through ``coherence.agree("reshard:plan", ...)`` (rank 0 broadcasts,
  every rank verifies) before any collective runs, so the fleet
  executes the identical stage list or nobody moves: a rank with a
  divergent plan aborts the reshard *before* the first all-to-all can
  mispair.
* **Admission** — every stage asks the HBM governor for headroom first
  (``memory.reserve_headroom``): resharding a near-budget array spills
  LRU victims instead of OOMing mid-transfer.  Stage buffers ride in
  the ledger's transient accounting so ``peak_live_bytes`` stays
  honest.
* **Rollback** — the source buffer is never donated and never mutated;
  a stage failure (real or ``RAMBA_FAULTS`` ``reshard:stage``) drops
  the partial destination, emits a ``reshard``/``rollback`` event, and
  re-raises as :class:`ReshardError` — the caller still holds the
  intact source, so a torn array is impossible by construction.

Fault sites: ``reshard:plan`` (after the fence, before stage 0) and
``reshard:stage`` (top of every stage) — both compose with ``rank=``,
``after=``, and ``hang:ms=`` payloads, which is how the chaos leg kills
a reshard mid-schedule on one rank only.

Everything observable lands on the observe stream as ``reshard``
events (action plan/stage/done/rollback with epoch, stage index and
bytes), the ``reshard.*`` counters, and per-transfer bytes on the
``distributed`` ledger (``note_transfer("reshard", ...)``).
"""

from __future__ import annotations

import hashlib
import itertools
import os
import threading
from typing import Optional

import numpy as np

from ramba_tpu import common as _common
from ramba_tpu.observe import events as _events
from ramba_tpu.observe import registry as _registry
from ramba_tpu.parallel import distributed as _distributed
from ramba_tpu.parallel import mesh as _mesh
from ramba_tpu.resilience import coherence as _coherence
from ramba_tpu.resilience import faults as _faults
from ramba_tpu.resilience import memory as _memory


class ReshardError(RuntimeError):
    """A reshard schedule failed (stage fault, plan divergence).  The
    source array is guaranteed intact — callers may retry, fall back to
    the checkpoint path, or surface the error."""


class PlanMismatch(ReshardError):
    """The coherence fence disagreed with this rank's locally-computed
    plan hash: the ranks would have executed different stage lists."""


#: Monotonic reshard epoch — one per reshard operation, advanced in
#: lockstep under SPMD (every rank plans the same reshard sequence).
_epoch_counter = itertools.count(1)
_epoch_lock = threading.Lock()


def _next_epoch() -> int:
    with _epoch_lock:
        return next(_epoch_counter)


# ---------------------------------------------------------------------------
# plan
# ---------------------------------------------------------------------------


def _norm_spec(spec) -> tuple:
    """Canonical PartitionSpec entries with trailing Nones stripped —
    ``P('x')`` and ``P('x', None)`` describe the same layout."""
    if spec is None:
        return ()
    entries = tuple(spec)
    while entries and entries[-1] is None:
        entries = entries[:-1]
    return entries


def _spec_of(value) -> tuple:
    """Normalized spec of a concrete array; () (replicated/single-device)
    when the value carries no NamedSharding on the current mesh."""
    from jax.sharding import NamedSharding

    sh = getattr(value, "sharding", None)
    if isinstance(sh, NamedSharding):
        return _norm_spec(sh.spec)
    return ()


def default_stage_bytes() -> int:
    """Per-stage transfer budget: ``RAMBA_RESHARD_STAGE_BYTES`` when
    set, else the governor's (coherently min-agreed) chunk target."""
    raw = os.environ.get("RAMBA_RESHARD_STAGE_BYTES")
    if raw:
        try:
            return max(1 << 16, _common.parse_bytes(raw))
        except ValueError:
            pass
    return _memory.chunk_target_bytes()


class Stage:
    """One slab of the transfer: global rows ``[lo, hi)`` along
    ``plan.axis``, moved by one collective step."""

    __slots__ = ("index", "lo", "hi", "nbytes")

    def __init__(self, index: int, lo: int, hi: int, nbytes: int):
        self.index = index
        self.lo = lo
        self.hi = hi
        self.nbytes = nbytes

    def __repr__(self):
        return f"Stage({self.index}, [{self.lo}:{self.hi}), {self.nbytes}B)"


class ReshardPlan:
    """An agreed, bounded-peak-memory schedule for one layout change."""

    __slots__ = ("shape", "dtype", "src_spec", "dst_spec", "axis",
                 "stages", "total_bytes", "max_stage_bytes")

    def __init__(self, shape, dtype, src_spec, dst_spec, axis, stages,
                 total_bytes, max_stage_bytes):
        self.shape = tuple(int(s) for s in shape)
        self.dtype = np.dtype(dtype)
        self.src_spec = tuple(src_spec)
        self.dst_spec = tuple(dst_spec)
        self.axis = axis            # None for a single-stage plan
        self.stages = list(stages)
        self.total_bytes = int(total_bytes)
        self.max_stage_bytes = int(max_stage_bytes)

    @property
    def peak_bound_bytes(self) -> int:
        """The schedule's peak-live guarantee: source + destination +
        the largest in-flight stage slab."""
        stage_max = max((s.nbytes for s in self.stages), default=0)
        if len(self.stages) <= 1:
            # one collective: src + dst are the only buffers alive
            return 2 * self.total_bytes
        return 2 * self.total_bytes + stage_max

    def describe(self) -> str:
        """Canonical plan text — what the coherence fence hashes.  Pure
        function of (shape, dtype, layouts, stage list), so SPMD ranks
        computing the same reshard produce byte-identical text."""
        rows = [
            f"shape={self.shape} dtype={self.dtype}",
            f"src={self.src_spec!r} dst={self.dst_spec!r} axis={self.axis}",
        ]
        rows += [f"stage {s.index}: [{s.lo}:{s.hi}) {s.nbytes}B"
                 for s in self.stages]
        return "\n".join(rows)

    def hash31(self) -> int:
        """The plan digest folded to 31 bits — the coherence transport
        is int32, so the fence broadcasts this and each rank compares."""
        h = hashlib.sha1(self.describe().encode()).digest()
        return int.from_bytes(h[:4], "big") & 0x7FFFFFFF


def plan_reshard(shape, dtype, src_spec, dst_spec, *,
                 max_stage_bytes: Optional[int] = None) -> ReshardPlan:
    """Build the stage schedule for ``shape``/``dtype`` moving from
    ``src_spec`` to ``dst_spec``.  Deterministic: same inputs → same
    plan on every rank (the fence then proves it)."""
    shape = tuple(int(s) for s in shape)
    dtype = np.dtype(dtype)
    src = _norm_spec(src_spec)
    dst = _norm_spec(dst_spec)
    total = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize \
        if shape else dtype.itemsize
    if max_stage_bytes is None:
        max_stage_bytes = default_stage_bytes()
    max_stage_bytes = max(1, int(max_stage_bytes))
    if total <= max_stage_bytes or not shape:
        return ReshardPlan(shape, dtype, src, dst, None,
                           [Stage(0, 0, shape[0] if shape else 1, total)],
                           total, max_stage_bytes)
    # Slab along the longest axis: most stage-count headroom, and the
    # slab boundary math stays a pure function of the shape.
    axis = int(np.argmax(shape))
    n = shape[axis]
    row_bytes = max(1, total // max(1, n))
    rows_per_stage = max(1, max_stage_bytes // row_bytes)
    stages = []
    lo = 0
    i = 0
    while lo < n:
        hi = min(n, lo + rows_per_stage)
        stages.append(Stage(i, lo, hi, (hi - lo) * row_bytes))
        lo = hi
        i += 1
    return ReshardPlan(shape, dtype, src, dst, axis, stages, total,
                       max_stage_bytes)


# ---------------------------------------------------------------------------
# execution
# ---------------------------------------------------------------------------

# jit caches keyed by program structure — a reshard sequence repeated
# over many arrays of one shape compiles its collectives once.
_identity_cache: dict = {}
_zeros_cache: dict = {}
_stage_cache: dict = {}


def _dst_sharding(plan: ReshardPlan, mesh=None):
    from jax.sharding import NamedSharding, PartitionSpec

    if mesh is None:
        mesh = _mesh.get_mesh()
    return NamedSharding(mesh, PartitionSpec(*plan.dst_spec))


def _identity_fn(dst_sharding):
    import jax

    fn = _identity_cache.get(dst_sharding)
    if fn is None:
        fn = jax.jit(lambda x: x, out_shardings=dst_sharding)
        _identity_cache[dst_sharding] = fn
    return fn


def _zeros_fn(shape, dtype, dst_sharding):
    import jax
    import jax.numpy as jnp

    key = (shape, str(dtype), dst_sharding)
    fn = _zeros_cache.get(key)
    if fn is None:
        fn = jax.jit(lambda: jnp.zeros(shape, dtype),
                     out_shardings=dst_sharding)
        _zeros_cache[key] = fn
    return fn


def _stage_fn(ndim, axis, size, dst_sharding):
    """Jitted slab move: slice ``size`` rows at traced offset ``lo``
    from the source layout, update them into the (donated) destination
    accumulator.  At most two compiles per plan — the body size and the
    remainder size."""
    import jax
    import jax.numpy as jnp

    key = (ndim, axis, size, dst_sharding)
    fn = _stage_cache.get(key)
    if fn is None:
        def body(dst, src, lo):
            slab = jax.lax.dynamic_slice_in_dim(src, lo, size, axis)
            starts = [jnp.zeros((), jnp.int32)] * ndim
            starts[axis] = lo
            return jax.lax.dynamic_update_slice(dst, slab, tuple(starts))

        fn = jax.jit(body, out_shardings=dst_sharding, donate_argnums=0)
        _stage_cache[key] = fn
    return fn


def agree_plan(plan: ReshardPlan, epoch: int) -> int:
    """The epoch fence: rank 0 broadcasts its plan hash, every rank
    verifies against its own.  Returns the coherence epoch of the round
    (0 when not engaged).  Raises :class:`PlanMismatch` on divergence —
    before any collective has run, so no rank is left mid-schedule."""
    if not _coherence.engaged():
        return 0
    mine = plan.hash31()
    agreed = _coherence.agree("reshard:plan", mine, reduce="bcast")
    if agreed != mine:
        _registry.inc("reshard.plan_mismatches")
        raise PlanMismatch(
            f"reshard epoch {epoch}: plan hash {mine:#x} disagrees with "
            f"fleet decision {agreed:#x}")
    return _coherence.last_epoch("reshard:plan")


def _gate(site: str, ep: int, **ctx) -> None:
    """Fault check + fleet agreement before a collective step.

    Under coherent multi-controller execution a fault injected on ONE
    rank must abort the stage on EVERY rank *before* its collective
    launches — otherwise the faulted rank leaves the schedule while its
    peers block inside an all-to-all that can never complete.  The
    injected error is caught locally, severity-agreed (max), and then
    raised fleet-wide; a clean gate costs one agreement round.  Not
    engaged: a plain ``faults.check``."""
    err: Optional[Exception] = None
    coh = _coherence.engaged()
    try:
        _faults.check(site, epoch=ep, **ctx)
    except Exception as e:
        if not coh:
            raise
        err = e
    if not coh:
        return
    my = _coherence.P_OK if err is None else _coherence.P_DROP
    decision = _coherence.agree(f"{site}:gate", my, reduce="max")
    if decision != _coherence.P_OK:
        if err is not None:
            raise err
        raise _coherence.CoherentAbort(f"{site}:gate", decision)


def execute_plan(value, plan: ReshardPlan, *, epoch: Optional[int] = None,
                 mesh=None):
    """Run an (already fenced) plan over a concrete ``jax.Array``.
    Returns the destination-layout array; the source is left intact.
    ``mesh`` overrides the destination mesh (live mesh reshape moves
    arrays onto a mesh that is not yet the global one).  Any failure
    rolls back (drops the partial destination) and re-raises as
    :class:`ReshardError`."""
    ep = epoch if epoch is not None else _next_epoch()
    dst_sharding = _dst_sharding(plan, mesh)
    _registry.inc("reshard.plans")
    _events.emit({
        "type": "reshard", "action": "plan", "epoch": ep,
        "stages": len(plan.stages), "bytes": plan.total_bytes,
        "peak_bound_bytes": plan.peak_bound_bytes,
        "src": repr(plan.src_spec), "dst": repr(plan.dst_spec),
    })
    # Destination on a different device set (live mesh reshape shrinking
    # or growing the fleet): jit cannot re-home operands, so the whole
    # array moves through one governed device_put instead of staged
    # collectives.  Peak-live is still src + dst.
    src_devices = getattr(getattr(value, "sharding", None), "device_set",
                          None)
    cross_mesh = (src_devices is not None
                  and src_devices != dst_sharding.device_set)
    try:
        _gate("reshard:plan", ep)
        if cross_mesh:
            _gate("reshard:stage", ep, stage=0)
            out = _memory.governed_device_put(value, dst_sharding,
                                              site="reshard:stage")
            out.block_until_ready()
            _registry.inc("reshard.stages")
            _registry.inc("reshard.cross_mesh")
            _distributed.note_transfer("reshard", plan.total_bytes)
            _events.emit({
                "type": "reshard", "action": "stage", "epoch": ep,
                "stage": 0, "bytes": plan.total_bytes,
                "cross_mesh": True,
            })
        elif len(plan.stages) <= 1:
            _gate("reshard:stage", ep, stage=0)
            _memory.reserve_headroom(plan.total_bytes, site="reshard:stage")
            _memory.ledger._begin_transient(plan.total_bytes)
            try:
                out = _identity_fn(dst_sharding)(value)
                out.block_until_ready()
            finally:
                _memory.ledger._end_transient(plan.total_bytes)
            _registry.inc("reshard.stages")
            _distributed.note_transfer("reshard", plan.total_bytes)
            _events.emit({
                "type": "reshard", "action": "stage", "epoch": ep,
                "stage": 0, "bytes": plan.total_bytes,
            })
        else:
            import jax.numpy as jnp

            _memory.reserve_headroom(plan.total_bytes, site="reshard:dst")
            dst = _zeros_fn(plan.shape, plan.dtype, dst_sharding)()
            _memory.ledger._begin_transient(plan.total_bytes)
            try:
                for st in plan.stages:
                    _gate("reshard:stage", ep, stage=st.index)
                    _memory.reserve_headroom(st.nbytes,
                                             site="reshard:stage")
                    _memory.ledger._begin_transient(st.nbytes)
                    try:
                        fn = _stage_fn(len(plan.shape), plan.axis,
                                       st.hi - st.lo, dst_sharding)
                        dst = fn(dst, value, jnp.int32(st.lo))
                        dst.block_until_ready()
                    finally:
                        _memory.ledger._end_transient(st.nbytes)
                    _registry.inc("reshard.stages")
                    _distributed.note_transfer("reshard", st.nbytes)
                    _events.emit({
                        "type": "reshard", "action": "stage", "epoch": ep,
                        "stage": st.index, "bytes": st.nbytes,
                    })
                out = dst
            finally:
                _memory.ledger._end_transient(plan.total_bytes)
    except ReshardError:
        raise
    except Exception as e:
        # The partial destination (if any) dies with this frame; the
        # source was never donated — rolling back IS dropping our work.
        _registry.inc("reshard.rollbacks")
        _events.emit({
            "type": "reshard", "action": "rollback", "epoch": ep,
            "error": f"{type(e).__name__}: {e}"[:200],
        })
        raise ReshardError(
            f"reshard epoch {ep} failed; source sharding intact") from e
    _registry.inc("reshard.completed")
    _events.emit({
        "type": "reshard", "action": "done", "epoch": ep,
        "bytes": plan.total_bytes, "stages": len(plan.stages),
    })
    return out


def reshard_value(value, new_spec, *,
                  max_stage_bytes: Optional[int] = None, mesh=None):
    """Reshard a concrete ``jax.Array`` to ``new_spec`` on the current
    mesh (or an explicit target ``mesh``): plan → fence → staged
    collectives.  Returns the new array (or ``value`` itself when the
    layout already matches)."""
    from jax.sharding import NamedSharding

    dst = _norm_spec(new_spec)
    src = _spec_of(value)
    sh = getattr(value, "sharding", None)
    target_mesh = mesh if mesh is not None else _mesh.get_mesh()
    if (src == dst and isinstance(sh, NamedSharding)
            and sh.mesh == target_mesh):
        return value
    plan = plan_reshard(value.shape, value.dtype, src, dst,
                        max_stage_bytes=max_stage_bytes)
    ep = _next_epoch()
    agree_plan(plan, ep)
    return execute_plan(value, plan, epoch=ep, mesh=mesh)


def reshard(arr, new_spec, *, max_stage_bytes: Optional[int] = None):
    """Reshard an array to ``new_spec`` in place and return it.

    ``arr`` may be a ``ramba_tpu.ndarray`` (lazy work is flushed, a
    spilled backing buffer is restored, and the array's leaf is swapped
    to the new layout — views through it keep working) or a raw
    ``jax.Array`` (functional: the resharded array is returned).  On
    schedule failure the array is untouched — same value, same layout.
    """
    from ramba_tpu.core.ndarray import ndarray as _ndarray

    if not isinstance(arr, _ndarray):
        return reshard_value(arr, new_spec,
                             max_stage_bytes=max_stage_bytes)
    if arr._base is not None:
        raise ValueError("reshard: views cannot be resharded; "
                         "reshard the base array")
    value = arr._value()  # flush + restore-from-spill
    out = reshard_value(value, new_spec, max_stage_bytes=max_stage_bytes)
    if out is not value:
        from ramba_tpu.core.expr import Const

        arr._set_expr(Const(out))
    return arr
