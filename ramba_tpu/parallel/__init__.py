"""ramba_tpu.parallel subpackage: mesh/partitioning, shard metadata,
distribution constraints, multi-host bring-up."""

from ramba_tpu.parallel import shardview  # noqa: F401
