"""ramba_tpu.parallel subpackage."""
