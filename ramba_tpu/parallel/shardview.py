"""Shard/distribution metadata queries.

Reference: /root/reference/ramba/shardview_array.py — the packed int32
shardview encoding (row0=size, row1=global index_start, ...) and its algebra
(mapslice/intersect/broadcast/...), plus ``find_owning_worker``
(/root/reference/ramba/common.py:287-680 area).

TPU-native design: XLA owns memory layout, so the *algebra* (slicing,
intersection, broadcasting of views) disappears into GSPMD; what remains
genuinely useful is the *query* surface — where does each shard of an array
live in global index space?  That is derived here from the array's
``NamedSharding`` rather than maintained by hand, so it can never go stale.
"""

from __future__ import annotations

import numpy as np

from ramba_tpu.parallel import mesh as _mesh


def _concrete(a):
    from ramba_tpu.core.ndarray import ndarray

    return a._value() if isinstance(a, ndarray) else a


def _all_shard_indices(v):
    """(device, index-tuple) for EVERY shard, including remote-host ones —
    addressable_shards alone would make multi-host queries partial."""
    return list(v.sharding.devices_indices_map(v.shape).items())


def shard_slices(a) -> list:
    """Per-device global index ranges, one tuple of slices per shard —
    EVERY shard, including remote-host ones under multi-controller
    execution, in mesh device order (reference: the per-worker shardview
    rows size/index_start, shardview_array.py:32-70; a worker table there
    covers all workers, not just local ones)."""
    v = _concrete(a)
    return [idx for _dev, idx in _all_shard_indices(v)]


def divisions(a) -> np.ndarray:
    """Reference-style (n_shards, 2, ndim) start/end table
    (reference: divisions_to_distribution / distribution_to_divisions,
    shardview_array.py:617-935).  Covers EVERY shard via
    devices_indices_map — addressable_shards alone would silently return a
    partial table on a multi-host mesh (ADVICE r1)."""
    v = _concrete(a)
    nd = len(v.shape)
    out = []
    for _dev, idx in _all_shard_indices(v):
        starts = [
            (sl.start if sl.start is not None else 0) for sl in idx
        ] + [0] * (nd - len(idx))
        ends = [
            (sl.stop if sl.stop is not None else dim)
            for sl, dim in zip(idx, v.shape)
        ] + list(v.shape[len(idx):])
        out.append([starts, ends])
    return np.asarray(out, dtype=np.int64)


def find_owning_worker(a, index) -> int:
    """Which worker (global device ordinal in the mesh) owns global
    ``index`` (reference: find_owning_worker, common.py:653-680).  Covers
    remote-host shards on multi-host meshes."""
    v = _concrete(a)
    index = tuple(int(i) for i in (
        index if isinstance(index, (tuple, list)) else (index,)
    ))
    mesh_devs = list(_mesh.get_mesh().devices.flat)
    for dev, idx in _all_shard_indices(v):
        ok = True
        for d, i in enumerate(index):
            sl = idx[d] if d < len(idx) else slice(None)
            lo = sl.start if sl.start is not None else 0
            hi = sl.stop if sl.stop is not None else v.shape[d]
            if not (lo <= i < hi):
                ok = False
                break
        if ok:
            try:
                return mesh_devs.index(dev)
            except ValueError:
                return int(getattr(dev, "id", 0))
    raise IndexError(f"index {index} out of bounds for shape {v.shape}")


# ---------------------------------------------------------------------------
# Division-table algebra
#
# The reference's shardview algebra (mapslice/intersect/broadcast/
# make_uni_dist, shardview_array.py:414-1017) operates on packed int32
# shardviews because every view/assignment must be routed by hand over
# ZMQ/MPI.  Under XLA the layout lives in NamedSharding and GSPMD routes
# data, so what remains useful is the same *queries* as plain box algebra
# over (n_shards, 2, ndim) start/end tables — for spmd kernels, I/O
# planning, and owner lookups.
# ---------------------------------------------------------------------------


def slice_divisions(divs: np.ndarray, index) -> np.ndarray:
    """Division table of ``a[index]`` in the sliced coordinate system
    (reference: mapslice + slice_distribution, shardview_array.py:414-614,
    617-695).  ``index`` is a tuple of slices and/or ints (negative
    allowed, NumPy semantics); steps must be positive unit.  Empty
    per-shard boxes come out start == end."""
    divs = np.asarray(divs)
    nd = divs.shape[2]
    if not isinstance(index, tuple):
        index = (index,)
    index = index + (slice(None),) * (nd - len(index))
    if len(index) != nd:
        raise IndexError(
            f"too many indices for a {nd}-dim division table: {index!r}"
        )
    out = divs.copy()
    dims = divs[:, 1, :].max(axis=0) if len(divs) else np.zeros(nd, int)
    for d, sl in enumerate(index):
        dim = int(dims[d])
        if isinstance(sl, (int, np.integer)):
            i = int(sl)
            if i < 0:
                i += dim
            if not 0 <= i < dim:
                raise IndexError(
                    f"index {sl} out of bounds for dim {d} of size {dim}"
                )
            sl = slice(i, i + 1)
        elif not isinstance(sl, slice):
            raise TypeError(
                f"slice_divisions supports slices and ints, got {sl!r}"
            )
        start, stop, step = sl.indices(dim)
        if step != 1:
            raise NotImplementedError("slice_divisions: positive unit steps")
        lo = np.clip(divs[:, 0, d], start, stop) - start
        hi = np.clip(divs[:, 1, d], start, stop) - start
        out[:, 0, d] = lo
        out[:, 1, d] = np.maximum(lo, hi)
    return out


def intersect_divisions(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Per-shard box intersection of two aligned tables (reference:
    intersect, shardview_array.py:486-530)."""
    a, b = np.asarray(a), np.asarray(b)
    lo = np.maximum(a[:, 0, :], b[:, 0, :])
    hi = np.minimum(a[:, 1, :], b[:, 1, :])
    return np.stack([lo, np.maximum(lo, hi)], axis=1)


def broadcast_divisions(divs: np.ndarray, shape) -> np.ndarray:
    """Expand a table to a broadcast ``shape`` (reference: broadcast,
    shardview_array.py:978-1017): new leading dims and size-1 dims cover
    the full broadcast extent on every shard."""
    divs = np.asarray(divs)
    n, _, nd = divs.shape
    shape = tuple(int(s) for s in shape)
    grow = len(shape) - nd
    if grow < 0:
        raise ValueError("broadcast shape has fewer dims than the table")
    out = np.zeros((n, 2, len(shape)), divs.dtype)
    out[:, 1, :grow] = np.asarray(shape[:grow])
    for d in range(nd):
        D = grow + d
        if np.all(divs[:, 1, d] <= 1) and shape[D] > 1:
            # size-1 source dim broadcast up: every shard sees the full
            # extent (the value is replicated along it)
            out[:, 0, D] = 0
            out[:, 1, D] = shape[D]
        else:
            out[:, 0, D] = divs[:, 0, d]
            out[:, 1, D] = divs[:, 1, d]
    return out


def make_uni_divisions(shape, worker: int = 0, n_workers=None) -> np.ndarray:
    """Whole array on one worker, empty boxes elsewhere (reference:
    make_uni_dist, shardview_array.py:1142-1158)."""
    shape = tuple(int(s) for s in shape)
    n = int(n_workers if n_workers is not None else _mesh.num_workers())
    out = np.zeros((n, 2, len(shape)), np.int64)
    out[worker, 1, :] = shape
    return out


def divisions_size(divs: np.ndarray) -> np.ndarray:
    """Element count per shard box."""
    divs = np.asarray(divs)
    return np.prod(np.maximum(0, divs[:, 1, :] - divs[:, 0, :]), axis=1)


def default_distribution(shape) -> np.ndarray:
    """Division table the default partitioner would choose for ``shape``
    (reference: default_distribution, shardview_array.py:907-935).  Pure
    metadata — no device allocation."""
    from jax.sharding import NamedSharding

    shape = tuple(int(s) for s in shape)
    mesh = _mesh.get_mesh()
    sh = NamedSharding(mesh, _mesh.default_spec(shape, mesh))
    out = []
    for _dev, idx in sh.devices_indices_map(shape).items():
        starts = [(sl.start if sl.start is not None else 0) for sl in idx]
        ends = [
            (sl.stop if sl.stop is not None else dim)
            for sl, dim in zip(idx, shape)
        ]
        out.append([starts, ends])
    return np.asarray(out, dtype=np.int64)
