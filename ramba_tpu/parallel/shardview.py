"""Shard/distribution metadata queries.

Reference: /root/reference/ramba/shardview_array.py — the packed int32
shardview encoding (row0=size, row1=global index_start, ...) and its algebra
(mapslice/intersect/broadcast/...), plus ``find_owning_worker``
(/root/reference/ramba/common.py:287-680 area).

TPU-native design: XLA owns memory layout, so the *algebra* (slicing,
intersection, broadcasting of views) disappears into GSPMD; what remains
genuinely useful is the *query* surface — where does each shard of an array
live in global index space?  That is derived here from the array's
``NamedSharding`` rather than maintained by hand, so it can never go stale.
"""

from __future__ import annotations

import numpy as np

from ramba_tpu.parallel import mesh as _mesh


def _concrete(a):
    from ramba_tpu.core.ndarray import ndarray

    return a._value() if isinstance(a, ndarray) else a


def _all_shard_indices(v):
    """(device, index-tuple) for EVERY shard, including remote-host ones —
    addressable_shards alone would make multi-host queries partial."""
    return list(v.sharding.devices_indices_map(v.shape).items())


def shard_slices(a) -> list:
    """Per-device global index ranges, one tuple of slices per addressable
    shard (reference: the per-worker shardview rows size/index_start,
    shardview_array.py:32-70)."""
    v = _concrete(a)
    return [s.index for s in v.addressable_shards]


def divisions(a) -> np.ndarray:
    """Reference-style (n_shards, 2, ndim) start/end table
    (reference: divisions_to_distribution / distribution_to_divisions,
    shardview_array.py:617-935).  Covers EVERY shard via
    devices_indices_map — addressable_shards alone would silently return a
    partial table on a multi-host mesh (ADVICE r1)."""
    v = _concrete(a)
    nd = len(v.shape)
    out = []
    for _dev, idx in _all_shard_indices(v):
        starts = [
            (sl.start if sl.start is not None else 0) for sl in idx
        ] + [0] * (nd - len(idx))
        ends = [
            (sl.stop if sl.stop is not None else dim)
            for sl, dim in zip(idx, v.shape)
        ] + list(v.shape[len(idx):])
        out.append([starts, ends])
    return np.asarray(out, dtype=np.int64)


def find_owning_worker(a, index) -> int:
    """Which worker (global device ordinal in the mesh) owns global
    ``index`` (reference: find_owning_worker, common.py:653-680).  Covers
    remote-host shards on multi-host meshes."""
    v = _concrete(a)
    index = tuple(int(i) for i in (
        index if isinstance(index, (tuple, list)) else (index,)
    ))
    mesh_devs = list(_mesh.get_mesh().devices.flat)
    for dev, idx in _all_shard_indices(v):
        ok = True
        for d, i in enumerate(index):
            sl = idx[d] if d < len(idx) else slice(None)
            lo = sl.start if sl.start is not None else 0
            hi = sl.stop if sl.stop is not None else v.shape[d]
            if not (lo <= i < hi):
                ok = False
                break
        if ok:
            try:
                return mesh_devs.index(dev)
            except ValueError:
                return int(getattr(dev, "id", 0))
    raise IndexError(f"index {index} out of bounds for shape {v.shape}")


def default_distribution(shape) -> np.ndarray:
    """Division table the default partitioner would choose for ``shape``
    (reference: default_distribution, shardview_array.py:907-935).  Pure
    metadata — no device allocation."""
    from jax.sharding import NamedSharding

    shape = tuple(int(s) for s in shape)
    mesh = _mesh.get_mesh()
    sh = NamedSharding(mesh, _mesh.default_spec(shape, mesh))
    out = []
    for _dev, idx in sh.devices_indices_map(shape).items():
        starts = [(sl.start if sl.start is not None else 0) for sl in idx]
        ends = [
            (sl.stop if sl.stop is not None else dim)
            for sl, dim in zip(idx, shape)
        ]
        out.append([starts, ends])
    return np.asarray(out, dtype=np.int64)
