"""Device mesh + partition planning.

TPU-native replacement for the reference's shard/distribution layer:

* the partition scheduler `compute_regular_schedule` that factorizes the worker
  count into per-dimension splits minimizing communication surface
  (/root/reference/ramba/common.py:287-680), and
* the per-worker shardview metadata (/root/reference/ramba/shardview_array.py).

Here the mesh is a `jax.sharding.Mesh` and a "distribution" is a
`jax.sharding.NamedSharding`; XLA GSPMD owns memory layout and inserts the
collectives the reference implements by hand over ZMQ/MPI
(/root/reference/ramba/ramba_queue_zmq.py, ramba_queue_mpi.py).  The
surface-minimizing schedule solver is retained for the manual shard_map
paths (stencil halo planning), where cut surface still determines halo
traffic volume.
"""

from __future__ import annotations

import itertools
import math
from functools import lru_cache
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ramba_tpu import common

_mesh: Optional[Mesh] = None
# Bumped every time the mesh changes so the fuser can invalidate compiled code
# that baked in sharding constraints against the old mesh.
mesh_epoch: int = 0


def _make_default_mesh() -> Mesh:
    import time

    t0 = time.perf_counter()
    devices = jax.devices()  # first call triggers backend init (TPU probe)
    init_s = time.perf_counter() - t0
    n = len(devices)
    if common.num_workers_env is not None:
        n = min(n, int(common.num_workers_env))
        devices = devices[:n]
    ndim = max(1, min(common.mesh_ndim, 3))
    factors = balanced_factors(n, ndim)
    factors = tuple(f for f in factors if f > 1) or (1,)
    names = tuple(f"d{i}" for i in range(len(factors)))
    dev_array = np.array(devices).reshape(factors)
    mesh = Mesh(dev_array, axis_names=names)
    from ramba_tpu.observe import health as _health

    _health.record_mesh(mesh, init_s)
    return mesh


def get_mesh() -> Mesh:
    global _mesh
    if _mesh is None:
        set_mesh(_make_default_mesh())
    return _mesh


def set_mesh(mesh: Mesh) -> None:
    """Install a global device mesh (user-facing; like RAMBA_WORKERS env)."""
    global _mesh, mesh_epoch
    _mesh = mesh
    mesh_epoch += 1


def num_workers() -> int:
    return get_mesh().devices.size


@lru_cache(maxsize=None)
def prime_factors(n: int) -> tuple:
    """Prime factorization (reference: gen_prime_factors,
    /root/reference/ramba/common.py:300-318)."""
    out = []
    d = 2
    while d * d <= n:
        while n % d == 0:
            out.append(d)
            n //= d
        d += 1
    if n > 1:
        out.append(n)
    return tuple(out)


@lru_cache(maxsize=None)
def balanced_factors(n: int, k: int) -> tuple:
    """Split n into k factors as balanced as possible (largest first)."""
    factors = [1] * k
    for p in sorted(prime_factors(n), reverse=True):
        factors[int(np.argmin(factors))] *= p
    return tuple(sorted(factors, reverse=True))


@lru_cache(maxsize=4096)
def compute_regular_schedule(shape: tuple, n: int) -> tuple:
    """Choose per-dimension splits of ``n`` workers over ``shape`` minimizing
    the inter-shard surface area.

    TPU-first re-design of the reference partition scheduler
    (/root/reference/ramba/common.py:287-680, modes ratio/surface/nodesurface):
    rather than materializing per-worker index ranges, the output here is just
    the split count per dimension; the actual layout is delegated to
    NamedSharding.  Splits never exceed the dimension size.
    """
    ndim = len(shape)
    if ndim == 0 or n <= 1:
        return (1,) * ndim
    best = None
    best_cost = math.inf
    primes = prime_factors(n)
    # Enumerate assignments of prime factors to dimensions (n is small: the
    # worker count, typically <= a few thousand; primes are few).
    for assignment in itertools.product(range(ndim), repeat=len(primes)):
        splits = [1] * ndim
        for p, d in zip(primes, assignment):
            splits[d] *= p
        if any(s > max(1, shape[d]) for d, s in enumerate(splits)):
            continue
        # Cost = total cut surface: for each dim, (splits-1) cuts, each of area
        # prod(shape)/shape[d].
        total = math.prod(shape) if shape else 1
        cost = sum(
            (s - 1) * (total / shape[d]) for d, s in enumerate(splits) if shape[d] > 0
        )
        if cost < best_cost:
            best_cost = cost
            best = tuple(splits)
    return best if best is not None else (1,) * ndim


def _spec_parallelism(spec: P, mesh: Mesh) -> int:
    total = 1
    for e in spec:
        if e is None:
            continue
        for nm in (e,) if isinstance(e, str) else e:
            total *= mesh.shape[nm]
    return total


def default_spec(shape: Sequence[int], mesh: Optional[Mesh] = None) -> P:
    """Pick a PartitionSpec for a new array of ``shape``.

    Small arrays are replicated (reference: do_not_distribute,
    /root/reference/ramba/common.py:217-218).  Otherwise the
    surface-minimizing partition solver chooses per-dimension split counts
    (the reference's compute_regular_schedule, common.py:287-680) and the
    splits are realized on mesh axes; when the mesh's factorization cannot
    realize the solver's choice at full parallelism, fall back to the
    greedy largest-dim assignment.
    """
    mesh = mesh or get_mesh()
    shape = tuple(int(s) for s in shape)
    if len(shape) == 0 or math.prod(shape) < common.dist_threshold:
        return P()
    n = mesh.devices.size
    solved = spec_from_splits(compute_regular_schedule(shape, n), mesh)
    if _spec_parallelism(solved, mesh) == n:
        return solved
    greedy = _greedy_spec(shape, mesh)
    if _spec_parallelism(greedy, mesh) > _spec_parallelism(solved, mesh):
        return greedy
    return solved


def _greedy_spec(shape: tuple, mesh: Mesh) -> P:
    """Largest-axis-to-largest-dim assignment (pre-solver behavior)."""
    axes = sorted(mesh.shape.items(), key=lambda kv: -kv[1])  # (name, size)
    dims_by_size = sorted(range(len(shape)), key=lambda d: -shape[d])
    assignment: dict[int, list] = {}
    used_dims = set()
    for name, size in axes:
        placed = False
        for d in dims_by_size:
            if d in used_dims:
                continue
            if shape[d] >= size:
                assignment[d] = [name]
                used_dims.add(d)
                placed = True
                break
        if not placed:
            # Stack this axis onto the largest already-assigned dim if the dim
            # can absorb it; otherwise leave it unused (replicate over it).
            for d in dims_by_size:
                if d in used_dims and shape[d] >= size * math.prod(
                    mesh.shape[a] for a in assignment[d]
                ):
                    assignment[d].append(name)
                    placed = True
                    break
    entries = []
    for d in range(len(shape)):
        if d in assignment:
            names = assignment[d]
            entries.append(names[0] if len(names) == 1 else tuple(names))
        else:
            entries.append(None)
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


def spec_from_splits(splits: Sequence[int], mesh: Optional[Mesh] = None) -> P:
    """Best-effort PartitionSpec for explicit per-dimension split counts
    (the TPU mapping of the reference's explicit ``divisions``/distribution
    arguments, e.g. create_array_with_divisions, ramba.py:8552-8560).

    Each dim with splits>1 greedily claims unused mesh axes whose sizes
    multiply to the requested split; dims whose request can't be met by the
    mesh are left replicated (best-effort, like the reference's schedule
    solver ignoring infeasible constraints)."""
    mesh = mesh or get_mesh()
    free = dict(mesh.shape)
    entries = []
    for s in splits:
        s = int(s)
        if s <= 1:
            entries.append(None)
            continue
        # single axis exact match first, then exhaustive subset search
        # (meshes have <= ~4 axes, so 2^k subsets is trivial)
        names = None
        for name, size in free.items():
            if size == s:
                names = [name]
                break
        if names is None:
            free_items = list(free.items())
            for r in range(2, len(free_items) + 1):
                for combo in itertools.combinations(free_items, r):
                    if math.prod(sz for _, sz in combo) == s:
                        names = [nm for nm, _ in combo]
                        break
                if names:
                    break
        if names:
            for nm in names:
                free.pop(nm)
            entries.append(names[0] if len(names) == 1 else tuple(names))
        else:
            entries.append(None)
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


def default_sharding(shape: Sequence[int]) -> NamedSharding:
    return NamedSharding(get_mesh(), default_spec(shape))


def replicated_sharding() -> NamedSharding:
    return NamedSharding(get_mesh(), P())
