"""Co-partitioning constraints between related arrays.

Reference (/root/reference/ramba/ramba.py): symbolic per-dimension
constraints — ``smap(axis=...)`` records that its operands must be
partitioned identically along an axis (:9915-9922); ``Constraint``/
``add_constraint`` (:5296-5315) collect them, ``get_unified_constraints``
(:4205-4277) unifies them across the DAG, and the partition solver
(``compute_multi_partition``, common.py:344-451) turns them into per-array
block schedules.

TPU-native: a constraint is a shared ``PartitionSpec``.  Mesh axes are
assigned to the constrained dimension and a ``with_sharding_constraint``
hint node is pushed onto each array's expression, so GSPMD lays every
constrained array out identically — the communication-free alignment the
reference's solver computes by hand.  Unification across chained ops is
GSPMD sharding propagation itself.
"""

from __future__ import annotations

import weakref
from collections import deque
from typing import Sequence

from jax.sharding import PartitionSpec as P

from ramba_tpu.core.expr import Node
from ramba_tpu.core.ndarray import ndarray
from ramba_tpu.parallel import mesh as _mesh

# Recorded constraints, for introspection/debugging (reference keeps the
# live list on the DAG and dumps it with RAMBA_DEBUG).  Bounded, and
# Constraint holds only weakrefs, so recording never pins arrays (or their
# device buffers) alive.
_constraints: deque = deque(maxlen=1024)


class Constraint:
    """"These arrays are partitioned only along ``axis``, identically."

    Reference: class Constraint (ramba.py:5296-5315)."""

    def __init__(self, arrays: Sequence[ndarray], axis: int):
        self._array_refs = [weakref.ref(a) for a in arrays]
        self.axis = int(axis)
        ndim = arrays[0].ndim if arrays else 1
        self.spec = axis_spec(ndim, axis)

    @property
    def arrays(self) -> list:
        """Still-live constrained arrays."""
        return [a for a in (r() for r in self._array_refs) if a is not None]

    def __repr__(self):
        return (f"Constraint(axis={self.axis}, n={len(self._array_refs)}, "
                f"spec={self.spec})")


def axis_spec(ndim: int, axis: int) -> P:
    """PartitionSpec placing every mesh axis on ``axis`` (replicating the
    rest) — the distribution the reference's solver produces for a
    single-axis constraint."""
    axis = axis % ndim
    mesh = _mesh.get_mesh()
    names = tuple(mesh.axis_names)
    entries: list = [None] * ndim
    entries[axis] = names[0] if len(names) == 1 else names
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


def add_constraint(arrays: Sequence[ndarray], axis: int) -> Constraint:
    """Constrain ``arrays`` to be co-partitioned along ``axis`` (reference:
    add_constraint, ramba.py:5296-5315).  Applied immediately as sharding
    hints on each array's pending expression."""
    arrs = [a for a in arrays if isinstance(a, ndarray)]
    con = Constraint(arrs, axis)
    for a in arrs:
        if a.ndim == 0:
            continue
        spec = axis_spec(a.ndim, axis)
        # divisibility guard: with_sharding_constraint handles uneven shards,
        # but axis size smaller than the mesh would force replication anyway
        k = _mesh.num_workers()
        if a.shape[axis % a.ndim] < k:
            continue
        a.write_expr(Node("shard_hint", (tuple(spec),), [a.read_expr()]))
    _constraints.append(con)
    return con


def get_constraints() -> list:
    return list(_constraints)


def clear_constraints() -> None:
    _constraints.clear()
