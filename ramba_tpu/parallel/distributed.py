"""Multi-host bring-up: the reference's cluster launcher, TPU-native.

Reference (/root/reference/ramba):

* Ray mode — driver spawns RemoteState actors over a placement group and
  wires ZMQ queues (ramba.py:10650-10724).
* MPI mode — the whole user program runs SPMD on every rank; rank 0 keeps
  driver semantics via ``in_driver()`` (common.py:49-100, README.md:168-176).
* At ≥100 workers a 2-level aggregation tree batches control messages
  (NUM_WORKERS_FOR_BCAST, common.py:27; tree helpers ramba.py:1825-1850).

TPU-native: a multi-host TPU slice runs one jax process per host
(multi-controller SPMD — exactly the reference's MPI mode).  Every process
executes the same program; ``jax.distributed.initialize`` wires the hosts;
the global device mesh then spans all hosts and XLA runs collectives over
ICI within a slice and DCN across slices.  No control tree is needed — XLA's
dispatch owns cross-host coordination — matching SURVEY §2.6's note.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np

_initialized = False


def _init_kwargs(kwargs: dict) -> dict:
    """Fold ``RAMBA_INIT_TIMEOUT_S`` into the ``jax.distributed.initialize``
    kwargs (as ``initialization_timeout``, seconds).  An explicit kwarg
    from the caller wins; a malformed or non-positive env value is
    ignored."""
    out = dict(kwargs)
    raw = os.environ.get("RAMBA_INIT_TIMEOUT_S")
    if raw:
        try:
            t = float(raw)
        except ValueError:
            t = 0.0
        if t > 0:
            out.setdefault("initialization_timeout", int(max(1, round(t))))
    return out


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    **kwargs,
) -> None:
    """Wire up multi-host execution (reference: worker bring-up at import,
    ramba.py:10650-10724; here explicit because jax owns process groups).

    On TPU pods the arguments are auto-detected from the environment; on
    CPU/GPU clusters pass coordinator_address/num_processes/process_id
    (or set JAX_COORDINATOR_ADDRESS etc.).  Safe to call when single-host:
    with no coordinator configured this is a no-op.
    """
    global _initialized
    if _initialized:
        return
    has_env = (
        coordinator_address is not None
        or os.environ.get("JAX_COORDINATOR_ADDRESS")
        or os.environ.get("TPU_WORKER_HOSTNAMES")
        or os.environ.get("MEGASCALE_COORDINATOR_ADDRESS")
    )
    if not has_env:
        return  # single-host: nothing to do
    try:
        from jax._src import xla_bridge

        if xla_bridge.backends_are_initialized():
            # Backend already up (e.g. the process computed before calling
            # initialize); too late to form a process group — stay
            # single-controller.  The reference has the same
            # initialize-at-import-or-never shape (common.py:683-758).
            from ramba_tpu.common import dprint

            dprint(1, "ramba_tpu.distributed.initialize: backend already "
                      "initialized; staying single-process")
            return
    except ImportError:
        pass
    import time

    from ramba_tpu.observe import health as _health
    from ramba_tpu.resilience import faults as _faults
    from ramba_tpu.resilience import retry as _retry

    t0 = time.perf_counter()
    kw = _init_kwargs(kwargs)

    # CPU multi-controller needs a cross-process collectives backend: with
    # jax's default ("none") the group forms and compiles, then every
    # cross-process computation fails at dispatch ("Multiprocess
    # computations aren't implemented on the CPU backend").  Selecting
    # gloo here — before the backend exists — makes bring-up on CPU
    # clusters (and the 2-process CI legs) actually executable; TPU
    # backends ignore it.
    try:
        from jax._src import xla_bridge as _xb

        if _xb.CPU_COLLECTIVES_IMPLEMENTATION.value == "none":
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (ImportError, AttributeError):
        pass  # older/newer jax without this option

    def connect():
        _faults.check("init_connect")
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            **kw,
        )

    def cleanup():
        # a half-formed distributed client must be torn down before the
        # next connect attempt can bind the coordinator channel again
        try:
            jax.distributed.shutdown()
        except Exception:
            pass

    try:
        _retry.call("init_connect", connect, on_retry=cleanup)
    except Exception as e:
        # Health event first, then re-raise WITH the original failure
        # chained (RetryBudgetExhausted carries the last connect error as
        # __cause__) — bring-up failures must never lose their root cause.
        _health.record(
            outcome="error", error=repr(e), source="distributed_init",
            init_seconds=time.perf_counter() - t0,
            cause=repr(e.__cause__) if e.__cause__ is not None else None,
        )
        raise
    _initialized = True
    # The process group just formed: any (rank, nprocs) the event stream
    # cached from a pre-bring-up emit is stale.  Re-probe before the health
    # record below so IT already carries the authoritative rank (and lands
    # in the right per-rank trace file).
    from ramba_tpu.observe import events as _events
    from ramba_tpu.resilience import coherence as _coherence

    _events.invalidate_rank()
    _coherence.invalidate()
    _health.record(
        outcome="ok", source="distributed_init",
        init_seconds=time.perf_counter() - t0,
        process_count=jax.process_count(),
        process_index=jax.process_index(),
    )


def barrier(tag: str) -> None:
    """Cross-rank sync point (no-op single-process).  Runs under the
    elastic watchdog deadline: a rank that never arrives (crashed,
    wedged collective) turns the infinite block into a fatal-classified
    ``RankStallError`` on the ranks still alive — the signal the
    drain/resume runbook (docs/index.md) keys on."""
    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils

    from ramba_tpu.resilience import elastic as _elastic

    _elastic.with_deadline(
        "barrier", lambda: multihost_utils.sync_global_devices(tag))


def note_transfer(kind: str, nbytes: int) -> None:
    """Account one cross-process transfer in the observability registry
    (kind: "allgather" | "broadcast" | ...).  Call sites: ndarray.asarray's
    process_allgather, fileio's driver-write flag broadcast."""
    from ramba_tpu.observe import registry as _registry

    _registry.inc(f"distributed.{kind}_count")
    if nbytes:
        _registry.inc(f"distributed.{kind}_bytes", int(nbytes))


def shutdown() -> None:
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False


def in_driver() -> bool:
    """True on the coordinating process (reference: in_driver() gates
    driver-only code in MPI SPMD mode, common.py:49-100)."""
    return jax.process_index() == 0


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def local_devices() -> list:
    return jax.local_devices()


def global_mesh(ici_shape: Optional[tuple] = None, axis_names=None):
    """Build a mesh spanning every host's devices.

    For multi-slice topologies, put the DCN-connected axis *first* so the
    leading (data-parallel) mesh dimension rides DCN and everything else
    stays on ICI — the layout SURVEY §2.7 calls for.
    """
    from jax.sharding import Mesh

    from ramba_tpu.parallel.mesh import balanced_factors

    devices = jax.devices()
    n = len(devices)
    if ici_shape is None:
        nproc = jax.process_count()
        if nproc > 1 and n % nproc == 0:
            ici_shape = (nproc, n // nproc)
        else:
            ici_shape = tuple(f for f in balanced_factors(n, 2) if f > 1) or (1,)
    if axis_names is None:
        axis_names = tuple(f"d{i}" for i in range(len(ici_shape)))
    return Mesh(np.array(devices).reshape(ici_shape), axis_names=axis_names)
