"""Boolean-mask arrays.

Reference semantics (/root/reference/docs/index.md:60-68, ramba.py:5908-5911,
6148-6154, 8476-8478): ``a[a > 0]`` produces an array that *keeps the logical
shape* and carries a boolean mask; elementwise ops apply under the mask,
writes are guarded, and reductions consider only selected elements.  The
reference emits ``if mask: ...`` guard lines into its fused Numba kernels;
here every masked op is a fused ``where`` select, and masked reductions
substitute the reduction identity — both stay inside the single jitted flush.
"""

from __future__ import annotations

import numpy as np

from ramba_tpu.core.expr import Node, make_map
from ramba_tpu.core.ndarray import ViewOp, as_exprable, ndarray


class _IdentityView(ViewOp):
    def read(self, base_expr):
        return base_expr

    def write(self, base_expr, value_expr):
        return value_expr


class MaskedArray(ndarray):
    """Same logical shape as its parent; only mask-selected elements are
    meaningful.  In-place ops write through to the parent (guarded)."""

    __slots__ = ("_mask",)

    def __init__(self, parent: ndarray, mask: ndarray):
        super().__init__(base=parent, view=_IdentityView())
        if not isinstance(mask, ndarray):
            # accept host boolean masks (numpy arrays / lists); NOTE the
            # polarity is the reference's a[a > 0] SELECTION mask (True =
            # selected), the inverse of numpy.ma's True = invalid
            from ramba_tpu.ops.creation import asarray as _as

            mask = _as(mask, dtype=bool)
        if tuple(mask.shape) != tuple(parent.shape):
            # a mismatched mask would silently broadcast in the fill but
            # not in the count, giving wrong statistics — refuse like np.ma
            raise ValueError(
                f"mask shape {tuple(mask.shape)} does not match data shape "
                f"{tuple(parent.shape)}"
            )
        self._mask = mask

    # -- guarded elementwise ---------------------------------------------------

    def _map(self, fname, *others, reverse=False):
        dense = self.read_expr()
        args = [as_exprable(o) for o in others]
        operands = [dense] + args
        if reverse:
            operands = operands[::-1]
        val = make_map(fname, operands)
        guarded = Node("masked_fill", (), [dense, self._mask.read_expr(), val])
        return MaskedArray(ndarray(guarded), self._mask)

    def _inplace_map(self, fname, other):
        dense = self.read_expr()
        val = make_map(fname, [dense, as_exprable(other)])
        if np.dtype(val.dtype) != self.dtype:
            val = Node("cast", (str(self.dtype),), [val])
        self._base.write_expr(
            Node("masked_fill", (), [dense, self._mask.read_expr(), val])
        )
        return self

    # -- masked reductions -----------------------------------------------------

    def _reduce(self, fname, axis=None, keepdims=False, ddof=None):
        from ramba_tpu.core.ndarray import _norm_axis

        axis = _norm_axis(axis, self.ndim)
        if fname in ("var", "std"):
            # two-pass via masked mean; ddof rescales by n/(n-ddof) with
            # n = selected count per reduction slice (numpy.ma semantics)
            m = self._reduce("mean", axis, True)
            d = (ndarray(self.read_expr()) - m)
            sq = d * d
            v = MaskedArray(sq, self._mask)._reduce("mean", axis, keepdims)
            if ddof:
                from ramba_tpu.ops.elementwise import where

                cnt = self._mask.sum(axis=axis, keepdims=keepdims)
                # slices with cnt <= ddof are degenerate; numpy.ma masks
                # them (data 0) — produce 0, not nan/inf
                v = where(cnt > ddof, v * (cnt / (cnt - float(ddof))), 0.0)
            return v.sqrt() if fname == "std" else v
        return ndarray(
            Node(
                "reduce_where",
                (fname, axis, bool(keepdims)),
                [self.read_expr(), self._mask.read_expr()],
            )
        )

    def count(self):
        return self._mask.sum()

    def compressed(self) -> np.ndarray:
        """Selected elements as a dense 1-D host array (data-dependent shape —
        must materialize; the reference faces the same constraint and keeps
        masked arrays logical-shaped for exactly this reason)."""
        dense = self.asarray()
        mask = self._mask.asarray()
        return dense[mask]
