"""The verified prepare-side plan cache (``RAMBA_PLANCERT=1``).

``analyze/plancert.py`` defines what a proof-carrying plan certificate
*is*; this module is the flush-path machinery that stores and redeems
them.  A repeat flush whose certificate validates skips the entire
prepare-side analysis pipeline — RAMBA_VERIFY rules, effect
classification, canonical hashing, compile-class proof, admission
estimate — behind one version-vector comparison, which is what makes
``RAMBA_VERIFY=strict`` cheaper than off for steady-state traffic.

Design points:

* **Keyed per flush, signed per epoch.**  The cache key carries the
  per-flush inputs (program structure, leaf shape/dtype signature,
  donation mask); the certificate's invalidation signature carries the
  ambient ones (mesh epoch, x64, rule set, shardings, budget band,
  autotune generation, class policy).  A hit re-captures only the
  signature.

* **Fault-forging flushes never certify.**  The donate-census /
  compile-bucket / memo-certifier fault sites deliberately corrupt the
  analyses a certificate snapshots; while any of them is armed the
  cache stands down entirely (lookups and stores), so a forged verdict
  can neither enter nor serve.  ``faults.configured`` is rank-identical,
  so SPMD ranks stand down in lockstep.

* **``plan:stale``** is this module's own fault site: it forges a
  stale-signature verdict onto an otherwise valid hit so strict mode's
  rejection path (raise) and warn mode's silent re-analysis are testable
  end-to-end.

* **Shared tier.**  Certified verdicts are portable by chash: with the
  fleet artifact tier armed (PR 17), ``publish`` writes a JSON blob to
  ``<artifacts>/plancert/<chash>.json`` and a local miss may adopt a
  peer replica's certificate (paying only canonicalization), so one
  replica's analysis warms the fleet.

* **Batched coherence.**  Multi-controller ranks agree on hit counts via
  one ``agree()`` round per RAMBA_PLANCERT_AGREE hits (default 16), not
  per flush; a divergent round clears the local cache so ranks
  re-converge through fresh analysis.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ramba_tpu.analyze import plancert as _plancert
from ramba_tpu.analyze.findings import Finding
from ramba_tpu.observe import events as _events
from ramba_tpu.observe import registry as _registry
from ramba_tpu.resilience import coherence as _coherence
from ramba_tpu.resilience import faults as _faults
from ramba_tpu.resilience import integrity as _integrity

_OFF = ("", "0", "off", "false", "no")

#: Fault sites that deliberately corrupt an analysis the certificate
#: snapshots — the cache stands down while any is armed.
_FORGE_SITES = ("donate_census", "compile:bucket", "memo:insert",
                "memo:hit")

#: Sentinel: the certificate's signature carries no ``shardings`` field
#: (the sharding-legality rule was disabled), so hits skip the digest.
_NO_SHARDING = object()


class _Entry:
    """One stored certificate plus its redemption fast path: the ambient
    probe captured when the certificate last validated and the expected
    shardings digest.  A lookup whose live probe equals ``probe`` and
    whose leaf shardings digest equals ``sharding`` is valid without
    re-building the signature vector (every non-shardings field is a
    pure function of the probe); any mismatch falls back to the full
    capture-and-compare, which self-heals ``probe`` on success (e.g. an
    env var rewritten to an equivalent spelling, or an adopted
    certificate whose home process had different ambient raw values)."""

    __slots__ = ("cert", "probe", "sharding", "sharding_objs", "hit")

    def __init__(self, cert: _plancert.PlanCertificate,
                 probe: Optional[Tuple[Any, ...]],
                 sharding_objs: Optional[Tuple[Any, ...]] = None):
        self.cert = cert
        self.probe = probe
        self.sharding = dict(cert.signature).get("shardings", _NO_SHARDING)
        # the live sharding objects the digest last validated against:
        # an equal tuple (identity fast path for the common repeat) is
        # proof the digest would match without rehashing
        self.sharding_objs = sharding_objs
        self.hit: Optional["Hit"] = None    # built on first redemption


def _sharding_objs(leaf_vals: Sequence[Any],
                   leaf_order: Sequence[int]) -> Optional[Tuple[Any, ...]]:
    """The live per-leaf sharding objects in canonical order (None on
    any indexing surprise).  Compared by ``==`` against the tuple cached
    at the last digest validation — jax sharding types define cheap
    structural equality, and CPython's identity shortcut makes the
    steady-state compare a few pointer tests."""
    try:
        if leaf_order:
            return tuple(getattr(leaf_vals[s], "sharding", None)
                         for s in leaf_order)
        return tuple(getattr(v, "sharding", None) for v in leaf_vals)
    except (IndexError, TypeError):
        return None


_lock = threading.Lock()
_store: "OrderedDict[Tuple[Any, ...], _Entry]" = OrderedDict()
_stats: Dict[str, int] = {}
_stale_causes: Dict[str, int] = {}
_pending_hits = 0


def enabled() -> bool:
    """Plan-certificate cache armed?  Off by default — ``RAMBA_PLANCERT=1``."""
    return (os.environ.get("RAMBA_PLANCERT") or "").strip().lower() \
        not in _OFF


def strict() -> bool:
    """Does the current RAMBA_VERIFY mode reject (rather than re-analyze)
    a stale certificate?"""
    if not os.environ.get("RAMBA_VERIFY"):
        return False
    from ramba_tpu.analyze import verifier as _verifier

    return _verifier.mode() == "strict"


def _max_entries() -> int:
    try:
        return max(1, int(os.environ.get("RAMBA_PLANCERT_MAX", "512")
                          or 512))
    except ValueError:
        return 512


def _agree_batch() -> int:
    try:
        return max(1, int(os.environ.get("RAMBA_PLANCERT_AGREE", "16")
                          or 16))
    except ValueError:
        return 16


def _bump(name: str, n: int = 1) -> None:
    _stats[name] = _stats.get(name, 0) + n


def _forgery_armed() -> bool:
    if not _faults.enabled():
        return False
    return any(_faults.configured(s) for s in _FORGE_SITES)


@dataclasses.dataclass(frozen=True)
class Hit:
    """One redeemed certificate.  ``tier`` is ``"hit"`` (local) or
    ``"shared"`` (adopted from the fleet artifact tier).  ``forged``
    marks a ``plan:stale`` fault-forged staleness verdict — the fuser
    rejects it under strict and silently re-analyzes under warn."""

    cert: _plancert.PlanCertificate
    tier: str
    forged: bool
    causes: Tuple[str, ...]


class _HashedKey:
    """Program-key wrapper carrying the hash precomputed at linearize
    time (``_Program.key_hash``): the instrs tuple is the large part of
    the cache key, and re-walking it for every dict operation would put
    an O(program) hash back on the redemption path the certificate just
    cleared."""

    __slots__ = ("key", "h")

    def __init__(self, key: Any, h: int):
        self.key = key
        self.h = h

    def __hash__(self) -> int:
        return self.h

    def __eq__(self, other: Any) -> bool:
        return self.key == getattr(other, "key", None)


def _key(program: Any, leaf_vals: Sequence[Any],
         donate_key: Tuple[int, ...]) -> Optional[Tuple[Any, ...]]:
    """Cache key: per-flush inputs only (ambient state lives in the
    certificate's signature).  None when the program has no key or the
    key is unhashable (``key_hash == -1`` — CPython ``hash()`` never
    returns -1) — such programs simply never certify."""
    try:
        kh = getattr(program, "key_hash", None)
        if kh is None:
            kh = hash(program.key)
        elif kh == -1:
            return None
        return (_HashedKey(program.key, kh),
                _plancert.aval_signature(leaf_vals), tuple(donate_key))
    except (AttributeError, TypeError):
        return None


def lookup(program: Any, leaf_vals: Sequence[Any],
           donate_key: Tuple[int, ...], label: str) -> Optional[Hit]:
    """Redeem a certificate for a prepared flush.  Returns None on miss
    or genuine staleness (both fall through to full analysis); a
    :class:`Hit` otherwise.  A genuine signature mismatch evicts, counts
    its causes, and emits a ``plan_stale`` trace event."""
    if not enabled() or _forgery_armed():
        return None
    key = _key(program, leaf_vals, donate_key)
    if key is None:
        return None
    tier = "hit"
    try:
        with _lock:
            _bump("lookups")
            entry = _store.get(key)
            if entry is not None:
                _store.move_to_end(key)
    except TypeError:       # unhashable program key — never certifiable
        return None
    if entry is None:
        entry = _adopt_shared(program, leaf_vals, donate_key, key)
        tier = "shared"
    if entry is None:
        with _lock:
            _bump("misses")
        _registry.inc("plancache.miss")
        return None
    cert = entry.cert
    # plan:stale — forge a stale-signature verdict onto a valid hit so
    # the strict rejection path is exercisable end-to-end.
    try:
        _faults.check("plan:stale", label=label)
    except _faults.InjectedFault:
        causes = cert.sig_fields or ("ruleset",)
        with _lock:
            _bump("forged_stale")
        _registry.inc("plancache.forged_stale")
        _emit_stale(label, cert, causes, forged=True)
        return Hit(cert=cert, tier=tier, forged=True, causes=causes)
    # Fast path: live ambient probe equals the probe this entry last
    # validated under, and the leaf shardings still match — every other
    # signature field is a pure function of the probe, so the
    # certificate is valid without rebuilding the vector.  Shardings
    # validate by object equality against the tuple the digest last
    # vouched for; only a changed tuple pays the rehash.
    valid = False
    probe = _plancert._ambient_probe()
    if probe is not None and probe == entry.probe:
        if entry.sharding is _NO_SHARDING:
            valid = True
        else:
            objs = _sharding_objs(leaf_vals, cert.leaf_order)
            if objs is not None and objs == entry.sharding_objs:
                valid = True
            elif _plancert.sharding_digest(leaf_vals, cert.leaf_order) \
                    == entry.sharding:
                valid = True
                entry.sharding_objs = objs
    if valid:
        causes: Tuple[str, ...] = ()
    else:
        fresh = _plancert.capture_signature(cert.sig_fields, leaf_vals,
                                            cert.leaf_order)
        if fresh == cert.signature:
            causes = ()
            # self-heal the fast path
            entry.probe = probe
            entry.sharding_objs = _sharding_objs(leaf_vals,
                                                 cert.leaf_order)
        else:
            causes = _plancert.stale_fields(cert.signature, fresh) \
                or ("ruleset",)
    if causes:
        with _lock:
            _store.pop(key, None)
            _bump("stale")
            _bump("misses")
            for c in causes:
                _stale_causes[c] = _stale_causes.get(c, 0) + 1
        _registry.inc("plancache.stale")
        _emit_stale(label, cert, causes, forged=False)
        return None
    with _lock:
        _bump("hits" if tier == "hit" else "shared_hits")
    _registry.inc("plancache.hit" if tier == "hit"
                  else "plancache.shared_hit")
    _note_hit()
    hit = entry.hit
    if hit is None or hit.tier != tier:
        hit = Hit(cert=cert, tier=tier, forged=False, causes=())
        entry.hit = hit
    return hit


def _emit_stale(label: str, cert: _plancert.PlanCertificate,
                causes: Sequence[str], forged: bool) -> None:
    ev: Dict[str, Any] = {
        "type": "plan_stale", "label": label, "causes": list(causes),
        "forged": bool(forged),
    }
    if cert.chash is not None:
        ev["chash"] = cert.chash
    _events.emit(ev)


def stale_findings(hit: Hit, label: str) -> List[Finding]:
    """The error findings a strict-mode flush raises for a certificate
    whose signature no longer validates."""
    return [Finding(
        rule="plan-stale",
        severity="error",
        node="program",
        message=(
            f"plan certificate for {label!r} failed signature validation "
            f"(stale fields: {', '.join(hit.causes) or '?'}); strict mode "
            "rejects rather than trusting a stale verdict"
        ),
    )]


# ---------------------------------------------------------------------------
# certification (the miss path)
# ---------------------------------------------------------------------------


def certify(work: Any) -> Optional[_plancert.PlanCertificate]:
    """Snapshot a fully-analyzed, verifier-clean flush as a certificate
    and store it.  Called by ``fuser._flush_prepare`` after the verifier
    ran on the miss path; returns None (and stores nothing) when the
    flush is ineligible — error findings, forging faults armed, or an
    unkeyable program."""
    if not enabled() or _forgery_armed():
        return None
    program = work.program
    leaf_vals = work.leaf_vals
    donate_key = tuple(work.donate_key)
    key = _key(program, leaf_vals, donate_key)
    if key is None:
        return None
    span = work.span or {}
    counts: Dict[str, int] = dict(span.get("findings") or {})
    if counts.get("error"):
        return None
    from ramba_tpu.analyze import verifier as _verifier

    if os.environ.get("RAMBA_VERIFY"):
        vmode = _verifier.mode()
        rule_names: List[str] = (
            _verifier.enabled_rules() if vmode != "off" else [])
    else:
        vmode, rule_names = "off", []

    mp = work.memo_plan
    effects_rep: Any = None
    if mp is not None:
        chash: Optional[str] = mp.chash
        form: Optional[str] = mp.form
        leaf_order: Tuple[int, ...] = tuple(mp.leaf_order)
        effects_rep = mp.effects
        memo_ok = bool(mp.certified)
    else:
        chash, form, leaf_order, memo_ok = None, None, (), False
    if effects_rep is None:
        from ramba_tpu.analyze import effects as _effects

        try:
            effects_rep = _effects.classify_program(program, donate_key)
        except Exception:  # noqa: BLE001 — no report, no certificate
            return None
    if chash is None:
        from ramba_tpu.analyze import canon as _canon

        try:
            cf = _canon.try_canonicalize(program)
        except Exception:  # noqa: BLE001
            cf = None
        if cf is not None:
            chash, form, leaf_order = cf.chash, cf.form, tuple(cf.leaf_order)

    cp = work.class_plan
    class_data: Optional[Tuple[Any, ...]] = None
    class_proof = ""
    if cp is not None:
        from ramba_tpu.compile import classes as _classes

        class_data = (tuple(cp.token), int(cp.n), int(cp.bucket),
                      tuple(cp.pad_slots), int(cp.pad_waste_bytes))
        class_proof = hashlib.sha256(
            repr((class_data, _classes.mode())).encode()).hexdigest()[:16]

    admit_est = 0
    try:
        from ramba_tpu.analyze import rules as _rules
        from ramba_tpu.resilience import memory as _memory

        admit_est = int(_rules.estimate_peak_bytes(
            program, _memory._leaf_avals(leaf_vals), donate_key))
    except Exception:  # noqa: BLE001 — estimate is advisory
        admit_est = 0

    at_backend: Optional[str] = None
    at_via: Optional[str] = None
    try:
        from ramba_tpu.core import autotune as _autotune

        d = _autotune.decision(work.fingerprint) \
            if work.fingerprint else None
        if d is not None:
            at_backend, at_via = d.get("backend"), d.get("via")
    except Exception:  # noqa: BLE001
        pass

    sig_fields = _plancert.signature_fields_for(rule_names)
    signature = _plancert.capture_signature(
        sig_fields, leaf_vals, leaf_order, mode=vmode,
        rule_names=rule_names)
    ruleset_digest = dict(signature).get("ruleset", "")
    finding_counts = tuple(sorted(counts.items()))
    cert = _plancert.PlanCertificate(
        label=work.label,
        fingerprint=work.fingerprint,
        chash=chash,
        canon_form=form,
        leaf_order=leaf_order,
        aval_sig=key[1],
        donate_key=donate_key,
        finding_counts=finding_counts,
        findings_digest=_plancert.findings_digest(
            finding_counts, str(ruleset_digest)),
        effect_memoizable=bool(effects_rep.memoizable),
        effect_reason=str(effects_rep.reason),
        effect_class=str(effects_rep.program_class),
        effects=effects_rep,
        memo_ok=memo_ok,
        class_data=class_data,
        class_proof=class_proof,
        admit_est_bytes=admit_est,
        autotune_backend=at_backend,
        autotune_via=at_via,
        versions=_plancert.component_versions(),
        ruleset=tuple(rule_names),
        sig_fields=sig_fields,
        signature=signature,
    )
    try:
        with _lock:
            _store[key] = _Entry(
                cert, _plancert._ambient_probe(),
                _sharding_objs(leaf_vals, cert.leaf_order))
            _store.move_to_end(key)
            cap = _max_entries()
            while len(_store) > cap:
                _store.popitem(last=False)
                _bump("evictions")
            _bump("stores")
    except TypeError:       # unhashable program key — never certifiable
        return None
    _registry.inc("plancache.store")
    if _events.trace_enabled():
        ev = _plancert.to_payload(cert)
        ev["type"] = "plan_cert"
        _events.emit(ev)
    return cert


def class_plan_from(cert: _plancert.PlanCertificate) -> Optional[Any]:
    """Rebuild the compile-class plan a certificate vouches for.  The
    stored proof bound (token, policy) at certification; the
    ``class_policy`` signature field already proved the policy unchanged,
    so the plan is reconstructible without re-running the op-safety
    walk."""
    if cert.class_data is None:
        return None
    from ramba_tpu.compile import classes as _classes

    token, n, bucket, pad_slots, pad_waste = cert.class_data
    try:
        return _classes.ClassPlan(tuple(token), int(n), int(bucket),
                                  tuple(pad_slots), int(pad_waste))
    except Exception:  # noqa: BLE001 — fall back to fresh planning
        return None


# ---------------------------------------------------------------------------
# shared artifact tier (fleet/artifacts.py)
# ---------------------------------------------------------------------------


def _shared_tier() -> Optional[Any]:
    """``fleet.artifacts`` when the cross-process certificate lane is
    armed for THIS process, else None.  Single-controller only (same
    reasoning as the shared memo lane: under SPMD one rank adopting a
    verdict its peers re-derive would still agree — but the adoption
    probe's filesystem traffic is per-rank waste, and a half-warmed
    artifact dir must not split the ranks' hit/miss decisions)."""
    if not os.environ.get("RAMBA_ARTIFACTS"):
        return None
    if (os.environ.get("RAMBA_PLANCERT_SHARED") or "1").strip().lower() \
            in _OFF:
        return None
    if _events._rank_info()[1] != 1:
        return None
    try:
        from ramba_tpu.fleet import artifacts as _artifacts
    except Exception:  # noqa: BLE001 — the tier must never break flushes
        return None
    if not _artifacts.armed():
        return None
    return _artifacts


#: integrity-envelope schema tag for shared certificate blobs
CERT_SCHEMA = "plancert.json"


def _cert_path(tier: Any, chash: str) -> str:
    return os.path.join(tier.artifacts_dir(), "plancert",
                        f"{chash}.json")


def publish(cert: Optional[_plancert.PlanCertificate]) -> bool:
    """Write a certificate to the shared artifact tier (keyed by chash)
    so peer replicas can adopt it.  Serving-plane call site
    (``serve/pipeline.py``); best-effort, never raises."""
    if cert is None or cert.chash is None or not enabled():
        return False
    tier = _shared_tier()
    if tier is None:
        return False
    try:
        data = json.dumps(_plancert.to_payload(cert),
                          sort_keys=True).encode()
    except (TypeError, ValueError):
        return False
    if not tier.store_blob(_cert_path(tier, cert.chash),
                           _integrity.wrap(data, CERT_SCHEMA)):
        return False
    with _lock:
        _bump("publishes")
    _registry.inc("plancache.publish")
    return True


def _adopt_shared(program: Any, leaf_vals: Sequence[Any],
                  donate_key: Tuple[int, ...],
                  key: Tuple[Any, ...]) -> Optional["_Entry"]:
    """On a local miss, probe the shared tier by chash and adopt a peer's
    certificate when its per-flush inputs match ours exactly.  Pays one
    canonicalization — still far cheaper than the full pipeline — and
    installs the adopted certificate locally so repeats are plain hits."""
    tier = _shared_tier()
    if tier is None:
        return None
    from ramba_tpu.analyze import canon as _canon

    try:
        cf = _canon.try_canonicalize(program)
    except Exception:  # noqa: BLE001
        return None
    if cf is None:
        return None
    raw = tier.load_blob(_cert_path(tier, cf.chash))
    if raw is None:
        return None
    try:
        payload = _integrity.unwrap(raw, CERT_SCHEMA, site="plancert:blob")
    except _integrity.IntegrityError:
        # digest mismatch or unstamped pre-plane blob: evict, re-derive
        tier.evict(_cert_path(tier, cf.chash))
        return None
    try:
        obj = json.loads(payload.decode())
    except (ValueError, UnicodeDecodeError) as e:
        _integrity.failure("plancert:blob", "deserialize",
                           detail=repr(e)[:200], chash=cf.chash)
        tier.evict(_cert_path(tier, cf.chash))
        return None
    cert = _plancert.from_payload(obj)
    if cert is None:
        tier.evict(_cert_path(tier, cf.chash))
        return None
    # per-flush inputs must match exactly; ambient state is checked by
    # the caller's signature comparison like any local hit
    if cert.aval_sig != key[1] or cert.donate_key != tuple(donate_key):
        return None
    if cert.versions != _plancert.component_versions():
        return None
    # probe=None: the home process's ambient raw values are unknowable,
    # so the first redemption validates through the full signature
    # comparison and self-heals the fast path.
    entry = _Entry(cert, None)
    try:
        with _lock:
            _store[key] = entry
            _store.move_to_end(key)
            cap = _max_entries()
            while len(_store) > cap:
                _store.popitem(last=False)
            _bump("adopted")
    except TypeError:       # unhashable program key — never certifiable
        return None
    _registry.inc("plancache.adopted")
    return entry


# ---------------------------------------------------------------------------
# batched coherence (ROADMAP 2b, hits only)
# ---------------------------------------------------------------------------


def _note_hit() -> None:
    """Per-hit bookkeeping for the epoch-batched coherence round: the
    agree() exchange is deferred until RAMBA_PLANCERT_AGREE hits have
    accumulated, so multi-controller ranks pay the collective once per
    batch instead of once per flush."""
    global _pending_hits
    if not _coherence.engaged():
        return
    with _lock:
        _pending_hits += 1
        due = _pending_hits >= _agree_batch()
    if due:
        flush_agree()


def flush_agree() -> None:
    """Run the deferred hit-count agreement round now (batch boundary,
    tests, or drain).  Ranks propose their batch hit count; a rank
    seeing a smaller agreed count than its own has hits its peers did
    not — it clears its local certificates and re-converges through
    fresh analysis."""
    global _pending_hits
    with _lock:
        n = _pending_hits
        _pending_hits = 0
    if n <= 0 or not _coherence.engaged():
        return
    agreed = _coherence.agree("plan:hits", n, reduce="min")
    with _lock:
        _bump("agree_rounds")
    if agreed < n:
        with _lock:
            _store.clear()
            _bump("divergences")
        _registry.inc("plancache.divergence")
        _events.emit({
            "type": "plan_divergence", "proposed": int(n),
            "agreed": int(agreed),
        })


# ---------------------------------------------------------------------------
# introspection
# ---------------------------------------------------------------------------


def snapshot() -> Dict[str, Any]:
    """Point-in-time view for diagnostics/bench: counters, stale causes,
    and the derived hit rate (hits + shared hits over lookups)."""
    with _lock:
        s = dict(_stats)
        causes = dict(_stale_causes)
        size = len(_store)
        pending = _pending_hits
    lookups = s.get("lookups", 0)
    hits = s.get("hits", 0) + s.get("shared_hits", 0)
    return {
        "enabled": enabled(),
        "entries": size,
        "pending_agree_hits": pending,
        "hit_rate": (hits / lookups) if lookups else 0.0,
        "stale_causes": causes,
        **s,
    }


def reset() -> None:
    """Drop every certificate and counter (tests)."""
    global _pending_hits
    with _lock:
        _store.clear()
        _stats.clear()
        _stale_causes.clear()
        _pending_hits = 0
