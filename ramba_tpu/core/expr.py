"""Lazy expression graph.

TPU-native replacement for the reference's lazy DAG + deferred-op fuser
(/root/reference/ramba/ramba.py:4387-5130 ``DAG`` and :8039-8532
``deferred_op``).  The reference accumulates op *strings* and compiles the
concatenation with Numba on every worker; here we accumulate structured
expression nodes and flush them as ONE traced/jitted function over sharded
``jax.Array``s (see core/fuser.py).  XLA performs the loop fusion the
reference's ``deferred_op.execute`` does by hand (ramba.py:8140-8255), and
GSPMD inserts the cross-shard communication the reference routes through its
queue transports.

Every node is immutable.  Evaluation semantics live in the ``OPS`` table —
plain Python functions over jax values; no source-string codegen, no eval().
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

# ---------------------------------------------------------------------------
# Nodes
# ---------------------------------------------------------------------------

# jax.typeof only exists in newer jax; jax.core.get_aval returns the same
# ShapedArray (shape/dtype/weak_type) for concrete arrays on older releases.
_typeof = getattr(jax, "typeof", None)
if _typeof is None:

    def _typeof(value):
        return jax.core.get_aval(value)


class Expr:
    """Base class. ``aval`` is a jax.ShapeDtypeStruct-like with shape/dtype."""

    __slots__ = ("aval", "__weakref__")

    @property
    def shape(self):
        return self.aval.shape

    @property
    def dtype(self):
        return self.aval.dtype


class Const(Expr):
    """Leaf holding a concrete (usually sharded) jax.Array."""

    __slots__ = ("value",)

    def __init__(self, value):
        self.value = value
        self.aval = _typeof(value)


class Scalar(Expr):
    """Leaf holding a python scalar.

    Passed into the jitted flush as a (weakly-typed) argument so that changing
    the *value* of a scalar does not invalidate the compile cache — the analog
    of the reference pickling op operands separately from the generated source
    whose name is a hash of the code only (ramba.py:8260-8265,8286-8298).
    """

    __slots__ = ("value",)

    def __init__(self, value):
        self.value = value
        self.aval = jax.eval_shape(lambda: jnp.asarray(value))


class Node(Expr):
    """Interior node: ``OPS[op](static, *args)``."""

    __slots__ = ("op", "static", "args")

    def __init__(self, op: str, static: tuple, args: Sequence[Expr], aval=None):
        self.op = op
        self.static = static
        self.args = tuple(args)
        if aval is None:
            aval = infer_aval(op, static, [a.aval for a in self.args])
        self.aval = aval


def as_expr(x: Any) -> Expr:
    if isinstance(x, Expr):
        return x
    if isinstance(x, (bool, int, float, complex, np.bool_, np.integer, np.floating)):
        return Scalar(x)
    if isinstance(x, (np.ndarray, jax.Array)):
        return Const(jnp.asarray(x))
    raise TypeError(f"cannot lift {type(x)} into an expression")


_aval_memo: dict = {}

_MEMO_SAFE_TYPES = (str, bytes, int, float, complex, bool, type(None), np.dtype)


def _value_hashable(x) -> bool:
    """True if ``x`` hashes by value (safe as a memo key component)."""
    if isinstance(x, _MEMO_SAFE_TYPES) or isinstance(x, (np.generic,)):
        return True
    if isinstance(x, (tuple, frozenset)):
        return all(_value_hashable(e) for e in x)
    return False


def infer_aval(op: str, static: tuple, arg_avals: Sequence[Any]) -> Any:
    """Shape/dtype inference by abstract evaluation of the op's own eval rule —
    guarantees inference always matches execution (the reference instead
    duplicates shape/dtype logic in every ``DAGshape``-returning API function,
    ramba.py:5133-5165).  Memoized: eval_shape costs ~1 ms, which would
    otherwise dominate graph-build time for op-chain workloads."""
    fn = OPS[op]
    try:
        key = (op, static, tuple(
            (tuple(a.shape), str(a.dtype), bool(getattr(a, "weak_type", False)))
            for a in arg_avals
        ))
        hash(key)
        if not _value_hashable(static):
            # identity-hashed statics (closures, array literals) can never
            # hit, and each miss would pin the object in the memo
            key = None
    except TypeError:
        key = None
    if key is not None:
        hit = _aval_memo.get(key)
        if hit is not None:
            return hit
    out = jax.eval_shape(lambda *a: fn(static, *a), *arg_avals)
    if key is not None:
        if len(_aval_memo) > 8192:
            _aval_memo.clear()
        _aval_memo[key] = out
    return out


# ---------------------------------------------------------------------------
# Op evaluation table
# ---------------------------------------------------------------------------

OPS: dict[str, Callable] = {}


def defop(name: str) -> Callable[[Callable], Callable]:
    def deco(fn: Callable) -> Callable:
        OPS[name] = fn
        return fn

    return deco


# -- elementwise maps --------------------------------------------------------

UNARY = {
    name: getattr(jnp, name)
    for name in [
        "negative", "positive", "absolute", "abs", "sqrt", "square", "cbrt",
        "reciprocal", "sign", "exp", "exp2", "expm1", "log", "log2", "log10",
        "log1p", "sin", "cos", "tan", "arcsin", "arccos", "arctan", "sinh",
        "cosh", "tanh", "arcsinh", "arccosh", "arctanh", "floor", "ceil",
        "trunc", "rint", "isnan", "isinf", "isfinite", "logical_not", "invert",
        "conj", "conjugate", "real", "imag", "degrees", "radians", "deg2rad",
        "rad2deg", "signbit", "spacing", "fabs", "sinc", "i0", "angle",
    ]
    if hasattr(jnp, name)
}

BINARY = {
    name: getattr(jnp, name)
    for name in [
        "add", "subtract", "multiply", "true_divide", "divide", "floor_divide",
        "mod", "remainder", "fmod", "power", "float_power", "arctan2", "hypot",
        "maximum", "minimum", "fmax", "fmin", "logaddexp", "logaddexp2",
        "logical_and", "logical_or", "logical_xor", "bitwise_and", "bitwise_or",
        "bitwise_xor", "left_shift", "right_shift", "equal", "not_equal",
        "less", "less_equal", "greater", "greater_equal", "copysign",
        "nextafter", "heaviside", "gcd", "lcm", "ldexp",
    ]
    if hasattr(jnp, name)
}

MAPFN: dict[str, Callable] = {}
MAPFN.update(UNARY)
MAPFN.update(BINARY)
MAPFN["where"] = jnp.where
MAPFN["matmul_elem"] = jnp.multiply  # placeholder slot


def _np_loop_dtypes(fname, args):
    """NumPy's exact (input..., output) dtypes for this ufunc application
    under NEP 50 — weak-typed jax values stand in as python scalars.
    Returns None when numpy promotion should not be enforced: x64 disabled
    (32-bit TPU execution keeps jax's own lattice — widening everything to
    f64 there would be both slow and silently truncated anyway), fname not
    a numpy ufunc, or unresolvable."""
    import jax as _jax

    if not _jax.config.jax_enable_x64:
        return None
    uf = getattr(np, fname, None)
    if not isinstance(uf, np.ufunc) or uf.nin != len(args) or uf.nout != 1:
        return None
    ins = []
    for a in args:
        dt = getattr(a, "dtype", None)
        if dt is None:
            if isinstance(a, (bool, int, float, complex)):
                ins.append(type(a))
                continue
            return None
        if getattr(a, "weak_type", False):
            kind = np.dtype(dt).kind
            ins.append({"b": bool, "i": int, "u": int, "f": float,
                        "c": complex}.get(kind, np.dtype(dt)))
        else:
            ins.append(np.dtype(dt))
    try:
        return uf.resolve_dtypes(tuple(ins) + (None,))
    except Exception:
        return None


@defop("map")
def _op_map(static, *args):
    (fname,) = static
    if fname == "where" and len(args) == 3 and jax.config.jax_enable_x64:
        # np.where is not a ufunc; its value operands take the numpy
        # common dtype (NEP 50)
        want = _np_loop_dtypes("add", args[1:])
        if want is not None:
            a2 = args[1] if getattr(args[1], "dtype", None) == want[-1] \
                else jnp.asarray(args[1], want[-1])
            a3 = args[2] if getattr(args[2], "dtype", None) == want[-1] \
                else jnp.asarray(args[2], want[-1])
            return jnp.where(args[0], a2, a3)
    loop = _np_loop_dtypes(fname, args)
    if loop is not None:
        # cast INPUTS to numpy's loop dtypes (computing in the wider type,
        # not just relabeling the result) — the reference computes with
        # numpy/Numba and so gets these semantics for free
        args = tuple(
            a if getattr(a, "dtype", None) == d
            and not getattr(a, "weak_type", True)
            else jnp.asarray(a, d)
            for a, d in zip(args, loop[:-1])
        )
        out = MAPFN[fname](*args)
        if out.dtype != loop[-1]:
            out = out.astype(loop[-1])
        return out
    return MAPFN[fname](*args)


def make_map(fname: str, operands: Sequence[Expr]) -> Expr:
    """Build an elementwise map node, strength-reducing ``power`` by a small
    static integer exponent into a multiply chain.

    Scalar operands are normally runtime arguments (to keep the compile cache
    value-independent), but a runtime exponent forces stablehlo.power — the
    exp/log path on the TPU VPU — where a literal ``x**2`` would compile to one
    multiply.  The reference has the same class of peephole in its codegen
    (division rewritten to multiply-by-reciprocal, ramba.py:6121-6126)."""
    if fname == "power" and len(operands) == 2:
        e = operands[1]
        if (
            isinstance(e, Scalar)
            and isinstance(e.value, (int, np.integer))
            and not isinstance(e.value, (bool, np.bool_))
            and 1 <= int(e.value) <= 4
            and operands[0].dtype != np.bool_  # bool ** int promotes to int8
        ):
            x = operands[0]
            out = x
            for _ in range(int(e.value) - 1):
                out = Node("map", ("multiply",), [out, x])
            return out
    return Node("map", (fname,), list(operands))


@defop("cast")
def _op_cast(static, x):
    (dtype,) = static
    return x.astype(jnp.dtype(dtype))


@defop("round")
def _op_round(static, x):
    (decimals,) = static
    return jnp.round(x, decimals)


# -- reductions --------------------------------------------------------------

REDFN = {
    name: getattr(jnp, name)
    for name in [
        "sum", "prod", "min", "max", "any", "all", "mean", "var", "std",
        "nansum", "nanprod", "nanmin", "nanmax", "nanmean", "nanvar", "nanstd",
        "argmin", "argmax", "nanargmin", "nanargmax", "count_nonzero", "median",
        "nanmedian", "ptp",
    ]
    if hasattr(jnp, name)
}


@defop("reduce")
def _op_reduce(static, x):
    fname, axis, keepdims, ddof = static
    fn = REDFN[fname]
    kwargs = {}
    if fname in ("var", "std", "nanvar", "nanstd") and ddof is not None:
        kwargs["ddof"] = ddof
    if fname in ("argmin", "argmax", "nanargmin", "nanargmax", "median", "nanmedian"):
        # no keepdims arg pre-numpy-2 signature quirks; normalize after
        r = fn(x, axis=axis)
        if keepdims and axis is not None:
            r = jnp.expand_dims(r, axis)
        elif keepdims and axis is None:
            r = jnp.reshape(r, (1,) * x.ndim)
        return r
    return fn(x, axis=axis, keepdims=keepdims, **kwargs)


@defop("reduce_where")
def _op_reduce_where(static, x, mask):
    """Masked reduction — the reference's maskarray path forces guarded
    reduction kernels (ramba.py:5908-5911,8476-8478)."""
    fname, axis, keepdims = static
    fn = REDFN[fname]
    if fname in ("mean",):
        return jnp.sum(jnp.where(mask, x, 0), axis=axis, keepdims=keepdims) / jnp.sum(
            mask, axis=axis, keepdims=keepdims
        )
    identities = {"sum": 0, "prod": 1, "any": False, "all": True}
    if fname in ("min", "max"):
        if x.dtype == jnp.dtype(bool):
            ident = fname == "min"  # min identity=True, max identity=False
        elif jnp.issubdtype(x.dtype, jnp.floating):
            ident = jnp.finfo(x.dtype).max if fname == "min" else jnp.finfo(x.dtype).min
        else:
            ident = jnp.iinfo(x.dtype).max if fname == "min" else jnp.iinfo(x.dtype).min
    else:
        ident = identities[fname]
    return fn(jnp.where(mask, x, ident), axis=axis, keepdims=keepdims)


@defop("cumulative")
def _op_cumulative(static, x):
    fname, axis = static
    # numpy promotes sub-word integer scans to the platform int (int64
    # under x64), same as sum/prod; jnp keeps the input dtype
    kind = jnp.dtype(x.dtype).kind
    if jax.config.jax_enable_x64 and kind in "biu":
        want = {"b": jnp.int64, "i": jnp.int64, "u": jnp.uint64}[kind]
        if jnp.dtype(x.dtype).itemsize < 8:
            x = x.astype(want)
    return getattr(jnp, fname)(x, axis=axis)


# -- indexing / views --------------------------------------------------------


def encode_index(idx) -> tuple:
    """Canonical hashable encoding of a basic index tuple."""
    if not isinstance(idx, tuple):
        idx = (idx,)
    out = []
    for it in idx:
        if it is None:
            out.append(("n",))
        elif it is Ellipsis:
            out.append(("e",))
        elif isinstance(it, slice):
            out.append(("s", it.start, it.stop, it.step))
        elif isinstance(it, (int, np.integer)):
            out.append(("i", int(it)))
        else:
            raise TypeError(f"not a basic index: {it!r}")
    return tuple(out)


def decode_index(enc: tuple):
    out = []
    for it in enc:
        if it[0] == "n":
            out.append(None)
        elif it[0] == "e":
            out.append(Ellipsis)
        elif it[0] == "s":
            out.append(slice(it[1], it[2], it[3]))
        else:
            out.append(it[1])
    return tuple(out)


@defop("getitem")
def _op_getitem(static, x):
    (enc,) = static
    return x[decode_index(enc)]


@defop("setitem")
def _op_setitem(static, x, v):
    (enc,) = static
    return x.at[decode_index(enc)].set(v.astype(x.dtype))


@defop("getitem_adv")
def _op_getitem_adv(static, x, *indexers):
    """Fancy-index gather.  The reference builds an all2all owner-lookup gather
    machine (ramba.py:6429-6545); on TPU this is a single XLA gather and GSPMD
    owns the communication."""
    enc, arraypos = static
    idx = list(decode_index(enc))
    it = iter(indexers)
    for p in arraypos:
        idx[p] = next(it)
    return x[tuple(idx)]


@defop("setitem_adv")
def _op_setitem_adv(static, x, v, *indexers):
    """Fancy-index scatter (reference: setitem_array_executor,
    ramba.py:6143-6295).  Duplicate indices follow XLA scatter semantics
    (unspecified winner), matching the reference's documented behavior
    (docs/index.md:71)."""
    enc, arraypos = static
    idx = list(decode_index(enc))
    it = iter(indexers)
    for p in arraypos:
        idx[p] = next(it)
    return x.at[tuple(idx)].set(v.astype(x.dtype))


@defop("masked_fill")
def _op_masked_fill(static, x, mask, v):
    """Boolean-mask write as a guarded select — the reference emits
    ``if mask: ...`` codelines (ramba.py:8476-8478); here it is a fused where."""
    return jnp.where(mask, v.astype(x.dtype) if hasattr(v, "astype") else v, x)


@defop("permute")
def _op_permute(static, x):
    (axes,) = static
    return jnp.transpose(x, axes)


@defop("reshape")
def _op_reshape(static, x):
    (shape,) = static
    return jnp.reshape(x, shape)


@defop("broadcast_to")
def _op_broadcast_to(static, x):
    (shape,) = static
    return jnp.broadcast_to(x, shape)


@defop("flip")
def _op_flip(static, x):
    (axes,) = static
    return jnp.flip(x, axes)


# -- structural --------------------------------------------------------------


def _np_common_dtype(args):
    """numpy's NEP-50 common dtype for a join of arrays, or None when jax
    promotion should stand (x64 off, or unresolvable)."""
    if not jax.config.jax_enable_x64:
        return None
    try:
        want = np.result_type(*[np.dtype(a.dtype) for a in args])
    except Exception:
        return None
    return want


@defop("concatenate")
def _op_concatenate(static, *args):
    (axis,) = static
    want = _np_common_dtype(args)
    if want is not None:
        args = [a.astype(want) if a.dtype != want else a for a in args]
    return jnp.concatenate(args, axis=axis)


@defop("stack")
def _op_stack(static, *args):
    (axis,) = static
    want = _np_common_dtype(args)
    if want is not None:
        args = [a.astype(want) if a.dtype != want else a for a in args]
    return jnp.stack(args, axis=axis)


@defop("pad")
def _op_pad(static, x, *consts):
    pad_width, mode = static
    if mode == "constant" and consts:
        return jnp.pad(x, pad_width, mode=mode, constant_values=consts[0])
    if mode == "empty":
        mode = "constant"
    return jnp.pad(x, pad_width, mode=mode)


@defop("moveaxis")
def _op_moveaxis(static, x):
    src, dst = static
    return jnp.moveaxis(x, src, dst)


@defop("repeat")
def _op_repeat(static, x):
    repeats, axis = static
    return jnp.repeat(x, repeats, axis=axis)


@defop("tile")
def _op_tile(static, x):
    (reps,) = static
    return jnp.tile(x, reps)


@defop("tril")
def _op_tril(static, x):
    (k,) = static
    return jnp.tril(x, k)


@defop("triu")
def _op_triu(static, x):
    (k,) = static
    return jnp.triu(x, k)


@defop("diag")
def _op_diag(static, x):
    (k,) = static
    return jnp.diag(x, k)


@defop("sort")
def _op_sort(static, x):
    (axis,) = static
    return jnp.sort(x, axis=axis)


@defop("argsort")
def _op_argsort(static, x):
    (axis,) = static
    return jnp.argsort(x, axis=axis)


@defop("take")
def _op_take(static, x, indices):
    (axis, mode) = static
    return jnp.take(x, indices, axis=axis, mode=mode)


# -- linear algebra ----------------------------------------------------------


@defop("matmul")
def _op_matmul(static, a, b):
    """The reference implements a 3-strategy distributed GEMM by hand
    (ramba.py:2493-3051,6993-7618); on TPU the MXU + GSPMD path is a single
    jnp.matmul with a deliberate accumulation dtype."""
    (prec,) = static
    return jnp.matmul(a, b, precision=prec)


@defop("dot")
def _op_dot(static, a, b):
    (prec,) = static
    return jnp.dot(a, b, precision=prec)


@defop("tensordot")
def _op_tensordot(static, a, b):
    (axes, prec) = static
    return jnp.tensordot(a, b, axes=axes, precision=prec)


@defop("einsum")
def _op_einsum(static, *args):
    (subscripts, prec) = static
    return jnp.einsum(subscripts, *args, precision=prec)


@defop("outer")
def _op_outer(static, a, b):
    return jnp.outer(a, b)


@defop("trace")
def _op_trace(static, a):
    offset, axis1, axis2 = static
    return jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2)


# -- creation ----------------------------------------------------------------


def _constrain(x, spec_tuple):
    """Apply a sharding constraint from an encoded PartitionSpec."""
    from jax.sharding import NamedSharding, PartitionSpec

    from ramba_tpu.parallel import mesh as _mesh

    if spec_tuple is None:
        return x
    spec = PartitionSpec(*spec_tuple)
    try:
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(_mesh.get_mesh(), spec)
        )
    except Exception:  # single-device or incompatible mesh: constraint is moot
        return x


@defop("arange")
def _op_arange(static, start, step):
    n, dtype, spec = static
    x = start + step * jax.lax.iota(jnp.dtype(dtype), n)
    return _constrain(x, spec)


@defop("linspace")
def _op_linspace(static, start, stop):
    num, endpoint, dtype, spec = static
    x = jnp.linspace(start, stop, num, endpoint=endpoint, dtype=jnp.dtype(dtype))
    return _constrain(x, spec)


@defop("full")
def _op_full(static, fill):
    shape, dtype, spec = static
    x = jnp.full(shape, fill, dtype=jnp.dtype(dtype))
    return _constrain(x, spec)


@defop("eye")
def _op_eye(static):
    n, m, k, dtype, spec = static
    return _constrain(jnp.eye(n, m, k=k, dtype=jnp.dtype(dtype)), spec)


@defop("fromfunction")
def _op_fromfunction(static, *args):
    """Index-space filler: the reference's Filler/fromfunction kernels
    (ramba.py:141-150,1535-1595,8952-8961) generate per-shard index loops; here
    broadcasted iotas feed a traced user function and XLA fuses the rest."""
    shape, dtype, spec, fn, with_index = static
    idx = [
        jax.lax.broadcasted_iota(jnp.int32, shape, d) for d in range(len(shape))
    ]
    # _call_kernel gives fromfunction/init_array fillers the same treatment
    # as skeleton kernels: NumPy-ufunc rerouting and auto-lowered data
    # branches (the reference Numba-compiles these fillers too,
    # ramba.py:1535-1595)
    from ramba_tpu.skeletons import _call_kernel

    if with_index:
        r = _call_kernel(fn, *idx, *args)
    else:
        r = _call_kernel(fn, *args)
    r = jnp.asarray(r)
    if dtype is not None:
        r = r.astype(jnp.dtype(dtype))
    if r.shape != tuple(shape):
        r = jnp.broadcast_to(r, shape)
    return _constrain(r, spec)


@defop("random")
def _op_random(static, key, *params):
    """Distributed RNG.  The reference seeds ``seed + worker_num`` per worker
    and runs np.random inside each shard (ramba.py:3824-3825,
    ramba/random/random.py); here a single jax.random call over the sharded
    output shape gives device-count-invariant streams."""
    kind, shape, dtype, spec = static
    shape = tuple(shape)
    dt = jnp.dtype(dtype)
    if kind == "uniform":
        x = jax.random.uniform(key, shape, dtype=dt)
    elif kind == "normal":
        x = jax.random.normal(key, shape, dtype=dt)
    elif kind == "randint":
        lo, hi = params
        x = jax.random.randint(key, shape, lo, hi, dtype=dt)
    elif kind == "uniform_range":
        lo, hi = params
        x = jax.random.uniform(key, shape, dtype=dt, minval=lo, maxval=hi)
    elif kind == "permutation":
        # n is static (the node's output shape)
        x = jax.random.permutation(key, shape[0])
    elif kind == "permutation_array":
        (arr,) = params
        x = jax.random.permutation(key, arr)
    elif kind == "exponential":
        x = jax.random.exponential(key, shape, dtype=dt)
    elif kind == "poisson":
        (lam,) = params
        x = jax.random.poisson(key, lam, shape).astype(dt)
    elif kind == "beta":
        a, b = params
        x = jax.random.beta(key, a, b, shape, dtype=dt)
    elif kind == "gamma":
        (a,) = params
        x = jax.random.gamma(key, a, shape, dtype=dt)
    elif kind == "binomial":
        n, pr = params
        x = jax.random.binomial(key, n, pr, shape).astype(dt)
    elif kind in ("choice", "choice_norepl"):
        replace = kind == "choice"
        if len(params) == 2:
            arr, p = params
            x = jax.random.choice(key, arr, shape, replace=replace, p=p)
        else:
            (arr,) = params
            x = jax.random.choice(key, arr, shape, replace=replace)
    else:
        raise ValueError(kind)
    if kind in ("beta", "gamma") and jax.config.jax_enable_x64:
        # jax<=0.4.37's gamma sampler (a while_loop rejection sampler, also
        # backing beta) miscompiles under SPMD partitioning with x64 enabled:
        # the partitioner emits an s64-vs-s32 compare in the loop condition
        # and the HLO verifier rejects it.  Leave these outputs unconstrained
        # — GSPMD still shards the consumer; only the sampler stays local.
        return x
    return _constrain(x, spec)


@defop("shard_hint")
def _op_shard_hint(static, x):
    (spec,) = static
    return _constrain(x, spec)


# -- host-function escape hatch (smap with a traced python function) ---------


@defop("apply")
def _op_apply(static, *args):
    """Run a user-supplied traceable function over the operands — the
    skeleton layer (smap/sreduce, reference ramba.py:9863-9984) lowers here
    when the function is jax-traceable."""
    (fn,) = static
    return fn(*args)
