"""ramba_tpu.core subpackage."""
