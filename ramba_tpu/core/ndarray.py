"""The user-visible distributed array.

TPU-native counterpart of the reference's front-end array stack:

* ``ndarray`` (/root/reference/ramba/ramba.py:5409-6901) — here a thin lazy
  handle over an expression graph whose leaves are sharded ``jax.Array``s.
* ``bdarray`` gid-registry + refcount-triggered remote deletion
  (ramba.py:1049-1158) — not needed: Python GC over the expression graph plus
  jax.Array reference counting frees shards automatically.
* view machinery (views share a gid and a shardview; ramba.py:5545-5565) —
  here a view holds its parent plus a reversible view op; reads re-derive the
  expression from the parent's *current* state, writes push an updated
  expression back through the chain, which gives NumPy view aliasing
  semantics on top of purely functional jax.

Operator methods are installed from op tables like the reference's
``make_method`` loops (ramba.py:7842-7993).
"""

from __future__ import annotations

import builtins
import itertools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ramba_tpu import common
from ramba_tpu.core import expr as E
from ramba_tpu.core import fuser
from ramba_tpu.core.expr import Const, Expr, Node, Scalar
from ramba_tpu.parallel import mesh as _mesh

_seq_counter = itertools.count()


# ---------------------------------------------------------------------------
# View ops — reversible transforms between a parent array and a derived view.
# ---------------------------------------------------------------------------


class ViewOp:
    def read(self, base_expr: Expr) -> Expr:
        raise NotImplementedError

    def write(self, base_expr: Expr, value_expr: Expr) -> Expr:
        """Return a new base expression with the viewed region replaced."""
        raise NotImplementedError


class SliceView(ViewOp):
    """Basic indexing view (slices/ints/newaxis; ± steps supported — the
    reference's mapslice/shardview algebra, shardview_array.py:414-614)."""

    def __init__(self, enc):
        self.enc = enc

    def read(self, base_expr):
        return Node("getitem", (self.enc,), [base_expr])

    def write(self, base_expr, value_expr):
        return Node("setitem", (self.enc,), [base_expr, value_expr])


class PermuteView(ViewOp):
    """Transpose/moveaxis-family view (reference: remap_axis,
    shardview_array.py:1024-1042)."""

    def __init__(self, axes):
        self.axes = tuple(axes)
        inv = [0] * len(self.axes)
        for i, a in enumerate(self.axes):
            inv[a] = i
        self.inv = tuple(inv)

    def read(self, base_expr):
        return Node("permute", (self.axes,), [base_expr])

    def write(self, base_expr, value_expr):
        return Node("permute", (self.inv,), [value_expr])


class ReshapeView(ViewOp):
    """Reshape is always a live view here (writes map back through the
    row-major bijection); the reference needs an explicit element-remap
    redistribution for the general case (RemoteState.reshape,
    ramba.py:2409-2491) — XLA owns that data movement now."""

    def __init__(self, shape, base_shape):
        self.shape = tuple(shape)
        self.base_shape = tuple(base_shape)

    def read(self, base_expr):
        return Node("reshape", (self.shape,), [base_expr])

    def write(self, base_expr, value_expr):
        return Node("reshape", (self.base_shape,), [value_expr])


class BroadcastView(ViewOp):
    def __init__(self, shape):
        self.shape = tuple(shape)

    def read(self, base_expr):
        return Node("broadcast_to", (self.shape,), [base_expr])

    def write(self, base_expr, value_expr):
        raise ValueError("broadcast views are read-only")


# ---------------------------------------------------------------------------
# ndarray
# ---------------------------------------------------------------------------


def _unary_table():
    return {
        # python operator protocol
        "__neg__": "negative", "__pos__": "positive", "__abs__": "absolute",
        "__invert__": "invert",
    }


_BINOPS = {
    # name -> (python op suffix, map fn)  — reference op tables
    # array_binop_funcs at ramba.py:7893-7921
    "add": "add", "sub": "subtract", "mul": "multiply",
    "truediv": "true_divide", "floordiv": "floor_divide", "mod": "mod",
    "pow": "power", "and": "bitwise_and", "or": "bitwise_or",
    "xor": "bitwise_xor", "lshift": "left_shift", "rshift": "right_shift",
}

_CMPOPS = {
    "lt": "less", "le": "less_equal", "gt": "greater", "ge": "greater_equal",
    "eq": "equal", "ne": "not_equal",
}

# unary methods installed on the class (reference array_unaryop_funcs,
# ramba.py:7923-7960)
_UNARY_METHODS = [
    "abs", "absolute", "sqrt", "square", "exp", "log", "sin", "cos", "tan",
    "arcsin", "arccos", "arctan", "sinh", "cosh", "tanh", "arcsinh",
    "arccosh", "arctanh", "floor", "ceil", "trunc", "isnan", "isinf",
    "negative", "log2", "log10", "log1p", "expm1", "sign", "reciprocal",
]

_REDUCTIONS = ["sum", "prod", "min", "max", "any", "all", "mean"]


class ndarray_flags:
    """Minimal flags object (reference: ndarray_flags ramba.py:5365 and
    set_writeable_executor ramba.py:5358-5365)."""

    __slots__ = ("_arr",)

    def __init__(self, arr):
        self._arr = arr

    @property
    def writeable(self):
        return not self._arr._readonly

    @writeable.setter
    def writeable(self, value):
        arr = self._arr
        if value:
            # every ancestor must be writable (write_expr recurses through
            # the whole view chain, so the flag must agree with it)
            base = arr._base
            while base is not None:
                if base._readonly:
                    raise ValueError(
                        "cannot set WRITEABLE flag to True of this array"
                    )
                base = base._base
        arr._readonly = not value

    def __getitem__(self, name):
        if name in ("WRITEABLE", "writeable"):
            return self.writeable
        raise KeyError(name)

    def __setitem__(self, name, value):
        if name in ("WRITEABLE", "writeable"):
            self.writeable = value
        else:
            raise KeyError(name)


class ndarray:
    __slots__ = ("_expr", "_base", "_view", "_aval", "_seq", "_readonly",
                 "__weakref__")

    # Win dispatch over numpy arrays in mixed expressions.
    __array_priority__ = 100.0

    def __init__(self, expr: Optional[Expr] = None, base: "ndarray" = None,
                 view: ViewOp = None, aval=None):
        self._seq = next(_seq_counter)
        self._base = base
        self._view = view
        self._expr = None
        # views of read-only arrays are read-only (numpy semantics)
        self._readonly = base._readonly if base is not None else False
        if base is not None:
            self._aval = (
                aval if aval is not None
                else view.read(_AbstractLeaf(base._aval)).aval
            )
        else:
            assert expr is not None
            self._set_expr(expr)
            self._aval = expr.aval
            if aval is not None:
                self._aval = aval

    # -- expression plumbing --------------------------------------------------

    def _set_expr(self, new: Expr):
        old = self._expr
        if isinstance(old, Const):
            fuser.owner_decref(old.value)
        self._expr = new
        if isinstance(new, Const):
            fuser.owner_incref(new.value, new)
            fuser.unregister_pending(self)
        else:
            fuser.register_pending(self)
            fuser.note_node_created(self)

    def __del__(self):
        try:
            if self._base is None and isinstance(self._expr, Const):
                fuser.owner_decref(self._expr.value)
        except Exception:
            pass

    def read_expr(self) -> Expr:
        if self._base is None:
            return self._expr
        return self._view.read(self._base.read_expr())

    def write_expr(self, value: Expr):
        # Only the written array's OWN flag gates the write (numpy: a view
        # taken before the base was frozen stays writeable and writes
        # through; ADVICE r1).  The recursion below must therefore bypass
        # the ancestors' flags.
        if self._readonly:
            raise ValueError("assignment destination is read-only")
        self._write_through(value)

    def _write_through(self, value: Expr):
        if self._base is None:
            self._set_expr(value)
        else:
            self._base._write_through(
                self._view.write(self._base.read_expr(), value)
            )

    @property
    def flags(self):
        return ndarray_flags(self)

    # -- basic properties -----------------------------------------------------

    @property
    def shape(self):
        return tuple(self._aval.shape)

    @property
    def dtype(self):
        return np.dtype(self._aval.dtype)

    @property
    def ndim(self):
        return len(self._aval.shape)

    @property
    def size(self):
        return int(np.prod(self._aval.shape, dtype=np.int64)) if self._aval.shape else 1

    @property
    def nbytes(self):
        return self.size * self.dtype.itemsize

    @property
    def itemsize(self):
        return self.dtype.itemsize

    @property
    def T(self):
        return self.transpose()

    @property
    def flat(self):
        return iter(self.reshape(-1).asarray())

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    # -- materialization ------------------------------------------------------

    def _value(self) -> jax.Array:
        """Concrete sharded jax.Array for this array (flushes lazy work)."""
        if self._base is None:
            if not isinstance(self._expr, Const):
                # flush the stream that OWNS this array's pending work
                # (waiting out any in-flight async flushes of it first) —
                # materialization from another thread/session must chase
                # the work to where it was built
                fuser.flush_for(self)
            if not isinstance(self._expr, Const):
                # Still lazy after a flush: an earlier failed flush
                # quarantined this array (the fuser pulls the roots of a
                # program that exhausted the degradation ladder out of the
                # pending registry).  Re-attempt this graph alone — an
                # innocent co-pending array materializes fine; a genuinely
                # broken one re-raises its real error here.
                self._set_expr(Const(fuser.flush_for(self,
                                                     extra=[self._expr])[0]))
            # leaf_value restores the buffer if the memory governor
            # spilled it to host while this array was cold
            return fuser.leaf_value(self._expr)
        base = self
        while base._base is not None:
            base = base._base
        return fuser.flush_for(base, extra=[self.read_expr()])[0]

    def asarray(self) -> np.ndarray:
        """Gather to a host NumPy array (reference: ndarray.asarray,
        ramba.py:5735-5765 — per-worker get_view + driver assembly; here a
        single device-to-host transfer).  Under multi-controller SPMD
        (jax.process_count() > 1) shards live on other processes'
        devices; an all-gather collective assembles the full value on
        EVERY process — the reference's MPI mode does the same driver
        assembly over its comm queues.  All processes must call this in
        lockstep (they do: each runs the same program)."""
        from ramba_tpu.utils import timing as _timing

        v = self._value()
        if not v.is_fully_addressable:
            from jax.experimental import multihost_utils
            from ramba_tpu.parallel import distributed as _distributed

            out = np.asarray(multihost_utils.process_allgather(v, tiled=True))
            _distributed.note_transfer("allgather", out.nbytes)
        else:
            out = np.asarray(v)
        _timing.note_transfer("device_to_host", out.nbytes)
        return out

    def __array__(self, dtype=None, copy=None):
        a = self.asarray()
        return a.astype(dtype) if dtype is not None else a

    def item(self):
        return self.asarray().item()

    def tolist(self):
        return self.asarray().tolist()

    def __bool__(self):
        return bool(self.asarray())

    def __int__(self):
        return int(self.asarray())

    def __float__(self):
        return float(self.asarray())

    def __index__(self):
        return int(self.asarray())

    def __complex__(self):
        return complex(self.asarray())

    def __repr__(self):
        return f"ramba_tpu.ndarray({self.asarray()!r:.200s}, shape={self.shape})"

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __hash__(self):
        return id(self)

    # -- elementwise helpers ---------------------------------------------------

    def _map(self, fname, *others, reverse=False):
        args = [as_exprable(o) for o in others]
        operands = [self.read_expr()] + args
        if reverse:
            operands = operands[::-1]
        return ndarray(E.make_map(fname, operands))

    def _inplace_map(self, fname, other):
        val = E.make_map(fname, [self.read_expr(), as_exprable(other)])
        if np.dtype(val.dtype) != self.dtype:
            val = Node("cast", (str(self.dtype),), [val])
        self.write_expr(val)
        return self

    def astype(self, dtype, copy=True):
        return ndarray(Node("cast", (str(np.dtype(dtype)),), [self.read_expr()]))

    def copy(self):
        return ndarray(self.read_expr())

    def fill(self, value):
        self.write_expr(
            Node("full", (self.shape, str(self.dtype),
                          _mesh.default_spec(self.shape)), [E.as_expr(value)])
        )

    def round(self, decimals=0):
        return ndarray(Node("round", (decimals,), [self.read_expr()]))

    def clip(self, a_min=None, a_max=None):
        out = self
        if a_min is not None:
            out = out._map("maximum", a_min)
        if a_max is not None:
            out = out._map("minimum", a_max)
        return out

    def conj(self):
        return self._map("conj")

    # -- reductions ------------------------------------------------------------

    def _reduce(self, fname, axis=None, keepdims=False, ddof=None):
        axis = _norm_axis(axis, self.ndim)
        out = ndarray(
            Node("reduce", (fname, axis, bool(keepdims), ddof), [self.read_expr()])
        )
        return out

    def var(self, axis=None, keepdims=False, ddof=0):
        return self._reduce("var", axis, keepdims, ddof)

    def std(self, axis=None, keepdims=False, ddof=0):
        return self._reduce("std", axis, keepdims, ddof)

    def argmin(self, axis=None):
        return self._reduce("argmin", axis)

    def argmax(self, axis=None):
        return self._reduce("argmax", axis)

    def cumsum(self, axis=None):
        x = self.reshape(-1) if axis is None else self
        return ndarray(Node("cumulative", ("cumsum", axis if axis is not None else 0),
                            [x.read_expr()]))

    def cumprod(self, axis=None):
        x = self.reshape(-1) if axis is None else self
        return ndarray(Node("cumulative", ("cumprod", axis if axis is not None else 0),
                            [x.read_expr()]))

    # -- shape manipulation (views) -------------------------------------------

    def reshape(self, *shape):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        shape = _fix_reshape(self.size, tuple(int(s) for s in shape))
        if shape == self.shape:
            return self
        return ndarray(base=self, view=ReshapeView(shape, self.shape))

    def ravel(self):
        return self.reshape(-1)

    def reshape_copy(self, *shape):
        """Materialized reshape (reference: ndarray.reshape_copy,
        ramba.py:6719-6720)."""
        return self.reshape(*shape).copy()

    def flatten(self):
        return self.reshape(-1).copy()

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        if not axes:
            axes = tuple(range(self.ndim))[::-1]
        axes = tuple(int(a) % self.ndim for a in axes)
        if axes == tuple(range(self.ndim)):
            return self
        return ndarray(base=self, view=PermuteView(axes))

    def swapaxes(self, a, b):
        axes = list(range(self.ndim))
        axes[a], axes[b] = axes[b], axes[a]
        return self.transpose(axes)

    def squeeze(self, axis=None):
        if axis is None:
            newshape = tuple(s for s in self.shape if s != 1)
        else:
            axs = axis if isinstance(axis, tuple) else (axis,)
            axs = {a % self.ndim for a in axs}
            newshape = tuple(s for i, s in enumerate(self.shape) if i not in axs)
        return self.reshape(newshape)

    def broadcast_to(self, shape):
        return ndarray(base=self, view=BroadcastView(shape))

    def take(self, indices, axis=None, mode="clip"):
        x = self.reshape(-1) if axis is None else self
        return ndarray(
            Node("take", (axis if axis is not None else 0, mode),
                 [x.read_expr(), as_exprable(indices)])
        )

    # -- indexing --------------------------------------------------------------

    def __getitem__(self, idx):
        kind, payload = _classify_index(idx, self.shape)
        if kind == "basic":
            return ndarray(base=self, view=SliceView(payload))
        if kind == "mask":
            from ramba_tpu.core.masked import MaskedArray

            return MaskedArray(self, payload)
        # advanced integer indexing -> gather (copy semantics)
        enc, arraypos, arrays = payload
        return ndarray(
            Node("getitem_adv", (enc, arraypos),
                 [self.read_expr()] + [as_exprable(a) for a in arrays])
        )

    def __setitem__(self, idx, value):
        kind, payload = _classify_index(idx, self.shape)
        vexpr = as_exprable(value)
        if kind == "basic":
            self.write_expr(Node("setitem", (payload,), [self.read_expr(), vexpr]))
        elif kind == "mask":
            mexpr = as_exprable(payload)
            if np.dtype(vexpr.dtype) != self.dtype:
                vexpr = Node("cast", (str(self.dtype),), [vexpr])
            self.write_expr(
                Node("masked_fill", (), [self.read_expr(), mexpr, vexpr])
            )
        else:
            enc, arraypos, arrays = payload
            self.write_expr(
                Node("setitem_adv", (enc, arraypos),
                     [self.read_expr(), vexpr] + [as_exprable(a) for a in arrays])
            )

    # -- linalg ---------------------------------------------------------------

    def dot(self, other):
        from ramba_tpu.ops import linalg

        return linalg.dot(self, other)

    def __matmul__(self, other):
        from ramba_tpu.ops import linalg

        return linalg.matmul(self, other)

    def __rmatmul__(self, other):
        from ramba_tpu.ops import linalg

        return linalg.matmul(other, self)

    # -- numpy protocol -------------------------------------------------------

    def __array_ufunc__(self, ufunc, method, *inputs, **kwargs):
        """Reference: __array_ufunc__ maps ufuncs onto ndarray methods via
        ufunc_map (ramba.py:6860-6894)."""
        name = ufunc.__name__
        out = kwargs.pop("out", None)
        if kwargs.pop("where", True) is not True:
            return NotImplemented
        if method == "__call__":
            if kwargs:
                return NotImplemented
            if name == "divide":
                name = "true_divide"
            if name == "matmul":
                # np_array @ rt_array arrives here (numpy defers via the
                # matmul ufunc, not __rmatmul__)
                from ramba_tpu.ops.linalg import matmul as _mm

                res = _mm(inputs[0], inputs[1])
            elif name not in E.MAPFN:
                return NotImplemented
            else:
                operands = [as_exprable(x) for x in inputs]
                res = ndarray(E.make_map(name, operands))
        elif method == "reduce":
            ufunc_red = {"add": "sum", "multiply": "prod", "minimum": "min",
                         "maximum": "max", "logical_and": "all",
                         "logical_or": "any"}
            if name not in ufunc_red:
                return NotImplemented
            axis = kwargs.pop("axis", 0)
            keepdims = kwargs.pop("keepdims", False)
            dtype = kwargs.pop("dtype", None)
            if kwargs:
                return NotImplemented
            (x,) = inputs
            x = x if isinstance(x, ndarray) else fromarray_auto(x)
            res = x._reduce(ufunc_red[name], axis, keepdims)
            if dtype is not None:
                res = res.astype(dtype)
        else:
            return NotImplemented
        if out is not None:
            (o,) = out if isinstance(out, tuple) else (out,)
            if isinstance(o, np.ndarray):
                # numpy target: materialize and copy back host-side with
                # numpy's ufunc out= casting contract (same_kind — silent
                # float->int truncation must raise like numpy does).
                # (np.add(rt, rt, out=np_buf) and np_buf += rt land here)
                np.copyto(o, res.asarray(), casting="same_kind")
                return o
            val = res.read_expr()
            if np.dtype(val.dtype) != o.dtype:
                val = Node("cast", (str(o.dtype),), [val])
            o.write_expr(val)
            return o
        return res

    def __array_function__(self, func, types, args, kwargs):
        """Reference: HANDLED_FUNCTIONS registry via @implements
        (ramba.py:8536-8543,6825-6858)."""
        from ramba_tpu.core.interop import HANDLED_FUNCTIONS

        if func in HANDLED_FUNCTIONS:
            return HANDLED_FUNCTIONS[func](*args, **kwargs)
        return NotImplemented


class _AbstractLeaf(Expr):
    """Shape/dtype-only leaf used to infer view avals without touching data."""

    __slots__ = ()

    def __init__(self, aval):
        self.aval = aval


def as_exprable(x) -> Expr:
    """Lift operands: ndarray -> its expression; numpy/jax array -> sharded
    Const; python scalar -> weakly typed Scalar leaf."""
    if isinstance(x, ndarray):
        return x.read_expr()
    if isinstance(x, (list, tuple)):
        x = np.asarray(x)
    if isinstance(x, (np.ndarray, jax.Array)) and getattr(x, "ndim", 0) > 0:
        return Const(_device_put_default(x))
    if isinstance(x, (np.ndarray, jax.Array)):
        return Const(jnp.asarray(x))
    return E.as_expr(x)


def put_sharded(x, sharding):
    """Upload a host array under ``sharding``.  Under multi-controller SPMD
    the sharding spans processes, where a plain ``device_put`` of host data
    aborts in native code — instead each process materializes only its own
    addressable shards from the (identical, SPMD) host copy via
    ``make_array_from_callback`` (the reference's MPI mode likewise has
    every rank slice its own piece out of the rank-local copy,
    common.py:49-100)."""
    if jax.process_count() > 1 and getattr(sharding, "mesh", None) is not None:
        xn = np.asarray(x)
        return jax.make_array_from_callback(
            xn.shape, sharding, lambda idx: xn[idx]
        )
    return jax.device_put(x, sharding)


def _device_put_default(x):
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        return x  # already a global (cross-process) array: keep as is
    x = np.asarray(x) if not isinstance(x, jax.Array) else x
    if isinstance(x, np.ndarray):
        from ramba_tpu.utils import timing as _timing

        _timing.note_transfer("host_to_device", x.nbytes)
    try:
        return put_sharded(x, _mesh.default_sharding(x.shape))
    except Exception:
        return jnp.asarray(x)


def fromarray_auto(x) -> ndarray:
    return ndarray(as_exprable(x))


def _norm_axis(axis, ndim):
    if axis is None:
        return None
    if isinstance(axis, (tuple, list)):
        return tuple(int(a) % ndim for a in axis)
    return int(axis) % ndim


def _fix_reshape(size, shape):
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1], dtype=np.int64))
        shape = tuple(size // max(known, 1) if s == -1 else s for s in shape)
    return shape


def expand_ellipsis(idx: tuple, ndim: int) -> tuple:
    """Replace an Ellipsis with the full slices it stands for (identity
    check: ``in`` would do elementwise == on array items)."""
    n_ellipsis = sum(1 for it in idx if it is Ellipsis)
    if n_ellipsis > 1:
        raise IndexError(
            "an index can only have a single ellipsis ('...')"
        )
    if n_ellipsis:
        pos = next(p for p, it in enumerate(idx) if it is Ellipsis)
        n_specified = sum(1 for i in idx if i is not None and i is not Ellipsis)
        fill = (slice(None),) * (ndim - n_specified)
        idx = idx[:pos] + fill + idx[pos + 1:]
    return idx


def _classify_index(idx, shape):
    """Split an index into basic / boolean-mask / advanced-integer cases.

    Reference analog: ndarray.__getitem__ dispatch between slicing views,
    maskarray creation, and the fancy-index gather path
    (ramba.py:5908-5911,6233-6267,6429-6545)."""
    if isinstance(idx, ndarray) and idx.dtype == np.bool_:
        return "mask", idx
    if isinstance(idx, np.ndarray) and idx.dtype == np.bool_:
        return "mask", fromarray_auto(idx)
    if not isinstance(idx, tuple):
        idx = (idx,)
    idx = expand_ellipsis(idx, len(shape))
    has_array = any(
        isinstance(i, (ndarray, np.ndarray, list, jax.Array)) for i in idx
    )
    if not has_array:
        # Bounds-check static integer indices (NumPy raises IndexError; raw
        # jax would clamp silently).
        dim = 0
        for it in idx:
            if it is None:
                continue
            if isinstance(it, (int, np.integer)):
                if dim >= len(shape) or not (-shape[dim] <= it < shape[dim]):
                    raise IndexError(
                        f"index {int(it)} is out of bounds for axis {dim} "
                        f"with size {shape[dim] if dim < len(shape) else 0}"
                    )
            dim += 1
        try:
            return "basic", E.encode_index(idx)
        except TypeError:
            pass
    # advanced: replace array positions with placeholders
    enc_parts = []
    arraypos = []
    arrays = []
    for p, it in enumerate(idx):
        if isinstance(it, (ndarray, np.ndarray, list, jax.Array)):
            arraypos.append(p)
            arrays.append(it if isinstance(it, ndarray) else np.asarray(it))
            enc_parts.append(("i", 0))  # placeholder, substituted at eval
        elif it is None:
            enc_parts.append(("n",))
        elif isinstance(it, slice):
            enc_parts.append(("s", it.start, it.stop, it.step))
        else:
            enc_parts.append(("i", int(it)))
    return "adv", (tuple(enc_parts), tuple(arraypos), arrays)


# ---------------------------------------------------------------------------
# Operator installation (reference: make_method loops, ramba.py:7893-7993)
# ---------------------------------------------------------------------------


def _install_operators():
    for pyname, fname in _BINOPS.items():
        def fwd(self, other, _f=fname):
            if not _is_operand(other):
                return NotImplemented
            return self._map(_f, other)

        def rev(self, other, _f=fname):
            if not _is_operand(other):
                return NotImplemented
            return self._map(_f, other, reverse=True)

        def inp(self, other, _f=fname):
            if not _is_operand(other):
                return NotImplemented
            return self._inplace_map(_f, other)

        setattr(ndarray, f"__{pyname}__", fwd)
        setattr(ndarray, f"__r{pyname}__", rev)
        setattr(ndarray, f"__i{pyname}__", inp)

    for pyname, fname in _CMPOPS.items():
        def cmp(self, other, _f=fname):
            if not _is_operand(other):
                return NotImplemented
            return self._map(_f, other)

        setattr(ndarray, f"__{pyname}__", cmp)

    for pyop, fname in _unary_table().items():
        def un(self, _f=fname):
            return self._map(_f)

        setattr(ndarray, pyop, un)

    def _divmod(self, other):
        return self._map("floor_divide", other), self._map("mod", other)

    ndarray.__divmod__ = _divmod

    for name in _UNARY_METHODS:
        fname = {"abs": "absolute"}.get(name, name)
        if fname not in E.MAPFN:
            continue

        def meth(self, _f=fname):
            return self._map(_f)

        if not hasattr(ndarray, name):
            setattr(ndarray, name, meth)

    def _finish_reduce(r, dtype, out, asarray):
        if dtype is not None:
            r = r.astype(dtype)
        if asarray:
            # Keep the (deferred) result in array form — shape (1,) for a
            # full reduction — so the caller can hold it without forcing a
            # flush (reference: reduction asarray kwarg, used e.g. at
            # ramba.py:6778 and sample pi integration).
            r = r.reshape((1,) if r.ndim == 0 else r.shape)
        if out is not None:
            out.write_expr(r.read_expr())
            return out
        return r

    # NumPy method positional order differs per reduction: sum/prod/mean
    # take (axis, dtype, out), min/max/any/all take (axis, out) — matching
    # exactly so e.g. ``a.min(0, out_arr)`` writes out_arr instead of
    # silently treating it as a dtype (ADVICE r1).  Everything past
    # NumPy's positional tail is keyword-only.
    for red in ("sum", "prod", "mean"):
        def rmeth(self, axis=None, dtype=None, out=None, *, keepdims=False,
                  asarray=False, _f=red):
            return _finish_reduce(
                self._reduce(_f, axis, keepdims), dtype, out, asarray
            )

        setattr(ndarray, red, rmeth)

    for red in ("min", "max", "any", "all"):
        def rmeth2(self, axis=None, out=None, *, keepdims=False,
                   asarray=False, _f=red):
            return _finish_reduce(
                self._reduce(_f, axis, keepdims), None, out, asarray
            )

        setattr(ndarray, red, rmeth2)


def _is_operand(x):
    return isinstance(
        x, (ndarray, np.ndarray, jax.Array, bool, int, float, complex,
            np.generic, list)
    ) or np.isscalar(x)


_install_operators()
