"""Effect-certified cross-flush result memoization (``RAMBA_MEMO``).

The compile cache (``fuser._compile_cache``) makes the *second* flush of
a program structure cheap; this cache makes it free — when, and only
when, the static certifier proves that is sound:

* the program's effect class is pure or RNG-keyed and it neither
  donates nor alias-escapes an input
  (:func:`ramba_tpu.analyze.effects.classify_program`);
* its statics fold to value tokens, so it has a canonical semantic
  fingerprint (:func:`ramba_tpu.analyze.canon.canonicalize`);
* every input binds to a *version token*: python scalars by value,
  device buffers by identity-under-weakref — jax arrays are immutable,
  so buffer identity is version identity, and the weakref death hook
  retires a token before ``id()`` reuse can forge it.

The memo key is ``(canonical hash, input tokens in canonical leaf
order, semantic fingerprint)`` — stable across sessions, tenants and
leaf orderings, unlike ``program.key``.

Cached results are ``Const``-wrapped and registered with the fuser's
owner census (``owner_incref(val, const)``), which has three deliberate
consequences: the memory governor's ledger accounts their bytes, its
LRU spiller may evict them to host (a hit transparently restores —
the cache is spill-aware for free), and a cached buffer always has a
live owner so no later flush can donate it out from under the cache.
The cache's own budget (``RAMBA_MEMO_BUDGET``, default 256m) bounds the
*logical* bytes it retains, LRU-evicted on insert.

Verification: the ``memo-safety`` rule (``analyze/rules.py``) audits
every flush-time plan; under ``RAMBA_VERIFY=strict`` an uncertified
plan aborts the flush before execution, and :func:`insert` additionally
refuses uncertified inserts even when rule filtering skipped the rule.
The ``memo:insert`` / ``memo:hit`` fault sites (``RAMBA_FAULTS``)
corrupt the certifier into approving an impure program — the seeded
violation the rule exists to catch.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import threading
import weakref
from typing import Any, Dict, List, Optional, Tuple

from ramba_tpu import common as _common
from ramba_tpu.analyze import canon as _canon
from ramba_tpu.analyze import effects as _effects
from ramba_tpu.observe import events as _events
from ramba_tpu.observe import registry as _registry
from ramba_tpu.resilience import faults as _faults
from ramba_tpu.resilience import memory as _memory
from ramba_tpu.resilience.spill import SpilledArray as _SpilledArray

_OFF = ("", "0", "off", "false", "no")


def enabled() -> bool:
    """Result memoization armed?  Off by default — ``RAMBA_MEMO=1``."""
    return (os.environ.get("RAMBA_MEMO") or "").strip().lower() not in _OFF


def budget_bytes() -> int:
    """Logical-byte budget for retained results (``RAMBA_MEMO_BUDGET``,
    ``common.parse_bytes`` grammar, default 256m; ``0`` = unbounded)."""
    raw = os.environ.get("RAMBA_MEMO_BUDGET")
    if raw:
        try:
            return max(0, _common.parse_bytes(raw))
        except ValueError:
            pass
    return 256 << 20


def _nbytes(v: Any) -> int:
    try:
        return int(v.nbytes)
    except Exception:
        return 0


@dataclasses.dataclass(frozen=True)
class MemoPlan:
    """One flush's memoization verdict, attached to ``_FlushWork`` and
    audited by the ``memo-safety`` verifier rule.

    ``memoizable`` is the operative decision (a fault site may force it
    True); ``certified`` is the certifier's genuine verdict — the two
    differ exactly when ``memo:insert``/``memo:hit`` injection seeded an
    impure program into the cache path.
    """

    memoizable: bool
    certified: bool
    reason: str
    chash: Optional[str]
    form: Optional[str]
    leaf_order: Tuple[int, ...]
    key: Optional[Tuple[Any, ...]]
    effects: Optional[_effects.EffectReport]
    #: content-addressed key for the fleet's shared memo tier
    #: (``fleet/artifacts.py``) — unlike ``key``, which binds inputs by
    #: buffer identity, this binds them by bytes and so survives a
    #: process boundary.  None when the tier is disarmed, the process is
    #: multi-controller, or the inputs exceed the shared-lane byte cap.
    shared_key: Optional[str] = None


# ---------------------------------------------------------------------------
# input version tokens
# ---------------------------------------------------------------------------

# id(value) -> (token, weakref).  The weakref death callback retires the
# token, so a recycled id() can never alias a dead buffer's version.
_tokens: Dict[int, Tuple[Any, Any]] = {}
_token_lock = threading.Lock()
_token_clock = itertools.count(1)


def _retire_token(key: int, ref: Any) -> None:
    with _token_lock:
        cur = _tokens.get(key)
        if cur is not None and cur[1] is ref:
            del _tokens[key]


def value_token(v: Any) -> Optional[Tuple[Any, ...]]:
    """Version token for one buffer input; None when the value cannot be
    tracked (not weak-referenceable) — the program is then unmemoizable."""
    k = id(v)
    with _token_lock:
        cur = _tokens.get(k)
        if cur is not None and cur[1]() is v:
            return cur[0]
        try:
            ref = weakref.ref(v, lambda r, _k=k: _retire_token(_k, r))
        except TypeError:
            return None
        token = ("buf", next(_token_clock))
        _tokens[k] = (token, ref)
        return token


# ---------------------------------------------------------------------------
# the cache
# ---------------------------------------------------------------------------


class _Entry:
    __slots__ = ("key", "consts", "nbytes", "hits")

    def __init__(self, key: Tuple[Any, ...], consts: List[Any],
                 nbytes: int) -> None:
        self.key = key
        self.consts = consts
        self.nbytes = nbytes
        self.hits = 0


class ResultCache:
    """Canonical-key LRU over Const-wrapped flush results.  dict
    preserves insertion order and hits re-insert, so iteration order is
    recency order and eviction pops the LRU — the compile cache's own
    discipline."""

    def __init__(self) -> None:
        self._entries: Dict[Tuple[Any, ...], _Entry] = {}
        self._lock = threading.Lock()
        self.total_bytes = 0
        self.hits = 0
        self.misses = 0
        self.inserts = 0
        self.evictions = 0
        self.insert_rejects = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def lookup(self, key: Tuple[Any, ...]) -> Optional[List[Any]]:
        with self._lock:
            e = self._entries.pop(key, None)
            if e is None:
                self.misses += 1
                return None
            self._entries[key] = e  # re-insert: MRU position
            e.hits += 1
            self.hits += 1
            consts = list(e.consts)
        vals: List[Any] = []
        for c in consts:
            v = c.value
            if isinstance(v, _SpilledArray):
                v = _memory.restore(c)
            else:
                _memory.ledger.touch(v)
            vals.append(v)
        return vals

    def insert(self, key: Tuple[Any, ...], outs: List[Any]) -> bool:
        from ramba_tpu.core import fuser as _fuser
        from ramba_tpu.core.expr import Const

        consts = []
        nbytes = 0
        for v in outs:
            c = Const(v)
            # census registration: the ledger accounts (and may spill)
            # the buffer, and a live owner blocks later donation of it
            _fuser.owner_incref(v, c)
            consts.append(c)
            nbytes += _nbytes(v)
        evicted: List[_Entry] = []
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                evicted.append(old)
                self.total_bytes -= old.nbytes
            self._entries[key] = _Entry(key, consts, nbytes)
            self.total_bytes += nbytes
            self.inserts += 1
            limit = budget_bytes()
            if limit:
                while self.total_bytes > limit and len(self._entries) > 1:
                    lru_key = next(iter(self._entries))
                    if lru_key == key:
                        break
                    lru = self._entries.pop(lru_key)
                    self.total_bytes -= lru.nbytes
                    self.evictions += 1
                    evicted.append(lru)
        for e in evicted:
            _release_entry(e)
        if evicted:
            _registry.inc("memo.evictions", len(evicted))
        return True

    def clear(self) -> None:
        with self._lock:
            dead = list(self._entries.values())
            self._entries.clear()
            self.total_bytes = 0
        for e in dead:
            _release_entry(e)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            looks = self.hits + self.misses
            return {
                "enabled": enabled(),
                "entries": len(self._entries),
                "bytes": self.total_bytes,
                "budget_bytes": budget_bytes(),
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": round(self.hits / looks, 4) if looks else 0.0,
                "inserts": self.inserts,
                "evictions": self.evictions,
                "insert_rejects": self.insert_rejects,
            }


def _release_entry(e: _Entry) -> None:
    from ramba_tpu.core import fuser as _fuser

    for c in e.consts:
        _fuser.owner_decref(c.value)
    e.consts = []


#: Process-wide result cache.
cache = ResultCache()


def _shared_tier():
    """``fleet.artifacts`` when the cross-process shared memo lane is
    armed for THIS process, else None.  Cheap env probe first so the
    common (disarmed) case costs one dict lookup; single-controller
    only — under SPMD a rank serving a shared-tier hit while its peers
    execute would desync the collective schedule."""
    if not os.environ.get("RAMBA_ARTIFACTS"):
        return None
    if _events._rank_info()[1] != 1:
        return None
    try:
        from ramba_tpu.fleet import artifacts as _artifacts
    except Exception:  # noqa: BLE001 — the tier must never break memo
        return None
    if not _artifacts.memo_shared_enabled():
        return None
    return _artifacts


def evict(plan: Optional["MemoPlan"]) -> bool:
    """Drop one plan's cached entry (and its census refs).  The
    integrity plane calls this when a shadow audit disagreed with the
    primary result — the cached bytes are suspect and must not be
    served again."""
    if plan is None or plan.key is None:
        return False
    with cache._lock:
        e = cache._entries.pop(plan.key, None)
        if e is None:
            return False
        cache.total_bytes -= e.nbytes
        cache.evictions += 1
    _release_entry(e)
    _registry.inc("memo.evictions")
    return True


def reset() -> None:
    """Drop every cached result and its census refs (tests)."""
    cache.clear()
    with _token_lock:
        _tokens.clear()


# ---------------------------------------------------------------------------
# the flush-path API (fuser._flush_prepare / _flush_dispatch)
# ---------------------------------------------------------------------------


def plan_for(program: Any, donate_key: Tuple[int, ...], leaves: List[Any],
             leaf_vals: List[Any]) -> Optional[MemoPlan]:
    """Certify one prepared flush.  Returns None when memoization is
    disarmed or the program is provably unmemoizable; otherwise a plan
    whose ``key`` binds the canonical hash to the current input
    versions.  The ``memo:insert`` / ``memo:hit`` fault sites corrupt
    the certification (memoizable forced True) so the ``memo-safety``
    rule has a real violation to catch."""
    if not enabled():
        return None
    from ramba_tpu.core.expr import Scalar

    rep = _effects.classify_program(program, tuple(donate_key))
    memoizable = rep.memoizable
    for site in ("memo:insert", "memo:hit"):
        try:
            _faults.check(site)
        except _faults.InjectedFault:
            # certifier corruption: admit this program regardless of its
            # effect class — the seeded violation RAMBA_VERIFY's
            # memo-safety rule exists to catch.  Only reachable under
            # explicit fault injection.
            memoizable = True
    if not memoizable:
        _registry.inc("memo.uncacheable")
        return None
    form = _canon.try_canonicalize(program)
    if form is None:
        _registry.inc("memo.not_canonical")
        return None
    tokens: List[Any] = []
    parts: List[Any] = []  # content-hashable form, canonical leaf order
    for slot in form.leaf_order:
        leaf = leaves[slot]
        if isinstance(leaf, Scalar):
            try:
                tokens.append(("s", type(leaf.value).__name__,
                               leaf.value))
                hash(tokens[-1])
            except TypeError:
                return None
            parts.append(tokens[-1])
        else:
            tok = value_token(leaf_vals[slot])
            if tok is None:
                return None
            tokens.append(tok)
            parts.append(leaf_vals[slot])
    from ramba_tpu.core import fuser as _fuser

    fingerprint = _fuser._semantic_fingerprint()
    key = (form.chash, tuple(tokens), fingerprint)
    shared_key = None
    tier = _shared_tier()
    if tier is not None and rep.memoizable:
        shared_key = tier.content_key(form.chash, parts, fingerprint)
    return MemoPlan(
        memoizable=True,
        certified=rep.memoizable,
        reason=rep.reason,
        chash=form.chash,
        form=form.form,
        leaf_order=form.leaf_order,
        key=key,
        effects=rep,
        shared_key=shared_key,
    )


def plan_from_cert(chash: Optional[str], form: Optional[str],
                   leaf_order: Tuple[int, ...],
                   effects: Optional[_effects.EffectReport],
                   leaves: List[Any],
                   leaf_vals: List[Any]) -> Optional[MemoPlan]:
    """Rebuild a certified :class:`MemoPlan` from a plan certificate
    (``analyze/plancert.py``) without re-running effect classification
    or canonicalization — the certificate already vouches for both, and
    its invalidation signature proves the verdicts still hold.  Only the
    live state binds per flush: the per-input version tokens and the
    shared-tier content key.  Returns None when memoization is disarmed,
    the certificate carried no canonical hash, or an input cannot be
    version-tracked (same bail-outs as :func:`plan_for`)."""
    if not enabled() or chash is None:
        return None
    from ramba_tpu.core.expr import Scalar

    tokens: List[Any] = []
    parts: List[Any] = []  # content-hashable form, canonical leaf order
    for slot in leaf_order:
        if slot >= len(leaves):
            return None
        leaf = leaves[slot]
        if isinstance(leaf, Scalar):
            try:
                tokens.append(("s", type(leaf.value).__name__,
                               leaf.value))
                hash(tokens[-1])
            except TypeError:
                return None
            parts.append(tokens[-1])
        else:
            tok = value_token(leaf_vals[slot])
            if tok is None:
                return None
            tokens.append(tok)
            parts.append(leaf_vals[slot])
    from ramba_tpu.core import fuser as _fuser

    fingerprint = _fuser._semantic_fingerprint()
    key = (chash, tuple(tokens), fingerprint)
    shared_key = None
    tier = _shared_tier()
    if tier is not None:
        shared_key = tier.content_key(chash, parts, fingerprint)
    return MemoPlan(
        memoizable=True,
        certified=True,
        reason="",
        chash=chash,
        form=form,
        leaf_order=tuple(leaf_order),
        key=key,
        effects=effects,
        shared_key=shared_key,
    )


def lookup(plan: Optional[MemoPlan]) -> Optional[List[Any]]:
    """Consult the result cache for a certified plan.  A hit returns the
    cached output values (restored from host spill when needed)."""
    if plan is None or not plan.memoizable or plan.key is None:
        return None
    vals = cache.lookup(plan.key)
    if vals is None:
        vals = _shared_lookup(plan)
        if vals is None:
            _registry.inc("memo.miss")
            return None
        return vals
    _registry.inc("memo.hit")
    _events.emit({
        "type": "memo_hit", "chash": plan.chash, "n_outs": len(vals),
    })
    return vals


def _shared_lookup(plan: MemoPlan) -> Optional[List[Any]]:
    """Probe the fleet's shared memo tier on a local miss.  A hit is
    promoted into the local cache (Const-wrapped, census-registered)
    so the next lookup never touches disk."""
    if plan.shared_key is None:
        return None
    tier = _shared_tier()
    if tier is None:
        return None
    arrays = tier.memo_load(plan.shared_key)
    if arrays is None:
        return None
    import jax.numpy as jnp

    vals: List[Any] = [jnp.asarray(a) for a in arrays]
    cache.insert(plan.key, vals)
    _registry.inc("memo.hit")
    _registry.inc("memo.shared_hit")
    _events.emit({
        "type": "memo_hit", "chash": plan.chash, "n_outs": len(vals),
        "tier": "shared",
    })
    return vals


def insert(plan: Optional[MemoPlan], outs: List[Any]) -> bool:
    """Insert one flush's outputs under the plan's key.  Strict-mode
    RAMBA_VERIFY refuses any insert the certifier did not approve —
    the backstop behind the memo-safety rule, effective even when rule
    filtering (RAMBA_VERIFY_RULES/_SKIP) bypassed the rule itself."""
    if plan is None or not plan.memoizable or plan.key is None:
        return False
    if not plan.certified:
        from ramba_tpu.analyze import verifier as _verifier

        if _verifier.mode() == "strict":
            cache.insert_rejects += 1
            _registry.inc("memo.insert_rejected")
            _events.emit({
                "type": "memo_insert_rejected", "chash": plan.chash,
                "reason": plan.reason,
            })
            return False
    cache.insert(plan.key, list(outs))
    _registry.inc("memo.insert")
    if plan.shared_key is not None and plan.certified:
        tier = _shared_tier()
        if tier is not None:
            # best-effort fleet publish: one replica's result becomes
            # every replica's shared-tier hit
            tier.memo_store(plan.shared_key, outs)
    return True
