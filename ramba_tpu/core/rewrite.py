"""Pattern-rewrite rules over the lazy expression graph.

TPU-native rebuild of the reference's DAG peephole rewrites
(/root/reference/ramba/ramba.py:4567-4789), which recognize the op patterns
xarray emits for groupby workloads (docs/index.md:53-58) and replace them
with direct implementations:

* ``rewrite_arange_reshape`` (:4567-4598) — ``arange(n).reshape(s)`` becomes
  a direct per-index filler.  Here that means generating values in the
  *target* sharding via broadcasted iotas instead of materializing a 1-D
  sharded iota and paying an all-to-all reshard on the reshape.
* ``rewrite_stack_mean_advindex`` (:4601-4677) — ``stack([reduce(x[:, idx_g])
  for g])`` (the xarray ``groupby().mean()`` expansion) becomes ONE segment
  reduction instead of k gathers + k reductions + a stack.
* ``rewrite_concatenate_binop_getitem`` (:4680-4789) — ``concatenate([
  x[:, idx_g] ∘ m[g] for g])`` (the xarray anomaly pattern) becomes two
  gathers + one fused elementwise op.

Rules run bottom-up once per flush (core/fuser.py); a rule returns a
replacement Node or None.  All matching is defensive: any structural
mismatch leaves the graph untouched.
"""

from __future__ import annotations

import numpy as np

from ramba_tpu.core.expr import Const, Expr, Node
from ramba_tpu.observe import registry as _registry
from ramba_tpu.resilience import faults as _faults

REDUCE_KINDS = {"mean", "nanmean", "sum", "nansum", "min", "max", "prod"}


def rewrite_arange_reshape(node: Node):
    """reshape(arange) -> fromfunction in the target shape/sharding
    (reference: ramba.py:4567-4598)."""
    if node.op != "reshape":
        return None
    (shape,) = node.static
    arg = node.args[0]
    if not (isinstance(arg, Node) and arg.op == "arange"):
        return None
    n, dtype, _spec = arg.static
    from ramba_tpu.parallel import mesh as _mesh

    spec = tuple(_mesh.default_spec(shape))
    start, step = arg.args
    shape = tuple(int(s) for s in shape)
    strides = []
    acc = 1
    for s in reversed(shape):
        strides.append(acc)
        acc *= s
    strides = tuple(reversed(strides))
    idx_dtype = "int64" if n > 2**31 else "int32"

    def fill_fn(*a):
        import jax.numpy as jnp

        idx = a[:-2]
        start_v, step_v = a[-2:]
        flat = 0
        for i, st in zip(idx, strides):
            flat = flat + i.astype(jnp.dtype(idx_dtype)) * st
        return (start_v + step_v * flat).astype(jnp.dtype(dtype))

    # hashable wrapper for cache stability across flushes
    filler = _HashedFill(("arange_reshape", shape, str(dtype), idx_dtype),
                         fill_fn)
    return Node(
        "fromfunction", (shape, dtype, spec, filler, True),
        [start, step], aval=None,
    )


class _HashedFill:
    """Wrap a function with a value-based hash key so structurally identical
    rewrites share one compile-cache entry."""

    __slots__ = ("key", "fn")

    def __init__(self, key, fn):
        self.key = key
        self.fn = fn

    def __call__(self, *args):
        return self.fn(*args)

    def __hash__(self):
        return hash(self.key)

    def __eq__(self, other):
        return isinstance(other, _HashedFill) and other.key == self.key


def _single_axis_gather(e: Expr):
    """Match getitem_adv with exactly one integer index array and full slices
    elsewhere.  Returns (base_expr, dim, index_const) or None."""
    if not (isinstance(e, Node) and e.op == "getitem_adv"):
        return None
    enc, arraypos = e.static
    if len(arraypos) != 1:
        return None
    dim = 0
    p = arraypos[0]
    for q, part in enumerate(enc):
        if q == p:
            break
        if part[0] == "n":
            return None
        if part[0] == "s" and part[1:] != (None, None, None):
            return None
        if part[0] == "i":
            return None
        dim += 1
    for q, part in enumerate(enc):
        if q == p:
            continue
        if part[0] != "s" or part[1:] != (None, None, None):
            return None
    idx = e.args[1]
    if not isinstance(idx, Const):
        return None
    return e.args[0], dim, idx


def rewrite_stack_reduce_advindex(node: Node):
    """stack([reduce(x[..., idx_g, ...], axis=dim) for g]) -> segment_reduce
    (reference: rewrite_stack_mean_advindex, ramba.py:4601-4677)."""
    if node.op != "stack" or len(node.args) < 2:
        return None
    (stack_axis,) = node.static
    kind = None
    dim = None
    base = None
    groups = []
    for a in node.args:
        if not (isinstance(a, Node) and a.op == "reduce"):
            return None
        k, raxis, keepdims, ddof = a.static
        if k not in REDUCE_KINDS or keepdims or ddof not in (None, 0):
            return None
        m = _single_axis_gather(a.args[0])
        if m is None:
            return None
        b, d, idx = m
        if raxis != d:
            return None
        if base is None:
            base, dim, kind = b, d, k
        elif b is not base or d != dim or k != kind:
            return None
        groups.append(np.asarray(idx.value))
    # full, disjoint coverage of the grouped dimension; duplicates inside a
    # single group would collapse under segment_reduce (the original sums
    # the element once per occurrence), so reject them too
    n = base.aval.shape[dim]
    if sum(len(g) for g in groups) != n:
        return None
    labels = np.full((n,), -1, np.int64)
    for g, idx in enumerate(groups):
        if idx.ndim != 1 or np.unique(idx).size != idx.size:
            return None
        if np.any(labels[idx] != -1):
            return None
        labels[idx] = g
    if np.any(labels < 0):
        return None
    out = Node(
        "segment_reduce",
        (kind, len(groups), dim),
        [base, Const(_to_device(labels.astype(np.int32)))],
    )
    # segment_reduce leaves groups on `dim`; stack puts them on stack_axis.
    if stack_axis != dim:
        out = Node("moveaxis", (dim, stack_axis), [out])
    return out


def rewrite_concat_binop_getitem(node: Node):
    """concatenate([binop(x[..., idx_g, ...], m[g]) for g]) ->
    binop(gather(x, cat(idx)), gather(m, group_of_position))
    (reference: rewrite_concatenate_binop_getitem, ramba.py:4680-4789).

    Two per-group operand forms are recognized:

    * plain ``m[g]`` — accepted only when trailing-alignment broadcasting
      places the gathered group axis exactly on the concat axis
      (x.ndim - dim == m.ndim - m_dim, and every m axis left of the group
      axis has size 1); anything else broadcasts differently before and
      after the rewrite, so it is left alone.
    * ``m[g][:, None]`` with 2-D x and m, groups on x axis 1 — the xarray
      climatology/anomaly idiom; lowered to take + transpose.
    """
    if node.op != "concatenate" or len(node.args) < 2:
        return None
    (axis,) = node.static
    base = None
    dim = None
    fname = None
    m_base = None
    swapped = None
    m_dim = None
    newaxis_form = None
    groups = []
    for gi, a in enumerate(node.args):
        if not (isinstance(a, Node) and a.op == "map" and len(a.args) == 2):
            return None
        (f,) = a.static
        lhs, rhs = a.args
        gl = _single_axis_gather(lhs)
        gr = _single_axis_gather(rhs)
        if gl is not None and gr is None:
            gather, other, sw = gl, rhs, False
        elif gr is not None and gl is None:
            gather, other, sw = gr, lhs, True
        else:
            return None
        b, d, idx = gather
        # other must be m[g] (optionally followed by one trailing newaxis)
        sel = _int_select_chain(other, gi)
        if sel is None:
            return None
        mb, mdim, nform = sel
        if base is None:
            base, dim, fname, m_base, swapped, m_dim, newaxis_form = (
                b, d, f, mb, sw, mdim, nform
            )
        elif (b is not base or d != dim or f != fname or mb is not m_base
              or sw != swapped or mdim != m_dim or nform != newaxis_form):
            return None
        groups.append(np.asarray(idx.value))
    if axis != dim:
        return None
    x_ndim = base.aval.ndim
    m_shape = tuple(m_base.aval.shape)
    if newaxis_form:
        # m[g][:, None]: supported shape pattern is 2-D x grouped on axis 1
        # with m laid out (groups, x_rows)
        if not (x_ndim == 2 and dim == 1 and len(m_shape) == 2
                and m_dim == 0):
            return None
    else:
        # plain m[g]: gathered group axis must land on the concat axis
        # under numpy trailing alignment, with no real axes left of it
        if len(m_shape) - m_dim != x_ndim - dim:
            return None
        if any(s != 1 for s in m_shape[:m_dim]):
            return None
    cat_idx = np.concatenate(groups)
    pos_group = np.concatenate(
        [np.full((len(g),), gi, np.int32) for gi, g in enumerate(groups)]
    )
    enc = tuple(
        ("i", 0) if q == dim else ("s", None, None, None)
        for q in range(x_ndim)
    )
    gathered_x = Node(
        "getitem_adv", (enc, (dim,)),
        [base, Const(_to_device(cat_idx))],
    )
    gathered_m = Node(
        "take", (m_dim, "clip"), [m_base, Const(_to_device(pos_group))]
    )
    if newaxis_form:
        # (n_positions, x_rows) -> (x_rows, n_positions) to align with x
        gathered_m = Node("permute", ((1, 0),), [gathered_m])
    args = [gathered_m, gathered_x] if swapped else [gathered_x, gathered_m]
    return Node("map", (fname,), args)


def _int_select(e: Expr, expect: int):
    """Match getitem picking integer index ``expect`` on exactly one dim,
    full slices elsewhere.  Returns (base, dim) or None."""
    if not (isinstance(e, Node) and e.op == "getitem"):
        return None
    (enc,) = e.static
    dim = None
    at = 0
    for part in enc:
        if part[0] == "i":
            if dim is not None or part[1] != expect:
                return None
            dim = at
            at += 1
        elif part[0] == "s" and part[1:] == (None, None, None):
            at += 1
        else:
            return None
    if dim is None:
        return None
    return e.args[0], dim


def _int_select_chain(e: Expr, expect: int):
    """Match ``m[g]`` or ``m[g][:, None]``.  Returns
    (m_base, group_dim, has_trailing_newaxis) or None."""
    sel = _int_select(e, expect)
    if sel is not None:
        return sel[0], sel[1], False
    # one wrapping getitem of full slices + a single trailing newaxis
    if not (isinstance(e, Node) and e.op == "getitem"):
        return None
    (enc,) = e.static
    if len(enc) < 1 or enc[-1] != ("n",):
        return None
    if any(part[0] != "s" or part[1:] != (None, None, None)
           for part in enc[:-1]):
        return None
    inner = _int_select(e.args[0], expect)
    if inner is None:
        return None
    return inner[0], inner[1], True


def _to_device(x: np.ndarray):
    import jax.numpy as jnp

    return jnp.asarray(x)


def rewrite_align_operand_layouts(node: Node):
    """Fused elementwise operands whose device layouts disagree: wrap
    the minority operands in ``shard_hint`` nodes targeting the most-
    sharded operand's layout, so GSPMD lowers an explicit resharding
    collective (all-to-all / collective-permute — the same lowering
    ``parallel.reshard`` schedules) instead of falling back to
    replicating one side.  Only full-shape concrete leaves participate
    — broadcasting operands, lazy subtrees, and spilled buffers are
    left for GSPMD's own propagation."""
    if node.op != "map" or len(node.args) < 2 or node.aval is None:
        return None
    from jax.sharding import NamedSharding

    from ramba_tpu.parallel import mesh as _mesh

    try:
        mesh = _mesh.get_mesh()
    except Exception:
        return None
    if mesh.size <= 1:
        return None
    out_shape = tuple(node.aval.shape)

    def _leaf_spec(a: Expr):
        if not isinstance(a, Const):
            return None
        v = a.value
        sh = getattr(v, "sharding", None)
        if not isinstance(sh, NamedSharding) or sh.mesh != mesh:
            return None
        if tuple(getattr(v, "shape", ())) != out_shape:
            return None
        entries = tuple(sh.spec)
        while entries and entries[-1] is None:
            entries = entries[:-1]
        return entries

    shaped = [(i, s) for i, s in ((i, _leaf_spec(a))
                                  for i, a in enumerate(node.args))
              if s is not None]
    if len(shaped) < 2 or len({s for _, s in shaped}) < 2:
        return None
    # Dominant layout = the one sharding the most dims (replication is
    # what this rule exists to avoid); ties go to the earliest operand.
    dom = None
    for _, s in shaped:
        if s and (dom is None
                  or sum(1 for e in s if e) > sum(1 for e in dom if e)):
            dom = s
    if not dom:
        return None
    new_args = list(node.args)
    changed = False
    for i, s in shaped:
        if s != dom:
            new_args[i] = Node("shard_hint", (dom,), [node.args[i]])
            changed = True
    if not changed:
        return None
    return Node(node.op, node.static, new_args, aval=node.aval)


RULES = [
    rewrite_arange_reshape,
    rewrite_stack_reduce_advindex,
    rewrite_concat_binop_getitem,
    rewrite_align_operand_layouts,
]

# Per-rule fire counts (observability; lets end-to-end tests assert that an
# xarray/pandas idiom actually took the rewritten path — cf. the reference's
# DAG-rewrite debug prints, ramba.py:4567-4789).
stats = {rule.__name__: 0 for rule in RULES}


def rewrite_roots(roots):
    """Apply RULES bottom-up across the expression forest (iterative — chains
    can be deeper than the Python recursion limit, cf. the fuser's iterative
    linearizer)."""
    _faults.check("rewrite")
    memo: dict[int, Expr] = {}
    out = []
    for root in roots:
        stack = [(root, False)]
        while stack:
            e, done = stack.pop()
            if id(e) in memo:
                continue
            if not isinstance(e, Node):
                memo[id(e)] = e
                continue
            if not done:
                stack.append((e, True))
                for a in e.args:
                    if id(a) not in memo:
                        stack.append((a, False))
                continue
            new_args = [memo[id(a)] for a in e.args]
            if all(n is o for n, o in zip(new_args, e.args)):
                cand = e
            else:
                cand = Node(e.op, e.static, new_args, aval=e.aval)
            for rule in RULES:
                try:
                    r = rule(cand)
                except Exception:
                    # Matching is meant to be defensive (a mismatch returns
                    # None); a rule that *raises* has a bug, and silently
                    # eating it hides the bug forever — count it so the
                    # miss shows up in diagnostics.
                    _registry.inc("resilience.rewrite_rule_error")
                    _registry.inc(
                        f"resilience.rewrite_rule_error.{rule.__name__}"
                    )
                    r = None
                if r is not None:
                    stats[rule.__name__] += 1
                    _registry.inc(f"rewrite.{rule.__name__}")
                    cand = r
                    break
            memo[id(e)] = cand
        out.append(memo[id(root)])
    return out
