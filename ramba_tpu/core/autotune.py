"""Ledger-driven backend autotuner (`ramba-autotune`).

``core/fuser.py::_get_compiled`` asks this module which lowering backend —
``xla`` (the default jit lowering) or ``pallas``
(``ops/pallas_backend.py``) — should serve a kernel fingerprint.  The
decision is *measured*, not modeled:

* ``RAMBA_AUTOTUNE`` unset/``off`` — every fingerprint takes ``xla``
  (selection ``default``); zero overhead, historical behavior.
* ``RAMBA_AUTOTUNE=race`` (or ``1``/``on``) — the first executions of a
  lowerable fingerprint alternate backends, each sample landing in that
  backend's slice of the kernel cost ledger (``observe/ledger.py``).
  Once every candidate holds ``RAMBA_AUTOTUNE_K`` (default 3) steady-state
  samples, the backend with the lower exec p50 is **latched** for the
  fingerprint and the loser's executable ages out of the fuser's LRU
  compile cache naturally.
* ``RAMBA_AUTOTUNE=force:<backend>`` — pin every lowerable fingerprint to
  one backend (measurement and A/B harnesses).

Latched decisions persist to ``RAMBA_AUTOTUNE_CACHE`` (a JSON decision
table, written atomically) so a later process skips the race entirely:
its selections come straight from the table (counted under the
``autotune.race_skipped`` registry counter; fresh races count under
``autotune.race_started``).

A Pallas failure at compile or first execution calls :func:`note_failure`,
which latches ``xla`` for the fingerprint and records the fallback on the
ledger's backend slice — degradation, never an error.

Race compiles must not block the serving hot path: when the async compile
pipeline (``serve/pipeline.py``) is live, :func:`maybe_prewarm` ships the
challenger's first (compile-paying) execution through it as a warm task,
so the race's steady-state samples start from an already-jitted callable.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
from typing import Optional

from ramba_tpu.observe import ledger as _ledger
from ramba_tpu.observe import registry as _registry

XLA = "xla"
PALLAS = "pallas"

_lock = threading.RLock()

# fingerprint -> {"backend": str, "via": str}; "via" in
# default|autotune|persisted|forced|fallback
_decisions: "dict[str, dict]" = {}
# fingerprint -> True once a prewarm task has been submitted
_prewarmed: "dict[str, bool]" = {}
# fingerprints whose pallas lowering failed (never re-raced this process)
_failed: "set[str]" = set()

_mode = "off"        # off | race | force
_forced: Optional[str] = None
_k = 3
_cache_path: Optional[str] = None
_table_loaded = False
# monotone counter bumped on every decision-table mutation (latch, table
# load, fallback, reset/reconfigure).  The plan-certificate validity
# analysis (analyze/plancert.py) folds this into its invalidation
# signature: a cached prepare verdict is only as fresh as the autotune
# table it read.
_generation = 0


def generation() -> int:
    """Decision-table generation: increments whenever any fingerprint's
    backend decision could have changed."""
    return _generation


def _bump_generation_locked() -> None:
    global _generation
    _generation += 1


def reconfigure(*, mode: Optional[str] = None,
                cache_path: Optional[str] = None,
                k: Optional[int] = None) -> None:
    """Reload configuration from the environment (keyword overrides for
    tests).  Clears in-memory decisions so mode changes take effect; the
    persisted table (if any) is lazily re-read."""
    global _mode, _forced, _k, _cache_path, _table_loaded
    with _lock:
        raw = os.environ.get("RAMBA_AUTOTUNE", "") if mode is None else mode
        raw = (raw or "").strip().lower()
        if raw in ("", "0", "off", "false", "no"):
            _mode, _forced = "off", None
        elif raw.startswith("force:"):
            b = raw.split(":", 1)[1]
            _mode, _forced = "force", (b if b in (XLA, PALLAS) else XLA)
        elif raw in ("race", "1", "on", "true", "yes"):
            _mode, _forced = "race", None
        else:
            _mode, _forced = "off", None
        try:
            _k = max(1, int(os.environ.get("RAMBA_AUTOTUNE_K", "3") or 3)
                     if k is None else int(k))
        except ValueError:
            _k = 3
        _cache_path = os.environ.get("RAMBA_AUTOTUNE_CACHE") \
            if cache_path is None else cache_path
        _decisions.clear()
        _prewarmed.clear()
        _failed.clear()
        _table_loaded = False
        _bump_generation_locked()


def reset() -> None:
    """Drop all decisions/race state (tests); keeps configuration."""
    with _lock:
        _decisions.clear()
        _prewarmed.clear()
        _failed.clear()
        _table_loaded = False
        _bump_generation_locked()


def mode() -> str:
    return _mode


def active() -> bool:
    return _mode != "off"


# ---------------------------------------------------------------------------
# persisted decision table
# ---------------------------------------------------------------------------


def _load_table_locked() -> None:
    global _table_loaded
    if _table_loaded:
        return
    _table_loaded = True
    if not _cache_path:
        return
    try:
        with open(_cache_path) as f:
            table = json.load(f)
    except (OSError, ValueError):
        return
    if not isinstance(table, dict):
        return
    n = 0
    for fp, row in table.get("decisions", {}).items():
        b = row.get("backend") if isinstance(row, dict) else None
        if b in (XLA, PALLAS) and fp not in _decisions:
            _decisions[fp] = {"backend": b, "via": "persisted"}
            n += 1
    if n:
        _registry.inc("autotune.table_loaded_decisions", n)
        _bump_generation_locked()


def _persist_table_locked() -> None:
    if not _cache_path:
        return
    table = {
        "version": 1,
        "decisions": {
            fp: {"backend": d["backend"], "via": d["via"]}
            for fp, d in _decisions.items()
            if d["via"] in ("autotune", "persisted", "fallback")
        },
    }
    try:
        d = os.path.dirname(os.path.abspath(_cache_path)) or "."
        fd, tmp = tempfile.mkstemp(prefix=".autotune-", dir=d)
        with os.fdopen(fd, "w") as f:
            json.dump(table, f, indent=0, sort_keys=True)
        os.replace(tmp, _cache_path)
    except OSError:
        pass


# ---------------------------------------------------------------------------
# selection
# ---------------------------------------------------------------------------


def _agree_winner(winner: str) -> str:
    """Cross-rank agreement on the latched backend.  In a multi-controller
    job every rank MUST latch the same backend per fingerprint (divergent
    lowerings would desync the SPMD program streams).  Race counts are
    ledger-driven and advance in lockstep, so all ranks reach the latch on
    the same dispatch; rank 0's measured winner becomes the decision —
    local p50s can disagree across ranks when the backends are close.

    Rides the resilience coherence layer (``coherence.agree`` with
    ``reduce="bcast"``), which does the transfer-ledger accounting and
    emits the ``coherence`` event itself — control-plane traffic is never
    silently swallowed.  A failed round falls back to the local winner
    with an ``outcome=local`` event, preserving the old best-effort
    semantics without the old bare ``except: pass``."""
    from ramba_tpu.resilience import coherence as _coherence

    if not _coherence.engaged():
        return winner
    v = _coherence.agree("autotune:winner",
                         1 if winner == PALLAS else 0, reduce="bcast")
    return PALLAS if v else XLA


def select(fp: str, program, leaf_vals) -> tuple:
    """Backend for this dispatch: ``(backend, via)``.

    ``via`` is ``default`` (autotune off or program not Pallas-lowerable),
    ``forced``, ``racing`` (still alternating, not yet latched),
    ``autotune`` (latched by a race this process), ``persisted`` (latched
    by the decision table), or ``fallback`` (Pallas failed earlier)."""
    if _mode == "off":
        return XLA, "default"
    from ramba_tpu.ops import pallas_backend as _pallas

    with _lock:
        _load_table_locked()
        d = _decisions.get(fp)
        if d is not None:
            return d["backend"], d["via"]
        if fp in _failed:
            return XLA, "fallback"
    if not _pallas.supports(program, leaf_vals):
        return XLA, "default"
    if _mode == "force":
        return _forced, "forced"

    # race: alternate backends until each holds K steady-state samples,
    # then latch the lower p50
    stats = _ledger.backend_stats(fp)
    counts = {b: (stats.get(b) or {}).get("count", 0) for b in (XLA, PALLAS)}
    with _lock:
        d = _decisions.get(fp)  # latched concurrently?
        if d is not None:
            return d["backend"], d["via"]
        if counts[XLA] == 0 and counts[PALLAS] == 0 \
                and fp not in _prewarmed:
            _prewarmed[fp] = False  # race begins now
            _registry.inc("autotune.race_started")
        if counts[XLA] >= _k and counts[PALLAS] >= _k:
            p50 = {b: (stats.get(b) or {}).get("p50_s") for b in (XLA, PALLAS)}
            winner = PALLAS if (p50[PALLAS] or float("inf")) < \
                (p50[XLA] or float("inf")) else XLA
            winner = _agree_winner(winner)
            _decisions[fp] = {"backend": winner, "via": "autotune"}
            _bump_generation_locked()
            _registry.inc("autotune.latched")
            _registry.gauge("autotune.decisions", float(len(_decisions)))
            _persist_table_locked()
            return winner, "autotune"
    # alternate toward whichever backend has fewer samples (pallas first,
    # so its compile cost is paid while xla is still warm in the jit cache)
    return (PALLAS, "racing") if counts[PALLAS] <= counts[XLA] \
        else (XLA, "racing")


def note_failure(fp: str, backend: str, err) -> None:
    """A backend failed to lower/compile/execute for this fingerprint:
    latch the other backend and record the fallback."""
    with _lock:
        _failed.add(fp)
        _decisions[fp] = {"backend": XLA, "via": "fallback"}
        _bump_generation_locked()
        _persist_table_locked()
    _ledger.record_backend_fallback(fp, backend, str(err))


def decision(fp: str) -> Optional[dict]:
    with _lock:
        d = _decisions.get(fp)
        return dict(d) if d is not None else None


def latched_via_autotune() -> bool:
    """True when at least one fingerprint's backend was latched by a
    measured race or the persisted table (bench.py's
    ``backend_selected_via`` flips to ``autotune`` on this)."""
    with _lock:
        return any(d["via"] in ("autotune", "persisted")
                   for d in _decisions.values())


def report() -> dict:
    """The ``autotune`` section of ``diagnostics.perf_report()``: mode,
    per-fingerprint decisions, and the measured race overhead (total
    steady-state seconds + compile seconds sunk into each loser)."""
    with _lock:
        decisions = {fp: dict(d) for fp, d in _decisions.items()}
        failed = sorted(_failed)
    overhead_s = 0.0
    races = 0
    for fp, d in decisions.items():
        if d["via"] != "autotune":
            continue
        races += 1
        stats = _ledger.backend_stats(fp)
        loser = PALLAS if d["backend"] == XLA else XLA
        ls = stats.get(loser) or {}
        overhead_s += float(ls.get("total_s") or 0.0)
        overhead_s += float(ls.get("compile_s") or 0.0)
    return {
        "mode": _mode if _mode != "force" else f"force:{_forced}",
        "k": _k,
        "cache_path": _cache_path,
        "decisions": decisions,
        "failed": failed,
        "races_latched": races,
        "race_overhead_s": round(overhead_s, 6),
    }


# ---------------------------------------------------------------------------
# pipeline prewarm: challenger compiles off the hot path
# ---------------------------------------------------------------------------


def maybe_prewarm(fp: str, program, leaf_vals, donate_key: tuple) -> None:
    """Submit the challenger backend's first (compile-paying) execution
    through the async compile pipeline, once per fingerprint, so race
    compiles never block a serving flush.  No-op when no pipeline is live
    (the synchronous path just pays the compile inline, as it always has
    for fresh XLA kernels)."""
    if _mode != "race":
        return
    with _lock:
        if _prewarmed.get(fp):
            return
        _prewarmed[fp] = True
    try:
        from ramba_tpu.serve import pipeline as _pipeline
        pipe = _pipeline.current_pipeline()
    except Exception:
        return
    if pipe is None or not hasattr(pipe, "submit_warm"):
        return
    import jax

    avals = []
    for v in leaf_vals:
        if hasattr(v, "shape") and hasattr(v, "dtype"):
            avals.append(jax.ShapeDtypeStruct(v.shape, v.dtype))
        else:
            avals.append(v)  # python scalar: pass through by value

    def warm():
        import jax.numpy as jnp
        from ramba_tpu.core import fuser as _fuser

        fn, _is_new, _fp, backend = _fuser._get_compiled(
            program, donate_key,
            leaf_vals=[
                jnp.zeros(a.shape, a.dtype)
                if isinstance(a, jax.ShapeDtypeStruct) else a
                for a in avals
            ],
            force_backend=PALLAS,
        )
        if backend != PALLAS:
            return
        args = [jnp.zeros(a.shape, a.dtype)
                if isinstance(a, jax.ShapeDtypeStruct) else a
                for a in avals]
        jax.block_until_ready(fn(*args))
        _registry.inc("autotune.prewarm_done")

    try:
        pipe.submit_warm(warm, label=f"autotune:{fp}")
        _registry.inc("autotune.prewarm_submitted")
    except Exception:
        pass


reconfigure()
