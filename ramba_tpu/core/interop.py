"""NumPy protocol interop: the ``__array_function__`` dispatch registry.

Reference: the ``@implements``/HANDLED_FUNCTIONS mechanism
(/root/reference/ramba/ramba.py:8536-8543) plus the generated module-level
wrappers (ramba.py:9682-9745) that let ``numpy.sin(ramba_array)`` and xarray
work through the NumPy dispatch protocol.
"""

from __future__ import annotations

HANDLED_FUNCTIONS: dict = {}


def implements(np_function):
    """Register an implementation for a NumPy function."""

    def decorator(func):
        HANDLED_FUNCTIONS[np_function] = func
        return func

    return decorator
