"""NumPy protocol interop: the ``__array_function__`` dispatch registry.

Reference: the ``@implements``/HANDLED_FUNCTIONS mechanism
(/root/reference/ramba/ramba.py:8536-8543) plus the generated module-level
wrappers (ramba.py:9682-9745) that let ``numpy.sin(ramba_array)`` and xarray
work through the NumPy dispatch protocol.
"""

from __future__ import annotations

import numpy as np

HANDLED_FUNCTIONS: dict = {}


def implements(np_function):
    """Register an implementation for a NumPy function (public extension
    point; reference: @implements, ramba.py:8536-8543)."""

    def decorator(func):
        HANDLED_FUNCTIONS[np_function] = func
        return func

    return decorator


def isscalar(x) -> bool:
    """Reference: ramba.isscalar (ramba.py:9854-9857) — 0-d distributed
    arrays count as scalars."""
    from ramba_tpu.core.ndarray import ndarray

    if isinstance(x, ndarray):
        return x.ndim == 0
    return np.isscalar(x)


def result_type(*args):
    """Reference: ramba.result_type (ramba.py:9833-9851) — numpy promotion
    with distributed arrays contributing their dtype."""
    from ramba_tpu.core.ndarray import ndarray

    return np.result_type(
        *[a.dtype if isinstance(a, ndarray) else a for a in args]
    )
