"""Flush machinery: lazy expression graphs -> one fused, jitted XLA module.

This is the TPU-native counterpart of the reference's two-stage execution
pipeline:

* ``DAG.execute_all`` — collect every pending node and run it in one batch
  (/root/reference/ramba/ramba.py:5080-5105), and
* ``deferred_op.execute`` — emit ONE fused kernel for the batch, name it by a
  hash of its source for caching, and ship it to all workers
  (/root/reference/ramba/ramba.py:8115-8316, hash at :8260-8265).

Differences, by design:

* Instead of generating Python source strings for Numba, the expression graph
  is linearized into a tiny instruction program which is interpreted once
  under ``jax.jit`` tracing; XLA does the loop fusion and GSPMD inserts the
  cross-shard collectives (the reference moves boundary data by hand at
  ramba.py:3549-3694).
* The compile cache is keyed on program *structure* only — leaf shapes/dtypes
  are specialized by jax.jit's own cache, and scalar operands are passed as
  weakly-typed arguments so changing a constant never recompiles.
* Buffer donation replaces the reference's in-place shard mutation: a leaf
  buffer that no live ndarray aliases is donated to XLA so e.g. ``a += 1``
  updates HBM in place (the reference's alias analysis for this is
  ramba.py:8435-8465).

Since the serving refactor, pending state is *per stream*: each
:class:`FlushStream` owns its own pending registry, node-count threshold,
and quarantine scope, so concurrent sessions (``ramba_tpu.serve``) cannot
flush — or poison — each other's half-built programs.  A process-wide
default stream preserves the historical single-stream behavior verbatim;
``_pending`` below IS the default stream's registry dict.  A flush is two
stages — :func:`_flush_prepare` (collect + rewrite + linearize + donation
census + verify, cheap, caller thread) and :func:`_flush_dispatch`
(admission + ladder execution + write-back) — shared by the synchronous
path here and the async compile pipeline in ``serve/pipeline.py`` so the
two can never drift.
"""

from __future__ import annotations

import contextvars
import hashlib
import itertools
import os
import threading
import time
import warnings
import weakref
from contextlib import contextmanager
from typing import Optional, Sequence

import jax

from ramba_tpu import common
from ramba_tpu.compile import classes as _classes
from ramba_tpu.compile import persist as _persist
from ramba_tpu.core import memo as _memo
from ramba_tpu.core import plancache as _plancache
from ramba_tpu.core.expr import Const, Expr, Node, Scalar, OPS
from ramba_tpu.observe import attrib as _attrib
from ramba_tpu.observe import events as _events
from ramba_tpu.observe import fleet as _fleet
from ramba_tpu.observe import ledger as _ledger
from ramba_tpu.observe import observer as _observer
from ramba_tpu.observe import profile as _profile
from ramba_tpu.observe import registry as _registry
from ramba_tpu.observe import slo as _slo
from ramba_tpu.observe import telemetry as _telemetry
from ramba_tpu.parallel import mesh as _mesh
from ramba_tpu.resilience import coherence as _coherence
from ramba_tpu.resilience import degrade as _degrade
from ramba_tpu.resilience import elastic as _elastic
from ramba_tpu.resilience import faults as _faults
from ramba_tpu.resilience import integrity as _integrity
from ramba_tpu.resilience import memory as _memory
from ramba_tpu.resilience.spill import SpilledArray as _SpilledArray
from ramba_tpu.utils import timing as _timing

# Donation is pointless for small buffers and fragments the jit cache (the
# donate mask is part of the compile key); only donate above this size.
DONATE_MIN_BYTES = 1 << 20


def _nbytes(v) -> int:
    """Buffer size, 0 when unknowable — extended dtypes (e.g. PRNG key
    arrays) raise from the ``nbytes`` property itself, so getattr-with-
    default is not enough."""
    try:
        return int(v.nbytes)
    except Exception:
        return 0


# ---------------------------------------------------------------------------
# cross-stream shared state + its locks
# ---------------------------------------------------------------------------

# id(buffer) -> number of live ndarrays whose materialized value IS that
# buffer.  Zero owners at flush time means nothing can observe the buffer
# after this flush, so it is safe to donate.
_const_owners: dict[int, int] = {}
_census_lock = threading.RLock()

# id(leaf value) -> number of prepared-but-not-finished flushes holding it
# as a program input.  A buffer referenced by MORE than one in-flight
# program must not be donated by any of them: streams can share subgraphs
# (and therefore leaves) and a donation in stream A would hand stream B a
# deleted buffer.  On the single default stream exactly one flush is ever
# in flight, so the count is always 1 and the donation decision reduces to
# the historical owners==0 test.
_inflight_leaves: dict[int, int] = {}
_flight_lock = threading.Lock()

# Bounded LRU compile cache; entries from an old mesh epoch are purged on
# the first flush after set_mesh (their sharding constraints baked in the old
# mesh), and user-function keys (fromfunction/apply statics) can't pin
# unbounded executables.  dict preserves insertion order and a hit re-inserts
# its key, so iteration order IS recency order and eviction pops the LRU.
# Shared by every stream (a program's structure is tenant-independent —
# sharing IS what makes coalesced dispatch compile-cache-warm) and guarded
# by _cache_lock now that streams flush concurrently.
_compile_cache: "dict" = {}
_COMPILE_CACHE_MAX = 512
_cache_epoch = 0
_cache_lock = threading.RLock()

# Monotone flush counters (observability; cf. reference dag-count history,
# ramba.py:5120-5128).  Process-wide across all streams.
stats = {"flushes": 0, "compiles": 0, "nodes_flushed": 0, "segments": 0}
_stats_lock = threading.Lock()


# ---------------------------------------------------------------------------
# flush streams
# ---------------------------------------------------------------------------

_stream_ids = itertools.count(1)


class FlushStream:
    """Session-scoped pending registry + flush scope.

    One per serving session (``serve.Session``), plus the process-wide
    default stream.  Each stream owns:

    * its pending registry (ndarrays with a non-Const expression),
    * its ``nodes_since_flush`` counter and ``max_pending_ops`` threshold
      (one tenant's build burst can no longer force-flush another
      tenant's half-built program),
    * its quarantine scope — a flush failure unregisters only THIS
      stream's roots, and
    * its flush ordering: ``_flush_lock`` serializes flushes of the same
      stream (concurrent flushes of one stream would double-execute and
      double-donate the same roots), while different streams flush
      concurrently.
    """

    __slots__ = ("stream_id", "name", "tenant", "max_pending_ops",
                 "quota_bytes", "on_threshold", "inflight", "stats",
                 "nodes_since_flush", "trace_id", "root_span",
                 "deadline_ms", "priority",
                 "_pending", "_lock", "_flush_lock", "__weakref__")

    def __init__(self, name: Optional[str] = None,
                 tenant: Optional[str] = None,
                 max_pending_ops: Optional[int] = None,
                 quota_bytes: Optional[int] = None):
        self.stream_id = next(_stream_ids)
        self.name = name or f"stream{self.stream_id}"
        self.tenant = tenant
        # None -> the process-wide common.max_pending_ops default
        self.max_pending_ops = max_pending_ops
        # per-tenant HBM quota enforced by memory-governor admission
        self.quota_bytes = quota_bytes
        # hook the serving session installs so threshold auto-flushes go
        # through the async pipeline instead of blocking the build thread
        self.on_threshold = None
        # causal trace identity (serve.Session mints these): every flush
        # span of this stream carries trace_id and chains to root_span
        self.trace_id: Optional[str] = None
        self.root_span: Optional[str] = None
        # overload plane (serve.Session mints these too): per-flush time
        # budget and brownout-shedding exemption — see serve/overload.py
        self.deadline_ms: Optional[float] = None
        self.priority = False
        # in-flight async work (objects with .wait()); serve/pipeline.py
        # maintains this so drain()/materialization can rendezvous
        self.inflight: list = []
        self.stats = {"flushes": 0, "nodes_flushed": 0, "quarantined": 0,
                      "enqueued": 0}
        self.nodes_since_flush = 0
        self._pending: dict[int, "weakref.ref"] = {}
        self._lock = threading.RLock()
        self._flush_lock = threading.RLock()
        _streams.add(self)

    def __repr__(self):
        return (f"<FlushStream {self.name!r} tenant={self.tenant!r} "
                f"pending={len(self._pending)}>")

    # -- registry ----------------------------------------------------------

    def register(self, arr) -> None:
        k = id(arr)

        def _cleanup(ref, _k=k, _s=self):
            with _s._lock:
                if _s._pending.get(_k) is ref:
                    del _s._pending[_k]
                else:
                    return
            with _reg_lock:
                if _arr_streams.get(_k) is _s:
                    del _arr_streams[_k]

        with self._lock:
            self._pending[k] = weakref.ref(arr, _cleanup)

    def unregister(self, arr) -> None:
        with self._lock:
            self._pending.pop(id(arr), None)

    def pending_arrays(self) -> list:
        out = []
        with self._lock:
            refs = list(self._pending.values())
        for r in refs:
            a = r()
            if a is not None:
                out.append(a)
        return out

    def pending_roots(self) -> list:
        """Pending ndarrays in deterministic (creation) order — the program
        the next flush of this stream will run is defined by this set."""
        roots = [a for a in self.pending_arrays()
                 if not isinstance(a._expr, Const)]
        roots.sort(key=lambda a: a._seq)
        return roots

    def _collect(self, *, detach: bool = False) -> list:
        """Atomically snapshot the roots of the next flush and reset the
        node counter.  ``detach`` (the async-enqueue path) additionally
        removes the roots from the registry so a later enqueue cannot
        collect — and double-execute — the same work; the returned strong
        references keep the arrays alive until write-back."""
        with self._lock:
            self.nodes_since_flush = 0
            roots = []
            for r in list(self._pending.values()):
                a = r()
                if a is not None and not isinstance(a._expr, Const):
                    roots.append(a)
            roots.sort(key=lambda a: a._seq)
            if detach:
                for a in roots:
                    self._pending.pop(id(a), None)
        if detach and roots:
            with _reg_lock:
                for a in roots:
                    if _arr_streams.get(id(a)) is self:
                        del _arr_streams[id(a)]
        return roots

    # -- thresholds --------------------------------------------------------

    def note_node_created(self) -> None:
        """Forced-flush safety valve for unbounded build loops — per
        stream, so one tenant's burst only flushes that tenant's work."""
        with self._lock:
            self.nodes_since_flush += 1
            cap = self.max_pending_ops
            if cap is None:
                cap = common.max_pending_ops
            fire = cap and self.nodes_since_flush >= cap
        if fire:
            hook = self.on_threshold
            if hook is not None:
                hook(self)
            else:
                self.flush()

    # -- flushing ----------------------------------------------------------

    def flush(self, extra: Sequence[Expr] = ()) -> list:
        """Synchronously materialize this stream's pending ndarrays (and
        ``extra`` expressions).  Returns the values of ``extra`` in
        order."""
        with self._flush_lock, stream_scope(self):
            roots = self._collect()
            work = _flush_prepare(self, roots, extra)
            if work is None:
                return []
            return _flush_dispatch(work)

    def drain(self) -> None:
        """Wait for every in-flight async flush of this stream (enqueued
        via serve/pipeline.py) to finish.  Failures surface through the
        tickets / later materialization, not here."""
        for t in list(self.inflight):
            wait = getattr(t, "wait", None)
            if wait is not None:
                try:
                    wait()
                except Exception:
                    pass


# All live streams (weak — a dropped session's stream must be collectable).
# FlushStream has no __eq__, so WeakSet membership is identity, as needed.
_streams: "weakref.WeakSet[FlushStream]" = weakref.WeakSet()

#: The process-wide default stream: everything outside a serve.Session.
_default_stream = FlushStream(name="default")

# Historical module-level registry — tests and debug tooling reach for
# ``fuser._pending`` directly; it IS the default stream's dict (the default
# stream only ever mutates, never replaces, this object).
_pending = _default_stream._pending

# id(arr) -> owning FlushStream for every pending ndarray, so
# materialization can flush the stream that owns the work regardless of
# which thread/session touches the array.
_arr_streams: dict[int, FlushStream] = {}
_reg_lock = threading.RLock()

_current_stream: "contextvars.ContextVar[Optional[FlushStream]]" = \
    contextvars.ContextVar("ramba_flush_stream", default=None)


def current_stream() -> FlushStream:
    s = _current_stream.get()
    return s if s is not None else _default_stream


def default_stream() -> FlushStream:
    return _default_stream


def current_tenant() -> Optional[str]:
    s = _current_stream.get()
    return s.tenant if s is not None else None


@contextmanager
def stream_scope(stream: FlushStream):
    """Make ``stream`` the current stream for the calling context (new
    lazy arrays register into it; ledger/counter attribution follows)."""
    token = _current_stream.set(stream)
    try:
        yield stream
    finally:
        _current_stream.reset(token)


def activate_stream(stream: FlushStream):
    """Non-contextmanager activation (serve.Session.__enter__); returns
    the token for :func:`deactivate_stream`."""
    return _current_stream.set(stream)


def deactivate_stream(token) -> None:
    _current_stream.reset(token)


def all_streams() -> list:
    """Live streams, default first, then by creation order."""
    out = [s for s in list(_streams) if s is not _default_stream]
    out.sort(key=lambda s: s.stream_id)
    return [_default_stream] + out


def stream_of(arr) -> FlushStream:
    """The stream that owns ``arr``'s pending work (current stream when
    the array is not pending anywhere — e.g. already materialized or
    quarantined)."""
    with _reg_lock:
        s = _arr_streams.get(id(arr))
    return s if s is not None else current_stream()


def register_pending(arr) -> None:
    k = id(arr)
    with _reg_lock:
        s = _arr_streams.get(k)
        if s is None:
            s = current_stream()
            _arr_streams[k] = s
    s.register(arr)


def unregister_pending(arr) -> None:
    k = id(arr)
    with _reg_lock:
        s = _arr_streams.pop(k, None)
    if s is not None:
        s.unregister(arr)
    else:
        # never registered under a stream (or already collected); make the
        # historical contract hold for direct callers
        _default_stream.unregister(arr)


def _pending_arrays() -> list:
    """Every pending ndarray across ALL streams (debug tooling and the
    sync barrier read this; per-stream work uses the stream's own)."""
    out = []
    for s in all_streams():
        out.extend(s.pending_arrays())
    return out


def note_node_created(arr=None) -> None:
    """Per-stream forced-flush safety valve.  With ``arr`` given, the
    counter/threshold of the *owning* stream advances; bare calls charge
    the current stream (historical signature)."""
    if arr is not None:
        stream_of(arr).note_node_created()
    else:
        current_stream().note_node_created()


# ---------------------------------------------------------------------------
# owner census (shared across streams; donation safety)
# ---------------------------------------------------------------------------


def owner_incref(buf, const=None) -> None:
    """Count one more live ndarray owning ``buf``.  When the owning
    ``Const`` node is supplied (ndarray._set_expr does), the buffer is
    also registered with the memory governor's live-bytes ledger."""
    with _census_lock:
        _const_owners[id(buf)] = _const_owners.get(id(buf), 0) + 1
    # outside the census lock: the memory ledger takes its own lock and
    # (on spill) calls back into owner_rekey — nesting would deadlock
    if const is not None:
        _memory.on_incref(const)


def owner_decref(buf) -> None:
    k = id(buf)
    with _census_lock:
        n = _const_owners.get(k, 0) - 1
        released = n <= 0
        if released:
            _const_owners.pop(k, None)
        else:
            _const_owners[k] = n
    if released:
        _memory.on_release(buf)


def owner_rekey(old, new) -> None:
    """Migrate the owner census when the memory governor swaps a Const's
    value object (device array ↔ host spill wrapper): the count follows
    the buffer identity, so the donation decision at the next flush sees
    the same aliasing it would have seen without the spill."""
    with _census_lock:
        n = _const_owners.pop(id(old), 0)
        if n > 0:
            _const_owners[id(new)] = _const_owners.get(id(new), 0) + n


def leaf_value(leaf):
    """Device value of a Const leaf, transparently restoring it from a
    host spill if the memory governor evicted it (resilience.memory)."""
    v = leaf.value
    if isinstance(v, _SpilledArray):
        return _memory.restore(leaf)
    return v


def _flight_incref(leaf_vals) -> list:
    keys = []
    with _flight_lock:
        for v in leaf_vals:
            k = id(v)
            _inflight_leaves[k] = _inflight_leaves.get(k, 0) + 1
            keys.append(k)
    return keys


def _flight_decref(keys) -> None:
    with _flight_lock:
        for k in keys:
            n = _inflight_leaves.get(k, 0) - 1
            if n <= 0:
                _inflight_leaves.pop(k, None)
            else:
                _inflight_leaves[k] = n


class _Program:
    """Buffer-free linearization of an expression DAG.

    ``instrs[i] = (op, static, arg_slots)`` where slots < n_leaves index the
    leaf arguments and later slots index prior instruction results.  Holding
    no jax.Array references makes the program safe to retain in the compile
    cache without pinning HBM.
    """

    __slots__ = ("instrs", "n_leaves", "leaf_kinds", "out_slots", "key",
                 "key_hash")

    def __init__(self, instrs, n_leaves, leaf_kinds, out_slots):
        self.instrs = instrs
        self.n_leaves = n_leaves
        self.leaf_kinds = leaf_kinds
        self.out_slots = tuple(out_slots)
        self.key = (tuple(instrs), n_leaves, leaf_kinds, self.out_slots)
        # Hashed at linearize time (the key is part of the capture
        # product) so prepare-side caches keyed on the program pay an
        # O(1) cached hash instead of re-walking the instrs tuple; -1
        # marks an unhashable key (static carrying a list/dict).
        try:
            self.key_hash = hash(self.key)
        except TypeError:
            self.key_hash = -1


def _linearize(roots: Sequence[Expr]):
    """Iterative postorder DFS over the DAG with node dedup (shared subexprs
    evaluate once — the fusion the reference gets by concatenating codelines
    into a single loop nest, ramba.py:8348-8423)."""
    slot: dict[int, int] = {}
    leaves: list = []
    instrs: list = []
    # first pass: collect leaves in deterministic order
    const_slot: dict[int, int] = {}  # id(buffer) -> leaf slot (dedup aliased)
    order: list[Expr] = []
    seen: set[int] = set()
    stack = [(r, False) for r in reversed(roots)]
    while stack:
        node, done = stack.pop()
        nid = id(node)
        if done:
            order.append(node)
            continue
        if nid in seen:
            continue
        seen.add(nid)
        if isinstance(node, Node):
            stack.append((node, True))
            for a in reversed(node.args):
                stack.append((a, False))
        else:
            order.append(node)
    for node in order:
        nid = id(node)
        if nid in slot:
            continue
        if isinstance(node, Const):
            bid = id(node.value)
            if bid in const_slot:
                slot[nid] = const_slot[bid]
                continue
            const_slot[bid] = len(leaves)
            slot[nid] = len(leaves)
            leaves.append(node)
        elif isinstance(node, Scalar):
            slot[nid] = len(leaves)
            leaves.append(node)
    n_leaves = len(leaves)
    for node in order:
        nid = id(node)
        if nid in slot or not isinstance(node, Node):
            continue
        args = tuple(slot[id(a)] for a in node.args)
        slot[nid] = n_leaves + len(instrs)
        instrs.append((node.op, node.static, args))
    leaf_kinds = tuple("C" if isinstance(l, Const) else "S" for l in leaves)
    out_slots = [slot[id(r)] for r in roots]
    return _Program(tuple(instrs), n_leaves, leaf_kinds, out_slots), leaves


def _build_callable(program: _Program):
    instrs = program.instrs
    n_leaves = program.n_leaves
    out_slots = program.out_slots

    def run(*leaf_vals):
        vals = list(leaf_vals)
        for op, static, argslots in instrs:
            vals.append(OPS[op](static, *(vals[s] for s in argslots)))
        return tuple(vals[s] for s in out_slots)

    return run


def _pending_roots() -> list:
    """Pending ndarrays of the CURRENT stream in deterministic (creation)
    order — the program the next flush will run is defined by this set."""
    return current_stream().pending_roots()


def _prepare_program(exprs: Sequence[Expr]):
    """Rewrite + linearize — shared by flush() and analyze_pending() so both
    always see the identical program.  Returns ``(program, leaves, exprs)``
    where ``exprs`` are the (possibly rewritten) roots, so the RAMBA_VERIFY
    verifier can re-check the very graph that was linearized."""
    if common.rewrite_enabled:
        from ramba_tpu.core.rewrite import rewrite_roots

        try:
            exprs = rewrite_roots(exprs)
        except Exception as e:
            # The rewriter is an optimizer: a crash in it must never take
            # the flush down.  Degrade to the unrewritten graph.
            _registry.inc("resilience.rewrite_bypassed")
            _events.emit({
                "type": "degrade", "site": "rewrite", "action": "rung",
                "from": "rewritten", "to": "unrewritten",
                "error": f"{type(e).__name__}: {e}"[:300],
            })
    program, leaves = _linearize(exprs)
    return program, leaves, exprs


def _program_label(program: _Program) -> str:
    """Stable per-structure label for profiling: hashes only the op sequence
    (statics can hold closures whose repr embeds memory addresses) — the
    reference names kernels sha256(code), ramba.py:8260-8265."""
    text = " ".join(op for op, _, _ in program.instrs) + f"|{program.n_leaves}"
    return "prog_" + hashlib.sha256(text.encode()).hexdigest()[:12]


def _semantic_fingerprint() -> tuple:
    """Trace-time global configuration the OPS eval rules consult.  Anything
    an eval rule reads while being traced MUST appear here: ``program.key``
    captures structure only, so two programs with identical structure but
    different trace-time semantics — e.g. NEP-50 promotion in
    ``expr._np_loop_dtypes``, which keys off ``jax_enable_x64`` — would
    otherwise share one compiled executable and silently reuse the wrong
    numerics (the collision the analyze graph-hygiene rule detects)."""
    return (bool(jax.config.jax_enable_x64),)


def _cache_key(program: _Program, donate_key: tuple,
               compile_class=None) -> tuple:
    """Full compile-cache key: structure + donation mask + the trace-time
    semantic fingerprint (+ the shape-bucket compile class, when the
    flush was bucketed — bucketed and exact-shape executables must never
    share an entry)."""
    if compile_class is None:
        return (program.key, donate_key, _semantic_fingerprint())
    return (program.key, donate_key, _semantic_fingerprint(),
            ("class",) + tuple(compile_class))


def _get_compiled(program: _Program, donate_key: tuple,
                  leaf_vals=None, force_backend: Optional[str] = None,
                  compile_class=None):
    """Compile-cache lookup (mesh-epoch aware, true LRU).  Returns
    ``(fn, is_new, fingerprint, backend)`` where ``fingerprint`` is the
    stable per-kernel key the cost ledger files this program under and
    ``backend`` names the lowering that produced ``fn`` (``"xla"`` /
    ``"pallas"``; None for the default XLA lowering when the autotuner is
    not consulted).  The backend-selection seam: with ``RAMBA_AUTOTUNE``
    armed and ``leaf_vals`` provided, ``core/autotune.py`` picks the
    backend per fingerprint from the cost ledger; ``force_backend`` pins
    it (races, prewarms, fallback retries).  XLA executables keep the
    historical cache key so fingerprints stay stable across autotune
    on/off; a Pallas executable lives under ``key + ("pallas",)`` — a
    loser backend ages out through the same LRU as everything else.  The
    whole lookup runs under ``_cache_lock`` — jax.jit object creation is
    lazy (the expensive compile happens at first *call*, outside), so
    the critical section stays short while concurrent streams can never
    corrupt the LRU order or double-count a miss."""
    global _cache_epoch
    from ramba_tpu.core import autotune as _autotune
    with _cache_lock:
        if _cache_epoch != _mesh.mesh_epoch:
            _compile_cache.clear()
            _cache_epoch = _mesh.mesh_epoch
        key = _cache_key(program, donate_key, compile_class)
        fp = _ledger.fingerprint(key)
        if force_backend is not None:
            backend = force_backend
        elif leaf_vals is not None and _autotune.active():
            backend, _via = _autotune.select(fp, program, leaf_vals)
        else:
            backend = None
        cache_key = key if backend != "pallas" else key + ("pallas",)
        fn = _compile_cache.pop(cache_key, None)
        if fn is not None:
            _compile_cache[cache_key] = fn  # re-insert: move to MRU position
            _registry.inc("fuser.cache_hit")
            _ledger.record_cache(fp, "hit")
            return fn, False, fp, backend
        build = None
        if backend == "pallas":
            from ramba_tpu.ops import pallas_backend as _pallas
            try:
                build = _pallas.lower_program(program, leaf_vals)
            except Exception as e:
                _autotune.note_failure(fp, "pallas", e)
                build = None
            if build is None:
                # not lowerable (or lowering failed): degrade to the XLA
                # backend, re-checking the cache under the XLA key
                backend = "xla" if force_backend is None \
                    or _autotune.active() else None
                cache_key = key
                fn = _compile_cache.pop(cache_key, None)
                if fn is not None:
                    _compile_cache[cache_key] = fn
                    _registry.inc("fuser.cache_hit")
                    _ledger.record_cache(fp, "hit")
                    return fn, False, fp, backend
        if len(_compile_cache) >= _COMPILE_CACHE_MAX:
            old_key = next(iter(_compile_cache))  # LRU: least recently used
            _compile_cache.pop(old_key)
            _registry.inc("fuser.cache_evict")
            _ledger.record_cache(_ledger.fingerprint(old_key), "evict")
            _events.emit({
                "type": "cache_evict",
                "key": _ledger.fingerprint(old_key),
                "capacity": _COMPILE_CACHE_MAX,
            })
        # Persistent AOT lane (compile/persist.py): a compile-cache miss
        # consults the on-disk executable cache before paying a compile.
        # A deserialized executable is a hit for accounting purposes —
        # is_new stays False so the ledger shows near-zero compile wall
        # in a warm process.
        if (leaf_vals is not None and backend != "pallas"
                and build is None and _persist.armed()):
            aot = _persist.lookup(fp, leaf_vals, program, donate_key)
            if aot is not None:
                _compile_cache[cache_key] = aot
                _ledger.record_cache(fp, "miss")
                return aot, False, fp, backend
        _faults.check("compile", instrs=len(program.instrs))
        fn = jax.jit(build if build is not None
                     else _build_callable(program),
                     donate_argnums=donate_key)
        _compile_cache[cache_key] = fn
        with _stats_lock:
            stats["compiles"] += 1
        _registry.inc("fuser.cache_miss")
        _ledger.record_cache(fp, "miss")
        if (leaf_vals is not None and backend != "pallas"
                and build is None and _persist.armed()):
            # register as an AOT candidate (compiles are rare; the one
            # small program-skeleton write stays off the steady state)
            _persist.note_compiled(fp, program, donate_key, leaf_vals,
                                   compile_class=compile_class)
        return fn, True, fp, backend


def _last_use_map(program: _Program) -> dict:
    """slot -> highest slot index that consumes it; program outputs are
    pinned past the end so they are never freed or donated."""
    instrs, n_leaves = program.instrs, program.n_leaves
    last_use: dict[int, int] = {}
    for i, (_op, _st, args) in enumerate(instrs):
        for s in args:
            last_use[s] = n_leaves + i
    inf = n_leaves + len(instrs) + 1
    for s in program.out_slots:
        last_use[s] = inf
    return last_use


def _byte_segment_end(instrs, n_leaves, start: int, slot_bytes: dict,
                      max_seg_bytes: int, seg_cap: int) -> int:
    """First instruction index past a byte-bounded segment starting at
    ``start``: accumulate the estimated bytes each instruction adds to
    the segment's live set (its output slot plus any external inputs it
    pulls in) and stop before the running total crosses
    ``max_seg_bytes``.  Always admits at least one instruction."""
    base = n_leaves + start
    ninstr = len(instrs)
    seen_in: set = set()
    seg_bytes = 0
    end = start
    while end < ninstr:
        if seg_cap and end - start >= seg_cap:
            break
        _op, _st, args = instrs[end]
        cost = slot_bytes.get(n_leaves + end, 0)
        for s in args:
            if s < base and s not in seen_in:
                cost += slot_bytes.get(s, 0)
        if end > start and seg_bytes + cost > max_seg_bytes:
            break
        for s in args:
            if s < base:
                seen_in.add(s)
        seg_bytes += cost
        end += 1
    return end


def _iter_segments(program: _Program, last_use: dict,
                   seg_size: Optional[int] = None, *,
                   slot_bytes: Optional[dict] = None,
                   max_seg_bytes: Optional[int] = None):
    """Split ``program`` into sub-programs of at most ``seg_size``
    (default ``common.max_program_instrs``) instructions — or, when
    ``max_seg_bytes``/``slot_bytes`` are given (the ``chunked`` rung), of
    bounded *estimated live bytes* per segment.  Yields
    ``(seg_prog, in_slots, out_here, top)`` where ``in_slots`` are the
    parent-program value slots the segment consumes, ``out_here`` the
    parent slots it must emit (used later or program outputs), and ``top``
    the first parent slot index past this segment."""
    instrs, n_leaves = program.instrs, program.n_leaves
    if seg_size is None:
        seg_size = common.max_program_instrs
    ninstr = len(instrs)
    start = 0
    while start < ninstr:
        if max_seg_bytes and slot_bytes is not None:
            end = _byte_segment_end(instrs, n_leaves, start, slot_bytes,
                                    max_seg_bytes, seg_size)
        else:
            end = min(start + seg_size, ninstr)
        base, top = n_leaves + start, n_leaves + end
        seg = instrs[start:end]
        in_slots = sorted(
            {s for _o, _s, args in seg for s in args if s < base}
        )
        remap = {s: j for j, s in enumerate(in_slots)}
        nin = len(in_slots)
        seg_instrs = tuple(
            (op, st, tuple(remap[s] if s < base else nin + (s - base)
                           for s in args))
            for op, st, args in seg
        )
        out_here = [s for s in range(base, top) if last_use.get(s, 0) >= top]
        seg_prog = _Program(
            seg_instrs,
            nin,
            tuple(program.leaf_kinds[s] if s < n_leaves else "C"
                  for s in in_slots),
            tuple(nin + (s - base) for s in out_here),
        )
        yield seg_prog, in_slots, out_here, top
        start = end


def _run_segmented(program: _Program, leaf_vals: list, donate_idx: tuple,
                   span: Optional[dict] = None,
                   seg_size: Optional[int] = None, *,
                   slot_bytes: Optional[dict] = None,
                   max_seg_bytes: Optional[int] = None,
                   rung: str = "fused"):
    """Execute an oversized program as chained jit calls of at most
    ``seg_size`` (default ``common.max_program_instrs``) instructions each.

    XLA compile time grows superlinearly with program length (a 3000-op
    elementwise chain took minutes on CPU), so one giant jit is a
    scalability hazard the reference never hits only because its tests cap
    chain length.  Segment boundaries cut the dataflow: values crossing a
    boundary become segment outputs carried to the next call.  Each segment
    is cached by its own structure, so a long chain of repeated ops compiles
    ONE segment and reuses it; cross-segment intermediates that die inside a
    segment are donated so the chain still updates HBM in place.
    """
    n_leaves = program.n_leaves
    last_use = _last_use_map(program)
    donate_set = set(donate_idx)
    vals: dict[int, object] = dict(enumerate(leaf_vals))
    for seg_prog, in_slots, out_here, top in _iter_segments(
        program, last_use, seg_size,
        slot_bytes=slot_bytes, max_seg_bytes=max_seg_bytes,
    ):
        seg_donate = []
        for j, s in enumerate(in_slots):
            if last_use.get(s, 0) >= top:
                continue  # still live after this segment
            if s < n_leaves and s not in donate_set:
                continue  # caller-visible leaf not cleared for donation
            if _nbytes(vals[s]) >= DONATE_MIN_BYTES:
                seg_donate.append(j)
        fn, is_new, fp, _backend = _get_compiled(seg_prog, tuple(seg_donate))
        seg_vals = [vals[s] for s in in_slots]
        outs = _execute_compiled(fn, seg_prog, seg_vals, is_new, span=span,
                                 fp=fp, rung=rung, donated=len(seg_donate))
        del seg_vals
        for s in in_slots:
            if last_use.get(s, 0) < top:
                del vals[s]
        for s, v in zip(out_here, outs):
            vals[s] = v
        with _stats_lock:
            stats["segments"] += 1
        _registry.inc("fuser.segments")
    return tuple(vals[s] for s in program.out_slots)


def _run_chunked(program: _Program, leaf_vals, donate_idx: tuple,
                 span: Optional[dict] = None):
    """The ``chunked`` rung: the segmented executor bounded by *estimated
    live bytes* per segment (resilience.memory supplies the target)
    instead of instruction count.  Donation-chain semantics are exactly
    ``_run_segmented``'s — mid-chain intermediates (and cleared leaves,
    when admission control routed here with a donate mask) still free as
    they die, which is what bounds the peak live set."""
    from ramba_tpu.analyze import rules as _rules

    avals = _memory._leaf_avals(leaf_vals)
    slot_bytes = _rules.slot_nbytes(program, avals)
    cap = _memory.chunk_target_bytes()
    if span is not None:
        span["chunk_bytes"] = cap
    _registry.inc("fuser.chunked_runs")
    return _run_segmented(program, leaf_vals, donate_idx, span=span,
                          slot_bytes=slot_bytes, max_seg_bytes=cap,
                          rung="chunked")


def _execute_compiled(fn, program: _Program, leaf_vals, is_new: bool,
                      span: Optional[dict] = None, fp: Optional[str] = None,
                      rung: str = "fused", donated: int = 0,
                      backend: Optional[str] = None):
    """Run one compiled program with the shared observability treatment:
    RAMBA_SHOW_CODE dump on first compile, profiler TraceAnnotation at
    RAMBA_TIMING>=2 or under RAMBA_PROFILE_DIR, first-call
    (trace+lower+XLA compile) vs steady-state timing attribution, a cost
    ledger record filed under ``fp`` (with the degradation ``rung`` this
    execution ran on), and — when ``span`` is given — a per-call child
    record in the flush span.  Used by both the monolithic and segmented
    flush paths so the two can never drift."""
    # Attribution clock starts at call entry — BEFORE the fault hooks — so
    # an injected execute delay lands in the sentinel's device window
    # exactly like a real device slowdown.
    t_call = time.perf_counter()
    _faults.check("execute", instrs=len(program.instrs))
    _faults.check("oom", instrs=len(program.instrs))
    if is_new and _ledger.cost_enabled() and fp is not None:
        # Before execution: donated input buffers are dead afterwards, and
        # AOT lowering wants live avals.
        _ledger.capture_cost(fp, fn, leaf_vals, backend=backend)
    if is_new and common.show_code:
        import sys

        # jaxpr + lowered StableHLO (the reference's RAMBA_SHOW_CODE
        # dumps the generated Numba source, ramba.py:8266-8284).
        # Lowering only — compiling here would build a throwaway AOT
        # executable the call below cannot reuse.
        print(
            jax.make_jaxpr(_build_callable(program))(*leaf_vals),
            file=sys.stderr,
        )
        try:
            print(fn.lower(*leaf_vals).as_text()[:20000], file=sys.stderr)
        except Exception:
            pass
    bytes_in = sum(_nbytes(v) for v in leaf_vals)
    t0 = time.perf_counter()
    if common.timing_level > 1 or _profile.enabled():
        # label the dispatch in profiler traces (RAMBA_PROFILE_DIR /
        # utils.timing.profiler_trace); off the hot path otherwise
        with _profile.annotation(_program_label(program)):
            outs = fn(*leaf_vals)
    else:
        outs = fn(*leaf_vals)
    dt = time.perf_counter() - t0
    sync_dt = None
    fence_dt = None
    # Cheap device fence: dt above stays the dispatch-time measurement
    # every existing consumer sees; the fence window is the on-device
    # tail the stage ledger files as device_execute.  Under
    # RAMBA_ATTRIB=sample:<N> the fence fires 1-in-N calls per
    # fingerprint (deterministic — see attrib.fence_decision), so the
    # steady state stops paying the serialization tax on every flush.
    if _attrib.fence_decision(fp, span) or _ledger.sync_timing():
        try:
            jax.block_until_ready(outs)
            fence_dt = time.perf_counter() - t0 - dt
        except Exception:
            fence_dt = None
        if fence_dt is not None:
            # the fence wait is observability's own cost: the device tail
            # would have overlapped the host had we not blocked on it
            _observer.add("fence", fence_dt)
        if fence_dt is not None and _ledger.sync_timing():
            # RAMBA_PERF=sync: a second, device-synchronized sample.
            sync_dt = dt + fence_dt
    if is_new:
        # jax.jit compiles lazily: the first call pays trace+lower+XLA
        # compile.  Attribute it separately so per-program execution times
        # stay comparable.
        _timing.add_time("trace_compile_first_call", dt)
    else:
        _timing.add_time("flush_execute", dt)
        if common.timing_level > 0:  # label hashing is off the hot path
            _timing.add_func_time(_program_label(program), dt)
    if fp is not None:
        _ledger.record_execute(
            fp, _program_label(program), len(program.instrs), rung, dt,
            is_new, bytes_in=bytes_in,
            bytes_out=sum(_nbytes(o) for o in outs),
            donated=donated, sync_seconds=sync_dt,
            tenant=current_tenant(), backend=backend,
        )
        if fence_dt is not None and not is_new:
            # steady-state fenced window (entry through fence) feeds the
            # roofline device-time estimate and the drift sentinel
            _attrib.record_device(fp, _program_label(program),
                                  time.perf_counter() - t_call,
                                  backend=backend)
        elif fence_dt is None and not is_new and _attrib.sampling():
            # unfenced sampled call: carry the rolling fenced p50 as an
            # estimate on the span (display-only — never a stage, the
            # device tail genuinely overlaps the host here)
            est = _attrib.estimated_device_s(fp)
            if est is not None and span is not None:
                span["device_est_s"] = round(
                    span.get("device_est_s", 0.0) + est, 6)
    if span is not None:
        if is_new:
            # first call pays trace+lower+XLA compile; the pre-call
            # prelude (cost probe, show_code lowering) bills here too
            _attrib.add_stage(span, "compile", (t0 - t_call) + dt)
        else:
            _attrib.add_stage(span, "dispatch", (t0 - t_call) + dt)
        if fence_dt is not None:
            _attrib.add_stage(span, "device_execute", fence_dt)
        call = {
            "label": _program_label(program),
            "cache": "miss" if is_new else "hit",
            "seconds": round(dt, 6),
        }
        if backend is not None:
            call["backend"] = backend
        span["calls"].append(call)
    return outs


def _attempt_fused(program: _Program, leaf_vals, donate_key: tuple,
                   span: Optional[dict], class_plan=None):
    """Rung 0: the normal fused path (monolithic jit, or the standard
    segmented executor above ``common.max_program_instrs``).  With
    ``RAMBA_COMPILE_CLASSES`` armed and a bucket plan certified for this
    flush, leaves are zero-padded up to the bucket before execution and
    outputs sliced back to the exact extent — the pad/slice wrapper that
    lets a million request shapes share one executable.  Only this rung
    buckets: the lower resilience rungs always run exact shapes, and the
    padded copies are fresh temporaries so donating them is safe while
    the original leaves stay alive for any fallback.  With
    ``RAMBA_AUTOTUNE`` armed this is where the backend race plays out:
    the autotuner may hand back the Pallas lowering, whose first
    (compile-paying) call is deferred through the async compile pipeline
    when one is live, and whose failures degrade to the XLA backend —
    recorded on the ledger — before the resilience ladder is ever
    involved."""
    if (
        common.max_program_instrs
        and len(program.instrs) > common.max_program_instrs
    ):
        return _run_segmented(program, leaf_vals, donate_key, span=span)
    if class_plan is not None:
        padded = _classes.apply(class_plan, leaf_vals)
        outs = _attempt_fused_exec(program, padded, donate_key, span,
                                   compile_class=class_plan.token)
        return _classes.strip(class_plan, outs)
    return _attempt_fused_exec(program, leaf_vals, donate_key, span)


def _attempt_fused_exec(program: _Program, leaf_vals, donate_key: tuple,
                        span: Optional[dict], compile_class=None):
    fn, is_new, fp, backend = _get_compiled(program, donate_key,
                                            leaf_vals=leaf_vals,
                                            compile_class=compile_class)
    if backend == "pallas":
        from ramba_tpu.core import autotune as _autotune

        if is_new and _autotune.mode() == "race":
            # Race compiles must not stall this flush (or, on the async
            # path, other tenants' tickets): when a compile pipeline is
            # live, warm the Pallas executable through it and serve this
            # flush from the XLA backend meanwhile.  (force:<backend>
            # deliberately compiles inline — the operator asked for that
            # backend now, not eventually.)
            pipe = None
            try:
                from ramba_tpu.serve import pipeline as _pipeline
                pipe = _pipeline.current_pipeline()
            except Exception:
                pipe = None
            # single-controller only: async warm completion would skew
            # the per-rank race counts out of SPMD lockstep, and the
            # latch agreement collective relies on that lockstep
            if pipe is not None and hasattr(pipe, "submit_warm") \
                    and jax.process_count() == 1:
                _autotune.maybe_prewarm(fp, program, leaf_vals, donate_key)
                fn, is_new, fp, backend = _get_compiled(
                    program, donate_key, leaf_vals=leaf_vals,
                    force_backend="xla", compile_class=compile_class)
                return _execute_compiled(
                    fn, program, leaf_vals, is_new, span=span, fp=fp,
                    rung="fused", donated=len(donate_key), backend=backend)
        try:
            return _execute_compiled(
                fn, program, leaf_vals, is_new, span=span, fp=fp,
                rung="fused", donated=len(donate_key), backend=backend)
        except _faults.InjectedFault:
            # execute/oom fault sites belong to the resilience ladder,
            # not to backend selection (the "pallas" fault site fires at
            # lowering time, inside _get_compiled)
            raise
        except Exception as e:
            # A Pallas kernel that traced fine can still fail at first
            # call (Mosaic compile) or at dispatch.  Degrade to the XLA
            # backend for this fingerprint — permanently — provided no
            # leaf buffer was consumed by the failed attempt.
            for v in leaf_vals:
                is_deleted = getattr(v, "is_deleted", None)
                if is_deleted is not None and is_deleted():
                    raise
            _autotune.note_failure(fp, "pallas", e)
            with _cache_lock:
                _compile_cache.pop(
                    _cache_key(program, donate_key, compile_class)
                    + ("pallas",), None)
            _events.emit({
                "type": "degrade", "site": "backend", "action": "backend",
                "from": "pallas", "to": "xla",
                "error": f"{type(e).__name__}: {e}"[:300],
            })
            fn, is_new, fp, backend = _get_compiled(
                program, donate_key, leaf_vals=leaf_vals,
                force_backend="xla", compile_class=compile_class)
            return _execute_compiled(
                fn, program, leaf_vals, is_new, span=span, fp=fp,
                rung="fused", donated=len(donate_key), backend=backend)
    return _execute_compiled(fn, program, leaf_vals, is_new, span=span,
                             fp=fp, rung="fused", donated=len(donate_key),
                             backend=backend)


def _run_eager(program: _Program, leaf_vals, span: Optional[dict]):
    """Rung 2: per-op eager dispatch — no jit, no fusion, no donation.
    Blocks on the results so any execution failure surfaces inside this
    rung (eager dispatch is async) rather than at a later materialize."""
    _faults.check("eager")
    t0 = time.perf_counter()
    # allow_all: eager ops on non-fully-addressable (multi-host) arrays
    # are refused by default; this rung runs them op-by-op deliberately
    with jax.spmd_mode("allow_all"):
        outs = _build_callable(program)(*leaf_vals)
    outs = jax.block_until_ready(outs)
    dt = time.perf_counter() - t0
    _ledger.record_execute(
        _ledger.fingerprint(_cache_key(program, ())),
        _program_label(program), len(program.instrs), "eager", dt, False,
        bytes_in=sum(_nbytes(v) for v in leaf_vals),
        bytes_out=sum(_nbytes(o) for o in outs),
        tenant=current_tenant(),
    )
    if span is not None:
        span["calls"].append({
            "label": _program_label(program),
            "cache": "eager",
            "seconds": round(dt, 6),
        })
    return outs


def _run_host(program: _Program, leaf_vals, span: Optional[dict]):
    """Rung 3 (last): interpret the whole program on the CPU backend —
    device → host fallback as a first-class path.  Inputs are pulled to
    host memory, the program runs eagerly on CPU, and outputs are placed
    back onto the accelerator mesh when it will accept them (kept
    host-committed otherwise: a degraded-but-correct result beats a
    crash).  Only offered single-controller — under multi-host SPMD no
    single process holds the global array."""
    _faults.check("host")
    import numpy as np
    from jax.sharding import NamedSharding

    t0 = time.perf_counter()
    cpu = jax.devices("cpu")[0]
    host_vals = []
    for v in leaf_vals:
        if isinstance(v, jax.Array):
            v = jax.device_put(np.asarray(v), cpu)
        host_vals.append(v)
    with jax.default_device(cpu):
        outs = _build_callable(program)(*host_vals)
    outs = jax.block_until_ready(outs)
    mesh = _mesh.get_mesh()
    res = []
    for o in outs:
        try:
            spec = _mesh.default_spec(o.shape, mesh)
            res.append(jax.device_put(o, NamedSharding(mesh, spec)))
        except Exception:
            res.append(o)
    dt = time.perf_counter() - t0
    _ledger.record_execute(
        _ledger.fingerprint(_cache_key(program, ())),
        _program_label(program), len(program.instrs), "host", dt, False,
        bytes_in=sum(_nbytes(v) for v in leaf_vals),
        bytes_out=sum(_nbytes(o) for o in res),
        tenant=current_tenant(),
    )
    if span is not None:
        span["calls"].append({
            "label": _program_label(program),
            "cache": "host",
            "seconds": round(dt, 6),
        })
    return tuple(res)


def _execute_resilient(program: _Program, leaf_vals, donate_key: tuple,
                       span: Optional[dict], skip_fused: bool = False,
                       route_chunked: bool = False,
                       tags: Optional[dict] = None,
                       deadline=None, class_plan=None):
    """Run the program down the degradation ladder (see
    ``resilience.degrade``): fused → split → chunked → eager → host.
    Returns ``(outs, rung_name)``; rung_name is "fused" on the healthy
    path.

    ``skip_fused`` (set when the RAMBA_VERIFY verifier found error
    findings in non-strict mode) starts the ladder at the split rung:
    no monolithic compile and no leaf donation, so a program the
    verifier distrusts can still produce a result without consuming
    caller-visible buffers.

    ``route_chunked`` (set by memory-governor admission control when the
    program cannot fit under the HBM watermark even after eviction)
    starts the ladder at the chunked rung — and, uniquely among
    below-fused rungs, KEEPS the donate mask: no failed attempt has
    consumed anything yet, and donating dead leaves is exactly what
    bounds the chunked peak.

    ``tags`` (e.g. ``{"tenant": ...}``) ride on every degrade event the
    ladder emits so the degradation timeline attributes to a tenant.

    ``deadline`` (a ``serve.overload.Deadline``) makes the ladder
    budget-aware: rungs whose rolling p50 cannot fit the remaining
    budget are pruned (single-controller; rank-local windows must not
    skew an SPMD ladder), every rung attempt re-checks expiry, and the
    elastic watchdog clamps to ``min(watchdog, remaining)``."""
    rungs = []
    if not skip_fused and not route_chunked:
        rungs.append(
            ("fused",
             lambda: _attempt_fused(program, leaf_vals, donate_key, span,
                                    class_plan=class_plan)))
    if (len(program.instrs) > 1 or skip_fused) and not route_chunked:
        cap = common.max_program_instrs or len(program.instrs)
        half = max(1, min(len(program.instrs), cap) // 2)
        # no leaf donation below the fused rung: a donated buffer consumed
        # by a failed attempt could not feed the next rung
        rungs.append(
            ("split",
             lambda: _run_segmented(program, leaf_vals, (), span=span,
                                    seg_size=half, rung="split")))
    if len(program.instrs) > 1 or route_chunked:
        chunk_donate = donate_key if route_chunked else ()
        rungs.append(
            ("chunked",
             lambda: _run_chunked(program, leaf_vals, chunk_donate, span)))
    rungs.append(("eager", lambda: _run_eager(program, leaf_vals, span)))
    try:
        single = jax.process_count() == 1
    except Exception:
        single = True
    if single:
        rungs.append(("host", lambda: _run_host(program, leaf_vals, span)))

    def leaves_alive() -> bool:
        for v in leaf_vals:
            is_deleted = getattr(v, "is_deleted", None)
            if is_deleted is not None and is_deleted():
                return False
        return True

    # Deadline-aware pruning: drop rungs whose rolling p50 cannot fit
    # the remaining budget (lazy import — serve imports this module).
    if deadline is not None:
        from ramba_tpu.serve import overload as _overload

        label = span.get("label", "?") if span else "?"
        tenant = tags.get("tenant") if tags else None
        rungs = _overload.prune_rungs(rungs, deadline, label,
                                      tenant=tenant)

    # Elastic watchdog: every rung attempt checks the "dispatch" fault
    # site (so RAMBA_FAULTS='dispatch:hang:ms=...' can seed a stall) and,
    # when RAMBA_WATCHDOG_S is armed, runs under a deadline — a hang
    # becomes a degrade-classified RankStallError, which the ladder
    # treats like any other failed rung instead of blocking forever.
    # With a request deadline, the per-attempt budget is clamped to
    # min(watchdog, remaining) so one slow rung cannot eat the whole
    # request budget before the ladder can try a cheaper rung.
    wd = _elastic.watchdog_seconds()

    def _guard(rung_name: str, thunk):
        def attempt():
            if deadline is not None:
                from ramba_tpu.serve import overload as _overload

                _overload.check_expired(
                    deadline, span.get("label", "?") if span else "?",
                    tenant=tags.get("tenant") if tags else None)
            _faults.check("dispatch", rung=rung_name)
            if _elastic.cancelled():
                # the watchdog gave up on this attempt while the fault
                # check slept; the ladder has moved on — running the rung
                # now would donate leaf buffers the recovery still owns
                raise RuntimeError(
                    f"abandoned {rung_name} attempt after watchdog stall")
            return thunk()

        if deadline is None:
            if wd is None:
                return attempt
            return lambda: _elastic.with_deadline("dispatch", attempt,
                                                  timeout_s=wd)

        def guarded():
            # clamp at attempt time — the remaining budget has shrunk
            # by however long the earlier rungs ran
            from ramba_tpu.serve import overload as _overload

            eff = _overload.clamp_watchdog(wd, deadline)
            if eff is None:
                return attempt()
            return _elastic.with_deadline("dispatch", attempt,
                                          timeout_s=eff)

        return guarded

    rungs = [(name, _guard(name, fn)) for name, fn in rungs]

    return _degrade.run_ladder("flush", rungs, leaf_check=leaves_alive,
                               tags=tags)


def _leaf_owner_counts(leaves) -> list:
    """Live-alias census per leaf slot: how many materialized ndarrays still
    own each Const leaf's buffer (Scalar leaves own nothing)."""
    with _census_lock:
        return [
            _const_owners.get(id(leaf.value), 0)
            if isinstance(leaf, Const) else 0
            for leaf in leaves
        ]


def _program_event(program: _Program, leaves, donate_key: tuple,
                   label: str, fingerprint: Optional[str] = None,
                   compile_class=None) -> dict:
    """Offline-lintable record of the program a flush is about to run —
    ``python -m ramba_tpu.analyze`` re-checks graph hygiene and donation
    hazards from these events without the live process, and the warm
    pool (``compile/warmpool.py``) ranks traces by the fingerprint +
    compile class recorded here.  Statics are repr-truncated: the
    offline rules need structure (op names, slot refs, donate mask,
    owner counts), not closure identities."""
    ev = {
        "type": "program", "label": label,
        "instrs": [[op, repr(st)[:160], list(args)]
                   for op, st, args in program.instrs],
        "n_leaves": program.n_leaves,
        "leaf_kinds": "".join(program.leaf_kinds),
        "out_slots": list(program.out_slots),
        "donate": list(donate_key),
        "owners": _leaf_owner_counts(leaves),
        "x64": bool(jax.config.jax_enable_x64),
    }
    if fingerprint is not None:
        ev["fingerprint"] = fingerprint
    if compile_class is not None:
        ev["compile_class"] = list(compile_class)
    return ev


def _verify_if_enabled(program: _Program, leaves, exprs, donate_key: tuple,
                       span: dict, label: str, memo_plan=None,
                       class_plan=None) -> bool:
    """RAMBA_VERIFY hook: statically verify the program about to execute
    (see ramba_tpu.analyze).  Strict mode raises ProgramVerificationError
    on error findings — before ``_get_compiled`` is ever reached, so a
    malformed program never compiles, let alone runs.  Non-strict mode
    returns True instead, routing the flush down the degradation ladder
    (skip the fused rung: no monolithic compile, no leaf donation).
    Zero-cost when RAMBA_VERIFY is unset."""
    if not os.environ.get("RAMBA_VERIFY"):
        return False
    from ramba_tpu.analyze import verifier as _verifier

    vmode = _verifier.mode()
    if vmode == "off":
        return False
    findings = _verifier.verify_flush(program, leaves, exprs, donate_key,
                                      label=label, memo_plan=memo_plan,
                                      class_plan=class_plan)
    if findings:
        counts: dict = {}
        for f in findings:
            counts[f.severity] = counts.get(f.severity, 0) + 1
        span["findings"] = counts
    errors = [f for f in findings if f.severity == "error"]
    if not errors:
        return False
    if vmode == "strict":
        from ramba_tpu.analyze.findings import ProgramVerificationError

        raise ProgramVerificationError(errors)
    span["verify_routed"] = True
    return True


# ---------------------------------------------------------------------------
# the staged flush: prepare (cheap, caller thread) -> dispatch (execution)
# ---------------------------------------------------------------------------


class _FlushWork:
    """Everything one flush needs between prepare and dispatch — the unit
    the async pipeline queues.  Holds STRONG references to the roots (a
    detached root left the pending registry at collect time and must not
    be collected before write-back) and to the leaf values (pinned +
    flight-counted until dispatch releases them)."""

    __slots__ = ("stream", "roots", "root_exprs", "extra_n", "program",
                 "leaves", "vexprs", "leaf_vals", "donate_key", "span",
                 "label", "fingerprint", "skip_fused", "pins", "flight",
                 "t_flush", "detached", "enqueued_at", "memo_plan",
                 "memo_hit", "deadline", "is_abandoned", "class_plan",
                 "plan_cert", "plan_cache")

    def __init__(self, stream, roots, extra_n):
        self.stream = stream
        self.roots = roots
        self.root_exprs = [a._expr for a in roots]
        self.extra_n = extra_n
        self.program = None
        self.leaves = None
        self.vexprs = None
        self.leaf_vals = None
        self.donate_key = ()
        self.span = None
        self.label = "?"
        self.fingerprint = None
        self.skip_fused = False
        self.pins = ()
        self.flight = ()
        self.t_flush = 0.0
        self.detached = False
        self.enqueued_at = None
        # result memoization (core/memo.py): the certified plan, and the
        # cached output values when a lookup already hit
        self.memo_plan = None
        self.memo_hit = None
        # overload plane (serve/overload.py): the request's time budget,
        # and a pipeline-installed probe for ticket abandonment (late
        # completions discard instead of writing back)
        self.deadline = None
        self.is_abandoned = None
        # shape-bucket compile class (compile/classes.py); None = exact
        self.class_plan = None
        # plan-certificate cache (core/plancache.py): the certificate
        # this flush ran under (redeemed or newly minted), and the hit
        # tier ("hit" | "shared") — None on the miss/disabled path
        self.plan_cert = None
        self.plan_cache = None


def _gather_leaf_vals(leaves):
    """Resolve leaf values for execution (restoring memory-governor
    spills).  Returns ``(leaf_vals, leaf_bytes)``."""
    leaf_vals = []
    leaf_bytes = 0
    for leaf in leaves:
        if isinstance(leaf, Const):
            v = leaf.value
            if isinstance(v, _SpilledArray):
                # Evicted by the memory governor; bring it home before the
                # donation decision so the census sees the device buffer.
                v = _memory.restore(leaf)
            leaf_vals.append(v)
            leaf_bytes += _nbytes(v)
        else:
            leaf_vals.append(leaf.value)
    return leaf_vals, leaf_bytes


def _donation_mask(leaves, leaf_vals) -> tuple:
    """Donate-eligible leaf slots: big enough, owned by no live ndarray,
    and held by no OTHER in-flight flush (each flush's own flight pin
    counts one, so a single stream behaves exactly as before)."""
    donate = []
    with _census_lock:
        owners = [
            _const_owners.get(id(v), 0) if isinstance(leaf, Const) else 1
            for leaf, v in zip(leaves, leaf_vals)
        ]
    with _flight_lock:
        flights = [_inflight_leaves.get(id(v), 0) for v in leaf_vals]
    for i, (leaf, v) in enumerate(zip(leaves, leaf_vals)):
        if not isinstance(leaf, Const):
            continue
        if (
            _nbytes(v) >= DONATE_MIN_BYTES
            and owners[i] == 0
            and flights[i] <= 1
        ):
            donate.append(i)
    return tuple(donate)


def _quarantine(work: "_FlushWork", e: Exception) -> None:
    """Quarantine: every rung of the ladder failed (or the error was
    fatal).  The roots of THIS program must leave the pending registry,
    or the one broken expression re-enters — and re-fails — every
    subsequent flush of its stream, cascading one error into unbounded
    collateral failures.  The arrays keep their lazy graphs; a later
    materialization re-attempts each one alone (ndarray._value), so
    innocent co-pending arrays still produce their values and only the
    truly broken graph re-raises.  Per-stream: other streams' pending
    work is untouched."""
    for arr in work.roots:
        unregister_pending(arr)  # no-op when the work was detached
    n = len(work.roots)
    work.stream.stats["quarantined"] += n
    _registry.inc("resilience.flush_quarantined", n)
    ev = {
        "type": "flush_error", "label": work.label,
        "quarantined": n,
        "error": f"{type(e).__name__}: {e}"[:300],
    }
    if work.stream.tenant is not None:
        ev["tenant"] = work.stream.tenant
    # Under coherent recovery the error that reached quarantine was
    # fleet-agreed (ladder terminal decisions are agreement rounds), so
    # every rank quarantines the same program on the same epoch; stamping
    # the epoch lets merge-ranks pair the quarantines without guessing.
    epoch = _coherence.last_epoch("flush:rung")
    if epoch:
        ev["coherence_epoch"] = epoch
    _events.emit(ev)


def _release(work: "_FlushWork") -> None:
    _memory.ledger.unpin(work.pins)
    work.pins = ()
    _flight_decref(work.flight)
    work.flight = ()


def _flush_discard(work: "_FlushWork") -> None:
    """Soft-discard prepared work that was shed before dispatch
    (overload plane: queue-full unwind, abandoned-ticket drop, shed
    verdict).  Unlike :func:`_quarantine` this is not a failure — no
    flush_error event, no quarantine counters: the roots just leave the
    pending set with their lazy graphs intact, so each array self-heals
    on next touch via the per-array re-flush path.  Pins and flight
    refs are released so the leaves stay donate-eligible."""
    for arr in work.roots:
        unregister_pending(arr)  # no-op when the work was detached
    _release(work)


def _flush_prepare(stream: FlushStream, roots: list,
                   extra: Sequence[Expr] = (), *,
                   detached: bool = False) -> Optional["_FlushWork"]:
    """Stage 1 of a flush: rewrite + linearize, open the span, gather
    leaf values, take the donation census, emit the program event, pin
    the leaves, and run the RAMBA_VERIFY verifier.  Cheap relative to
    execution — this is the part an async enqueue runs on the caller
    thread.  Returns None when there is nothing to run.

    ``detached`` marks work whose roots already left the pending registry
    (async enqueue): any failure here must quarantine them, or they would
    silently vanish.  On the synchronous path only a verifier rejection
    quarantines (matching the historical single-stream flush)."""
    exprs = [a._expr for a in roots] + list(extra)
    if not exprs:
        return None
    work = _FlushWork(stream, roots, len(exprs) - len(roots))
    work.detached = detached
    work.t_flush = time.perf_counter()
    try:
        rw_before = None
        if common.rewrite_enabled:
            from ramba_tpu.core.rewrite import stats as _rw_stats

            rw_before = dict(_rw_stats)
        program, leaves, vexprs = _prepare_program(exprs)
        linearize_s = time.perf_counter() - work.t_flush
        rewrite_fires = {}
        if rw_before is not None:
            from ramba_tpu.core.rewrite import stats as _rw_stats

            rewrite_fires = {
                k: v - rw_before.get(k, 0)
                for k, v in _rw_stats.items()
                if v != rw_before.get(k, 0)
            }
        label = _program_label(program)
        span = {
            "type": "flush",
            "label": label,
            "instrs": len(program.instrs),
            "n_leaves": program.n_leaves,
            "n_roots": len(roots),
            "linearize_s": round(linearize_s, 6),
            "rewrite_fires": rewrite_fires,
            "calls": [],
            "stages": {},
        }
        if stream is not _default_stream:
            span["stream"] = stream.name
        if stream.tenant is not None:
            span["tenant"] = stream.tenant
        if stream.trace_id is not None:
            # the flush span gets its own span id and chains to the
            # session root; dispatch re-scopes to it so rung/stall/memory
            # events become its children
            span["trace_id"] = stream.trace_id
            span["span_id"] = _telemetry.mint_id()
            span["parent_span"] = stream.root_span
        work.program, work.leaves, work.vexprs = program, leaves, vexprs
        work.label, work.span = label, span

        leaf_vals, leaf_bytes = _gather_leaf_vals(leaves)
        work.leaf_vals = leaf_vals
        work.flight = _flight_incref(leaf_vals)
        donate_key = _donation_mask(leaves, leaf_vals)
        try:
            _faults.check("donate_census", donated=len(donate_key))
        except _faults.InjectedFault:
            # Deliberately corrupt the donate mask (ignore the alias
            # census) — the seeded violation the RAMBA_VERIFY
            # donation-hazard rule exists to catch.  Only reachable under
            # explicit fault injection.
            donate_key = tuple(
                i for i, leaf in enumerate(leaves) if isinstance(leaf, Const)
            )
        work.donate_key = donate_key
        span["donated"] = len(donate_key)
        span["leaf_bytes"] = leaf_bytes
        span["mem_live_bytes"] = _memory.ledger.live_bytes
        _profile.ensure_started()
        _telemetry.ensure_started()
        _fleet.ensure_started()
        # In-flight leaves are never spill candidates: admission-
        # triggered (or oom-triggered) eviction during THIS flush must
        # not pull a buffer the program is about to read.
        work.pins = _memory.ledger.pin_values(leaf_vals)
        # Everything above is graph capture and leaf plumbing — the
        # per-flush cost no cache can remove, paid identically whether
        # or not a certificate redeems.  Everything below is the
        # analysis pipeline, which a plan certificate skips; the stage
        # ledger splits the two ("trace" vs "prepare") so the waterfall
        # shows exactly what the fast path saves.
        t_analysis = time.perf_counter()
        # Plan-certificate fast path (RAMBA_PLANCERT; analyze/plancert.py
        # + core/plancache.py): a repeat flush whose certificate's
        # invalidation signature still validates skips the entire
        # analysis pipeline below — class proof, fingerprint derivation,
        # memo certification, and the verifier — behind one
        # version-vector comparison.  A plan:stale-forged "hit" is held
        # aside instead of redeemed: strict mode rejects it below with
        # the same quarantine discipline as a verifier error, warn mode
        # silently re-analyzes.
        plan_hit = None
        stale_hit = None
        if _plancache.enabled():
            try:
                hit = _plancache.lookup(program, leaf_vals, donate_key,
                                        label)
            except Exception:
                hit = None
            if hit is not None and hit.forged:
                if _plancache.strict():
                    stale_hit = hit
            elif hit is not None:
                plan_hit = hit
        if plan_hit is not None:
            # Redeem: every verdict below is adopted from the certificate.
            cert = plan_hit.cert
            class_plan = _plancache.class_plan_from(cert)
            work.class_plan = class_plan
            if class_plan is not None:
                span["compile_class"] = list(class_plan.token)
                span["pad_waste_bytes"] = class_plan.pad_waste_bytes
            work.fingerprint = cert.fingerprint or _ledger.fingerprint(
                _cache_key(program, donate_key,
                           class_plan.token
                           if class_plan is not None else None))
            if _classes.enabled():
                _classes.note_decision(work.fingerprint, class_plan)
            if class_plan is not None:
                _ledger.record_class(work.fingerprint, class_plan.token,
                                     class_plan.pad_waste_bytes,
                                     label=label)
            if _events.trace_enabled():
                pev = _program_event(
                    program, leaves, donate_key, label,
                    fingerprint=work.fingerprint,
                    compile_class=(class_plan.token
                                   if class_plan is not None else None))
                pev["plan_cache"] = plan_hit.tier
                if cert.chash is not None:
                    pev["chash"] = cert.chash
                if "trace_id" in span:
                    pev.setdefault("trace_id", span["trace_id"])
                    pev.setdefault("parent_span", span["span_id"])
                _events.emit(pev)
            # The memo plan is rebuilt, not re-certified: only the input
            # version tokens and shared content key are live state.
            work.memo_plan = None
            if cert.memo_ok:
                try:
                    work.memo_plan = _memo.plan_from_cert(
                        cert.chash, cert.canon_form, cert.leaf_order,
                        cert.effects, leaves, leaf_vals)
                except Exception:
                    work.memo_plan = None
            work.plan_cert = cert
            work.plan_cache = plan_hit.tier
            span["plan_cache"] = plan_hit.tier
            if cert.chash is not None:
                span["chash"] = cert.chash
            if cert.finding_counts:
                # the certified verdict's findings, re-stamped so the
                # span is indistinguishable from a fresh analysis
                span["findings"] = dict(cert.finding_counts)
        elif stale_hit is None:
            # Compile-class planning (RAMBA_COMPILE_CLASSES): bucket the
            # leading dim so shape-varying traffic shares executables.
            # The decision is a pure function of (program, shapes,
            # policy), so SPMD ranks agree by construction.  The
            # compile:bucket fault site forges a plan that skips the
            # op-safety proof — the seeded violation the compile-class
            # verify rule exists to catch.
            class_plan = None
            if _classes.enabled():
                try:
                    class_plan = _classes.plan_for(program, leaf_vals)
                except Exception:
                    class_plan = None
            try:
                _faults.check("compile:bucket", label=label)
            except _faults.InjectedFault:
                forged = _classes.forced_plan(program, leaf_vals)
                if forged is not None:
                    class_plan = forged
            work.class_plan = class_plan
            if class_plan is not None:
                span["compile_class"] = list(class_plan.token)
                span["pad_waste_bytes"] = class_plan.pad_waste_bytes
            # The fingerprint folds in the class token: each bucket is
            # its own executable, its own ledger row, its own persist
            # entry.
            work.fingerprint = _ledger.fingerprint(_cache_key(
                program, donate_key,
                class_plan.token if class_plan is not None else None))
            if _classes.enabled():
                _classes.note_decision(work.fingerprint, class_plan)
            if class_plan is not None:
                _ledger.record_class(work.fingerprint, class_plan.token,
                                     class_plan.pad_waste_bytes,
                                     label=label)
            if _events.trace_enabled():
                pev = _program_event(
                    program, leaves, donate_key, label,
                    fingerprint=work.fingerprint,
                    compile_class=(class_plan.token
                                   if class_plan is not None else None))
                if "trace_id" in span:
                    pev.setdefault("trace_id", span["trace_id"])
                    pev.setdefault("parent_span", span["span_id"])
                _events.emit(pev)
            # Result-memoization certification (RAMBA_MEMO; None when
            # off or the program is provably uncacheable).  The plan is
            # built before the verifier runs so the memo-safety rule
            # audits it.
            try:
                work.memo_plan = _memo.plan_for(program, donate_key,
                                                leaves, leaf_vals)
            except Exception:
                work.memo_plan = None
    except Exception as e:
        if detached:
            _quarantine(work, e)
        _release(work)
        raise
    if stale_hit is not None:
        # strict mode: a certificate that fails signature validation is
        # rejected exactly like a verifier error — quarantine + raise
        # before anything compiles.
        from ramba_tpu.analyze.findings import ProgramVerificationError

        err = ProgramVerificationError(
            _plancache.stale_findings(stale_hit, label))
        _quarantine(work, err)
        _release(work)
        raise err
    if plan_hit is None:
        t_verify = time.perf_counter()
        try:
            work.skip_fused = _verify_if_enabled(
                program, leaves, vexprs, donate_key, span, label,
                memo_plan=work.memo_plan, class_plan=work.class_plan,
            )
        except Exception as e:
            _quarantine(work, e)
            _release(work)
            raise
        if os.environ.get("RAMBA_VERIFY"):  # keep the stage ledger sparse
            _attrib.add_stage(span, "verify",
                              time.perf_counter() - t_verify)
        if work.skip_fused:
            # a verifier-distrusted flush must not populate (or consult)
            # the result cache: whatever routed it down the ladder may be
            # the very defect the memo-safety rule flagged.  The class
            # plan is dropped for the same reason — the ladder's fallback
            # rungs run exact shapes, so a flagged bucket claim never
            # touches data.  It must not certify either, for the same
            # reason.
            work.memo_plan = None
            work.class_plan = None
        elif _plancache.enabled():
            # Miss path completed a full, verifier-clean analysis:
            # snapshot it as a certificate for the next repeat.
            try:
                work.plan_cert = _plancache.certify(work)
            except Exception:
                work.plan_cert = None
    if work.memo_plan is not None:
        try:
            work.memo_hit = _memo.lookup(work.memo_plan)
        except Exception:
            work.memo_hit = None
    # Mint the request deadline (serve/overload.py) at prepare time so
    # the budget clock covers queueing.  Lazy import (serve imports this
    # module); gated so the common no-deadline path never pays it.
    if stream.deadline_ms is not None or os.environ.get("RAMBA_DEADLINE_MS"):
        from ramba_tpu.serve import overload as _overload

        work.deadline = _overload.mint_deadline(stream.deadline_ms)
        if work.deadline is not None:
            span["deadline_ms"] = work.deadline.budget_ms
    # The kernel fingerprint rides the span so offline tooling and the
    # incident explainer can join a flush back to its per-fingerprint
    # baselines without the live ledger.
    if work.fingerprint is not None:
        span["fingerprint"] = work.fingerprint
    # Caller-thread attribution: "trace" is linearize + fuse + leaf
    # gather + donation census (unavoidable per flush); "prepare" is the
    # analysis pipeline from there on — class/memo/plan certification or
    # the certificate redemption — minus the verifier, which has its own
    # stage.
    _attrib.add_stage(span, "trace", t_analysis - work.t_flush)
    _attrib.add_stage(
        span, "prepare",
        (time.perf_counter() - t_analysis)
        - span["stages"].get("verify", 0.0))
    return work


def _revalidate_donation(work: "_FlushWork") -> None:
    """Async work dispatches arbitrarily later than it was prepared: a
    buffer that looked donate-safe at enqueue may since have gained a
    live owner (the user materialized an alias) or another in-flight
    program (a different stream enqueued a graph sharing the leaf).
    Donation may only SHRINK here — a smaller mask cannot introduce the
    hazards the enqueue-time verifier checked for."""
    if not work.donate_key:
        return
    fresh = set(_donation_mask(work.leaves, work.leaf_vals))
    kept = tuple(i for i in work.donate_key if i in fresh)
    if kept != work.donate_key:
        work.span["donate_revoked"] = len(work.donate_key) - len(kept)
        work.donate_key = kept
        work.span["donated"] = len(kept)
        work.fingerprint = _ledger.fingerprint(_cache_key(
            work.program, kept,
            work.class_plan.token if work.class_plan is not None else None))
        work.span["fingerprint"] = work.fingerprint


def _finish_memo_hit(work: "_FlushWork") -> list:
    """Complete a flush whose outputs the result cache already holds:
    no admission, no compile, no execution — just write-back and span
    bookkeeping.  The span carries ``cache="memo"`` so trace tooling can
    tell a memo hit from a compile-cache hit, and the slow-flush ledger
    is deliberately NOT fed (a near-zero memo wall would poison the
    program's rolling latency history)."""
    stream, span, program = work.stream, work.span, work.program
    outs = work.memo_hit
    work.memo_hit = None
    _release(work)
    with _stats_lock:
        stats["flushes"] += 1
        stats["nodes_flushed"] += len(program.instrs)
    stream.stats["flushes"] += 1
    stream.stats["nodes_flushed"] += len(program.instrs)
    _registry.inc("fuser.flushes")
    _registry.inc("fuser.nodes_flushed", len(program.instrs))
    if stream.tenant is not None:
        _registry.inc(f"serve.tenant.{stream.tenant}.flushes")
        _registry.inc(f"serve.tenant.{stream.tenant}.nodes",
                      len(program.instrs))
    work.leaf_vals = None
    for arr, expr, val in zip(work.roots, work.root_exprs, outs):
        if arr._expr is expr:
            arr._set_expr(Const(val))
    span["segments"] = 0
    span["compile_s"] = 0.0
    span["execute_s"] = 0.0
    span["cache"] = "memo"
    span["memo_hit"] = True
    span["out_bytes"] = sum(_nbytes(v) for v in outs)
    span["wall_s"] = round(time.perf_counter() - work.t_flush, 6)
    _attrib.finalize_span(span, fp=work.fingerprint)
    _events.emit(span)
    _slo.observe_span(span)
    _elastic.note_progress("flush")
    return list(outs[len(work.roots):])


def _flush_dispatch(work: "_FlushWork", *, coalesced: int = 0) -> list:
    """Stage 2 of a flush: admission control, ladder execution, Const
    write-back, span finalization.  Returns the values of the work's
    ``extra`` expressions.  Runs on the caller thread (sync path) or the
    pipeline's compile worker (async path).

    The whole stage runs inside the flush span's trace scope, so every
    event emitted underneath — degrade rungs, memory admissions/rejects,
    watchdog stalls, barrier spans, slow_flush verdicts — is auto-stamped
    as a child of this flush (observe/telemetry.py)."""
    span = work.span
    with _telemetry.span_scope(span.get("trace_id"), span.get("span_id")):
        return _flush_dispatch_traced(work, coalesced=coalesced)


def _flush_dispatch_traced(work: "_FlushWork", *, coalesced: int = 0) -> list:
    stream, span, program = work.stream, work.span, work.program
    roots, label = work.roots, work.label
    if work.enqueued_at is not None:
        queue_s = time.perf_counter() - work.enqueued_at
        span["queue_s"] = round(queue_s, 6)
        # queue_s spans submit -> this dispatch; the pipeline already
        # billed the group-pop -> this-ticket slice as coalesce
        _attrib.add_stage(
            span, "queue_wait",
            queue_s - span.get("stages", {}).get("coalesce", 0.0))
    if coalesced > 1:
        span["coalesced"] = coalesced
    # Overload shed verdict — before admission, compile, and execution,
    # so a shed costs microseconds.  Epoch-agreed across ranks when
    # coherence is engaged (all ranks shed the identical request set).
    # A shed is a soft discard, not a failure: no quarantine, no
    # flush_error — the roots keep their graphs and self-heal on touch.
    if work.deadline is not None or work.enqueued_at is not None:
        from ramba_tpu.serve import overload as _overload

        try:
            _overload.dispatch_verdict(
                deadline=work.deadline, enqueued_at=work.enqueued_at,
                tenant=stream.tenant,
                priority=getattr(stream, "priority", False), label=label)
        except _overload.OverloadError:
            _flush_discard(work)
            raise
    if (work.memo_hit is None and work.memo_plan is not None
            and work.enqueued_at is not None):
        # Dispatch-time re-lookup (queued work only — the sync path just
        # looked up in prepare): a prepare-time miss may have become a
        # hit while this work sat queued (an earlier ticket with the same
        # canonical key executed and inserted) — this is what turns
        # serving-batch duplicates into CSE merges.
        try:
            work.memo_hit = _memo.lookup(work.memo_plan)
        except Exception:
            pass
    if work.memo_hit is not None:
        return _finish_memo_hit(work)
    tags = {"tenant": stream.tenant} if stream.tenant is not None else None
    leaf_vals = work.leaf_vals
    try:
        if work.detached:
            _revalidate_donation(work)
        t_admit = time.perf_counter()
        route_chunked = _memory.admit(program, leaf_vals, work.donate_key,
                                      span, tenant=stream.tenant,
                                      quota=stream.quota_bytes)
        _attrib.add_stage(span, "admit", time.perf_counter() - t_admit)
        # Hedged dispatch: when RAMBA_HEDGE_FACTOR is set and the program
        # is effect-certified pure with no donation, a dispatch running
        # past factor x its rolling p95 races a second attempt; the first
        # result wins and the loser is cancel-flagged.  Gated on the env
        # var so the common path never imports the overload plane here.
        hedge_s = None
        if os.environ.get("RAMBA_HEDGE_FACTOR") and not work.skip_fused:
            from ramba_tpu.serve import overload as _overload

            hedge_s = _overload.hedge_threshold(label, program,
                                                work.donate_key)
        _stages_pre = sum(span["stages"].get(k, 0.0) for k in
                          ("compile", "dispatch", "device_execute"))
        t_ladder = time.perf_counter()
        with _profile.flush_annotation("ramba_flush:" + label,
                                       trace_id=span.get("trace_id")):
            with warnings.catch_warnings():
                warnings.filterwarnings("ignore", message=".*[Dd]onat.*")
                if hedge_s is not None:
                    outs, rung = _overload.run_hedged(
                        lambda hspan: _execute_resilient(
                            program, leaf_vals, work.donate_key, hspan,
                            skip_fused=work.skip_fused,
                            route_chunked=route_chunked, tags=tags,
                            deadline=work.deadline,
                            class_plan=work.class_plan),
                        hedge_s, span=span, label=label,
                        tenant=stream.tenant)
                else:
                    outs, rung = _execute_resilient(
                        program, leaf_vals, work.donate_key, span,
                        skip_fused=work.skip_fused,
                        route_chunked=route_chunked, tags=tags,
                        deadline=work.deadline,
                        class_plan=work.class_plan)
    except Exception as e:
        _quarantine(work, e)
        raise
    finally:
        _release(work)
    t_writeback = time.perf_counter()
    # Host-side ladder residual — jit-cache lookup, guard/retry control,
    # donation prep, pin release — is dispatch-path overhead: bill the
    # slice of the ladder window the per-call stamps did not cover.
    _attrib.add_stage(
        span, "dispatch",
        (t_writeback - t_ladder)
        - (sum(span["stages"].get(k, 0.0) for k in
               ("compile", "dispatch", "device_execute")) - _stages_pre))
    if rung != "fused":
        span["degraded"] = rung
    with _stats_lock:
        stats["flushes"] += 1
        stats["nodes_flushed"] += len(program.instrs)
    stream.stats["flushes"] += 1
    stream.stats["nodes_flushed"] += len(program.instrs)
    _registry.inc("fuser.flushes")
    _registry.inc("fuser.nodes_flushed", len(program.instrs))
    if stream.tenant is not None:
        _registry.inc(f"serve.tenant.{stream.tenant}.flushes")
        _registry.inc(f"serve.tenant.{stream.tenant}.nodes",
                      len(program.instrs))
    # Shadow recompute audit (RAMBA_AUDIT=<1-in-N>): re-execute a sample
    # of effect-certified pure, non-donating flushes on the eager rung
    # and compare byte identity — the tripwire for silent compute/memory
    # corruption.  The primary outs are ALWAYS what gets served (audit
    # on/off is byte-identical); a mismatch only suppresses the memo
    # insert and evicts, so poison never enters a cache.
    audit_mismatch = False
    if (work.memo_plan is not None and work.memo_plan.certified
            and not work.donate_key and rung == "fused"
            and not work.memo_hit and _integrity.audit_every() > 0):
        shadow_leaves = leaf_vals
        audit_mismatch = _integrity.shadow_audit(
            label, outs,
            lambda: _run_eager(program, shadow_leaves, None),
            plan=work.memo_plan, span=span)
    if work.memo_plan is not None and not audit_mismatch:
        try:
            _memo.insert(work.memo_plan, list(outs))
        except Exception:
            _registry.inc("memo.insert_failed")
    work.leaf_vals = None  # drop donated-buffer refs before write-back
    del leaf_vals
    if (work.is_abandoned is not None and work.is_abandoned()
            and not _coherence.engaged()):
        # The caller abandoned the ticket while this dispatch ran: a
        # late completion must not write results back into a stream
        # nobody is reading.  The arrays keep their lazy graphs and
        # self-heal on next touch.  Single-controller only — under SPMD
        # write-back skew would diverge the next traced program.
        _registry.inc("serve.abandoned_late")
    else:
        for arr, expr, val in zip(roots, work.root_exprs, outs):
            # Async only: skip write-back if the user re-assigned the
            # array's expression while this flush was in flight — their
            # newer graph wins (it still references this one's nodes and
            # will recompute).
            if arr._expr is expr:
                arr._set_expr(Const(val))
    calls = span["calls"]
    span["segments"] = len(calls) - 1 if len(calls) > 1 else 0
    span["compile_s"] = round(
        sum(c["seconds"] for c in calls if c["cache"] == "miss"), 6
    )
    span["execute_s"] = round(
        sum(c["seconds"] for c in calls if c["cache"] == "hit"), 6
    )
    span["cache"] = (
        "miss" if any(c["cache"] == "miss" for c in calls) else "hit"
    )
    span["out_bytes"] = sum(_nbytes(v) for v in outs)
    span["wall_s"] = round(time.perf_counter() - work.t_flush, 6)
    _attrib.add_stage(span, "write_back", time.perf_counter() - t_writeback)
    _attrib.finalize_span(span, fp=work.fingerprint)
    _events.emit(span)
    # Slow-flush sentinel: compares this flush against the program's own
    # rolling history and emits at most one slow_flush event (after the
    # span, so the trace reads cause-then-verdict).
    _ledger.observe_flush(span)
    _slo.observe_span(span)
    _elastic.note_progress("flush")
    return list(outs[len(roots):])


def flush(extra: Sequence[Expr] = ()) -> list:
    """Materialize every pending ndarray of the CURRENT stream (and
    ``extra`` expressions) in one fused jit call (or, above
    ``common.max_program_instrs`` instructions, a chain of bounded jit
    calls — see ``_run_segmented``).  Returns the values of ``extra`` in
    order."""
    return current_stream().flush(extra)


def flush_for(arr, extra: Sequence[Expr] = ()) -> list:
    """Flush the stream that owns ``arr``'s pending work (waiting out any
    in-flight async flushes of that stream first), regardless of which
    stream is current — materialization must chase the work to where it
    was built."""
    s = stream_of(arr)
    s.drain()
    return s.flush(extra)


def analyze_pending() -> Optional[dict]:
    """Compile (without executing) the program the next flush would run and
    return XLA's memory analysis — the rebuild's answer to the reference's
    CI memory-behavior tests, which assert that giant fused expressions fit
    in RAM only if no temporaries materialize
    (/root/reference/ramba/tests/test_distributed_array.py:100-108,193-199).
    The pending graph is left pending.  Returns None if nothing is pending.
    """
    roots = _pending_roots()
    exprs = [a._expr for a in roots]
    if not exprs:
        return None
    program, leaves, _vexprs = _prepare_program(exprs)
    avals = []
    for leaf in leaves:
        v = leaf.value
        if isinstance(v, (jax.Array, _SpilledArray)):
            # a spilled leaf carries its device sharding; analysis must
            # not force a restore (analyze_pending never executes)
            avals.append(
                jax.ShapeDtypeStruct(v.shape, v.dtype, sharding=v.sharding)
            )
        else:
            avals.append(jax.ShapeDtypeStruct(jax.numpy.asarray(v).shape,
                                              jax.numpy.asarray(v).dtype))
    out = {"instructions": len(program.instrs), "n_leaves": program.n_leaves}
    if (
        common.max_program_instrs
        and len(program.instrs) > common.max_program_instrs
    ):
        # The next flush will run segmented (_run_segmented), and compiling
        # the monolith here would hit the very superlinear-compile hazard
        # segmentation avoids — so analyze what will actually run: compile
        # each distinct segment (chains repeat one structure) and report the
        # PEAK per-segment sizes, chaining avals with jax.eval_shape.
        # Sharding on intermediates is dropped (eval_shape carries none);
        # GSPMD would propagate it, so temp sizes are an upper bound.
        vals_avals = dict(enumerate(avals))
        last_use = _last_use_map(program)
        # keyed on structure AND input avals: seg_prog.key deliberately
        # excludes shapes/dtypes, but memory numbers depend on them
        seen_keys = {}
        out["segments"] = 0
        peak = {name: 0 for name in (
            "temp_size_in_bytes", "argument_size_in_bytes",
            "output_size_in_bytes", "generated_code_size_in_bytes")}
        for seg_prog, in_slots, out_here, _top in _iter_segments(
            program, last_use
        ):
            seg_avals = [vals_avals[s] for s in in_slots]
            ak = (seg_prog.key,
                  tuple((a.shape, str(a.dtype)) for a in seg_avals))
            ma = seen_keys.get(ak)
            if ma is None:
                compiled = (
                    jax.jit(_build_callable(seg_prog))
                    .lower(*seg_avals)
                    .compile()
                )
                ma = compiled.memory_analysis()
                seen_keys[ak] = ma
            for name in peak:
                v = getattr(ma, name, None)
                if v is not None:
                    peak[name] = max(peak[name], v)
            out_avals = jax.eval_shape(
                _build_callable(seg_prog), *seg_avals
            )
            for s, av in zip(out_here, out_avals):
                vals_avals[s] = av
            out["segments"] += 1
        out.update(peak)
        return out
    compiled = jax.jit(_build_callable(program)).lower(*avals).compile()
    ma = compiled.memory_analysis()
    for name in ("temp_size_in_bytes", "argument_size_in_bytes",
                 "output_size_in_bytes", "generated_code_size_in_bytes"):
        out[name] = getattr(ma, name, None)
    return out


def sync() -> None:
    """Flush EVERY stream, wait out in-flight async work, and block until
    device completion (the reference's ``ramba.sync`` barriers on a
    remote ``nop``, ramba.py:9843-9849)."""
    waiters = _pending_arrays()
    for s in all_streams():
        s.flush()
    for s in all_streams():
        s.drain()
    jax.block_until_ready(
        [a._expr.value for a in waiters
         if isinstance(a._expr, Const)
         and isinstance(a._expr.value, jax.Array)]  # spilled: nothing in flight
    )
    # a sync is a "the world is settled" point: the buffered trace
    # writer's pending lines belong on disk too
    _events.sync()


def evaluate(expr: Expr):
    """Evaluate one expression (flushing the current stream's pending work
    alongside it)."""
    if isinstance(expr, Const):
        return leaf_value(expr)
    return flush(extra=[expr])[0]
