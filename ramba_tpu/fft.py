"""``ramba_tpu.fft`` — the numpy.fft namespace over distributed arrays.

Like ``ramba_tpu.linalg``, this goes beyond the reference (which exposes
no fft submodule): every transform lowers lazily through ``jax.numpy.fft``
so it fuses into the surrounding flush and runs on device.  Frequency
helpers (fftfreq/rfftfreq) are creation ops; fftshift/ifftshift are lazy
index shuffles.
"""

from __future__ import annotations

import numpy as np

from ramba_tpu.ops.extras import _lazy


def _fft1(name, a, n=None, axis=-1, norm=None):
    kw = {"axis": int(axis)}
    if n is not None:
        kw["n"] = int(n)
    if norm is not None:
        kw["norm"] = norm
    return _lazy(f"fft.{name}", a, **kw)


def fft(a, n=None, axis=-1, norm=None):
    return _fft1("fft", a, n, axis, norm)


def ifft(a, n=None, axis=-1, norm=None):
    return _fft1("ifft", a, n, axis, norm)


def rfft(a, n=None, axis=-1, norm=None):
    return _fft1("rfft", a, n, axis, norm)


def irfft(a, n=None, axis=-1, norm=None):
    return _fft1("irfft", a, n, axis, norm)


def hfft(a, n=None, axis=-1, norm=None):
    return _fft1("hfft", a, n, axis, norm)


def ihfft(a, n=None, axis=-1, norm=None):
    return _fft1("ihfft", a, n, axis, norm)


def _fftn(name, a, s=None, axes=None, norm=None):
    kw = {}
    if s is not None:
        kw["s"] = tuple(int(x) for x in s)
    if axes is not None:
        kw["axes"] = tuple(int(x) for x in axes)
    if norm is not None:
        kw["norm"] = norm
    return _lazy(f"fft.{name}", a, **kw)


def fft2(a, s=None, axes=(-2, -1), norm=None):
    return _fftn("fft2", a, s, axes, norm)


def ifft2(a, s=None, axes=(-2, -1), norm=None):
    return _fftn("ifft2", a, s, axes, norm)


def rfft2(a, s=None, axes=(-2, -1), norm=None):
    return _fftn("rfft2", a, s, axes, norm)


def irfft2(a, s=None, axes=(-2, -1), norm=None):
    return _fftn("irfft2", a, s, axes, norm)


def fftn(a, s=None, axes=None, norm=None):
    return _fftn("fftn", a, s, axes, norm)


def ifftn(a, s=None, axes=None, norm=None):
    return _fftn("ifftn", a, s, axes, norm)


def rfftn(a, s=None, axes=None, norm=None):
    return _fftn("rfftn", a, s, axes, norm)


def irfftn(a, s=None, axes=None, norm=None):
    return _fftn("irfftn", a, s, axes, norm)


def _axes_kw(axes):
    from ramba_tpu.ops.extras import _axis_arg

    return {} if axes is None else {"axes": _axis_arg(axes)}


def fftshift(x, axes=None):
    return _lazy("fft.fftshift", x, **_axes_kw(axes))


def ifftshift(x, axes=None):
    return _lazy("fft.ifftshift", x, **_axes_kw(axes))


def fftfreq(n, d=1.0):
    from ramba_tpu.ops.creation import fromarray

    return fromarray(np.fft.fftfreq(int(n), d=float(d)))


def rfftfreq(n, d=1.0):
    from ramba_tpu.ops.creation import fromarray

    return fromarray(np.fft.rfftfreq(int(n), d=float(d)))
