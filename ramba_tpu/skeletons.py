"""Algorithmic skeletons: smap / sreduce / sstencil / scumulative / spmd.

Reference: /root/reference/docs/index.md:83-267 and the driver/worker pairs at
ramba.py:9863-10180 (smap_internal, sreduce_internal, sstencil, scumulative,
spmd) with worker methods at ramba.py:2203-2491,3315-3491.

TPU-native design:

* ``smap``/``sreduce`` — the reference string-generates per-element Numba
  kernels (get_smap_fill, ramba.py:1600-1694).  Here the user function is
  jax-traceable and vectorized into the lazy graph, so it fuses with
  surrounding ops in the same flush.
* ``sstencil`` — the reference pads shards, exchanges halos point-to-point
  (LocalNdarray.getborder, ramba.py:1260-1322) and compiles a per-worker
  numba.stencil with an asymmetric neighborhood (ramba.py:3339-3358).  Here
  relative-offset accesses are discovered by probing the kernel and lowered
  to shifted-slice arithmetic; XLA GSPMD turns the shifted reads into halo
  collective-permutes over ICI automatically.
* ``scumulative`` — the reference runs a local scan then a sequential
  worker-to-worker carry chain (ramba.py:3378-3437).  Here blocks scan in
  parallel (lax.scan under vmap) and the carry fix-up is unrolled over
  blocks inside the same compiled program.
* ``spmd`` — the reference drops to raw per-worker execution
  (ramba.py:3477-3491).  Here it is a ``shard_map`` over the mesh; local
  shards arrive as jax arrays wrapped in a LocalView that supports
  ``get_local()`` (read) and ``set_local()`` (functional write-back, the
  TPU-native replacement for in-place shard mutation).
"""

from __future__ import annotations

import threading
import warnings
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ramba_tpu import common
from ramba_tpu.core.expr import Const, Node, defop
from ramba_tpu.core.fuser import sync as _sync
from ramba_tpu.core.ndarray import ndarray
from ramba_tpu.observe import registry as _registry
from ramba_tpu.ops.creation import asarray
from ramba_tpu.parallel import mesh as _mesh
from ramba_tpu.resilience import memory as _gov_memory
from ramba_tpu.utils import compat as _compat

# ---------------------------------------------------------------------------
# smap / smap_index
# ---------------------------------------------------------------------------


class KernelTraceError(RuntimeError):
    """A user kernel did something jax cannot trace (data-dependent Python
    branching / host conversion).  smap/smap_index catch this and fall back
    to host evaluation; other skeletons surface it loudly — silent wrong
    answers are never an option (round-3 verdict weak #2)."""


class KernelBranchError(KernelTraceError):
    """Specifically a data-dependent ``if`` — the recoverable case: the
    two-sided branch trace can usually lower it to ``jnp.where``."""


_BRANCH_MSG = (
    "kernel has data-dependent control flow jax cannot compile and the "
    "two-sided branch trace cannot express (simple `if x > 0:` branches "
    "are auto-lowered to where(); this one is not — e.g. a data-dependent "
    "loop count, float()/int() conversion feeding control flow, or too "
    "many branch paths). Rewrite with `np.where`/`jnp.where`/`lax.cond`, "
    "or accept the slow host-evaluation fallback where the skeleton "
    "provides one (smap/smap_index). The reference compiles such kernels "
    "with Numba on CPU (ramba.py:1600-1694)."
)


# --- two-sided branch tracing (round-4 verdict #6) --------------------------
# A kernel that branches on data (`if x > 0:`) is re-executed once per
# reachable branch path with forced True/False decisions; the recorded
# branch conditions then combine the per-path results with nested
# ``jnp.where`` — per-element semantics, exactly what the reference's
# Numba-compiled per-element kernels give (ramba.py:1600-1694), but on
# device.  Caveats (documented in docs/index.md): BOTH sides of every
# branch execute (side effects fire on every path; untaken-branch math may
# produce inf/nan that the `where` then discards), results promote to a
# common dtype, and the kernel must be deterministic.  Data-dependent LOOP
# counts are not expressible this way — the depth cap below turns them into
# a KernelTraceError, and smap's host fallback takes over.

_MAX_BRANCH_DEPTH = 16
_MAX_BRANCH_PATHS = 64

_active_decider = None


class _Decider:
    """One kernel execution's branch decisions: replays ``forced`` then
    defaults to True, recording every decision and its traced condition."""

    __slots__ = ("forced", "decisions", "conds")

    def __init__(self, forced):
        self.forced = tuple(forced)
        self.decisions = []
        self.conds = []

    def decide(self, cond):
        i = len(self.decisions)
        if i >= _MAX_BRANCH_DEPTH:
            raise KernelTraceError(
                "kernel exceeded the branch-enumeration depth limit "
                f"({_MAX_BRANCH_DEPTH}); a data-dependent loop cannot be "
                "lowered to where(). " + _BRANCH_MSG
            )
        d = self.forced[i] if i < len(self.forced) else True
        self.decisions.append(d)
        self.conds.append(cond)
        return d


def _explore_branches(run):
    """Enumerate every reachable branch path of ``run`` by re-executing it
    under forced decisions.  Returns [(path, conds, result), ...] leaves."""
    global _active_decider
    leaves = []
    pending = [()]
    while pending:
        if len(leaves) >= _MAX_BRANCH_PATHS:
            raise KernelTraceError(
                f"kernel has over {_MAX_BRANCH_PATHS} branch paths. "
                + _BRANCH_MSG
            )
        prefix = pending.pop()
        dec = _Decider(prefix)
        prev = _active_decider
        _active_decider = dec
        try:
            out = run()
        finally:
            _active_decider = prev
        path = tuple(dec.decisions)
        leaves.append((path, dec.conds, out))
        for d in range(len(prefix), len(path)):
            pending.append(path[:d] + (False,))
    return leaves


def _combine_branches(leaves):
    """Fold branch-path results into one value with nested jnp.where over
    the recorded conditions (scalar conds inside vectorize; array conds in
    stencil bodies — both mean per-element selection)."""
    exact = {path: out for path, _c, out in leaves}
    cond_at = {}
    for path, conds, _o in leaves:
        for d in range(len(path)):
            cond_at.setdefault(path[:d], conds[d])

    def build(prefix):
        if prefix in exact:
            return _unwrap(exact[prefix])
        return jnp.where(
            _unwrap(cond_at[prefix]),
            build(prefix + (True,)),
            build(prefix + (False,)),
        )

    return build(())


class _KVal:
    """Kernel-value proxy: lets user kernels written against *NumPy* (the
    reference compiles them with Numba, so ``np.maximum(x, y)`` is idiomatic
    there) trace under jax.  NumPy ufuncs dispatch here via __array_ufunc__
    and are rerouted to jax.numpy; arithmetic operators chain through."""

    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v

    def __bool__(self):
        if _active_decider is not None:
            return _active_decider.decide(self.v)
        raise KernelBranchError(_BRANCH_MSG)

    def __float__(self):
        raise KernelTraceError(
            "kernel converts a traced value to a Python float; " + _BRANCH_MSG
        )

    def __int__(self):
        raise KernelTraceError(
            "kernel converts a traced value to a Python int; " + _BRANCH_MSG
        )

    __index__ = __int__

    def __array_ufunc__(self, ufunc, method, *inputs, **kwargs):
        if method != "__call__" or kwargs:
            return NotImplemented
        name = {"divide": "true_divide", "absolute": "abs"}.get(
            ufunc.__name__, ufunc.__name__
        )
        fn = getattr(jnp, name, None)
        if fn is None:
            return NotImplemented
        return _KVal(fn(*[_unwrap(i) for i in inputs]))

    def __array_function__(self, func, types, args, kwargs):
        # non-ufunc numpy functions in kernels (np.where, np.clip, ...)
        # reroute to their jax.numpy namesakes
        fn = getattr(jnp, func.__name__, None)
        if fn is None:
            return NotImplemented

        def unw(x):
            if isinstance(x, (tuple, list)):
                return type(x)(unw(i) for i in x)
            return _unwrap(x)

        return _KVal(fn(*unw(args), **{k: unw(v) for k, v in kwargs.items()}))

    def __getitem__(self, idx):
        return _KVal(self.v[idx])

    @property
    def shape(self):
        return jnp.shape(self.v)

    @property
    def dtype(self):
        return jnp.result_type(self.v)


def _unwrap(x):
    return x.v if isinstance(x, _KVal) else x


def _install_kval_ops():
    binops = {
        "add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
        "truediv": jnp.true_divide, "floordiv": jnp.floor_divide,
        "mod": jnp.mod, "pow": jnp.power, "and": jnp.bitwise_and,
        "or": jnp.bitwise_or, "xor": jnp.bitwise_xor,
        "lt": jnp.less, "le": jnp.less_equal, "gt": jnp.greater,
        "ge": jnp.greater_equal, "eq": jnp.equal, "ne": jnp.not_equal,
    }
    for name, fn in binops.items():
        def fwd(self, other, _f=fn):
            return _KVal(_f(self.v, _unwrap(other)))

        def rev(self, other, _f=fn):
            return _KVal(_f(_unwrap(other), self.v))

        setattr(_KVal, f"__{name}__", fwd)
        if name not in ("lt", "le", "gt", "ge", "eq", "ne"):
            setattr(_KVal, f"__r{name}__", rev)
    for name, fn in {"neg": jnp.negative, "pos": jnp.positive,
                     "abs": jnp.abs, "invert": jnp.invert}.items():
        def un(self, _f=fn):
            return _KVal(_f(self.v))

        setattr(_KVal, f"__{name}__", un)


_install_kval_ops()


def _is_truth_ambiguous(e: BaseException) -> bool:
    """True only for numpy/jnp's non-scalar bool() error ('The truth value
    of an array ... is ambiguous') — requiring BOTH phrases keeps user
    kernels' own ValueErrors (which could contain either word) surfacing
    from their original call instead of a confusing branch-trace rerun."""
    s = str(e)
    return "truth value" in s and "ambiguous" in s


def _kwrap(vals):
    def wrap(v):
        if isinstance(v, tuple):  # e.g. smap_index's index tuple
            return tuple(wrap(e) for e in v)
        if isinstance(v, (jax.Array, jnp.ndarray)) or hasattr(v, "aval"):
            return _KVal(v)
        return v

    return [wrap(v) for v in vals]


def _call_kernel(func, *vals):
    """Call a user kernel on traced values; if it reaches for NumPy (which
    cannot consume tracers), retry with _KVal proxies.  A kernel that
    branches on data is auto-lowered via the two-sided branch trace
    (``_explore_branches`` + ``jnp.where`` combine); only kernels the trace
    cannot express (float()/int() conversion, data-dependent loop counts,
    path explosion) raise KernelTraceError — smap converts that into a host
    fallback, other skeletons let it surface loudly (never a silent wrong
    answer)."""
    branched = False
    try:
        return _unwrap(func(*vals))
    except jax.errors.TracerBoolConversionError:
        branched = True  # branch on a raw traced scalar: enumerate below
    except ValueError as e:
        # non-scalar operands (e.g. _tree_reduce's vector halves) raise
        # "truth value ... ambiguous" on a data branch; other ValueErrors
        # are kernel bugs and must surface from the original call
        if not _is_truth_ambiguous(e):
            raise
        branched = True
    except (jax.errors.TracerArrayConversionError, TypeError):
        try:
            return _unwrap(func(*_kwrap(vals)))
        except KernelBranchError:
            branched = True
        # float()/int() conversions raise plain KernelTraceError and are
        # not expressible as where(): let them propagate
    if not branched:  # pragma: no cover - defensive
        raise KernelTraceError(_BRANCH_MSG)
    wrapped = _kwrap(vals)
    try:
        leaves = _explore_branches(lambda: func(*wrapped))
    except (TypeError, jax.errors.TracerBoolConversionError) as e:
        # the branch-exploring re-trace hit something untraceable that the
        # first probe did not (e.g. a host conversion only reachable down a
        # forced branch path): surface it as a KernelTraceError so smap's
        # host fallback engages instead of an opaque jax error
        raise KernelTraceError(_BRANCH_MSG) from e
    _registry.inc("skeletons.branch_lowered")
    return _combine_branches(leaves)


class _Lit:
    """Identity-hashed wrapper so unhashable literals (e.g. whole numpy
    arrays passed through to the kernel) can live in a node's static tuple."""

    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v

    def __hash__(self):
        return id(self)

    def __eq__(self, other):
        return self is other


def _split_operands(args):
    """Partition skeleton args into element-wise array operands vs
    pass-through literals (the reference passes non-distributed args whole,
    docs/index.md:108-113)."""
    slots = []  # ("arr", operand_index) | ("lit", _Lit)
    operands = []
    for a in args:
        if isinstance(a, ndarray):
            slots.append(("arr", len(operands)))
            operands.append(a.read_expr())
        else:
            slots.append(("lit", _Lit(a)))
    return slots, operands


# Once-per-KERNEL host-fallback warning state.  A module-global boolean
# would warn for the first offending kernel only — every later kernel
# that silently falls off the device would go unreported — and two
# threads racing the flag could drop the warning entirely.
_fallback_warn_lock = threading.Lock()
_fallback_warned_kernels: set = set()


def _warn_host_fallback_once(func) -> bool:
    """True exactly once per kernel (thread-safe) — the caller should warn."""
    try:
        with _fallback_warn_lock:
            if func in _fallback_warned_kernels:
                return False
            _fallback_warned_kernels.add(func)
            return True
    except TypeError:  # unhashable callable: warn every time
        return True


def fallback_warned_kernels() -> frozenset:
    """Kernels that have taken (and warned about) the host fallback."""
    with _fallback_warn_lock:
        return frozenset(_fallback_warned_kernels)


def reset_fallback_warnings() -> None:
    """Test-visible reset hook: re-arm the once-per-kernel warning so a
    repeated suite (or a fresh test) observes it again."""
    with _fallback_warn_lock:
        _fallback_warned_kernels.clear()


def _host_smap(func, slots, with_index, ndim, arrs):
    """Host-evaluation fallback for kernels jax cannot trace (data-dependent
    Python branches).  The reference Numba-compiles arbitrary Python kernels
    (ramba.py:1600-1694); the TPU-native equivalent of "just run the Python"
    is a pure_callback: correct for any kernel, but it round-trips through
    the host — rewrite hot kernels with `where` to stay on the MXU/VPU."""
    if jax.process_count() > 1:
        # pure_callback cannot consume an array sharded across processes
        # (no single host sees the data); the reference has no analogue
        # either — its MPI mode Numba-compiles every kernel, and the
        # compilable cases are exactly what the branch trace already
        # lowered on-device before reaching here.
        raise KernelTraceError(
            "kernel is not expressible on-device (see previous error) and "
            "the per-element host fallback is unavailable under "
            "multi-controller execution; rewrite the kernel with "
            "np.where/jnp.where/lax.cond"
        )
    if _warn_host_fallback_once(func):
        warnings.warn(
            f"smap kernel {getattr(func, '__name__', repr(func))} is not "
            "jax-traceable (data-dependent branching); falling back to "
            "per-element host evaluation. Rewrite the branch with "
            "np.where/jnp.where for TPU-speed execution."
        )
    shape = np.broadcast_shapes(*[tuple(a.shape) for a in arrs]) if arrs else ()

    def call_one(*elem_vals):
        it = iter(elem_vals)
        idx = tuple(int(next(it)) for _ in range(ndim)) if with_index else None
        call_args = []
        for kind, payload in slots:
            call_args.append(next(it) if kind == "arr" else payload.v)
        if with_index:
            return func(idx, *call_args)
        return func(*call_args)

    # Output dtype probe (the result aval must be declared before the data
    # exists).  A branching kernel can return different dtypes per branch,
    # so probe at mixed-sign/zero samples and promote across them; the host
    # fn below still verifies the real result casts losslessly.
    dtypes = []
    for sample_val in (1, -1, 0):
        try:
            samples = []
            if with_index:
                samples += [np.zeros((), np.int64)] * ndim
            for kind, payload in slots:
                if kind == "arr":
                    samples.append(
                        np.dtype(arrs[payload].dtype).type(sample_val)
                    )
            dtypes.append(np.result_type(call_one(*samples)))
        except Exception:  # noqa: BLE001 - e.g. kernel needs real data
            pass
    out_dtype = (
        np.result_type(*dtypes) if dtypes
        else np.result_type(*[np.dtype(a.dtype) for a in arrs])
    )
    # x32 regime (TPU): pure_callback rejects 64-bit result dtypes outright;
    # fold the probed dtype through jax's truncation lattice (identity when
    # x64 is on)
    out_dtype = np.dtype(jax.dtypes.canonicalize_dtype(out_dtype))

    def host(*arrays):
        arrays = [np.asarray(a) for a in arrays]
        # Index planes follow the traced path exactly: iota over the main
        # operand's shape, broadcast with the operands (ndim == arrs[0].ndim).
        ins = (
            [np.broadcast_to(ix, shape) for ix in np.indices(arrays[0].shape)]
            if with_index else []
        )
        ins += [np.broadcast_to(a, shape) for a in arrays]
        if not shape:
            res = np.asarray(call_one(*[a[()] for a in ins]))
        else:
            # Explicit loop + one whole-list promotion: np.vectorize would
            # lock the output dtype to the FIRST element's branch and
            # silently truncate later elements (e.g. int branch first,
            # float branch later).
            vals = [call_one(*xs) for xs in zip(*[a.ravel() for a in ins])]
            res = np.asarray(vals).reshape(shape)
        if res.size == 0:
            return np.zeros(shape, out_dtype)
        if res.dtype != out_dtype and not np.can_cast(
            res.dtype, out_dtype, casting="same_kind"
        ):
            raise KernelTraceError(
                f"host-fallback kernel returned dtype {res.dtype} where the "
                f"probe inferred {out_dtype}; annotate the kernel so every "
                f"branch returns one dtype"
            )
        return res.astype(out_dtype)

    return jax.pure_callback(
        host, jax.ShapeDtypeStruct(shape, out_dtype), *arrs,
        vmap_method="expand_dims",
    )


@defop("smap")
def _op_smap(static, *arrs):
    func, slots, with_index, ndim = static

    def elem(*vals):
        it = iter(vals)
        idx_vals = []
        if with_index:
            idx_vals = [next(it) for _ in range(ndim)]
        call_args = []
        for kind, payload in slots:
            if kind == "arr":
                call_args.append(next(it))
            else:
                call_args.append(payload.v)
        if with_index:
            return _call_kernel(func, tuple(idx_vals), *call_args)
        return _call_kernel(func, *call_args)

    try:
        vec = jnp.vectorize(elem)
        if with_index:
            shape = arrs[0].shape
            iotas = [jax.lax.broadcasted_iota(jnp.int32, shape, d)
                     for d in range(len(shape))]
            return vec(*iotas, *arrs)
        return vec(*arrs)
    except KernelTraceError:
        _registry.inc("skeletons.host_fallback")
        return _host_smap(func, slots, with_index, ndim, arrs)


def _maybe_constrain(all_args, axis):
    """smap's axis kwarg records a co-partitioning constraint between the
    operands (reference: ramba.py:9915-9922); here it pins every ndarray
    operand to the same single-axis sharding."""
    if axis is None:
        return
    from ramba_tpu.parallel.constraints import add_constraint

    add_constraint([a for a in all_args if isinstance(a, ndarray)], axis)


def smap(func: Callable, arr, *args, axis=None):
    """Reference: ramba.smap (docs/index.md:92-137, ramba.py:9863-9931)."""
    arr = asarray(arr)
    _maybe_constrain((arr,) + args, axis)
    slots, operands = _split_operands((arr,) + args)
    return ndarray(Node("smap", (func, tuple(slots), False, arr.ndim), operands))


def smap_index(func: Callable, arr, *args, axis=None):
    arr = asarray(arr)
    _maybe_constrain((arr,) + args, axis)
    slots, operands = _split_operands((arr,) + args)
    return ndarray(Node("smap", (func, tuple(slots), True, arr.ndim), operands))


# ---------------------------------------------------------------------------
# sreduce / sreduce_index
# ---------------------------------------------------------------------------


class SreduceReducer:
    """Worker-local vs cross-worker reducer split (reference:
    SreduceReducer, ramba.py:9934-9939)."""

    def __init__(self, worker_reducer, driver_reducer):
        self.worker_reducer = worker_reducer
        self.driver_reducer = driver_reducer


def _tree_reduce(flat, identity, comb):
    """Fold-halves log₂ tree reduce.  Unlike ``lax.reduce``, the combine
    is an ordinary vectorized elementwise op, so arbitrary kernels work —
    including branch-lowered select() combines, which XLA:CPU's reduce
    emitter rejects ("Unsupported reduction computation").  This is also
    literally the reference's reduction shape: its workers combine
    partials over a log₂ message tree (ramba.py:2296-2331)."""
    n = flat.shape[0]
    size = 1 << max(0, int(n - 1).bit_length())
    if size != n:
        flat = jnp.concatenate(
            [flat, jnp.full((size - n,), identity, flat.dtype)]
        )
    while flat.shape[0] > 1:
        half = flat.shape[0] // 2
        flat = comb(flat[:half], flat[half:])
    return flat[0]


@defop("sreduce")
def _op_sreduce(static, mapped):
    local_fn, global_fn, identity, use_shard_split = static
    if not use_shard_split:
        flat = mapped.reshape(-1)
        return _tree_reduce(flat, jnp.asarray(identity, flat.dtype),
                            lambda a, b: _call_kernel(local_fn, a, b))

    # SreduceReducer path: per-shard reduce with the worker reducer inside
    # shard_map, then combine the per-shard partials with the driver reducer
    # (the reference's log2 tree over comm queues, ramba.py:2296-2331).
    mesh = _mesh.get_mesh()
    axes = tuple(mesh.axis_names)
    flat = mapped.reshape(-1)
    n = int(np.prod([mesh.shape[a] for a in axes]))
    pad = (-flat.shape[0]) % n
    if pad:
        flat = jnp.concatenate(
            [flat, jnp.full((pad,), identity, flat.dtype)], 0
        )

    def local(block):
        r = _tree_reduce(block, jnp.asarray(identity, block.dtype),
                         lambda a, b: _call_kernel(local_fn, a, b))
        return r[None]

    partials = _compat.shard_map(
        local, mesh=mesh, in_specs=P(axes), out_specs=P(axes),
        check_vma=False,
    )(flat)
    return _tree_reduce(partials, jnp.asarray(identity, partials.dtype),
                        lambda a, b: _call_kernel(global_fn, a, b))


def _sreduce_impl(func, reducer, identity, arr, args, with_index):
    arr = asarray(arr)
    slots, operands = _split_operands((arr,) + args)
    mapped = ndarray(
        Node("smap", (func, tuple(slots), with_index, arr.ndim), operands)
    )
    if isinstance(reducer, SreduceReducer):
        static = (reducer.worker_reducer, reducer.driver_reducer, identity, True)
    else:
        static = (reducer, reducer, identity, False)
    return ndarray(Node("sreduce", static, [mapped.read_expr()]))


def sreduce(func, reducer, identity, arr, *args):
    """Reference: ramba.sreduce (docs/index.md:141-186, ramba.py:9942-9984)."""
    return _sreduce_impl(func, reducer, identity, arr, args, False)


def sreduce_index(func, reducer, identity, arr, *args):
    return _sreduce_impl(func, reducer, identity, arr, args, True)


# ---------------------------------------------------------------------------
# stencil decorator + sstencil
# ---------------------------------------------------------------------------


class _ProbeValue:
    """Arithmetic-absorbing value used while probing a stencil kernel for
    its relative-offset access pattern (the reference probes with a local
    numba.stencil run, ramba.py:9989-10000)."""

    def _op(self, *_, **__):
        return _ProbeValue()

    for _name in ["__add__", "__radd__", "__sub__", "__rsub__", "__mul__",
                  "__rmul__", "__truediv__", "__rtruediv__", "__pow__",
                  "__rpow__", "__neg__", "__floordiv__", "__rfloordiv__",
                  "__mod__", "__rmod__", "__abs__", "__lt__", "__le__",
                  "__gt__", "__ge__", "__eq__", "__ne__", "__and__",
                  "__or__", "__xor__", "__invert__"]:
        locals()[_name] = _op
    del _name
    __hash__ = object.__hash__

    # numpy ufuncs on probe values (e.g. np.maximum(p, q)) absorb too
    def __array_ufunc__(self, ufunc, method, *inputs, **kwargs):
        return _ProbeValue()

    def __bool__(self):
        # A branch during the offset probe would silently hide the
        # not-taken branch's neighborhood — under the branch enumerator
        # every path runs, so the union of offsets is captured; without it
        # (direct host __call__ path) refuse loudly (use np.where).
        if _active_decider is not None:
            return _active_decider.decide(None)
        raise KernelBranchError(_BRANCH_MSG)


class _ProbeProxy:
    def __init__(self):
        self.offsets = []

    def __getitem__(self, off):
        if not isinstance(off, tuple):
            off = (off,)
        self.offsets.append(tuple(int(o) for o in off))
        return _ProbeValue()


class _ShiftProxy:
    """Relative indexing over the interior window: ``a[di, dj]`` becomes a
    shifted static slice; XLA fuses all shifted reads into one stencil
    kernel and GSPMD inserts the halo exchange the reference does by hand
    (compute_from_border tables, shardview_array.py:1069-1136)."""

    def __init__(self, arr, lo, interior, wrap=False):
        self.arr = arr
        self.lo = lo
        self.interior = interior
        self.wrap = wrap

    def __getitem__(self, off):
        if not isinstance(off, tuple):
            off = (off,)
        idx = tuple(
            slice(o - l, o - l + n)
            for o, l, n in zip(off, self.lo, self.interior)
        )
        piece = self.arr[idx]
        return _KVal(piece) if self.wrap else piece


class StencilKernel:
    """Result of the ``ramba.stencil`` decorator (reference: StencilMetadata,
    ramba.py:441-541).  Callable directly on host arrays, or distributed via
    ``sstencil``."""

    def __init__(self, func):
        self.func = func
        self._probe_cache = None
        self._probe_key = None

    def neighborhood(self, slots):
        """Probe the kernel: array slots get offset-recording proxies,
        literal slots get their real values (additional sstencil args 'may be
        of any type', docs/index.md).  Only cacheable when the kernel takes
        no literal args — literal values can steer which offsets are read."""
        has_literals = any(kind == "lit" for kind, _ in slots)
        cache_key = None if has_literals else tuple(kind for kind, _ in slots)
        if (has_literals or self._probe_cache is None
                or self._probe_key != cache_key):
            probes = []
            call_args = []
            for kind, payload in slots:
                if kind == "arr":
                    p = _ProbeProxy()
                    probes.append(p)
                    call_args.append(p)
                else:
                    call_args.append(payload.v)
            try:
                # branch enumeration visits every path, so a branching
                # kernel's probe records the UNION of both sides' offsets
                _explore_branches(lambda: self.func(*call_args))
            except Exception as e:  # kernel must be offset-indexing only
                raise ValueError(
                    f"could not probe stencil kernel {self.func}: {e}"
                ) from e
            all_offs = [o for p in probes for o in p.offsets]
            nd = len(all_offs[0]) if all_offs else 1
            lo = tuple(min(0, *(o[d] for o in all_offs)) if all_offs else 0
                       for d in range(nd))
            hi = tuple(max(0, *(o[d] for o in all_offs)) if all_offs else 0
                       for d in range(nd))
            # tap count steers the pallas kernel's VMEM block budget
            self._probe_cache = (lo, hi, len(all_offs))
            self._probe_key = cache_key
        return self._probe_cache

    def __call__(self, *args):
        # direct host call (reference: "using a Ramba stencil directly only
        # NumPy arrays may be used", docs/index.md)
        slots = []
        operands = []
        for a in args:
            if isinstance(a, (np.ndarray, list, jax.Array)):
                slots.append(("arr", len(operands)))
                operands.append(jnp.asarray(a))
            else:
                slots.append(("lit", _Lit(a)))
        lo, hi, taps = self.neighborhood(tuple(slots))
        return np.asarray(
            _eval_stencil((self.func, lo, hi, tuple(slots), taps), *operands)
        )


def stencil(func=None, **kwargs):
    """Decorator (reference: ramba.stencil, ramba.py:508-541)."""
    if func is None:
        return lambda f: StencilKernel(f)
    return StencilKernel(func)


_pallas_fallback_warned = False


def stencil_interior(func, lo, hi, slots, arrs):
    """Evaluate the stencil body over the interior window of ``arrs`` via
    shifted static slices; returns the raw interior values (shape = arr
    shape minus the neighborhood extent), no border zeroing."""
    shape = arrs[0].shape
    interior = tuple(
        s - (h - l) for s, l, h in zip(shape, lo, hi)
    )

    def build_args(wrap):
        out = []
        for kind, payload in slots:
            if kind == "arr":
                out.append(_ShiftProxy(arrs[payload], lo, interior, wrap=wrap))
            else:
                out.append(payload.v)
        return out

    return call_stencil_body(func, build_args)


def call_stencil_body(func, build_args):
    """Evaluate a stencil body given ``build_args(wrap) -> call_args``
    (shift proxies over slices — XLA path — or VMEM slabs — Pallas path).
    Handles the NumPy-ufunc retry and auto-lowers data branches: a
    per-element ``if`` in the reference's Numba kernels becomes an
    array-shaped where() here, the branch condition being a shifted slice,
    so the two-sided combine selects per point."""
    try:
        return _unwrap(func(*build_args(False)))
    except jax.errors.TracerBoolConversionError:
        pass  # branch on a raw traced scalar: enumerate below
    except ValueError as e:
        # non-scalar slices (traced or concrete) raise "The truth value of
        # an array ... is ambiguous" on a data branch; any OTHER ValueError
        # is a genuine kernel bug and must surface from the original call
        if not _is_truth_ambiguous(e):
            raise
    except (jax.errors.TracerArrayConversionError, TypeError):
        try:
            return _unwrap(func(*build_args(True)))
        except KernelBranchError:
            pass
    wrapped = build_args(True)
    try:
        leaves = _explore_branches(lambda: func(*wrapped))
    except (TypeError, jax.errors.TracerBoolConversionError) as e:
        # see _call_kernel: untraceable constructs first reached during the
        # branch re-trace become a KernelTraceError with the actionable
        # message instead of a raw tracer error
        raise KernelTraceError(_BRANCH_MSG) from e
    _registry.inc("skeletons.branch_lowered")
    return _combine_branches(leaves)


def _eval_stencil(static, *arrs):
    global _pallas_fallback_warned
    func, lo, hi, slots, taps = static
    from ramba_tpu.ops import stencil_sharded

    if stencil_sharded.eligible(lo, hi, arrs):
        try:
            return stencil_sharded.run(func, lo, hi, slots, arrs, taps)
        except Exception as e:  # same fence as the pallas path below
            if not _pallas_fallback_warned:
                _pallas_fallback_warned = True
                warnings.warn(
                    f"sharded stencil path unavailable, using GSPMD "
                    f"shifted-slice path: {type(e).__name__}: {e}"
                )
    if len(arrs[0].shape) == 2:
        from ramba_tpu.ops import pallas_backend

        fam = pallas_backend.family("stencil")
        if fam is not None and fam.available(arrs):
            try:
                return fam.run(func, lo, hi, slots, arrs, taps)
            except Exception as e:  # fall back to the XLA path, but say so
                if not _pallas_fallback_warned:
                    _pallas_fallback_warned = True
                    warnings.warn(
                        f"pallas stencil kernel unavailable, using XLA "
                        f"shifted-slice path: {type(e).__name__}: {e}"
                    )
    shape = arrs[0].shape
    interior = tuple(
        s - (h - l) for s, l, h in zip(shape, lo, hi)
    )
    val = stencil_interior(func, lo, hi, slots, arrs)
    out = jnp.zeros(shape, val.dtype)
    idx = tuple(slice(-l, -l + n) for l, n in zip(lo, interior))
    return out.at[idx].set(val)


defop("stencil")(_eval_stencil)


def _eval_stencil_iter(static, *arrs):
    func, lo, hi, slots, taps, iters = static
    one = (func, lo, hi, slots, taps)

    def body(_, a):
        return _eval_stencil(one, a, *arrs[1:])

    # A dtype-promoting kernel (int input, float literals) returns a wider
    # dtype than the carry starts with, which fori_loop rejects; seed the
    # carry with the single-sweep output dtype so semantics keep matching
    # `iters` chained sstencil calls.
    out = jax.eval_shape(lambda a: body(0, a), arrs[0])
    a0 = arrs[0] if arrs[0].dtype == out.dtype else arrs[0].astype(out.dtype)
    return jax.lax.fori_loop(0, iters, body, a0)


defop("stencil_iter")(_eval_stencil_iter)


def _stencil_node(st, arr, args):
    if not isinstance(st, StencilKernel):
        st = StencilKernel(st)
    arr = asarray(arr)
    full_args = [arr] + [
        asarray(a) if isinstance(a, (np.ndarray, list)) else a for a in args
    ]
    slots, operands = _split_operands(tuple(full_args))
    lo, hi, taps = st.neighborhood(tuple(slots))
    if len(lo) != arr.ndim:
        raise ValueError(
            f"stencil kernel indexes {len(lo)} dims but array has {arr.ndim}"
        )
    return st, lo, hi, slots, taps, operands


def sstencil(st, arr, *args):
    """Reference: ramba.sstencil (docs/index.md:190-215, ramba.py:9987-10054).
    Border cells of the output are zero (the stencil writes only indices
    where the full neighborhood is in range).  Extra args may be arrays
    (element-aligned, relative-indexed) or literals of any type."""
    st, lo, hi, slots, taps, operands = _stencil_node(st, arr, args)
    return ndarray(
        Node("stencil", (st.func, lo, hi, tuple(slots), taps), operands)
    )


def sstencil_iterate(st, arr, iters, *args):
    """Run ``iters`` stencil sweeps inside ONE compiled program
    (``lax.fori_loop`` over the single-sweep evaluation; extra args are
    loop-invariant).  Semantics match ``iters`` chained ``sstencil`` calls
    (border cells re-zeroed each sweep).

    This is the TPU-native replacement for the reference's persistent
    ``local_border`` halo buffers (ramba.py:1947-2071, 1260-1322; round-3
    verdict missing #4): instead of caching padded shards host-side across
    calls, the entire sweep loop lives on-device — halos move over ICI
    inside the loop, intermediates never materialize to HBM as separate
    roots, and compile cost is one sweep body rather than ``iters``
    unrolled copies."""
    iters = int(iters)
    if iters < 0:
        raise ValueError(f"iters must be >= 0, got {iters}")
    st, lo, hi, slots, taps, operands = _stencil_node(st, arr, args)
    return ndarray(
        Node(
            "stencil_iter",
            (st.func, lo, hi, tuple(slots), taps, iters),
            operands,
        )
    )


# ---------------------------------------------------------------------------
# scumulative
# ---------------------------------------------------------------------------


def _probe_associative(local_func, final_func) -> bool:
    """Decide whether the scan can lower to ``lax.associative_scan``.

    Host-side probe with concrete floats (the reference decides the carry
    protocol per-op by construction; here the user's pair of functions is
    opaque, so associativity is tested numerically):

    * combine(a, b) := local_func(b, a) must be associative, and
    * final_func(c, t) must equal combine(c, t) (the cross-block carry
      application must be the same op).

    Advisor r3: positive-only samples let clamped accumulators (e.g.
    ``max(0, x+c)``) pass while being non-associative on mixed-sign data.
    The sample set now spans mixed signs, zero, integers, and large/small
    magnitudes.  Residual risk remains for kernels associative on all
    probed triples but not globally (probing can never be a proof) —
    pass ``associative=False`` to force the always-correct sequential
    carry chain, or ``associative=True`` to skip the probe.

    Any exception (e.g. a kernel that only accepts arrays) or mismatch
    falls back to the sequential path — detection can only upgrade.
    """
    try:
        rng = np.random.RandomState(7)
        trips = [
            (5.0, -7.0, 3.0),            # mixed sign (catches clamps)
            (-1.0, 2.0, -3.0),
            (0.0, 1.0, -1.0),            # zeros
            (0.0, 0.0, 0.0),
            (1e8, -3.7, 1e-4),           # large/small magnitude
            (-1e8, 1e8, 1.0),
            (7.0, -3.0, 2.0),            # integer-valued
            (2.0, 2.0, 2.0),
        ] + [tuple(t) for t in rng.uniform(-4.0, 4.0, size=(8, 3))]

        def comb(a, b):
            return float(local_func(np.float64(b), np.float64(a)))

        for a, b, c in trips:
            if not np.isclose(comb(comb(a, b), c), comb(a, comb(b, c)),
                              rtol=1e-9, atol=1e-12):
                return False
            if not np.isclose(float(final_func(np.float64(a), np.float64(b))),
                              comb(a, b), rtol=1e-9, atol=1e-12):
                return False
        return True
    except Exception:
        return False


@defop("scumulative")
def _op_scumulative(static, x):
    local_func, final_func, associative, axis, distribute = static
    x = jnp.moveaxis(x, axis, 0)  # scan along the leading axis
    n = x.shape[0]
    rest = x.shape[1:]
    mesh = _mesh.get_mesh()
    axes = tuple(mesh.axis_names)
    nsh = int(np.prod([mesh.shape[a] for a in axes]))

    def local_scan(b):
        if associative:
            # log-depth vectorized scan on the VPU — the TPU-native
            # replacement for the reference's per-element Numba loop
            return jax.lax.associative_scan(
                lambda a, c: _call_kernel(local_func, c, a), b, axis=0
            )

        def step(carry, xi):
            y = jnp.where(carry[1], _call_kernel(local_func, xi, carry[0]), xi)
            return (y, jnp.asarray(True)), y

        (_, _), ys = jax.lax.scan(
            step, (jnp.zeros(b.shape[1:], x.dtype), jnp.asarray(False)), b
        )
        return ys

    if not distribute or nsh == 1 or n < nsh * 2:
        return jnp.moveaxis(local_scan(x), 0, axis)

    # Distributed: per-shard scan under shard_map, then a cross-shard carry
    # fix-up.  The reference chains carries worker-to-worker sequentially
    # over its comm queues (ramba.py:3378-3437); here each shard all-gathers
    # the per-shard totals (nsh rest-slices — one small collective) and
    # folds its own exclusive carry locally, so the only cross-shard
    # dependency is one all-gather instead of an nsh-deep message chain.
    pad = (-n) % nsh
    xp = (
        jnp.pad(x, [(0, pad)] + [(0, 0)] * len(rest)) if pad else x
    )
    # trace-time estimate of the carry fix-up collective: every shard
    # all-gathers the per-shard totals, nsh rest-slices each
    _registry.inc(
        "skeletons.scan_allgather_bytes_est",
        nsh * nsh * int(np.prod(rest, dtype=np.int64))
        * np.dtype(x.dtype).itemsize,
    )

    def per_shard(b):
        ys = local_scan(b)
        t = ys[-1]
        idx = jax.lax.axis_index(axes)
        ts = jax.lax.all_gather(t, axes, tiled=False)  # (nsh, *rest)

        def fold(c, args):
            j, tj = args
            nc = jnp.where(j == 0, tj, _call_kernel(final_func, c, tj))
            return nc, c  # emit the carry BEFORE tj: exclusive prefix

        _, excl = jax.lax.scan(
            fold, jnp.zeros(rest, ys.dtype), (jnp.arange(nsh), ts)
        )
        carry = excl[idx]
        fixed = _call_kernel(final_func, carry, ys)
        return jnp.where(idx == 0, ys, fixed)

    spec = P(axes, *([None] * len(rest)))
    out = _compat.shard_map(
        per_shard, mesh=mesh, in_specs=spec, out_specs=spec,
        check_vma=False,
    )(xp)
    if pad:
        out = out[:n]
    return jnp.moveaxis(out, 0, axis)


_warned_nonassoc = False


def _scan_axis_shards(arr, axis, mesh) -> int:
    """How many mesh shards actually split ``axis`` of ``arr``: read the
    operand's concrete sharding spec when it is a realized leaf on the
    current mesh, otherwise the spec the planner would assign
    (``default_spec``).  Replaces the old global-mesh-size heuristic — an
    array replicated (or sharded only on OTHER axes) scans each block whole
    regardless of how many devices the mesh has."""
    spec = None
    try:
        e = arr._expr
        if isinstance(e, Const):
            sh = getattr(e.value, "sharding", None)
            smesh = getattr(sh, "mesh", None)
            if (
                smesh is not None
                and tuple(getattr(smesh, "axis_names", ()))
                == tuple(mesh.axis_names)
                and getattr(sh, "spec", None) is not None
            ):
                spec = tuple(sh.spec)
    except Exception:
        spec = None
    if spec is None:
        spec = tuple(_mesh.default_spec(arr.shape, mesh))
    entry = spec[axis] if axis < len(spec) else None
    if entry is None:
        return 1
    names = entry if isinstance(entry, (tuple, list)) else (entry,)
    k = 1
    for nm in names:
        k *= int(mesh.shape.get(nm, 1))
    return k


def _warn_nonassoc_sharded(k, nsh) -> None:
    """Round-4 verdict #8: a non-rebasable kernel on a sharded scan axis is
    exact only per block (per-block carry semantics, same as the
    reference's scumulative_final) — say so loudly, once.  ``k`` is the
    shard count along the scan axis (from ``_scan_axis_shards``); the
    caller only invokes this when the distributed path will actually run."""
    global _warned_nonassoc
    if _warned_nonassoc:
        return
    import warnings

    _warned_nonassoc = True
    warnings.warn(
        "scumulative: the kernel failed the associativity probe and the "
        f"scan axis is sharded over {k} of the mesh's {nsh} devices.  "
        "Each shard scans its own "
        "block and the cross-shard carry is applied via final_func(boundary, "
        "block) — per-block carry semantics, identical to the reference's "
        "scumulative_final, which can differ from an exact sequential scan "
        "for non-rebasable kernels (e.g. clamped accumulators).  Pass "
        "associative=True if the kernel is in fact associative, or keep the "
        "scan axis unsharded for exact semantics.",
        RuntimeWarning,
        stacklevel=3,
    )


def scumulative(local_func, final_func, arr, axis=0, dtype=None, out=None,
                *, associative=None):
    """Reference: ramba.scumulative (docs/index.md:219-243,
    ramba.py:10057-10063,3378-3437) — N-D with ``axis``, accumulation
    ``dtype``, and ``out=`` like the reference signature.

    ``associative=True`` (or a successful host-side probe when None, the
    default — see ``_probe_associative`` for its limits) lowers the
    per-shard scan to ``lax.associative_scan``; ``associative=False``
    forces the sequential ``lax.scan`` element chain.  Either way blocks
    scan in parallel per shard and the cross-shard carry is fixed up with
    one totals all-gather inside the same program.

    Distributed contract (same as the reference, docs/index.md:219-243):
    ``final_func(boundary, block)`` must rebase a block-local scan given
    the previous block's final value.  Kernels that cannot be rebased
    elementwise (e.g. clamped accumulators) are exact only on the
    single-shard path — identical to the reference, whose
    ``scumulative_final`` applies final_func per worker block."""
    arr = asarray(arr)
    axis = int(axis)
    if not (-arr.ndim <= axis < arr.ndim):
        raise ValueError(
            f"axis {axis} out of range for {arr.ndim}-D array"
        )
    axis %= arr.ndim
    if dtype is not None and np.dtype(dtype) != arr.dtype:
        arr = arr.astype(dtype)
    if associative is None:
        associative = _probe_associative(local_func, final_func)
    mesh = _mesh.get_mesh()
    nsh = int(np.prod(list(mesh.shape.values())))
    n = arr.shape[axis] if arr.ndim else 0
    k = _scan_axis_shards(arr, axis, mesh) if nsh > 1 else 1
    # distribute only when the scan axis is actually split: a replicated
    # operand (or one sharded on other axes) scans whole blocks locally,
    # exactly — no carry fix-up, no warning
    distribute = (
        nsh > 1 and k > 1 and n >= max(nsh * 2, common.dist_threshold)
    )
    if not associative and distribute:
        _warn_nonassoc_sharded(k, nsh)
    res = ndarray(
        Node(
            "scumulative",
            (local_func, final_func, bool(associative), axis, distribute),
            [arr.read_expr()],
        )
    )
    if out is not None:
        if tuple(out.shape) != tuple(arr.shape):
            raise ValueError(
                f"out shape {out.shape} != array shape {arr.shape}"
            )
        res = res if out.dtype == res.dtype else res.astype(out.dtype)
        out.write_expr(res.read_expr())
        return out
    return res


# ---------------------------------------------------------------------------
# spmd
# ---------------------------------------------------------------------------


def _spec_entry_names(entry):
    """Mesh axis names a PartitionSpec entry shards over: () for None,
    (name,) for a bare string, tuple(entry) for an axis group.  The single
    normalization point for spec-entry handling in this module (review r4:
    four hand-rolled copies drifted independently)."""
    if entry is None:
        return ()
    return (entry,) if isinstance(entry, str) else tuple(entry)


def _shard_count(mesh, names) -> int:
    """Number of shards along a dim sharded over the ``names`` axis group."""
    n = 1
    for nm in names:
        n *= mesh.shape[nm]
    return n


class LocalView:
    """Per-worker view of a distributed array inside ``spmd`` (reference:
    LocalNdarray with get_local, ramba.py:1169-1357, docs/index.md:247-266).
    ``set_local`` is the functional replacement for in-place shard mutation:
    the updated block is written back to the source array after the call.
    ``global_start`` gives this shard's offset in global index space (the
    reference's per-shard ``subspace`` shardview row index_start,
    shardview_array.py:32-70)."""

    # (spec-entry normalization shared with spmd lives at module level:
    #  _spec_entry_names / _shard_count)

    def __init__(self, block, global_start=None, global_shape=None,
                 spec=None, mesh=None):
        self._block = block
        self._updated = None
        self._global_start = global_start
        self._global_shape = global_shape
        self._spec = spec
        self._mesh = mesh

    def get_local(self):
        return self._block if self._updated is None else self._updated

    def set_local(self, value):
        self._updated = jnp.asarray(value, self._block.dtype)

    @property
    def global_start(self):
        """Per-dim global index of this shard's [0,...,0] element (traced
        int32 scalars, usable inside the spmd kernel)."""
        if self._global_start is None:
            raise ValueError("global_start is only available inside spmd")
        return self._global_start

    @property
    def global_shape(self):
        """Global shape of the distributed array (static ints)."""
        if self._global_shape is None:
            raise ValueError("global_shape is only available inside spmd")
        return self._global_shape

    @property
    def local_valid(self):
        """Per-dim count of VALID rows in this block (traced int32).  For
        uneven distributions the trailing block is zero-padded up to the
        uniform SPMD block size; rows at index >= local_valid[d] are
        padding and their writes are discarded (reference parity: exact
        per-worker shapes, ramba.py:1169-1357, expressed the SPMD way)."""
        if self._global_start is None or self._global_shape is None:
            raise ValueError("local_valid is only available inside spmd")
        return tuple(
            jnp.clip(
                jnp.asarray(g, jnp.int32) - s, 0, b
            )
            for g, s, b in zip(
                self._global_shape, self._global_start, self._block.shape
            )
        )

    def halo(self, depth):
        """This worker's block extended by ``depth`` cells of neighboring
        shards' edge data per dim (zeros beyond the global domain) — the
        reference's ``LocalNdarray.getborder`` surface
        (ramba.py:1260-1322), expressed as an explicit ``ppermute``
        exchange inside the spmd program.  ``depth`` is an int or per-dim
        tuple; returns a jnp array of shape ``block + 2*depth`` per dim
        (reads the current ``get_local()`` state, so halos reflect prior
        ``set_local`` updates).  Corners arrive via sequential per-dim
        exchange (each dim ships the already-extended slab).

        Uneven distributions: the zero padding of the trailing block is
        treated as data by the exchange; kernels on uneven shards should
        mask with ``local_valid`` as usual."""
        if self._spec is None or self._mesh is None:
            raise ValueError("halo() is only available inside spmd")
        from ramba_tpu.ops.stencil_sharded import _exchange

        x = self.get_local()
        nd = x.ndim
        if isinstance(depth, int):
            depth = (depth,) * nd
        if len(depth) != nd or any(d < 0 for d in depth):
            raise ValueError(
                f"halo depth {depth!r} must be {nd} non-negative ints"
            )
        mesh = self._mesh
        spec = tuple(self._spec) + (None,) * (nd - len(tuple(self._spec)))
        for d in range(nd):
            if not depth[d]:
                continue
            names = _spec_entry_names(spec[d])
            nshards = _shard_count(mesh, names)
            if nshards > 1:
                if depth[d] > x.shape[d]:
                    # one ppermute hop reaches only the adjacent shard;
                    # check the CURRENT extent (set_local may have
                    # changed it), not the original block's
                    raise ValueError(
                        f"halo depth {depth[d]} exceeds the local block "
                        f"extent {x.shape[d]} along dim {d}"
                    )
                x = _exchange(x, d, names, nshards, depth[d], depth[d])
            else:
                # whole dim is local: beyond it lies the global boundary,
                # so any depth is well-defined zeros
                pads = [(0, 0)] * nd
                pads[d] = (depth[d], depth[d])
                x = jnp.pad(x, pads)
        return x

    @property
    def valid_mask(self):
        """Boolean mask over this block, True where the element is real
        data and False in the zero-padding of an uneven distribution.
        Use to bound block-coupled computations, e.g.
        ``masked = jnp.where(lv.valid_mask, lv.get_local(), identity)``."""
        cur = self.get_local().shape
        if cur != self._block.shape:
            # valid counts are defined in the ORIGINAL block's coordinates;
            # a reshaped slab (e.g. halo-extended via set_local) would get a
            # silently misaligned mask (ADVICE r4) — refuse loudly instead
            raise ValueError(
                f"valid_mask refers to the original {self._block.shape} "
                f"block but the local slab is now {cur}; read valid_mask "
                "before a shape-changing set_local(), or mask manually "
                "with local_valid"
            )
        valid = self.local_valid
        mask = jnp.ones(cur, bool)
        for d, nv in enumerate(valid):
            idx = jnp.arange(cur[d])
            shape = [1] * len(cur)
            shape[d] = -1
            mask = mask & (idx.reshape(shape) < nv)
        return mask

    @property
    def shape(self):
        return self.get_local().shape

    @property
    def dtype(self):
        return self.get_local().dtype


_replicated_write_warned = False
_uneven_pad_warned = False


def worker_id():
    """Inside ``spmd``: this worker's linear index (reference: worker_num
    passed to every remote kernel)."""
    m = _mesh.get_mesh()
    idx = jnp.zeros((), jnp.int32)
    mult = 1
    for name in reversed(m.axis_names):
        idx = idx + jax.lax.axis_index(name) * mult
        mult *= m.shape[name]
    return idx


def spmd(func, *args):
    """Reference: ramba.spmd (docs/index.md:247-266, ramba.py:10173-10180,
    3477-3491).  Runs ``func`` once per mesh device under shard_map; ndarray
    args arrive as LocalView shards; ``set_local`` updates propagate back.

    Reference parity for arbitrary distributions (ramba.py:1169-1357):
    uneven shards are zero-padded to the uniform SPMD block internally and
    unpadded on write-back (a one-time warning fires; kernels must bound
    block-coupled computations with ``LocalView.local_valid`` /
    ``LocalView.valid_mask`` — zero-padding is the correct identity for
    add-style contractions but skews min/mean/max over the block);
    replicated (small) arrays arrive whole on every device, like the
    reference's replicated bdarrays.  Writes to copies replicated along
    any mesh axis resolve deterministically to the coordinate-0 copy."""
    mesh = _mesh.get_mesh()
    axes = tuple(mesh.axis_names)
    arr_positions = [i for i, a in enumerate(args) if isinstance(a, ndarray)]
    arrays = [args[i] for i in arr_positions]
    vals = [a._value() for a in arrays]
    specs = []
    for v in vals:
        # Respect the sharding the user (or the layout solver) already gave
        # the array — re-sharding to default_spec would hand the kernel
        # different shard bounds than the ones set up (r2 verdict weak #6).
        spec = None
        existing = getattr(v, "sharding", None)
        if (
            isinstance(existing, NamedSharding)
            and existing.mesh == mesh
            and tuple(existing.spec) != ()
        ):
            spec = existing.spec
        if spec is None:
            spec = _mesh.default_spec(v.shape, mesh)
        specs.append(spec)
    # Zero-pad uneven dims up to shard_map's uniform block size; padding is
    # sliced back off after the call, so pad-region writes are discarded.
    orig_shapes = [tuple(v.shape) for v in vals]
    padded = []
    for v, spec in zip(vals, specs):
        pads = [(0, 0)] * v.ndim
        for d, entry in enumerate(tuple(spec)):
            k = _shard_count(mesh, _spec_entry_names(entry))
            if k > 1:
                pads[d] = (0, (-v.shape[d]) % k)
        if any(p[1] for p in pads):
            # Loud signal (review round 4): zero-padding is the correct
            # identity for add-style contractions but silently skews
            # min/mean/max-style block computations — point kernels at the
            # masking tools instead of corrupting quietly.
            global _uneven_pad_warned
            if not _uneven_pad_warned:
                _uneven_pad_warned = True
                warnings.warn(
                    f"spmd: array of shape {tuple(v.shape)} does not divide "
                    f"evenly over the mesh; trailing blocks are zero-padded "
                    f"to the uniform SPMD block. Block-coupled computations "
                    f"(min/mean/matmul over the block) must mask the padding "
                    f"via LocalView.local_valid or LocalView.valid_mask."
                )
            v = jnp.pad(v, pads)
        # Governor-accounted placement: these operand copies live outside
        # the fuser's owner census, so a raw device_put here would dodge
        # both admission control and peak-live bookkeeping.
        padded.append(_gov_memory.governed_device_put(
            v, NamedSharding(mesh, spec), site="spmd_pad"))
    vals = padded

    def _starts(spec, block_shape):
        """Global offset of this device's block per dim, from mesh coords
        (reference: per-shard index_start, shardview_array.py:32-70)."""
        out = []
        for d, entry in enumerate(spec):
            names = _spec_entry_names(entry)
            if not names:
                out.append(jnp.zeros((), jnp.int32))
                continue
            pos = jnp.zeros((), jnp.int32)
            for nm in names:
                pos = pos * mesh.shape[nm] + jax.lax.axis_index(nm)
            out.append(pos * block_shape[d])
        out += [jnp.zeros((), jnp.int32)] * (len(block_shape) - len(out))
        return tuple(out)

    def inner(*blocks):
        views = [
            LocalView(b, _starts(s, b.shape), gs, spec=s, mesh=mesh)
            for b, s, gs in zip(blocks, specs, orig_shapes)
        ]
        call_args = list(args)
        for p, v in zip(arr_positions, views):
            call_args[p] = v
        func(*call_args)
        outs = []
        for v, s in zip(views, specs):
            o = v.get_local()
            # Mesh axes the spec does not mention hold replicated copies of
            # this array — fully replicated (spec all-None) or partially
            # (e.g. P('d0', None) on a 2-axis mesh replicates along d1).
            # Divergent writes across those copies would otherwise be
            # dropped arbitrarily by out_specs; make the coordinate-0 copy
            # win deterministically and say so (reference semantics: the
            # driver reads worker 0's copy of replicated bdarrays).
            mentioned = set()
            for entry in tuple(s):
                mentioned.update(_spec_entry_names(entry))
            unused = tuple(nm for nm in axes if nm not in mentioned)
            if unused and v._updated is not None:
                global _replicated_write_warned
                if not _replicated_write_warned:
                    _replicated_write_warned = True
                    warnings.warn(
                        f"spmd kernel wrote to an array replicated along "
                        f"mesh ax{'is' if len(unused) == 1 else 'es'} "
                        f"{unused}; the coordinate-0 copy wins (reference "
                        f"semantics) — device-divergent writes to "
                        f"replicated copies are not merged"
                    )
                o = jax.lax.all_gather(o, unused, tiled=False)[0]
            outs.append(o)
        return tuple(outs)

    outs = _compat.shard_map(
        inner, mesh=mesh, in_specs=tuple(specs), out_specs=tuple(specs),
        check_vma=False,
    )(*vals)
    for a, new, gs in zip(arrays, outs, orig_shapes):
        if tuple(new.shape) != gs:
            new = new[tuple(slice(0, s) for s in gs)]
        a.write_expr(Const(new))
    return None


def barrier():
    """Reference: ramba.barrier (Ray BarrierActor, ramba.py:883-916) — here
    simply a device sync."""
    _sync()
