"""Proof-carrying plan certificates (``analyze/plancert.py`` +
``core/plancache.py``, RAMBA_PLANCERT).

The contract under test, in order of importance:

* **Soundness of redemption** — a hit must be provably equivalent to a
  fresh analysis: byte-identical results, the certified verdicts
  (findings, effect class, compile class) re-stamped on the span, and
  the verifier/certifier pipeline actually SKIPPED (counted via a
  wrapped ``_verify_if_enabled``).
* **Sound invalidation** — every ambient input a signature field reads
  (rule set, governor budget band, mesh epoch, …) must flip the
  certificate stale when it changes, with the changed field named in
  ``stale_causes``; the re-analysis then re-certifies.
* **Strict-mode rejection** — a ``plan:stale``-forged staleness verdict
  raises ``ProgramVerificationError`` under strict and silently
  re-analyzes (byte-identical) under warn.
* **Shared tier** — a published certificate is adoptable by chash from
  the fleet artifact tier, and the adopted copy redeems like a local
  hit.
* **Batched coherence** — hit agreement runs per batch, and a divergent
  round clears the local store.

The SPMD analog (lockstep hit/miss decisions on both ranks) is
``scripts/two_process_suite.py --plancache-leg``; the randomized
byte-identity oracle is the plan-cache leg in test_fuzz.py.
"""

import io
import json

import numpy as np
import pytest

import ramba_tpu as rt
from ramba_tpu.analyze import lint as alint
from ramba_tpu.analyze import plancert
from ramba_tpu.analyze.findings import ProgramVerificationError
from ramba_tpu.core import fuser, plancache
from ramba_tpu.observe import events
from ramba_tpu.resilience import faults


@pytest.fixture(autouse=True)
def _isolate(monkeypatch):
    """Armed plan cache under strict verify, empty store, no faults; the
    ambient env the signatures read is scoped per-test."""
    from ramba_tpu.core import memo

    fuser.flush()
    faults.configure(None)
    monkeypatch.setenv("RAMBA_PLANCERT", "1")
    monkeypatch.setenv("RAMBA_VERIFY", "strict")
    for k in ("RAMBA_VERIFY_RULES", "RAMBA_VERIFY_SKIP",
              "RAMBA_HBM_BUDGET", "RAMBA_ARTIFACTS", "RAMBA_MEMO"):
        monkeypatch.delenv(k, raising=False)
    plancache.reset()
    plancert.reset_caches()
    memo.reset()
    yield
    faults.reset()
    plancache.reset()
    plancert.reset_caches()
    memo.reset()


def _workload():
    a = rt.fromarray(np.arange(256.0).reshape(16, 16))
    b = rt.fromarray(np.ones((16, 16)))
    return np.asarray((a + b) * 2.0 - 0.5)


def _counting_verifier(monkeypatch):
    """Wrap the fuser's verifier entry point so tests can prove a hit
    skipped the analysis pipeline rather than merely matching output."""
    calls = []
    inner = fuser._verify_if_enabled

    def wrapper(*args, **kwargs):
        calls.append(1)
        return inner(*args, **kwargs)

    monkeypatch.setattr(fuser, "_verify_if_enabled", wrapper)
    return calls


def test_off_by_default(monkeypatch):
    monkeypatch.setenv("RAMBA_PLANCERT", "0")
    _workload()
    _workload()
    snap = plancache.snapshot()
    assert not snap["enabled"]
    assert snap["entries"] == 0 and snap.get("lookups") is None


def test_repeat_hits_and_skips_analysis(monkeypatch):
    calls = _counting_verifier(monkeypatch)
    first = _workload()
    n_miss = len(calls)
    assert n_miss >= 1
    assert plancache.snapshot().get("stores", 0) >= 1
    second = _workload()
    snap = plancache.snapshot()
    assert snap.get("hits", 0) >= 1 and not snap.get("stale")
    # the hit redeemed the certificate: no fresh verifier run
    assert len(calls) == n_miss
    assert first.tobytes() == second.tobytes()
    span = events.last(1, type="flush")[-1]
    assert span.get("plan_cache") == "hit"
    # the stage ledger splits trace from prepare so the waterfall shows
    # what the fast path saves; both must be stamped on a hit
    stages = span.get("stages") or {}
    assert "trace" in stages and "prepare" in stages


def test_ruleset_change_invalidates(monkeypatch):
    _workload()
    _workload()
    assert plancache.snapshot().get("hits", 0) >= 1
    monkeypatch.setenv("RAMBA_VERIFY_RULES", "shape-dtype")
    _workload()
    snap = plancache.snapshot()
    assert snap.get("stale", 0) >= 1
    assert snap["stale_causes"].get("ruleset", 0) >= 1
    # the re-analysis re-certified under the new rule set: repeats hit
    h0 = snap.get("hits", 0)
    _workload()
    assert plancache.snapshot().get("hits", 0) == h0 + 1


def test_budget_band_change_invalidates(monkeypatch):
    _workload()
    _workload()
    assert plancache.snapshot().get("hits", 0) >= 1
    monkeypatch.setenv("RAMBA_HBM_BUDGET", str(1 << 30))
    _workload()
    snap = plancache.snapshot()
    assert snap.get("stale", 0) >= 1
    assert snap["stale_causes"].get("budget_band", 0) >= 1


def test_mesh_epoch_change_invalidates():
    from ramba_tpu.parallel import mesh as pmesh

    _workload()
    _workload()
    assert plancache.snapshot().get("hits", 0) >= 1
    pmesh.mesh_epoch += 1
    try:
        _workload()
        snap = plancache.snapshot()
        assert snap.get("stale", 0) >= 1
        assert snap["stale_causes"].get("mesh_epoch", 0) >= 1
    finally:
        pmesh.mesh_epoch -= 1


def test_forged_stale_strict_raises():
    first = _workload()
    _workload()
    with faults.active("plan:stale:always"):
        with pytest.raises(ProgramVerificationError, match="plan-stale"):
            _workload()
    fuser.flush()
    # the forged verdict never corrupted the cache: repeats still hit
    h0 = plancache.snapshot().get("hits", 0)
    again = _workload()
    assert plancache.snapshot().get("hits", 0) == h0 + 1
    assert again.tobytes() == first.tobytes()


def test_forged_stale_warn_reanalyzes(monkeypatch):
    monkeypatch.setenv("RAMBA_VERIFY", "warn")
    calls = _counting_verifier(monkeypatch)
    first = _workload()
    n_miss = len(calls)
    with faults.active("plan:stale:always"):
        second = _workload()
    # warn mode silently re-ran the full analysis instead of trusting
    # (or raising on) the forged verdict — byte-identical either way
    assert len(calls) > n_miss
    assert second.tobytes() == first.tobytes()
    snap = plancache.snapshot()
    assert snap.get("forged_stale", 0) >= 1
    assert not snap.get("stale")    # forged, not genuine


def test_forging_fault_sites_stand_down():
    # while an analysis-corrupting fault is armed the cache must neither
    # serve nor store — a forged verdict certified once would outlive
    # the fault plan
    _workload()
    s0 = plancache.snapshot().get("stores", 0)
    with faults.active("memo:insert:always"):
        _workload()
    snap = plancache.snapshot()
    assert snap.get("stores", 0) == s0
    assert snap.get("hits") is None


def test_certificate_roundtrips_through_payload():
    _workload()
    entry = next(iter(plancache._store.values()))
    cert = entry.cert
    back = plancert.from_payload(
        json.loads(json.dumps(plancert.to_payload(cert))))
    assert back is not None
    assert back.signature == cert.signature
    assert back.sig_fields == cert.sig_fields
    assert back.findings_digest == cert.findings_digest
    assert back.aval_sig == cert.aval_sig


def test_shared_tier_adoption(tmp_path, monkeypatch):
    from ramba_tpu.fleet import artifacts

    monkeypatch.setenv("RAMBA_ARTIFACTS", str(tmp_path))
    artifacts.configure(str(tmp_path))
    try:
        _workload()
        certs = [e.cert for e in plancache._store.values()]
        assert certs and all(c.chash for c in certs)
        for c in certs:
            assert plancache.publish(c)
        # a fresh process is modeled by dropping the local store: the
        # next flush misses locally, adopts by chash, and redeems
        plancache.reset()
        first = _workload()
        snap = plancache.snapshot()
        assert snap.get("adopted", 0) >= 1
        assert snap.get("shared_hits", 0) >= 1
        span = events.last(1, type="flush")[-1]
        assert span.get("plan_cache") == "shared"
        # and the adopted copy is now a plain local hit
        second = _workload()
        assert plancache.snapshot().get("hits", 0) >= 1
        assert first.tobytes() == second.tobytes()
    finally:
        artifacts.reset()


def test_batched_agree_divergence_clears(monkeypatch):
    class _Stub:
        def engaged(self):
            return True

        def agree(self, name, n, reduce="min"):
            return n - 1    # a peer saw fewer hits: divergence

    monkeypatch.setattr(plancache, "_coherence", _Stub())
    monkeypatch.setenv("RAMBA_PLANCERT_AGREE", "2")
    _workload()
    _workload()     # hit 1: below batch, no agree round yet
    snap = plancache.snapshot()
    assert snap.get("agree_rounds") is None
    assert snap["pending_agree_hits"] == 1
    _workload()     # hit 2 completes the batch: divergent round
    snap = plancache.snapshot()
    assert snap.get("agree_rounds", 0) == 1
    assert snap.get("divergences", 0) == 1
    assert snap["entries"] == 0 and snap["pending_agree_hits"] == 0
    ev = events.last(1, type="plan_divergence")
    assert ev and ev[-1]["agreed"] == ev[-1]["proposed"] - 1


def test_eviction_cap(monkeypatch):
    monkeypatch.setenv("RAMBA_PLANCERT_MAX", "1")
    a = rt.fromarray(np.arange(16.0))
    np.asarray(a + 1.0)
    np.asarray(a * 3.0)
    snap = plancache.snapshot()
    assert snap["entries"] == 1
    assert snap.get("evictions", 0) >= 1
    del a


def test_plan_audit_over_live_trace(tmp_path, capsys):
    path = str(tmp_path / "plan.jsonl")
    events.configure(path)
    try:
        for _ in range(3):
            _workload()
    finally:
        events.configure(None)
    evs = alint.load_events(alint.discover(path)[0])
    assert any(e.get("type") == "plan_cert" for e in evs)
    assert alint.main(["--plan-audit", path]) == 0
    out = capsys.readouterr().out
    assert "plan audit" in out
    assert "proof re-derives" in out
    assert "PROOF BROKEN" not in out
    rec = alint.plan_audit(evs, file=io.StringIO())
    assert rec["certificates"] >= 1
    assert rec["would_hits"] >= rec["live_hits"] >= 1
    assert rec["proof_broken"] == {}


def test_plan_audit_flags_broken_proof(tmp_path, capsys):
    path = str(tmp_path / "plan.jsonl")
    events.configure(path)
    try:
        _workload()
        _workload()
    finally:
        events.configure(None)
    evs = alint.load_events(alint.discover(path)[0])
    for e in evs:
        if e.get("type") == "plan_cert":
            # corrupt the stored effect verdict: the offline replay must
            # catch a certificate whose proof no longer re-derives
            e["effect"][2] = "host-effecting"
    rec = alint.plan_audit(evs, file=io.StringIO())
    assert rec["proof_broken"]
