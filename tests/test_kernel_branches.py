"""Data-dependent Python branches in skeleton kernels.

Round-3 verdict weak #2: ``smap(lambda x: x*2 if x > 0 else -x, ...)``
silently dropped the else-branch (``_KVal`` had no ``__bool__``).  The
reference Numba-compiles arbitrary Python kernels, branches included
(/root/reference/ramba/ramba.py:1600-1694).

Round-4 verdict #6: branches are now AUTO-LOWERED to the device — the
kernel is re-executed once per reachable branch path (two-sided trace)
and the per-path results combine with ``jnp.where`` on the recorded
conditions, giving the reference's per-element branch semantics at XLA
speed.  Only kernels the trace cannot express (float()/int() conversion
feeding control flow, data-dependent loop counts, path explosion) take
the old host fallback (smap/smap_index) or raise ``KernelTraceError``
loudly — never wrong numbers.
"""

import numpy as np
import pytest

import ramba_tpu as rt


def _no_host_fallback():
    from ramba_tpu import skeletons

    skeletons.reset_fallback_warnings()
    return skeletons


def test_smap_branching_kernel_correct():
    # the exact probe from the round-3 verdict
    r = rt.smap(lambda x: x * 2 if x > 0 else -x, [-1.0, 2.0, -3.0])
    np.testing.assert_allclose(np.asarray(r), [1.0, 4.0, 3.0])


def test_smap_branching_kernel_stays_on_device():
    # round-4 verdict #6: simple branches lower to where() — NO host
    # fallback, no warning
    skeletons = _no_host_fallback()
    np.asarray(rt.smap(lambda x: 1.0 if x > 0 else 0.0, [-1.0, 1.0]))
    assert not skeletons.fallback_warned_kernels()


def test_smap_branching_sharded():
    # large enough to distribute over the 8-device mesh
    x = np.linspace(-1, 1, 4096)
    r = rt.smap(lambda v: v * 2 if v > 0 else -v, x)
    from tests.helpers import default_rtol

    np.testing.assert_allclose(
        np.asarray(r), np.where(x > 0, x * 2, -x), rtol=default_rtol(1e-12)
    )


def test_smap_nested_and_elif_branches():
    def k(v):
        if v > 0.5:
            if v > 0.75:
                return v * 4
            return v * 2
        elif v < -0.5:
            return -v
        return v * 0.0

    x = np.linspace(-1, 1, 257)
    want = np.select(
        [x > 0.75, x > 0.5, x < -0.5], [x * 4, x * 2, -x], 0.0
    )
    skeletons = _no_host_fallback()
    r = rt.smap(k, x)
    np.testing.assert_allclose(np.asarray(r), want, rtol=1e-12)
    assert not skeletons.fallback_warned_kernels()


def test_smap_traceable_kernel_stays_on_device():
    # kernels expressed with np.where never take the host fallback
    skeletons = _no_host_fallback()
    x = np.linspace(-1, 1, 64)
    r = rt.smap(lambda v: np.where(v > 0, v * 2, -v), x)
    np.testing.assert_allclose(np.asarray(r), np.where(x > 0, x * 2, -x))
    assert not skeletons.fallback_warned_kernels()


def test_smap_index_branching():
    x = np.array([1.0, 2.0, 3.0, 4.0])
    r = rt.smap_index(lambda i, v: v if i[0] % 2 == 0 else -v, x)
    np.testing.assert_allclose(np.asarray(r), [1.0, -2.0, 3.0, -4.0])


def test_smap_branching_with_literal_arg():
    x = np.array([-2.0, 0.5, 3.0])
    r = rt.smap(lambda v, cap: v if v < cap else cap, x, 1.0)
    np.testing.assert_allclose(np.asarray(r), np.minimum(x, 1.0))


def test_smap_branch_int_result_dtype():
    r = rt.smap(lambda x: 1 if x > 0 else 0, [-1.0, 2.0])
    assert np.asarray(r).tolist() == [0, 1]


def test_smap_branch_mixed_dtype_promotes():
    # review round 4: int branch must not truncate the float branch's
    # values (where() promotes to the common dtype)
    r = rt.smap(lambda x: 0 if x > 0 else x / 2, [3.0, -5.0])
    from tests.helpers import map_dtype

    out = np.asarray(r)
    assert out.dtype == map_dtype(np.float64)
    np.testing.assert_allclose(out, [0.0, -2.5])


def test_smap_index_branching_broadcast_operands():
    # review round 4: index planes must follow the main operand's shape
    # (traced-path semantics), not the broadcast output shape
    a = np.array([1.0, -2.0, 3.0])
    b = np.ones((4, 3))
    r = rt.smap_index(
        lambda i, x, y: x + y + i[0] if x > 0 else -x,
        rt.fromarray(a),
        rt.fromarray(b),
    )
    exp = np.where(
        a[None, :] > 0, a[None, :] + b + np.arange(3)[None, :], -a[None, :]
    )
    np.testing.assert_allclose(np.asarray(r), exp)


def test_smap_branch_on_wide_values_on_device():
    # round 4 expected this to need the host (dtype only discoverable at
    # values the probe never saw); the branch trace evaluates BOTH sides
    # symbolically so it just works on device now
    skeletons = _no_host_fallback()
    r = rt.smap(lambda x: x / 2 if abs(x) > 10 else 0, [1.0, 100.0])
    np.testing.assert_allclose(np.asarray(r), [0.0, 50.0])
    assert not skeletons.fallback_warned_kernels()


import jax as _jax

_MULTIPROC = _jax.process_count() > 1


@pytest.mark.skipif(
    _MULTIPROC,
    reason="pure_callback host fallback is single-controller only "
           "(no process sees the whole array); the loud-error contract "
           "is covered by test_host_fallback_refuses_multiprocess",
)
def test_smap_data_dependent_loop_falls_back_to_host():
    # a data-dependent LOOP count cannot become where(): depth cap fires
    # and the host fallback takes over, with the one-time warning
    def countdown(x):
        n = x
        while n > 0:
            n = n - 1.0
        return n

    skeletons = _no_host_fallback()
    with pytest.warns(UserWarning, match="host evaluation"):
        r = rt.smap(countdown, [2.5, -1.0, 0.5])
    np.testing.assert_allclose(np.asarray(r), [-0.5, -1.0, -0.5])


@pytest.mark.skipif(
    not _MULTIPROC,
    reason="exercises the multi-controller loud-error contract",
)
def test_host_fallback_refuses_multiprocess():
    def countdown(x):
        n = x
        while n > 0:
            n = n - 1.0
        return n

    with pytest.raises(rt.KernelTraceError, match="multi-controller"):
        np.asarray(rt.smap(countdown, [2.5, -1.0]))


def test_sreduce_branching_large_sharded():
    # regression: XLA:CPU's reduce emitter rejects select-based reducer
    # computations ("Unsupported reduction computation") at sharded sizes;
    # the fold-halves tree reduce must handle a branch-lowered combine on
    # a distributed operand
    v = np.linspace(-3.0, 3.0, 100_000)
    best = rt.sreduce(
        lambda x: x,
        lambda a, b: a if a > b else b,
        -np.inf,
        rt.fromarray(v),
    )
    assert float(best) == pytest.approx(v.max())


def test_sreduce_branching_runs_on_device():
    # round 4 raised loudly here; the branch trace lowers the reducer
    got = float(
        rt.sreduce(
            lambda x: x,
            lambda a, b: a + b if a > 0 else b,
            0.0,
            [1.0, 2.0],
        )
    )
    assert got == 3.0


def test_stencil_branching_runs_on_device():
    # round 4 refused to probe branching stencil kernels; the enumerator
    # now records the UNION of both branches' neighborhoods and the body
    # lowers to a per-point where()
    @rt.stencil
    def pick(a):
        v = a[0, 1]
        if v > 0:
            return v
        return a[0, -1]

    from tests.helpers import default_rtol

    x = np.random.RandomState(4).randn(16, 16)
    got = np.asarray(rt.sstencil(pick, rt.fromarray(x)))
    right = np.roll(x, -1, axis=1)
    left = np.roll(x, 1, axis=1)
    want = np.where(right > 0, right, left)
    want[:, 0] = want[:, -1] = 0.0  # border zeroing, both offsets depth 1
    np.testing.assert_allclose(got, want, rtol=default_rtol(1e-12))


def test_fromfunction_and_init_array_branching():
    # round-5: fillers get the same kernel treatment as skeletons (the
    # reference Numba-compiles them too, ramba.py:1535-1595)
    d = rt.fromfunction(lambda i, j: i * 2 if i > j else -j, (4, 4))
    i, j = np.arange(4)[:, None], np.arange(4)[None, :]
    np.testing.assert_allclose(
        np.asarray(d), np.where(i > j, i * 2.0, -j * 1.0))
    e = rt.init_array(16, lambda k: k * 2 if k % 2 == 0 else -k)
    np.testing.assert_allclose(
        np.asarray(e),
        np.array([k * 2 if k % 2 == 0 else -k for k in range(16)], float))
    # np.* ufunc rerouting in fillers
    w = rt.fromfunction(lambda i, j: np.where(i > j, i, -j), (4, 4))
    np.testing.assert_allclose(
        np.asarray(w), np.fromfunction(lambda i, j: np.where(i > j, i, -j),
                                       (4, 4)))


def test_scumulative_branching_runs_on_device():
    # small array stays on one shard -> exact sequential semantics
    v = np.ones(16)
    got = np.asarray(
        rt.scumulative(
            lambda x, c: x + c if c > 0 else x,
            lambda c, t: c + t,
            v,
            associative=False,
        )
    )
    want = [v[0]]
    for xi in v[1:]:
        want.append(xi + want[-1] if want[-1] > 0 else xi)
    np.testing.assert_allclose(got, np.array(want))


@pytest.mark.slow  # the host path is a per-element Python loop over 3M
# elements — minutes of wall clock on small CI machines; run via -m slow
@pytest.mark.skipif(
    _MULTIPROC,
    reason="pure_callback reference timing needs the single-controller "
           "host fallback; perf contract is measured on that leg",
)
def test_branch_lowering_beats_host_fallback():
    # round-4 verdict #6 "done" bar: >=100x over pure_callback on the same
    # branching kernel
    import time

    from ramba_tpu import skeletons

    def k(x):
        return x * 2 if x > 0 else -x

    import jax

    # big enough that the device path's few-ms dispatch floor is noise
    # next to the host path's per-element Python loop; completion is
    # block_until_ready (the host gather would otherwise dominate the
    # device timing and hide the compute gap being measured)
    n = 3_000_000
    x = np.linspace(-1, 1, n)
    arr = rt.fromarray(x)

    def best_of(reps, f):
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            f()
            times.append(time.perf_counter() - t0)
        return min(times)

    jax.block_until_ready(rt.smap(k, arr)._value())  # compile
    device_s = best_of(
        5, lambda: jax.block_until_ready(rt.smap(k, arr)._value())
    )

    jarr = arr._value()
    host_fn = jax.jit(
        lambda a: skeletons._host_smap(k, (("arr", 0),), False, 1, [a])
    )
    jax.block_until_ready(host_fn(jarr))  # compile
    host_s = best_of(2, lambda: jax.block_until_ready(host_fn(jarr)))

    assert host_s / device_s >= 100, (host_s, device_s)
