"""Data-dependent Python branches in skeleton kernels.

Round-3 verdict weak #2: ``smap(lambda x: x*2 if x > 0 else -x, ...)``
silently dropped the else-branch (``_KVal`` had no ``__bool__``).  The
reference Numba-compiles arbitrary Python kernels, branches included
(/root/reference/ramba/ramba.py:1600-1694); here branching kernels must
either produce *correct* results (smap/smap_index fall back to host
evaluation via pure_callback) or raise ``KernelTraceError`` loudly —
never return wrong numbers.
"""

import numpy as np
import pytest

import ramba_tpu as rt


def test_smap_branching_kernel_correct():
    # the exact probe from the round-3 verdict
    r = rt.smap(lambda x: x * 2 if x > 0 else -x, [-1.0, 2.0, -3.0])
    np.testing.assert_allclose(np.asarray(r), [1.0, 4.0, 3.0])


def test_smap_branching_kernel_warns_once():
    from ramba_tpu import skeletons

    skeletons._host_fallback_warned = False
    with pytest.warns(UserWarning, match="host evaluation"):
        np.asarray(rt.smap(lambda x: 1.0 if x > 0 else 0.0, [-1.0, 1.0]))


def test_smap_branching_sharded():
    # large enough to distribute over the 8-device mesh
    x = np.linspace(-1, 1, 4096)
    r = rt.smap(lambda v: v * 2 if v > 0 else -v, x)
    from tests.helpers import default_rtol

    np.testing.assert_allclose(
        np.asarray(r), np.where(x > 0, x * 2, -x), rtol=default_rtol(1e-12)
    )


def test_smap_traceable_kernel_stays_on_device():
    # kernels expressed with np.where never take the host fallback
    from ramba_tpu import skeletons

    skeletons._host_fallback_warned = False
    x = np.linspace(-1, 1, 64)
    r = rt.smap(lambda v: np.where(v > 0, v * 2, -v), x)
    np.testing.assert_allclose(np.asarray(r), np.where(x > 0, x * 2, -x))
    assert not skeletons._host_fallback_warned


def test_smap_index_branching():
    x = np.array([1.0, 2.0, 3.0, 4.0])
    r = rt.smap_index(lambda i, v: v if i[0] % 2 == 0 else -v, x)
    np.testing.assert_allclose(np.asarray(r), [1.0, -2.0, 3.0, -4.0])


def test_smap_branching_with_literal_arg():
    x = np.array([-2.0, 0.5, 3.0])
    r = rt.smap(lambda v, cap: v if v < cap else cap, x, 1.0)
    np.testing.assert_allclose(np.asarray(r), np.minimum(x, 1.0))


def test_smap_branch_int_result_dtype():
    r = rt.smap(lambda x: 1 if x > 0 else 0, [-1.0, 2.0])
    assert np.asarray(r).tolist() == [0, 1]


def test_smap_branch_mixed_dtype_promotes():
    # review round 4: int branch at the probe sample must not truncate the
    # float branch's values
    r = rt.smap(lambda x: 0 if x > 0 else x / 2, [3.0, -5.0])
    from tests.helpers import map_dtype

    out = np.asarray(r)
    assert out.dtype == map_dtype(np.float64)
    np.testing.assert_allclose(out, [0.0, -2.5])


def test_smap_index_branching_broadcast_operands():
    # review round 4: index planes must follow the main operand's shape
    # (traced-path semantics), not the broadcast output shape
    a = np.array([1.0, -2.0, 3.0])
    b = np.ones((4, 3))
    r = rt.smap_index(
        lambda i, x, y: x + y + i[0] if x > 0 else -x,
        rt.fromarray(a),
        rt.fromarray(b),
    )
    exp = np.where(
        a[None, :] > 0, a[None, :] + b + np.arange(3)[None, :], -a[None, :]
    )
    np.testing.assert_allclose(np.asarray(r), exp)


def test_smap_branch_probe_miss_raises_not_truncates():
    # dtype only discoverable on values the probe never sees: loud error
    # beats silent truncation
    from ramba_tpu.utils.debug import drain_effect_errors

    with pytest.raises(Exception, match="probe inferred"):
        np.asarray(rt.smap(lambda x: x / 2 if abs(x) > 10 else 0, [1.0, 100.0]))
    # the failing pure_callback leaves a poisoned runtime token; drain it here
    # so the error doesn't resurface as "Exception ignored in atexit"
    drain_effect_errors()


def test_sreduce_branching_raises_loudly():
    with pytest.raises(rt.KernelTraceError, match="branches on a traced"):
        float(
            rt.sreduce(
                lambda x: x,
                lambda a, b: a + b if a > 0 else b,
                0.0,
                [1.0, 2.0],
            )
        )


def test_stencil_branching_raises_loudly():
    @rt.stencil
    def bad(a):
        v = a[0, 1]
        return v if v > 0 else a[0, -1]

    with pytest.raises(ValueError, match="could not probe"):
        rt.sstencil(bad, rt.fromarray(np.ones((8, 8))))


def test_scumulative_branching_raises_loudly():
    with pytest.raises(rt.KernelTraceError):
        np.asarray(
            rt.scumulative(
                lambda x, c: x + c if c > 0 else x,
                lambda c, t: c + t,
                np.ones(16),
                associative=False,
            )
        )
