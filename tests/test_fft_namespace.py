"""Differential tests for ramba_tpu.fft (beyond the reference, which
exposes no fft submodule)."""

import numpy as np
import pytest

import ramba_tpu as rt
from tests.helpers import default_atol, default_rtol


def _cmp(got, want, rtol=1e-8):
    np.testing.assert_allclose(
        np.asarray(got), want, rtol=default_rtol(rtol), atol=default_atol()
    )


@pytest.fixture
def sig():
    return np.random.RandomState(0).rand(128)


@pytest.fixture
def img():
    return np.random.RandomState(1).rand(16, 32)


class TestTransforms:
    def test_fft_roundtrip(self, sig):
        a = rt.fromarray(sig)
        f = rt.fft.fft(a)
        _cmp(f, np.fft.fft(sig), rtol=1e-6)
        back = rt.fft.ifft(f)
        _cmp(np.asarray(back).real, sig, rtol=1e-5)

    def test_rfft_family(self, sig):
        a = rt.fromarray(sig)
        _cmp(rt.fft.rfft(a), np.fft.rfft(sig), rtol=1e-6)
        _cmp(rt.fft.irfft(rt.fft.rfft(a)), sig, rtol=1e-5)
        _cmp(rt.fft.ihfft(a), np.fft.ihfft(sig), rtol=1e-6)

    def test_fft_args(self, sig):
        a = rt.fromarray(sig)
        _cmp(rt.fft.fft(a, n=64), np.fft.fft(sig, n=64), rtol=1e-6)
        _cmp(rt.fft.fft(a, norm="ortho"), np.fft.fft(sig, norm="ortho"),
             rtol=1e-6)

    def test_2d_and_nd(self, img):
        a = rt.fromarray(img)
        _cmp(rt.fft.fft2(a), np.fft.fft2(img), rtol=1e-6)
        _cmp(rt.fft.rfft2(a), np.fft.rfft2(img), rtol=1e-6)
        _cmp(rt.fft.fftn(a, axes=(0,)), np.fft.fftn(img, axes=(0,)),
             rtol=1e-6)
        _cmp(np.asarray(rt.fft.ifftn(rt.fft.fftn(a))).real, img, rtol=1e-5)

    def test_shift_freq(self, sig):
        a = rt.fromarray(sig)
        _cmp(rt.fft.fftshift(a), np.fft.fftshift(sig))
        _cmp(rt.fft.ifftshift(rt.fft.fftshift(a)), sig)
        _cmp(rt.fft.fftfreq(64, d=0.5), np.fft.fftfreq(64, d=0.5))
        _cmp(rt.fft.rfftfreq(64), np.fft.rfftfreq(64))

    def test_np_dispatch(self, sig):
        a = rt.fromarray(sig)
        got = np.fft.rfft(a)
        assert isinstance(got, type(a))
        _cmp(got, np.fft.rfft(sig), rtol=1e-6)

    def test_fuses_with_elementwise(self, sig):
        from ramba_tpu.core import fuser

        a = rt.fromarray(sig)
        rt.sync()
        f0 = fuser.stats["flushes"]
        power = rt.abs(rt.fft.rfft(a * 2.0)) ** 2
        total = float(rt.sum(power))
        assert fuser.stats["flushes"] == f0 + 1
        np.testing.assert_allclose(
            total, (np.abs(np.fft.rfft(sig * 2)) ** 2).sum(),
            rtol=default_rtol(1e-6))
