"""Pallas stencil kernel, run in interpreter mode on the CPU mesh (the
real-TPU lowering of the same kernel is exercised by bench.py on hardware).
"""

import numpy as np
import pytest

import ramba_tpu as rt
from ramba_tpu.ops import stencil_pallas


@pytest.fixture
def interpret_mode(monkeypatch):
    monkeypatch.setattr(stencil_pallas, "_INTERPRET", True)
    monkeypatch.setattr(stencil_pallas, "_ENABLED", True)
    # pin dispatch to the single-chip kernel: the multi-device composed
    # path (shard_map + ppermute + local kernel) has its own test file
    from ramba_tpu.ops import stencil_sharded

    monkeypatch.setattr(stencil_sharded, "eligible", lambda *a, **k: False)


def _prk_star2(w=None):
    @rt.stencil
    def star2(a):
        return (
            0.25 * (a[0, 1] + a[0, -1] + a[1, 0] + a[-1, 0])
            + 0.125 * (a[0, 2] + a[0, -2] + a[2, 0] + a[-2, 0])
        )

    return star2


def _star2_numpy(x):
    out = np.zeros_like(x)
    out[2:-2, 2:-2] = (
        0.25 * (x[2:-2, 3:-1] + x[2:-2, 1:-3] + x[3:-1, 2:-2] + x[1:-3, 2:-2])
        + 0.125 * (x[2:-2, 4:] + x[2:-2, :-4] + x[4:, 2:-2] + x[:-4, 2:-2])
    )
    return out


class TestPallasStencil:
    def test_star2_matches_numpy(self, interpret_mode):
        x = np.arange(40 * 36, dtype=np.float32).reshape(40, 36) / 7.0
        out = rt.sstencil(_prk_star2(), rt.fromarray(x)).asarray()
        np.testing.assert_allclose(out, _star2_numpy(x), rtol=1e-5)

    def test_available_gating(self):
        import jax
        import jax.numpy as jnp

        a = jnp.zeros((16, 16), jnp.float32)
        # CPU without interpret mode: not available
        assert not stencil_pallas.available([a])

    def test_odd_sizes(self, interpret_mode):
        # non-multiple-of-128 width, non-multiple-of-block height
        x = np.random.RandomState(0).rand(37, 131).astype(np.float32)
        out = rt.sstencil(_prk_star2(), rt.fromarray(x)).asarray()
        np.testing.assert_allclose(out, _star2_numpy(x), rtol=1e-4, atol=1e-5)

    def test_asymmetric_offsets(self, interpret_mode):
        @rt.stencil
        def shifted(a):
            return a[-1, 0] + a[0, 2]

        x = np.arange(64, dtype=np.float32).reshape(8, 8)
        out = rt.sstencil(shifted, rt.fromarray(x)).asarray()
        e = np.zeros_like(x)
        e[1:, :-2] = x[:-1, :-2] + x[1:, 2:]
        np.testing.assert_allclose(out, e)

    def test_two_input_arrays(self, interpret_mode):
        @rt.stencil
        def mix(a, b):
            return a[0, 0] + 0.5 * (b[-1, 0] + b[1, 0])

        x = np.random.RandomState(1).rand(24, 40).astype(np.float32)
        y = np.random.RandomState(2).rand(24, 40).astype(np.float32)
        out = rt.sstencil(mix, rt.fromarray(x), rt.fromarray(y)).asarray()
        e = np.zeros_like(x)
        e[1:-1, :] = x[1:-1, :] + 0.5 * (y[:-2, :] + y[2:, :])
        np.testing.assert_allclose(out, e, rtol=1e-6)

    def test_numpy_kernel_body(self, interpret_mode):
        @rt.stencil
        def npk(a):
            return np.maximum(a[0, -1], a[0, 1])

        x = np.random.RandomState(3).rand(16, 20).astype(np.float32)
        out = rt.sstencil(npk, rt.fromarray(x)).asarray()
        e = np.zeros_like(x)
        e[:, 1:-1] = np.maximum(x[:, :-2], x[:, 2:])
        np.testing.assert_allclose(out, e)


@pytest.fixture
def no_fallback(monkeypatch):
    """Make any silent fall-back to the XLA or padded path a hard failure."""
    import ramba_tpu.skeletons as sk

    def boom(*a, **k):
        raise AssertionError("padded path used, fast path expected")

    monkeypatch.setattr(stencil_pallas, "_run_padded", boom)
    monkeypatch.setattr(sk, "_pallas_fallback_warned", False)
    import warnings as _w

    real_warn = _w.warn

    def strict_warn(msg, *a, **k):
        if "pallas stencil" in str(msg):
            raise AssertionError(f"fallback: {msg}")
        return real_warn(msg, *a, **k)

    monkeypatch.setattr("warnings.warn", strict_warn)


class TestPallasFastPath:
    """The aligned-shape kernel: no pad pass, double-buffered slab DMA."""

    def test_eligibility(self):
        import jax.numpy as jnp

        a = jnp.zeros((40, 128), jnp.float32)
        b = jnp.zeros((40, 130), jnp.float32)  # W not 128-aligned
        c = jnp.zeros((37, 128), jnp.float32)  # H not 8-aligned
        assert stencil_pallas._fast_eligible((-2, -2), (2, 2), [a])
        assert not stencil_pallas._fast_eligible((-2, -2), (2, 2), [b])
        assert not stencil_pallas._fast_eligible((-2, -2), (2, 2), [c])

    def test_fast_star2_matches_numpy(self, interpret_mode, no_fallback):
        x = np.random.RandomState(0).rand(40, 128).astype(np.float32)
        out = rt.sstencil(_prk_star2(), rt.fromarray(x)).asarray()
        np.testing.assert_allclose(out, _star2_numpy(x), rtol=1e-5, atol=1e-6)

    def test_fast_multiblock(self, interpret_mode, no_fallback, monkeypatch):
        # force several grid steps so the double-buffer rotation is exercised
        monkeypatch.setattr(stencil_pallas, "_BH", 8)
        x = np.random.RandomState(1).rand(64, 256).astype(np.float32)
        out = rt.sstencil(_prk_star2(), rt.fromarray(x)).asarray()
        np.testing.assert_allclose(out, _star2_numpy(x), rtol=1e-5, atol=1e-6)

    def test_fast_single_block(self, interpret_mode, no_fallback, monkeypatch):
        monkeypatch.setattr(stencil_pallas, "_BH", 64)
        x = np.random.RandomState(2).rand(32, 128).astype(np.float32)
        out = rt.sstencil(_prk_star2(), rt.fromarray(x)).asarray()
        np.testing.assert_allclose(out, _star2_numpy(x), rtol=1e-5, atol=1e-6)

    def test_fast_two_inputs(self, interpret_mode, no_fallback, monkeypatch):
        monkeypatch.setattr(stencil_pallas, "_BH", 16)

        @rt.stencil
        def mix(a, b):
            return a[0, 0] + 0.5 * (b[-1, 0] + b[1, 0])

        x = np.random.RandomState(3).rand(48, 128).astype(np.float32)
        y = np.random.RandomState(4).rand(48, 128).astype(np.float32)
        out = rt.sstencil(mix, rt.fromarray(x), rt.fromarray(y)).asarray()
        e = np.zeros_like(x)
        e[1:-1, :] = x[1:-1, :] + 0.5 * (y[:-2, :] + y[2:, :])
        np.testing.assert_allclose(out, e, rtol=1e-6)

    def test_fast_asymmetric(self, interpret_mode, no_fallback, monkeypatch):
        monkeypatch.setattr(stencil_pallas, "_BH", 8)

        @rt.stencil
        def shifted(a):
            return a[-3, 0] + a[0, 5]

        x = np.random.RandomState(5).rand(40, 128).astype(np.float32)
        out = rt.sstencil(shifted, rt.fromarray(x)).asarray()
        e = np.zeros_like(x)
        e[3:, :-5] = x[:-3, :-5] + x[3:, 5:]
        np.testing.assert_allclose(out, e)
