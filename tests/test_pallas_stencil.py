"""Pallas stencil kernel, run in interpreter mode on the CPU mesh (the
real-TPU lowering of the same kernel is exercised by bench.py on hardware).
"""

import numpy as np
import pytest

import ramba_tpu as rt
from ramba_tpu.ops import stencil_pallas


@pytest.fixture
def interpret_mode(monkeypatch):
    monkeypatch.setattr(stencil_pallas, "_INTERPRET", True)
    monkeypatch.setattr(stencil_pallas, "_ENABLED", True)


def _prk_star2(w=None):
    @rt.stencil
    def star2(a):
        return (
            0.25 * (a[0, 1] + a[0, -1] + a[1, 0] + a[-1, 0])
            + 0.125 * (a[0, 2] + a[0, -2] + a[2, 0] + a[-2, 0])
        )

    return star2


def _star2_numpy(x):
    out = np.zeros_like(x)
    out[2:-2, 2:-2] = (
        0.25 * (x[2:-2, 3:-1] + x[2:-2, 1:-3] + x[3:-1, 2:-2] + x[1:-3, 2:-2])
        + 0.125 * (x[2:-2, 4:] + x[2:-2, :-4] + x[4:, 2:-2] + x[:-4, 2:-2])
    )
    return out


class TestPallasStencil:
    def test_star2_matches_numpy(self, interpret_mode):
        x = np.arange(40 * 36, dtype=np.float32).reshape(40, 36) / 7.0
        out = rt.sstencil(_prk_star2(), rt.fromarray(x)).asarray()
        np.testing.assert_allclose(out, _star2_numpy(x), rtol=1e-5)

    def test_available_gating(self):
        import jax
        import jax.numpy as jnp

        a = jnp.zeros((16, 16), jnp.float32)
        # CPU without interpret mode: not available
        assert not stencil_pallas.available([a])

    def test_odd_sizes(self, interpret_mode):
        # non-multiple-of-128 width, non-multiple-of-block height
        x = np.random.RandomState(0).rand(37, 131).astype(np.float32)
        out = rt.sstencil(_prk_star2(), rt.fromarray(x)).asarray()
        np.testing.assert_allclose(out, _star2_numpy(x), rtol=1e-4, atol=1e-5)

    def test_asymmetric_offsets(self, interpret_mode):
        @rt.stencil
        def shifted(a):
            return a[-1, 0] + a[0, 2]

        x = np.arange(64, dtype=np.float32).reshape(8, 8)
        out = rt.sstencil(shifted, rt.fromarray(x)).asarray()
        e = np.zeros_like(x)
        e[1:, :-2] = x[:-1, :-2] + x[1:, 2:]
        np.testing.assert_allclose(out, e)

    def test_two_input_arrays(self, interpret_mode):
        @rt.stencil
        def mix(a, b):
            return a[0, 0] + 0.5 * (b[-1, 0] + b[1, 0])

        x = np.random.RandomState(1).rand(24, 40).astype(np.float32)
        y = np.random.RandomState(2).rand(24, 40).astype(np.float32)
        out = rt.sstencil(mix, rt.fromarray(x), rt.fromarray(y)).asarray()
        e = np.zeros_like(x)
        e[1:-1, :] = x[1:-1, :] + 0.5 * (y[:-2, :] + y[2:, :])
        np.testing.assert_allclose(out, e, rtol=1e-6)

    def test_numpy_kernel_body(self, interpret_mode):
        @rt.stencil
        def npk(a):
            return np.maximum(a[0, -1], a[0, 1])

        x = np.random.RandomState(3).rand(16, 20).astype(np.float32)
        out = rt.sstencil(npk, rt.fromarray(x)).asarray()
        e = np.zeros_like(x)
        e[:, 1:-1] = np.maximum(x[:, :-2], x[:, 2:])
        np.testing.assert_allclose(out, e)
