"""Tests for the auxiliary subsystems: timing, debug artifacts, pattern
rewrites, constraints, jit/remote, distributed bring-up.

Reference test model: everything end-to-end differential vs NumPy
(/root/reference/ramba/tests/test_distributed_array.py:240-260 run_both).
"""

import os

import numpy as np
import pytest

import ramba_tpu as rt
from tests.helpers import default_rtol, map_dtype, oracle
from ramba_tpu.core import fuser
from ramba_tpu.core.expr import Node
from ramba_tpu.core.rewrite import rewrite_roots


# ---------------------------------------------------------------------------
# timing
# ---------------------------------------------------------------------------


class TestTiming:
    def test_counters_accumulate(self):
        from ramba_tpu.utils import timing

        timing.add_time("unit_test", 1.5)
        timing.add_time("unit_test", 0.5)
        timing.add_sub_time("unit_test", "sub", 0.25)
        snap = timing.get_timing()
        assert snap["timers"]["unit_test"] == (2.0, 2)
        assert snap["sub_timers"][("unit_test", "sub")] == (0.25, 1)

    def test_flush_records_exec_and_per_func(self, monkeypatch):
        from ramba_tpu import common
        from ramba_tpu.utils import timing

        monkeypatch.setattr(common, "timing_level", 1)  # per_func is gated
        timing.reset()
        for _ in range(2):  # 2nd run is a guaranteed compile-cache hit
            a = rt.arange(1000) * 2.0
            rt.sync()
        snap = timing.get_timing()
        assert snap["timers"].get("flush_execute", (0, 0))[1] >= 1
        assert len(snap["per_func"]) >= 1

    def test_summary_prints(self, capsys):
        import io

        from ramba_tpu.utils import timing

        timing.add_time("printable", 0.1)
        buf = io.StringIO()
        timing.timing_summary(file=buf)
        assert "printable" in buf.getvalue()

    def test_timer_context(self):
        from ramba_tpu.utils import timing

        timing.reset()
        with timing.timer("ctx"):
            pass
        assert timing.time_dict["ctx"][1] == 1


# ---------------------------------------------------------------------------
# debug artifacts
# ---------------------------------------------------------------------------


class TestDebug:
    def test_output_dot(self, tmp_path):
        from ramba_tpu.utils import debug

        a = rt.arange(100) + 1.0
        b = rt.sin(a)
        path = tmp_path / "g.dot"
        text = debug.output_dot(str(path))
        assert "digraph" in text
        assert "map" in text
        assert path.exists()
        rt.sync()

    def test_report_pending(self):
        import io

        from ramba_tpu.utils import debug

        rt.sync()
        a = rt.arange(50) * 3
        buf = io.StringIO()
        n = debug.report_pending(file=buf)
        assert n >= 1
        assert "pending" in buf.getvalue()
        rt.sync()
        buf2 = io.StringIO()
        assert debug.report_pending(file=buf2) == 0


# ---------------------------------------------------------------------------
# pattern rewrites (reference: ramba.py:4567-4789)
# ---------------------------------------------------------------------------


class TestRewrites:
    def test_arange_reshape_values(self):
        a = rt.arange(24).reshape(4, 6) + 0
        np.testing.assert_array_equal(a.asarray(),
                                      np.arange(24).reshape(4, 6))

    def test_arange_reshape_rewrites_to_fill(self):
        a = rt.arange(24, dtype=np.float64)
        r = Node("reshape", ((4, 6),), [a.read_expr()])
        (out,) = rewrite_roots([r])
        assert out.op == "fromfunction"
        rt.sync()

    def test_stack_mean_advindex_values(self):
        # the xarray groupby().mean() expansion (docs/index.md:53-58)
        x = np.arange(48, dtype=np.float64).reshape(4, 12)
        labels = np.arange(12) % 3
        X = rt.fromarray(x)
        cols = [np.where(labels == g)[0] for g in range(3)]
        stacked = rt.stack([rt.mean(X[:, idx], axis=1) for idx in cols],
                           axis=1)
        expect = np.stack([x[:, idx].mean(axis=1) for idx in cols], axis=1)
        np.testing.assert_allclose(stacked.asarray(), expect)

    def test_stack_mean_advindex_rewrites_to_segment_reduce(self):
        x = np.arange(48, dtype=np.float64).reshape(4, 12)
        labels = np.arange(12) % 3
        X = rt.fromarray(x)
        cols = [np.where(labels == g)[0] for g in range(3)]
        stacked = rt.stack([rt.mean(X[:, idx], axis=1) for idx in cols],
                           axis=1)
        (out,) = rewrite_roots([stacked.read_expr()])
        ops = _collect_ops(out)
        assert "segment_reduce" in ops
        assert "stack" not in ops
        rt.sync()

    def test_concat_binop_getitem_values(self):
        # the xarray anomaly pattern: x[:, idx_g] - m[g], concatenated
        x = np.arange(60, dtype=np.float64).reshape(5, 12)
        labels = np.arange(12) % 3
        m = np.stack([x[:, labels == g].mean(axis=1) for g in range(3)], 0)
        X, M = rt.fromarray(x), rt.fromarray(m)
        cols = [np.where(labels == g)[0] for g in range(3)]
        parts = [X[:, idx] - M[g][:, None] for g, idx in enumerate(cols)]
        out = rt.concatenate(parts, axis=1)
        # the [:, None] climatology idiom must fire the rewrite
        (r,) = rewrite_roots([out.read_expr()])
        ops = _collect_ops(r)
        assert "concatenate" not in ops
        assert "take" in ops
        expect = np.concatenate(
            [x[:, idx] - m[g][:, None] for g, idx in enumerate(cols)], axis=1
        )
        np.testing.assert_allclose(out.asarray(), expect)

    def test_stack_reduce_duplicate_in_group_no_rewrite(self):
        # duplicates within one group: original counts twice, segment_reduce
        # would count once -> the rewrite must not fire, values must match
        x = np.arange(24, dtype=np.float64).reshape(4, 6)
        X = rt.fromarray(x)
        groups = [np.array([0, 0, 1]), np.array([2, 3, 4, 5])]
        stacked = rt.stack([rt.sum(X[:, i], axis=1) for i in groups], axis=1)
        (r,) = rewrite_roots([stacked.read_expr()])
        assert "segment_reduce" not in _collect_ops(r)
        expect = np.stack([x[:, i].sum(axis=1) for i in groups], axis=1)
        np.testing.assert_allclose(stacked.asarray(), expect)

    def test_concat_binop_misaligned_no_rewrite(self):
        # 1-D-per-group operand against rows grouped on axis 0: trailing
        # broadcast alignment differs before/after -> must not fire
        x = np.arange(60, dtype=np.float64).reshape(12, 5)
        labels = np.arange(12) % 3
        m = np.array([10.0, 20.0, 30.0])
        X, M = rt.fromarray(x), rt.fromarray(m)
        rows = [np.where(labels == g)[0] for g in range(3)]
        parts = [X[idx] - M[g] for g, idx in enumerate(rows)]
        out = rt.concatenate(parts, axis=0)
        expect = np.concatenate(
            [x[idx] - m[g] for g, idx in enumerate(rows)], axis=0
        )
        np.testing.assert_allclose(out.asarray(), expect)

    def test_concat_binop_scalar_groups_rewrites(self):
        # 1-D x grouped on axis 0 with scalar-per-group operand: aligned,
        # fires and stays correct
        x = np.arange(12, dtype=np.float64)
        labels = np.arange(12) % 3
        m = np.array([10.0, 20.0, 30.0])
        X, M = rt.fromarray(x), rt.fromarray(m)
        pos = [np.where(labels == g)[0] for g in range(3)]
        parts = [X[idx] * M[g] for g, idx in enumerate(pos)]
        out = rt.concatenate(parts, axis=0)
        (r,) = rewrite_roots([out.read_expr()])
        assert "concatenate" not in _collect_ops(r)
        expect = np.concatenate(
            [x[idx] * m[g] for g, idx in enumerate(pos)]
        )
        np.testing.assert_allclose(out.asarray(), expect)

    def test_rewrite_disabled_flag(self, monkeypatch):
        from ramba_tpu import common

        monkeypatch.setattr(common, "rewrite_enabled", False)
        a = rt.arange(24).reshape(4, 6) + 0
        np.testing.assert_array_equal(a.asarray(),
                                      np.arange(24).reshape(4, 6))


def _collect_ops(root):
    ops = []
    stack = [root]
    seen = set()
    while stack:
        e = stack.pop()
        if id(e) in seen:
            continue
        seen.add(id(e))
        if isinstance(e, Node):
            ops.append(e.op)
            stack.extend(e.args)
    return ops


# ---------------------------------------------------------------------------
# constraints (reference: ramba.py:5296-5315,9915-9922)
# ---------------------------------------------------------------------------


class TestConstraints:
    def test_smap_axis_constraint(self):
        from ramba_tpu.parallel import constraints

        constraints.clear_constraints()
        a = rt.arange(1024).astype(np.float64)
        b = rt.ones(1024)
        out = rt.smap(lambda x, y: x + y, a, b, axis=0)
        assert len(constraints.get_constraints()) == 1
        np.testing.assert_allclose(out.asarray(),
                                   np.arange(1024) + 1.0)

    def test_add_constraint_2d(self):
        from ramba_tpu.parallel import constraints

        constraints.clear_constraints()
        a = rt.fromarray(np.arange(64, dtype=np.float64).reshape(8, 8))
        b = rt.fromarray(np.ones((8, 8)))
        con = rt.add_constraint([a, b], axis=1)
        assert con.axis == 1
        np.testing.assert_allclose((a * b).asarray(),
                                   np.arange(64).reshape(8, 8))


# ---------------------------------------------------------------------------
# jit / remote (reference: ramba.py:549-874)
# ---------------------------------------------------------------------------


class TestJitRemote:
    def test_jit_on_ndarray(self):
        @rt.jit
        def f(x, y):
            return x * 2 + y

        a = rt.arange(100).astype(np.float64)
        out = f(a, 3.0)
        assert isinstance(out, rt.ndarray)
        np.testing.assert_allclose(out.asarray(), np.arange(100) * 2 + 3)

    def test_jit_plain_args(self):
        @rt.jit
        def f(x):
            return x + 1

        assert int(f(np.int64(1))) == 2

    def test_remote_function(self):
        @rt.remote
        def work(x):
            return x * x

        fut = work.remote(7)
        assert rt.get(fut) == 49
        assert work(3) == 9

    def test_remote_class(self):
        @rt.remote
        class Counter:
            def __init__(self, start):
                self.n = start

            def incr(self, k):
                self.n += k
                return self.n

        c = Counter.remote(10)
        assert rt.get(c.incr.remote(5)) == 15
        assert rt.get([c.incr.remote(1), c.incr.remote(1)]) == [16, 17]


# ---------------------------------------------------------------------------
# distributed bring-up (reference: common.py:49-100, ramba.py:10650-10724)
# ---------------------------------------------------------------------------


class TestDistributed:
    def test_in_driver_and_process_identity(self):
        import jax

        # single host: the one process IS the driver; cross-process leg:
        # exactly rank 0 is (the reference's MPI in_driver gating)
        assert rt.distributed.in_driver() == (jax.process_index() == 0)
        assert rt.distributed.process_count() == jax.process_count()
        assert rt.distributed.process_index() == jax.process_index()

    def test_initialize_noop_without_coordinator(self):
        rt.distributed.initialize()  # must not raise when already up/solo

    def test_global_mesh(self):
        import jax

        m = rt.distributed.global_mesh()
        assert m.devices.size == len(jax.devices())

    def test_local_devices(self):
        import jax

        assert (len(rt.distributed.local_devices())
                == len(jax.devices()) // jax.process_count())


class TestPersistentCache:
    """Reference: RAMBA_CACHE Numba disk cache (ramba.py:177-246) — here the
    XLA compilation cache persisted to disk."""

    def test_cache_dir_created_and_populated(self, tmp_path, monkeypatch):
        import jax

        from ramba_tpu import common

        cache_dir = str(tmp_path / "xla_cache")
        monkeypatch.setenv("RAMBA_CACHE", cache_dir)
        status = common.setup_persistent_cache()
        assert status.path == cache_dir and status.ok, status
        assert status.enabled
        assert os.path.isdir(cache_dir)
        try:
            # a fresh program structure so the executable is actually compiled
            a = rt.arange(257.0)
            b = rt.tanh(a) * 3.0 + rt.arange(257.0)
            b.asarray()
            rt.sync()
            assert len(os.listdir(cache_dir)) >= 1
        finally:
            jax.config.update("jax_compilation_cache_dir", None)

    def test_disabled_by_default(self, monkeypatch):
        from ramba_tpu import common

        monkeypatch.delenv("RAMBA_CACHE", raising=False)
        monkeypatch.setattr(common, "cache_env", None)
        status = common.setup_persistent_cache()
        assert status.path is None and status.ok and not status.enabled
        monkeypatch.setenv("RAMBA_CACHE", "0")
        assert common.setup_persistent_cache().path is None


class TestApiParity:
    """Module-level names from the reference public surface
    (ramba.py:8546-9857) added for completeness."""

    def test_isscalar(self):
        assert rt.isscalar(3) and rt.isscalar(2.5)
        assert not rt.isscalar(np.zeros(3))
        assert rt.isscalar(rt.fromarray(np.float64(2.0)))
        assert not rt.isscalar(rt.arange(4))

    def test_result_type(self):
        a = rt.arange(4).astype(np.int32)
        assert rt.result_type(a, np.float64) == np.result_type(np.int32, np.float64)

    def test_implements_extension(self):
        from ramba_tpu.core.interop import HANDLED_FUNCTIONS

        fn = np.trapezoid if hasattr(np, "trapezoid") else np.trapz
        try:
            @rt.implements(fn)
            def my_trap(y, *args, **kwargs):
                return "custom"

            assert fn(rt.arange(5.0)) == "custom"
        finally:
            HANDLED_FUNCTIONS.pop(fn, None)

    def test_apply_index(self):
        shape = (10, 8, 6)
        dim_shapes, (cindex, axismap) = rt.apply_index(
            shape, (slice(1, 9, 2), 3, slice(None)))
        assert dim_shapes == (4, 6)
        assert axismap == [0, 2]
        assert cindex[1] == slice(3, 4, 1)

    def test_reshape_copy(self):
        a = rt.arange(12.0)
        b = rt.reshape_copy(a, (3, 4))
        b[0, 0] = 99.0
        assert float(a[0]) == 0.0  # copy, not a view
        c = a.reshape_copy(4, 3)
        assert c.shape == (4, 3)

    def test_create_array_with_divisions(self):
        # split-count form
        a = rt.create_array_with_divisions((16, 8), (4, 1), dtype=np.float64)
        assert a.shape == (16, 8) and a.dtype == map_dtype(np.float64)
        # reference (nworkers, 2, ndim) start/end ranges form: 4 row blocks
        div = np.array([[[i * 4, 0], [(i + 1) * 4, 8]] for i in range(4)])
        b = rt.create_array_with_divisions((16, 8), div)
        assert b.shape == (16, 8)
        b[:] = 1.0
        assert float(b.sum()) == 128.0

    def test_fromarray_distribution_forms(self):
        from jax.sharding import PartitionSpec as P

        x = np.arange(64.0).reshape(8, 8)
        for dist in (None, (4, 1), P("d0"), ):
            a = rt.fromarray(x, distribution=dist)
            np.testing.assert_allclose(a.asarray(), x)

    def test_comm_stats(self, capsys):
        rt.reset_timing()
        a = rt.fromarray(np.arange(1000.0))
        a.asarray()
        st = rt.timing.comm_stats
        nbytes = 1000 * np.dtype(map_dtype(np.float64)).itemsize
        assert st["host_to_device_bytes"] >= nbytes
        assert st["device_to_host_bytes"] >= nbytes
        rt.print_comm_stats(file=None)  # prints to stderr

    def test_timing_str_and_passthroughs(self):
        # reference surface: module-level add_time/add_sub_time/time_dict/
        # get_timing_str (ramba.py:985-1019); orphan sub-timers must be
        # visible in reports (review r4)
        rt.reset_timing()
        rt.add_time("flush", 0.25)
        rt.add_sub_time("flush", "compile", 0.1)
        rt.add_sub_time("orphan", "x", 0.1)
        s = rt.get_timing_str(details=True)
        assert "flush: 0.25s(1)" in s and "compile: 0.1s(1)" in s, s
        assert "orphan" in s and "x: 0.1s(1)" in s, s
        assert "flush" in rt.time_dict
        rt.reset_timing()

    def test_numpy_alias_reexports(self):
        # /root/reference/ramba/__init__.py:20 re-exports numpy C-named
        # aliases; drop-in users reference them as ramba.double etc.
        for name in ("byte", "short", "intc", "uint", "half", "single",
                     "double", "longdouble", "csingle", "cdouble"):
            assert getattr(rt, name) is getattr(np, name), name
        assert rt.iinfo(rt.int32).max == 2 ** 31 - 1
        assert rt.finfo(np.float32).eps == np.finfo(np.float32).eps

    def test_reset_timing(self):
        rt.timing.add_time("x", 1.0)
        rt.reset_timing()
        assert "x" not in rt.timing.time_dict


class TestApiParityReviewFixes:
    def test_apply_index_bounds_and_ellipsis(self):
        with pytest.raises(IndexError):
            rt.apply_index((10,), (15,))
        ds, (ci, am) = rt.apply_index((3, 4), (Ellipsis, 2))
        assert ds == (3,) and am == [0] and ci[1] == slice(2, 3, 1)
        ds, _ = rt.apply_index((3, 4), (None, slice(None), 1))
        assert ds == (1, 3)
        ds, (ci, _) = rt.apply_index((5,), (-2,))
        assert ci[0] == slice(3, 4, 1)

    def test_spec_from_splits_subset(self):
        import jax
        from jax.sharding import Mesh

        from ramba_tpu.parallel.mesh import spec_from_splits

        if len(jax.devices()) < 8:
            pytest.skip("needs 8 devices for the (2,2,2) mesh")
        devs = np.array(jax.devices()[:8]).reshape(2, 2, 2)
        mesh = Mesh(devs, axis_names=("a", "b", "c"))
        spec = spec_from_splits((4,), mesh)
        # 4 needs two of the 2-sized axes
        assert spec and isinstance(spec[0], tuple) and len(spec[0]) == 2

    def test_fromarray_distribution_counts_transfer(self):
        rt.reset_timing()
        rt.fromarray(np.arange(4096.0), distribution=(8,))
        assert rt.timing.comm_stats["host_to_device_bytes"] >= 4096 * 8


class TestApplyIndexCanonical:
    def test_negative_step_slice_reusable(self):
        ds, (ci, _) = rt.apply_index((5,), (slice(None, None, -1),))
        assert ds == (5,)
        x = np.arange(5)
        np.testing.assert_array_equal(x[ci[0]], x[::-1])
        ds2, (ci2, _) = rt.apply_index((10,), (slice(8, 2, -2),))
        assert ds2 == (3,)
        np.testing.assert_array_equal(np.arange(10)[ci2[0]],
                                      np.arange(10)[8:2:-2])

class TestAdviceBacklogR2:
    """Regression tests for the round-1 ADVICE items (VERDICT r2 #10)."""

    def test_min_out_positional(self):
        # a.min(0, out) must WRITE out (numpy positional order is
        # (axis, out) for min/max/any/all — no dtype slot)
        a = rt.fromarray(np.arange(12.0).reshape(3, 4))
        out = rt.zeros(4)
        r = a.min(0, out)
        assert r is out
        np.testing.assert_allclose(out.asarray(), [0.0, 1.0, 2.0, 3.0])
        out2 = rt.zeros(3)
        a.max(1, out2)
        np.testing.assert_allclose(out2.asarray(), [3.0, 7.0, 11.0])

    def test_module_level_out_positional(self):
        a = rt.fromarray(np.arange(12.0).reshape(3, 4))
        out = rt.zeros(4)
        assert rt.min(a, 0, out) is out
        np.testing.assert_allclose(out.asarray(), [0.0, 1.0, 2.0, 3.0])
        # sum keeps numpy's (a, axis, dtype, out) order
        out3 = rt.zeros(4)
        assert rt.sum(a, 0, None, out3) is out3
        np.testing.assert_allclose(out3.asarray(), [12.0, 15.0, 18.0, 21.0])

    def test_any_all_out(self):
        a = rt.fromarray(np.array([[True, False], [True, True]]))
        out = rt.zeros(2, dtype=bool)
        assert a.all(0, out) is out
        np.testing.assert_array_equal(out.asarray(), [True, False])

    def test_double_ellipsis_raises(self):
        a = rt.fromarray(np.arange(12.0).reshape(3, 4))
        with pytest.raises(IndexError, match="single ellipsis"):
            a[..., ...]

    def test_pre_freeze_view_stays_writeable(self):
        # numpy: a view taken before the base is frozen keeps its own
        # writeable flag and writes through
        a = rt.fromarray(np.zeros(6))
        v = a[2:5]
        a.flags.writeable = False
        assert v.flags.writeable
        v[0] = 7.0
        np.testing.assert_allclose(a.asarray(), [0, 0, 7.0, 0, 0, 0])
        # but a NEW view of the frozen base is read-only
        w = a[1:3]
        assert not w.flags.writeable
        with pytest.raises(ValueError):
            w[0] = 1.0

    def test_divisions_covers_all_shards(self):
        from ramba_tpu.parallel.shardview import divisions

        a = rt.zeros((64, 64))
        rt.sync()
        d = divisions(a)
        import jax

        assert d.shape[0] == len(jax.devices())
        # the union of shard boxes covers the full array exactly
        total = sum(
            int(np.prod(np.maximum(0, d[i, 1] - d[i, 0])))
            for i in range(d.shape[0])
        )
        assert total == 64 * 64

class TestMultiProcess:
    """The reference CI's mpiexec -n 2 leg (python-package.yml:40-46), as
    jax multi-controller SPMD.  Spawns two fresh processes, so it is gated
    behind RAMBA_TPU_MULTIPROC_TEST=1 to keep the default suite fast.
    The FULL-suite version of this leg is scripts/two_process_suite.py,
    which runs every test cross-process (round-4 verdict #4)."""

    @pytest.mark.skipif(
        not os.environ.get("RAMBA_TPU_MULTIPROC_TEST"),
        reason="2-process smoke spawns fresh processes; run via "
               "RAMBA_TPU_MULTIPROC_TEST=1, or use the full cross-process "
               "leg: scripts/two_process_suite.py",
    )
    def test_two_process_smoke(self):
        import subprocess
        import sys

        script = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts", "two_process_smoke.py",
        )
        r = subprocess.run(
            [sys.executable, "-u", script], capture_output=True, text=True,
            timeout=300,
        )
        assert r.returncode == 0, r.stdout + r.stderr

class TestTraceND:
    def test_trace_matches_numpy(self):
        a = np.arange(24.0).reshape(2, 3, 4)
        for kw in ({}, {"offset": 1}, {"axis1": 1, "axis2": 2},
                   {"offset": -1, "axis1": 0, "axis2": 2}):
            got = rt.trace(rt.fromarray(a), **kw).asarray()
            np.testing.assert_allclose(got, np.trace(a, **{
                "offset": kw.get("offset", 0),
                "axis1": kw.get("axis1", 0),
                "axis2": kw.get("axis2", 1),
            }))
        m = np.arange(16.0).reshape(4, 4)
        assert float(rt.trace(rt.fromarray(m))) == np.trace(m)

class TestDtypePromotionParity:
    """NumPy NEP-50 promotion parity (the reference computes with
    numpy/Numba and inherits these semantics; here numpy's own
    ufunc.resolve_dtypes supplies the loop dtypes under x64)."""

    DTYPES = [np.int8, np.uint8, np.int32, np.int64, np.float32,
              np.float64, np.bool_]

    def test_binop_matrix(self):
        import warnings

        for d1 in self.DTYPES:
            for d2 in self.DTYPES:
                a = np.ones(4, dtype=d1)
                b = np.full(4, 2, dtype=d2)
                for op in ("add", "multiply", "true_divide", "maximum"):
                    with warnings.catch_warnings():
                        warnings.simplefilter("ignore")
                        want = np.asarray(getattr(oracle(), op)(a, b))
                        got = getattr(np, op)(
                            rt.fromarray(a), rt.fromarray(b)
                        ).asarray()
                    assert got.dtype == want.dtype, (op, d1, d2, got.dtype)
                    np.testing.assert_allclose(got, want)

    def test_weak_scalar_promotion(self):
        # NEP 50: int32_arr + python_float -> float64; f32_arr + float -> f32
        # (x32 regime: jax lattice -> f32 for the first case, via oracle)
        orc = oracle()
        x = rt.fromarray(np.ones(4, np.int32))
        assert (x + 2.0).asarray().dtype == np.asarray(
            orc.add(np.ones(4, np.int32), 2.0)).dtype
        y = rt.fromarray(np.ones(4, np.float32))
        assert (y + 2.0).asarray().dtype == np.float32
        assert (x + 2).asarray().dtype == np.int32

    def test_int_division_is_float64(self):
        # (float32 under the x32 regime's jax lattice)
        a = rt.fromarray(np.array([1, 2, 7], np.int32))
        r = (a / rt.fromarray(np.array([2, 4, 2], np.int32))).asarray()
        assert r.dtype == np.asarray(
            oracle().true_divide(np.ones(1, np.int32), np.ones(1, np.int32))
        ).dtype
        np.testing.assert_allclose(r, [0.5, 0.5, 3.5])

class TestViewAliasingEdges:
    """Write-through across gnarly view chains (reference: views share a
    gid and all writes land in the base shards, ramba.py:5545-5565)."""

    @pytest.mark.parametrize("name,mutate", [
        ("neg step write",
         lambda a: a[::-1].__setitem__((0, slice(None)), 99.0)),
        ("reshape view write",
         lambda a: a.reshape(6, 4).__setitem__((2, slice(None)), -1.0)),
        ("chained view write", lambda a: a[1:][1:].__setitem__(0, 5.0)),
        ("transpose slice iadd", lambda a: a.T[2:4].__iadd__(10.0)),
        ("ravel write",
         lambda a: a.reshape(-1).__setitem__(slice(3, 9), 0.0)),
        ("col neg step imul", lambda a: a[:, ::-2].__imul__(2.0)),
        ("newaxis write",
         lambda a: a[:, None, :].__setitem__((1, 0, slice(None)), 7.0)),
    ])
    def test_write_through(self, name, mutate):
        w = np.arange(24.0).reshape(4, 6)
        g = rt.fromarray(w.copy())
        mutate(w)
        mutate(g)
        np.testing.assert_allclose(np.asarray(g), w, err_msg=name)


class TestCumulativePromotion:
    def test_small_int_scans_widen(self):
        # numpy: cumsum/cumprod of sub-word ints promote to int64/uint64
        for dt in (np.int8, np.int16, np.int32, np.uint8, np.bool_):
            a = np.ones(10, dtype=dt)
            for op in ("cumsum", "cumprod"):
                w = np.asarray(getattr(oracle(), op)(a))
                g = getattr(rt, op)(rt.fromarray(a)).asarray()
                assert g.dtype == w.dtype, (op, dt, g.dtype, w.dtype)
                np.testing.assert_array_equal(g, w)

class TestJoinPromotionParity:
    def test_concat_stack_where_mixed_dtypes(self):
        i = np.ones(4, np.int32)
        f = np.ones(4, np.float32)
        for name, fn in [
            ("concat", lambda ap: ap.concatenate(
                [ap.asarray(i), ap.asarray(f)])),
            ("stack", lambda ap: ap.stack(
                [ap.asarray(i), ap.asarray(f)])),
            ("where", lambda ap: ap.where(
                ap.asarray(i) > 0, ap.asarray(i), ap.asarray(f))),
        ]:
            w = np.asarray(fn(oracle()))
            g = np.asarray(fn(rt))
            assert g.dtype == w.dtype, (name, g.dtype, w.dtype)
            np.testing.assert_allclose(g, w)
        # weak scalar in where keeps the array dtype (NEP 50)
        r = rt.where(rt.fromarray(f) > 0, rt.fromarray(f), 0.0).asarray()
        assert r.dtype == np.float32

class TestModfDivmod:
    def test_modf(self):
        v = np.array([1.7, -2.3, 0.5, -0.0])
        wf, wi = np.modf(v)
        gf, gi = rt.modf(rt.fromarray(v))
        np.testing.assert_allclose(gf.asarray(), wf,
                                   rtol=default_rtol(1e-7))
        np.testing.assert_allclose(gi.asarray(), wi)

    def test_divmod(self):
        a = np.array([7, -7, 9])
        b = np.array([3, 3, -4])
        wq, wr = np.divmod(a, b)
        gq, gr = rt.divmod(rt.fromarray(a), rt.fromarray(b))
        np.testing.assert_array_equal(gq.asarray(), wq)
        np.testing.assert_array_equal(gr.asarray(), wr)
