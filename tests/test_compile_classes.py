"""Compile classes, persistent AOT cache, and warm pool (PR 14:
``ramba_tpu/compile/``, RAMBA_COMPILE_CLASSES / RAMBA_CACHE / RAMBA_AOT).

The contract under test, in order of importance:

* **Byte identity** — a bucketed execution (pad to the compile class,
  run at the bucket shape, slice back) must produce byte-identical
  results to the exact-shape execution of the same program, proven by a
  seeded fuzz oracle with RAMBA_VERIFY=strict and memoization on.
* **Safety discipline** — only elementwise programs may bucket; a
  shape-sensitive instruction (flip, reduce, cumulative, ...) bails out
  to an exact-shape compile (``compile.bucket_bailout``), and a forged
  bucket claim (fault site ``compile:bucket``) is caught by the
  ``compile-class`` verify rule *before* any data is touched.
* **Warm start** — a second process sharing a persist cache answers
  from deserialized AOT executables: zero compiles, zero compile
  seconds in its ledger.  Corrupt entries evict and recompile
  (``compile:persist``), never raise.
* **Executable sharing** — a randomized-leading-dim soak under pow2
  keeps the compile-cache hit rate above 95%: many request extents,
  a handful of executables.

The SPMD analog (identical bucket decisions on both ranks, warm phase
answering from the shared cache) is ``scripts/two_process_suite.py
--warmstart-leg``.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax as _jax

import ramba_tpu as rt
from ramba_tpu import common
from ramba_tpu.analyze.findings import ProgramVerificationError
from ramba_tpu.compile import classes, persist, warmpool
from ramba_tpu.core import fuser
from ramba_tpu.observe import events, ledger, registry
from ramba_tpu.resilience import faults

_MULTIPROC = _jax.process_count() > 1
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _isolate():
    """Empty pending set, pow2 classes armed, persist disarmed, no
    faults; env restored manually (not via monkeypatch) so the final
    ``classes.reset()`` re-reads the *restored* environment and nothing
    leaks into other test modules."""
    saved = {k: os.environ.get(k)
             for k in ("RAMBA_COMPILE_CLASSES", "RAMBA_CACHE", "RAMBA_AOT")}
    fuser.flush()
    faults.configure(None)
    os.environ["RAMBA_COMPILE_CLASSES"] = "pow2"
    os.environ.pop("RAMBA_CACHE", None)
    os.environ.pop("RAMBA_AOT", None)
    classes.reset()
    persist.reset()
    yield
    faults.reset()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    classes.reset()
    persist.reset()


def _findings(fs, rule, severity=None):
    return [f for f in fs if f.rule == rule
            and (severity is None or f.severity == severity)]


# ---------------------------------------------------------------------------
# bucket math
# ---------------------------------------------------------------------------


class TestBucketMath:
    def test_pow2(self):
        pol = ("pow2",)
        assert [classes.bucket_for(n, pol) for n in
                (1, 2, 3, 4, 5, 8, 9, 255, 256, 300)] == \
            [1, 2, 4, 4, 8, 8, 16, 256, 256, 512]

    def test_linear(self):
        pol = ("linear", 5)
        assert [classes.bucket_for(n, pol) for n in
                (1, 4, 5, 6, 11, 300)] == [5, 5, 5, 10, 15, 300]

    def test_degenerate_extents_pass_through(self):
        assert classes.bucket_for(0, ("pow2",)) == 0
        assert classes.bucket_for(-3, ("linear", 4)) == -3

    def test_policy_parse(self):
        assert classes._parse("") == ("off",)
        assert classes._parse("off") == ("off",)
        assert classes._parse("0") == ("off",)
        assert classes._parse("pow2") == ("pow2",)
        assert classes._parse("1") == ("pow2",)
        assert classes._parse("linear:16") == ("linear", 16)
        # malformed policies fail safe to exact shapes, never crash
        assert classes._parse("linear:zero") == ("off",)
        assert classes._parse("linear:0") == ("off",)
        assert classes._parse("cubic") == ("off",)


# ---------------------------------------------------------------------------
# planning: who buckets, who bails
# ---------------------------------------------------------------------------


class TestPlanning:
    def test_elementwise_flush_buckets_and_lands_on_span(self):
        base = np.arange(40, dtype=np.float32).reshape(5, 8)
        a = rt.array(base)
        out = np.asarray(a * 2.0 + 1.0)
        np.testing.assert_array_equal(out, base * 2.0 + 1.0)
        snap = classes.snapshot()
        assert snap["planned"] >= 1 and snap["padded"] >= 1, snap
        assert snap["pad_bytes"] > 0 and snap["pad_waste_frac"] > 0
        span = events.last(1, type="flush")[-1]
        assert span.get("compile_class") == ["pow2", 8], span
        assert span.get("pad_waste_bytes", 0) > 0

    def test_class_charged_to_ledger(self):
        a = rt.array(np.ones((5, 8), np.float32))
        np.asarray(rt.expm1(a) * 0.5)
        ks = ledger.snapshot()["kernels"]
        tagged = [k for k in ks.values()
                  if k.get("compile_class") == ["pow2", 8]]
        assert tagged, "no ledger entry carries the compile class"
        assert any(k.get("pad_waste", 0) > 0 for k in tagged)

    def test_decision_recorded_per_fingerprint(self):
        a = rt.array(np.ones((6, 8), np.float32))
        np.asarray(a + 2.5)
        dec = classes.decisions()
        assert ("pow2", 8) in dec.values(), dec

    def test_exact_power_of_two_pads_nothing(self):
        base = np.arange(32, dtype=np.float32).reshape(4, 8)
        p0 = classes.snapshot()["padded"]
        out = np.asarray(rt.array(base) * 3.0)
        np.testing.assert_array_equal(out, base * 3.0)
        snap = classes.snapshot()
        assert snap["planned"] >= 1
        assert snap["padded"] == p0  # bucket == n: plan, but no pad

    def test_shape_sensitive_program_bails_out(self):
        base = np.arange(40, dtype=np.float32).reshape(5, 8)
        b0 = classes.snapshot()["bailouts"]
        r0 = registry.get("compile.bucket_bailout")
        got = float(rt.sum(rt.array(base) * 2.0))
        assert got == pytest.approx(float(np.sum(base * 2.0)))
        assert classes.snapshot()["bailouts"] > b0
        assert registry.get("compile.bucket_bailout") > r0

    def test_broadcast_leaf_not_padded(self):
        x = np.arange(40, dtype=np.float32).reshape(5, 8)
        row = np.arange(8, dtype=np.float32).reshape(1, 8)
        out = np.asarray(rt.array(x) + rt.array(row))
        np.testing.assert_array_equal(out, x + row)
        assert classes.snapshot()["planned"] >= 1

    def test_linear_policy_token(self, monkeypatch):
        monkeypatch.setenv("RAMBA_COMPILE_CLASSES", "linear:4")
        classes.reset()
        base = np.ones((6, 8), np.float32)
        np.asarray(rt.array(base) * 4.0)
        span = events.last(1, type="flush")[-1]
        assert span.get("compile_class") == ["linear:4", 8], span

    def test_off_plans_nothing(self, monkeypatch):
        monkeypatch.setenv("RAMBA_COMPILE_CLASSES", "off")
        classes.reset()
        np.asarray(rt.array(np.ones((5, 8), np.float32)) * 2.0)
        snap = classes.snapshot()
        assert snap["planned"] == 0 and snap["bailouts"] == 0
        span = events.last(1, type="flush")[-1]
        assert "compile_class" not in span


# ---------------------------------------------------------------------------
# byte identity: bucketed vs exact-shape oracle (fuzz)
# ---------------------------------------------------------------------------


_UNARY = [rt.tanh, rt.sin, rt.exp, lambda t: t * 1.5 - 0.25]
_BINARY = [lambda t, u: t + u, lambda t, u: t * u,
           lambda t, u: t - 0.5 * u, rt.maximum]


class TestByteIdentity:
    def test_fuzz_bucketed_matches_exact(self, monkeypatch):
        """Seeded random map chains over random (n, k) leaves, each run
        twice — classes off (oracle) and pow2 (bucketed) — with the
        strict verifier and memoization on.  assert_array_equal is byte
        identity: elementwise rows are computed independently, so the
        pad/slice wrapper must be exact, not approximately right."""
        from ramba_tpu.core import memo

        monkeypatch.setenv("RAMBA_VERIFY", "strict")
        monkeypatch.setenv("RAMBA_MEMO", "1")
        memo.reset()
        rng = np.random.default_rng(1414)
        try:
            for _trial in range(10):
                n = int(rng.integers(1, 34))
                k = int(rng.integers(1, 10))
                base = rng.standard_normal((n, k)).astype(np.float32)
                other = rng.standard_normal((n, k)).astype(np.float32)
                steps = [(int(rng.integers(len(_UNARY))),
                          int(rng.integers(len(_BINARY))))
                         for _ in range(int(rng.integers(1, 4)))]

                def compute():
                    x, y = rt.array(base), rt.array(other)
                    z = x
                    for ui, bi in steps:
                        z = _BINARY[bi](_UNARY[ui](z), y)
                    return np.asarray(z)

                monkeypatch.setenv("RAMBA_COMPILE_CLASSES", "off")
                classes.reset()
                exact = compute()
                monkeypatch.setenv("RAMBA_COMPILE_CLASSES", "pow2")
                classes.reset()
                bucketed = compute()
                np.testing.assert_array_equal(exact, bucketed)
            assert classes.snapshot()["planned"] >= 1
        finally:
            memo.reset()


# ---------------------------------------------------------------------------
# the compile-class verify rule vs a forged bucket claim
# ---------------------------------------------------------------------------


class TestVerifyRule:
    def test_forged_claim_raises_in_strict(self, monkeypatch):
        monkeypatch.setenv("RAMBA_VERIFY", "strict")
        base = np.arange(48, dtype=np.float32).reshape(6, 8)
        a = rt.array(base)
        b = rt.flip(a * 2.0, axis=0)  # flip would read the pad rows
        with faults.inject("compile:bucket", "once"):
            with pytest.raises(ProgramVerificationError) as ei:
                fuser.flush()
        errs = _findings(ei.value.findings, "compile-class", "error")
        assert errs, ei.value.findings
        assert "shape-sensitive" in errs[0].message
        # nothing executed on the forged plan; the retry (fault consumed)
        # bails out to exact shapes and computes the right answer
        monkeypatch.setenv("RAMBA_VERIFY", "0")
        np.testing.assert_array_equal(np.asarray(b),
                                      np.flip(base * 2.0, axis=0))

    def test_forged_claim_routes_down_ladder_in_warn(self, monkeypatch):
        monkeypatch.setenv("RAMBA_VERIFY", "warn")
        base = np.arange(48, dtype=np.float32).reshape(6, 8)
        b = rt.flip(rt.array(base) * 2.0, axis=0)
        with faults.inject("compile:bucket", "once"):
            fuser.flush()
        ev = events.last(8, type="finding")
        assert any(e["rule"] == "compile-class" for e in ev), ev
        # the distrusted flush dropped the plan: exact-shape fallback,
        # correct bytes
        np.testing.assert_array_equal(np.asarray(b),
                                      np.flip(base * 2.0, axis=0))

    def test_honest_bucketed_flush_is_clean_in_strict(self, monkeypatch):
        monkeypatch.setenv("RAMBA_VERIFY", "strict")
        base = np.arange(24, dtype=np.float32).reshape(3, 8)
        out = np.asarray(rt.array(base) * 2.0 + 1.0)  # must not raise
        np.testing.assert_array_equal(out, base * 2.0 + 1.0)
        assert classes.snapshot()["planned"] >= 1


# ---------------------------------------------------------------------------
# persistent AOT cache
# ---------------------------------------------------------------------------


class TestPersistCache:
    def test_disarmed_without_cache_dir(self):
        assert not persist.armed()
        assert persist.snapshot()["dir"] is None

    def test_ramba_aot_zero_disarms(self, tmp_path, monkeypatch):
        monkeypatch.setenv("RAMBA_CACHE", str(tmp_path / "c"))
        monkeypatch.setenv("RAMBA_AOT", "0")
        persist.reconfigure()
        assert not persist.armed()

    def test_aot_roundtrip_serves_without_recompiling(
            self, tmp_path, monkeypatch):
        monkeypatch.setenv("RAMBA_CACHE", str(tmp_path / "cache"))
        persist.reconfigure()
        assert persist.armed(), persist.snapshot()
        # forget executables compiled before the lane was armed — only a
        # fresh compile registers an AOT candidate
        with fuser._cache_lock:
            fuser._compile_cache.clear()
        base = np.arange(40, dtype=np.float32).reshape(5, 8)
        np.asarray(rt.array(base) * 3.0 + 1.0)
        rep = persist.save_topk(4)
        assert rep["stored"] >= 1, rep
        assert persist.snapshot()["bytes_written"] > 0
        # a fresh in-memory cache must answer from disk: is_new stays
        # False, so the ledger sees near-zero compile wall
        with fuser._cache_lock:
            fuser._compile_cache.clear()
        h0 = persist.snapshot()["hits"]
        out = np.asarray(rt.array(base) * 3.0 + 1.0)
        np.testing.assert_array_equal(out, base * 3.0 + 1.0)
        snap = persist.snapshot()
        assert snap["hits"] == h0 + 1, snap
        assert snap["bytes_read"] > 0

    def test_corrupt_entry_evicts_and_recompiles(
            self, tmp_path, monkeypatch):
        monkeypatch.setenv("RAMBA_CACHE", str(tmp_path / "cache"))
        persist.reconfigure()
        with fuser._cache_lock:
            fuser._compile_cache.clear()
        base = np.arange(40, dtype=np.float32).reshape(5, 8)
        np.asarray(rt.array(base) * 7.0)
        assert persist.save_topk(4)["stored"] >= 1
        with fuser._cache_lock:
            fuser._compile_cache.clear()
        c0 = persist.snapshot()["corrupt"]
        with faults.inject("compile:persist", "once"):
            out = np.asarray(rt.array(base) * 7.0)  # must NOT raise
        np.testing.assert_array_equal(out, base * 7.0)
        snap = persist.snapshot()
        assert snap["corrupt"] == c0 + 1, snap
        assert registry.get("compile.persist_corrupt") >= 1
        # the bad entry was evicted from disk; the recompile re-registered
        # the fingerprint as a fresh AOT candidate
        assert snap["candidates"] >= 1


class TestPersistInit:
    def test_cache_status_fields_and_event(self, tmp_path, monkeypatch):
        import jax

        cache_dir = str(tmp_path / "xc")
        monkeypatch.setenv("RAMBA_CACHE", cache_dir)
        try:
            st = common.setup_persistent_cache()
            assert st.ok and st.enabled and st.path == cache_dir, st
            ev = events.last(3, type="compile.persist_init")
            assert ev and ev[-1]["path"] == cache_dir and ev[-1]["ok"]
        finally:
            jax.config.update("jax_compilation_cache_dir", None)

    def test_survives_clear_caches_and_reinit(self, tmp_path, monkeypatch):
        """The PR-3 reset path: jax latches the persistent-cache state on
        first compile; a re-init after ``jax.clear_caches()`` must land
        compiled artifacts in the (re)configured dir."""
        import jax

        cache_dir = str(tmp_path / "xc2")
        monkeypatch.setenv("RAMBA_CACHE", cache_dir)
        try:
            st = common.setup_persistent_cache()
            assert st.ok and st.path == cache_dir, st
            jax.clear_caches()
            st2 = common.setup_persistent_cache()
            assert st2.ok and st2.path == cache_dir, st2
            a = rt.arange(517.0)
            np.asarray(rt.tanh(a) * 3.0 + a)
            assert len(os.listdir(cache_dir)) >= 1
        finally:
            jax.config.update("jax_compilation_cache_dir", None)

    def test_disabled_status_is_ok(self, monkeypatch):
        monkeypatch.delenv("RAMBA_CACHE", raising=False)
        monkeypatch.setattr(common, "cache_env", None)
        st = common.setup_persistent_cache()
        assert st.path is None and st.ok and not st.enabled


# ---------------------------------------------------------------------------
# warm-compile observability + trace-replay warm pool
# ---------------------------------------------------------------------------


class TestWarmObservability:
    def test_warm_scope_tags_ledger_and_perf_report(self):
        from ramba_tpu import diagnostics

        with fuser._cache_lock:
            fuser._compile_cache.clear()
        with ledger.compile_source("warm"):
            a = rt.array(np.arange(24, dtype=np.float32).reshape(3, 8))
            np.asarray(rt.sinh(a) * 1.25)
        ks = ledger.snapshot()["kernels"]
        warm = [k for k in ks.values() if k.get("warm_compiles")]
        assert warm, "no ledger entry tagged source=warm"
        rep = diagnostics.perf_report()
        comp = rep.get("compile")
        assert comp and comp["compiles"]["warm"] >= 1, comp
        assert comp["compiles"]["warm_s"] >= 0.0
        assert comp["classes"]["mode"] == "pow2"

    @pytest.mark.skipif(_MULTIPROC, reason="single-process pipeline test")
    def test_warmpool_replays_trace_through_pipeline(
            self, tmp_path, monkeypatch):
        monkeypatch.setenv("RAMBA_CACHE", str(tmp_path / "cache"))
        persist.reconfigure()
        trace = str(tmp_path / "trace.jsonl")
        saved_path = events._trace_path
        events.configure(trace)
        try:
            a = rt.array(np.arange(32, dtype=np.float32).reshape(4, 8))
            np.asarray(rt.exp(a * 0.125))
        finally:
            events.configure(saved_path)
        assert persist.saved_fingerprints(), "program skeleton not saved"
        # forget the executable; the warm pool must rebuild it from the
        # trace + skeleton, through submit_warm (tagged source=warm)
        with fuser._cache_lock:
            fuser._compile_cache.clear()
        w0 = registry.get("compile.warmpool_submit")
        report = warmpool.warm(trace, top_k=4)
        assert report["submitted"] >= 1, report
        assert report["warmed"] >= 1 and report["failed"] == 0, report
        assert registry.get("compile.warmpool_submit") > w0
        ks = ledger.snapshot()["kernels"]
        assert any(k.get("warm_compiles") for k in ks.values())
        from ramba_tpu import serve

        serve.shutdown()

    def test_trace_report_prints_warm_demand_split(self, tmp_path):
        trace = str(tmp_path / "t.jsonl")
        saved_path = events._trace_path
        events.configure(trace)
        try:
            with fuser._cache_lock:
                fuser._compile_cache.clear()
            a = rt.array(np.arange(16, dtype=np.float32).reshape(2, 8))
            np.asarray(a * 5.0 - 2.0)
        finally:
            events.configure(saved_path)
        r = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "trace_report.py"), trace],
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert r.returncode == 0, r.stderr[-1000:]
        assert "compiles:" in r.stdout and "demand" in r.stdout, r.stdout
        assert "bucketed flushes:" in r.stdout, r.stdout


# ---------------------------------------------------------------------------
# second-process warm start (the acceptance criterion)
# ---------------------------------------------------------------------------


# argv: <phase>.  cold compiles + stores AOT entries; warm (same
# RAMBA_CACHE) must answer from them with zero compiles in its ledger.
_WARMSTART_CHILD = """
import json
import sys
import numpy as np
import ramba_tpu as rt
from ramba_tpu import common
from ramba_tpu.compile import classes, persist
from ramba_tpu.observe import ledger
assert classes.enabled(), 'RAMBA_COMPILE_CLASSES not armed'
common.setup_persistent_cache()
persist.reconfigure()
assert persist.armed(), persist.snapshot()
base = np.arange(48, dtype=np.float32).reshape(6, 8)
got = np.asarray((rt.array(base) * 2.0 + 1.0).asarray())
assert np.array_equal(got, base * 2.0 + 1.0), got
if sys.argv[1] == 'cold':
    rep = persist.save_topk(8)
    assert rep['stored'] + rep['skipped'] >= 1, rep
ks = ledger.snapshot()['kernels'].values()
print(json.dumps({
    'compiles': sum(k['compiles'] for k in ks),
    'compile_s': sum(k['compile_s'] for k in ks),
    'hits': persist.snapshot()['hits'],
    'call_fallbacks': persist.snapshot()['call_fallbacks'],
}))
"""


class TestWarmStart:
    def test_second_process_pays_zero_compiles(self, tmp_path):
        env = dict(os.environ)
        env.update(JAX_PLATFORMS="cpu", RAMBA_COMPILE_CLASSES="pow2",
                   RAMBA_CACHE=str(tmp_path / "cache"), PYTHONPATH=REPO)
        for k in ("RAMBA_AOT", "RAMBA_FAULTS", "RAMBA_TRACE", "RAMBA_MEMO",
                  "RAMBA_VERIFY", "RAMBA_PERF", "RAMBA_TEST_PROCS"):
            env.pop(k, None)
        reports = {}
        for phase in ("cold", "warm"):
            r = subprocess.run(
                [sys.executable, "-c", _WARMSTART_CHILD, phase],
                capture_output=True, text=True, timeout=240,
                cwd=REPO, env=env)
            assert r.returncode == 0, (phase, r.stderr[-2000:])
            reports[phase] = json.loads(r.stdout.strip().splitlines()[-1])
        assert reports["cold"]["compiles"] >= 1, reports
        # the acceptance criterion: near-zero compile wall in the warm
        # process's ledger — here exactly zero, served from AOT entries
        assert reports["warm"]["compiles"] == 0, reports
        assert reports["warm"]["compile_s"] == 0.0, reports
        assert reports["warm"]["hits"] >= 1, reports
        assert reports["warm"]["call_fallbacks"] == 0, reports


# ---------------------------------------------------------------------------
# randomized-shape soak: many extents, a handful of executables
# ---------------------------------------------------------------------------


class TestShapeSoak:
    def test_soak_holds_95_percent_hit_rate(self):
        rng = np.random.default_rng(99)
        h0 = registry.get("fuser.cache_hit")
        m0 = registry.get("fuser.cache_miss")
        p0 = classes.snapshot()["planned"]
        for i in range(240):
            n = int(rng.integers(1, 301))
            base = np.full((n, 4), float(i % 7), np.float32)
            out = np.asarray(rt.array(base) * 2.0 + 1.0)
            assert out.shape == (n, 4)
            if i % 40 == 0:  # spot-check values, not just shapes
                np.testing.assert_array_equal(out, base * 2.0 + 1.0)
        hits = registry.get("fuser.cache_hit") - h0
        misses = registry.get("fuser.cache_miss") - m0
        assert hits + misses >= 240
        rate = hits / (hits + misses)
        # pow2 folds extents 1..300 onto <= 10 buckets: at most ~10
        # compiles across 240 flushes
        assert rate > 0.95, (hits, misses, rate)
        assert classes.snapshot()["planned"] - p0 >= 240
