"""Memory-pressure governor: budget, ledger, spill, admission, chunked rung.

Covers ``ramba_tpu.resilience.memory`` + its fuser integration:

* ``common.parse_bytes`` grammar and the ``RAMBA_HBM_BUDGET`` /
  ``RAMBA_HBM_WATERMARK`` / ``RAMBA_CHUNK_BYTES`` knobs,
* the live-bytes ledger riding the fuser's owner census (incref/decref
  deltas, peak high-water mark),
* host spill + transparent restore-on-touch, asserted bit-exact and via
  the host-boundary transfer counters,
* pre-flush admission control under a tight budget: evict, then route to
  the ``chunked`` rung — result identical to NumPy, with the flush span
  and ``memory.*`` counters recording the decision,
* the budgetless default: the fused fast path runs with zero extra
  transfers and zero governor counters,
* oom-class recovery: evict → drop one rung → retry, and the
  ``bytes=`` fault payload the eviction sizing keys on,
* the byte-bounded segmenter backing the ``chunked`` rung.
"""

import numpy as np
import pytest

import jax as _jax
import ramba_tpu as rt
from ramba_tpu import common, diagnostics
from ramba_tpu.core import fuser
from ramba_tpu.observe import registry
from ramba_tpu.resilience import faults, memory, spill
from ramba_tpu.utils import timing

_MULTIPROC = _jax.process_count() > 1


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    """No leaked fault plans or budget env between tests; fast backoff."""
    monkeypatch.setenv("RAMBA_RETRY_BASE_S", "0.001")
    monkeypatch.delenv("RAMBA_HBM_BUDGET", raising=False)
    monkeypatch.delenv("RAMBA_HBM_WATERMARK", raising=False)
    monkeypatch.delenv("RAMBA_HBM_ESTIMATE", raising=False)
    monkeypatch.delenv("RAMBA_CHUNK_BYTES", raising=False)
    faults.configure(None)
    yield
    faults.reset()


# -- parse_bytes / knobs -----------------------------------------------------


def test_parse_bytes_grammar():
    assert common.parse_bytes("1g") == 1 << 30
    assert common.parse_bytes("512k") == 512 << 10
    assert common.parse_bytes("1.5m") == int(1.5 * (1 << 20))
    assert common.parse_bytes("2kb") == 2048
    assert common.parse_bytes("2kib") == 2048
    assert common.parse_bytes("4T") == 4 << 40
    assert common.parse_bytes("64") == 64
    assert common.parse_bytes(128) == 128
    for bad in ("", "abc", "12q"):
        with pytest.raises(ValueError):
            common.parse_bytes(bad)


def test_budget_watermark_chunk_env(monkeypatch):
    monkeypatch.setenv("RAMBA_HBM_BUDGET", "1m")
    assert memory.budget_bytes() == 1 << 20
    assert memory.watermark_bytes() == int((1 << 20) * 0.9)
    monkeypatch.setenv("RAMBA_HBM_WATERMARK", "0.5")
    assert memory.watermark_bytes() == 1 << 19
    monkeypatch.setenv("RAMBA_HBM_WATERMARK", "700k")
    assert memory.watermark_bytes() == 700 << 10
    monkeypatch.setenv("RAMBA_CHUNK_BYTES", "128k")
    assert memory.chunk_target_bytes() == 128 << 10
    monkeypatch.delenv("RAMBA_CHUNK_BYTES")
    monkeypatch.setenv("RAMBA_HBM_WATERMARK", "0.5")
    assert memory.chunk_target_bytes() == max(1 << 16, (1 << 19) // 4)


def test_no_budget_on_cpu_default():
    # CPU backends report no bytes_limit and the env is clean (fixture):
    # the governor must be disabled, not guessing.
    if memory.device_budget_bytes() is None:
        assert memory.budget_bytes() is None
        assert memory.watermark_bytes() is None


# -- the ledger --------------------------------------------------------------


def test_ledger_tracks_realized_leaves():
    fuser.flush()
    before = memory.ledger.live_bytes
    x = rt.fromarray(np.ones(1024, np.float32))
    rt.sync()
    assert memory.ledger.live_bytes == before + 4096
    assert memory.ledger.peak_live_bytes >= before + 4096
    del x
    assert memory.ledger.live_bytes == before


def test_memory_report_shape():
    fuser.flush()
    x = rt.fromarray(np.ones((32, 32), np.float32))
    rt.sync()
    rep = diagnostics.memory_report(top=100)
    for key in ("budget_bytes", "watermark_bytes", "live_bytes",
                "spilled_bytes", "pinned_bytes", "peak_live_bytes",
                "evictions", "restores", "arrays", "top"):
        assert key in rep, key
    assert rep["arrays"] >= 1
    assert any(r["nbytes"] == 4096 for r in rep["top"])
    assert diagnostics.snapshot()["memory"]["arrays"] >= 1
    del x


# -- spill / restore ---------------------------------------------------------


@pytest.mark.skipif(_MULTIPROC, reason="spill requires fully-addressable "
                    "arrays (single-controller)")
def test_spill_restore_round_trip_with_transfer_counters():
    fuser.flush()
    data = np.random.RandomState(1).rand(64, 64).astype(np.float32)
    x = rt.fromarray(data)
    rt.sync()
    d2h0 = timing.comm_stats["device_to_host_bytes"]
    h2d0 = timing.comm_stats["host_to_device_bytes"]
    restores0 = memory.ledger.restores
    freed = memory.ledger.evict_until(memory.ledger.live_bytes or 1)
    assert freed >= data.nbytes
    assert isinstance(x._expr.value, spill.SpilledArray)
    assert memory.ledger.spilled_bytes >= data.nbytes
    assert timing.comm_stats["device_to_host_bytes"] - d2h0 >= data.nbytes
    # touch restores transparently, bit-exact
    out = np.asarray(x)
    np.testing.assert_array_equal(out, data)
    assert isinstance(x._expr.value, _jax.Array)
    assert memory.ledger.restores == restores0 + 1
    assert timing.comm_stats["host_to_device_bytes"] - h2d0 >= data.nbytes
    del x


@pytest.mark.skipif(_MULTIPROC, reason="spill requires fully-addressable "
                    "arrays (single-controller)")
def test_spilled_leaf_computes_correctly():
    # A chain whose LEAF is currently spilled must flush correctly: the
    # flush leaf-gather restores it before execution.
    fuser.flush()
    data = np.arange(2048, dtype=np.float32)
    x = rt.fromarray(data)
    rt.sync()
    memory.ledger.evict_until(memory.ledger.live_bytes or 1)
    assert isinstance(x._expr.value, spill.SpilledArray)
    got = float(rt.sum(x * 2.0 + 1.0))
    exp = float(np.sum(data.astype(np.float64) * 2.0 + 1.0))
    assert got == pytest.approx(exp, rel=1e-4)
    del x


# -- admission control: the acceptance test ----------------------------------


@pytest.mark.skipif(_MULTIPROC, reason="eviction is asserted "
                    "single-controller; SPMD runs the --memory-leg instead")
def test_tight_budget_evicts_and_routes_chunked(monkeypatch):
    fuser.flush()
    # a cold 256 KB array the governor can evict...
    cold_np = np.random.RandomState(2).rand(256, 256).astype(np.float32)
    cold = rt.fromarray(cold_np)
    # ...and a 64 KB working set whose chain estimate alone exceeds the
    # watermark, so eviction cannot save the fused path.
    x_np = np.random.RandomState(3).rand(128, 128).astype(np.float32)
    x = rt.fromarray(x_np)
    rt.sync()
    monkeypatch.setenv("RAMBA_HBM_BUDGET", "150k")
    monkeypatch.setenv("RAMBA_HBM_ESTIMATE", "analytic")
    ev0 = registry.get("memory.evictions")
    rej0 = registry.get("memory.admission_rejects")

    y = x * 2.0 + 1.0
    z = rt.sqrt(y) + y * 0.5
    got = float(rt.sum(z))

    exp = float(np.sum(np.sqrt(x_np * 2.0 + 1.0) + (x_np * 2.0 + 1.0) * 0.5))
    assert got == pytest.approx(exp, rel=1e-3)
    span = diagnostics.last_flushes(1)[0]
    assert span.get("degraded") == "chunked", span
    assert span.get("admission") == "chunked"
    assert span.get("mem_peak_est", 0) > 0
    assert span.get("segments", 0) >= 2, span
    assert registry.get("memory.evictions") > ev0
    assert registry.get("memory.admission_rejects") == rej0 + 1
    assert isinstance(cold._expr.value, spill.SpilledArray)
    evs = [e for e in diagnostics.snapshot()["events"]
           if e.get("type") == "memory"]
    actions = {e.get("action") for e in evs}
    assert {"admit", "watermark", "spill", "reject"} <= actions, actions
    # the evicted array survives, transparently restored on touch
    np.testing.assert_array_equal(np.asarray(cold), cold_np)
    del x, cold


def test_roomy_budget_admits_fused(monkeypatch):
    fuser.flush()
    monkeypatch.setenv("RAMBA_HBM_BUDGET", "64m")
    monkeypatch.setenv("RAMBA_HBM_ESTIMATE", "analytic")
    rej0 = registry.get("memory.admission_rejects")
    got = float(rt.sum(rt.arange(1024) * 2.0 + 1.0))
    assert got == pytest.approx(float(np.sum(np.arange(1024) * 2.0 + 1.0)),
                                rel=1e-6)
    span = diagnostics.last_flushes(1)[0]
    assert "degraded" not in span
    assert "admission" not in span
    assert registry.get("memory.admission_rejects") == rej0


def test_budget_unset_is_transparent():
    # The documented CPU default: no budget -> the governor never
    # estimates, spills, or transfers.  The only host-boundary traffic is
    # the scalar fetch itself.
    fuser.flush()
    ev0 = registry.get("memory.evictions")
    rs0 = registry.get("memory.restores")
    rej0 = registry.get("memory.admission_rejects")
    h2d0 = timing.comm_stats["host_to_device_bytes"]
    d2h0 = timing.comm_stats["device_to_host_bytes"]
    got = float(rt.sum(rt.arange(2048) * 3.0 + 1.0))
    assert got == pytest.approx(float(np.sum(np.arange(2048) * 3.0 + 1.0)),
                                rel=1e-6)
    span = diagnostics.last_flushes(1)[0]
    assert "degraded" not in span
    assert "admission" not in span
    assert registry.get("memory.evictions") == ev0
    assert registry.get("memory.restores") == rs0
    assert registry.get("memory.admission_rejects") == rej0
    assert timing.comm_stats["host_to_device_bytes"] == h2d0
    # one scalar fetch, nothing array-sized
    assert timing.comm_stats["device_to_host_bytes"] - d2h0 <= 64


# -- oom-class recovery ------------------------------------------------------


def test_classify_oom_is_distinct():
    from ramba_tpu.resilience import retry

    assert retry.classify(faults.InjectedResourceExhausted("x", 1)) == "oom"
    assert retry.classify(RuntimeError("RESOURCE_EXHAUSTED: boom")) == "oom"
    assert retry.classify(RuntimeError("DEADLINE_EXCEEDED")) == "retryable"
    assert retry.classify(RuntimeError("anything else")) == "fatal"


@pytest.mark.skipif(_MULTIPROC, reason="eviction is asserted "
                    "single-controller")
def test_injected_oom_evicts_then_drops_one_rung():
    fuser.flush()
    cold_np = np.random.RandomState(4).rand(128, 128).astype(np.float32)
    cold = rt.fromarray(cold_np)
    rt.sync()
    fuser._compile_cache.clear()
    ev0 = registry.get("memory.evictions")
    with faults.inject("oom", "1"):
        got = float(rt.sum(rt.arange(1024) * 5.0 + 7.0))
    assert got == pytest.approx(float(np.sum(np.arange(1024) * 5.0 + 7.0)),
                                rel=1e-6)
    span = diagnostics.last_flushes(1)[0]
    assert span.get("degraded") == "split"
    assert registry.get("memory.evictions") > ev0
    evs = [e for e in diagnostics.snapshot()["events"]
           if e.get("type") == "memory"]
    assert any(e.get("action") == "oom_evict" for e in evs)
    np.testing.assert_array_equal(np.asarray(cold), cold_np)
    del cold


@pytest.mark.skipif(_MULTIPROC, reason="eviction is asserted "
                    "single-controller")
def test_evict_for_oom_sizes_from_bytes_hint():
    fuser.flush()
    # drain any colder residents left by earlier tests so LRU order below
    # is exactly a-then-b
    memory.ledger.evict_until(memory.ledger.live_bytes or 0)
    a = rt.fromarray(np.ones((64, 64), np.float32))   # 16 KB, colder
    b = rt.fromarray(np.ones((128, 128), np.float32))  # 64 KB, warmer
    rt.sync()
    exc = faults.InjectedResourceExhausted("oom", 1, nbytes=4096)
    freed = memory.evict_for_oom(exc)
    assert freed >= 4096
    # LRU: the colder array went first; the byte hint stopped it there
    assert isinstance(a._expr.value, spill.SpilledArray)
    assert isinstance(b._expr.value, _jax.Array)
    del a, b


def test_fault_bytes_payload():
    faults.configure("oom:once:bytes=1g")
    with pytest.raises(faults.InjectedResourceExhausted) as ei:
        faults.check("oom")
    assert ei.value.bytes == 1 << 30
    assert "allocating 1073741824 bytes" in str(ei.value)
    assert "RESOURCE_EXHAUSTED" in str(ei.value)
    with pytest.raises(ValueError):
        faults.configure("oom:once:bytes=nope")
    with pytest.raises(ValueError):
        faults.configure("oom:once:bytes=1k:bytes=2k")


# -- the byte-bounded segmenter ----------------------------------------------


def _toy_instrs(n):
    # a linear chain: instr i consumes slot i, produces slot i+1 (1 leaf)
    return [("op", None, (i,)) for i in range(n)]


def test_byte_segment_end_bounds_live_bytes():
    instrs = _toy_instrs(6)
    slot_bytes = {i: 100 for i in range(7)}
    # tiny cap: always at least one instruction per segment
    ends = []
    start = 0
    while start < 6:
        end = fuser._byte_segment_end(instrs, 1, start, slot_bytes, 1, 0)
        assert end == start + 1
        ends.append(end)
        start = end
    assert ends == [1, 2, 3, 4, 5, 6]
    # roomy cap: one segment swallows the whole chain
    assert fuser._byte_segment_end(instrs, 1, 0, slot_bytes, 10**9, 0) == 6
    # instruction cap still wins over a roomy byte cap
    assert fuser._byte_segment_end(instrs, 1, 0, slot_bytes, 10**9, 2) == 2
    # mid cap: segments stay under the byte bound
    start = 0
    while start < 6:
        end = fuser._byte_segment_end(instrs, 1, start, slot_bytes, 250, 0)
        assert start < end <= 6
        # live estimate per segment: outputs + first-seen external inputs
        assert (end - start) * 100 + 100 <= 350
        start = end


def test_chunk_bytes_env_drives_segment_count(monkeypatch):
    # No budget needed: RAMBA_CHUNK_BYTES alone sizes the chunked rung —
    # drive it directly through the degradation ladder.
    fuser.flush()
    fuser._compile_cache.clear()
    monkeypatch.setenv("RAMBA_CHUNK_BYTES", "64k")
    n = 8192
    a = rt.arange(n) * 2.0
    b = a + 1.0
    c = rt.sqrt(b) * 0.5
    with faults.active("execute:2:oom", seed=0):
        got = float(rt.sum(c))
    exp = float(np.sum(np.sqrt(np.arange(n) * 2.0 + 1.0) * 0.5))
    assert got == pytest.approx(exp, rel=1e-4)
    span = diagnostics.last_flushes(1)[0]
    # fused oomed, split oomed, chunked ran byte-bounded segments
    assert span.get("degraded") == "chunked", span
    assert span.get("chunk_bytes") == 64 << 10
