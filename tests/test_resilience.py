"""Resilience layer: fault injection, retry policies, degradation ladder.

Covers ``ramba_tpu.resilience`` plus its integrations:

* deterministic fault injection (``RAMBA_FAULTS`` grammar, seeded
  probability modes with reproducible fire patterns),
* retry engine: budgets, exponential backoff determinism, retryable vs
  degrade vs fatal classification, budget exhaustion with the original
  error chained,
* the flush degradation ladder fused → split → chunked → eager → host
  with counters asserted via ``observe.registry`` and the degraded rung
  recorded in the flush span,
* atomic checkpointing (a crashed save never corrupts the published
  checkpoint; ``CheckpointCorruptError`` on unreadable/mismatched
  restores),
* fileio read retries, skeletons' once-per-kernel host-fallback warning,
  and ``distributed.initialize`` connect retry (subprocess),
* the acceptance workload: ``RAMBA_FAULTS=compile:once`` in a subprocess
  completes correctly with ``resilience.retries`` >= 1 and a degradation
  event in the ``RAMBA_TRACE`` JSONL that ``trace_report.py`` renders.
"""

import json
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

import jax as _jax
import ramba_tpu as rt
from ramba_tpu import common, diagnostics
from ramba_tpu.core import fuser
from ramba_tpu.observe import registry
from ramba_tpu.resilience import faults, retry

_MULTIPROC = _jax.process_count() > 1


@pytest.fixture(autouse=True)
def _fast_clean_faults(monkeypatch):
    """No leaked fault plans between tests, and near-zero backoff so
    retry-path tests stay fast."""
    monkeypatch.setenv("RAMBA_RETRY_BASE_S", "0.001")
    faults.configure(None)
    yield
    faults.reset()  # re-arm from env (unset in tier-1 -> disarmed)


def _fires(site, n):
    out = []
    for _ in range(n):
        try:
            faults.check(site)
            out.append(False)
        except faults.InjectedFault:
            out.append(True)
    return out


# -- faults.py ---------------------------------------------------------------


def test_fault_modes():
    faults.configure("a:once,b:2,c:after=2,d:always")
    assert _fires("a", 3) == [True, False, False]
    assert _fires("b", 4) == [True, True, False, False]
    assert _fires("c", 4) == [False, False, True, True]
    assert _fires("d", 3) == [True, True, True]
    assert _fires("unarmed", 2) == [False, False]
    st = faults.stats()
    assert st["a"] == {"calls": 3, "fired": 1}
    assert st["d"] == {"calls": 3, "fired": 3}
    faults.configure(None)
    assert not faults.enabled()


def test_probability_mode_is_deterministic():
    def pattern(seed):
        faults.configure("p:0.5", seed=seed)
        return _fires("p", 100)

    p1, p2 = pattern(7), pattern(7)
    assert p1 == p2, "same seed must reproduce the exact fire pattern"
    assert 20 <= sum(p1) <= 80, f"p=0.5 fired {sum(p1)}/100 times"
    assert pattern(8) != p1, "different seed must change the pattern"


def test_bad_spec_rejected_strict_warned_from_env():
    with pytest.raises(ValueError):
        faults.configure("compile")  # no mode
    with pytest.raises(ValueError):
        faults.configure("compile:sometimes")
    with pytest.raises(ValueError):
        faults.configure("compile:1.5")  # probability out of range
    with pytest.warns(UserWarning, match="malformed"):
        faults.configure("compile:sometimes,execute:once", strict=False)
    assert _fires("execute", 1) == [True]  # good chunk still armed


def test_oom_site_and_kinds():
    faults.configure("oom:once")
    with pytest.raises(faults.InjectedResourceExhausted,
                       match="RESOURCE_EXHAUSTED"):
        faults.check("oom")
    faults.configure("x:once:fatal")
    with pytest.raises(faults.InjectedFault) as ei:
        faults.check("x")
    assert not ei.value.retryable


def test_inject_context_restores_previous_plan():
    faults.configure("compile:always")
    with faults.inject("compile", "once"):
        assert _fires("compile", 2) == [True, False]
    assert _fires("compile", 2) == [True, True]  # "always" restored
    faults.configure(None)
    with faults.inject("execute", "once"):
        assert _fires("execute", 1) == [True]
    assert not faults.enabled()


# -- retry.py ----------------------------------------------------------------


def test_classify():
    assert retry.classify(ValueError("bad operand")) == "fatal"
    assert retry.classify(TypeError("no")) == "fatal"
    assert retry.classify(TimeoutError("slow")) == "retryable"
    assert retry.classify(ConnectionResetError()) == "retryable"
    assert retry.classify(FileNotFoundError("gone")) == "fatal"
    assert retry.classify(PermissionError("no")) == "fatal"
    assert retry.classify(OSError("disk hiccup")) == "retryable"
    # real and injected RESOURCE_EXHAUSTED are the distinct oom class:
    # recoverable by eviction, never retried blindly in place
    assert retry.classify(
        RuntimeError("RESOURCE_EXHAUSTED: out of memory allocating")
    ) == "oom"
    assert retry.classify(RuntimeError("UNAVAILABLE: socket closed")) \
        == "retryable"
    # lowercase English prose must NOT look like a gRPC status code
    assert retry.classify(
        RuntimeError("the host fallback is unavailable under "
                     "multi-controller execution")
    ) == "fatal"
    assert retry.classify(retry.RetryBudgetExhausted("x")) == "degrade"
    assert retry.classify(faults.InjectedFault("s", 1)) == "retryable"
    assert retry.classify(faults.InjectedResourceExhausted("s", 1)) \
        == "oom"


def test_retry_recovers_and_records_health():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise TimeoutError("transient blip")
        return "ok"

    before = registry.get("resilience.retries.unit_site")
    assert retry.call("unit_site", flaky) == "ok"
    assert calls["n"] == 3
    assert registry.get("resilience.retries.unit_site") == before + 2
    hs = [e for e in diagnostics.health_events(50)
          if e.get("source") == "unit_site"]
    assert hs and hs[-1]["outcome"] == "recovered" \
        and hs[-1]["retries"] == 2


def test_retry_budget_exhausted_chains_cause(monkeypatch):
    monkeypatch.setenv("RAMBA_RETRY_UNIT_X_ATTEMPTS", "2")
    calls = {"n": 0}

    def always_down():
        calls["n"] += 1
        raise TimeoutError("still down")

    before = registry.get("resilience.retry_exhausted.unit.x")
    with pytest.raises(retry.RetryBudgetExhausted) as ei:
        retry.call("unit.x", always_down)
    assert calls["n"] == 2, "per-site env budget must cap attempts"
    assert isinstance(ei.value.__cause__, TimeoutError)
    assert "still down" in str(ei.value)
    assert registry.get("resilience.retry_exhausted.unit.x") == before + 1


def test_fatal_error_not_retried():
    calls = {"n": 0}

    def broken():
        calls["n"] += 1
        raise ValueError("programming error")

    before = registry.get("resilience.retries")
    with pytest.raises(ValueError, match="programming error"):
        retry.call("unit_fatal", broken)
    assert calls["n"] == 1, "fatal errors must propagate unretried"
    assert registry.get("resilience.retries") == before


def test_backoff_deterministic_and_capped():
    pol = retry.RetryPolicy(attempts=6, base_s=0.1, max_s=0.3,
                            jitter=0.5, seed=7)
    d1 = [pol.delay("site", a) for a in range(1, 6)]
    assert d1 == [pol.delay("site", a) for a in range(1, 6)]
    assert all(d <= 0.3 * 1.25 + 1e-12 for d in d1), d1
    assert all(d > 0 for d in d1)
    other = retry.RetryPolicy(attempts=6, base_s=0.1, max_s=0.3,
                              jitter=0.5, seed=8)
    assert d1[0] != other.delay("site", 1), "jitter must depend on the seed"
    assert retry.RetryPolicy(base_s=0.0).delay("site", 1) == 0.0


# -- the flush degradation ladder -------------------------------------------


def _chain(scale, offset, n=1024):
    a = rt.arange(n) * scale + offset
    return float(rt.sum(a))


def _expect(scale, offset, n=1024):
    return float(np.sum(np.arange(n) * scale + offset))


def test_flush_retries_through_injected_compile_fault():
    fuser.flush()
    fuser._compile_cache.clear()
    before = registry.get("resilience.retries.flush")
    with faults.inject("compile", "once"):
        got = _chain(3.0, 2.0)
    assert got == pytest.approx(_expect(3.0, 2.0), rel=1e-6)
    assert registry.get("resilience.retries.flush") >= before + 1
    evs = diagnostics.resilience_events(50)
    assert any(e["type"] == "fault" and e["site"] == "compile" for e in evs)
    assert any(e["type"] == "degrade" and e.get("action") == "retry"
               and e.get("site") == "flush" for e in evs)
    span = diagnostics.last_flushes(1)[0]
    assert "degraded" not in span, "an in-place retry is not a rung change"


def test_ladder_split_on_injected_oom():
    fuser.flush()
    fuser._compile_cache.clear()
    before_steps = registry.get("resilience.degrade.split")
    before_rec = registry.get("resilience.degrade_recovered")
    with faults.inject("oom", "1"):
        got = _chain(5.0, 7.0)
    assert got == pytest.approx(_expect(5.0, 7.0), rel=1e-6)
    assert registry.get("resilience.degrade.split") == before_steps + 1
    assert registry.get("resilience.degrade_recovered") == before_rec + 1
    span = diagnostics.last_flushes(1)[0]
    assert span.get("degraded") == "split"
    evs = diagnostics.resilience_events(50)
    assert any(e.get("action") == "rung" and e.get("to") == "split"
               for e in evs)
    assert any(e.get("action") == "recovered" and e.get("rung") == "split"
               for e in evs)


@pytest.mark.skipif(_MULTIPROC, reason="deep rungs are asserted "
                    "single-controller; multi-host keeps fused/split")
def test_ladder_reaches_eager(monkeypatch):
    monkeypatch.setenv("RAMBA_RETRY_ATTEMPTS", "2")
    fuser.flush()
    fuser._compile_cache.clear()
    with faults.inject("compile", "always"):
        got = _chain(3.5, 1.0)
    assert got == pytest.approx(_expect(3.5, 1.0), rel=1e-6)
    span = diagnostics.last_flushes(1)[0]
    assert span.get("degraded") == "eager"
    assert registry.get("resilience.degrade.eager") >= 1
    # both the fused and split rungs exhausted their budgets first
    assert registry.get("resilience.retry_exhausted.flush") >= 2


@pytest.mark.skipif(_MULTIPROC, reason="host rung is single-controller only")
def test_ladder_reaches_host(monkeypatch):
    monkeypatch.setenv("RAMBA_RETRY_ATTEMPTS", "2")
    fuser.flush()
    fuser._compile_cache.clear()
    with faults.active("compile:always,eager:always"):
        got = _chain(2.0, -3.0, n=512)
    assert got == pytest.approx(_expect(2.0, -3.0, n=512), rel=1e-6)
    span = diagnostics.last_flushes(1)[0]
    assert span.get("degraded") == "host"
    assert registry.get("resilience.degrade.host") >= 1
    evs = diagnostics.resilience_events(50)
    rungs = [e.get("to") for e in evs if e.get("action") == "rung"]
    assert "host" in rungs


def test_fatal_flush_errors_skip_the_ladder():
    # A fatal (programming) error must propagate unchanged from the fused
    # rung — no retries, no rung transitions.
    fuser.flush()
    before = registry.prefixed("resilience.")
    with faults.inject("compile", "once", kind="fatal"):
        fuser._compile_cache.clear()
        with pytest.raises(faults.InjectedFault):
            _chain(9.0, 9.0)
    after = registry.prefixed("resilience.")
    # only injection + quarantine accounting moved; no retry/degrade
    # counters fired
    moved = {k for k in after if after[k] != before.get(k, 0)}
    assert moved <= {"resilience.fault_injected",
                     "resilience.fault_injected.compile",
                     "resilience.flush_quarantined"}, moved


def test_failed_flush_quarantines_roots():
    # One broken pending expression must not poison every later flush:
    # the failed program's roots leave the pending registry (counted as
    # resilience.flush_quarantined), unrelated work proceeds untouched,
    # and a quarantined array still materializes on demand by
    # re-attempting its own graph alone.
    fuser.flush()
    a = rt.arange(256) * 3.0
    fuser._compile_cache.clear()
    before = registry.get("resilience.flush_quarantined")
    with faults.inject("compile", "once", kind="fatal"):
        with pytest.raises(faults.InjectedFault):
            np.asarray(a)
    assert registry.get("resilience.flush_quarantined") > before
    # the pending registry no longer carries the failed program's roots,
    # so an unrelated computation flushes cleanly
    got = _chain(2.0, 1.0, n=128)
    assert got == pytest.approx(_expect(2.0, 1.0, n=128), rel=1e-6)
    # and the quarantined array self-heals when touched again (the fault
    # was one-shot; its graph re-runs alone and succeeds)
    np.testing.assert_allclose(np.asarray(a), np.arange(256) * 3.0)


def test_no_faults_means_zero_resilience_counters():
    fuser.flush()
    before = registry.prefixed("resilience.")
    got = _chain(1.5, -2.0, n=3000)
    assert got == pytest.approx(_expect(1.5, -2.0, n=3000), rel=1e-6)
    assert registry.prefixed("resilience.") == before


def test_rewrite_crash_degrades_to_unrewritten_graph():
    if not common.rewrite_enabled:
        pytest.skip("rewrites disabled in this regime")
    fuser.flush()
    before = registry.get("resilience.rewrite_bypassed")
    with faults.inject("rewrite", "once"):
        out = np.asarray(rt.arange(64).reshape(8, 8))
    np.testing.assert_allclose(out, np.arange(64).reshape(8, 8))
    assert registry.get("resilience.rewrite_bypassed") == before + 1


# -- checkpoint.py -----------------------------------------------------------


def _ck(tmp_path, name):
    return str(tmp_path / name)


def test_checkpoint_failed_save_preserves_published(tmp_path, monkeypatch):
    pytest.importorskip("orbax.checkpoint")
    from ramba_tpu import checkpoint

    monkeypatch.setenv("RAMBA_RETRY_ATTEMPTS", "2")
    p = _ck(tmp_path, "ck_atomic")
    w = rt.arange(100) * 1.0
    checkpoint.save(p, {"w": w})
    # crash-mid-write: every attempt of the re-save fails; the PUBLISHED
    # checkpoint must keep the original contents
    with faults.inject("checkpoint_io", "always"):
        with pytest.raises(retry.RetryBudgetExhausted):
            checkpoint.save(p, {"w": rt.arange(100) * 3.0}, force=True)
    back = checkpoint.restore(p)
    np.testing.assert_allclose(np.asarray(back["w"]), np.arange(100) * 1.0)
    # crash debris at the staging path must not block the next save
    junk = p + ".ramba-tmp"
    os.makedirs(junk, exist_ok=True)
    with open(os.path.join(junk, "partial"), "w") as f:
        f.write("torn write")
    checkpoint.save(p, {"w": rt.arange(100) * 3.0}, force=True)
    back2 = checkpoint.restore(p)
    np.testing.assert_allclose(np.asarray(back2["w"]), np.arange(100) * 3.0)
    assert not os.path.exists(junk)


def test_checkpoint_save_refuses_overwrite_without_force(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    from ramba_tpu import checkpoint

    p = _ck(tmp_path, "ck_nof")
    checkpoint.save(p, {"w": rt.arange(16) * 1.0})
    with pytest.raises(ValueError, match="force=True"):
        checkpoint.save(p, {"w": rt.arange(16) * 2.0})


def test_checkpoint_io_retries_transient_fault(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    from ramba_tpu import checkpoint

    p = _ck(tmp_path, "ck_retry")
    before = registry.get("resilience.retries.checkpoint_io")
    with faults.inject("checkpoint_io", "once"):
        checkpoint.save(p, {"w": rt.arange(32) * 2.0})
    assert registry.get("resilience.retries.checkpoint_io") == before + 1
    back = checkpoint.restore(p)
    np.testing.assert_allclose(np.asarray(back["w"]), np.arange(32) * 2.0)


def test_checkpoint_restore_corrupt_raises_clear_error(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    from ramba_tpu import checkpoint

    with pytest.raises(checkpoint.CheckpointCorruptError,
                       match="no checkpoint directory"):
        checkpoint.restore(_ck(tmp_path, "ck_missing"))
    empty = tmp_path / "ck_empty"
    empty.mkdir(exist_ok=True)
    with pytest.raises(checkpoint.CheckpointCorruptError):
        checkpoint.restore(str(empty))


def test_checkpoint_restore_target_mismatch(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ramba_tpu import checkpoint
    from ramba_tpu.parallel import mesh as _mesh

    p = _ck(tmp_path, "ck_tgt")
    w = rt.arange(64) * 1.0
    checkpoint.save(p, {"w": w})
    saved_dtype = np.asarray(w).dtype
    sh = NamedSharding(_mesh.get_mesh(), P())
    wrong_shape = jax.ShapeDtypeStruct((32,), saved_dtype, sharding=sh)
    with pytest.raises(checkpoint.CheckpointCorruptError):
        checkpoint.restore(p, {"w": wrong_shape})
    ok = jax.ShapeDtypeStruct((64,), saved_dtype, sharding=sh)
    with pytest.raises(checkpoint.CheckpointCorruptError):
        checkpoint.restore(p, {"w": ok, "extra": ok})  # structure mismatch


@pytest.mark.skipif(_MULTIPROC, reason="spill requires fully-addressable "
                    "arrays (single-controller)")
def test_checkpoint_of_spilled_array_round_trips(tmp_path):
    # An array the memory governor evicted to host must still checkpoint:
    # the save path touches the leaf, which transparently restores it to
    # the device, and the bytes round-trip exactly.
    pytest.importorskip("orbax.checkpoint")
    from ramba_tpu import checkpoint
    from ramba_tpu.resilience import memory, spill

    fuser.flush()
    data = np.arange(512, dtype=np.float64) * 1.5
    w = rt.fromarray(data)
    rt.sync()
    assert isinstance(w._expr.value, _jax.Array)
    freed = memory.ledger.evict_until(memory.ledger.live_bytes or 1)
    assert freed > 0, "nothing was spilled"
    assert isinstance(w._expr.value, spill.SpilledArray)
    p = _ck(tmp_path, "ck_spilled")
    checkpoint.save(p, {"w": w})
    # the save touched the leaf -> it is resident again
    assert isinstance(w._expr.value, _jax.Array)
    back = checkpoint.restore(p)
    np.testing.assert_array_equal(np.asarray(back["w"]), np.asarray(w))
    np.testing.assert_array_equal(np.asarray(w), data.astype(np.asarray(w).dtype))


# -- fileio ------------------------------------------------------------------


def test_fileio_read_retries_transient_fault(tmp_path):
    from ramba_tpu import fileio

    rank = os.environ.get("RAMBA_TEST_PROC_ID", "0")
    p = tmp_path / f"fileio_retry_r{rank}.npy"
    data = np.arange(4096, dtype=np.float32)
    np.save(p, data)
    before = registry.get("resilience.retries.fileio")
    with faults.inject("fileio", "once"):
        out = np.asarray(fileio.load(str(p)))
    np.testing.assert_allclose(out, data)
    assert registry.get("resilience.retries.fileio") >= before + 1


# -- skeletons: once-per-kernel host-fallback warning ------------------------


@pytest.mark.skipif(_MULTIPROC,
                    reason="host fallback is single-controller only")
def test_host_fallback_warns_once_per_kernel():
    from ramba_tpu import skeletons

    def countdown(x):
        n = x
        while n > 0:
            n = n - 1.0
        return n

    def countup(x):
        n = x
        while n < 0:
            n = n + 1.0
        return n

    skeletons.reset_fallback_warnings()
    with pytest.warns(UserWarning, match="countdown.*host evaluation"):
        np.asarray(rt.smap(countdown, [1.5, -1.0]))
    assert countdown in skeletons.fallback_warned_kernels()
    # same kernel again (different shape -> fresh trace): no second warning
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        np.asarray(rt.smap(countdown, [0.5, 2.0, 1.0]))
    assert not [w for w in caught if "host evaluation" in str(w.message)]
    # a DIFFERENT kernel falling back still warns (a global flag wouldn't)
    with pytest.warns(UserWarning, match="countup.*host evaluation"):
        np.asarray(rt.smap(countup, [-1.5, 1.0]))
    # the reset hook re-arms the first kernel
    skeletons.reset_fallback_warnings()
    assert not skeletons.fallback_warned_kernels()
    with pytest.warns(UserWarning, match="host evaluation"):
        np.asarray(rt.smap(countdown, [2.5]))


# -- distributed bring-up ----------------------------------------------------


def test_init_timeout_env(monkeypatch):
    from ramba_tpu.parallel import distributed

    monkeypatch.delenv("RAMBA_INIT_TIMEOUT_S", raising=False)
    assert distributed._init_kwargs({}) == {}
    monkeypatch.setenv("RAMBA_INIT_TIMEOUT_S", "7")
    assert distributed._init_kwargs({}) == {"initialization_timeout": 7}
    assert distributed._init_kwargs({"initialization_timeout": 3}) == \
        {"initialization_timeout": 3}  # explicit kwarg wins
    monkeypatch.setenv("RAMBA_INIT_TIMEOUT_S", "bogus")
    assert distributed._init_kwargs({}) == {}
    monkeypatch.setenv("RAMBA_INIT_TIMEOUT_S", "0")
    assert distributed._init_kwargs({}) == {}


def _scrubbed_env():
    env = dict(os.environ)
    for k in ("RAMBA_TEST_PROCS", "RAMBA_TEST_PROC_ID", "RAMBA_TEST_COORD",
              "RAMBA_TEST_SHARED_TMP", "RAMBA_PROFILE_DIR", "RAMBA_TRACE",
              "RAMBA_FAULTS"):
        env.pop(k, None)
    env["JAX_PLATFORMS"] = "cpu"
    return env


def test_init_connect_retries_then_chains_cause():
    # Subprocess: initialize() early-returns in-process once the backend is
    # up, so the connect path only exists pre-first-computation.  The
    # injected fault fires BEFORE jax dials the (bogus) coordinator.
    code = (
        "from ramba_tpu.parallel import distributed\n"
        "from ramba_tpu.resilience import faults, retry\n"
        "from ramba_tpu import diagnostics\n"
        "try:\n"
        "    distributed.initialize(coordinator_address='127.0.0.1:1',\n"
        "                           num_processes=2, process_id=0)\n"
        "except retry.RetryBudgetExhausted as e:\n"
        "    assert isinstance(e.__cause__, faults.InjectedFault), e.__cause__\n"
        "    c = diagnostics.counters()\n"
        "    assert c.get('resilience.retries.init_connect', 0) >= 1, c\n"
        "    hs = [h for h in diagnostics.health_events(20)\n"
        "          if h.get('source') == 'distributed_init']\n"
        "    assert hs and hs[-1]['outcome'] == 'error', hs\n"
        "    assert 'InjectedFault' in hs[-1].get('cause', ''), hs[-1]\n"
        "    print('INIT_RETRY_OK')\n"
        "else:\n"
        "    raise SystemExit('initialize unexpectedly succeeded')\n"
    )
    env = _scrubbed_env()
    env["RAMBA_FAULTS"] = "init_connect:always"
    env["RAMBA_RETRY_ATTEMPTS"] = "2"
    env["RAMBA_RETRY_BASE_S"] = "0"
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "INIT_RETRY_OK" in r.stdout


# -- acceptance: env-driven fault + trace + report ---------------------------


def test_compile_once_env_trace_and_report(tmp_path):
    rank = os.environ.get("RAMBA_TEST_PROC_ID", "0")
    path = tmp_path / f"trace_faults_{rank}.jsonl"
    code = (
        "import numpy as np\n"
        "import ramba_tpu as rt\n"
        "a = rt.arange(4096) * 2.0 + 1.0\n"
        "s = float(rt.sum(a))\n"
        "exp = float(np.sum(np.arange(4096) * 2.0 + 1.0))\n"
        "assert abs(s - exp) <= 1e-6 * abs(exp), (s, exp)\n"
        "from ramba_tpu import diagnostics\n"
        "c = diagnostics.counters()\n"
        "assert c.get('resilience.retries', 0) >= 1, c\n"
        "print('RETRIES=%d' % c['resilience.retries'])\n"
    )
    env = _scrubbed_env()
    env["RAMBA_FAULTS"] = "compile:once"
    env["RAMBA_RETRY_BASE_S"] = "0.001"
    env["RAMBA_TRACE"] = str(path)
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    assert int(r.stdout.strip().rsplit("RETRIES=", 1)[1]) >= 1

    evs = [json.loads(ln) for ln in path.read_text().splitlines()
           if ln.strip()]
    assert any(e.get("type") == "fault" and e.get("site") == "compile"
               for e in evs)
    assert any(e.get("type") == "degrade" and e.get("action") == "retry"
               and e.get("site") == "flush" for e in evs)

    rep = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "scripts",
                      "trace_report.py"), str(path)],
        capture_output=True, text=True, timeout=120,
    )
    assert rep.returncode == 0, rep.stderr[-2000:]
    assert "degradation timeline" in rep.stdout
    assert "degradation totals:" in rep.stdout
    assert "retry" in rep.stdout
