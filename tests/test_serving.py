"""Serving subsystem: session-scoped flush streams + async compile pipeline.

Covers ``ramba_tpu.serve`` and the fuser's stream refactor:

* ``FlushStream`` isolation — one stream's pending work, threshold
  counter, and quarantine scope never leak into another stream (or the
  default stream),
* the per-stream ``max_pending_ops`` auto-flush (and the ``on_threshold``
  hook serving sessions use to route threshold flushes async),
* ``RoundRobin`` fairness: FIFO within a tenant, rotation between
  tenants, head-only fingerprint coalescing,
* the async pipeline: ticket resolution, error propagation (an enqueued
  flush fails exactly like a synchronous one, just later), coalesced
  batch dispatch,
* per-tenant quota admission routing an over-quota flush to the chunked
  rung without touching other tenants,
* thread-safety regression hammers for the counters registry, event
  ring, and kernel cost ledger (8 writer threads, exact final counts),
* the acceptance soak: >= 8 concurrent sessions with mixed shapes under
  seeded fault injection produce byte-identical results vs single-stream
  execution, with zero cross-tenant quarantine bleed.

Threaded tests are single-controller only: concurrent flush ordering is
nondeterministic across threads, which SPMD collectives cannot tolerate
(the deterministic SPMD story is ``two_process_suite.py --serving-leg``).
"""

import io
import threading

import numpy as np
import pytest

import jax as _jax
import ramba_tpu as rt
from ramba_tpu import diagnostics, serve
from ramba_tpu.core import fuser
from ramba_tpu.core.expr import Const
from ramba_tpu.observe import events, ledger, registry
from ramba_tpu.resilience import faults
from ramba_tpu.serve.fairness import RoundRobin
from ramba_tpu.serve.pipeline import CompilePipeline

_MULTIPROC = _jax.process_count() > 1

spmd_skip = pytest.mark.skipif(
    _MULTIPROC,
    reason="threaded serving is single-controller; SPMD uses --serving-leg",
)


@pytest.fixture(autouse=True)
def _clean_serving(monkeypatch):
    """Fast retries, no leaked faults, no leaked pipeline worker, and no
    half-open streams bleeding pending work into the next test."""
    monkeypatch.setenv("RAMBA_RETRY_BASE_S", "0.001")
    faults.configure(None)
    yield
    serve.shutdown()
    faults.reset()
    fuser.sync()


# -- RoundRobin --------------------------------------------------------------


def test_roundrobin_fifo_within_tenant():
    q = RoundRobin()
    for i in range(5):
        q.push("a", ("a", i))
    got = [q.pop_group(1, timeout=0)[0] for _ in range(5)]
    assert got == [("a", i) for i in range(5)]
    assert q.pop_group(1, timeout=0) == []


def test_roundrobin_rotates_between_tenants():
    q = RoundRobin()
    for i in range(3):
        q.push("a", ("a", i))
    q.push("b", ("b", 0))
    q.push("c", ("c", 0))
    order = [q.pop_group(1, timeout=0)[0] for _ in range(5)]
    # b and c each wait at most one rotation despite a's backlog
    assert order == [("a", 0), ("b", 0), ("c", 0), ("a", 1), ("a", 2)]


def test_roundrobin_coalesces_matching_heads_only():
    q = RoundRobin()
    fp = {("a", 0): "X", ("a", 1): "X", ("a", 2): "Y", ("a", 3): "X",
          ("b", 0): "X"}
    for item in [("a", 0), ("a", 1), ("a", 2), ("a", 3)]:
        q.push("a", item)
    q.push("b", ("b", 0))
    g1 = q.pop_group(8, fingerprint_of=fp.get, timeout=0)
    # a's two consecutive X heads coalesce, plus b's matching head; a's
    # trailing X is BEHIND Y so taking it would break a's FIFO order
    assert g1 == [("a", 0), ("a", 1), ("b", 0)]
    g2 = q.pop_group(8, fingerprint_of=fp.get, timeout=0)
    assert g2 == [("a", 2)]
    assert q.pop_group(8, fingerprint_of=fp.get, timeout=0) == [("a", 3)]


def test_roundrobin_coalesce_cap_and_close():
    q = RoundRobin()
    for i in range(6):
        q.push("a", ("a", i))
    g = q.pop_group(4, fingerprint_of=lambda _: "same", timeout=0)
    assert g == [("a", i) for i in range(4)]
    q.close()
    # close drains remaining work, then returns [] forever
    assert q.pop_group(4, fingerprint_of=lambda _: "same") == \
        [("a", 4), ("a", 5)]
    assert q.pop_group(4) == []


def test_roundrobin_close_wakes_blocked_pop():
    q = RoundRobin()
    out = []

    def waiter():
        out.append(q.pop_group(1, timeout=30))

    t = threading.Thread(target=waiter)
    t.start()
    q.close()
    t.join(timeout=10)
    assert not t.is_alive() and out == [[]]


# -- FlushStream isolation ---------------------------------------------------


def test_stream_isolation_pending_and_flush():
    fuser.flush()
    s1 = fuser.FlushStream(name="iso1")
    s2 = fuser.FlushStream(name="iso2")
    with fuser.stream_scope(s1):
        a = rt.arange(32) * 2.0
    with fuser.stream_scope(s2):
        b = rt.arange(32) + 7.0

    def _has(stream, arr):
        return any(x is arr for x in stream.pending_roots())

    assert _has(s1, a) and not _has(s2, a)
    assert _has(s2, b) and not _has(s1, b)
    assert not _has(fuser.default_stream(), a)
    s1.flush()
    # s1's flush materialized only s1's work
    assert isinstance(a._expr, Const)
    assert not isinstance(b._expr, Const)
    assert any(x is b for x in s2.pending_roots())
    np.testing.assert_array_equal(np.asarray(a), np.arange(32) * 2.0)
    np.testing.assert_array_equal(np.asarray(b), np.arange(32) + 7.0)


def test_materialization_chases_owning_stream():
    # Touching an array outside its stream's scope must still flush the
    # stream that owns the work (cross-thread handoff of results).
    s = fuser.FlushStream(name="owner")
    with fuser.stream_scope(s):
        a = rt.arange(16) * 3.0
    # current stream is back to default here
    np.testing.assert_array_equal(np.asarray(a), np.arange(16) * 3.0)
    assert s.stats["flushes"] == 1


def test_per_stream_threshold_autoflush():
    fuser.flush()
    s = fuser.FlushStream(name="cap", max_pending_ops=4)
    before_default = fuser.default_stream().nodes_since_flush
    with fuser.stream_scope(s):
        arrs = [rt.arange(8) + float(i) for i in range(6)]
    assert s.stats["flushes"] >= 1  # the cap fired mid-build
    # a session's burst never advances the default stream's counter
    assert fuser.default_stream().nodes_since_flush == before_default
    for i, a in enumerate(arrs):
        np.testing.assert_array_equal(np.asarray(a), np.arange(8) + i)


def test_threshold_hook_routes_instead_of_flushing():
    fired = []
    s = fuser.FlushStream(name="hook", max_pending_ops=3)
    s.on_threshold = fired.append
    with fuser.stream_scope(s):
        a = rt.arange(8) * 1.0
        b = rt.arange(8) * 2.0
    assert fired and all(x is s for x in fired)
    assert s.stats["flushes"] == 0  # the hook replaced the sync flush
    np.testing.assert_array_equal(np.asarray(b), np.arange(8) * 2.0)
    np.testing.assert_array_equal(np.asarray(a), np.arange(8) * 1.0)


def test_default_stream_spans_carry_no_serving_fields():
    fuser.flush()
    a = rt.arange(64) * 1.5
    np.asarray(a)
    span = diagnostics.last_flushes(1)[0]
    assert "stream" not in span and "tenant" not in span


# -- async pipeline ----------------------------------------------------------


@spmd_skip
def test_session_async_flush_ticket():
    with serve.Session(tenant="async1") as s:
        a = rt.arange(128) * 2.0 + 1.0
        t = s.flush()
        assert t.wait(timeout=60) == []
        assert t.done
    np.testing.assert_array_equal(np.asarray(a), np.arange(128) * 2.0 + 1.0)
    assert s.stats["enqueued"] >= 1 and s.stats["flushes"] >= 1


@spmd_skip
def test_empty_flush_returns_finished_ticket():
    with serve.Session(tenant="empty") as s:
        t = s.flush()
        assert t.done and t.wait() == []


@spmd_skip
def test_ticket_propagates_flush_error_and_quarantines():
    fuser._compile_cache.clear()
    with serve.Session(tenant="doomed") as s:
        a = rt.arange(48) * 5.0
        with faults.inject("compile", "once", kind="fatal"):
            t = s.flush()
            with pytest.raises(faults.InjectedFault):
                t.wait(timeout=60)
        assert s.stats["quarantined"] >= 1
        # the quarantined array self-heals when touched (fault was one-shot)
        np.testing.assert_array_equal(np.asarray(a), np.arange(48) * 5.0)


@spmd_skip
def test_quarantine_never_bleeds_across_tenants():
    fuser.flush()
    fuser._compile_cache.clear()
    pipe = CompilePipeline()
    bad = serve.Session(tenant="bleed-bad", pipeline=pipe)
    good = serve.Session(tenant="bleed-good", pipeline=pipe)
    with good:
        h = rt.arange(64) * 0.5
        with bad:
            b = rt.arange(64) * 9.0
            with faults.inject("compile", "once", kind="fatal"):
                t = bad.flush()
                with pytest.raises(faults.InjectedFault):
                    t.wait(timeout=60)
            assert bad.stream.stats["quarantined"] >= 1
        # bad quarantined its own roots; good's pending work is intact
        assert good.stream.stats["quarantined"] == 0
        assert any(x is h for x in good.stream.pending_roots())
        np.testing.assert_array_equal(np.asarray(h), np.arange(64) * 0.5)
    assert good.stream.stats["quarantined"] == 0
    # the quarantined array self-heals when touched (fault was one-shot)
    np.testing.assert_array_equal(np.asarray(b), np.arange(64) * 9.0)
    pipe.stop()


@spmd_skip
def test_coalescing_dispatches_matching_fingerprints_together():
    fuser.flush()
    pipe = CompilePipeline(coalesce=8)
    pipe._ensure_worker = lambda: None  # drive the dispatch loop by hand
    before = registry.get("serve.coalesced")
    with serve.Session(tenant="co", pipeline=pipe) as s:
        arrs, tickets = [], []
        for i in range(3):
            arrs.append(rt.arange(64) * 2.0)  # identical structure each time
            tickets.append(s.flush())
        group = pipe.queue.pop_group(
            8, fingerprint_of=lambda t: t.work.fingerprint, timeout=0)
        assert len(group) == 3
        pipe._dispatch_group(group)
        for t in tickets:
            assert t.wait(timeout=60) == [] and t.coalesced == 3
        for a in arrs:
            np.testing.assert_array_equal(np.asarray(a), np.arange(64) * 2.0)
    assert registry.get("serve.coalesced") - before == 3
    ev = events.last(5, type="serve_coalesce")
    assert ev and ev[-1]["n"] == 3 and ev[-1]["tenants"] == ["co"]
    pipe.stop()


@spmd_skip
def test_abandoned_session_work_self_heals():
    s = serve.Session(tenant="abandon")
    tok = fuser.activate_stream(s.stream)
    try:
        a = rt.arange(32) + 4.0
    finally:
        fuser.deactivate_stream(tok)
    s.close(drain=False)  # nothing dispatched; the array keeps its graph
    np.testing.assert_array_equal(np.asarray(a), np.arange(32) + 4.0)


# -- tenant quotas & attribution ---------------------------------------------


@spmd_skip
def test_tenant_quota_routes_over_quota_flush_chunked():
    fuser.flush()
    before = registry.get("serve.quota_rejects")
    with serve.Session(tenant="quota-t", quota="16k") as s:
        a = rt.arange(16384) * 2.0 + 1.0  # ~64KB f32 / 128KB f64, >> 16KB
        s.flush(wait=True)
    np.testing.assert_allclose(np.asarray(a), np.arange(16384) * 2.0 + 1.0)
    assert registry.get("serve.quota_rejects") - before >= 1
    spans = [f for f in diagnostics.last_flushes(10)
             if f.get("tenant") == "quota-t"]
    assert spans and spans[-1].get("tenant_admission") == "chunked"
    assert spans[-1].get("degraded") == "chunked"
    rep = serve.tenant_report()
    assert rep["quota-t"]["quota_rejects"] >= 1


@spmd_skip
def test_tenant_attribution_in_reports():
    fuser.flush()
    with serve.Session(tenant="acct") as s:
        a = rt.arange(96) * 3.0
        s.flush(wait=True)
    np.asarray(a)
    rep = serve.tenant_report()
    assert rep["acct"]["flushes"] >= 1 and rep["acct"]["nodes"] >= 1
    assert rep["acct"]["executes"] >= 1
    # the kernel cost ledger carries the per-tenant execution split
    snap = ledger.snapshot()
    assert any("acct" in (k.get("tenants") or {})
               for k in snap["kernels"].values())
    # diagnostics surfaces the rollup in both machine and human form
    assert diagnostics.snapshot()["serving"]["acct"]["flushes"] >= 1
    buf = io.StringIO()
    diagnostics.report(file=buf)
    assert "serving (per tenant)" in buf.getvalue()
    assert "acct" in buf.getvalue()


# -- thread-safety hammers ---------------------------------------------------


def _hammer(n_threads, fn):
    errs = []

    def run():
        try:
            fn()
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=run) for _ in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    assert not errs, errs


def test_counter_registry_hammer():
    # Regression for the registry lock: unlocked read-modify-write
    # increments lose updates under contention.
    key = "test.serving.hammer"
    registry.counters.pop(key, None)
    N = 20000
    _hammer(8, lambda: [registry.inc(key) for _ in range(N)])
    assert registry.get(key) == 8 * N
    registry.counters.pop(key, None)


def test_event_ring_hammer():
    # Concurrent emit must neither raise nor duplicate sequence numbers.
    N = 2000
    _hammer(8, lambda: [events.emit({"type": "test_hammer"})
                        for _ in range(N)])
    seqs = [e["seq"] for e in events.ring if e.get("type") == "test_hammer"]
    assert len(seqs) == len(set(seqs))
    events.ring.clear()


def test_kernel_ledger_hammer():
    # Concurrent record_execute on ONE fingerprint: the rolling window
    # and per-tenant counts must add up exactly.
    fp = "hammerfp"
    N = 2000
    _hammer(8, lambda: [
        ledger.record_execute(fp, "hammer", 1, "fused", 0.001, False,
                              tenant="ht")
        for _ in range(N)
    ])
    snap = ledger.snapshot()["kernels"].get(fp)
    assert snap is not None
    assert snap["exec"]["count"] == 8 * N
    assert snap["tenants"]["ht"] == 8 * N
    ledger.reset()


# -- the acceptance soak -----------------------------------------------------


_SOAK_SHAPES = [(257,), (64, 3), (31,), (8, 8, 2), (500,), (129,), (16, 17),
                (77,)]


def _soak_build(i):
    """Session ``i``'s workload: a few dependent elementwise programs over
    a shape from the mixed pool.  Elementwise-only so results are
    bitwise-deterministic regardless of flush/fusion boundaries."""
    shape = _SOAK_SHAPES[i % len(_SOAK_SHAPES)]
    n = int(np.prod(shape))
    a = rt.reshape(rt.arange(n), shape) * (i + 1.0)
    b = rt.sqrt(a + 1.0) + i
    c = b * 2.0 - rt.reshape(rt.arange(n), shape) * 0.25
    d = rt.abs(c) + b
    return a, d


@spmd_skip
def test_threaded_soak_eight_sessions_byte_identical():
    fuser.sync()
    n_sessions = 8
    # single-stream baseline first: the exact bytes each session must get
    expected = {}
    for i in range(n_sessions):
        a, d = _soak_build(i)
        expected[i] = (np.asarray(a).tobytes(), np.asarray(d).tobytes(),
                       np.asarray(a).shape)
    fuser.sync()

    results = {}
    barrier = threading.Barrier(n_sessions)

    def session_worker(i):
        with serve.Session(tenant=f"soak{i % 4}") as s:
            barrier.wait(timeout=60)  # maximize interleaving
            a, d = _soak_build(i)
            s.flush()  # async mid-build flush races the builds below
            e = d * 1.0 + 0.0  # more work enqueued behind the async flush
            s.flush(wait=True)
            results[i] = (np.asarray(a).tobytes(), np.asarray(d).tobytes(),
                          np.asarray(a).shape, np.asarray(e).tobytes(),
                          s.stream)

    # seeded deterministic faults: retry must absorb them invisibly
    faults.configure("execute:2,compile:2", seed=7)
    try:
        threads = [threading.Thread(target=session_worker, args=(i,))
                   for i in range(n_sessions)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in threads)
    finally:
        faults.configure(None)

    assert len(results) == n_sessions
    for i in range(n_sessions):
        a_b, d_b, shp, e_b, stream = results[i]
        assert shp == expected[i][2]
        assert a_b == expected[i][0], f"session {i}: a diverged"
        assert d_b == expected[i][1], f"session {i}: d diverged"
        assert e_b == expected[i][1], f"session {i}: e diverged"
        # no cross-tenant interference: nothing quarantined anywhere
        assert stream.stats["quarantined"] == 0, (i, stream.stats)
    # every tenant shows up in the serving rollup with clean accounting
    rep = serve.tenant_report()
    for t in range(4):
        assert rep[f"soak{t}"]["flushes"] >= 1
        assert rep[f"soak{t}"]["quota_rejects"] == 0
