"""Rank-coherent recovery: the consensus control plane (resilience/coherence).

Covers the agreement primitive itself (epochs, reductions, the
propose/decide split, loopback ``force`` transport, single-controller
no-op), its error vocabulary (``CoherentAbort`` routing through
``retry.classify``), the coherent retry engine and degradation ladder
(lockstep attempts, fleet-agreed terminal classes, the
donation-exhausted abort — ISSUE 10 satellite), the ``rank=<i>``
fault-injection payload, and the observability contract: every round
emits a ``coherence`` event and accounts its bytes on the transfer
ledger — never silently swallowed.

The cross-process acceptance soak lives in
``scripts/two_process_suite.py --chaos-leg``; these tests drive the same
code paths single-process through the ``RAMBA_COHERENCE=force``
loopback seam.
"""

import pytest

import ramba_tpu as rt  # noqa: F401  (bootstraps the package like peers)
from ramba_tpu import diagnostics
from ramba_tpu.observe import events
from ramba_tpu.resilience import coherence, degrade, faults, retry


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    """Fresh coherence state per test, fast backoff, no leaked faults."""
    monkeypatch.setenv("RAMBA_RETRY_BASE_S", "0.001")
    faults.configure(None)
    coherence.reset()
    yield
    coherence.reset()
    faults.reset()


@pytest.fixture
def _force(monkeypatch):
    """Engage the full coherence bookkeeping over the loopback
    transport (single-process unit-test seam)."""
    monkeypatch.setenv("RAMBA_COHERENCE", "force")
    coherence.reset()
    yield
    coherence.reset()


def _coherence_events():
    return [e for e in events.snapshot_ring()
            if e.get("type") == "coherence"]


# -- the primitive -----------------------------------------------------------


def test_single_process_on_mode_is_a_noop(monkeypatch):
    monkeypatch.setenv("RAMBA_COHERENCE", "on")
    before = len(_coherence_events())
    assert not coherence.engaged()
    assert coherence.agree("t:site", coherence.P_OOM) == coherence.P_OOM
    assert coherence.decide("t:site", coherence.P_DROP) == coherence.P_DROP
    coherence.propose("t:site", coherence.P_FATAL)
    # no epoch, no event, no pending state: byte-identical behavior
    assert coherence.last_epoch("t:site") == 0
    assert coherence.report()["pending"] == {}
    assert len(_coherence_events()) == before


def test_off_mode_disarms_even_if_multiprocess(monkeypatch):
    monkeypatch.setenv("RAMBA_COHERENCE", "off")
    assert coherence.mode() == "off"
    assert not coherence.engaged()
    assert coherence.agree("t:site", 3) == 3
    assert coherence.last_epoch("t:site") == 0


def test_force_mode_rounds_epochs_events_and_ledger(_force):
    c0 = diagnostics.counters()
    d = coherence.agree("t:site", coherence.P_DROP)
    assert d == coherence.P_DROP  # loopback: own proposal wins
    coherence.agree("t:site", coherence.P_OK)
    coherence.agree("t:other", 5, reduce="min")
    assert coherence.last_epoch("t:site") == 2
    assert coherence.last_epoch("t:other") == 1
    evs = _coherence_events()[-3:]
    assert [(e["site"], e["epoch"]) for e in evs] == [
        ("t:site", 1), ("t:site", 2), ("t:other", 1)]
    assert all("decision" in e and "proposal" in e for e in evs)
    c1 = diagnostics.counters()
    assert c1.get("coherence.rounds", 0) - c0.get("coherence.rounds", 0) == 3
    # satellite: control-plane traffic lands on the transfer ledger
    assert c1.get("distributed.coherence_count", 0) \
        - c0.get("distributed.coherence_count", 0) == 3
    assert c1.get("distributed.coherence_bytes", 0) \
        > c0.get("distributed.coherence_bytes", 0)


def test_propose_decide_merges_pending_severity_max(_force):
    coherence.propose("t:site", coherence.P_RETRY)
    coherence.propose("t:site", coherence.P_OOM)
    coherence.propose("t:site", coherence.P_DROP)  # lower: must not regress
    assert coherence.report()["pending"] == {"t:site": coherence.P_OOM}
    d = coherence.decide("t:site", coherence.P_OK)
    assert d == coherence.P_OOM
    assert coherence.report()["pending"] == {}  # consumed by the round
    # next decide is unaffected
    assert coherence.decide("t:site", coherence.P_OK) == coherence.P_OK


def test_agree_rejects_bad_reduce(_force):
    with pytest.raises(ValueError):
        coherence.agree("t:site", 0, reduce="sum")


def test_report_shape(_force):
    coherence.agree("t:a", 1)
    r = coherence.report()
    assert r["mode"] == "force" and r["engaged"]
    assert r["epochs"] == {"t:a": 1}
    assert r["overhead_s"] >= 0.0


# -- CoherentAbort routing ---------------------------------------------------


def test_coherent_abort_classification():
    for code, cls in ((coherence.P_RETRY, "retryable"),
                      (coherence.P_DROP, "degrade"),
                      (coherence.P_OOM, "oom"),
                      (coherence.P_FATAL, "fatal")):
        e = coherence.CoherentAbort("flush:rung", code)
        assert e.coherent_classification == cls
        assert retry.classify(e) == cls
        assert e.decision == code
    assert "peer rank" in str(coherence.CoherentAbort("s", coherence.P_FATAL))
    assert coherence.classification_code("oom") == coherence.P_OOM
    assert coherence.decision_class(coherence.P_DROP) == "degrade"


# -- coherent retry ----------------------------------------------------------


def test_coherent_retry_success_and_recovery(_force):
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionError("Connection refused")
        return "ok"

    assert retry.call("t_site", flaky, coherent=True) == "ok"
    assert calls["n"] == 3
    # every attempt consumed one agreement round at retry:<site>
    assert coherence.last_epoch("retry:t_site") == 3


def test_coherent_retry_fatal_passthrough(_force):
    with pytest.raises(TypeError):
        retry.call("t_site", lambda: (_ for _ in ()).throw(TypeError("x")),
                   coherent=True)
    assert coherence.last_epoch("retry:t_site") == 1


def test_coherent_retry_budget_exhausted(_force, monkeypatch):
    monkeypatch.setenv("RAMBA_RETRY_ATTEMPTS", "2")

    def always():
        raise ConnectionError("Connection refused")

    with pytest.raises(retry.RetryBudgetExhausted) as ei:
        retry.call("t_site", always, coherent=True)
    assert isinstance(ei.value.__cause__, ConnectionError)
    assert coherence.last_epoch("retry:t_site") == 2


def test_coherent_retry_peer_decision_drags_success(_force, monkeypatch):
    """A locally-successful rank must drop when the fleet agrees to —
    simulated by forcing the decision above the local P_OK proposal."""
    monkeypatch.setattr(coherence, "decide",
                        lambda site, local, **kw: coherence.P_DROP)
    with pytest.raises(coherence.CoherentAbort) as ei:
        retry.call("t_site", lambda: "fine", coherent=True)
    assert ei.value.coherent_classification == "degrade"


# -- coherent ladder ---------------------------------------------------------


def test_coherent_ladder_drop_and_recover(_force):
    seen = []

    def r0():
        seen.append("fused")
        raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")

    def r1():
        seen.append("split")
        return 99

    out, rung = degrade.run_ladder("t_flush", [("fused", r0), ("split", r1)])
    assert (out, rung) == (99, "split")
    # one rung round per rung outcome: oom at fused, ok at split
    assert coherence.last_epoch("t_flush:rung") == 2


def test_coherent_ladder_fatal_aborts_everywhere(_force):
    def r0():
        raise TypeError("programming error")

    with pytest.raises(TypeError):
        degrade.run_ladder("t_flush", [("fused", r0), ("split", lambda: 1)])
    assert coherence.last_epoch("t_flush:rung") == 1


def test_coherent_ladder_donation_exhausted_aborts(_force):
    """ISSUE 10 satellite: donated inputs consumed + no lower rung =
    every rank surfaces the same fatal-class terminal error (the local
    degrade-class failure rides along as the abort's cause), with the
    decision recorded as fatal on the agreement stream."""
    def r0():
        raise retry.RetryBudgetExhausted("t_flush: budget gone")

    with pytest.raises(coherence.CoherentAbort) as ei:
        degrade.run_ladder("t_flush",
                           [("fused", r0), ("split", lambda: 1)],
                           leaf_check=lambda: False)
    assert ei.value.coherent_classification == "fatal"
    assert "RetryBudgetExhausted" in str(ei.value)  # original not swallowed
    evs = [e for e in _coherence_events() if e["site"] == "t_flush:rung"]
    assert evs and evs[-1]["decision"] == coherence.P_FATAL


def test_coherent_ladder_forced_drop_with_dead_leaves(_force, monkeypatch):
    """A peer-forced drop on a rank whose own attempt succeeded (and
    consumed its donated leaves) must coherently abort, not re-run the
    lower rung against deleted buffers."""
    decisions = iter([coherence.P_DROP, coherence.P_FATAL])
    monkeypatch.setattr(coherence, "decide",
                        lambda site, local, **kw: next(decisions))
    alive = {"ok": True}

    def r0():
        alive["ok"] = False  # the successful attempt donated the leaves
        return "done"

    with pytest.raises(coherence.CoherentAbort) as ei:
        degrade.run_ladder("t_flush",
                           [("fused", r0), ("split", lambda: 1)],
                           leaf_check=lambda: alive["ok"])
    assert ei.value.coherent_classification == "fatal"


def test_noncoherent_ladder_unchanged(monkeypatch):
    """Coherence off: the ladder is the historical rank-local machine."""
    monkeypatch.setenv("RAMBA_COHERENCE", "off")

    def r0():
        raise retry.RetryBudgetExhausted("x")

    out, rung = degrade.run_ladder("t_flush",
                                   [("fused", r0), ("split", lambda: 7)])
    assert (out, rung) == (7, "split")
    assert coherence.last_epoch("t_flush:rung") == 0


# -- rank=<i> fault payload --------------------------------------------------


def test_fault_rank_payload_parses_and_gates():
    # single process: process_index 0 -> rank=0 fires, rank=1 disarms
    faults.configure("a:always:rank=0,b:always:rank=1")
    with pytest.raises(faults.InjectedFault):
        faults.check("a")
    for _ in range(3):
        faults.check("b")  # never fires here
    st = faults.stats()
    assert st["b"] == {"calls": 3, "fired": 0}  # counters still advance


def test_fault_rank_payload_composes_with_after():
    faults.configure("c:after=2:rank=0")
    fired = []
    for _ in range(4):
        try:
            faults.check("c")
            fired.append(False)
        except faults.InjectedFault:
            fired.append(True)
    assert fired == [False, False, True, True]


def test_fault_rank_payload_rejects_garbage():
    with pytest.raises(ValueError):
        faults._parse_one("a:once:rank=x")
    with pytest.raises(ValueError):
        faults._parse_one("a:once:rank=1:rank=2")
