"""Differential tests for ramba_tpu.linalg (beyond the reference, which
exposes no linalg namespace): device-lowered decompositions vs numpy, and
the host-boundary eig family."""

import numpy as np
import pytest

import ramba_tpu as rt
from tests.helpers import default_atol, default_rtol


def _cmp(got, want, rtol=1e-8):
    np.testing.assert_allclose(
        np.asarray(got), want, rtol=default_rtol(rtol), atol=default_atol()
    )


@pytest.fixture
def spd():
    rng = np.random.RandomState(0)
    m = rng.rand(6, 6)
    return m @ m.T + 6 * np.eye(6)


@pytest.fixture
def rect():
    return np.random.RandomState(1).rand(8, 5)


class TestDeviceLowered:
    def test_norm(self, rect):
        a = rt.fromarray(rect)
        _cmp(rt.linalg.norm(a), np.linalg.norm(rect))
        _cmp(rt.linalg.norm(a, axis=1), np.linalg.norm(rect, axis=1))
        _cmp(rt.linalg.norm(a, ord=1), np.linalg.norm(rect, ord=1), rtol=1e-6)
        v = rt.fromarray(rect[:, 0])
        _cmp(rt.linalg.norm(v, ord=np.inf),
             np.linalg.norm(rect[:, 0], ord=np.inf))

    def test_det_slogdet_inv_solve(self, spd):
        a = rt.fromarray(spd)
        _cmp(rt.linalg.det(a), np.linalg.det(spd), rtol=1e-6)
        gs, gl = rt.linalg.slogdet(a)
        ws, wl = np.linalg.slogdet(spd)
        _cmp(gs, ws)
        _cmp(gl, wl, rtol=1e-6)
        _cmp(rt.linalg.inv(a), np.linalg.inv(spd), rtol=1e-6)
        b = np.random.RandomState(2).rand(6)
        _cmp(rt.linalg.solve(a, rt.fromarray(b)), np.linalg.solve(spd, b),
             rtol=1e-6)

    def test_cholesky_eigh(self, spd):
        a = rt.fromarray(spd)
        _cmp(rt.linalg.cholesky(a), np.linalg.cholesky(spd), rtol=1e-6)
        gw, gv = rt.linalg.eigh(a)
        ww, wv = np.linalg.eigh(spd)
        _cmp(gw, ww, rtol=1e-6)
        # eigenvectors are sign-ambiguous: compare reconstructions
        _cmp(np.asarray(gv) @ np.diag(np.asarray(gw)) @ np.asarray(gv).T,
             spd, rtol=1e-5)
        _cmp(rt.linalg.eigvalsh(a), np.linalg.eigvalsh(spd), rtol=1e-6)

    def test_qr_svd(self, rect):
        a = rt.fromarray(rect)
        q, r = rt.linalg.qr(a)
        _cmp(np.asarray(q) @ np.asarray(r), rect, rtol=1e-6)
        u, s, vt = rt.linalg.svd(a, full_matrices=False)
        _cmp(np.asarray(u) * np.asarray(s) @ np.asarray(vt), rect, rtol=1e-5)
        _cmp(rt.linalg.svd(a, compute_uv=False),
             np.linalg.svd(rect, compute_uv=False), rtol=1e-6)

    def test_rank_power_pinv_cond(self, spd, rect):
        assert int(rt.linalg.matrix_rank(rt.fromarray(spd))) == 6
        _cmp(rt.linalg.matrix_power(rt.fromarray(spd), 3),
             np.linalg.matrix_power(spd, 3), rtol=1e-6)
        _cmp(rt.linalg.pinv(rt.fromarray(rect)), np.linalg.pinv(rect),
             rtol=1e-5)
        _cmp(rt.linalg.cond(rt.fromarray(spd)), np.linalg.cond(spd),
             rtol=1e-5)

    def test_lstsq(self, rect):
        b = np.random.RandomState(3).rand(8)
        gx = rt.linalg.lstsq(rt.fromarray(rect), rt.fromarray(b))[0]
        wx = np.linalg.lstsq(rect, b, rcond=None)[0]
        _cmp(gx, wx, rtol=1e-5)

    def test_fuses_with_surrounding_ops(self, spd):
        from ramba_tpu.core import fuser

        a = rt.fromarray(spd)
        rt.sync()
        f0 = fuser.stats["flushes"]
        out = rt.linalg.norm(a * 2.0) + 1.0
        val = float(out)
        assert fuser.stats["flushes"] == f0 + 1
        np.testing.assert_allclose(val, np.linalg.norm(spd * 2) + 1,
                                   rtol=default_rtol(1e-8))


class TestHostBoundary:
    def test_eig(self, spd):
        w, v = rt.linalg.eig(rt.fromarray(spd))
        np.testing.assert_allclose(sorted(w.real),
                                   sorted(np.linalg.eigvals(spd).real),
                                   rtol=default_rtol(1e-8))
        np.testing.assert_allclose(
            sorted(rt.linalg.eigvals(rt.fromarray(spd)).real),
            sorted(np.linalg.eigvals(spd).real), rtol=default_rtol(1e-8))


class TestNumpyDispatch:
    def test_np_linalg_routes_here(self, spd):
        a = rt.fromarray(spd)
        got = np.linalg.norm(a)
        _cmp(got, np.linalg.norm(spd))
        _cmp(np.linalg.det(a), np.linalg.det(spd), rtol=1e-6)
        _cmp(np.linalg.inv(a), np.linalg.inv(spd), rtol=1e-6)


class TestReviewRegressions:
    def test_result_namedtuples(self, rect, spd):
        # numpy 2.x attribute access: .U/.S/.Vh, .Q/.R, .sign/.logabsdet,
        # .eigenvalues/.eigenvectors
        r = rt.linalg.svd(rt.fromarray(rect), full_matrices=False)
        _cmp(np.asarray(r.U) * np.asarray(r.S) @ np.asarray(r.Vh), rect,
             rtol=1e-5)
        qr = rt.linalg.qr(rt.fromarray(rect))
        _cmp(np.asarray(qr.Q) @ np.asarray(qr.R), rect, rtol=1e-6)
        sl = rt.linalg.slogdet(rt.fromarray(spd))
        _cmp(sl.sign, 1.0)
        eh = rt.linalg.eigh(rt.fromarray(spd))
        _cmp(eh.eigenvalues, np.linalg.eigh(spd).eigenvalues, rtol=1e-6)

    def test_numpy_kwargs_forward(self, spd, rect):
        # numpy-signature keywords must not TypeError through the dispatch
        _cmp(np.linalg.pinv(rt.fromarray(rect), rcond=1e-10),
             np.linalg.pinv(rect, rcond=1e-10), rtol=1e-5)
        _cmp(np.linalg.eigvalsh(rt.fromarray(spd), UPLO="U"),
             np.linalg.eigvalsh(spd, UPLO="U"), rtol=1e-6)
        _cmp(np.linalg.cholesky(rt.fromarray(spd), upper=True),
             np.linalg.cholesky(spd, upper=True), rtol=1e-6)

    def test_matrix_rank_tol_is_absolute(self):
        # numpy positional tol is an ABSOLUTE cutoff; must not be
        # reinterpreted as jax's relative rtol.  Largest singular value is
        # 100, so absolute (rank 3) and relative (rank 1) disagree here —
        # review r4 found the earlier test masked the conflation at
        # s_max == 1.
        d = np.diag([100.0, 0.05, 0.04])
        a = rt.fromarray(d)
        assert int(rt.linalg.matrix_rank(a, 1e-3)) == \
            int(np.linalg.matrix_rank(d, 1e-3)) == 3
        d2 = np.diag([1.0, 0.5, 1e-4])
        assert int(rt.linalg.matrix_rank(rt.fromarray(d2), 1e-3)) == 2
        assert int(rt.linalg.matrix_rank(a)) == 3

    def test_lstsq_numpy_residual_semantics(self):
        # underdetermined system: numpy's residuals output is empty
        a = np.random.RandomState(4).rand(3, 5)
        b = np.random.RandomState(5).rand(3)
        g = rt.linalg.lstsq(rt.fromarray(a), rt.fromarray(b))
        w = np.linalg.lstsq(a, b, rcond=None)
        assert np.asarray(g[1]).size == w[1].size == 0
        _cmp(g[0], w[0], rtol=1e-5)

    def test_axis_accepts_numpy_ints(self):
        m = np.random.RandomState(6).rand(4, 5)
        _cmp(rt.linalg.norm(rt.fromarray(m), axis=np.int64(1)),
             np.linalg.norm(m, axis=np.int64(1)))
        v = np.arange(8.0)
        _cmp(rt.fft.fftshift(rt.fromarray(v), axes=np.int64(0)),
             np.fft.fftshift(v, axes=np.int64(0)))

    def test_no_spurious_dispatch_entries(self):
        from ramba_tpu.core.interop import HANDLED_FUNCTIONS

        assert np.linalg.LinAlgError not in HANDLED_FUNCTIONS

    def test_matrix_rank_batched_and_1d(self):
        # review r4: absolute-tol rank must count per matrix for stacked
        # inputs and handle 1-D without SVD
        A = np.diag([100.0, 0.05, 0.04])
        B = np.diag([1.0, 1e-5, 1e-6])
        stacked = np.stack([A, B])
        got = np.asarray(rt.linalg.matrix_rank(rt.fromarray(stacked), 1e-3))
        np.testing.assert_array_equal(got, [3, 1])
        v = np.array([0.0, 2.0, 0.0])
        assert int(rt.linalg.matrix_rank(rt.fromarray(v), 1e-3)) == 1
        assert int(rt.linalg.matrix_rank(rt.fromarray(np.zeros(3)), 1e-3)) == 0


class TestMultiDotEinsumPath:
    def test_multi_dot_matches_numpy(self):
        rs = np.random.RandomState(0)
        A, B, C, D = rs.rand(10, 30), rs.rand(30, 5), rs.rand(5, 60), \
            rs.rand(60, 8)
        got = np.asarray(rt.linalg.multi_dot(
            [rt.fromarray(A), rt.fromarray(B), rt.fromarray(C),
             rt.fromarray(D)]))
        np.testing.assert_allclose(got, np.linalg.multi_dot([A, B, C, D]),
                                   rtol=default_rtol(1e-10))

    def test_multi_dot_vector_ends(self):
        rs = np.random.RandomState(1)
        v1, A, B, v2 = rs.rand(10), rs.rand(10, 30), rs.rand(30, 8), \
            rs.rand(8)
        got = rt.linalg.multi_dot(
            [rt.fromarray(v1), rt.fromarray(A), rt.fromarray(B),
             rt.fromarray(v2)])
        np.testing.assert_allclose(
            float(got), np.linalg.multi_dot([v1, A, B, v2]),
            rtol=default_rtol(1e-10))

    def test_einsum_path_shape_only(self):
        A = rt.fromarray(np.zeros((8, 4)))
        B = rt.fromarray(np.zeros((4, 16)))
        path, _report = rt.einsum_path("ij,jk->ik", A, B)
        want, _ = np.einsum_path("ij,jk->ik", np.zeros((8, 4)),
                                 np.zeros((4, 16)))
        assert path == want
        # np.* dispatch
        path2, _ = np.einsum_path("ij,jk->ik", A, B)
        assert path2 == want
