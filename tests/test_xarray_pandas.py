"""End-to-end differential tests against pandas and (when present) xarray.

Reference: /root/reference/ramba/tests/test_groupby.py (climatology /
anomaly patterns with pandas date labels, 14 tests) and test_xarray.py:11-33
(a ramba array wrapped in xarray.DataArray driven through arithmetic /
ufuncs / transpose / reductions).

xarray is optional in this image — those tests importorskip; the pandas
differentials always run.
"""

import numpy as np
import pytest

import pandas as pd

import ramba_tpu as rt
from tests.helpers import default_atol, default_rtol
from ramba_tpu.core import rewrite


def _climatology(x, labels, num_groups):
    """Anomaly vs per-group mean via the framework's groupby."""
    gb = rt.fromarray(x).groupby(1, labels, num_groups=num_groups)
    return (gb - gb.mean()).asarray()


def _pandas_climatology(x, labels):
    """Same computation through pandas: per-column group means, broadcast."""
    df = pd.DataFrame(x.T)
    means = df.groupby(np.asarray(labels)).transform("mean")
    return (df - means).to_numpy().T


class TestPandasGroupby:
    def test_dayofyear_climatology(self):
        # the reference's test_mean_groupby1 pattern: 5 years of daily data,
        # labels = day-of-year from a real pandas date range
        dates = pd.date_range("2000-1-1", "2004-12-31", freq="D")
        labels = np.asarray([d.dayofyear - 1 for d in dates])
        x = np.arange(2 * len(dates), dtype=np.float64).reshape(2, len(dates))
        got = _climatology(x, labels, 366)
        want = _pandas_climatology(x, labels)
        np.testing.assert_allclose(got, want, rtol=default_rtol(1e-10), atol=default_atol())

    def test_season_groupby(self):
        dates = pd.date_range("2000-1-1", "2004-12-31", freq="D")
        labels = np.asarray([(d.month % 12) // 3 for d in dates])
        x = np.random.RandomState(0).rand(3, len(dates))
        got = _climatology(x, labels, 4)
        want = _pandas_climatology(x, labels)
        np.testing.assert_allclose(got, want, rtol=default_rtol(1e-9), atol=default_atol())

    @pytest.mark.parametrize("kind", ["mean", "sum", "min", "max", "std"])
    def test_reductions_match_pandas(self, kind):
        dates = pd.date_range("2001-1-1", "2001-12-31", freq="D")
        labels = np.asarray([d.month - 1 for d in dates])
        x = np.random.RandomState(1).rand(4, len(dates))
        gb = rt.fromarray(x).groupby(1, labels, num_groups=12)
        got = getattr(gb, kind)().asarray()
        pdf = pd.DataFrame(x.T).groupby(labels)
        want = getattr(pdf, kind)(ddof=0).to_numpy().T if kind == "std" \
            else getattr(pdf, kind)().to_numpy().T
        np.testing.assert_allclose(got, want, rtol=default_rtol(1e-9), atol=default_atol())

    def test_labels_as_ramba_array_from_pandas(self):
        dates = pd.date_range("2002-1-1", "2002-12-31", freq="D")
        labels = rt.fromarray(
            np.asarray([d.month - 1 for d in dates], dtype=np.int32)
        )
        x = np.random.RandomState(2).rand(2, len(dates))
        gb = rt.fromarray(x).groupby(1, labels, num_groups=12)
        got = gb.sum().asarray()
        want = pd.DataFrame(x.T).groupby(
            np.asarray([d.month - 1 for d in dates])
        ).sum().to_numpy().T
        np.testing.assert_allclose(got, want, rtol=default_rtol(1e-10), atol=default_atol())


class TestRewriteFiresEndToEnd:
    """The hand-expanded xarray idioms must take the rewritten path in a
    real flush (asserted via rewrite.stats), with pandas numerics."""

    def test_stack_mean_advindex_fires_in_flush(self):
        dates = pd.date_range("2001-1-1", "2001-12-31", freq="D")
        labels = np.asarray([d.month - 1 for d in dates])
        x = np.random.RandomState(3).rand(3, len(dates))
        X = rt.fromarray(x)
        cols = [np.where(labels == g)[0] for g in range(12)]
        before = rewrite.stats["rewrite_stack_reduce_advindex"]
        stacked = rt.stack(
            [rt.mean(X[:, idx], axis=1) for idx in cols], axis=1
        )
        got = stacked.asarray()  # flush happens here
        assert rewrite.stats["rewrite_stack_reduce_advindex"] > before
        want = pd.DataFrame(x.T).groupby(labels).mean().to_numpy().T
        np.testing.assert_allclose(got, want, rtol=default_rtol(1e-9), atol=default_atol())

    def test_concat_binop_getitem_fires_in_flush(self):
        dates = pd.date_range("2001-1-1", "2001-12-31", freq="D")
        labels = np.asarray([d.month - 1 for d in dates])
        x = np.random.RandomState(4).rand(3, len(dates))
        m = np.stack([x[:, labels == g].mean(axis=1) for g in range(12)], 0)
        X, M = rt.fromarray(x), rt.fromarray(m)
        cols = [np.where(labels == g)[0] for g in range(12)]
        before = rewrite.stats["rewrite_concat_binop_getitem"]
        parts = [X[:, idx] - M[g][:, None] for g, idx in enumerate(cols)]
        out = rt.concatenate(parts, axis=1)
        got = out.asarray()
        assert rewrite.stats["rewrite_concat_binop_getitem"] > before
        # pandas anomaly on the permuted column order
        perm = np.concatenate(cols)
        want = _pandas_climatology(x, labels)[:, perm]
        np.testing.assert_allclose(got, want, rtol=default_rtol(1e-9), atol=default_atol())


class TestXarrayInterop:
    """Reference: test_xarray.py:11-33 — a distributed array inside
    xarray.DataArray, driven through arithmetic, np ufuncs, transpose, and
    reductions via __array_function__/__array_ufunc__."""

    def setup_method(self, method):
        self.xr = pytest.importorskip("xarray")

    def test_dataarray_arithmetic_chain(self):
        xr = self.xr
        ra = rt.fromfunction(lambda x, y: x + y, (10, 20))
        da = xr.DataArray(ra)
        out = np.sin((da + 10.0) * 7.1).transpose().sum()
        want = np.sin((np.fromfunction(lambda x, y: x + y, (10, 20)) + 10.0)
                      * 7.1).transpose().sum()
        assert np.isclose(float(out.data), float(want))

    def test_dataarray_groupby_via_data(self):
        xr = self.xr
        dates = pd.date_range("2000-1-1", "2000-12-31", freq="D")
        x = np.random.RandomState(5).rand(2, len(dates))
        da = xr.DataArray(
            rt.fromarray(x),
            coords={"time": dates},
            dims=("x", "time"),
        )
        labels = np.asarray([d.month - 1 for d in dates])
        gb = da.data.groupby(1, labels, num_groups=12)
        got = (gb - gb.mean()).asarray()
        np.testing.assert_allclose(
            got, _pandas_climatology(x, labels), rtol=1e-9
        )


class _DataArrayDouble:
    """Minimal stand-in for how ``xarray.Variable`` dispatches to a wrapped
    duck array (xarray applies ufuncs and numpy API functions to ``.data``
    via ``__array_ufunc__``/``__array_function__`` and rewraps the result).
    xarray itself is absent from this image (round-3 verdict weak #9) and
    cannot be installed, so this double drives the SAME protocol surface the
    reference's test_xarray.py exercises; ``TestXarrayInterop`` above still
    importorskips onto the real thing where it exists."""

    def __init__(self, data):
        self.data = data

    def _wrap(self, v):
        return _DataArrayDouble(v)

    def __add__(self, other):
        return self._wrap(self.data + other)

    def __mul__(self, other):
        return self._wrap(self.data * other)

    def __array_ufunc__(self, ufunc, method, *inputs, **kwargs):
        inputs = tuple(
            i.data if isinstance(i, _DataArrayDouble) else i for i in inputs
        )
        return self._wrap(getattr(ufunc, method)(*inputs, **kwargs))

    def transpose(self):
        return self._wrap(np.transpose(self.data))

    def sum(self):
        return self._wrap(np.sum(self.data))

    def mean(self, axis=None):
        return self._wrap(np.mean(self.data, axis=axis))

    def where(self, cond, other):
        return self._wrap(np.where(cond, self.data, other))


class TestDuckArrayProtocol:
    """Reference flow test_xarray.py:11-33 through the dispatch double —
    verifies the __array_ufunc__/__array_function__ surface a DataArray
    wrapper relies on, without xarray in the image."""

    def test_reference_test1_flow(self):
        # the exact chain of reference test_xarray.py test1
        def impl(app):
            ra1 = app.fromfunction(lambda x, y: x + y, (10, 20))
            xa1 = _DataArrayDouble(ra1)
            xa2 = xa1 + 10.0
            xa22 = xa2 * 7.1
            xa3 = np.sin(xa22)
            xa4 = xa3.transpose()
            xa5 = xa4.sum()
            return float(np.asarray(xa5.data))

        got = impl(rt)
        want = impl(np)
        assert np.isclose(got, want, rtol=default_rtol(1e-9))

    def test_double_mean_where_surface(self):
        # the .mean(axis=)/.where(cond, other) methods a DataArray exposes
        x = np.random.RandomState(12).rand(5, 7)
        da = _DataArrayDouble(rt.fromarray(x))
        np.testing.assert_allclose(
            np.asarray(da.mean(axis=1).data), x.mean(axis=1),
            rtol=default_rtol(1e-10), atol=default_atol())
        np.testing.assert_allclose(
            np.asarray(da.where(np.asarray(da.data) > 0.5, 0.0).data),
            np.where(x > 0.5, x, 0.0),
            rtol=default_rtol(1e-12), atol=default_atol())

    def test_wrapped_results_stay_distributed(self):
        # np functions called on the wrapper's .data must return framework
        # arrays (not silently fall back to numpy) so a DataArray stays
        # lazy/distributed through the chain
        ra = rt.fromfunction(lambda x, y: x + y, (10, 20))
        da = _DataArrayDouble(ra)
        out = np.sin((da + 10.0) * 7.1).transpose()
        assert isinstance(out.data, type(ra)), type(out.data)

    def test_numpy_api_functions_through_protocol(self):
        # the np-namespace functions xarray's duck_array_ops calls
        x = np.random.RandomState(11).rand(6, 8)
        ra = rt.fromarray(x)
        np.testing.assert_allclose(
            np.asarray(np.nanmean(ra, axis=0)), np.nanmean(x, axis=0),
            rtol=default_rtol(1e-10), atol=default_atol())
        np.testing.assert_allclose(
            np.asarray(np.where(ra > 0.5, ra, 0.0)), np.where(x > 0.5, x, 0.0),
            rtol=default_rtol(1e-12), atol=default_atol())
        np.testing.assert_allclose(
            np.asarray(np.concatenate([ra, ra], axis=1)),
            np.concatenate([x, x], axis=1),
            rtol=default_rtol(1e-12), atol=default_atol())
        # mixed duck/plain operands promote into the framework
        mixed = np.concatenate([ra, x], axis=0)
        assert isinstance(mixed, type(ra))
        np.testing.assert_allclose(
            np.asarray(mixed), np.concatenate([x, x], axis=0),
            rtol=default_rtol(1e-12), atol=default_atol())


class TestShardedLabels:
    def test_groupby_with_distributed_label_array(self):
        """Labels big enough to shard (the reference ships label arrays to
        workers as distributed arrays, test_groupby.py coord_days)."""
        n = 4096
        x = np.random.RandomState(6).rand(4, n)
        labels_np = (np.arange(n) * 7) % 12
        labels = rt.fromarray(labels_np.astype(np.int32))
        gb = rt.fromarray(x).groupby(1, labels, num_groups=12)
        got = gb.mean().asarray()
        want = np.stack(
            [x[:, labels_np == g].mean(axis=1) for g in range(12)], axis=1
        )
        np.testing.assert_allclose(got, want, rtol=default_rtol(1e-10), atol=default_atol())
        anom = (gb - gb.mean()).asarray()
        np.testing.assert_allclose(anom, x - want[:, labels_np], rtol=default_rtol(1e-9), atol=default_atol())
