"""Partition solver in the live layout path.

The reference chooses per-dimension split counts that minimize inter-shard
surface area (compute_regular_schedule, /root/reference/ramba/common.py:
287-680) and every created array gets that layout.  Here the same solver
drives ``default_spec`` on the (4, 2) two-axis default mesh, so 2-D arrays
get surface-minimizing 2-D splits instead of maximal-surface 1-D ones.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import ramba_tpu as rt
from ramba_tpu.parallel.mesh import (
    compute_regular_schedule,
    default_spec,
    get_mesh,
)


class TestSolver:
    @pytest.mark.parametrize(
        "shape,n,want",
        [
            # square 2-D: balanced split minimizes cut surface
            ((8192, 8192), 8, (4, 2)),
            ((8192, 8192), 4, (2, 2)),
            ((8192, 8192), 16, (4, 4)),
            # skewed: cut the long dim more
            ((100000, 10), 8, (8, 1)),
            ((10, 100000), 8, (1, 8)),
            # 1-D: all splits on the only dim
            ((1 << 20,), 8, (8,)),
            # 3-D cube
            ((64, 64, 64), 8, (2, 2, 2)),
            # short first dim: every cut of the long dim costs only 4
            ((4, 100000), 8, (1, 8)),
        ],
    )
    def test_split_choices(self, shape, n, want):
        got = compute_regular_schedule(shape, n)
        # accept permutations that tie on cost for square shapes
        if sorted(got) == sorted(want) and shape[0] == shape[-1]:
            return
        assert got == want, (shape, n, got)

    def test_default_spec_uses_solver(self):
        mesh = get_mesh()
        if mesh.devices.size != 8 or len(mesh.axis_names) < 2:
            pytest.skip("needs the default (4,2) test mesh")
        # 2-D square array: both mesh axes used, one per dim
        spec = default_spec((1024, 1024))
        entries = tuple(spec)
        used = [e for e in entries if e is not None]
        assert len(used) == 2, spec
        # 1-D array: full 8-way split via both axes stacked
        spec1 = default_spec((1 << 16,))
        (e,) = tuple(spec1)
        names = (e,) if isinstance(e, str) else tuple(e)
        assert int(np.prod([mesh.shape[a] for a in names])) == 8

    def test_small_arrays_replicated(self):
        assert default_spec((4, 4)) == P()


class TestTwoDMeshRegressions:
    def test_groupby_on_2d_sharded_view(self):
        """segment reductions were silently wrong when the segment axis was
        sharded on a multi-axis mesh (GSPMD scatter-add miscompile); pinned
        unsharded in _op_segment_reduce."""
        x = np.arange(120.0).reshape(10, 12)
        r = rt.fromarray(x)[2:9, 1:11].T
        xs = x[2:9, 1:11].T
        labels = (np.arange(7) * 2) % 4
        gb = r.groupby(1, labels, num_groups=4)
        got = gb.sum().asarray()
        want = np.stack(
            [xs[:, labels == g].sum(axis=1) if (labels == g).any()
             else np.zeros(10) for g in range(4)],
            axis=1,
        )
        np.testing.assert_allclose(got, want)

    def test_stencil_halo_traffic_smaller_on_2d_split(self):
        """A (4,2) 2-D split of a square stencil operand moves less halo
        than a 1-D 8-way split: per-iteration ppermute bytes shrink from
        2*W*r rows-only-but-7-cuts to the 2-D surface."""
        import jax

        if len(jax.devices()) < 4:
            pytest.skip("needs >= 4 devices for a 2-D mesh split")

        from ramba_tpu.ops import stencil_sharded

        @rt.stencil
        def five(a):
            return a[0, 0] + 0.25 * (
                a[-1, 0] + a[1, 0] + a[0, -1] + a[0, 1]
            )

        n = 256
        x = jnp.zeros((n, n), jnp.float32)

        def step(v):
            return stencil_sharded.run(
                five.func, (-1, -1), (1, 1), (("arr", 0),), [v], 5
            )

        hlo = jax.jit(step).lower(x).compile().as_text()
        import re

        # Count only instructions that *are* collective-permutes ("= f32[..]
        # collective-permute(") — fusions/concats merely naming a permute as
        # an operand on the same line must not be counted as halo traffic.
        halo_elems = 0
        for m in re.finditer(
            r"= f32\[(\d+),(\d+)\][^=\n]*collective-permute\(", hlo
        ):
            halo_elems += int(m.group(1)) * int(m.group(2))
        # 2-D (4,2) split of 256x256 with radius 1: per-shard halos are
        # column slivers (64,1) and row slivers (1,~130) — a few hundred
        # elements.  A 1-D 8-way split would move full 256-wide rows
        # (>=512 elements per shard pair).  Assert the 2-D regime.
        assert 0 < halo_elems < 512, halo_elems


class TestDivisionAlgebra:
    """Box algebra over (n_shards, 2, ndim) division tables — the query
    surface of the reference's shardview algebra (shardview_array.py:
    414-1017), reduced to what matters without hand-routed comm."""

    def _table(self):
        from ramba_tpu.parallel.shardview import divisions

        a = rt.zeros((64, 64))
        rt.sync()
        return divisions(a)

    def test_slice_divisions_covers_slice(self):
        from ramba_tpu.parallel.shardview import divisions_size, slice_divisions

        d = self._table()
        s = slice_divisions(d, (slice(10, 50), slice(None, 32)))
        # boxes tile the sliced region exactly
        assert int(divisions_size(s).sum()) == 40 * 32
        assert s[:, 1, 0].max() == 40 and s[:, 1, 1].max() == 32

    def test_slice_divisions_int_index(self):
        from ramba_tpu.parallel.shardview import divisions_size, slice_divisions

        d = self._table()
        s = slice_divisions(d, (7,))
        assert int(divisions_size(s).sum()) == 64  # one row, all cols
        # negative index: numpy semantics (last row)
        s2 = slice_divisions(d, (-1,))
        assert int(divisions_size(s2).sum()) == 64
        np.testing.assert_array_equal(
            divisions_size(s2), divisions_size(slice_divisions(d, (63,)))
        )
        with pytest.raises(IndexError):
            slice_divisions(d, (64,))
        with pytest.raises(TypeError):
            slice_divisions(d, (None,))

    def test_intersect(self):
        from ramba_tpu.parallel.shardview import (
            divisions_size, intersect_divisions,
        )

        d = self._table()
        full = intersect_divisions(d, d)
        np.testing.assert_array_equal(full, d)
        # intersect with a disjoint table is empty
        import numpy as _np

        shifted = d.copy()
        shifted[:, :, 0] += 64
        assert int(divisions_size(intersect_divisions(d, shifted)).sum()) == 0

    def test_broadcast(self):
        from ramba_tpu.parallel.shardview import (
            broadcast_divisions, divisions_size,
        )

        n = 8
        one = np.zeros((n, 2, 2), np.int64)
        one[:, 1, 0] = np.arange(n) + 1  # uneven row boxes
        one[:, 0, 0] = np.arange(n)
        one[:, 1, 1] = 1  # size-1 col dim
        b = broadcast_divisions(one, (3, n, 5))
        assert b.shape == (n, 2, 3)
        # new leading dim + broadcast col dim cover the full extent
        assert (b[:, 1, 0] == 3).all() and (b[:, 1, 2] == 5).all()

    def test_make_uni(self):
        from ramba_tpu.parallel.shardview import (
            divisions_size, make_uni_divisions,
        )

        u = make_uni_divisions((4, 4), worker=2, n_workers=8)
        sizes = divisions_size(u)
        assert sizes[2] == 16 and sizes.sum() == 16
