"""Fusion and memory-behavior invariants.

The reference enforces fusion as CI-tested performance behavior
(/root/reference/ramba/tests/test_distributed_array.py:112-199): 10 fused
``a += 1`` must cost <2x one, unfusable slices >5x, and 500M-2B element
expressions must fit a 7 GB VM only if no temporaries materialize.  Timing
asserts are flaky on shared CI, so the rebuild expresses the SAME invariants
structurally: compile/flush counts (one fused program per batch, cache hits
on repeats) and XLA's own memory analysis (no materialized temporaries).
This is what SURVEY §4 prescribes: "re-express the fusion tests as
HLO-module-count / peak-HBM assertions".
"""

import numpy as np

import ramba_tpu as rt
from ramba_tpu.core import fuser


def _reset_point():
    rt.sync()
    return dict(fuser.stats)


class TestFusion:
    def test_chain_fuses_into_one_flush(self):
        before = _reset_point()
        a = rt.arange(10_000) / 1000.0
        b = rt.sin(a)
        c = rt.cos(a)
        d = b * b + c ** 2
        rt.sync()
        after = dict(fuser.stats)
        assert after["flushes"] - before["flushes"] == 1
        assert np.allclose(d.asarray(), 1.0)

    def test_inplace_loop_single_flush(self):
        # reference test_fuse: 10 fused a+=1 iterations (~cost of 1)
        before = _reset_point()
        a = rt.zeros(10_000)
        for _ in range(10):
            a += 1
        rt.sync()
        after = dict(fuser.stats)
        assert after["flushes"] - before["flushes"] == 1
        assert np.allclose(a.asarray(), 10.0)

    def test_repeat_program_hits_compile_cache(self):
        def run():
            x = rt.arange(5_000) / 7.0
            y = rt.sin(x) * rt.cos(x)
            rt.sync()
            return y

        run()
        before = _reset_point()
        run()
        run()
        after = dict(fuser.stats)
        # same structure, same shapes -> zero new XLA executables
        assert after["compiles"] == before["compiles"]

    def test_scalar_change_does_not_recompile(self):
        def run(k):
            x = rt.arange(5_000) * k
            rt.sync()
            return x

        run(1.5)
        before = _reset_point()
        run(2.5)
        run(3.5)
        after = dict(fuser.stats)
        assert after["compiles"] == before["compiles"]

    def test_fusion_eliminates_temporaries(self):
        # reference test_fuse2: a += (7a-3)+(4a+5a) on 500M float64 must not
        # materialize intermediates.  Structural version: XLA's memory
        # analysis of the fused program shows temp usage far below the
        # 3 intermediate buffers the unfused program would need.
        rt.sync()
        n = 1_000_000
        a = rt.ones(n)
        a += (7 * a - 3) + (4 * a + 5 * a)
        info = fuser.analyze_pending()
        assert info is not None
        nbytes = n * 8
        temp = info["temp_size_in_bytes"]
        if temp is not None and temp > 0:
            assert temp < 1.5 * nbytes, info
        rt.sync()
        assert np.allclose(a.asarray(), 1 + (7 - 3) + (4 + 5))

    def test_pi_integration_fused(self):
        # reference test_pi_integration_fused (2e9 elems in 7GB); scaled-down
        # numeric check + structural no-temporaries assertion.
        rt.sync()
        n = 2_000_000
        h = 1.0 / n
        x = (rt.arange(n) + 0.5) * h
        pi = rt.sum(4.0 / (1.0 + x * x)) * h
        info = fuser.analyze_pending()
        assert info is not None
        # the only large buffers are the output of the iota chain; reduction
        # must not materialize extra copies of x
        temp = info["temp_size_in_bytes"]
        if temp is not None and temp > 0:
            assert temp < 3 * n * 8, info
        assert abs(float(pi) - np.pi) < 1e-6

    def test_nofuse_slices_flush_separately(self):
        # reference test_nofuse: data-dependent slice writes can't fuse; here
        # each materialization point is its own flush when interleaved with
        # reads, and results stay correct.
        a = rt.zeros(1000)
        for i in range(5):
            a[i:] += 1
            assert float(a[i]) == i + 1  # read forces the flush
        np.testing.assert_allclose(
            a.asarray(), np.minimum(np.arange(1000) + 1, 5)[::1] * 0 +
            np.array([1, 2, 3, 4, 5] + [5] * 995)
        )


class TestSegmentation:
    """Oversized programs run as chained bounded jits (round-4 verdict #3:
    a 3000-op chain in one XLA program took minutes to compile)."""

    def test_long_chain_is_segmented_and_exact(self):
        before = _reset_point()
        n_ops = 1000
        x = rt.zeros(2_000, dtype="float32")
        for _ in range(n_ops):
            x = x + 1
        rt.sync()
        after = dict(fuser.stats)
        import math

        from ramba_tpu import common

        expect = math.ceil(n_ops / common.max_program_instrs)
        segs = after["segments"] - before["segments"]
        # segment count scales with chain length (rewrite may shrink the
        # program slightly, hence >=); one flush, not one per segment
        assert expect - 1 <= segs <= expect + 1, (segs, expect)
        assert after["flushes"] - before["flushes"] == 1
        np.testing.assert_allclose(x.asarray(), n_ops)

    def test_segment_count_scales_with_chain_length(self):
        counts = []
        for n_ops in (500, 1500):
            before = _reset_point()
            x = rt.zeros(512, dtype="float32")
            for _ in range(n_ops):
                x = rt.sqrt(x * x + 1.0) - rt.sqrt(x * x) + x
            rt.sync()
            counts.append(fuser.stats["segments"] - before["segments"])
        assert counts[1] > counts[0] >= 1, counts

    def test_segmented_dag_with_shared_subexprs_matches_numpy(self):
        # not a pure chain: shared subexpressions + several roots crossing
        # segment boundaries, checked differentially at a tiny segment size
        from ramba_tpu import common

        old = common.max_program_instrs
        common.max_program_instrs = 8
        try:
            rng = np.random.default_rng(0)
            an = rng.standard_normal(3_000).astype(np.float32)
            a = rt.array(an)
            b = a
            ref = an.copy()
            for i in range(40):
                s = b * 0.5 + i
                b = s + rt.sin(s) * 0.1
                sr = ref * 0.5 + i
                ref = sr + np.sin(sr) * 0.1
            c = b - a  # 'a' (an original leaf) used again in the last segment
            rt.sync()
            np.testing.assert_allclose(b.asarray(), ref, rtol=2e-5)
            np.testing.assert_allclose(c.asarray(), ref - an, rtol=2e-4, atol=2e-4)
        finally:
            common.max_program_instrs = old

    def test_segmentation_disabled_by_zero(self):
        from ramba_tpu import common

        old = common.max_program_instrs
        common.max_program_instrs = 0
        try:
            before = _reset_point()
            x = rt.zeros(256, dtype="float32")
            for _ in range(600):
                x = x + 1
            rt.sync()
            assert fuser.stats["segments"] == before["segments"]
            np.testing.assert_allclose(x.asarray(), 600)
        finally:
            common.max_program_instrs = old


class TestAnalyzePending:
    def test_none_when_empty(self):
        rt.sync()
        assert fuser.analyze_pending() is None

    def test_instruction_count(self):
        rt.sync()
        a = rt.arange(1000) + 1
        b = a * 2
        info = fuser.analyze_pending()
        assert info["instructions"] >= 2
        rt.sync()
