"""Device-side resharding collectives + live mesh elasticity.

Covers ``ramba_tpu.parallel.reshard`` and its integration seams:

* schedule construction: single-stage vs byte-bounded slab staging, the
  peak-live bound arithmetic (src + dst + one in-flight slab), and the
  31-bit plan hash the coherence fence broadcasts,
* round-trip resharding (row → column → replicated → row) asserted
  byte-identical, with ``reshard.*`` counters and the ledger's
  transient-byte accounting settling back to zero,
* rollback on an injected ``reshard:stage`` fault: the source array is
  untouched (same bytes, same layout) and the schedule is re-runnable,
* the rewrite rule that aligns disagreeing operand layouts with an
  inserted reshard (shard_hint) instead of falling back to replication,
* resharding a spilled array (restore-from-host then stage),
* governor-accounted ``device_put`` (the skeletons padded-operand seam),
* local live mesh reshape: live rung byte-identical, fault-forced
  checkpoint-fallback rung byte-identical, ladder counters.
"""

import gc

import numpy as np
import pytest

import jax as _jax
import ramba_tpu as rt
from ramba_tpu.core import rewrite
from ramba_tpu.observe import registry
from ramba_tpu.parallel import mesh as mesh_mod
from ramba_tpu.parallel import reshard as reshard_mod
from ramba_tpu.resilience import elastic, faults, memory, spill

_MULTIPROC = _jax.process_count() > 1


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.setenv("RAMBA_RETRY_BASE_S", "0.001")
    monkeypatch.delenv("RAMBA_HBM_BUDGET", raising=False)
    monkeypatch.delenv("RAMBA_RESHARD_STAGE_BYTES", raising=False)
    faults.configure(None)
    yield
    faults.reset()


def _axes():
    return tuple(mesh_mod.get_mesh().axis_names)


# -- schedule construction ---------------------------------------------------


def test_plan_single_stage_when_under_budget():
    p = reshard_mod.plan_reshard((8, 8), np.float32, (), (("d0",),),
                                 max_stage_bytes=1 << 20)
    assert len(p.stages) == 1
    assert p.total_bytes == 8 * 8 * 4
    assert p.stages[0].nbytes == p.total_bytes
    # single stage: whole src + whole dst live at once
    assert p.peak_bound_bytes == 2 * p.total_bytes


def test_plan_staged_slab_math():
    shape, cap = (128, 64), 1 << 12
    p = reshard_mod.plan_reshard(shape, np.float32, (("d0",),),
                                 ((None, ("d1",))), max_stage_bytes=cap)
    assert len(p.stages) > 1
    assert p.axis == 0  # longest dim
    # slabs tile the axis exactly, in order, without overlap
    assert p.stages[0].lo == 0
    assert p.stages[-1].hi == shape[0]
    for a, b in zip(p.stages, p.stages[1:]):
        assert a.hi == b.lo
    assert sum(s.nbytes for s in p.stages) == p.total_bytes
    assert all(s.nbytes <= cap for s in p.stages)
    assert p.max_stage_bytes == max(s.nbytes for s in p.stages)
    # bound: src + dst + one in-flight slab
    assert p.peak_bound_bytes == 2 * p.total_bytes + p.max_stage_bytes


def test_plan_hash_is_31_bit_and_layout_sensitive():
    a = reshard_mod.plan_reshard((64, 32), np.float32, (("d0",),),
                                 ((None, ("d1",))))
    b = reshard_mod.plan_reshard((64, 32), np.float32, (("d0",),),
                                 ((None, ("d1",))))
    c = reshard_mod.plan_reshard((64, 32), np.float32, (("d0",),), ())
    assert a.hash31() == b.hash31()
    assert a.hash31() != c.hash31()
    for p in (a, c):
        assert 0 <= p.hash31() < 2 ** 31


def test_stage_bytes_env_floor(monkeypatch):
    monkeypatch.setenv("RAMBA_RESHARD_STAGE_BYTES", "1")
    assert reshard_mod.default_stage_bytes() == 1 << 16  # floored
    monkeypatch.setenv("RAMBA_RESHARD_STAGE_BYTES", "2m")
    assert reshard_mod.default_stage_bytes() == 2 << 20


# -- execution ---------------------------------------------------------------


@pytest.mark.skipif(_MULTIPROC, reason="single-controller layout checks")
def test_roundtrip_byte_identical_with_counters():
    ax = _axes()
    data = np.arange(64 * 32, dtype=np.float32).reshape(64, 32)
    a = rt.asarray(data)
    before = registry.get("reshard.completed")
    rt.reshard(a, (None, ax))          # row → column
    assert np.array_equal(np.asarray(a), data)
    rt.reshard(a, ())                  # column → replicated
    assert np.array_equal(np.asarray(a), data)
    rt.reshard(a, (ax,))               # replicated → row
    assert np.array_equal(np.asarray(a), data)
    assert registry.get("reshard.completed") >= before + 3
    assert memory.ledger.transient_bytes == 0


@pytest.mark.skipif(_MULTIPROC, reason="single-controller layout checks")
def test_staged_execution_bounded_and_identical():
    ax = _axes()
    data = np.arange(256 * 64, dtype=np.float32).reshape(256, 64)
    a = rt.asarray(data)
    plan = reshard_mod.plan_reshard(a.shape, a.dtype, (ax,), (None, ax),
                                    max_stage_bytes=1 << 12)
    assert len(plan.stages) > 1
    s0 = registry.get("reshard.stages")
    rt.reshard(a, (None, ax), max_stage_bytes=1 << 12)
    assert np.array_equal(np.asarray(a), data)
    assert registry.get("reshard.stages") - s0 == len(plan.stages)
    assert memory.ledger.transient_bytes == 0
    # the ledger's high-water mark saw the transfer go through
    assert memory.ledger.peak_live_bytes >= data.nbytes


@pytest.mark.skipif(_MULTIPROC, reason="fault is asserted in-process")
def test_rollback_on_stage_fault_leaves_source_intact():
    ax = _axes()
    data = np.arange(256 * 64, dtype=np.float32).reshape(256, 64)
    a = rt.asarray(data)
    np.asarray(a)  # materialise the source layout
    faults.configure("reshard:stage:after=2")
    r0 = registry.get("reshard.rollbacks")
    with pytest.raises(reshard_mod.ReshardError, match="sharding intact"):
        rt.reshard(a, (None, ax), max_stage_bytes=1 << 12)
    assert registry.get("reshard.rollbacks") == r0 + 1
    # source untouched: same bytes, and the schedule re-runs clean
    assert np.array_equal(np.asarray(a), data)
    faults.configure(None)
    rt.reshard(a, (None, ax), max_stage_bytes=1 << 12)
    assert np.array_equal(np.asarray(a), data)
    assert memory.ledger.transient_bytes == 0


def test_views_are_rejected():
    a = rt.asarray(np.arange(64, dtype=np.float32).reshape(8, 8))
    v = a[2:6]
    with pytest.raises(ValueError, match="views"):
        rt.reshard(v, ())


@pytest.mark.skipif(_MULTIPROC, reason="spill requires fully-addressable "
                                       "shards")
def test_spilled_array_reshards_after_restore():
    ax = _axes()
    data = np.arange(64 * 32, dtype=np.float32).reshape(64, 32)
    a = rt.asarray(data)
    np.asarray(a)
    memory.ledger.evict_until(memory.ledger.live_bytes or 1)
    assert isinstance(a._expr.value, spill.SpilledArray)
    rt.reshard(a, (None, ax))
    assert not isinstance(a._expr.value, spill.SpilledArray)
    assert np.array_equal(np.asarray(a), data)


# -- rewrite-inserted reshard ------------------------------------------------


@pytest.mark.skipif(_MULTIPROC, reason="single-controller layout checks")
def test_rewrite_aligns_disagreeing_operand_layouts():
    ax = _axes()
    da = np.arange(64 * 32, dtype=np.float32).reshape(64, 32)
    db = np.linspace(0.0, 1.0, 64 * 32, dtype=np.float32).reshape(64, 32)
    a = rt.asarray(da)
    b = rt.asarray(db)
    rt.reshard(b, (None, ax))  # now a and b disagree on layout
    n0 = rewrite.stats.get("rewrite_align_operand_layouts", 0)
    c = a + b
    got = np.asarray(c)
    assert rewrite.stats.get("rewrite_align_operand_layouts", 0) == n0 + 1
    np.testing.assert_allclose(got, da + db, rtol=1e-6)


# -- governed device_put -----------------------------------------------------


@pytest.mark.skipif(_MULTIPROC, reason="transient accounting is "
                                       "asserted in-process")
def test_governed_device_put_accounts_transient_bytes():
    data = np.arange(1024, dtype=np.float32)
    g0 = registry.get("memory.governed_puts")
    out = memory.governed_device_put(data, site="test")
    assert np.array_equal(np.asarray(out), data)
    assert registry.get("memory.governed_puts") == g0 + 1
    assert memory.ledger.transient_bytes >= data.nbytes
    del out
    gc.collect()
    assert memory.ledger.transient_bytes == 0
    assert "transient_bytes" in memory.ledger.snapshot()


# -- live mesh reshape -------------------------------------------------------


def _submesh(n):
    devs = np.asarray(_jax.devices()[:n])
    return _jax.sharding.Mesh(devs, ("d0",))


@pytest.mark.skipif(_MULTIPROC, reason="local mesh surgery")
def test_live_reshape_live_rung_byte_identical():
    old = mesh_mod.get_mesh()
    if old.devices.size < 2:
        pytest.skip("needs >= 2 local devices")
    data = np.arange(64 * 32, dtype=np.float32).reshape(64, 32)
    a = rt.asarray(data)
    np.asarray(a)
    try:
        res = elastic.live_reshape(_submesh(2))
        assert res["mode"] == "live"
        assert dict(mesh_mod.get_mesh().shape) == {"d0": 2}
        assert np.array_equal(np.asarray(a), data)
        assert len(a._value().sharding.device_set) == 2
        # compute proceeds on the new mesh
        np.testing.assert_allclose(np.asarray(a + 1.0), data + 1.0)
    finally:
        mesh_mod.set_mesh(old)
    assert elastic.report()["live_reshapes"] >= 1


@pytest.mark.skipif(_MULTIPROC, reason="local mesh surgery")
def test_live_reshape_fault_falls_back_to_checkpoint(tmp_path):
    old = mesh_mod.get_mesh()
    if old.devices.size < 2:
        pytest.skip("needs >= 2 local devices")
    data = np.arange(32 * 16, dtype=np.float32).reshape(32, 16)
    a = rt.asarray(data)
    np.asarray(a)
    faults.configure("reshard:plan:always")
    try:
        res = elastic.live_reshape(_submesh(2), manager=str(tmp_path))
        assert res["mode"] == "checkpoint"
        assert dict(mesh_mod.get_mesh().shape) == {"d0": 2}
        assert np.array_equal(np.asarray(a), data)
    finally:
        faults.configure(None)
        mesh_mod.set_mesh(old)
    assert elastic.report()["reshape_fallbacks"] >= 1
