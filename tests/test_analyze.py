"""Static analysis: the flush-time verifier (``RAMBA_VERIFY``) and the
``ramba_tpu.analyze`` rule set.

One seeded-violation fixture per rule, each asserting the exact
``Finding`` the rule must emit:

* ``donation-hazard``    — the ``donate_census`` fault site corrupts the
  donate mask exactly as a census bug would, and the verifier must catch
  it before XLA consumes an aliased buffer (strict: raise; warn: route
  down the ladder and still produce the right answer).  The segmented
  replay leg simulates a broken ``_last_use_map``.
* ``shape-dtype``        — a Node whose recorded aval disagrees with
  re-inference (the signature of a rewrite-rule bug).
* ``sharding-legality``  — a hint naming a mesh axis that does not
  exist, a non-associative distributed scan, a stencil halo wider than
  one shard.
* ``graph-hygiene``      — forward slot references, dangling outputs,
  dead subgraphs, and the compile-cache key collision detector (run
  against a deliberately fingerprint-less keying function — the exact
  deficiency ``fuser._cache_key`` fixed).

Plus the offline lint path (``python -m ramba_tpu.analyze``) over a
synthetic trace, and negative controls: valid flushes under strict mode
must produce zero error findings (the fuzz leg in test_fuzz.py widens
this).
"""

import json

import numpy as np
import pytest

import jax

import ramba_tpu as rt
from ramba_tpu import analyze, common
from ramba_tpu.analyze import lint as alint
from ramba_tpu.analyze import rules as arules
from ramba_tpu.analyze import verifier as averifier
from ramba_tpu.analyze.findings import Finding, ProgramVerificationError
from ramba_tpu.core import fuser
from ramba_tpu.core.expr import Node, as_expr
from ramba_tpu.observe import events
from ramba_tpu.parallel import mesh as pmesh
from ramba_tpu.resilience import faults


@pytest.fixture(autouse=True)
def _isolate(monkeypatch):
    """Start each test with an empty pending set and no fault plan; keep
    the suite's outer RAMBA_VERIFY (the strict CI leg) from leaking into
    tests that exercise a specific mode by letting them monkeypatch it."""
    from ramba_tpu.core import memo

    fuser.flush()
    faults.configure(None)
    memo.reset()
    yield
    faults.reset()
    memo.reset()


def _findings(fs, rule, severity=None):
    return [f for f in fs if f.rule == rule
            and (severity is None or f.severity == severity)]


# ---------------------------------------------------------------------------
# donation-hazard
# ---------------------------------------------------------------------------


class TestDonationHazard:
    def test_strict_raises_before_execution(self, monkeypatch):
        monkeypatch.setenv("RAMBA_VERIFY", "1")
        a = rt.asarray(np.ones((512, 512)))  # live owner of its buffer
        b = a + 1.0
        with faults.inject("donate_census", "once"):
            with pytest.raises(ProgramVerificationError) as ei:
                fuser.flush()
        errs = _findings(ei.value.findings, "donation-hazard", "error")
        assert errs, ei.value.findings
        assert errs[0].node.startswith("leaf")
        assert "alias" in errs[0].message
        # Nothing executed, nothing donated: both arrays still usable.
        monkeypatch.setenv("RAMBA_VERIFY", "0")
        np.testing.assert_array_equal(np.asarray(a), 1.0)
        np.testing.assert_array_equal(np.asarray(b), 2.0)

    def test_warn_mode_routes_down_ladder(self, monkeypatch):
        monkeypatch.setenv("RAMBA_VERIFY", "warn")
        a = rt.asarray(np.ones((256, 256)))
        b = a * 3.0
        with faults.inject("donate_census", "once"):
            fuser.flush()
        span = events.last(1, type="flush")[-1]
        assert span.get("verify_routed") is True
        assert span.get("degraded") == "split"  # fused rung skipped
        assert span["findings"]["error"] >= 1
        ev = events.last(5, type="finding")
        assert any(e["rule"] == "donation-hazard" for e in ev)
        # The degraded path donates nothing, so the answer and the aliased
        # input both survive.
        np.testing.assert_array_equal(np.asarray(b), 3.0)
        np.testing.assert_array_equal(np.asarray(a), 1.0)

    def test_clean_flush_has_no_findings(self, monkeypatch):
        monkeypatch.setenv("RAMBA_VERIFY", "1")
        a = rt.asarray(np.ones((256, 256)))
        b = a + a
        fuser.flush()  # must not raise
        np.testing.assert_array_equal(np.asarray(b), 2.0)

    def test_scalar_and_out_of_range_slots(self):
        prog = fuser._Program((("negative", None, (0,)),), 2, ("C", "S"), (2,))
        view = averifier.ProgramView(program=prog, donate=(1, 7))
        fs = arules.RULES["donation-hazard"](view)
        assert Finding(
            "donation-hazard", "error", "leaf1",
            "donated leaf is a python scalar, not a device buffer",
        ) in fs
        assert any(f.node == "leaf7" and "only 2 leaves" in f.message
                   for f in fs)

    def test_donated_program_output(self):
        prog = fuser._Program((("negative", None, (0,)),), 1, ("C",), (0, 1))
        view = averifier.ProgramView(program=prog, donate=(0,))
        fs = arules.RULES["donation-hazard"](view)
        assert Finding(
            "donation-hazard", "error", "leaf0",
            "donated leaf is also a program output; XLA would return "
            "a deleted buffer",
        ) in fs

    def test_segmented_replay_catches_bad_liveness(self, monkeypatch):
        # slot0's true last use is instr2 (slot 3); a liveness bug that
        # thinks it dies in segment 0 would donate it mid-chain and hand
        # segment 1 a deleted buffer.  The rule replays fuser's segment
        # donation decisions and must flag the read-after-donate.
        instrs = (
            ("negative", None, (0,)),
            ("negative", None, (1,)),
            ("add", None, (0, 2)),
            ("negative", None, (3,)),
        )
        prog = fuser._Program(instrs, 1, ("C",), (4,))
        bad = dict(fuser._last_use_map(prog))
        bad[0] = 1
        monkeypatch.setattr(fuser, "_last_use_map", lambda p: bad)
        view = averifier.ProgramView(program=prog, donate=(0,), seg_size=2)
        fs = arules.RULES["donation-hazard"](view)
        seg = [f for f in fs if f.node == "slot0" and "segment" in f.message]
        assert seg and seg[0].severity == "error"
        assert "already donated by segment 0" in seg[0].message

    def test_segmented_real_flush_clean_under_strict(self, monkeypatch):
        # End-to-end negative control: a long chain over a donatable
        # (unowned, >=1MB) leaf runs segmented under strict verification
        # without a single finding — fuser's actual liveness is sound.
        monkeypatch.setenv("RAMBA_VERIFY", "1")
        monkeypatch.setattr(common, "max_program_instrs", 3)
        a = rt.asarray(np.ones((512, 512)))
        b = a
        for _ in range(8):
            b = b + 1.0
        del a  # owner count drops to 0: the leaf becomes donate-eligible
        np.testing.assert_array_equal(np.asarray(b), 9.0)


# ---------------------------------------------------------------------------
# shape-dtype
# ---------------------------------------------------------------------------


class TestShapeDtype:
    def test_corrupt_recorded_aval(self):
        a = rt.asarray(np.ones((3, 3), np.float32))
        b = a + a
        node = b._expr
        assert isinstance(node, Node)
        bad = Node(node.op, node.static, node.args,
                   aval=jax.ShapeDtypeStruct((5, 5), np.dtype(np.int32)))
        view = averifier.ProgramView(exprs=[bad])
        fs = arules.RULES["shape-dtype"](view)
        anchor = f"node0:{node.op}"
        want_shape = tuple(node.aval.shape)
        want_dtype = node.aval.dtype
        assert Finding(
            "shape-dtype", "error", anchor,
            f"recorded shape (5, 5) != re-inferred {want_shape}",
        ) in fs
        assert Finding(
            "shape-dtype", "error", anchor,
            f"recorded dtype int32 != re-inferred {want_dtype}",
        ) in fs
        fuser.flush()  # drain b

    def test_faithful_graph_is_clean(self):
        a = rt.asarray(np.arange(12.0).reshape(3, 4))
        b = (a * 2.0).T + 1.0
        fs = analyze.analyze_exprs([b._expr], rule_names=["shape-dtype"])
        assert fs == []
        fuser.flush()


# ---------------------------------------------------------------------------
# sharding-legality
# ---------------------------------------------------------------------------


def _multidevice_mesh():
    m = pmesh.get_mesh()
    if int(m.devices.size) <= 1:
        pytest.skip("sharding-legality distribution checks need >1 device")
    return m


class TestShardingLegality:
    def test_hint_names_unknown_mesh_axis(self):
        x = as_expr(np.ones((8, 8), np.float32))
        hint = Node("shard_hint", (("bogus_axis",),), [x], aval=x.aval)
        fs = analyze.analyze_exprs([hint], rule_names=["sharding-legality"])
        errs = _findings(fs, "sharding-legality", "error")
        assert errs and "'bogus_axis'" in errs[0].message
        assert errs[0].node.endswith(":shard_hint")

    def test_nonassociative_distributed_scan_warns(self):
        _multidevice_mesh()
        x = as_expr(np.ones((4096,), np.float32))
        node = Node("scumulative", (None, None, False, 0, True), [x],
                    aval=x.aval)
        fs = arules.RULES["sharding-legality"](
            averifier.ProgramView(exprs=[node]))
        assert [(f.severity, f.node) for f in fs] == [
            ("warning", "node0:scumulative")]
        assert "non-associative" in fs[0].message

    def test_associative_distributed_scan_is_clean(self):
        x = as_expr(np.ones((4096,), np.float32))
        node = Node("scumulative", (None, None, True, 0, True), [x],
                    aval=x.aval)
        fs = arules.RULES["sharding-legality"](
            averifier.ProgramView(exprs=[node]))
        assert fs == []

    def test_stencil_halo_wider_than_shard(self):
        mesh = _multidevice_mesh()
        n = 4096
        x = as_expr(np.ones((n,), np.float32))
        # halo > ceil(n / total devices) on every possible axis assignment
        halo = n // 2 + 1
        node = Node("stencil", (None, (-halo,), (halo,), (0,), ()), [x],
                    aval=x.aval)
        fs = arules.RULES["sharding-legality"](
            averifier.ProgramView(exprs=[node]))
        warns = _findings(fs, "sharding-legality", "warning")
        assert warns, (fs, mesh.shape)
        assert "halo" in warns[0].message and "shard width" in warns[0].message

    def test_small_stencil_halo_is_clean(self):
        x = as_expr(np.ones((4096,), np.float32))
        node = Node("stencil", (None, (-1,), (1,), (0,), ()), [x],
                    aval=x.aval)
        fs = arules.RULES["sharding-legality"](
            averifier.ProgramView(exprs=[node]))
        assert fs == []


# ---------------------------------------------------------------------------
# graph-hygiene
# ---------------------------------------------------------------------------


class TestGraphHygiene:
    def test_forward_reference_is_a_cycle(self):
        prog = fuser._Program((("add", None, (0, 2)),), 1, ("C",), (1,))
        view = averifier.ProgramView(program=prog, key_registry={})
        fs = arules.RULES["graph-hygiene"](view)
        errs = _findings(fs, "graph-hygiene", "error")
        assert errs and errs[0].node == "instr0:add"
        assert "forward/self reference" in errs[0].message

    def test_dangling_output_slot(self):
        prog = fuser._Program((), 1, ("C",), (5,))
        view = averifier.ProgramView(program=prog, key_registry={})
        fs = arules.RULES["graph-hygiene"](view)
        assert any(f.severity == "error" and f.node == "slot5"
                   and "dangles" in f.message for f in fs)

    def test_dead_subgraph_warns(self):
        prog = fuser._Program(
            (("negative", None, (0,)), ("exp", None, (0,))),
            1, ("C",), (2,),
        )
        view = averifier.ProgramView(program=prog, key_registry={})
        fs = arules.RULES["graph-hygiene"](view)
        warns = _findings(fs, "graph-hygiene", "warning")
        assert warns and warns[0].node == "instr0"
        assert "dead subgraph" in warns[0].message
        assert "negative" in warns[0].message

    def test_real_program_is_clean(self):
        a = rt.asarray(np.ones((4, 4)))
        b = (a + 1.0) * a
        prog, _leaves, _ = fuser._prepare_program([b._expr])
        view = averifier.ProgramView(program=prog, key_registry={})
        assert arules.RULES["graph-hygiene"](view) == []
        fuser.flush()


# ---------------------------------------------------------------------------
# compile-cache key: the collision detector, and the fingerprint fix the
# detector motivated
# ---------------------------------------------------------------------------


class TestCacheKey:
    def _program(self):
        a = rt.asarray(np.ones((4, 4), np.float32))
        b = a * 2.0
        prog, _leaves, _ = fuser._prepare_program([b._expr])
        fuser.flush()
        return prog

    def test_detector_flags_fingerprintless_keying(self):
        # Key programs the pre-fix way (structure only).  The same key
        # observed under two semantic regimes is exactly the stale-cache
        # bug the fingerprint field now prevents.
        prog = self._program()
        reg = {}
        deficient = lambda p, d: (p.key, d)
        assert arules.check_cache_key(
            prog, (), key_fn=deficient, fingerprint=("x64", False),
            registry=reg) == []
        fs = arules.check_cache_key(
            prog, (), key_fn=deficient, fingerprint=("x64", True),
            registry=reg)
        assert len(fs) == 1
        assert fs[0].rule == "graph-hygiene" and fs[0].severity == "error"
        assert "collision" in fs[0].message
        assert "('x64', False)" in fs[0].message

    def test_live_key_carries_the_fingerprint(self):
        # Regression for the fix itself: toggling jax_enable_x64 must
        # change fuser._cache_key even for a structurally identical
        # program (NEP-50 promotion in expr reads x64 at trace time).
        prog = self._program()
        old = bool(jax.config.jax_enable_x64)
        k1 = fuser._cache_key(prog, ())
        try:
            jax.config.update("jax_enable_x64", not old)
            k2 = fuser._cache_key(prog, ())
        finally:
            jax.config.update("jax_enable_x64", old)
        assert k1[0] == k2[0]  # same structure...
        assert k1 != k2        # ...distinct executables

    def test_unhashable_key_warns(self):
        prog = self._program()
        fs = arules.check_cache_key(
            prog, (), key_fn=lambda p, d: [p.key], registry={})
        assert len(fs) == 1 and fs[0].severity == "warning"
        assert "unhashable" in fs[0].message


# ---------------------------------------------------------------------------
# verifier plumbing: modes, rule selection, event emission
# ---------------------------------------------------------------------------


class TestVerifierPlumbing:
    def test_mode_parsing(self, monkeypatch):
        for v, want in [("", "off"), ("0", "off"), ("off", "off"),
                        ("1", "strict"), ("strict", "strict"),
                        ("errors", "strict"), ("warn", "warn"),
                        ("yes-please", "warn")]:
            monkeypatch.setenv("RAMBA_VERIFY", v)
            assert averifier.mode() == want, v
        monkeypatch.delenv("RAMBA_VERIFY")
        assert averifier.mode() == "off"

    def test_rule_selection(self, monkeypatch):
        monkeypatch.delenv("RAMBA_VERIFY_RULES", raising=False)
        monkeypatch.delenv("RAMBA_VERIFY_SKIP", raising=False)
        assert set(averifier.enabled_rules()) == set(arules.RULES)
        monkeypatch.setenv("RAMBA_VERIFY_RULES", "graph-hygiene,shape-dtype")
        assert averifier.enabled_rules() == ["shape-dtype", "graph-hygiene"]
        monkeypatch.setenv("RAMBA_VERIFY_SKIP", "shape-dtype")
        assert averifier.enabled_rules() == ["graph-hygiene"]

    def test_skip_disables_a_rule(self, monkeypatch):
        monkeypatch.setenv("RAMBA_VERIFY", "1")
        monkeypatch.setenv("RAMBA_VERIFY_SKIP", "donation-hazard")
        a = rt.asarray(np.ones((512, 512)))
        b = a + 1.0
        with faults.inject("donate_census", "once"):
            fuser.flush()  # hazard seeded, rule disabled: no raise
        np.testing.assert_array_equal(np.asarray(b), 2.0)
        del a

    def test_finding_validates_severity(self):
        with pytest.raises(ValueError):
            Finding("r", "catastrophic", "n", "m")

    def test_as_event_shape(self):
        f = Finding("shape-dtype", "error", "node0:add", "boom")
        assert f.as_event("lbl") == {
            "type": "finding", "rule": "shape-dtype", "severity": "error",
            "node": "node0:add", "message": "boom", "label": "lbl",
        }


# ---------------------------------------------------------------------------
# offline lint (python -m ramba_tpu.analyze)
# ---------------------------------------------------------------------------


def _program_event(**over):
    ev = {"type": "program", "label": "prog_test",
          "instrs": [["negative", "None", [0]]], "n_leaves": 1,
          "leaf_kinds": "C", "out_slots": [1], "donate": [],
          "owners": [1], "x64": False}
    ev.update(over)
    return ev


class TestOfflineLint:
    def test_recheck_flags_recorded_hazard(self, tmp_path, capsys):
        p = tmp_path / "t.jsonl"
        p.write_text(json.dumps(_program_event(donate=[0], owners=[2])) + "\n")
        rc = alint.main([str(p)])
        out = capsys.readouterr().out
        assert rc == 0  # errors reported, but not --strict
        assert "[donation-hazard]" in out and "prog_test" in out
        assert alint.main(["--strict", str(p)]) == 1

    def test_cross_regime_key_collision(self, tmp_path):
        p = tmp_path / "t.jsonl"
        p.write_text(json.dumps(_program_event(x64=False)) + "\n"
                     + json.dumps(_program_event(x64=True)) + "\n")
        pairs = alint.lint_events(alint.load_events(str(p)))
        assert any(f.severity == "error" and "collision" in f.message
                   for _lbl, f in pairs)

    def test_clean_trace(self, tmp_path, capsys):
        p = tmp_path / "t.jsonl"
        p.write_text(json.dumps(_program_event()) + "\n")
        assert alint.main(["--strict", str(p)]) == 0
        assert "clean" in capsys.readouterr().out

    def test_json_output(self, tmp_path, capsys):
        p = tmp_path / "t.jsonl"
        p.write_text(json.dumps(_program_event(donate=[0], owners=[3])) + "\n")
        assert alint.main(["--json", str(p)]) == 0
        lines = [json.loads(ln) for ln in
                 capsys.readouterr().out.strip().splitlines()]
        assert lines and lines[0]["rule"] == "donation-hazard"
        assert lines[0]["type"] == "finding"

    def test_missing_trace_exits_2(self, tmp_path, capsys):
        assert alint.main([str(tmp_path / "absent.jsonl")]) == 2

    def test_live_trace_roundtrip(self, tmp_path, monkeypatch):
        # A real traced flush produces program events the offline linter
        # re-checks clean.
        path = str(tmp_path / "live.jsonl")
        events.configure(path)
        try:
            a = rt.asarray(np.ones((64, 64)))
            b = a + 1.0
            fuser.flush()
            np.asarray(b)
        finally:
            events.configure(None)
        evs = alint.load_events(alint.discover(path)[0])
        assert any(e.get("type") == "program" for e in evs)
        assert alint.lint_events(evs) == []


# ---------------------------------------------------------------------------
# effect classification (the memoization certifier's front half)
# ---------------------------------------------------------------------------


class TestEffects:
    def test_pure_program(self):
        a = rt.asarray(np.ones((4, 4)))
        b = (a + 1.0) * a
        prog, _leaves, _ = fuser._prepare_program([b._expr])
        rep = analyze.classify_program(prog)
        assert rep.program_class == "pure"
        assert rep.memoizable and rep.reason == ""
        assert rep.host_instrs == () and rep.alias_outs == ()
        fuser.flush()

    def test_rng_program_is_memoizable(self):
        rt.random.seed(0)
        r = rt.random.random((4,)) + 1.0
        prog, _leaves, _ = fuser._prepare_program([r._expr])
        rep = analyze.classify_program(prog)
        assert rep.program_class == "rng"
        assert rep.rng_instrs  # the draw itself
        assert rep.memoizable  # key is an operand, so replay is sound
        fuser.flush()

    def test_closure_static_is_host_effecting(self):
        ff = rt.fromfunction(lambda i, j: i + j, (3, 3))
        prog, _leaves, _ = fuser._prepare_program([(ff + 1.0)._expr])
        rep = analyze.classify_program(prog)
        assert rep.program_class == "host"
        assert not rep.memoizable
        assert "host-effecting" in rep.reason
        fuser.flush()

    def test_alias_escaping_output_vetoes(self):
        # out slot 0 < n_leaves: the program returns an input unchanged
        prog = fuser._Program((("negative", None, (0,)),), 1, ("C",), (0, 1))
        rep = analyze.classify_program(prog)
        assert rep.alias_outs == (0,)
        assert not rep.memoizable and "aliases a program input" in rep.reason

    def test_donation_vetoes(self):
        prog = fuser._Program((("negative", None, (0,)),), 1, ("C",), (1,))
        rep = analyze.classify_program(prog, donate=(0,))
        assert rep.donating
        assert not rep.memoizable and "donates" in rep.reason

    def test_static_token_folds_values_not_identities(self):
        assert analyze.static_token(("add", 3, 2.5)) is not None
        assert analyze.static_token(np.dtype("float32")) == (
            "dtype", "float32")
        assert analyze.static_token(np.float32(2.0)) is not None
        # identity-hashed: a closure's repr embeds its address
        assert analyze.static_token(repr(lambda x: x)) is None
        assert analyze.static_token((lambda x: x,)) is None


# ---------------------------------------------------------------------------
# canonical subgraph hashing
# ---------------------------------------------------------------------------


class TestCanon:
    def _chash(self, expr):
        prog, _leaves, _ = fuser._prepare_program([expr])
        return analyze.canonicalize(prog).chash

    def test_commutative_operand_order_is_normalized(self):
        a = rt.asarray(np.arange(6.0))
        b = rt.asarray(np.ones(6))
        h_ab = self._chash(((a + b) * 2.0)._expr)
        h_ba = self._chash(((b + a) * 2.0)._expr)
        h_sub = self._chash(((a - b) * 2.0)._expr)
        assert h_ab == h_ba            # add commutes
        assert h_ab != h_sub           # subtract does not
        fuser.flush()

    def test_alpha_renaming_across_different_leaves(self):
        # the same shape of computation over DIFFERENT arrays must hash
        # identically — slots are alpha-renamed, not identity-keyed
        a = rt.asarray(np.arange(6.0))
        b = rt.asarray(np.ones(6))
        c = rt.asarray(np.arange(6.0) * 3)
        assert (self._chash(((a + b) * 2.0)._expr)
                == self._chash(((c + b) * 2.0)._expr))
        fuser.flush()

    def test_closure_static_is_not_canonical(self):
        ff = rt.fromfunction(lambda i, j: i * j, (3, 3))
        prog, _leaves, _ = fuser._prepare_program([(ff + 1.0)._expr])
        assert analyze.try_canonicalize(prog) is None
        with pytest.raises(analyze.NotCanonical):
            analyze.canonicalize(prog)
        fuser.flush()

    def test_dead_instructions_do_not_constrain(self):
        # a dead instr with an untokenizable static must not block
        # canonicalization — dead code is not part of the semantics
        prog = fuser._Program(
            (("apply", (lambda x: x,), (0,)), ("negative", None, (0,))),
            1, ("C",), (2,),
        )
        form = analyze.try_canonicalize(prog)
        assert form is not None
        live = fuser._Program((("negative", None, (0,)),), 1, ("C",), (1,))
        assert form.chash == analyze.canonicalize(live).chash

    def test_stability_across_process_values(self):
        # the hash is derived from structure only — it must be a pure
        # function of the canonical form string (cross-session stable)
        a = rt.asarray(np.ones(4))
        prog, _leaves, _ = fuser._prepare_program([(a * 2.0)._expr])
        f1 = analyze.canonicalize(prog)
        f2 = analyze.canonicalize(prog)
        assert f1.chash == f2.chash and f1.form == f2.form
        assert f1.leaf_order == f2.leaf_order
        fuser.flush()


# ---------------------------------------------------------------------------
# dead entropy (graph-hygiene extension)
# ---------------------------------------------------------------------------


class TestDeadEntropy:
    def test_dead_rng_draw_flagged(self):
        # instr0: an RNG draw nothing consumes; instr1 feeds the output
        prog = fuser._Program(
            (("random", ("uniform", (4,), "float32", None), (0,)),
             ("negative", None, (0,))),
            1, ("C",), (2,),
        )
        view = averifier.ProgramView(program=prog, key_registry={},
                                     canon_registry={})
        fs = arules.RULES["graph-hygiene"](view)
        dead_entropy = [f for f in fs if "dead-entropy" in f.message]
        assert dead_entropy and dead_entropy[0].severity == "warning"
        assert dead_entropy[0].node == "instr0:random"

    def test_live_rng_draw_not_flagged(self):
        prog = fuser._Program(
            (("random", ("uniform", (4,), "float32", None), (0,)),),
            1, ("C",), (1,),
        )
        view = averifier.ProgramView(program=prog, key_registry={},
                                     canon_registry={})
        fs = arules.RULES["graph-hygiene"](view)
        assert not any("dead-entropy" in f.message for f in fs)


# ---------------------------------------------------------------------------
# canonical-hash collision detector
# ---------------------------------------------------------------------------


class TestCanonCollision:
    def _program(self):
        a = rt.asarray(np.ones((4, 4), np.float32))
        prog, _leaves, _ = fuser._prepare_program([(a * 2.0)._expr])
        fuser.flush()
        return prog

    def test_seeded_collision_is_flagged(self):
        # Seed the registry with the program's hash bound to a DIFFERENT
        # form — exactly what a truncated-digest collision (or a forged
        # key) would look like.
        prog = self._program()
        form = analyze.canonicalize(prog)
        reg = {form.chash: "some-other-canonical-form"}
        fs = arules.check_canon_collision(prog, registry=reg)
        assert len(fs) == 1
        assert fs[0].severity == "error" and "collision" in fs[0].message

    def test_repeat_observation_is_clean(self):
        prog = self._program()
        reg = {}
        assert arules.check_canon_collision(prog, registry=reg) == []
        assert arules.check_canon_collision(prog, registry=reg) == []
        assert len(reg) == 1

    def test_uncanonical_program_is_skipped(self):
        prog = fuser._Program(
            (("apply", (lambda x: x,), (0,)),), 1, ("C",), (1,))
        assert arules.check_canon_collision(prog, registry={}) == []


# ---------------------------------------------------------------------------
# memo-safety: the seeded-certifier-corruption fixture
# ---------------------------------------------------------------------------


class TestMemoSafety:
    def test_rule_flags_donating_plan(self):
        import types

        prog = fuser._Program((("negative", None, (0,)),), 1, ("C",), (1,))
        plan = types.SimpleNamespace(memoizable=True, chash="x", form="y")
        view = averifier.ProgramView(program=prog, donate=(0,),
                                     memo_plan=plan)
        fs = arules.RULES["memo-safety"](view)
        assert any(f.severity == "error" and "donates" in f.message
                   for f in fs)

    def test_rule_flags_alias_escape_and_host(self):
        import types

        prog = fuser._Program(
            (("apply", (lambda x: x,), (0,)),), 1, ("C",), (0, 1))
        plan = types.SimpleNamespace(memoizable=True, chash="x", form="y")
        view = averifier.ProgramView(program=prog, memo_plan=plan)
        fs = arules.RULES["memo-safety"](view)
        assert any("host-effecting" in f.message for f in fs)
        assert any("aliases a program input" in f.message for f in fs)

    def test_no_plan_is_vacuously_safe(self):
        prog = fuser._Program((("negative", None, (0,)),), 1, ("C",), (1,))
        view = averifier.ProgramView(program=prog, donate=(0,))
        assert arules.RULES["memo-safety"](view) == []

    def test_fault_seeded_violation_warn_mode(self, monkeypatch):
        # The memo:insert fault corrupts the certifier into admitting a
        # donating program (donation seeded by donate_census); warn mode
        # must flag it, route the flush down the ladder, and never let
        # the poisoned plan touch the cache.
        from ramba_tpu.core import memo

        monkeypatch.setenv("RAMBA_MEMO", "1")
        monkeypatch.setenv("RAMBA_VERIFY", "warn")
        monkeypatch.setenv("RAMBA_VERIFY_RULES", "memo-safety")
        a = rt.asarray(np.ones((64, 64)))
        b = a + 1.0
        faults.configure("memo:insert:always,donate_census:always")
        try:
            fuser.flush()
        finally:
            faults.configure(None)
        ev = events.last(5, type="finding")
        assert any(e["rule"] == "memo-safety" for e in ev)
        span = events.last(1, type="flush")[-1]
        assert span.get("verify_routed") is True
        assert len(memo.cache) == 0  # poisoned plan never cached
        np.testing.assert_array_equal(np.asarray(b), 2.0)
        np.testing.assert_array_equal(np.asarray(a), 1.0)

    def test_fault_seeded_violation_strict_raises(self, monkeypatch):
        monkeypatch.setenv("RAMBA_MEMO", "1")
        monkeypatch.setenv("RAMBA_VERIFY", "strict")
        monkeypatch.setenv("RAMBA_VERIFY_RULES", "memo-safety")
        a = rt.asarray(np.ones((64, 64)))
        b = a + 1.0
        faults.configure("memo:insert:always,donate_census:always")
        try:
            with pytest.raises(ProgramVerificationError) as ei:
                fuser.flush()
        finally:
            faults.configure(None)
        errs = _findings(ei.value.findings, "memo-safety", "error")
        assert errs, ei.value.findings
        # nothing executed: both arrays still usable afterwards
        monkeypatch.setenv("RAMBA_VERIFY", "0")
        np.testing.assert_array_equal(np.asarray(b), 2.0)
        np.testing.assert_array_equal(np.asarray(a), 1.0)

    def test_strict_insert_backstop_without_the_rule(self, monkeypatch):
        # Even with the rule filtered out, strict mode's insert-time
        # backstop refuses the uncertified plan.
        from ramba_tpu.core import memo
        from ramba_tpu.observe import registry

        monkeypatch.setenv("RAMBA_MEMO", "1")
        monkeypatch.setenv("RAMBA_VERIFY", "strict")
        monkeypatch.setenv("RAMBA_VERIFY_SKIP",
                           "memo-safety,donation-hazard")
        rejected0 = registry.get("memo.insert_rejected")
        a = rt.asarray(np.ones((64, 64)))
        b = a + 1.0
        faults.configure("memo:insert:always,donate_census:always")
        try:
            fuser.flush()
        finally:
            faults.configure(None)
        assert registry.get("memo.insert_rejected") == rejected0 + 1
        assert len(memo.cache) == 0
        np.testing.assert_array_equal(np.asarray(b), 2.0)
        del a


# ---------------------------------------------------------------------------
# ramba-lint --memo-audit
# ---------------------------------------------------------------------------


class TestMemoAudit:
    def test_audit_groups_and_rates(self, tmp_path, capsys):
        ev = _program_event()
        flush = {"type": "flush", "label": "prog_test", "out_bytes": 128}
        p = tmp_path / "t.jsonl"
        p.write_text("\n".join(
            [json.dumps(ev)] * 3 + [json.dumps(flush)] * 3) + "\n")
        assert alint.main(["--memo-audit", str(p)]) == 0
        out = capsys.readouterr().out
        assert "would-be hits: 2" in out
        assert "memoizable" in out

    def test_audit_json(self, tmp_path, capsys):
        p = tmp_path / "t.jsonl"
        p.write_text(json.dumps(_program_event()) + "\n")
        assert alint.main(["--memo-audit", "--json", str(p)]) == 0
        rec = json.loads(capsys.readouterr().out.strip())
        assert rec["programs"] == 1 and rec["would_hits"] == 0
        assert rec["top"][0]["memoizable"] is True

    def test_audit_flags_uncacheable(self, tmp_path, capsys):
        # a donating recorded program is grouped but marked uncacheable
        ev = _program_event(donate=[0])
        p = tmp_path / "t.jsonl"
        p.write_text((json.dumps(ev) + "\n") * 2)
        assert alint.main(["--memo-audit", str(p)]) == 0
        out = capsys.readouterr().out
        assert "uncacheable" in out and "would-be hits: 0" in out
