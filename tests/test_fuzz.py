"""Seeded differential fuzz: random op pipelines vs NumPy.

The reference's suite is differential (`run_both` closures executed under
numpy and under the framework); this generalizes it: deterministic random
programs chain creation, elementwise ops, views, reductions, and
manipulation over both backends and must agree in dtype and numerically
in value (f32 reductions allow accumulation-order noise — XLA reduces in
tree order, numpy sequentially).  Seeds are fixed so failures reproduce.
"""

import numpy as np
import pytest

import ramba_tpu as rt

UNARY = ["negative", "abs", "sqrt", "exp", "log1p", "floor", "tanh", "square"]
BINARY = ["add", "subtract", "multiply", "maximum", "minimum", "arctan2",
          "hypot", "true_divide"]
REDUCE = ["sum", "mean", "min", "max", "std", "prod"]


def _rand_array(rng, max_nd=2):
    nd = rng.randint(1, max_nd + 1)
    shape = tuple(int(rng.randint(2, 9)) for _ in range(nd))
    kind = rng.randint(3)
    if kind == 0:
        return rng.rand(*shape)  # f64 in [0,1): safe for log1p/sqrt
    if kind == 1:
        return rng.rand(*shape).astype(np.float32)
    return rng.randint(1, 9, size=shape).astype(np.int64)


def _rand_view(rng, shape):
    """A random basic-index view keeping every dim nonempty."""
    idx = []
    for dim in shape:
        c = rng.randint(3)
        if c == 0:
            idx.append(slice(None))
        elif c == 1:
            lo = rng.randint(0, dim)
            idx.append(slice(lo, rng.randint(lo + 1, dim + 1)))
        else:
            idx.append(slice(None, None, -1))
    return tuple(idx)


def _gen_program(seed):
    """Emit (arrays, ops) where every op is valid by construction — shapes
    are simulated exactly during generation."""
    rng = np.random.RandomState(seed)
    arrays = [_rand_array(rng) for _ in range(3)]
    shapes = [a.shape for a in arrays]
    ops = []
    for _ in range(rng.randint(4, 10)):
        c = rng.randint(5)
        i = rng.randint(len(shapes))
        if c == 0:
            ops.append(("unary", (UNARY[rng.randint(len(UNARY))], i)))
            shapes.append(shapes[i])
        elif c == 1:
            j = rng.randint(len(shapes))
            if shapes[i] != shapes[j] or shapes[i] == ():
                continue
            ops.append(("binary", (BINARY[rng.randint(len(BINARY))], i, j)))
            shapes.append(shapes[i])
        elif c == 2:
            if not shapes[i]:
                continue
            idx = _rand_view(rng, shapes[i])
            ops.append(("view", (i, idx)))
            shapes.append(tuple(
                len(range(*sl.indices(d)))
                for sl, d in zip(idx, shapes[i])
            ))
        elif c == 3:
            ops.append(("transpose", i))
            shapes.append(tuple(reversed(shapes[i])))
        else:
            axis = 0 if (shapes[i] and rng.randint(2)) else None
            ops.append(("reduce", (REDUCE[rng.randint(len(REDUCE))], i, axis)))
            shapes.append(() if axis is None else shapes[i][1:])
    return arrays, ops


def _run_program(app, arrays, ops):
    vals = [app.asarray(a) for a in arrays]
    for kind, payload in ops:
        if kind == "unary":
            name, i = payload
            vals.append(getattr(app, name)(vals[i]))
        elif kind == "binary":
            name, i, j = payload
            vals.append(getattr(app, name)(vals[i], vals[j]))
        elif kind == "view":
            i, idx = payload
            vals.append(vals[i][idx])
        elif kind == "transpose":
            vals.append(vals[payload].T)
        else:
            name, i, axis = payload
            vals.append(getattr(app, name)(vals[i], axis=axis))
    return [np.asarray(v) for v in vals]


def _check(seed):
    arrays, ops = _gen_program(seed)
    want = _run_program(np, arrays, ops)
    got = _run_program(rt, arrays, ops)
    assert len(want) == len(got)
    from tests.helpers import map_dtype, x64_enabled

    if not x64_enabled():
        # x32 contract: dtypes match jax's own lattice — which diverges
        # from mere 64->32 truncation on ops like floor(int) (numpy
        # promotes to float, jax keeps int).  Run the program through jnp
        # as the oracle.  Integer VALUES are compared against jnp too:
        # once an int chain wraps past 2^31, numpy-in-int64 and
        # wrapped-int32 arithmetic diverge under non-ring ops
        # (maximum/true_divide/mean), so truncating numpy's answer is not
        # a valid expectation.  Float values still compare against numpy
        # (higher-precision ground truth) with an f32 tolerance.
        import jax.numpy as jnp

        oracle_vals = _run_program(jnp, arrays, ops)
    else:
        oracle_vals = None

    for k, (w, g) in enumerate(zip(want, got)):
        assert g.shape == w.shape, (seed, k, g.shape, w.shape)
        exp_dtype = oracle_vals[k].dtype if oracle_vals else map_dtype(w.dtype)
        assert g.dtype == exp_dtype, (seed, k, g.dtype, exp_dtype)
        if oracle_vals is not None and np.issubdtype(exp_dtype, np.integer):
            np.testing.assert_array_equal(g, oracle_vals[k],
                                          err_msg=f"value {k} (seed {seed})")
            continue
        if exp_dtype != w.dtype:
            w = w.astype(exp_dtype)
        if not x64_enabled():
            rtol = 1e-4
        else:
            rtol = 3e-5 if w.dtype == np.float32 else 1e-6
        np.testing.assert_allclose(g, w, rtol=rtol, atol=1e-12,
                                   err_msg=f"value {k} (seed {seed})")


@pytest.mark.parametrize("seed", range(40))
def test_random_program(seed):
    _check(seed)


@pytest.mark.skipif(
    not __import__("os").environ.get("RAMBA_TPU_FUZZ_WIDE"),
    reason="set RAMBA_TPU_FUZZ_WIDE=1 for the 500-seed sweep",
)
@pytest.mark.parametrize("block", range(10))
def test_random_program_wide(block):
    for seed in range(40 + block * 46, 40 + (block + 1) * 46):
        _check(seed)


# ---------------------------------------------------------------------------
# Verifier leg: every valid generated program must flush clean under
# RAMBA_VERIFY strict mode — an error finding on a well-formed graph is a
# false positive, and strict mode turns it into ProgramVerificationError.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", range(0, 40, 4))
def test_random_program_verified_strict(seed, monkeypatch):
    monkeypatch.setenv("RAMBA_VERIFY", "strict")
    _check(seed)


# ---------------------------------------------------------------------------
# Plan-certificate leg: the same random programs, run twice under
# RAMBA_PLANCERT=1 + strict verify — the second pass redeems certificates
# minted by the first, so every redeemed verdict is checked byte-for-byte
# against the full-analysis answer on arbitrary program shapes.  The
# plan:stale variants seed the module's own fault site: warn mode must
# silently re-analyze (still matching numpy), strict must reject.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", range(0, 40, 4))
def test_random_program_plan_cache_strict(seed, monkeypatch):
    from ramba_tpu.core import plancache

    monkeypatch.setenv("RAMBA_VERIFY", "strict")
    monkeypatch.setenv("RAMBA_PLANCERT", "1")
    plancache.reset()
    try:
        _check(seed)            # first pass analyzes + certifies
        _check(seed)            # second pass redeems — same oracle
        snap = plancache.snapshot()
        assert snap.get("hits", 0) >= 1, snap
        assert not snap.get("stale"), snap
    finally:
        plancache.reset()


@pytest.mark.parametrize("seed", range(0, 40, 8))
def test_random_program_plan_stale_warn_reanalyzes(seed, monkeypatch):
    from ramba_tpu.core import plancache
    from ramba_tpu.resilience import faults

    monkeypatch.setenv("RAMBA_VERIFY", "warn")
    monkeypatch.setenv("RAMBA_PLANCERT", "1")
    plancache.reset()
    try:
        _check(seed)
        with faults.active("plan:stale:0.5", seed=seed):
            _check(seed)        # forged verdicts silently re-analyze
    finally:
        plancache.reset()


@pytest.mark.parametrize("seed", [0, 16])
def test_random_program_plan_stale_strict_raises(seed, monkeypatch):
    from ramba_tpu.analyze.findings import ProgramVerificationError
    from ramba_tpu.core import fuser, plancache
    from ramba_tpu.resilience import faults

    monkeypatch.setenv("RAMBA_VERIFY", "strict")
    monkeypatch.setenv("RAMBA_PLANCERT", "1")
    plancache.reset()
    try:
        _check(seed)
        with faults.active("plan:stale:always", seed=seed):
            with pytest.raises(ProgramVerificationError,
                               match="plan-stale"):
                _check(seed)    # first redemption is forged: rejected
    finally:
        fuser.flush()
        plancache.reset()


# ---------------------------------------------------------------------------
# Memory-pressure leg: the same random programs must survive seeded device
# OOM — each compiled execute has a 20% (seed-deterministic) chance of
# RESOURCE_EXHAUSTED, so the ladder's evict → drop-rung → retry path runs
# on arbitrary program shapes and must still converge to numpy's answer.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", range(0, 40, 5))
def test_random_program_survives_seeded_oom(seed, monkeypatch):
    from ramba_tpu.resilience import faults

    monkeypatch.setenv("RAMBA_RETRY_BASE_S", "0.001")
    with faults.active("execute:0.2:oom", seed=seed):
        _check(seed)


# ---------------------------------------------------------------------------
# Mutation + manipulation fuzz: setitem, masked writes, fancy indexing,
# concatenate/stack/pad/roll/sort/take — the reference's other test axis
# (test_distributed_array.py drives slicing/assignment heavily).
# ---------------------------------------------------------------------------


def _gen_mut_program(seed):
    rng = np.random.RandomState(seed)
    base = rng.rand(8, 10)
    steps = []
    for _ in range(rng.randint(3, 8)):
        c = rng.randint(7)
        if c == 0:  # basic setitem
            r = rng.randint(8)
            steps.append(("set_row", (r, rng.rand(10))))
        elif c == 1:  # masked write
            steps.append(("masked_add", float(rng.rand())))
        elif c == 2:  # fancy get
            steps.append(("fancy_get", tuple(rng.randint(0, 8, size=3))))
        elif c == 3:  # fancy set
            steps.append(("fancy_set",
                          (tuple(rng.randint(0, 8, size=2)), float(rng.rand()))))
        elif c == 4:
            steps.append(("roll", int(rng.randint(-5, 6))))
        elif c == 5:
            steps.append(("concat_self", None))
        else:
            steps.append(("take", tuple(rng.randint(0, 10, size=4))))
    return base, steps


def _run_mut(app, base, steps):
    a = app.asarray(base.copy())
    outs = []
    for kind, payload in steps:
        if kind == "set_row":
            r, v = payload
            a[r] = v
        elif kind == "masked_add":
            a[a > payload] += 1.0
        elif kind == "fancy_get":
            outs.append(np.asarray(a[np.asarray(payload)]))
        elif kind == "fancy_set":
            rows, val = payload
            a[np.asarray(rows)] = val
        elif kind == "roll":
            outs.append(np.asarray(app.roll(a, payload, axis=1)))
        elif kind == "concat_self":
            outs.append(np.asarray(app.concatenate([a, a], axis=0)))
        else:
            outs.append(np.asarray(app.take(a, np.asarray(payload), axis=1)))
    outs.append(np.asarray(a))
    return outs


@pytest.mark.parametrize("seed", range(25))
def test_mutation_program(seed):
    base, steps = _gen_mut_program(seed)
    want = _run_mut(np, base, steps)
    got = _run_mut(rt, base, steps)
    assert len(want) == len(got)
    from tests.helpers import default_rtol, map_dtype

    for k, (w, g) in enumerate(zip(want, got)):
        assert g.shape == w.shape and g.dtype == map_dtype(w.dtype), (seed, k)
        np.testing.assert_allclose(g, w, rtol=default_rtol(1e-12),
                                   err_msg=f"value {k} (seed {seed})")
