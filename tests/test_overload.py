"""The overload-control plane (serve/overload.py).

Single-process coverage of the four tentpole pieces — deadline
propagation, admission control + shedding, per-tenant circuit breakers,
hedged dispatch — plus the satellite fixes (bounded fairness queue,
ticket abandonment).  Every shed must surface as a *classified* error
(never a bare TimeoutError the retry layer would happily re-attempt),
fail fast, and leave the shed arrays able to self-heal on next touch.

The coherent (epoch-agreed, rank-identical) shedding story is SPMD-only
and lives in ``two_process_suite.py --overload-leg``.
"""

import threading
import time

import numpy as np
import pytest

import jax as _jax
import ramba_tpu as rt
from ramba_tpu import serve
from ramba_tpu.core import fuser
from ramba_tpu.core.expr import Const
from ramba_tpu.observe import events, ledger, registry
from ramba_tpu.resilience import faults, retry
from ramba_tpu.serve import overload
from ramba_tpu.serve.fairness import RoundRobin
from ramba_tpu.serve.pipeline import CompilePipeline

_MULTIPROC = _jax.process_count() > 1

spmd_skip = pytest.mark.skipif(
    _MULTIPROC,
    reason="threaded serving is single-controller; SPMD uses --overload-leg",
)


@pytest.fixture(autouse=True)
def _clean_overload(monkeypatch):
    """Fast retries, clean breakers/brownout/faults, no leaked pipeline
    worker, and no half-open streams bleeding into the next test."""
    monkeypatch.setenv("RAMBA_RETRY_BASE_S", "0.001")
    faults.configure(None)
    overload.reset()
    yield
    serve.shutdown()  # also resets overload state
    faults.reset()
    fuser.sync()
    ledger.reconfigure()


def _manual_pipeline(**kw) -> CompilePipeline:
    """A pipeline whose worker never starts — tests drive dispatch
    inline with ``_drive`` for deterministic timing."""
    pipe = CompilePipeline(**kw)
    pipe._ensure_worker = lambda: None
    return pipe


def _drive(pipe: CompilePipeline, max_group: int = 8) -> int:
    """Dispatch everything queued; returns the number of groups run."""
    n = 0
    while True:
        group = pipe.queue.pop_group(
            max_group, fingerprint_of=lambda t: t.work.fingerprint,
            timeout=0)
        if not group:
            return n
        pipe._dispatch_group(group)
        n += 1


# -- deadlines ---------------------------------------------------------------


def test_deadline_clock():
    d = overload.Deadline(50.0)
    assert not d.expired()
    assert 0.0 < d.remaining_s() <= 0.05
    late = overload.Deadline(50.0, now=time.monotonic() - 1.0)
    assert late.expired() and late.remaining_s() < 0
    assert late.elapsed_ms() >= 1000.0


def test_mint_deadline_opt_in(monkeypatch):
    assert overload.mint_deadline(None) is None
    assert overload.mint_deadline(10.0).budget_ms == 10.0
    monkeypatch.setenv("RAMBA_DEADLINE_MS", "250")
    assert overload.mint_deadline(None).budget_ms == 250.0
    monkeypatch.setenv("RAMBA_DEADLINE_MS", "0")
    assert overload.mint_deadline(None) is None


def test_clamp_watchdog():
    d = overload.Deadline(10_000.0)
    # remaining dominates a larger watchdog; watchdog dominates a larger
    # remaining; no deadline leaves the watchdog untouched (incl. None)
    assert overload.clamp_watchdog(30.0, d) < 10.0
    assert overload.clamp_watchdog(1.0, d) == 1.0
    assert overload.clamp_watchdog(None, d) <= 10.0
    assert overload.clamp_watchdog(5.0, None) == 5.0
    assert overload.clamp_watchdog(None, None) is None
    expired = overload.Deadline(10.0, now=time.monotonic() - 1.0)
    # floored so an expired budget still arms (0 would mean "unarmed")
    assert overload.clamp_watchdog(30.0, expired) == pytest.approx(0.001)


@spmd_skip
def test_expired_deadline_sheds_before_dispatch():
    """A queued flush whose budget expired is shed in O(ms) with a
    classified DeadlineExceededError — before compile/dispatch — and the
    shed array self-heals on next touch."""
    pipe = _manual_pipeline()
    with serve.Session(tenant="dl", pipeline=pipe, deadline_ms=20) as s:
        a = rt.ones((16, 16)) * 3.0
        ticket = s.flush()
        assert ticket.deadline is not None
        time.sleep(0.05)  # budget spent while queued
        t0 = time.perf_counter()
        _drive(pipe)
        shed_wall = time.perf_counter() - t0
        with pytest.raises(overload.DeadlineExceededError) as ei:
            ticket.wait(5)
        assert ei.value.shed_classification == "deadline"
        assert ei.value.stage == "dispatch"
        assert shed_wall < 0.25  # no compile happened behind the shed
        assert registry.get("serve.shed.deadline") >= 1
        sheds = events.last(5, type="shed")
        assert any(e["reason"] == "deadline" for e in sheds)
    # self-heal OUTSIDE the session: inside it every re-flush inherits
    # the stream's 20ms budget (compile alone blows that), which is the
    # deadline doing its job — the undeadlined default stream heals it
    np.testing.assert_allclose(a.asarray(), 3.0)


@spmd_skip
def test_fresh_deadline_admits():
    pipe = _manual_pipeline()
    with serve.Session(tenant="dl2", pipeline=pipe, deadline_ms=60_000) as s:
        a = rt.ones((8, 8)) + 1.0
        ticket = s.flush()
        _drive(pipe)
        assert ticket.wait(5) == []
        np.testing.assert_allclose(a.asarray(), 2.0)


def test_deadline_rung_pruning_and_exhaustion():
    """Rungs whose rolling p50 cannot fit the remaining budget are
    skipped; when nothing fits the ladder sheds with stage='ladder'."""
    ledger.reconfigure(min_samples=3)
    for _ in range(4):
        ledger.observe_flush({"label": "L", "wall_s": 10.0})
        ledger.observe_flush({"label": "L", "degraded": "split",
                              "wall_s": 0.001})
    assert ledger.rung_quantile("L", "fused", 0.5) == 10.0
    assert ledger.rung_quantile("L", "split", 0.5) == 0.001
    assert ledger.rung_quantile("L", "chunked", 0.5) is None  # no history
    d = overload.Deadline(100.0)
    rungs = [("fused", lambda: 1), ("split", lambda: 2),
             ("chunked", lambda: 3)]
    kept = overload.prune_rungs(rungs, d, "L")
    # fused (p50=10s) cannot fit 100ms; split can; chunked has no
    # history so it gets the benefit of the doubt
    assert [n for n, _ in kept] == ["split", "chunked"]
    assert registry.get("serve.deadline_rung_skips") >= 1
    # all rungs over budget -> classified shed at the ladder stage
    with pytest.raises(overload.DeadlineExceededError) as ei:
        overload.prune_rungs([("fused", lambda: 1)], d, "L")
    assert ei.value.stage == "ladder"
    # no deadline -> untouched
    assert overload.prune_rungs(rungs, None, "L") is rungs


# -- CoDel sojourn control ---------------------------------------------------


def test_codel_tolerates_spikes_drops_standing_queue():
    c = overload._CoDel()
    t = 100.0
    # below target: never drops, resets the above-clock
    assert not c.should_drop(0.01, target_s=0.05, interval_s=0.2, now=t)
    # a transient spike above target survives the interval grace
    assert not c.should_drop(0.06, target_s=0.05, interval_s=0.2, now=t)
    assert not c.should_drop(0.07, target_s=0.05, interval_s=0.2, now=t + 0.1)
    # dipping below target resets — no drop even after the interval
    assert not c.should_drop(0.01, target_s=0.05, interval_s=0.2, now=t + 0.15)
    assert not c.should_drop(0.08, target_s=0.05, interval_s=0.2, now=t + 0.2)
    # standing above target for the whole interval: drop-from-front
    assert c.should_drop(0.08, target_s=0.05, interval_s=0.2, now=t + 0.45)
    assert c.drops == 1


def test_sojourn_shed_via_dispatch_verdict(monkeypatch):
    monkeypatch.setenv("RAMBA_SERVE_SOJOURN_MS", "5")
    monkeypatch.setenv("RAMBA_SERVE_SOJOURN_INTERVAL_MS", "1")
    old = time.perf_counter() - 1.0  # 1s sojourn >> 5ms target
    # first verdict arms the CoDel above-clock, second (past the 1ms
    # interval) drops
    overload.dispatch_verdict(deadline=None, enqueued_at=old,
                              tenant="sj", priority=False, label="L")
    time.sleep(0.005)
    with pytest.raises(overload.ShedError) as ei:
        overload.dispatch_verdict(deadline=None, enqueued_at=old,
                                  tenant="sj", priority=False, label="L")
    assert ei.value.reason == "sojourn"
    assert registry.get("serve.shed.sojourn") >= 1


# -- brownout state machine --------------------------------------------------


def test_brownout_transitions_and_events():
    b = overload._Brownout()
    assert b.state == "green"
    # one hot signal -> yellow
    assert b.update(queue_ratio=0.6, memory_frac=0.0,
                    breached=False) == "yellow"
    # two hot signals (or one extreme) -> red
    assert b.update(queue_ratio=0.6, memory_frac=0.9,
                    breached=False) == "red"
    assert b.update(queue_ratio=0.96, memory_frac=0.0,
                    breached=False) == "red"
    # cool signals recover
    assert b.update(queue_ratio=0.0, memory_frac=0.0,
                    breached=False) == "green"
    assert b.transitions["green->yellow"] == 1
    assert b.transitions["yellow->red"] == 1
    evs = events.last(10, type="brownout")
    assert any(e["from"] == "yellow" and e["to"] == "red" for e in evs)


def test_brownout_gates_speculative_and_red_sheds():
    assert overload.allow_speculative()
    overload._brownout.update(queue_ratio=0.6, memory_frac=0.0,
                              breached=False)
    assert not overload.allow_speculative()
    # admit_submit recomputes from live signals: a backlog at the full
    # depth cap is the queue signal that forces red
    cap = overload.queue_depth_cap()
    with pytest.raises(overload.ShedError) as ei:
        overload.admit_submit(tenant="t", priority=False, queue_depth=cap)
    assert ei.value.reason == "brownout"
    assert overload.brownout_state() == "red"
    # priority tenants ride through red
    overload.admit_submit(tenant="t", priority=True, queue_depth=cap)


@spmd_skip
def test_warm_work_shed_under_brownout():
    pipe = _manual_pipeline()
    overload._brownout.state = "yellow"
    ran = []
    t = pipe.submit_warm(lambda: ran.append(1), label="warm-test")
    assert t.done and t.wait(1) == []
    assert ran == []  # the thunk never ran — and never queued
    assert len(pipe.queue) == 0
    assert registry.get("serve.warm_shed") >= 1


# -- circuit breakers --------------------------------------------------------


def test_breaker_full_cycle(monkeypatch):
    monkeypatch.setenv("RAMBA_BREAKER_THRESHOLD", "3")
    monkeypatch.setenv("RAMBA_BREAKER_COOLDOWN_S", "0.05")
    b = overload.CircuitBreaker("acme")
    b.admit()
    b.record(False)
    b.record(False)
    assert b.state == "closed"  # under threshold
    b.record(False)
    assert b.state == "open" and b.trips == 1
    # open fails fast — O(ms), carries retry_after
    t0 = time.perf_counter()
    with pytest.raises(overload.CircuitOpenError) as ei:
        b.admit()
    assert (time.perf_counter() - t0) < 0.005
    assert ei.value.shed_classification == "breaker"
    assert ei.value.retry_after_s is not None
    # cooldown -> half-open, exactly one probe
    time.sleep(0.06)
    b.admit()
    assert b.state == "half_open"
    with pytest.raises(overload.CircuitOpenError):
        b.admit()  # second concurrent probe refused
    # probe success closes and clears the failure window
    b.record(True)
    assert b.state == "closed"
    b.record(False)
    assert b.state == "closed"  # window was cleared on close
    evs = events.last(10, type="breaker")
    assert any(e["action"] == "open" for e in evs)
    assert any(e["action"] == "closed" for e in evs)


def test_breaker_probe_failure_reopens(monkeypatch):
    monkeypatch.setenv("RAMBA_BREAKER_THRESHOLD", "1")
    monkeypatch.setenv("RAMBA_BREAKER_COOLDOWN_S", "0.02")
    b = overload.CircuitBreaker("x")
    b.record(False)
    assert b.state == "open"
    time.sleep(0.03)
    b.admit()  # the probe
    b.record(False)
    assert b.state == "open" and b.trips == 2


@spmd_skip
def test_breaker_trips_on_flush_errors_and_fails_fast(monkeypatch):
    """Repeated flush errors open the tenant's breaker; the next submit
    fails in O(ms) with no prepare work and the pending graph intact."""
    monkeypatch.setenv("RAMBA_BREAKER_THRESHOLD", "2")
    monkeypatch.setenv("RAMBA_BREAKER_COOLDOWN_S", "30")
    pipe = _manual_pipeline()
    with serve.Session(tenant="flaky", pipeline=pipe) as s:
        faults.configure("compile:always:fatal")
        doomed = []
        for _ in range(2):
            fuser._compile_cache.clear()  # cached compiles skip the site
            doomed.append(rt.ones((8, 8)) * 2.0)
            t = s.flush()
            _drive(pipe)
            with pytest.raises(faults.InjectedFault):
                t.wait(5)
        faults.configure(None)
        assert overload.breaker_for("flaky").state == "open"
        a = rt.ones((8, 8)) * 5.0
        t0 = time.perf_counter()
        with pytest.raises(overload.CircuitOpenError):
            s.flush()
        assert (time.perf_counter() - t0) < 0.05
        # the rejected submit detached nothing: the array still flushes
        np.testing.assert_allclose(a.asarray(), 5.0)
        # sheds must not feed the breaker's failure window back
        assert len(overload.breaker_for("flaky").failures) == 2
        s.close(drain=False)


# -- bounded fairness queue --------------------------------------------------


def test_queue_depth_cap_rejects_with_classified_error():
    q = RoundRobin(depth_cap=2)
    q.push("a", 1)
    q.push("a", 2)
    before = registry.get("serve.shed.queue_full")
    with pytest.raises(overload.QueueFullError) as ei:
        q.push("a", 3)
    assert ei.value.tenant == "a" and ei.value.cap == 2
    assert registry.get("serve.shed.queue_full") == before + 1
    assert any(e["reason"] == "queue_full"
               for e in events.last(5, type="shed"))
    # other tenants are unaffected; popping frees capacity
    q.push("b", 1)
    assert q.pop_group(1, timeout=0) == [1]
    q.push("a", 3)
    assert q.depth("a") == 2


def test_queue_depth_env_default(monkeypatch):
    monkeypatch.setenv("RAMBA_SERVE_QUEUE_DEPTH", "1")
    q = RoundRobin()
    q.push("a", 1)
    with pytest.raises(overload.QueueFullError):
        q.push("a", 2)
    monkeypatch.setenv("RAMBA_SERVE_QUEUE_DEPTH", "0")  # 0 disables
    for i in range(100):
        q.push("a", i)


@spmd_skip
def test_submit_unwinds_on_queue_full(monkeypatch):
    """The depth cap is the last-resort backstop: a backlog at the cap
    already reads as red brownout, so non-priority submits shed *before*
    the push — only priority traffic (which rides through red) can reach
    QueueFullError.  A rejection after prepare must release the work's
    pins so the arrays self-heal."""
    monkeypatch.setenv("RAMBA_SERVE_QUEUE_DEPTH", "1")
    pipe = _manual_pipeline()
    with serve.Session(tenant="qf", pipeline=pipe, priority=True) as s:
        a = rt.ones((8, 8)) * 2.0
        t1 = s.flush()
        b = rt.ones((8, 8)) * 7.0
        with pytest.raises(overload.QueueFullError):
            s.flush()
        assert len(s.stream.inflight) == 1  # the rejected ticket unwound
        _drive(pipe)
        assert t1.wait(5) == []
        np.testing.assert_allclose(a.asarray(), 2.0)
        np.testing.assert_allclose(b.asarray(), 7.0)  # self-healed


# -- ticket abandonment (regression) -----------------------------------------


@spmd_skip
def test_abandoned_ticket_discarded_not_written_back():
    """wait(timeout) expiry abandons the ticket: the classified
    TicketAbandoned (still a TimeoutError for caller compat) replaces
    the bare TimeoutError, the queued dispatch is dropped instead of
    executing for nobody, and the arrays self-heal on next touch."""
    pipe = _manual_pipeline()
    with serve.Session(tenant="ab", pipeline=pipe) as s:
        a = rt.ones((8, 8)) * 4.0
        ticket = s.flush()
        with pytest.raises(TimeoutError) as ei:  # caller-compatible type
            ticket.wait(0.01)  # worker disabled: guaranteed to expire
        assert isinstance(ei.value, overload.TicketAbandoned)
        assert ticket.abandoned and not ticket.done
        before = registry.get("serve.abandoned_drop")
        _drive(pipe)
        assert registry.get("serve.abandoned_drop") == before + 1
        with pytest.raises(overload.TicketAbandoned):
            ticket.wait(5)
        assert any(e["reason"] == "abandoned"
                   for e in events.last(5, type="shed"))
        # nothing was executed for the abandoned ticket...
        assert not isinstance(a._expr, Const)
        # ...and the array still self-heals to the right bytes
        np.testing.assert_allclose(a.asarray(), 4.0)
        s.close(drain=False)


@spmd_skip
def test_late_completion_skips_write_back():
    """A ticket abandoned mid-dispatch must not write results back into
    the stream the caller walked away from."""
    pipe = _manual_pipeline()
    with serve.Session(tenant="late", pipeline=pipe) as s:
        a = rt.ones((8, 8)) * 9.0
        ticket = s.flush()
        work = ticket.work
        # simulate "abandoned after dispatch started": the pipeline's
        # pre-dispatch drop check has passed, the probe flips later
        work.is_abandoned = lambda: True
        result = fuser._flush_dispatch(work)
        assert registry.get("serve.abandoned_late") >= 1
        assert not isinstance(a._expr, Const)  # no write-back
        # resolve before touching: materialization drains the stream,
        # which would otherwise wait forever on the undone ticket
        ticket._resolve(result)
        np.testing.assert_allclose(a.asarray(), 9.0)  # self-heals
        s.close(drain=False)


# -- shed classification -----------------------------------------------------


def test_sheds_classify_fatal_never_retryable():
    """Every overload error must classify 'fatal' in retry.classify —
    re-attempting a shed defeats the shed.  TicketAbandoned is the sharp
    case: it IS a TimeoutError, which classifies retryable by default."""
    assert retry.classify(TimeoutError("bare")) == "retryable"  # baseline
    for exc in (
        overload.DeadlineExceededError("d"),
        overload.QueueFullError("t", 5, 5),
        overload.ShedError("brownout"),
        overload.CircuitOpenError("t", "open"),
        overload.TicketAbandoned("gone"),
        overload.OverloadError("generic"),
    ):
        assert retry.classify(exc) == "fatal", type(exc).__name__


# -- hedged dispatch ---------------------------------------------------------


def test_hedge_threshold_gates(monkeypatch):
    class _P:
        instrs = [("mul", None, (0, 1))]
        n_leaves = 2
        out_slots = (2,)

    class _Host:
        instrs = [("apply", "<function f at 0x7f>", (0,))]
        n_leaves = 1
        out_slots = (1,)

    # factor unset -> off even for pure programs
    monkeypatch.delenv("RAMBA_HEDGE_FACTOR", raising=False)
    assert overload.hedge_threshold("L", _P(), ()) is None
    monkeypatch.setenv("RAMBA_HEDGE_FACTOR", "2.0")
    # pure + history -> threshold = factor * p95
    ledger.reconfigure(min_samples=3)
    for _ in range(4):
        ledger.observe_flush({"label": "HL", "wall_s": 0.1})
    assert overload.hedge_threshold("HL", _P(), ()) == pytest.approx(0.2)
    # no history -> off
    assert overload.hedge_threshold("nohist", _P(), ()) is None
    # host-effecting program -> never hedged
    assert overload.hedge_threshold("HL", _Host(), ()) is None
    # donation -> never hedged (the loser would read consumed buffers)
    assert overload.hedge_threshold("HL", _P(), (0,)) is None


def test_run_hedged_primary_wins_no_hedge():
    span = {"calls": []}
    out = overload.run_hedged(lambda sp: ("ok", "fused"), 5.0,
                              span=span, label="L")
    assert out == ("ok", "fused")
    assert registry.get("serve.hedge.fired") == 0


def test_run_hedged_hedge_wins_and_loser_cancelled():
    from ramba_tpu.resilience import elastic

    release = threading.Event()
    primary_cancelled = threading.Event()
    calls = []

    def execute(sp):
        calls.append(1)
        if len(calls) == 1:  # primary: stall until released, then check
            release.wait(10)
            if elastic.cancelled():
                primary_cancelled.set()
                raise RuntimeError("cancelled loser")
            return ("primary", "fused")
        return ("hedge", "fused")

    span = {"calls": []}
    out = overload.run_hedged(execute, 0.02, span=span, label="L")
    assert out == ("hedge", "fused")
    assert registry.get("serve.hedge.fired") == 1
    assert registry.get("serve.hedge.won_hedge") == 1
    release.set()
    assert primary_cancelled.wait(5)  # loser saw its cancel flag
    evs = events.last(10, type="hedge")
    assert any(e["action"] == "fired" for e in evs)
    assert any(e.get("winner") == "hedge" for e in evs)


def test_run_hedged_propagates_winner_error():
    def execute(sp):
        raise ValueError("boom")

    with pytest.raises(ValueError, match="boom"):
        overload.run_hedged(execute, 5.0, span={"calls": []}, label="L")


@spmd_skip
def test_hedge_byte_identity_on_vs_off(monkeypatch):
    """End-to-end: a seeded serve:hedge delay makes the primary slow,
    the hedge fires and wins, and the winner's bytes are identical to
    the unhedged run — that is what the purity certificate buys."""
    pipe = _manual_pipeline()

    def run_once(session_tenant):
        with serve.Session(tenant=session_tenant, pipeline=pipe) as s:
            a = (rt.ones((16, 16)) * 3.0) + 1.0
            t = s.flush()
            _drive(pipe)
            t.wait(10)
            return np.asarray(a.asarray()).copy()

    # unhedged baseline + rolling history for the program's label
    ledger.reconfigure(min_samples=3)
    baseline = run_once("h0")
    for i in range(4):
        np.testing.assert_array_equal(run_once(f"warm{i}"), baseline)
    # arm hedging: tiny threshold so the seeded 150ms primary delay
    # always loses the race to the un-delayed hedge attempt
    monkeypatch.setenv("RAMBA_HEDGE_FACTOR", "0.5")
    faults.configure("serve:hedge:delay:ms=150")
    fired_before = registry.get("serve.hedge.fired")
    hedged = run_once("hedged")
    faults.configure(None)
    assert registry.get("serve.hedge.fired") == fired_before + 1
    assert registry.get("serve.hedge.won_hedge") >= 1
    np.testing.assert_array_equal(hedged, baseline)


# -- fault sites -------------------------------------------------------------


def test_serve_admit_fault_becomes_shed(monkeypatch):
    """An injected serve:admit fault is converted into a shed verdict
    (reason=fault) — the hook the rank-skewed chaos leg drives."""
    faults.configure("serve:admit:2")
    with pytest.raises(overload.ShedError) as ei:
        overload.dispatch_verdict(deadline=None, enqueued_at=None,
                                  tenant="f", priority=False, label="L")
    assert ei.value.reason == "fault"
    with pytest.raises(overload.ShedError):
        overload.dispatch_verdict(deadline=None, enqueued_at=None,
                                  tenant="f", priority=False, label="L")
    # spec exhausted (mode "2" = first two checks): admitted now
    overload.dispatch_verdict(deadline=None, enqueued_at=None,
                              tenant="f", priority=False, label="L")
    assert registry.get("serve.shed.fault") >= 2


def test_verdict_inactive_is_free():
    """No deadline, no sojourn target, no serve:admit fault: the verdict
    decides nothing and must not emit, count, or agree."""
    before = registry.get("serve.shed")
    overload.dispatch_verdict(deadline=None, enqueued_at=time.perf_counter(),
                              tenant="idle", priority=False, label="L")
    assert registry.get("serve.shed") == before


# -- observability -----------------------------------------------------------


def test_overload_report_and_diagnostics():
    overload._brownout.update(queue_ratio=0.6, memory_frac=0.0,
                              breached=False)
    overload.breaker_for("rep").record(False)
    rep = overload.report()
    assert rep["brownout"]["state"] == "yellow"
    assert rep["breakers"]["rep"]["recent_failures"] == 1
    assert "queue_depth_cap" in rep
    from ramba_tpu import diagnostics
    import io

    buf = io.StringIO()
    diagnostics.report(file=buf)
    # the section renders once there is overload activity
    assert "brownout=yellow" in buf.getvalue()


def test_breaker_trip_is_flight_incident():
    from ramba_tpu.observe import telemetry

    assert telemetry.is_incident({"type": "breaker", "action": "open"})
    assert not telemetry.is_incident({"type": "breaker",
                                      "action": "closed"})
    assert telemetry.is_incident({"type": "slo_breach"})
