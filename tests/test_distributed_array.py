"""Differential tests vs NumPy.

Port of the reference test strategy (/root/reference/ramba/tests/
test_distributed_array.py): run the same closure once with app=numpy and once
with app=ramba_tpu and compare (`run_both`, reference :240-260).  Class split
mirrors the reference: TestBasic / TestOps / TestBroadcast / TestReduction /
TestFusion / TestRandom / TestDel / TestApps.
"""

import numpy as np
import pytest

import ramba_tpu as rt


def _to_np(x):
    if hasattr(x, "asarray"):
        return x.asarray()
    return np.asarray(x) if isinstance(x, (list, tuple, np.ndarray)) else x


def run_both(fn, rtol=None):
    """Reference: run_both/rb_comparer (test_distributed_array.py:240-260)."""
    expected = fn(np)
    got = fn(rt)
    compare(got, expected, rtol)


def compare(got, expected, rtol=None):
    from tests.helpers import default_atol, default_rtol

    if isinstance(expected, (tuple, list)) and not isinstance(expected, np.ndarray):
        assert len(got) == len(expected)
        for g, e in zip(got, expected):
            compare(g, e, rtol)
        return
    g = _to_np(got)
    e = np.asarray(expected)
    assert np.asarray(g).shape == e.shape, f"{np.asarray(g).shape} != {e.shape}"
    np.testing.assert_allclose(
        np.asarray(g, dtype=e.dtype), e,
        rtol=default_rtol(rtol), atol=default_atol(),
    )


class TestBasic:
    def test_arange(self):
        run_both(lambda app: app.arange(100))

    def test_arange_start_step(self):
        run_both(lambda app: app.arange(3, 50, 4))

    def test_linspace(self):
        run_both(lambda app: app.linspace(0.0, 5.0, 17))

    def test_zeros_ones_full(self):
        run_both(lambda app: app.zeros((5, 7)))
        run_both(lambda app: app.ones(11))
        run_both(lambda app: app.full((3, 4), 2.5))

    def test_eye(self):
        run_both(lambda app: app.eye(7))
        run_both(lambda app: app.eye(5, 8, 2))

    def test_slicing(self):
        def f(app):
            a = app.arange(100).reshape(10, 10)
            return a[2:7, 3], a[::2], a[1:9:3, ::-1], a[-3:, -4:-1]

        run_both(f)

    def test_negative_step(self):
        def f(app):
            a = app.arange(30)
            return a[::-1], a[25:3:-2], a[::-3]

        run_both(f)

    def test_setitem_slice(self):
        def f(app):
            a = app.zeros((8, 8))
            a[2:5, 1:7] = 3.0
            a[0] = app.arange(8)
            return a

        run_both(f)

    def test_view_write_through(self):
        def f(app):
            a = app.zeros((6, 6))
            b = a[2:4]
            b += 5.0
            return a

        run_both(f)

    def test_transpose_write_through(self):
        def f(app):
            a = app.arange(12).reshape(3, 4).astype(float)
            t = a.T
            t += 1.0
            return a

        run_both(f)

    def test_fancy_index_get(self):
        def f(app):
            a = app.arange(50) * 2
            idx = app.asarray(np.array([3, 7, 1, 42, 0]))
            return a[idx]

        run_both(f)

    def test_fancy_index_set(self):
        def f(app):
            a = app.zeros(20)
            idx = np.array([1, 5, 9])
            a[app.asarray(idx)] = 7.0
            return a

        run_both(f)

    def test_concatenate(self):
        def f(app):
            a = app.arange(10).reshape(2, 5)
            b = app.ones((3, 5))
            return app.concatenate([a, b], axis=0)

        run_both(f)

    def test_stack_split(self):
        def f(app):
            a = app.arange(12)
            b = a * 2
            s = app.stack([a, b])
            parts = app.split(app.arange(12), 3)
            return s, parts[0], parts[2]

        run_both(f)

    def test_pad(self):
        def f(app):
            a = app.arange(6).reshape(2, 3).astype(float)
            return (
                app.pad(a, 1),
                app.pad(a, ((1, 2), (0, 1)), mode="edge"),
                app.pad(a, 2, mode="wrap"),
            )

        run_both(f)

    def test_triu_tril(self):
        def f(app):
            a = app.arange(25).reshape(5, 5)
            return app.triu(a), app.tril(a, -1), app.triu(a, 2)

        run_both(f)

    def test_where(self):
        def f(app):
            a = app.arange(20) - 10
            return app.where(a > 0, a, -a)

        run_both(f)

    def test_clip(self):
        run_both(lambda app: app.clip(app.arange(20) - 10, -3, 5))

    def test_reshape(self):
        def f(app):
            a = app.arange(24)
            return a.reshape(4, 6), a.reshape(2, 3, 4), a.reshape(-1, 12)

        run_both(f)

    def test_reshape_general(self):
        # general reshape = full redistribution in the reference
        # (ramba.py:2409-2491); free here
        run_both(lambda app: app.arange(36).reshape(6, 6).reshape(4, 9))

    def test_mgrid(self):
        def f(app):
            g = app.mgrid[0:5, 0:3]
            return g

        run_both(f)

    def test_meshgrid(self):
        def f(app):
            x = app.arange(4)
            y = app.arange(3)
            xx, yy = app.meshgrid(x, y)
            return xx, yy

        run_both(f)

    def test_flip_roll(self):
        def f(app):
            a = app.arange(12).reshape(3, 4)
            return app.flip(a, 0), app.roll(app.arange(10), 3)

        run_both(f)

    def test_masked(self):
        def f(app):
            a = app.arange(20).astype(float)
            if app is np:
                a[a > 10] += 100.0
            else:
                a[a > 10] += 100.0
            return a

        run_both(f)

    def test_masked_reduction(self):
        a = rt.arange(20) - 10
        m = a[a > 0]
        assert float(m.sum()) == float(np.sum(np.arange(20)[np.arange(20) > 10] - 10))
        npa = np.arange(20) - 10
        assert float(m.mean()) == pytest.approx(float(npa[npa > 0].mean()))

    def test_masked_array_host_mask(self):
        # round-5: MaskedArray accepts a host numpy selection mask directly
        # (True = selected, the a[a > 0] polarity — inverse of np.ma)
        v = np.random.RandomState(7).rand(8, 8)
        sel = v <= 0.8
        m = rt.MaskedArray(rt.fromarray(v), mask=sel)
        ref = np.ma.masked_array(v, mask=~sel)
        assert float(m.mean()) == pytest.approx(float(ref.mean()))
        assert float(m.var(ddof=1)) == pytest.approx(float(ref.var(ddof=1)))
        assert int(m.count()) == int(sel.sum())

    def test_masked_var_std_ddof(self):
        # round-3 verdict weak #7: ddof was accepted and silently dropped
        x = np.random.RandomState(3).randn(6, 8)
        a = rt.fromarray(x)
        m = a[a > 0]
        ref = np.ma.masked_array(x, mask=~(x > 0))
        for ddof in (0, 1, 2):
            assert float(m.var(ddof=ddof)) == pytest.approx(
                float(ref.var(ddof=ddof))
            )
            assert float(m.std(ddof=ddof)) == pytest.approx(
                float(ref.std(ddof=ddof))
            )
        from tests.helpers import default_rtol

        np.testing.assert_allclose(
            np.asarray(m.var(axis=0, ddof=1)),
            ref.var(axis=0, ddof=1).filled(0.0),
            rtol=default_rtol(1e-7),
        )

    def test_masked_setitem(self):
        def f(app):
            a = app.arange(10).astype(float)
            a[a < 5] = -1.0
            return a

        run_both(f)

    def test_astype(self):
        run_both(lambda app: app.arange(10).astype(np.float32).astype(np.int64))

    def test_scalar_index(self):
        a = rt.arange(10) * 3
        assert int(a[4]) == 12
        assert float(a[-1]) == 27.0

    def test_item_bool(self):
        assert bool(rt.asarray(np.array(True)))
        assert int(rt.arange(5).sum()) == 10

    def test_len_iter(self):
        a = rt.arange(5)
        assert len(a) == 5
        assert [int(x) for x in a] == [0, 1, 2, 3, 4]

    def test_repeat_tile(self):
        def f(app):
            a = app.arange(4)
            return app.repeat(a, 3), app.tile(a, 2)

        run_both(f)

    def test_sort(self):
        def f(app):
            a = app.asarray(np.array([5.0, 1.0, 4.0, 2.0, 3.0]))
            return app.sort(a), app.argsort(a)

        run_both(f)

    def test_expand_squeeze(self):
        def f(app):
            a = app.arange(6).reshape(2, 3)
            b = app.expand_dims(a, 0)
            return b, app.squeeze(b)

        run_both(f)

    def test_newaxis(self):
        def f(app):
            a = app.arange(5)
            return a[:, None] + a[None, :]

        run_both(f)


class TestOps:
    """Matrix of operand combinations — reference TestOps runs every binop
    over dist/non-dist/0-d/numpy/scalar pairs."""

    @pytest.mark.parametrize("op", ["add", "subtract", "multiply", "true_divide",
                                    "floor_divide", "mod", "power", "maximum",
                                    "minimum", "arctan2", "hypot"])
    def test_binop_array_array(self, op):
        def f(app):
            a = app.arange(1, 25).reshape(4, 6).astype(float)
            b = app.full((4, 6), 2.5)
            return getattr(app, op)(a, b) if hasattr(app, op) else None

        run_both(f)

    @pytest.mark.parametrize("s", [3, -1.5, 2.0])
    def test_binop_scalar(self, s):
        def f(app):
            a = app.arange(10).astype(float)
            return a + s, s + a, a * s, a - s, s - a, a / s, a ** 2

        run_both(f)

    def test_binop_numpy_operand(self):
        npb = np.arange(12, dtype=float).reshape(3, 4) + 1

        def f(app):
            a = app.arange(12).reshape(3, 4).astype(float)
            return a + npb, npb + a, a * npb

        run_both(f)

    def test_comparisons(self):
        def f(app):
            a = app.arange(10)
            return a > 4, a <= 2, a == 5, a != 5

        run_both(f)

    @pytest.mark.parametrize("op", ["sin", "cos", "tan", "exp", "log", "sqrt",
                                    "tanh", "arctan", "floor", "ceil", "abs"])
    def test_unary(self, op):
        def f(app):
            a = app.arange(1, 30).astype(float) / 7.0
            return getattr(app, op)(a)

        run_both(f, rtol=1e-12)

    def test_unary_methods(self):
        a = rt.arange(1, 10).astype(float)
        np.testing.assert_allclose(a.sqrt().asarray(), np.sqrt(np.arange(1, 10.0)))

    def test_iops(self):
        def f(app):
            a = app.arange(10).astype(float)
            a += 1
            a *= 2
            a -= 3
            a /= 4
            return a

        run_both(f)

    def test_iop_int_preserves_dtype(self):
        from tests.helpers import map_dtype

        a = rt.arange(10)
        a += 1
        assert a.dtype == map_dtype(np.arange(10).dtype)

    def test_divmod_neg_pos_abs(self):
        def f(app):
            a = app.arange(10) - 5
            return -a, +a, abs(a), a // 3, a % 3

        run_both(f)

    def test_bitwise(self):
        def f(app):
            a = app.arange(16)
            return a & 5, a | 3, a ^ 9, a << 2, a >> 1

        run_both(f)

    def test_pow_small_int_exponents(self):
        # the strength-reduction peephole (make_map) must preserve numpy
        # dtype/value semantics, including the bool**int -> int8 promotion
        def f(app):
            a = app.arange(11) - 5
            x = app.arange(11) / 3.0
            return a ** 2, a ** 3, a ** 4, x ** 1, x ** 2, x ** 5

        run_both(f)
        # bool base must NOT be strength-reduced to bool*bool: numpy
        # promotes bool**int to an integer dtype (int8; jax picks int64 —
        # the width differs but the kind must be integral)
        b = rt.fromarray(np.array([True, False, True]))
        assert (b ** 2).dtype.kind == "i"
        np.testing.assert_array_equal((b ** 2).asarray(),
                                      np.array([1, 0, 1]))

    def test_zero_d(self):
        def f(app):
            a = app.arange(10)
            s = a.sum()
            return a + s, s * 2

        run_both(f)

    def test_numpy_ufunc_protocol(self):
        a = rt.arange(8).astype(float)
        out = np.sin(a)  # dispatches through __array_ufunc__
        assert isinstance(out, rt.ndarray)
        np.testing.assert_allclose(out.asarray(), np.sin(np.arange(8.0)))

    def test_numpy_function_protocol(self):
        a = rt.arange(8).astype(float)
        assert isinstance(np.sum(a), rt.ndarray)
        assert float(np.sum(a)) == 28.0
        c = np.concatenate([a, a])
        assert isinstance(c, rt.ndarray)
        assert c.shape == (16,)


class TestBroadcast:
    def test_broadcast_binop(self):
        def f(app):
            a = app.arange(12).reshape(3, 4).astype(float)
            b = app.arange(4).astype(float)
            return a + b, a * b

        run_both(f)

    def test_outer_style(self):
        # BASELINE config 5: A[:,None]+B[None,:] cross-shard broadcast
        def f(app):
            a = app.arange(50).astype(float)
            b = app.arange(40).astype(float)
            return a[:, None] + b[None, :]

        run_both(f)

    def test_broadcast_to(self):
        run_both(lambda app: app.broadcast_to(app.arange(4), (3, 4)))

    def test_scalar_broadcast_3d(self):
        def f(app):
            a = app.arange(24).reshape(2, 3, 4)
            b = app.arange(4)
            return a - b

        run_both(f)


class TestReduction:
    @pytest.mark.parametrize("red", ["sum", "prod", "min", "max", "mean"])
    def test_full_reduce(self, red):
        def f(app):
            a = app.arange(1, 25).reshape(4, 6).astype(float) / 10.0
            return getattr(app, red)(a)

        run_both(f)

    @pytest.mark.parametrize("axis", [0, 1, None, (0, 1)])
    def test_axis_sum(self, axis):
        def f(app):
            a = app.arange(24).reshape(4, 6).astype(float)
            return app.sum(a, axis=axis)

        run_both(f)

    def test_keepdims(self):
        run_both(lambda app: app.sum(app.arange(24).reshape(4, 6), axis=1,
                                     keepdims=True))

    def test_var_std(self):
        def f(app):
            a = app.arange(20).astype(float)
            return app.var(a), app.std(a), a.var(ddof=1), a.std(ddof=1)

        run_both(f)

    def test_any_all(self):
        def f(app):
            a = app.arange(10)
            return app.any(a > 8), app.all(a >= 0), app.any(a > 100)

        run_both(f)

    def test_argminmax(self):
        def f(app):
            a = app.asarray(np.array([3.0, 1.0, 4.0, 1.0, 5.0, 0.5]))
            return app.argmin(a), app.argmax(a)

        run_both(f)

    def test_method_reductions(self):
        a = rt.arange(24).reshape(4, 6).astype(float)
        e = np.arange(24).reshape(4, 6).astype(float)
        np.testing.assert_allclose(a.sum(axis=0).asarray(), e.sum(axis=0))
        np.testing.assert_allclose(a.max(axis=1).asarray(), e.max(axis=1))
        assert float(a.mean()) == e.mean()

    def test_cumsum(self):
        def f(app):
            a = app.arange(20).astype(float)
            b = app.arange(12).reshape(3, 4)
            return app.cumsum(a), app.cumsum(b, axis=0), app.cumsum(b, axis=1)

        run_both(f)

    def test_nan_reductions(self):
        v = np.array([1.0, np.nan, 3.0, np.nan, 5.0])

        def f(app):
            a = app.asarray(v)
            return app.nansum(a), app.nanmean(a), app.nanmax(a)

        run_both(f)

    def test_count_nonzero(self):
        run_both(lambda app: app.count_nonzero(app.arange(10) % 3))

    def test_reduce_then_use(self):
        # reduction result feeding back into elementwise (fusion across)
        def f(app):
            a = app.arange(100).astype(float)
            return (a - app.mean(a)) / app.std(a)

        run_both(f)


class TestLinalg:
    def test_matmul_2d(self):
        def f(app):
            a = app.arange(24).reshape(4, 6).astype(float)
            b = app.arange(30).reshape(6, 5).astype(float)
            return a @ b

        run_both(f)

    def test_dot_vec(self):
        def f(app):
            a = app.arange(10).astype(float)
            return app.dot(a, a)

        run_both(f)

    def test_matvec(self):
        def f(app):
            a = app.arange(12).reshape(3, 4).astype(float)
            v = app.arange(4).astype(float)
            return a @ v

        run_both(f)

    def test_matmul_nd(self):
        def f(app):
            a = app.arange(2 * 3 * 4).reshape(2, 3, 4).astype(float)
            b = app.arange(2 * 4 * 5).reshape(2, 4, 5).astype(float)
            return app.matmul(a, b)

        run_both(f)

    def test_tensordot_einsum_outer(self):
        def f(app):
            a = app.arange(12).reshape(3, 4).astype(float)
            b = app.arange(12).reshape(4, 3).astype(float)
            return (
                app.tensordot(a, b, axes=1),
                app.einsum("ij,jk->ik", a, b),
                app.outer(app.arange(3), app.arange(4)),
            )

        run_both(f)

    def test_matmul_big_sharded(self):
        n = 256
        a = rt.ones((n, n))
        c = (a @ a).asarray()
        np.testing.assert_allclose(c, np.full((n, n), float(n)))


class TestFusion:
    """Reference perf-invariants (test_distributed_array.py:112-199) re-cast
    as compile/flush-count assertions: 10 chained ops must flush as ONE
    compiled module, and a repeated identical graph must hit the compile
    cache."""

    def test_chain_fuses_to_one_flush(self):
        rt.sync()
        before = dict(rt.fuser_stats)
        a = rt.arange(1000).astype(float)
        for _ in range(10):
            a += 1
        rt.sync()
        after = dict(rt.fuser_stats)
        assert after["flushes"] == before["flushes"] + 1

    def test_compile_cache_hit(self):
        def step():
            a = rt.arange(512).astype(float)
            b = rt.sin(a) * 2 + 1
            rt.sync()
            return b

        step()
        rt.sync()
        before = dict(rt.fuser_stats)
        step()
        after = dict(rt.fuser_stats)
        assert after["compiles"] == before["compiles"], "expected compile-cache hit"

    def test_common_subexpr_shared(self):
        a = rt.arange(100).astype(float)
        b = rt.sin(a)
        c = b + 1
        d = b * 2
        rt.sync()
        from tests.helpers import default_rtol

        np.testing.assert_allclose(
            (c + d).asarray(), np.sin(np.arange(100.0)) * 3 + 1,
            rtol=default_rtol(1e-7),
        )


class TestRandom:
    def test_shapes_dtype(self):
        a = rt.random.random((100, 4))
        assert a.shape == (100, 4)
        v = a.asarray()
        assert ((v >= 0) & (v < 1)).all()

    def test_seed_determinism(self):
        rt.random.seed(42)
        a = rt.random.normal(size=1000).asarray()
        rt.random.seed(42)
        b = rt.random.normal(size=1000).asarray()
        np.testing.assert_array_equal(a, b)

    def test_normal_moments(self):
        rt.random.seed(0)
        a = rt.random.normal(loc=3.0, scale=2.0, size=200_000)
        assert float(a.mean()) == pytest.approx(3.0, abs=0.05)
        assert float(a.std()) == pytest.approx(2.0, abs=0.05)

    def test_randint(self):
        v = rt.random.randint(5, 15, size=1000).asarray()
        assert v.min() >= 5 and v.max() < 15

    def test_default_rng(self):
        r = rt.random.default_rng(7)
        v = r.random(100).asarray()
        assert v.shape == (100,)


class TestDel:
    def test_dead_lazy_array_skipped(self):
        rt.sync()
        a = rt.arange(1000) * 3
        del a
        rt.sync()  # must not fail; dead root simply vanishes

    def test_gc_frees_pending(self):
        import gc

        from ramba_tpu.core import fuser

        rt.sync()
        a = rt.arange(100) + 1
        del a
        gc.collect()
        assert all(
            r() is None or isinstance(r()._expr, type(None).__class__) or True
            for r in list(fuser._pending.values())
        )
        rt.sync()


class TestApps:
    """End-to-end mini-apps (reference TestApps: manual matmuls, π
    integration, test_distributed_array.py)."""

    def test_pi_integration(self):
        # reference: test_pi_integration_fused (:100-108)
        n = 1_000_000
        x = (rt.arange(n) + 0.5) / n
        pi = 4.0 * rt.mean(1.0 / (1.0 + x * x))
        assert float(pi) == pytest.approx(np.pi, abs=1e-5)

    def test_benchmark_chain(self):
        # the headline benchmark (reference README.md:39-55) at small scale
        def f(app):
            A = app.arange(10000) / 1000.0
            B = app.sin(A)
            C = app.cos(A)
            return B * B + C ** 2

        run_both(f, rtol=1e-12)

    def test_jacobi_small(self):
        def f(app):
            a = app.zeros((32, 32))
            a[0, :] = 1.0
            for _ in range(5):
                b = a.copy()
                interior = (
                    b[:-2, 1:-1] + b[2:, 1:-1] + b[1:-1, :-2] + b[1:-1, 2:]
                ) / 4.0
                a[1:-1, 1:-1] = interior
            return a

        run_both(f)

    def test_manual_matmul(self):
        # reference TestApps manual matmul via broadcast+reduce
        def f(app):
            a = app.arange(12).reshape(3, 4).astype(float)
            b = app.arange(20).reshape(4, 5).astype(float)
            return app.sum(a[:, :, None] * b[None, :, :], axis=1)

        run_both(f)


class TestReviewRegressions:
    """Regressions for the round-1 code-review findings."""

    def test_mixed_advanced_indexing(self):
        def f(app):
            a = app.zeros((5, 5))
            a[app.asarray(np.array([0, 2])), 1] = 9.0
            return a, a[app.asarray(np.array([0, 2])), 1]

        run_both(f)

    def test_ufunc_reduce_axis(self):
        a = rt.arange(12).reshape(3, 4).astype(float)
        e = np.arange(12).reshape(3, 4).astype(float)
        r = np.add.reduce(a, axis=1)
        np.testing.assert_allclose(_to_np(r), np.add.reduce(e, axis=1))

    def test_like_on_pylist(self):
        compare(rt.zeros_like([1, 2, 3]), np.zeros_like([1, 2, 3]))
        compare(rt.ones_like([[1.0, 2.0]]), np.ones_like([[1.0, 2.0]]))
        compare(rt.full_like([1, 2], 7), np.full_like([1, 2], 7))

    def test_bool_masked_minmax(self):
        b = rt.asarray(np.array([True, False, True]))
        assert bool(b[b].max()) is True
        assert bool(b[b].min()) is True

    def test_moveaxis_negative(self):
        def f(app):
            a = app.arange(24).reshape(2, 3, 4)
            return app.moveaxis(a, -1, 0), app.moveaxis(a, 0, -1)

        run_both(f)

    def test_no_namespace_leakage(self):
        assert not hasattr(rt, "np")
        assert not hasattr(rt, "Node")
        assert not hasattr(rt, "as_exprable")


class TestApps:
    """Reference: TestApps (test_distributed_array.py) — manual matmuls via
    broadcast/expand_dims + reduction, and the pi-integration demo."""

    def test_matmul1_broadcast_transpose(self):
        def impl(app):
            A = app.fromfunction(lambda x, y: x + y, (20, 30))
            B = app.fromfunction(lambda x, y: x + y, (30, 40))
            return (
                app.broadcast_to(A.T, (40, 30, 20)).T
                * app.broadcast_to(B, (20, 30, 40))
            ).sum(axis=1)

        run_both(impl)

    def test_matmul2_expand_dims(self):
        def impl(app):
            A = app.fromfunction(lambda x, y: x + y, (20, 30))
            B = app.fromfunction(lambda x, y: x + y, (30, 40))
            return (app.expand_dims(A, 2) * B).sum(axis=1)

        run_both(impl)

    def test_matmul_big_fused(self):
        # Reference: test_matmul_big1/2 — broadcasted products must run
        # without materializing the 3-D intermediate (sized for the CPU test
        # mesh; the no-temporaries guarantee itself is asserted via XLA
        # memory analysis in test_fusion.py).
        A = rt.fromfunction(lambda x, y: x + y, (300, 330))
        B = rt.fromfunction(lambda x, y: x + y, (330, 360))
        C = (rt.expand_dims(A, 2) * B).sum(axis=1)
        c_12_4 = ((np.arange(330) + 12) * (np.arange(330) + 4)).sum()
        assert float(C[12, 4]) == float(c_12_4)

    def test_pi_integration(self):
        def impl(app):
            nsteps = 1000
            step = 1.0 / nsteps
            X = app.linspace(0.5 * step, 1.0 - 0.5 * step, nsteps)
            Y = 1.0 / (1.0 + X * X)
            pi = 4.0 * step * app.sum(Y)
            return int(pi * 1e8)

        run_both(impl)

    def test_sum_asarray_kwarg(self):
        # Reference: reduction asarray=True keeps the deferred result in
        # (1,)-array form (sample pi demo; ramba.py:6778).
        Y = rt.arange(1000).astype(np.float64)
        s = rt.sum(Y, asarray=True)
        assert s.shape == (1,)
        assert float(s[0]) == float(np.arange(1000).sum())
        s2 = Y.sum(asarray=True)
        assert s2.shape == (1,)
        assert float(s2[0]) == float(np.arange(1000).sum())


class TestAverageMedian:
    def test_average_plain(self):
        run_both(lambda app: app.average(app.arange(20).reshape(4, 5)))

    def test_average_axis_weights(self):
        w = np.array([1.0, 2.0, 3.0, 4.0])

        def impl(app):
            a = app.arange(20).reshape(4, 5).astype(np.float64)
            return app.average(a, axis=0, weights=w)

        run_both(impl)

    def test_average_full_weights(self):
        w = np.arange(1.0, 21.0).reshape(4, 5)

        def impl(app):
            a = app.arange(20).reshape(4, 5).astype(np.float64)
            return app.average(a, axis=1, weights=w)

        run_both(impl)

    def test_average_returned(self):
        w = np.array([1.0, 2.0, 3.0])
        e_avg, e_scl = np.average(np.arange(12.0).reshape(3, 4), axis=0,
                                  weights=w, returned=True)
        g_avg, g_scl = rt.average(rt.arange(12.0).reshape(3, 4), axis=0,
                                  weights=w, returned=True)
        np.testing.assert_allclose(_to_np(g_avg), e_avg)
        np.testing.assert_allclose(_to_np(g_scl), np.broadcast_to(e_scl, e_avg.shape))

    def test_average_errors(self):
        a = rt.arange(12.0).reshape(3, 4)
        with pytest.raises(TypeError):
            rt.average(a, weights=np.ones(3))
        with pytest.raises(ValueError):
            rt.average(a, axis=0, weights=np.ones(4))

    def test_median_axis(self):
        def impl(app):
            a = app.arange(24).reshape(4, 6).astype(np.float64)
            return app.median(a), app.median(a, axis=1), app.median(a, axis=0)

        run_both(impl)


class TestOpsMatrix:
    """Reference TestOps test1-test14: every pairing of distributed (large),
    replicated (small, below the dist threshold), and 0-d operands against
    ramba/numpy/scalar counterparts."""

    SIZES = {"dist": 2048, "small": 8}  # 8 < RAMBA_DIST_THRESHOLD=100

    @pytest.mark.parametrize("ls", ["dist", "small"])
    @pytest.mark.parametrize("rs", ["dist", "small"])
    def test_ramba_ramba(self, ls, rs):
        nl, nr = self.SIZES[ls], self.SIZES[rs]
        if nl != nr:
            pytest.skip("shape mismatch combo")

        def f(app):
            a = app.arange(nl).astype(np.float64) + 1
            b = app.arange(nr).astype(np.float64) * 2 + 1
            return a + b, a * b, a / b, a - b

        run_both(f)

    @pytest.mark.parametrize("side", ["left", "right"])
    @pytest.mark.parametrize("sz", ["dist", "small"])
    def test_ramba_numpy(self, side, sz):
        n = self.SIZES[sz]
        nb = np.linspace(1.0, 2.0, n)

        def f(app):
            a = app.arange(n).astype(np.float64) + 1
            return (a + nb, a * nb) if side == "left" else (nb + a, nb * a)

        run_both(f)

    @pytest.mark.parametrize("sz", ["dist", "small"])
    def test_ramba_0d(self, sz):
        n = self.SIZES[sz]

        def f(app):
            a = app.arange(n).astype(np.float64) + 1
            z = app.asarray(np.float64(3.0)) if app is np else app.fromarray(np.float64(3.0))
            return a + z, z * a, a / z

        run_both(f)

    def test_0d_0d(self):
        x = rt.fromarray(np.float64(3.0))
        y = rt.fromarray(np.float64(4.0))
        assert float(x + y) == 7.0
        assert float(x * y) == 12.0
        assert (x + y).shape == ()

    def test_0d_scalar_and_casts(self):
        # reference TestBasic 0-d family: getitem/setitem/float-cast
        z = rt.zeros(())
        z += 5
        assert float(z) == 5.0
        a = rt.arange(10).astype(np.float64)
        s = a[3]          # 0-d view of a distributed array
        assert s.shape == ()
        assert float(s) == 3.0
        a[3] = 99.0       # 0-d setitem
        assert float(a[3]) == 99.0


class TestDgemm:
    """Reference TestDgemm: matmul/dot over transposed, sliced and N-D
    operand shapes."""

    def _ab(self, app, sa, sb):
        a = app.arange(int(np.prod(sa))).reshape(sa).astype(np.float64)
        b = app.arange(int(np.prod(sb))).reshape(sb).astype(np.float64) + 1
        return a, b

    def test_2Dx1D(self):
        run_both(lambda app: app.matmul(*self._ab(app, (6, 4), (4,))))

    def test_1Dx2D(self):
        run_both(lambda app: app.matmul(*self._ab(app, (4,), (4, 5))))

    def test_2Dx2D(self):
        run_both(lambda app: app.matmul(*self._ab(app, (5, 7), (7, 3))))

    def test_2DTx2DT(self):
        def f(app):
            a, b = self._ab(app, (7, 5), (3, 7))
            return app.matmul(a.T, b.T)

        run_both(f)

    def test_2Dx2D_slice(self):
        def f(app):
            a, b = self._ab(app, (8, 10), (12, 6))
            return app.matmul(a[1:6, 2:8], b[3:9, :4])

        run_both(f)

    def test_3Dx1D(self):
        run_both(lambda app: app.matmul(*self._ab(app, (2, 5, 4), (4,))))

    def test_1Dx3D(self):
        run_both(lambda app: app.matmul(*self._ab(app, (5,), (2, 5, 4))))

    def test_5Dx3D(self):
        run_both(lambda app: app.matmul(
            *self._ab(app, (2, 1, 3, 4, 5), (3, 5, 2))))

    def test_dot_3Dx1D(self):
        run_both(lambda app: app.dot(*self._ab(app, (2, 5, 4), (4,))))

    def test_dot_1Dx3D(self):
        # np.dot(1-D, N-D) sums over the second-to-last axis of b
        run_both(lambda app: app.dot(*self._ab(app, (5,), (2, 5, 4))))

    def test_dot_5Dx3D(self):
        run_both(lambda app: app.dot(
            *self._ab(app, (2, 1, 3, 4, 5), (3, 5, 2))))


class TestDel:
    """Reference TestDel: deleting arrays/views must not corrupt others
    sharing state, and pending lazy nodes must survive deletion of inputs."""

    def test_del_base_keeps_view_data(self):
        a = rt.arange(100).astype(np.float64)
        v = a + 1  # lazy node referencing a
        del a
        np.testing.assert_allclose(v.asarray(), np.arange(100.0) + 1)

    def test_del_pending_output(self):
        a = rt.arange(50).astype(np.float64)
        b = a * 2
        del b  # pending node dropped before any flush
        rt.sync()
        np.testing.assert_allclose(a.asarray(), np.arange(50.0))

    def test_del_view_then_write_base(self):
        a = rt.fromarray(np.arange(20.0))
        t = a[5:15]
        del t
        a += 1
        np.testing.assert_allclose(a.asarray(), np.arange(20.0) + 1)


class TestDistributionArgument:
    """Reference docs: 'all the functions in Ramba that generate a new array
    take an additional distribution parameter' (docs/index.md)."""

    def test_creation_with_distribution(self):
        from jax.sharding import PartitionSpec as P

        from ramba_tpu.parallel import mesh as _mesh

        n = 1024
        d0 = _mesh.get_mesh().shape["d0"]
        for make in (
            lambda d: rt.zeros((n, 8), distribution=d),
            lambda d: rt.ones((n, 8), distribution=d),
            lambda d: rt.full((n, 8), 3.0, distribution=d),
            lambda d: rt.fromfunction(lambda i, j: i + j, (n, 8), distribution=d),
        ):
            # (nw, 1): explicit split counts -> realized with whatever mesh
            # axes multiply to nw; P("d0"): raw spec -> d0-way split
            nw = rt.num_workers()
            from tests.helpers import local_shard_count

            for dist, rows in (((nw, 1), n // nw), (P("d0"), n // d0)):
                a = make(dist)
                assert a.shape == (n, 8)
                v = a._value()
                # one addressable shard per LOCAL device (the nw global
                # shards split across processes on the cross-process leg)
                assert len(v.addressable_shards) == local_shard_count()
                assert v.addressable_shards[0].data.shape[0] == rows

    def test_arange_linspace_distribution(self):
        from tests.helpers import local_shard_count

        nw = rt.num_workers()
        a = rt.arange(4096, distribution=(nw,))
        assert len(a._value().addressable_shards) == local_shard_count()
        le = rt.linspace(0.0, 1.0, 4096, distribution=(nw,))
        np.testing.assert_allclose(le.asarray(), np.linspace(0.0, 1.0, 4096))

    def test_elementwise_preserves_distribution(self):
        # docs: 'Elementwise operations on such arrays maintain this selected
        # partitioning on the output arrays' — GSPMD propagates shardings
        nw = rt.num_workers()
        a = rt.zeros((1024, 8), distribution=(nw, 1)) + 1.0
        v = a._value()
        assert v.addressable_shards[0].data.shape[0] == 1024 // nw


class TestFlags:
    """Reference: ndarray_flags + set_writeable (ramba.py:5365,5358-5365)."""

    def test_readonly_blocks_writes(self):
        a = rt.arange(10).astype(np.float64)
        a.flags.writeable = False
        assert not a.flags.writeable
        with pytest.raises(ValueError):
            a[0] = 1.0
        with pytest.raises(ValueError):
            a += 1
        a.flags.writeable = True
        a[0] = 1.0
        assert float(a[0]) == 1.0

    def test_view_of_readonly_is_readonly(self):
        a = rt.arange(10).astype(np.float64)
        a.flags.writeable = False
        v = a[2:5]
        assert not v.flags.writeable
        with pytest.raises(ValueError):
            v.flags.writeable = True  # reference raises for this case
        with pytest.raises(ValueError):
            v += 1

    def test_dict_style_access(self):
        a = rt.arange(5)
        assert a.flags["WRITEABLE"]
        a.flags["WRITEABLE"] = False
        with pytest.raises(ValueError):
            a[0] = 1


class TestExtras:
    """Secondary NumPy surface (ramba_tpu/ops/extras.py)."""

    def test_lazy_static_shape(self):
        from tests.helpers import x64_enabled

        ntn_kw = {} if x64_enabled() else {"posinf": 7.0}

        def f(app):
            a = app.arange(10).astype(np.float64)
            b = app.arange(12).reshape(3, 4).astype(np.float64)
            return (
                app.diff(a), app.diff(b, axis=0), app.cross(
                    app.asarray(np.array([1.0, 0, 0])),
                    app.asarray(np.array([0, 1.0, 0]))),
                app.kron(app.asarray(np.array([1.0, 2.0])),
                         app.asarray(np.array([3.0, 4.0]))),
                # x32 only: pin the inf fill (the default, dtype max, is
                # regime-dependent); x64 keeps default-fill parity coverage
                app.nan_to_num(
                    app.asarray(np.array([1.0, np.nan, np.inf])), **ntn_kw
                ),
            )

        run_both(f)

    def test_gradient(self):
        x = np.arange(20.0) ** 2
        g = rt.gradient(rt.fromarray(x))
        np.testing.assert_allclose(_to_np(g), np.gradient(x))
        m = np.arange(12.0).reshape(3, 4)
        gs = rt.gradient(rt.fromarray(m))
        es = np.gradient(m)
        for got, e in zip(gs, es):
            np.testing.assert_allclose(_to_np(got), e)

    def test_searchsorted_digitize_isin(self):
        def f(app):
            a = app.asarray(np.array([1.0, 3.0, 5.0, 7.0]))
            v = app.asarray(np.array([2.0, 6.0]))
            return (app.searchsorted(a, v),
                    app.digitize(v, np.array([0.0, 4.0, 8.0])),
                    app.isin(app.arange(6), np.array([1, 4])))

        run_both(f)

    def test_bincount(self):
        x = np.array([0, 1, 1, 3, 2, 1])

        def f(app):
            return app.bincount(app.asarray(x)), app.bincount(
                app.asarray(x), minlength=8)

        run_both(f)

    def test_cov_corrcoef(self):
        m = np.random.RandomState(0).rand(3, 8)

        def f(app):
            return app.cov(app.asarray(m)), app.corrcoef(app.asarray(m))

        run_both(f, rtol=1e-8)

    def test_convolve_interp(self):
        def f(app):
            a = app.asarray(np.array([1.0, 2.0, 3.0]))
            v = app.asarray(np.array([0.0, 1.0, 0.5]))
            x = app.asarray(np.array([1.5, 2.5]))
            xp = app.asarray(np.array([1.0, 2.0, 3.0]))
            fp = app.asarray(np.array([3.0, 2.0, 0.0]))
            return app.convolve(a, v), app.interp(x, xp, fp)

        run_both(f)

    def test_host_boundary_ops(self):
        x = np.array([3, 1, 2, 3, 0, 1])
        np.testing.assert_array_equal(rt.unique(rt.fromarray(x)), np.unique(x))
        np.testing.assert_array_equal(
            rt.nonzero(rt.fromarray(x))[0], np.nonzero(x)[0])
        np.testing.assert_array_equal(
            rt.setdiff1d(rt.fromarray(x), np.array([1, 3])),
            np.setdiff1d(x, [1, 3]))
        h, edges = rt.histogram(rt.fromarray(x.astype(float)), bins=4)
        eh, ee = np.histogram(x.astype(float), bins=4)
        np.testing.assert_array_equal(h, eh)
        np.testing.assert_allclose(edges, ee)

    def test_append(self):
        def f(app):
            a = app.arange(6).reshape(2, 3)
            return (app.append(a, app.ones((1, 3), dtype=a.dtype), axis=0),
                    app.append(app.arange(3), app.arange(2)))

        run_both(f)

    def test_extra_ufuncs(self):
        def f(app):
            a = app.arange(1, 7)
            return (app.gcd(a, app.full_like(a, 4)),
                    app.lcm(a, app.full_like(a, 3)),
                    app.fabs(app.arange(-3.0, 3.0)),
                    app.sinc(app.arange(5).astype(np.float64) / 7))

        run_both(f, rtol=1e-8)


class TestExtrasReviewFixes:
    def test_interp_left_right(self):
        x = np.array([-1.0, 5.0])
        xp, fp = np.array([0.0, 1.0]), np.array([10.0, 20.0])
        got = rt.interp(rt.fromarray(x), xp, fp, left=-7.0, right=99.0)
        np.testing.assert_allclose(_to_np(got),
                                   np.interp(x, xp, fp, left=-7.0, right=99.0))

    def test_argwhere_exported(self):
        x = np.array([0, 3, 0, 5])
        np.testing.assert_array_equal(rt.argwhere(rt.fromarray(x)),
                                      np.argwhere(x))

    def test_nan_to_num_kwargs(self):
        x = np.array([np.nan, np.inf, -np.inf])
        got = rt.nan_to_num(rt.fromarray(x), nan=1.0, posinf=2.0, neginf=-2.0)
        np.testing.assert_allclose(_to_np(got), [1.0, 2.0, -2.0])
