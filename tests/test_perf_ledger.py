"""Kernel cost ledger, slow-flush sentinel, and perf tooling (ramba-perf).

Covers ``ramba_tpu.observe.ledger`` + the fuser hooks + the offline CLIs:

* rolling-window p50/p95 math and full-history count/total/min/max,
* stable kernel fingerprints (equal cache keys fingerprint equally;
  donation mask and semantic regime separate them),
* ledger accumulation through real flushes (compile vs execute
  attribution, cache hit/miss, rung counts, bytes),
* true-LRU compile cache with ``fuser.cache_evict`` counter + event,
* the slow-flush sentinel firing exactly once per offending flush under
  an injected ``delay:ms=`` fault,
* the ``delay:ms=<n>`` RAMBA_FAULTS grammar itself,
* ``scripts/perf_diff.py`` verdicts on synthetic captures,
* ``scripts/trace_report.py --merge-ranks`` over hand-built multi-rank
  JSONL (including a truncated final line), and slow_flush visibility in
  the single-file report,
* ``observe.events`` rank re-probing (no permanent ``(0, 1)`` cache
  before distributed bring-up).
"""

import io
import json
import os
import subprocess
import sys
import time

import pytest

import jax as _jax
import ramba_tpu as rt
from ramba_tpu import diagnostics
from ramba_tpu.core import fuser
from ramba_tpu.core.expr import Const
from ramba_tpu.observe import events, ledger
from ramba_tpu.resilience import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_MULTIPROC = _jax.process_count() > 1


def _chain():
    a = rt.arange(512) * 3.0 + 1.0
    return float(rt.sum(a))


# ---------------------------------------------------------------------------
# rolling stats + fingerprints (pure units)
# ---------------------------------------------------------------------------


def test_rolling_window_quantile_math():
    r = ledger._Rolling(window=128)
    for i in range(1, 101):
        r.add(float(i))
    assert r.count == 100
    assert r.min == 1.0 and r.max == 100.0
    assert abs(r.total - 5050.0) < 1e-9
    assert r.quantile(0.50) == 50.0
    assert r.quantile(0.95) == 95.0
    assert r.quantile(1.0) == 100.0
    s = r.summary()
    assert s["p50_s"] == 50.0 and s["p95_s"] == 95.0

    # quantiles are over the bounded window; count/total keep full history
    r2 = ledger._Rolling(window=4)
    for v in (1.0, 2.0, 3.0, 4.0, 100.0):
        r2.add(v)
    assert r2.count == 5
    assert r2.quantile(0.5) == 3.0  # window is [2, 3, 4, 100]

    assert ledger._Rolling(window=4).quantile(0.5) is None


def test_fingerprint_stable_and_distinct():
    prog_key = ((("mul", None, (0,)),), 1, ("C",), (1,))
    key_a = (prog_key, (), (False,))
    # a separately-constructed equal tuple must fingerprint identically
    key_b = (((("mul", None, (0,)),), 1, ("C",), (1,)), (), (False,))
    fp = ledger.fingerprint(key_a)
    assert fp == ledger.fingerprint(key_b)
    assert len(fp) == 12
    # donation mask and semantic regime are part of the kernel identity
    assert ledger.fingerprint((prog_key, (0,), (False,))) != fp
    assert ledger.fingerprint((prog_key, (), (True,))) != fp
    # objects whose repr embeds addresses degrade to type/qualname tokens:
    # two distinct-but-equal-shaped closures must not split the fingerprint
    key_c = (prog_key, (), (False,), (lambda x: x,))
    key_d = (prog_key, (), (False,), (lambda x: x,))
    assert ledger.fingerprint(key_c) == ledger.fingerprint(key_d)


# ---------------------------------------------------------------------------
# ledger accumulation through real flushes
# ---------------------------------------------------------------------------


def test_ledger_accumulates_compile_and_exec():
    fuser.flush()
    diagnostics.reset()
    fuser._compile_cache.clear()
    v1 = _chain()
    v2 = _chain()
    assert v1 == v2
    rep = diagnostics.perf_report()
    fused = [k for k in rep["kernels"].values() if k["rungs"].get("fused")]
    assert fused, rep["kernels"]
    k = max(fused, key=lambda e: e["cache"]["misses"])
    assert k["label"].startswith("prog_")
    assert k["compiles"] >= 1
    assert k["compile_s"] > 0.0
    assert k["exec"]["count"] >= 1
    assert k["exec"]["p50_s"] is not None and k["exec"]["p50_s"] > 0.0
    assert k["exec"]["min_s"] <= k["exec"]["p50_s"] <= k["exec"]["max_s"]
    assert k["cache"]["misses"] >= 1 and k["cache"]["hits"] >= 1
    assert k["bytes_out"] > 0
    assert k["rungs"]["fused"] >= 2
    # per-program flush wall windows feed the sentinel
    assert rep["flushes"]
    win = list(rep["flushes"].values())[0]
    assert win["count"] >= 2 and win["p50_s"] > 0.0


def test_sync_mode_records_synchronized_window():
    fuser.flush()
    ledger.reconfigure(mode="sync")
    try:
        diagnostics.reset()
        fuser._compile_cache.clear()
        _chain()
        _chain()
        rep = diagnostics.perf_report()
        assert rep["mode"] == "sync"
        synced = [k for k in rep["kernels"].values() if k.get("sync")]
        assert synced, rep["kernels"]
        s = synced[0]["sync"]
        assert s["count"] >= 1 and s["p50_s"] > 0.0
        if not _MULTIPROC:
            # sync mode implies cost capture; CPU XLA supplies flops
            assert any(k.get("flops") is not None
                       for k in rep["kernels"].values())
    finally:
        ledger.reconfigure()  # back to env-driven config


def test_ledger_records_eager_rung():
    fuser.flush()
    diagnostics.reset()
    a = rt.arange(64) * 2.0
    program, leaves, _ = fuser._prepare_program([a._expr])
    leaf_vals = [fuser.leaf_value(lf) if isinstance(lf, Const) else lf.value
                 for lf in leaves]
    outs = fuser._run_eager(program, leaf_vals, None)
    assert len(outs) == 1
    rep = diagnostics.perf_report()
    rungs = {}
    for k in rep["kernels"].values():
        for name, n in k["rungs"].items():
            rungs[name] = rungs.get(name, 0) + n
    assert rungs.get("eager", 0) >= 1, rungs


def test_diagnostics_report_includes_kernel_table():
    _chain()
    buf = io.StringIO()
    diagnostics.report(file=buf)
    out = buf.getvalue()
    assert "-- kernels" in out
    assert "hit/miss/evict" in out


# ---------------------------------------------------------------------------
# true-LRU compile cache + evict accounting
# ---------------------------------------------------------------------------


def test_compile_cache_true_lru_with_evict_counter(monkeypatch):
    from ramba_tpu.parallel import mesh as _mesh

    fuser.flush()
    monkeypatch.setattr(fuser, "_COMPILE_CACHE_MAX", 2)
    saved = dict(fuser._compile_cache)
    fuser._compile_cache.clear()
    fuser._cache_epoch = _mesh.mesh_epoch
    try:
        # jax.jit traces lazily, so programs with fake op names are safe
        # in _get_compiled as long as the returned fn is never called
        progs = [
            fuser._Program((((f"fakeop{i}", None, (0,)),)), 1, ("C",), (1,))
            for i in range(3)
        ]
        keys = [fuser._cache_key(p, ()) for p in progs]
        before = diagnostics.counters().get("fuser.cache_evict", 0)

        _fn, new0, fp0, _b = fuser._get_compiled(progs[0], ())
        assert new0
        _fn, new1, _, _b = fuser._get_compiled(progs[1], ())
        assert new1
        _fn, hit0, fp0b, _b = fuser._get_compiled(progs[0], ())  # refresh
        assert not hit0 and fp0b == fp0
        _fn, new2, _, _b = fuser._get_compiled(progs[2], ())  # evicts prog1
        assert new2

        # FIFO would have evicted prog0 (oldest insert); true LRU keeps it
        # because the hit refreshed its recency, and evicts prog1 instead
        assert keys[0] in fuser._compile_cache
        assert keys[1] not in fuser._compile_cache
        assert keys[2] in fuser._compile_cache

        after = diagnostics.counters().get("fuser.cache_evict", 0)
        assert after == before + 1
        evs = events.last(5, type="cache_evict")
        assert evs and evs[-1]["key"] == ledger.fingerprint(keys[1])
        # the ledger distinguishes capacity churn from cold misses
        entry = diagnostics.perf_report()["kernels"][
            ledger.fingerprint(keys[1])]
        assert entry["cache"]["evicts"] >= 1
    finally:
        fuser._compile_cache.clear()
        fuser._compile_cache.update(saved)


def test_program_fix_point_construction():
    # sanity: the hand-built _Program above matches what _get_compiled
    # expects (instrs tuple-of-tuples, out slot past the leaves)
    p = fuser._Program((("fakeop", None, (0,)),), 1, ("C",), (1,))
    assert p.key[0] == (("fakeop", None, (0,)),)
    assert p.n_leaves == 1 and p.out_slots == (1,)


# ---------------------------------------------------------------------------
# delay fault grammar + slow-flush sentinel
# ---------------------------------------------------------------------------


def test_delay_fault_grammar():
    sp = faults._parse_one("execute:delay:ms=50")
    assert sp.mode == "delay" and sp.kind == "delay"
    assert sp.delay_ms == 50.0
    with pytest.raises(ValueError):
        faults._parse_one("execute:delay")  # ms= payload required
    with pytest.raises(ValueError):
        faults._parse_one("execute:once:ms=50")  # ms= only with delay
    with pytest.raises(ValueError):
        faults._parse_one("execute:delay:ms=-5")
    with pytest.raises(ValueError):
        faults._parse_one("execute:delay:fatal:ms=5")  # delay takes no kind
    with pytest.raises(ValueError):
        faults._parse_one("execute:delay:ms=5:ms=6")


def test_delay_fault_sleeps_without_raising():
    with faults.active("mysite:delay:ms=40"):
        t0 = time.perf_counter()
        faults.check("mysite")  # must NOT raise
        dt = time.perf_counter() - t0
    assert dt >= 0.03, dt
    ev = events.last(3, type="fault")[-1]
    assert ev["site"] == "mysite"
    assert ev["kind"] == "delay" and ev["ms"] == 40.0


def test_slow_flush_sentinel_fires_once_per_offending_flush():
    fuser.flush()
    ledger.reconfigure(min_samples=3, factor=5.0)
    try:
        for _ in range(4):  # build the rolling baseline
            _chain()
        base = len(events.last(0, type="slow_flush"))
        with faults.active("execute:delay:ms=150"):
            _chain()
        assert len(events.last(0, type="slow_flush")) == base + 1
        with faults.active("execute:delay:ms=150"):
            _chain()  # a second offending flush fires exactly once more
        assert len(events.last(0, type="slow_flush")) == base + 2
        ev = events.last(1, type="slow_flush")[-1]
        for k in ("label", "rung", "wall_s", "p50_s", "slowdown",
                  "bytes_in", "bytes_out", "compile_s", "execute_s",
                  "cache"):
            assert k in ev, f"slow_flush missing {k!r}"
        assert ev["label"].startswith("prog_")
        assert ev["rung"] == "fused"
        assert ev["wall_s"] > ev["p50_s"] * 5.0
        assert diagnostics.counters().get("perf.slow_flush", 0) >= 2
        assert diagnostics.perf_report()["slow_flushes"] >= 2
    finally:
        ledger.reconfigure()


def test_sentinel_quiet_on_healthy_flushes_and_disabled_by_factor():
    fuser.flush()
    ledger.reconfigure(min_samples=3, factor=5.0)
    try:
        base = len(events.last(0, type="slow_flush"))
        for _ in range(6):
            _chain()
        assert len(events.last(0, type="slow_flush")) == base
        # factor <= 0 disables the sentinel even for a glacial flush
        ledger.reconfigure(min_samples=3, factor=0.0)
        with faults.active("execute:delay:ms=150"):
            _chain()
        assert len(events.last(0, type="slow_flush")) == base
    finally:
        ledger.reconfigure()


# ---------------------------------------------------------------------------
# perf_diff CLI on synthetic captures
# ---------------------------------------------------------------------------


def _capture(p50: float, value: float = 2.0) -> dict:
    return {
        "value": value,
        "kernels": {
            "abc123def456": {
                "label": "prog_synthetic",
                "exec": {"count": 10, "p50_s": p50, "total_s": p50 * 10},
                "compile_s": 0.4,
            },
        },
    }


def _run_perf_diff(tmp_path, old: dict, new: dict, *extra):
    f_old = tmp_path / "old.json"
    f_new = tmp_path / "new.json"
    f_old.write_text(json.dumps(old))
    f_new.write_text(json.dumps(new))
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "perf_diff.py"),
         str(f_old), str(f_new), *extra],
        capture_output=True, text=True,
    )


def test_perf_diff_identical_captures_pass(tmp_path):
    r = _run_perf_diff(tmp_path, _capture(0.01), _capture(0.01))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "verdict: ok" in r.stdout


def test_perf_diff_flags_2x_kernel_slowdown(tmp_path):
    r = _run_perf_diff(tmp_path, _capture(0.01), _capture(0.025))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "REGRESSION" in r.stdout
    assert "abc123def456" in r.stdout
    # --json mode carries the same verdict machine-readably
    rj = _run_perf_diff(tmp_path, _capture(0.01), _capture(0.025), "--json")
    assert rj.returncode == 1
    verdict = json.loads(rj.stdout)
    assert verdict["verdict"] == "regressed"
    assert verdict["regressions"][0]["ratio"] == pytest.approx(2.5)


def test_perf_diff_improvement_and_metric_direction(tmp_path):
    r = _run_perf_diff(tmp_path, _capture(0.03), _capture(0.01))
    assert r.returncode == 0
    assert "improved" in r.stdout
    # headline scalar regression (value = chain wall, lower is better)
    r2 = _run_perf_diff(tmp_path, _capture(0.01, value=2.0),
                        _capture(0.01, value=5.0))
    assert r2.returncode == 1
    assert "value" in r2.stdout


def test_perf_diff_usage_errors(tmp_path):
    # baseline without a kernels/metrics section
    f = tmp_path / "empty.json"
    f.write_text(json.dumps({"n": 1}))
    g = tmp_path / "new.json"
    g.write_text(json.dumps(_capture(0.01)))
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "perf_diff.py"),
         str(f), str(g)],
        capture_output=True, text=True,
    )
    assert r.returncode == 2
    r2 = _run_perf_diff(tmp_path, _capture(0.01), _capture(0.01),
                        "--threshold", "0.9")
    assert r2.returncode == 2


# ---------------------------------------------------------------------------
# trace_report: --merge-ranks + slow_flush visibility
# ---------------------------------------------------------------------------


def _write_rank_file(path, evs, trailing_garbage: bool = False):
    with open(path, "w") as f:
        for e in evs:
            f.write(json.dumps(e) + "\n")
        if trailing_garbage:
            # a crashed writer leaves a truncated final line
            f.write('{"type":"flush","label":"prog_tail","ts":1.0')


def test_trace_report_merge_ranks(tmp_path):
    base = tmp_path / "t.jsonl"
    r0 = [
        {"type": "health", "source": "distributed_init", "outcome": "ok",
         "ts": 100.0, "seq": 1, "rank": 0},
        {"type": "flush", "label": "prog_a", "ts": 100.1, "seq": 2,
         "rank": 0, "wall_s": 0.01, "cache": "miss"},
        {"type": "flush", "label": "prog_b", "ts": 100.2, "seq": 3,
         "rank": 0, "wall_s": 0.01, "cache": "hit"},
    ]
    r1 = [
        {"type": "health", "source": "distributed_init", "outcome": "ok",
         "ts": 200.0, "seq": 1, "rank": 1},
        {"type": "flush", "label": "prog_a", "ts": 200.1, "seq": 2,
         "rank": 1, "wall_s": 0.01, "cache": "miss"},
        {"type": "flush", "label": "prog_b", "ts": 200.25, "seq": 3,
         "rank": 1, "wall_s": 0.3, "degraded": "chunked", "cache": "hit"},
        {"type": "slow_flush", "label": "prog_b", "rung": "chunked",
         "slowdown": 30.0, "wall_s": 0.3, "p50_s": 0.01,
         "ts": 200.26, "seq": 4, "rank": 1},
    ]
    _write_rank_file(f"{base}.rank0", r0)
    _write_rank_file(f"{base}.rank1", r1, trailing_garbage=True)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "trace_report.py"),
         str(base), "--merge-ranks"],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "2 rank(s)" in r.stdout
    # the 100 s clock skew is measured off the bring-up anchors...
    assert "r1=+100.0000s" in r.stdout
    # ...so the two bring-up events land at the same adjusted instant
    assert r.stdout.count("+   0.000s") >= 2
    # rank 1 degraded to chunked while rank 0 stayed fused at flush #1
    assert "rank divergence at flush #1" in r.stdout
    assert "r0=prog_b/fused" in r.stdout and "r1=prog_b/chunked" in r.stdout
    assert "slow_flush" in r.stdout
    # the truncated final line warns to stderr without crashing the merge
    assert "unparseable" in r.stderr


def test_trace_report_merge_ranks_lockstep(tmp_path):
    base = tmp_path / "ok.jsonl"
    for rank in range(2):
        _write_rank_file(f"{base}.rank{rank}", [
            {"type": "health", "source": "distributed_init", "outcome": "ok",
             "ts": 10.0 + rank, "seq": 1, "rank": rank},
            {"type": "flush", "label": "prog_a", "ts": 10.1 + rank, "seq": 2,
             "rank": rank, "wall_s": 0.01, "cache": "miss"},
        ])
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "trace_report.py"),
         str(base), "--merge-ranks"],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "rank divergence: none" in r.stdout


def test_trace_report_single_file_shows_slow_flush(tmp_path):
    path = tmp_path / "s.jsonl"
    _write_rank_file(path, [
        {"type": "flush", "label": "prog_a", "ts": 1.0, "seq": 1,
         "wall_s": 0.5, "cache": "hit"},
        {"type": "slow_flush", "label": "prog_a", "rung": "fused",
         "wall_s": 0.5, "p50_s": 0.01, "slowdown": 50.0, "compile_s": 0.0,
         "execute_s": 0.4, "cache": "hit", "ts": 1.5, "seq": 2},
    ])
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "trace_report.py"),
         str(path)],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "slow flushes (1):" in r.stdout
    assert "rung=fused" in r.stdout


# ---------------------------------------------------------------------------
# events rank re-probing
# ---------------------------------------------------------------------------


def test_rank_info_not_cached_until_authoritative(monkeypatch):
    monkeypatch.setattr(events, "_rank", None)
    calls = []

    def fake_probe_pre():
        calls.append(1)
        return (0, 1, False)

    monkeypatch.setattr(events, "_probe_rank", fake_probe_pre)
    assert events._rank_info() == (0, 1)
    assert events._rank_info() == (0, 1)
    assert len(calls) == 2  # non-authoritative answers are NOT cached

    monkeypatch.setattr(events, "_probe_rank", lambda: (1, 2, True))
    assert events._rank_info() == (1, 2)
    # once authoritative, the cache holds even if the probe changes
    monkeypatch.setattr(events, "_probe_rank", fake_probe_pre)
    assert events._rank_info() == (1, 2)

    # invalidate_rank (called by distributed.initialize) forces a re-probe
    events.invalidate_rank()
    assert events._rank_info() == (0, 1)


def test_probe_rank_authoritative_with_live_backend():
    # the suite has computed by now, so a backend exists: the probe must
    # be authoritative and agree with jax
    r, n, authoritative = events._probe_rank()
    assert authoritative
    assert (r, n) == (_jax.process_index(), _jax.process_count())
