"""Test harness configuration.

Mirrors the reference CI strategy (/root/reference/.github/workflows/
python-package.yml:40-46): the reference runs its suite on a fake 2-worker
cluster (Ray local + mpiexec -n 2); here we run on an 8-device virtual CPU
mesh via --xla_force_host_platform_device_count so every sharding/collective
path executes without TPU hardware, and enable x64 so numerics match NumPy
exactly for differential tests.

Must run before any jax backend initialization; the axon TPU site-hook forces
jax_platforms, so we override through jax.config rather than the env var.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
