"""Test harness configuration.

Mirrors the reference CI strategy (/root/reference/.github/workflows/
python-package.yml:40-46): the reference runs its suite on a fake 2-worker
cluster (Ray local + mpiexec -n 2); here we run on an 8-device virtual CPU
mesh via --xla_force_host_platform_device_count so every sharding/collective
path executes without TPU hardware.

Two numerics legs (round-3 verdict weak #5):

* default (``RAMBA_TEST_X64`` unset or "1"): x64 on — numerics match NumPy
  exactly, so differential tests compare bit-for-bit dtypes.
* ``RAMBA_TEST_X64=0``: x64 off — the regime that actually executes on a
  TPU, where jax truncates 64-bit dtypes to 32-bit.  Value comparisons
  stay exact (tolerances aside); dtype expectations are mapped through
  jax's truncation lattice via ``tests.helpers`` (map_dtype/oracle).

Must run before any jax backend initialization; the axon TPU site-hook forces
jax_platforms, so we override through jax.config rather than the env var.
"""

import os

# Device-count leg (reference CI runs the identical suite at 2 workers AND
# a larger count, python-package.yml:40-46): RAMBA_TEST_DEVICES=2 re-runs
# everything on a 2-device mesh; default 8.
N_DEVICES = int(os.environ.get("RAMBA_TEST_DEVICES", "8"))

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={N_DEVICES}"
)

X64 = os.environ.get("RAMBA_TEST_X64", "1") not in ("0", "")

import jax

# Hardware leg (round-4 verdict #7): RAMBA_TEST_TPU=1 leaves the site-hook's
# platform selection (axon/tpu) in place and runs in the chip's native x32
# regime — driven by scripts/tpu_test_pass.py, which probes bring-up first.
if os.environ.get("RAMBA_TEST_TPU", "") in ("1", "true"):
    jax.config.update("jax_enable_x64", False)
else:
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", X64)

