"""Test harness configuration.

Mirrors the reference CI strategy (/root/reference/.github/workflows/
python-package.yml:40-46): the reference runs its suite on a fake 2-worker
cluster (Ray local + mpiexec -n 2); here we run on an 8-device virtual CPU
mesh via --xla_force_host_platform_device_count so every sharding/collective
path executes without TPU hardware.

Two numerics legs (round-3 verdict weak #5):

* default (``RAMBA_TEST_X64`` unset or "1"): x64 on — numerics match NumPy
  exactly, so differential tests compare bit-for-bit dtypes.
* ``RAMBA_TEST_X64=0``: x64 off — the regime that actually executes on a
  TPU, where jax truncates 64-bit dtypes to 32-bit.  Value comparisons
  stay exact (tolerances aside); dtype expectations are mapped through
  jax's truncation lattice via ``tests.helpers`` (map_dtype/oracle).

Must run before any jax backend initialization; the axon TPU site-hook forces
jax_platforms, so we override through jax.config rather than the env var.
"""

import os

# Device-count leg (reference CI runs the identical suite at 2 workers AND
# a larger count, python-package.yml:40-46): RAMBA_TEST_DEVICES=2 re-runs
# everything on a 2-device mesh; default 8.
N_DEVICES = int(os.environ.get("RAMBA_TEST_DEVICES", "8"))

# Cross-process leg (round-4 verdict #4; the reference runs its ENTIRE
# suite under `mpiexec -n 2`, python-package.yml:40-46): the runner
# scripts/two_process_suite.py launches this same suite once per rank with
# RAMBA_TEST_PROCS/RAMBA_TEST_PROC_ID/RAMBA_TEST_COORD set; each rank owns
# N_DEVICES/PROCS local CPU devices and the global mesh spans both.
PROCS = int(os.environ.get("RAMBA_TEST_PROCS", "1"))

if PROCS <= 1:
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={N_DEVICES}"
    )
else:
    # per-rank local device count via XLA_FLAGS — works on every jax
    # version (the jax_num_cpu_devices config option is newer than some
    # supported releases)
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={max(1, N_DEVICES // PROCS)}"
    )

X64 = os.environ.get("RAMBA_TEST_X64", "1") not in ("0", "")

import jax

# Hardware leg (round-4 verdict #7): RAMBA_TEST_TPU=1 leaves the site-hook's
# platform selection (axon/tpu) in place and runs in the chip's native x32
# regime — driven by scripts/tpu_test_pass.py, which probes bring-up first.
if os.environ.get("RAMBA_TEST_TPU", "") in ("1", "true"):
    jax.config.update("jax_enable_x64", False)
elif PROCS > 1:
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", X64)

    from ramba_tpu.parallel import distributed

    distributed.initialize(
        coordinator_address=os.environ["RAMBA_TEST_COORD"],
        num_processes=PROCS,
        process_id=int(os.environ["RAMBA_TEST_PROC_ID"]),
    )
    assert jax.process_count() == PROCS, (
        f"cross-process leg failed to form the group: "
        f"process_count={jax.process_count()} != {PROCS}"
    )

    import hashlib
    import pathlib

    import pytest

    @pytest.fixture
    def tmp_path(request):
        """Rank-SHARED deterministic tmp dir: pytest's stock tmp_path
        numbers directories per process (rank 0 gets ...0, rank 1 races to
        ...1), so distributed save/load tests would read paths the driver
        rank never wrote.  Derive the dir from the test nodeid instead —
        identical on every rank; single-writer discipline comes from the
        driver-gated writes in ramba_tpu.fileio."""
        base = pathlib.Path(os.environ["RAMBA_TEST_SHARED_TMP"])
        d = base / hashlib.sha1(
            request.node.nodeid.encode()
        ).hexdigest()[:16]
        d.mkdir(parents=True, exist_ok=True)
        return d
else:
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", X64)

