"""Workload-level models built on the framework (ramba_tpu/models/)."""

import numpy as np

import ramba_tpu as rt
from tests.helpers import default_rtol, x64_enabled
from ramba_tpu.core import fuser
from ramba_tpu.models.jacobi import jacobi2d, residual
from ramba_tpu.models.kmeans import kmeans
from ramba_tpu.models.pi import integrate_pi


class TestPi:
    def test_value(self):
        assert abs(integrate_pi(1_000_000) - np.pi) < (1e-9 if x64_enabled() else 1e-6)

    def test_fully_fused(self):
        rt.sync()
        before = fuser.stats["flushes"]
        integrate_pi(100_000)
        assert fuser.stats["flushes"] == before + 1


class TestJacobi:
    def test_converges_toward_solution(self):
        n = 16
        f = np.ones((n, n))
        u = jacobi2d(f, iters=400)
        # after many sweeps the interior residual is far below the rhs
        assert residual(u, f) < 0.05
        # symmetric problem -> symmetric iterate
        ua = u.asarray()
        np.testing.assert_allclose(ua, ua.T, atol=1e-6 if x64_enabled() else 1e-5)

    def test_block_flushing_reuses_compiles(self):
        from ramba_tpu.core import fuser

        f = np.ones((16, 16))
        jacobi2d(f, iters=100, flush_every=25, fused_loop=False)  # warm
        before = fuser.stats["compiles"]
        jacobi2d(f, iters=100, flush_every=25, fused_loop=False)
        # identical block structure -> no new XLA modules
        assert fuser.stats["compiles"] == before

    def test_fused_loop_matches_blockwise(self):
        f = np.random.RandomState(2).rand(16, 16)
        a = jacobi2d(f, iters=40, fused_loop=True).asarray()
        b = jacobi2d(f, iters=40, fused_loop=False).asarray()
        np.testing.assert_allclose(a, b, rtol=default_rtol(1e-12),
                                   atol=1e-12 if x64_enabled() else 1e-6)

    def test_matches_numpy_sweeps(self):
        n = 24
        rng = np.random.RandomState(0)
        f = rng.rand(n, n)
        got = jacobi2d(f, iters=5).asarray()
        u = np.zeros((n, n))
        for _ in range(5):
            nxt = np.zeros_like(u)
            nxt[1:-1, 1:-1] = 0.25 * (
                u[:-2, 1:-1] + u[2:, 1:-1] + u[1:-1, :-2] + u[1:-1, 2:]
                + f[1:-1, 1:-1]
            )
            u = nxt
        np.testing.assert_allclose(got, u, rtol=default_rtol(1e-6), atol=1e-8 if x64_enabled() else 1e-6)


class TestKMeans:
    def test_separated_clusters(self):
        rng = np.random.RandomState(1)
        a = rng.randn(60, 2) + np.array([10.0, 0.0])
        b = rng.randn(60, 2) + np.array([-10.0, 0.0])
        pts = np.concatenate([a, b])
        cents, labels = kmeans(pts, k=2, iters=8)
        # the two clusters are recovered: labels constant within each half
        assert len(set(labels[:60])) == 1
        assert len(set(labels[60:])) == 1
        assert labels[0] != labels[60]
        got = np.sort(cents[:, 0])
        np.testing.assert_allclose(got, [-10.0, 10.0], atol=0.5)
