"""Effect-certified result memoization (``core/memo.py``, RAMBA_MEMO).

The contract under test, in order of importance:

* **Byte identity** — a memo-on run must produce byte-identical results
  to a memo-off run of the same expression sequence (the fuzz leg walks
  seeded random op chains twice, so the second pass replays from cache).
* **Version keying** — a hit requires the *same* input buffers (device
  buffers key by identity-under-weakref, scalars by value); fresh
  buffers or a changed scalar must miss, never serve stale bytes.
* **Budget discipline** — ``RAMBA_MEMO_BUDGET`` bounds retained bytes
  with LRU eviction on insert, and evicted entries release their owner
  census refs.
* **Spill transparency** — a cached result the memory governor spilled
  to host restores on hit, bit-exact.
* **Serving CSE** — coalesced tickets sharing a canonical key execute
  once; followers are memo-served (``serve.cse_merged``).

The SPMD analog (identical canonical hashes and lockstep hits on both
ranks) is ``scripts/two_process_suite.py --memo-leg``.
"""

import numpy as np
import pytest

import jax as _jax

import ramba_tpu as rt
from ramba_tpu.core import fuser, memo
from ramba_tpu.observe import events, registry
from ramba_tpu.resilience import faults, memory, spill

_MULTIPROC = _jax.process_count() > 1


@pytest.fixture(autouse=True)
def _isolate(monkeypatch):
    """Empty pending set, armed memo, empty cache, no faults; the memo
    env is scoped per-test so the suite's outer config never leaks."""
    fuser.flush()
    faults.configure(None)
    monkeypatch.setenv("RAMBA_MEMO", "1")
    monkeypatch.delenv("RAMBA_MEMO_BUDGET", raising=False)
    monkeypatch.delenv("RAMBA_VERIFY", raising=False)
    memo.reset()
    yield
    faults.reset()
    memo.reset()


def test_off_by_default(monkeypatch):
    monkeypatch.setenv("RAMBA_MEMO", "0")
    a = rt.fromarray(np.arange(16.0))
    np.asarray(a * 2.0)
    np.asarray(a * 2.0)
    snap = memo.cache.snapshot()
    assert snap["entries"] == 0 and snap["hits"] == 0
    assert not snap["enabled"]
    del a


def test_repeat_over_same_buffers_hits():
    a = rt.fromarray(np.arange(16.0))
    b = rt.fromarray(np.ones(16))
    first = np.asarray((a + b) * 2.0)
    h0 = memo.cache.hits
    second = np.asarray((a + b) * 2.0)
    assert memo.cache.hits == h0 + 1
    np.testing.assert_array_equal(first, second)
    span = events.last(1, type="flush")[-1]
    assert span.get("cache") == "memo" and span.get("memo_hit") is True
    assert span.get("compile_s") == 0.0
    del a, b


def test_fresh_buffers_miss():
    # same canonical program, NEW buffers: version tokens differ
    r1 = np.asarray(rt.fromarray(np.arange(16.0)) * 2.0)
    h0 = memo.cache.hits
    r2 = np.asarray(rt.fromarray(np.arange(16.0)) * 2.0)
    assert memo.cache.hits == h0
    np.testing.assert_array_equal(r1, r2)


def test_scalar_is_part_of_the_key():
    a = rt.fromarray(np.arange(16.0))
    np.asarray(a * 2.0)
    h0 = memo.cache.hits
    out3 = np.asarray(a * 3.0)  # different scalar: MUST miss
    assert memo.cache.hits == h0
    np.testing.assert_array_equal(out3, np.arange(16.0) * 3.0)
    out2 = np.asarray(a * 2.0)  # original scalar: hit again
    assert memo.cache.hits == h0 + 1
    np.testing.assert_array_equal(out2, np.arange(16.0) * 2.0)
    del a


def test_commutative_swap_shares_canonical_hash():
    # x+y and y+x canonicalize identically; whether the *key* also
    # matches depends on operand-symmetric alpha ordering, so assert
    # only the semantic invariant (equal hashes, equal results).
    from ramba_tpu import analyze

    a = rt.fromarray(np.arange(16.0))
    b = rt.fromarray(np.ones(16))
    p1, _l1, _ = fuser._prepare_program([(a + b)._expr])
    p2, _l2, _ = fuser._prepare_program([(b + a)._expr])
    assert analyze.canonicalize(p1).chash == analyze.canonicalize(p2).chash
    np.testing.assert_array_equal(np.asarray(a + b), np.asarray(b + a))
    del a, b


def test_rng_reseed_does_not_serve_stale_sample():
    # fresh PRNG key buffers => fresh version tokens => no false hit
    rt.random.seed(0)
    s0 = np.asarray(rt.random.random((8,)) + 0.0)
    rt.random.seed(1)
    s1 = np.asarray(rt.random.random((8,)) + 0.0)
    assert not np.array_equal(s0, s1)


def test_byte_identity_fuzz_memo_on_vs_off(monkeypatch):
    """The acceptance property: a seeded random op-chain workload run
    twice with memo on (second pass all-hit where certified) must be
    byte-identical to the memo-off oracle."""
    rng = np.random.RandomState(7)
    bases = [rng.rand(8, 8) for _ in range(3)]

    def workload():
        arrs = [rt.fromarray(b) for b in bases]
        outs = []
        state = np.random.RandomState(42)
        for _ in range(12):
            i, j = state.randint(len(arrs)), state.randint(len(arrs))
            op = state.randint(4)
            if op == 0:
                e = arrs[i] + arrs[j]
            elif op == 1:
                e = arrs[i] * 2.0 - arrs[j]
            elif op == 2:
                e = rt.maximum(arrs[i], arrs[j])
            else:
                e = (arrs[i] * arrs[j]).sum()
            outs.append(np.asarray(e))
        return outs

    monkeypatch.setenv("RAMBA_MEMO", "0")
    oracle = workload()
    monkeypatch.setenv("RAMBA_MEMO", "1")
    memo.reset()
    first = workload()
    second = workload()  # replays against the warm cache
    assert memo.cache.hits > 0, memo.cache.snapshot()
    for o, f, s in zip(oracle, first, second):
        np.testing.assert_array_equal(o, f)
        np.testing.assert_array_equal(o, s)


def test_lru_eviction_under_budget(monkeypatch):
    monkeypatch.setenv("RAMBA_MEMO_BUDGET", "1k")
    a = rt.fromarray(np.arange(64.0))  # 512B result per flush (x64)
    for k in range(6):
        np.asarray(a + float(k))
    snap = memo.cache.snapshot()
    assert snap["evictions"] > 0
    assert snap["bytes"] <= 1024 or snap["entries"] == 1
    # evicted keys miss and recompute correctly; resident keys hit
    np.testing.assert_array_equal(np.asarray(a + 0.0), np.arange(64.0))
    del a


@pytest.mark.skipif(_MULTIPROC, reason="spill requires fully-addressable "
                    "arrays (single-controller)")
def test_spilled_cache_entry_restores_on_hit():
    data = np.random.RandomState(3).rand(64, 64)
    a = rt.fromarray(data)
    rt.sync()
    first = np.asarray(a * 2.0 + 1.0)
    assert memo.cache.snapshot()["entries"] == 1
    restores0 = memory.ledger.restores
    # spill only the cached OUTPUT: a spilled input restores to a fresh
    # buffer (new version token — a sound miss), which is not the path
    # under test here
    pins = memory.ledger.pin_values([a._expr.value])
    try:
        memory.ledger.evict_until(memory.ledger.live_bytes or 1)
    finally:
        memory.ledger.unpin(pins)
    [entry] = list(memo.cache._entries.values())
    assert isinstance(entry.consts[0].value, spill.SpilledArray)
    h0 = memo.cache.hits
    again = np.asarray(a * 2.0 + 1.0)
    assert memo.cache.hits == h0 + 1  # hit, through the spill
    assert memory.ledger.restores > restores0
    np.testing.assert_array_equal(first, again)
    del a


def test_cached_buffer_is_census_owned():
    # an entry's buffers carry a live owner ref; eviction releases it
    a = rt.fromarray(np.arange(32.0))
    np.asarray(a * 5.0)
    [entry] = list(memo.cache._entries.values())
    buf = entry.consts[0].value
    assert fuser._const_owners.get(id(buf), 0) >= 1
    memo.cache.clear()
    assert fuser._const_owners.get(id(buf), 0) == 0
    del a


def test_serving_batch_cse(monkeypatch):
    """Concurrent tenants submitting the same canonical subgraph over
    shared buffers: one execution, followers memo-served and counted as
    CSE merges."""
    import threading

    from ramba_tpu import serve

    base = rt.fromarray(np.arange(128.0))
    other = rt.fromarray(np.ones(128))
    rt.sync()
    cse0 = registry.get("serve.cse_merged")
    errs = []

    def worker(i):
        try:
            with serve.Session(tenant=f"cse{i}") as s:
                for _ in range(4):
                    r = base + other
                    s.flush(wait=True)
                    del r
        except Exception as e:  # noqa: BLE001
            errs.append(repr(e)[:200])

    threads = [__import__("threading").Thread(target=worker, args=(i,))
               for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    serve.shutdown()
    assert not errs, errs
    # 12 submissions of one canonical program over stable buffers: all
    # but the first are memo hits (whether same-batch CSE or cross-batch)
    assert memo.cache.hits >= 8, memo.cache.snapshot()
    assert registry.get("serve.cse_merged") >= cse0
    np.testing.assert_array_equal(
        np.asarray(base + other), np.arange(128.0) + 1.0)
    del base, other


def test_verify_strict_with_memo_is_clean(monkeypatch):
    # certified plans sail through strict verification — no false
    # positives from the memo-safety rule on honest flushes
    monkeypatch.setenv("RAMBA_VERIFY", "strict")
    a = rt.fromarray(np.arange(16.0))
    np.asarray(a * 2.0)
    h0 = memo.cache.hits
    np.asarray(a * 2.0)  # hit under strict
    assert memo.cache.hits == h0 + 1
    del a
