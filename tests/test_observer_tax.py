"""Self-metering observability plane (PR 20).

Covers ``ramba_tpu.observe.observer`` (the observer-tax ledger),
sampled attribution (``RAMBA_ATTRIB=sample:<N>``), tail-based trace
retention (``RAMBA_TRACE_SAMPLE``), the buffered JSONL writer, and the
incident explainer:

* fence sampling is a pure function of the fingerprint's flush sequence
  number — deterministic, replayable, independent per fingerprint, and
  the fence stays *armed* (``fence_enabled()``) under sampling,
* unfenced flushes carry ``device_source:"estimated"`` with a
  ``device_est_s`` stand-in from the rolling fenced p50, and never a
  ``device_execute`` stage,
* the file lane head-samples 1-in-N traces by a deterministic trace-id
  hash; an incident retroactively latches the chain (tail latch), a
  rotated buffer leaves a ``trace_gap`` marker,
* writer overflow/failure is counted (``events.write_dropped`` /
  ``events.write_errors``), never raised; ring overwrites count
  ``events.ring_dropped``,
* the explainer names the dominant divergent stage with an
  operator-facing verdict for >= 3 distinct dominant-stage scenarios,
  and the ``slow_flush`` sentinel stamps it onto the event,
* ``scripts/trace_report.py`` treats estimated-vs-fenced as NOT a rank
  divergence and renders sampled-out gaps instead of ORPHANED.
"""

import contextlib
import json
import os
import subprocess
import sys

import ramba_tpu as rt
from ramba_tpu import diagnostics
from ramba_tpu.observe import attrib, events, observer, registry, telemetry
from ramba_tpu.resilience import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _chain(n=2711):
    a = rt.arange(n) * 2.0 + 1.0
    return float(rt.sum(a))


@contextlib.contextmanager
def _env(**kv):
    saved = {k: os.environ.get(k) for k in kv}
    for k, v in kv.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _counter(name):
    return registry.snapshot()["counters"].get(name, 0)


# ---------------------------------------------------------------------------
# deterministic fence sampling
# ---------------------------------------------------------------------------


def test_sample_env_parse_keeps_fence_armed():
    with _env(RAMBA_ATTRIB="sample:4"):
        attrib.reconfigure()
        try:
            assert attrib.fence_enabled()  # armed, just not every call
            assert attrib.sampling()
            assert attrib.sample_every() == 4
        finally:
            pass
    attrib.reconfigure()
    assert not attrib.sampling() and attrib.sample_every() == 1


def test_fence_decision_deterministic_and_replayable():
    with _env(RAMBA_ATTRIB="sample:4"):
        attrib.reconfigure()
        attrib.reset()
        try:
            fp = "ab" * 6
            dec = [attrib.fence_decision(fp) for _ in range(9)]
            assert dec == [True, False, False, False,
                           True, False, False, False, True]
            # independent counter per fingerprint: a fresh fp starts at
            # seq 0, which is ALWAYS fenced (cold kernels get a sample)
            assert attrib.fence_decision("cd" * 6) is True
            rep = attrib.sampling_report()
            assert rep["sample_every"] == 4 and rep["enabled"]
            assert rep["fingerprints"][fp]["calls"] == 9
            assert rep["fingerprints"][fp]["fenced_seqs"] == [0, 4, 8]
            # replay after reset is bit-identical: the verdict is a pure
            # function of call order, never RNG, never timing — the
            # property that keeps SPMD ranks in lockstep
            attrib.reset()
            assert [attrib.fence_decision(fp) for _ in range(9)] == dec
        finally:
            attrib.reset()
    attrib.reconfigure()


def test_fence_decision_stamps_device_source():
    with _env(RAMBA_ATTRIB="sample:2"):
        attrib.reconfigure()
        attrib.reset()
        try:
            fp = "ee" * 6
            s0, s1 = {}, {}
            assert attrib.fence_decision(fp, s0) is True
            assert attrib.fence_decision(fp, s1) is False
            assert s0["device_source"] == "fenced" and s0["fence_seq"] == 0
            assert s1["device_source"] == "estimated" and s1["fence_seq"] == 1
            # a segmented flush with any fenced segment reads as fenced
            attrib.fence_decision(fp, s1)
            assert s1["device_source"] == "fenced"
        finally:
            attrib.reset()
    attrib.reconfigure()


def test_fence_decision_off_and_always_modes():
    with _env(RAMBA_ATTRIB="off"):
        attrib.reconfigure()
        assert attrib.fence_decision("ab" * 6) is False
    with _env(RAMBA_ATTRIB=None):
        attrib.reconfigure()
        # always-on: every call fences, no sequence bookkeeping
        assert all(attrib.fence_decision("ab" * 6) for _ in range(3))
        assert not attrib.sampling()
    attrib.reconfigure()


def test_estimated_device_source_on_real_flushes():
    with _env(RAMBA_ATTRIB="sample:2", RAMBA_PERF="1"):
        attrib.reconfigure()
        attrib.reset()
        try:
            for _ in range(6):
                _chain(3301)
            spans = [s for s in diagnostics.last_flushes(6)
                     if s.get("device_source")]
            srcs = {s["device_source"] for s in spans}
            assert {"fenced", "estimated"} <= srcs, spans
            for s in spans:
                if s["device_source"] == "estimated":
                    # the estimate is display-only: never a stage (the
                    # device tail genuinely overlaps the host unfenced)
                    assert "device_execute" not in s.get("stages", {}), s
                    assert s.get("fence_seq") is not None
            # once a fenced steady-state sample exists, unfenced flushes
            # carry the rolling fenced p50 as device_est_s
            est = [s for s in spans if s["device_source"] == "estimated"
                   and s.get("device_est_s") is not None]
            assert est, spans
            for s in est:
                assert s["device_est_s"] > 0
            # the report carries the sampling block under sampling mode
            rep = attrib.attribution_report()
            assert rep["sampling"]["sample_every"] == 2
            assert rep["sampling"]["fingerprints"]
        finally:
            attrib.reset()
    attrib.reconfigure()


def test_estimated_device_s_needs_fenced_history():
    attrib.reset()
    assert attrib.estimated_device_s("99" * 6) is None
    assert attrib.estimated_device_s(None) is None
    attrib.record_device("99" * 6, "prog_x", 0.004)
    attrib.record_device("99" * 6, "prog_x", 0.006)
    est = attrib.estimated_device_s("99" * 6)
    assert est is not None and 0.004 <= est <= 0.006
    attrib.reset()


# ---------------------------------------------------------------------------
# tail-based trace retention + buffered writer
# ---------------------------------------------------------------------------


def _pick_tid(sampled_in, start=0):
    """First trace id (deterministic hash) with the wanted verdict."""
    i = start
    while True:
        tid = f"t-{i:04d}"
        if events.trace_sampled_in(tid) == sampled_in:
            return tid
        i += 1


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


def test_tail_latch_replays_buffered_chain(tmp_path):
    path = str(tmp_path / "t.jsonl")
    events.configure(path, sample=4)
    try:
        tid_out = _pick_tid(False)
        tid_in = _pick_tid(True)
        b0 = _counter("events.tail_buffered")
        l0 = _counter("events.tail_latched")
        for i in range(3):
            events.emit({"type": "flush", "label": "prog_t", "i": i,
                         "trace_id": tid_out})
        events.emit({"type": "flush", "label": "prog_t", "i": 99,
                     "trace_id": tid_in})
        events.sync()
        evs = _read_jsonl(path)
        # steady state: the sampled-out chain is buffered, not written;
        # the sampled-in chain writes through
        assert [e.get("trace_id") for e in evs] == [tid_in]
        assert _counter("events.tail_buffered") == b0 + 3
        # incident: the chain is latched and replayed IN ORDER ahead of
        # the incident line
        events.emit({"type": "slow_flush", "label": "prog_t",
                     "trace_id": tid_out})
        events.sync()
        chain = [e for e in _read_jsonl(path)
                 if e.get("trace_id") == tid_out]
        assert [e.get("i") for e in chain[:3]] == [0, 1, 2]
        assert chain[3]["type"] == "slow_flush"
        assert _counter("events.tail_latched") == l0 + 1
        # later events of a latched trace write through unsampled
        events.emit({"type": "flush", "label": "prog_t", "i": 7,
                     "trace_id": tid_out})
        events.sync()
        chain = [e for e in _read_jsonl(path)
                 if e.get("trace_id") == tid_out]
        assert chain[-1].get("i") == 7
        # events with NO trace id always write through
        events.emit({"type": "health", "source": "x", "outcome": "ok"})
        events.sync()
        assert any(e.get("type") == "health" for e in _read_jsonl(path))
    finally:
        events.configure(None)


def test_tail_buffer_rotation_leaves_gap_marker(tmp_path):
    path = str(tmp_path / "t.jsonl")
    events.configure(path, sample=4)
    try:
        tid = _pick_tid(False)
        n = 70  # > the 64-event per-trace buffer: 6 oldest rotate out
        for i in range(n):
            events.emit({"type": "flush", "label": "prog_g", "i": i,
                         "trace_id": tid})
        events.emit({"type": "slow_flush", "label": "prog_g",
                     "trace_id": tid})
        events.sync()
        evs = [e for e in _read_jsonl(path) if e.get("trace_id") == tid]
        gaps = [e for e in evs if e.get("type") == "trace_gap"]
        assert len(gaps) == 1 and gaps[0]["dropped"] == n - 64, gaps
        kept = [e.get("i") for e in evs if e.get("type") == "flush"]
        assert kept == list(range(n - 64, n))  # newest 64 survive
    finally:
        events.configure(None)


def test_buffered_writer_overflow_drops_counted(tmp_path):
    path = str(tmp_path / "t.jsonl")
    events.configure(path, buffer_max=4)
    try:
        d0 = _counter("events.write_dropped")
        # hold the writer lock: drains can't run, the pending buffer
        # fills to buffer_max and further lines drop (counted, no raise,
        # no blocking — the writer must never backpressure the flush)
        with events._write_lock:
            for i in range(10):
                events.emit({"type": "bench_tick", "i": i})
        events.sync()
        assert _counter("events.write_dropped") >= d0 + 6
        assert len(_read_jsonl(path)) <= 4
    finally:
        events.configure(None)


def test_write_errors_counted_not_raised(tmp_path, monkeypatch):
    path = str(tmp_path / "t.jsonl")
    events.configure(path)
    try:
        class _Bad:
            def write(self, s):
                raise OSError("disk full")

        monkeypatch.setattr(events, "_file", lambda: _Bad())
        e0 = _counter("events.write_errors")
        events.emit({"type": "bench_tick", "i": 0})
        events.sync()  # must not raise
        assert _counter("events.write_errors") >= e0 + 1
    finally:
        monkeypatch.undo()
        events.configure(None)


def test_ring_dropped_counter():
    events.configure(None)
    r0 = _counter("events.ring_dropped")
    n = events.ring.maxlen + 10
    for i in range(n):
        events.emit({"type": "bench_tick", "i": i})
    assert _counter("events.ring_dropped") >= r0 + 10


def test_trace_sampled_in_deterministic():
    events.configure(None, sample=4)
    try:
        tids = [f"t-{i:04d}" for i in range(64)]
        verdicts = [events.trace_sampled_in(t) for t in tids]
        assert any(verdicts) and not all(verdicts)
        # pure hash: same answer on every call (and on every rank)
        assert [events.trace_sampled_in(t) for t in tids] == verdicts
        # no trace id -> always in; sample 1 -> everything in
        assert events.trace_sampled_in(None)
    finally:
        events.configure(None)
    assert all(events.trace_sampled_in(t) for t in ("a", "b", "c"))


# ---------------------------------------------------------------------------
# observer-tax ledger
# ---------------------------------------------------------------------------


def test_observer_ledger_accounting():
    observer.reset()
    observer.add("events", 0.002)
    observer.add("events", 0.001)
    observer.add("fence", 0.004)
    observer.add("fence", -1.0)  # negative clock skew: ignored
    with observer.taxed("telemetry"):
        pass
    snap = observer.snapshot()
    comps = snap["components"]
    assert comps["events"]["count"] == 2
    assert abs(comps["events"]["seconds"] - 0.003) < 1e-9
    assert comps["fence"]["count"] == 1
    assert comps["telemetry"]["count"] == 1
    assert snap["total_s"] >= 0.007
    observer.reset()
    assert observer.snapshot()["components"] == {}


def test_observer_tax_frac_denominator_is_flush_wall():
    observer.reset()
    attrib.reset()
    assert observer.tax_frac() is None  # no attributed wall yet
    _chain(3307)  # one real flush: attrib totals + emit/ledger billing
    frac = observer.tax_frac()
    assert frac is not None and 0.0 < frac
    snap = observer.snapshot()
    assert snap.get("tax_frac") == frac
    # the flush itself billed the plane's components
    assert "events" in snap["components"]
    assert "ledger" in snap["components"]
    attrib.reset()
    observer.reset()


def test_observer_surfaces_in_diagnostics_and_telemetry():
    observer.reset()
    observer.add("fleet", 0.001)
    rep = diagnostics.observer_report()
    assert rep["components"]["fleet"]["seconds"] > 0
    assert "observer" in diagnostics.snapshot()
    import io
    buf = io.StringIO()
    diagnostics.report(file=buf)
    assert "observer tax" in buf.getvalue()
    prom = telemetry.render()
    line = next(ln for ln in prom.splitlines()
                if ln.startswith("ramba_observer_seconds_total{"))
    assert 'component="fleet"' in line  # (rank label rides along)
    observer.reset()


# ---------------------------------------------------------------------------
# incident explainer
# ---------------------------------------------------------------------------


def _seed_baseline(fp, n=5):
    """Five steady spans -> per-stage rolling baselines for ``fp``."""
    for _ in range(n):
        span = {"stages": {"prepare": 0.001, "queue_wait": 0.001,
                           "dispatch": 0.004, "device_execute": 0.004},
                "wall_s": 0.011}
        attrib.finalize_span(span, fp=fp)


def test_explainer_verdicts_three_dominant_stages():
    attrib.reset()
    try:
        fp = "fe" * 6
        _seed_baseline(fp)
        # 1: queue_wait 12x baseline -> overload
        why = attrib.explain(
            {"stages": {"prepare": 0.001, "queue_wait": 0.012,
                        "dispatch": 0.004, "device_execute": 0.004},
             "wall_s": 0.022, "fingerprint": fp})
        assert why["stage"] == "queue_wait"
        assert why["verdict"] == "overload"
        assert 11.0 <= why["ratio"] <= 13.0
        assert "12.0x baseline -> overload" in why["text"]
        # 2: compile appearing on a steady-state fingerprint (no
        # baseline window at all) -> cache miss, divergent by existence
        why = attrib.explain(
            {"stages": {"prepare": 0.001, "queue_wait": 0.001,
                        "compile": 0.050, "dispatch": 0.004,
                        "device_execute": 0.004},
             "wall_s": 0.061, "fingerprint": fp})
        assert why["stage"] == "compile"
        assert why["verdict"] == "cache miss"
        assert why["ratio"] is None and "compile -> cache miss" in why["text"]
        # 3: device_execute dominates -> device regression (explicit fp
        # argument wins over the span stamp)
        why = attrib.explain(
            {"stages": {"prepare": 0.001, "queue_wait": 0.001,
                        "dispatch": 0.004, "device_execute": 0.040},
             "wall_s": 0.047}, fp=fp)
        assert why["stage"] == "device_execute"
        assert why["verdict"] == "device regression"
        # 4: unattributed residual blowing up -> untracked interference
        why = attrib.explain(
            {"stages": {"prepare": 0.001, "queue_wait": 0.001,
                        "dispatch": 0.004, "device_execute": 0.004},
             "unattributed_s": 0.030, "wall_s": 0.041, "fingerprint": fp})
        assert why["stage"] == "unattributed"
        assert "untracked interference" in why["verdict"]
    finally:
        attrib.reset()


def test_explainer_silent_without_divergence_or_history():
    attrib.reset()
    try:
        fp = "fd" * 6
        # no baselines at all -> None (nothing to diff against)
        assert attrib.explain(
            {"stages": {"prepare": 0.001}, "wall_s": 0.001,
             "fingerprint": fp}) is None
        _seed_baseline(fp)
        # a span AT baseline -> None (no stage exceeds 1.5x its p50)
        assert attrib.explain(
            {"stages": {"prepare": 0.001, "queue_wait": 0.001,
                        "dispatch": 0.004, "device_execute": 0.004},
             "wall_s": 0.011, "fingerprint": fp}) is None
        # no fingerprint -> None
        assert attrib.explain(
            {"stages": {"prepare": 0.9}, "wall_s": 1.0}) is None
    finally:
        attrib.reset()


def test_slow_flush_event_carries_why_verdict():
    attrib.reset()
    with _env(RAMBA_SLOW_FLUSH_FACTOR="4", RAMBA_PERF="1"):
        from ramba_tpu.observe import ledger
        ledger.reconfigure()
        try:
            for _ in range(6):
                _chain(4201)
            base = len(events.last(0, type="slow_flush"))
            with faults.active("execute:delay:ms=200"):
                _chain(4201)
            evs = events.last(0, type="slow_flush")
            assert len(evs) == base + 1, evs[-2:]
            ev = evs[-1]
            # the explainer stamped the sentinel event with its verdict
            assert ev.get("why") and ev.get("why_stage") in (
                attrib.STAGES + ("unattributed",))
            assert ev.get("why_verdict") == attrib._EXPLAIN_VERDICTS[
                ev["why_stage"]]
            assert ev["why"].endswith(ev["why_verdict"])
        finally:
            attrib.reset()
    from ramba_tpu.observe import ledger
    ledger.reconfigure()


# ---------------------------------------------------------------------------
# trace_report: estimated spans + sampled-out gaps
# ---------------------------------------------------------------------------


def _write_jsonl(path, events_):
    with open(path, "w") as f:
        for e in events_:
            f.write(json.dumps(e) + "\n")


def _trace_report(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "trace_report.py"),
         *args],
        capture_output=True, text=True,
    )


def test_merge_ranks_estimated_is_not_divergence(tmp_path):
    base = tmp_path / "m.jsonl"
    # rank 0 fenced (full waterfall), rank 1 sampled out at the same
    # flush index: no device_execute stage, but device_source says why
    _write_jsonl(f"{base}.rank0", [
        {"type": "flush", "label": "prog_a", "ts": 10.1, "seq": 1,
         "rank": 0, "wall_s": 0.01, "cache": "hit",
         "device_source": "fenced", "unattributed_s": 0.001,
         "stages": {"prepare": 0.002, "dispatch": 0.003,
                    "device_execute": 0.004}},
    ])
    _write_jsonl(f"{base}.rank1", [
        {"type": "flush", "label": "prog_a", "ts": 10.1, "seq": 1,
         "rank": 1, "wall_s": 0.01, "cache": "hit",
         "device_source": "estimated", "device_est_s": 0.004,
         "unattributed_s": 0.005,
         "stages": {"prepare": 0.002, "dispatch": 0.003}},
    ])
    r = _trace_report(str(base), "--merge-ranks")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "rank divergence: none" in r.stdout
    # ...but a genuinely MISSING fence (no device_source alibi) at the
    # same index still flags — sampling must not mask real skew
    _write_jsonl(f"{base}.rank1", [
        {"type": "flush", "label": "prog_a", "ts": 10.1, "seq": 1,
         "rank": 1, "wall_s": 0.01, "cache": "hit",
         "unattributed_s": 0.005,
         "stages": {"prepare": 0.002, "dispatch": 0.003}},
    ])
    r2 = _trace_report(str(base), "--merge-ranks")
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert "rank divergence at flush #0" in r2.stdout


def test_attrib_report_renders_estimated_spans(tmp_path):
    path = tmp_path / "t.jsonl"
    _write_jsonl(path, [
        {"type": "flush", "label": "prog_a", "ts": 1.0, "seq": 1,
         "wall_s": 0.01, "unattributed_s": 0.001,
         "device_source": "fenced",
         "stages": {"prepare": 0.002, "dispatch": 0.003,
                    "device_execute": 0.004}},
        {"type": "flush", "label": "prog_a", "ts": 1.1, "seq": 2,
         "wall_s": 0.01, "unattributed_s": 0.005,
         "device_source": "estimated", "device_est_s": 0.0042,
         "stages": {"prepare": 0.002, "dispatch": 0.003}},
        {"type": "slow_flush", "label": "prog_a", "ts": 1.2, "seq": 3,
         "why": "queue_wait 12.0x baseline -> overload",
         "why_stage": "queue_wait", "why_verdict": "overload"},
    ])
    r = _trace_report(str(path), "--attrib")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "sampled attribution: 1 fenced / 1 estimated" in r.stdout
    assert "(est)" in r.stdout
    assert "incident explainer verdicts" in r.stdout
    assert "queue_wait 12.0x baseline -> overload" in r.stdout


def test_trace_chain_gap_classified_not_orphaned(tmp_path):
    path = tmp_path / "t.jsonl"
    # chain whose early spans rotated out of the tail buffer: the child
    # event's parent is gone, but the trace_gap marker explains why
    _write_jsonl(path, [
        {"type": "trace_gap", "trace_id": "req-1", "dropped": 6,
         "reason": "tail_buffer_rotation", "ts": 1.0, "seq": 1},
        {"type": "flush", "label": "prog_a", "ts": 1.1, "seq": 2,
         "trace_id": "req-1", "span_id": "s2", "wall_s": 0.01},
        {"type": "degrade", "action": "rung", "ts": 1.2, "seq": 3,
         "trace_id": "req-1", "parent_span": "s-rotated-out"},
    ])
    r = _trace_report(str(path), "--trace", "req-1")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "sampling gap: 6 event(s)" in r.stdout
    assert "sampled-out events (1)" in r.stdout
    assert "ORPHANED" not in r.stdout
    # without a gap marker the same shape is a genuine orphan
    _write_jsonl(path, [
        {"type": "flush", "label": "prog_a", "ts": 1.1, "seq": 1,
         "trace_id": "req-2", "span_id": "s2", "wall_s": 0.01},
        {"type": "degrade", "action": "rung", "ts": 1.2, "seq": 2,
         "trace_id": "req-2", "parent_span": "s-missing-rank"},
    ])
    r2 = _trace_report(str(path), "--trace", "req-2")
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert "ORPHANED events (1)" in r2.stdout
    assert "sampling gap" not in r2.stdout
