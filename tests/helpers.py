"""Shared helpers for the two numerics legs (round-3 verdict weak #5).

The default leg runs ``jax_enable_x64=True`` so differential tests compare
against NumPy bit-for-bit.  The ``RAMBA_TEST_X64=0`` leg runs the regime
that actually executes on a TPU: jax truncates 64-bit dtypes to 32-bit
(float64→float32, int64→int32, ...), so

* expected *dtypes* must be mapped through jax's truncation lattice
  (``map_dtype``), and
* *value* tolerances must account for float32 arithmetic
  (``default_rtol``/``default_atol``) — value semantics are still checked,
  only the precision differs.
"""

import numpy as np


def x64_enabled() -> bool:
    import jax

    return bool(jax.config.jax_enable_x64)


_TRUNC = {
    np.dtype(np.float64): np.dtype(np.float32),
    np.dtype(np.int64): np.dtype(np.int32),
    np.dtype(np.uint64): np.dtype(np.uint32),
    np.dtype(np.complex128): np.dtype(np.complex64),
}


def map_dtype(dt):
    """Expected dtype under the active regime: identity when x64 is on,
    jax's 64→32-bit truncation lattice when off."""
    dt = np.dtype(dt)
    if x64_enabled():
        return dt
    return _TRUNC.get(dt, dt)


def default_rtol(rtol=None):
    """Comparison rtol for the active regime.  Under x64 callers' tight
    defaults stand; under x32 float32 arithmetic plus reduction
    accumulation needs ~1e-4."""
    if x64_enabled():
        return 1e-10 if rtol is None else rtol
    return max(1e-4, rtol or 0.0)


def default_atol(atol=None):
    if x64_enabled():
        return 1e-12 if atol is None else atol
    return max(1e-4, atol or 0.0)


def oracle():
    """Differential oracle for the active regime: numpy under x64 (NumPy
    semantics are the contract there), jax.numpy under x32 (on TPU the jax
    lattice IS the documented dtype contract — see SURVEY §2.9 note)."""
    if x64_enabled():
        return np
    import jax.numpy as jnp

    return jnp


def local_shard_count() -> int:
    """Expected number of ADDRESSABLE shards of a default-sharded array:
    all workers single-controller, this process's slice of them under the
    cross-process leg (RAMBA_TEST_PROCS)."""
    import jax

    import ramba_tpu as rt

    return max(1, rt.num_workers() // jax.process_count())


def driver_write(fn) -> None:
    """Run a host-side file write once (driver rank) with a cross-process
    barrier — for tests that prepare input files by hand.  Single-process:
    just runs fn."""
    from ramba_tpu.fileio import _driver_write_barrier

    _driver_write_barrier(fn)
