"""Fleet observability federation: snapshot spool, collector, health model.

Covers ``ramba_tpu/observe/fleet.py`` and its seams:

* spool publishing: atomic versioned documents named by replica id, the
  identity block, monotone publish_seq, env-driven autostart off the
  flush path,
* the collector's edge cases — the ones a real fleet throws at it:
  stale snapshots, torn/truncated JSON (classified, NEVER a crash),
  mismatched schema_version, and the healthy -> stale -> dead
  transition as a snapshot ages past the RAMBA_FLEET_STALE_X /
  RAMBA_FLEET_DEAD_X thresholds,
* degraded classification from the published signals block (brownout,
  open breakers, latched SLO breaches),
* fleet rollups: goodput reconciliation against per-replica documents,
  exact merged SLO histograms, dead replicas excluded from aggregation,
* Prometheus federation rendering with ``replica`` labels, and
* cross-process trace stitching: ``trace_report.py --trace`` over a
  directory of per-replica JSONL files, including orphan-half flagging.

The live multi-process soak (3 publishers, SIGKILL mid-soak, collector
CLI) is scripts/two_process_suite.py --fleet-leg; these tests pin the
library logic with hand-built spool directories and injected clocks.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from ramba_tpu import diagnostics
from ramba_tpu.observe import fleet, registry, slo

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_fleet(monkeypatch):
    monkeypatch.delenv("RAMBA_FLEET_DIR", raising=False)
    monkeypatch.delenv("RAMBA_FLEET_INTERVAL_S", raising=False)
    monkeypatch.delenv("RAMBA_FLEET_STALE_X", raising=False)
    monkeypatch.delenv("RAMBA_FLEET_DEAD_X", raising=False)
    fleet.reset()
    yield
    fleet.reset()


def _doc(tmp_path, replica="h-1-0", age_s=0.0, interval_s=5.0,
         schema_version=None, signals=None, counters=None,
         diagnostics_extra=None, now=1_000_000.0):
    """Hand-build one spool document the way a publisher would."""
    ident = {"schema_version": diagnostics.SCHEMA_VERSION,
             "host": replica.rsplit("-", 2)[0],
             "pid": int(replica.rsplit("-", 2)[1]),
             "rank": int(replica.rsplit("-", 2)[2]),
             "nprocs": 1, "device_kind": "cpu",
             "start_time_wall": now - 3600.0,
             "start_time_mono": 1.0}
    sig = {"brownout": "green", "open_breakers": [], "breaker_trips": 0,
           "shed_total": 0, "slo_breached": [], "heartbeat_running": False,
           "heartbeat_age_s": None, "heartbeat_interval_s": None}
    sig.update(signals or {})
    diag = {"counters": counters or {}}
    diag.update(diagnostics_extra or {})
    doc = {"schema_version": (diagnostics.SCHEMA_VERSION
                              if schema_version is None else schema_version),
           "identity": ident, "replica": replica,
           "interval_s": interval_s,
           "published_at": now - age_s,
           "published_mono": 100.0 - age_s,
           "publish_seq": 7, "signals": sig, "diagnostics": diag}
    path = os.path.join(tmp_path, f"{replica}.json")
    with open(path, "w") as f:
        json.dump(doc, f)
    return doc


NOW = 1_000_000.0


# -- publisher ---------------------------------------------------------------


def test_publish_writes_versioned_identity_document(tmp_path):
    path = fleet.publish(str(tmp_path))
    assert path and os.path.exists(path)
    doc = json.load(open(path))
    assert doc["schema_version"] == diagnostics.SCHEMA_VERSION
    ident = doc["identity"]
    assert ident["pid"] == os.getpid()
    assert doc["replica"] == fleet.replica_id(ident)
    assert os.path.basename(path) == doc["replica"] + ".json"
    assert doc["publish_seq"] >= 1
    assert doc["signals"]["brownout"] in ("green", "yellow", "red")
    assert "counters" in doc["diagnostics"]
    assert not [p for p in os.listdir(tmp_path) if p.endswith(".tmp")]


def test_publish_seq_monotone_and_single_file(tmp_path):
    p1 = fleet.publish(str(tmp_path))
    s1 = json.load(open(p1))["publish_seq"]
    p2 = fleet.publish(str(tmp_path))
    s2 = json.load(open(p2))["publish_seq"]
    assert p1 == p2, "one replica republishes in place"
    assert s2 == s1 + 1
    assert registry.get("fleet.publishes") >= 2


def test_publish_noop_without_fleet_dir():
    assert fleet.fleet_dir() is None
    assert fleet.publish() is None
    assert not fleet.started()


def test_ensure_started_spins_up_publisher_thread(tmp_path, monkeypatch):
    monkeypatch.setenv("RAMBA_FLEET_DIR", str(tmp_path))
    monkeypatch.setenv("RAMBA_FLEET_INTERVAL_S", "0.05")
    fleet.reset()
    fleet.ensure_started()
    assert fleet.started()
    def _docs():
        # poll for the final document, not the transient .tmp sibling
        return [p for p in os.listdir(str(tmp_path)) if p.endswith(".json")]

    deadline = time.time() + 10
    while time.time() < deadline and not _docs():
        time.sleep(0.02)
    assert _docs(), "spool thread publishes without any explicit call"
    fleet.stop()
    assert not fleet.started()


# -- classification ----------------------------------------------------------


def test_fresh_green_snapshot_is_healthy(tmp_path):
    _doc(str(tmp_path), age_s=0.5, now=NOW)
    h = fleet.health(str(tmp_path), now=NOW)
    row = h["replicas"]["h-1-0"]
    assert row["state"] == fleet.HEALTHY
    assert h["fleet_state"] == fleet.HEALTHY
    assert h["counts"][fleet.HEALTHY] == 1
    assert row["age_s"] == pytest.approx(0.5)


def test_healthy_to_stale_to_dead_as_snapshot_ages(tmp_path):
    """The replica-death transition, driven purely by the injected
    clock: fresh -> stale past 1.5x interval -> dead past 2x."""
    _doc(str(tmp_path), interval_s=5.0, age_s=0.0, now=NOW)
    assert fleet.health(str(tmp_path),
                        now=NOW)["fleet_state"] == fleet.HEALTHY
    # age 7.5s == 1.5 x 5s is NOT yet stale (strict >); 7.6s is
    assert fleet.health(str(tmp_path),
                        now=NOW + 7.6)["fleet_state"] == fleet.STALE
    assert fleet.health(str(tmp_path),
                        now=NOW + 10.1)["fleet_state"] == fleet.DEAD
    row = fleet.health(str(tmp_path), now=NOW + 10.1)["replicas"]["h-1-0"]
    assert row["state"] == fleet.DEAD
    assert "2x interval" in row["reason"]


def test_stale_and_dead_factors_from_env(tmp_path, monkeypatch):
    monkeypatch.setenv("RAMBA_FLEET_STALE_X", "3")
    monkeypatch.setenv("RAMBA_FLEET_DEAD_X", "6")
    _doc(str(tmp_path), interval_s=1.0, age_s=2.0, now=NOW)
    assert fleet.health(str(tmp_path),
                        now=NOW)["fleet_state"] == fleet.HEALTHY
    assert fleet.health(str(tmp_path),
                        now=NOW + 2.0)["fleet_state"] == fleet.STALE
    assert fleet.health(str(tmp_path),
                        now=NOW + 5.0)["fleet_state"] == fleet.DEAD


def test_torn_document_classified_stale_never_crashes(tmp_path):
    """A truncated write from a dying process is DATA, not an error:
    the collector classifies it and moves on."""
    _doc(str(tmp_path), replica="ok-1-0", now=NOW)
    with open(tmp_path / "torn-2-0.json", "w") as f:
        f.write('{"schema_version": 1, "identity": {"pid": 2, "ho')
    with open(tmp_path / "empty-3-0.json", "w") as f:
        f.write("")
    h = fleet.health(str(tmp_path), now=NOW)
    assert h["replicas"]["ok-1-0"]["state"] == fleet.HEALTHY
    assert h["replicas"]["torn-2-0"]["state"] == fleet.STALE
    assert "Error" in h["replicas"]["torn-2-0"]["reason"]
    assert h["replicas"]["empty-3-0"]["state"] == fleet.STALE
    assert h["fleet_state"] == fleet.STALE


def test_mismatched_schema_version_skipped_as_stale(tmp_path):
    _doc(str(tmp_path), replica="old-1-0",
         schema_version=diagnostics.SCHEMA_VERSION + 1, now=NOW)
    row = fleet.health(str(tmp_path), now=NOW)["replicas"]["old-1-0"]
    assert row["state"] == fleet.STALE
    assert "schema_version" in row["reason"]


def test_degraded_from_signals(tmp_path):
    _doc(str(tmp_path), replica="brown-1-0",
         signals={"brownout": "red"}, now=NOW)
    _doc(str(tmp_path), replica="breaker-2-0",
         signals={"open_breakers": ["acme"]}, now=NOW)
    _doc(str(tmp_path), replica="slo-3-0",
         signals={"slo_breached": ["acme"]}, now=NOW)
    _doc(str(tmp_path), replica="wedged-4-0",
         signals={"heartbeat_running": True, "heartbeat_age_s": 9.0,
                  "heartbeat_interval_s": 1.0}, now=NOW)
    h = fleet.health(str(tmp_path), now=NOW)
    states = {r: row["state"] for r, row in h["replicas"].items()}
    assert states == {r: fleet.DEGRADED for r in states}
    assert "brownout red" in h["replicas"]["brown-1-0"]["reason"]
    assert "acme" in h["replicas"]["breaker-2-0"]["reason"]
    assert "SLO" in h["replicas"]["slo-3-0"]["reason"]
    assert "heartbeat" in h["replicas"]["wedged-4-0"]["reason"]
    assert h["fleet_state"] == fleet.DEGRADED


def test_empty_or_missing_dir_is_vacuously_healthy(tmp_path):
    h = fleet.health(str(tmp_path / "nope"))
    assert h["replicas"] == {} and h["fleet_state"] == fleet.HEALTHY


# -- rollup ------------------------------------------------------------------


def test_rollup_goodput_reconciles_and_excludes_dead(tmp_path):
    _doc(str(tmp_path), replica="a-1-0", now=NOW,
         counters={"fuser.flushes": 10, "fuser.nodes_flushed": 30,
                   "serve.flushes": 10, "serve.shed": 1})
    _doc(str(tmp_path), replica="b-2-0", now=NOW,
         counters={"fuser.flushes": 7, "fuser.nodes_flushed": 21,
                   "serve.flushes": 7})
    # a corpse: counted by health, EXCLUDED from aggregation
    _doc(str(tmp_path), replica="dead-3-0", age_s=60.0, now=NOW,
         counters={"fuser.flushes": 1000})
    roll = fleet.rollup(str(tmp_path), now=NOW)
    assert roll["replicas"] == ["a-1-0", "b-2-0"]
    gp = roll["goodput"]
    assert gp["flushes"] == 17 and gp["nodes_flushed"] == 51
    assert gp["shed_total"] == 1
    assert gp["flushes"] == sum(
        r["flushes"] for r in gp["replicas"].values())
    assert gp["replicas"]["a-1-0"]["uptime_s"] == pytest.approx(3600.0)


def test_rollup_merges_slo_histograms_exactly(tmp_path):
    """Fixed-bucket summaries merge by cumulative-count addition — the
    merged percentile must equal a single histogram fed both streams."""
    h1, h2, ref = slo.Histogram(), slo.Histogram(), slo.Histogram()
    for v in (0.001, 0.004, 0.004, 0.02):
        h1.observe(v)
        ref.observe(v)
    for v in (0.08, 0.3, 1.2):
        h2.observe(v)
        ref.observe(v)
    _doc(str(tmp_path), replica="a-1-0", now=NOW, diagnostics_extra={
        "slo": {"histograms": {"e2e": {"acme": h1.summary()}}}})
    _doc(str(tmp_path), replica="b-2-0", now=NOW, diagnostics_extra={
        "slo": {"histograms": {"e2e": {"acme": h2.summary()}}}})
    merged = fleet.rollup(str(tmp_path), now=NOW)["slo"]["e2e"]["acme"]
    want = ref.summary()
    assert merged["count"] == want["count"] == 7
    for q in ("p50_ms", "p95_ms", "p99_ms"):
        assert merged[q] == pytest.approx(want[q])
    assert merged["sum_s"] == pytest.approx(want["sum_s"])


def test_rollup_cache_and_roofline_comparison(tmp_path):
    _doc(str(tmp_path), replica="warm-1-0", now=NOW,
         counters={"fuser.cache_hit": 9, "fuser.cache_miss": 1},
         diagnostics_extra={"perf": {
             "compile": {"persist": {"hits": 5, "misses": 0}},
             "attribution": {"rooflines": {
                 "fp1": {"label": "prog_a", "bound": "memory",
                         "frac_of_peak": 0.8}}}}})
    _doc(str(tmp_path), replica="cold-2-0", now=NOW,
         counters={"fuser.cache_hit": 1, "fuser.cache_miss": 9},
         diagnostics_extra={"perf": {
             "compile": {"persist": {"hits": 0, "misses": 5}},
             "attribution": {"rooflines": {
                 "fp1": {"label": "prog_a", "bound": "memory",
                         "frac_of_peak": 0.1}}}}})
    roll = fleet.rollup(str(tmp_path), now=NOW)
    assert roll["caches"]["warm-1-0"]["jit_hit_rate"] == pytest.approx(0.9)
    assert roll["caches"]["cold-2-0"]["jit_hit_rate"] == pytest.approx(0.1)
    assert roll["caches"]["warm-1-0"]["aot_hits"] == 5
    worst = roll["rooflines"]
    assert worst[0]["replica"] == "cold-2-0"  # worst first
    assert worst[0]["frac_of_peak"] == pytest.approx(0.1)


# -- Prometheus federation ---------------------------------------------------


def test_render_fleet_exposition_with_replica_labels(tmp_path):
    _doc(str(tmp_path), replica="a-1-0", now=NOW,
         counters={"fuser.flushes": 4})
    _doc(str(tmp_path), replica="b-2-0", age_s=60.0, now=NOW)
    body = fleet.render(str(tmp_path), now=NOW)
    assert ('ramba_fleet_replica_state{replica="a-1-0",state="healthy"} 1'
            in body)
    assert ('ramba_fleet_replica_state{replica="b-2-0",state="dead"} 1'
            in body)
    assert 'ramba_fleet_replicas{state="healthy"} 1' in body
    assert 'ramba_fleet_replicas{state="dead"} 1' in body
    assert 'ramba_fleet_flushes_total{replica="a-1-0"} 4' in body
    assert "ramba_fleet_goodput_flushes_total 4" in body
    assert 'ramba_process_info{' in body and 'pid="1"' in body


def test_write_textfile_atomic(tmp_path):
    spool = tmp_path / "spool"
    spool.mkdir()
    _doc(str(spool), now=time.time())
    out = tmp_path / "fleet.prom"
    fleet.write_textfile(str(out), str(spool))
    assert "ramba_fleet_replicas" in out.read_text()
    assert not list(tmp_path.glob("*.tmp"))


# -- stitched traces ---------------------------------------------------------


def _run_report(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "trace_report.py"),
         *args],
        capture_output=True, text=True,
    )


def test_trace_stitching_across_replica_dirs_flags_orphans(tmp_path):
    """Two replicas' JSONL files under one directory: the --trace chain
    must stitch spans sharing the trace_id across the process boundary
    and flag the half whose parent span was never collected."""
    (tmp_path / "replica0").mkdir()
    (tmp_path / "replica1").mkdir()
    r0 = [
        {"type": "serve_session", "trace_id": "T1", "span_id": "R",
         "stream": "session:acme", "tenant": "acme", "ts": 1.0, "seq": 1},
        {"type": "flush", "label": "prog_a", "trace_id": "T1",
         "span_id": "S1", "parent_span": "R", "ts": 1.1, "seq": 2,
         "wall_s": 0.01, "cache": "miss"},
    ]
    r1 = [
        # stitched: replica1's flush parented by replica0's session root
        {"type": "flush", "label": "prog_b", "trace_id": "T1",
         "span_id": "S2", "parent_span": "R", "ts": 1.2, "seq": 1,
         "wall_s": 0.02, "cache": "hit"},
        {"type": "degrade", "site": "flush", "action": "rung",
         "from": "fused", "to": "split", "trace_id": "T1",
         "parent_span": "S2", "ts": 1.25, "seq": 2},
        # orphaned half: its parent ran in a process we did not collect
        {"type": "stall", "site": "flush", "waited_s": 1.0,
         "classification": "wedge", "trace_id": "T1",
         "parent_span": "LOST", "ts": 1.4, "seq": 3},
    ]
    for name, evs in (("replica0", r0), ("replica1", r1)):
        with open(tmp_path / name / "trace.jsonl", "w") as f:
            for e in evs:
                f.write(json.dumps(e) + "\n")
    r = _run_report(str(tmp_path), "--trace", "T1")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "2 process(es)" in r.stdout
    assert "replica0/trace" in r.stdout and "replica1/trace" in r.stdout
    # both flush spans in ONE chain, in time order
    assert r.stdout.index("prog_a") < r.stdout.index("prog_b")
    assert "fused->split" in r.stdout
    assert "ORPHANED" in r.stdout
    assert "parent_span=LOST" in r.stdout
    # the merged timeline walks the same directory
    m = _run_report(str(tmp_path), "--merge-ranks")
    assert m.returncode == 0, m.stdout + m.stderr
    assert "2 rank(s)" in m.stdout
