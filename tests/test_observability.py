"""Observability: flush spans, the counter registry, and the trace sink.

Covers the ``ramba_tpu.observe`` package + ``ramba_tpu.diagnostics``:

* every flush emits a span into the in-memory ring with compile/execute
  attribution and a cache flag (miss on first compile, hit on re-run),
* named counters fire for rewrite-rule applications and smap host
  fallbacks,
* ``RAMBA_TRACE=<path>`` produces a valid JSONL file with exactly one
  record per flush (checked in a subprocess so the env var is read at
  import, as in production), and ``scripts/trace_report.py`` summarizes it,
* with tracing disabled the ring still records spans but no file is
  touched.
"""

import json
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

import jax as _jax
import ramba_tpu as rt
from ramba_tpu import common, diagnostics
from ramba_tpu.core import fuser
from ramba_tpu.observe import events

_MULTIPROC = _jax.process_count() > 1

_SPAN_KEYS = (
    "label", "instrs", "n_leaves", "linearize_s", "rewrite_fires",
    "donated", "leaf_bytes", "out_bytes", "segments", "cache",
    "compile_s", "execute_s", "wall_s", "calls",
)


def _run_chain():
    a = rt.arange(512) * 3.0 + 1.0
    return float(rt.sum(a))


def test_flush_span_miss_then_hit():
    fuser.flush()  # drain unrelated pending work
    fuser._compile_cache.clear()
    before = diagnostics.counters()

    v1 = _run_chain()
    span1 = diagnostics.last_flushes(1)[0]
    for k in _SPAN_KEYS:
        assert k in span1, f"flush span missing {k!r}"
    assert span1["type"] == "flush"
    assert span1["cache"] == "miss"
    assert span1["compile_s"] > 0.0
    assert span1["instrs"] >= 1
    assert span1["wall_s"] >= span1["compile_s"]
    assert span1["calls"] and span1["calls"][0]["cache"] == "miss"

    v2 = _run_chain()
    span2 = diagnostics.last_flushes(1)[0]
    assert span2 is not span1
    assert span2["label"] == span1["label"]
    assert span2["cache"] == "hit"
    assert span2["compile_s"] == 0.0
    assert span2["execute_s"] > 0.0
    assert v1 == v2

    after = diagnostics.counters()
    assert after.get("fuser.cache_miss", 0) >= before.get("fuser.cache_miss", 0) + 1
    assert after.get("fuser.cache_hit", 0) >= before.get("fuser.cache_hit", 0) + 1
    assert after.get("fuser.flushes", 0) >= before.get("fuser.flushes", 0) + 2


@pytest.mark.skipif(
    not common.rewrite_enabled, reason="graph rewrites disabled by env"
)
def test_rewrite_fire_counter_and_span():
    fuser.flush()
    before = diagnostics.counters().get("rewrite.rewrite_arange_reshape", 0)
    r = rt.arange(4096).reshape(64, 64)
    np.asarray(r)
    after = diagnostics.counters().get("rewrite.rewrite_arange_reshape", 0)
    assert after >= before + 1
    span = diagnostics.last_flushes(1)[0]
    assert span["rewrite_fires"].get("rewrite_arange_reshape", 0) >= 1


@pytest.mark.skipif(
    _MULTIPROC,
    reason="pure_callback host fallback is single-controller only",
)
def test_host_fallback_counter():
    def countdown(x):
        n = x
        while n > 0:
            n = n - 1.0
        return n

    before = diagnostics.counters().get("skeletons.host_fallback", 0)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        out = np.asarray(rt.smap(countdown, [2.5, -1.0, 0.5]))
    np.testing.assert_allclose(out, [-0.5, -1.0, -0.5])
    after = diagnostics.counters().get("skeletons.host_fallback", 0)
    assert after >= before + 1


def test_branch_lowered_counter():
    before = diagnostics.counters().get("skeletons.branch_lowered", 0)
    out = np.asarray(rt.smap(lambda x: x + 1 if x > 0 else x - 1, [1.0, -1.0]))
    np.testing.assert_allclose(out, [2.0, -2.0])
    after = diagnostics.counters().get("skeletons.branch_lowered", 0)
    assert after >= before + 1


def test_diagnostics_report_and_dump(tmp_path, capsys):
    _run_chain()
    import io

    buf = io.StringIO()
    diagnostics.report(file=buf)
    text = buf.getvalue()
    assert "ramba_tpu diagnostics" in text
    assert "counters" in text
    rank = os.environ.get("RAMBA_TEST_PROC_ID", "0")
    p = diagnostics.dump(str(tmp_path / f"diag_{rank}.json"))
    with open(p) as f:
        snap = json.load(f)
    assert "counters" in snap and "events" in snap


def test_trace_jsonl_one_record_per_flush(tmp_path):
    rank = os.environ.get("RAMBA_TEST_PROC_ID", "0")
    path = tmp_path / f"trace_{rank}.jsonl"
    code = (
        "import numpy as np\n"
        "import ramba_tpu as rt\n"
        "a = rt.arange(256) * 2.0\n"
        "float(rt.sum(a))\n"
        "b = rt.arange(256) * 2.0\n"
        "float(rt.sum(b))\n"
        "np.asarray(rt.arange(1024).reshape(32, 32))\n"
        "from ramba_tpu.core import fuser\n"
        "print('FLUSHES=%d' % fuser.stats['flushes'])\n"
    )
    env = dict(os.environ)
    for k in ("RAMBA_TEST_PROCS", "RAMBA_TEST_PROC_ID", "RAMBA_TEST_COORD",
              "RAMBA_TEST_SHARED_TMP", "RAMBA_PROFILE_DIR"):
        env.pop(k, None)
    env["JAX_PLATFORMS"] = "cpu"
    env["RAMBA_TRACE"] = str(path)
    r = subprocess.run(
        [sys.executable, "-c", code], env=env, capture_output=True,
        text=True, timeout=300,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    n_flushes = int(r.stdout.strip().rsplit("FLUSHES=", 1)[1])

    lines = [ln for ln in path.read_text().splitlines() if ln.strip()]
    evs = [json.loads(ln) for ln in lines]  # every line must parse
    flushes = [e for e in evs if e.get("type") == "flush"]
    assert len(flushes) == n_flushes
    for f in flushes:
        for k in _SPAN_KEYS:
            assert k in f, f"trace record missing {k!r}"
        assert f["cache"] in ("hit", "miss")
    # identical chains: first compiles, second hits the cache
    assert flushes[0]["cache"] == "miss"
    assert any(f["cache"] == "hit" for f in flushes)

    rep = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "scripts",
                      "trace_report.py"),
         str(path)],
        capture_output=True, text=True, timeout=120,
    )
    assert rep.returncode == 0, rep.stderr[-2000:]
    assert "flushes:" in rep.stdout
    assert "cache:" in rep.stdout


@pytest.mark.skipif(
    bool(os.environ.get("RAMBA_TRACE")),
    reason="this process has tracing enabled (two-process trace leg)",
)
def test_disabled_trace_writes_no_file():
    assert not events.trace_enabled()
    n0 = len(events.ring)
    _run_chain()
    assert len(events.ring) > n0 or events.ring.maxlen == len(events.ring)
    assert events._trace_file is None  # no sink ever opened
