"""Pallas kernel backend + ledger-driven autotuner (ISSUE 9).

Covers ``ramba_tpu.core.autotune`` + ``ramba_tpu.ops.pallas_backend`` +
the fuser backend seam:

* mode parsing (``off`` / ``race`` / ``force:<backend>``) and the
  selection state machine on a deterministic fake ledger — alternation
  order, latch-on-K-samples, lower-p50 wins,
* persisted decision table: atomic write, reload across a simulated
  restart (``via: persisted``), and the second process skipping the race
  (``autotune.race_started`` does not advance),
* Pallas interpret-mode parity, byte-identical vs the XLA lowering for
  every registered kernel family: fused elementwise chains (map/cast +
  vector outputs), reductions on exact data (int sum) and on
  order-independent kinds (float min/max), and masked segment reductions
  (groupby sum/prod/min/max),
* seeded ``RAMBA_FAULTS=pallas:once`` leg: Pallas lowering failure
  degrades to XLA, latches ``via: fallback``, and records the fallback
  on the kernel ledger + event stream,
* the loser's compiled executable staying evictable through the
  existing true-LRU compile cache,
* race compiles offloaded through ``CompilePipeline.submit_warm`` (the
  flush that triggers a fresh Pallas compile is served from XLA while
  the challenger warms in the background),
* observability: ``diagnostics.perf_report()["autotune"]`` and the
  per-backend ledger columns in the telemetry exposition.
"""

import json
import os

import numpy as np
import pytest

import jax as _jax
import ramba_tpu as rt
from ramba_tpu import diagnostics
from ramba_tpu.core import autotune, fuser
from ramba_tpu.observe import events, ledger
from ramba_tpu.ops import pallas_backend
from ramba_tpu.resilience import faults

_MULTIPROC = _jax.process_count() > 1


def _counter(name):
    return diagnostics.counters().get(name, 0)


@pytest.fixture
def clean_autotune():
    """Autotune disarmed + pristine state, whatever the ambient env says;
    restores the env-driven configuration afterwards."""
    saved = {
        k: os.environ.pop(k, None)
        for k in ("RAMBA_AUTOTUNE", "RAMBA_AUTOTUNE_K", "RAMBA_AUTOTUNE_CACHE")
    }
    autotune.reconfigure()
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    autotune.reconfigure()


def _seed_exec(fp, backend, seconds, n=1):
    for _ in range(n):
        ledger.record_execute(fp, "fake", 1, "fused", seconds,
                              is_new=False, backend=backend)


# ---------------------------------------------------------------------------
# mode parsing + selection state machine (deterministic fake ledger)
# ---------------------------------------------------------------------------


class TestModes:
    def test_mode_parsing(self, clean_autotune):
        for raw, want in (("", "off"), ("0", "off"), ("off", "off"),
                          ("race", "race"), ("1", "race"), ("on", "race"),
                          ("force:pallas", "force"), ("force:xla", "force"),
                          ("garbage", "off")):
            autotune.reconfigure(mode=raw)
            assert autotune.mode() == want, raw
        autotune.reconfigure(mode="force:pallas")
        assert autotune.active()
        autotune.reconfigure(mode="off")
        assert not autotune.active()

    def test_env_driven_reconfigure(self, clean_autotune):
        os.environ["RAMBA_AUTOTUNE"] = "race"
        os.environ["RAMBA_AUTOTUNE_K"] = "7"
        autotune.reconfigure()
        assert autotune.mode() == "race"
        assert autotune.report()["k"] == 7

    def test_off_mode_is_default_xla(self, clean_autotune):
        assert autotune.select("fp-off", None, []) == ("xla", "default")

    def test_force_modes(self, clean_autotune, monkeypatch):
        monkeypatch.setattr(pallas_backend, "supports", lambda *a: True)
        autotune.reconfigure(mode="force:pallas")
        assert autotune.select("fp-f1", None, []) == ("pallas", "forced")
        autotune.reconfigure(mode="force:xla")
        assert autotune.select("fp-f1", None, []) == ("xla", "forced")
        # a program the Pallas backend can't lower is never forced onto it
        monkeypatch.setattr(pallas_backend, "supports", lambda *a: False)
        autotune.reconfigure(mode="force:pallas")
        assert autotune.select("fp-f2", None, []) == ("xla", "default")


class TestRace:
    def test_fake_ledger_race_latches_faster_backend(self, clean_autotune,
                                                     monkeypatch):
        autotune.reconfigure(mode="race", k=2)
        monkeypatch.setattr(pallas_backend, "supports", lambda *a: True)
        fp = "fp-race-pallas-wins"
        before = _counter("autotune.race_started")
        # empty ledger: the challenger races first (pays compile early)
        assert autotune.select(fp, None, []) == ("pallas", "racing")
        assert _counter("autotune.race_started") == before + 1
        _seed_exec(fp, "pallas", 0.001, n=2)
        # alternation steers toward the backend with fewer samples
        assert autotune.select(fp, None, []) == ("xla", "racing")
        _seed_exec(fp, "xla", 0.005, n=2)
        # both hold K steady-state samples: lower p50 latches
        assert autotune.select(fp, None, []) == ("pallas", "autotune")
        assert autotune.decision(fp) == {"backend": "pallas",
                                         "via": "autotune"}
        assert autotune.latched_via_autotune()
        # latched decisions are sticky — no more ledger consultation
        _seed_exec(fp, "xla", 0.0001, n=10)
        assert autotune.select(fp, None, []) == ("pallas", "autotune")

    def test_fake_ledger_race_xla_wins(self, clean_autotune, monkeypatch):
        autotune.reconfigure(mode="race", k=1)
        monkeypatch.setattr(pallas_backend, "supports", lambda *a: True)
        fp = "fp-race-xla-wins"
        _seed_exec(fp, "pallas", 0.004)
        _seed_exec(fp, "xla", 0.002)
        assert autotune.select(fp, None, []) == ("xla", "autotune")
        rep = autotune.report()
        assert rep["races_latched"] >= 1
        # the loser's measured time is the race overhead
        assert rep["race_overhead_s"] >= 0.004

    def test_unsupported_program_never_races(self, clean_autotune,
                                             monkeypatch):
        autotune.reconfigure(mode="race", k=1)
        monkeypatch.setattr(pallas_backend, "supports", lambda *a: False)
        before = _counter("autotune.race_started")
        assert autotune.select("fp-unsup", None, []) == ("xla", "default")
        assert _counter("autotune.race_started") == before


class TestPersistence:
    def test_decision_table_roundtrip_skips_race(self, clean_autotune,
                                                 tmp_path, monkeypatch):
        cache = str(tmp_path / "autotune.json")
        monkeypatch.setattr(pallas_backend, "supports", lambda *a: True)
        autotune.reconfigure(mode="race", k=1, cache_path=cache)
        fp = "fp-persist"
        _seed_exec(fp, "pallas", 0.001)
        _seed_exec(fp, "xla", 0.005)
        assert autotune.select(fp, None, []) == ("pallas", "autotune")
        with open(cache) as f:
            table = json.load(f)
        assert table["decisions"][fp]["backend"] == "pallas"

        # simulated restart: fresh in-memory state, same cache path
        races_before = _counter("autotune.race_started")
        loaded_before = _counter("autotune.table_loaded_decisions")
        autotune.reconfigure(mode="race", k=1, cache_path=cache)
        assert autotune.decision(fp) is None  # cleared — reload is lazy
        assert autotune.select(fp, None, []) == ("pallas", "persisted")
        assert autotune.latched_via_autotune()
        # the second process never started a race for this fingerprint
        assert _counter("autotune.race_started") == races_before
        assert _counter("autotune.table_loaded_decisions") \
            == loaded_before + 1

    def test_missing_table_is_not_an_error(self, clean_autotune, tmp_path,
                                           monkeypatch):
        monkeypatch.setattr(pallas_backend, "supports", lambda *a: True)
        autotune.reconfigure(mode="race", k=1,
                             cache_path=str(tmp_path / "nope.json"))
        assert autotune.select("fp-nocache", None, []) == ("pallas", "racing")

    def test_fallback_persisted(self, clean_autotune, tmp_path):
        cache = str(tmp_path / "autotune.json")
        autotune.reconfigure(mode="race", k=1, cache_path=cache)
        autotune.note_failure("fp-fb", "pallas", RuntimeError("mosaic"))
        with open(cache) as f:
            table = json.load(f)
        assert table["decisions"]["fp-fb"] == {"backend": "xla",
                                               "via": "fallback"}


class TestFallback:
    def test_note_failure_latches_xla(self, clean_autotune):
        autotune.reconfigure(mode="race", k=1)
        fp = "fp-fail"
        before = _counter("autotune.backend_fallback")
        autotune.note_failure(fp, "pallas", RuntimeError("boom"))
        assert autotune.select(fp, None, []) == ("xla", "fallback")
        assert _counter("autotune.backend_fallback") == before + 1
        stats = ledger.backend_stats(fp)
        assert stats["pallas"]["fallbacks"] == 1
        evs = events.last(5, type="backend_fallback")
        assert evs and evs[-1]["fingerprint"] == fp
        assert not autotune.latched_via_autotune()


# ---------------------------------------------------------------------------
# Pallas interpret-mode parity: byte-identical vs the XLA lowering
# ---------------------------------------------------------------------------


def _forced(backend):
    autotune.reconfigure(mode=f"force:{backend}")


def _pallas_exec_count():
    # compiles + steady-state samples: a single forced run is is_new and
    # lands in the compile column, which still proves Pallas executed
    total = 0
    for e in ledger.snapshot().get("kernels", {}).values():
        b = e.get("backends", {}).get("pallas")
        if b:
            total += b.get("exec", {}).get("count", 0) + b.get("compiles", 0)
    return total


@pytest.mark.skipif(_MULTIPROC, reason="forced-backend parity is a "
                    "single-controller concern; the SPMD leg is "
                    "two_process_suite --autotune-leg")
class TestPallasParity:
    N = 128 * 16  # lane-aligned 1-D length

    def _both(self, build):
        """Run ``build()`` under each forced backend; assert the Pallas
        leg actually executed a Pallas kernel (no silent degrade)."""
        _forced("xla")
        ref = build()
        before = _pallas_exec_count()
        _forced("pallas")
        got = build()
        assert _pallas_exec_count() > before, \
            "pallas backend did not execute (classifier rejected program?)"
        return ref, got

    def test_elemwise_chain_bytes_identical(self, clean_autotune):
        base = rt.arange(self.N) / 7.0
        rt.sync()

        def build():
            B = rt.sin(base)
            C = rt.cos(base)
            D = B * B + C * C
            del B, C
            s = float(rt.sum(D))
            out = np.asarray(D)
            del D
            return out, s

        (dx, sx), (dp, sp) = self._both(build)
        assert dx.dtype == dp.dtype
        assert np.array_equal(dx, dp)
        # sin^2 + cos^2 sums exactly: every element is 1.0
        assert sx == sp

    def test_int_chain_and_sum_exact(self, clean_autotune):
        base = rt.arange(self.N)
        rt.sync()

        def build():
            return int(rt.sum(base * 3 + 1))

        vx, vp = self._both(build)
        assert vx == vp

    def test_float_min_max_order_independent(self, clean_autotune):
        base = rt.sin(rt.arange(self.N) / 3.0)
        rt.sync()

        def build():
            D = base * 2.0
            return float(rt.min(D)), float(rt.max(D))

        (lo_x, hi_x), (lo_p, hi_p) = self._both(build)
        assert lo_x == lo_p and hi_x == hi_p

    def test_scalar_operand_promotion_matches(self, clean_autotune):
        # python-scalar operands exercise the weak-type promotion plan
        base = rt.arange(self.N) / 11.0
        rt.sync()

        def build():
            D = rt.maximum(base, 0.25) * 2 + 1
            out = np.asarray(D)
            del D
            return out

        dx, dp = self._both(build)
        assert dx.dtype == dp.dtype and np.array_equal(dx, dp)

    def test_segment_reduce_parity(self, clean_autotune):
        data = rt.arange(self.N) % 97
        labels = np.arange(self.N) % 8
        rt.sync()

        def build():
            out = {}
            for kind in ("sum", "prod", "min", "max"):
                g = data.groupby(0, labels, num_groups=8)
                out[kind] = np.asarray(getattr(g, kind)())
            return out

        ref, got = self._both(build)
        for kind in ref:
            assert ref[kind].dtype == got[kind].dtype, kind
            assert np.array_equal(ref[kind], got[kind]), kind

    def test_stencil_family_registered_with_interpret_fallback(
            self, clean_autotune, monkeypatch):
        from ramba_tpu.ops import stencil_pallas

        pallas_backend._ensure_builtins()
        fam = pallas_backend.family("stencil")
        assert fam is not None
        assert "stencil" in pallas_backend.family_names()
        # no TPU present: run() falls back to interpret=True instead of
        # raising (the availability gate still keeps it off by default)
        monkeypatch.setattr(stencil_pallas, "_INTERPRET", True)
        monkeypatch.setattr(stencil_pallas, "_ENABLED", True)
        from ramba_tpu.ops import stencil_sharded
        monkeypatch.setattr(stencil_sharded, "eligible", lambda *a, **k: False)

        @rt.stencil
        def shifted(a):
            return a[-1, 0] + a[0, 1]

        x = np.arange(64, dtype=np.float32).reshape(8, 8)
        out = rt.sstencil(shifted, rt.fromarray(x)).asarray()
        e = np.zeros_like(x)
        e[1:, :-1] = x[:-1, :-1] + x[1:, 1:]
        np.testing.assert_allclose(out, e)


# ---------------------------------------------------------------------------
# fault injection: Pallas lowering failure degrades to XLA, on the record
# ---------------------------------------------------------------------------


@pytest.mark.skipif(_MULTIPROC, reason="single-controller fault leg")
class TestFaultInjection:
    def test_pallas_fault_degrades_to_xla_and_records(self, clean_autotune):
        autotune.reconfigure(mode="race", k=2)
        faults.configure("pallas:once")
        try:
            base = rt.arange(128 * 16) / 3.0
            rt.sync()

            def chain():
                D = rt.sin(base) * 2.0
                return float(rt.sum(D))

            vals = [chain() for _ in range(4)]
            # the injected lowering failure never corrupts results
            assert max(vals) == min(vals)
            rep = autotune.report()
            assert rep["failed"], rep
            fp = rep["failed"][0]
            assert rep["decisions"][fp] == {"backend": "xla",
                                            "via": "fallback"}
            assert ledger.backend_stats(fp)["pallas"]["fallbacks"] >= 1
            evs = events.last(10, type="backend_fallback")
            assert any(e["fingerprint"] == fp for e in evs)
        finally:
            faults.configure(None)
            faults.reset()  # re-arm from env (unset in tier-1 -> disarmed)


# ---------------------------------------------------------------------------
# loser evictable via the existing true-LRU compile cache
# ---------------------------------------------------------------------------


@pytest.mark.skipif(_MULTIPROC, reason="single-controller cache leg")
def test_loser_executable_evictable_via_lru(clean_autotune, monkeypatch):
    from ramba_tpu.parallel import mesh as _mesh

    fuser.flush()
    saved = dict(fuser._compile_cache)
    fuser._compile_cache.clear()
    fuser._cache_epoch = _mesh.mesh_epoch
    try:
        _forced("pallas")
        base = rt.arange(128 * 2) / 3.0
        rt.sync()
        D = rt.cos(base) * 0.5
        float(rt.sum(D))
        del D
        pallas_keys = [k for k in fuser._compile_cache
                       if k and k[-1] == "pallas"]
        assert pallas_keys, "forced pallas run left no pallas cache entry"
        # now shrink the cache and push fresh programs through: the
        # pallas executable is ordinary LRU freight, not pinned
        monkeypatch.setattr(fuser, "_COMPILE_CACHE_MAX", 1)
        autotune.reconfigure(mode="off")
        for i in range(len(fuser._compile_cache) + 1):
            p = fuser._Program(((f"fake-evict{i}", None, (0,)),),
                               1, ("C",), (1,))
            fuser._get_compiled(p, ())
        assert all(k not in fuser._compile_cache for k in pallas_keys)
    finally:
        fuser._compile_cache.clear()
        fuser._compile_cache.update(saved)


# ---------------------------------------------------------------------------
# race compiles ride the async compile pipeline
# ---------------------------------------------------------------------------


def test_submit_warm_runs_thunk_and_captures_errors():
    from ramba_tpu.serve import pipeline as pl

    p = pl.CompilePipeline()
    try:
        done = []
        t = p.submit_warm(lambda: done.append(1), label="ok")
        assert t.wait(10) == []
        assert done == [1]
        boom = p.submit_warm(lambda: 1 / 0, label="boom")
        with pytest.raises(ZeroDivisionError):
            boom.wait(10)
    finally:
        p.stop()


@pytest.mark.skipif(_MULTIPROC, reason="single-controller prewarm leg")
def test_race_prewarm_offloads_challenger_compile(clean_autotune):
    import time as _time

    from ramba_tpu.serve import pipeline as pl

    autotune.reconfigure(mode="race", k=1)
    pl.get_pipeline()  # a live pipeline arms the deferral path
    submitted_before = _counter("autotune.prewarm_submitted")
    done_before = _counter("autotune.prewarm_done")
    base = rt.arange(128 * 4) / 13.0
    rt.sync()

    def chain():
        return float(rt.sum(rt.tanh(base) * 1.5))

    first = chain()
    assert _counter("autotune.prewarm_submitted") == submitted_before + 1
    deadline = _time.monotonic() + 30
    while _counter("autotune.prewarm_done") < done_before + 1:
        assert _time.monotonic() < deadline, "prewarm never completed"
        _time.sleep(0.01)
    # once warm, the race proceeds and every execution stays correct
    vals = [chain() for _ in range(6)]
    assert all(v == first for v in vals)


# ---------------------------------------------------------------------------
# observability wiring
# ---------------------------------------------------------------------------


def test_perf_report_autotune_section(clean_autotune, monkeypatch):
    monkeypatch.setattr(pallas_backend, "supports", lambda *a: True)
    autotune.reconfigure(mode="race", k=1)
    fp = "fp-report"
    _seed_exec(fp, "pallas", 0.001)
    _seed_exec(fp, "xla", 0.002)
    assert autotune.select(fp, None, []) == ("pallas", "autotune")
    rep = diagnostics.perf_report()["autotune"]
    assert rep["mode"] == "race"
    assert rep["decisions"][fp]["backend"] == "pallas"
    assert rep["races_latched"] >= 1
    # off + no decisions: the section stays out of perf captures
    autotune.reconfigure(mode="off")
    assert "autotune" not in diagnostics.perf_report()


def test_telemetry_exports_backend_and_autotune_series(clean_autotune,
                                                       monkeypatch):
    from ramba_tpu.observe import telemetry

    monkeypatch.setattr(pallas_backend, "supports", lambda *a: True)
    autotune.reconfigure(mode="race", k=1)
    fp = "fp-telemetry"
    _seed_exec(fp, "pallas", 0.001)
    _seed_exec(fp, "xla", 0.002)
    assert autotune.select(fp, None, []) == ("pallas", "autotune")
    text = telemetry.render()
    assert 'ramba_kernel_backend_exec_total' in text
    assert 'backend="pallas"' in text
    assert "ramba_autotune_decisions" in text
    assert "ramba_autotune_races_latched_total" in text


def test_ledger_entry_summary_has_backend_columns(clean_autotune):
    fp = "fp-columns"
    _seed_exec(fp, "pallas", 0.002, n=3)
    ledger.record_execute(fp, "fake", 1, "fused", 0.5, is_new=True,
                          backend="pallas")
    entry = ledger.snapshot()["kernels"][fp]
    b = entry["backends"]["pallas"]
    assert b["exec"]["count"] == 3
    assert b["compiles"] == 1 and b["compile_s"] >= 0.5
    stats = ledger.backend_stats(fp)
    assert stats["pallas"]["count"] == 3
    assert stats["pallas"]["p50_s"] == pytest.approx(0.002)
