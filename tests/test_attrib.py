"""Critical-path attribution plane: waterfalls, rooflines, sentinel.

Covers ``ramba_tpu.observe.attrib`` + the fuser/pipeline stage stamps +
the offline CLIs:

* every flush span carries a monotonically-ordered stage ledger whose
  durations plus the ``unattributed_s`` residual reconcile with span
  wall time (within 5 % for benched kernels),
* roofline math (``classify``) on a fake peak table — achieved rates,
  fraction of peak, bandwidth-vs-compute boundedness at the ridge point,
* ``RAMBA_PEAKS_JSON`` override resolution (inline JSON and file path,
  device_kind substring match, default fallback),
* live roofline rows built from fenced device windows + ledger cost
  models under ``RAMBA_PERF=1``,
* the perf-regression sentinel: exactly one ``perf_regression`` event +
  flight-recorder incident under ``RAMBA_FAULTS=execute:delay:ms=150``,
  silence on a clean soak, baselines persisted/restored across
  processes via ``RAMBA_BASELINE_DIR``,
* ``RAMBA_PROFILE=deep`` profiler-annotation smoke,
* Prometheus series: stage totals + rooflines + regressions, and the
  compile-class/AOT satellite counters,
* ``scripts/trace_report.py --attrib`` and ``scripts/roofline_report.py``
  on synthetic inputs, ``scripts/perf_diff.py`` device-kind warning.
"""

import contextlib
import glob
import json
import os
import subprocess
import sys

import ramba_tpu as rt
from ramba_tpu import diagnostics
from ramba_tpu.observe import attrib, events, ledger, profile, telemetry
from ramba_tpu.resilience import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _chain(n=2711):
    a = rt.arange(n) * 2.0 + 1.0
    return float(rt.sum(a))


def _big_chain():
    a = rt.arange(1_500_000) * 1.000001 + 0.5
    b = rt.sqrt(a * a + 1.0)
    return float(rt.sum(b))


@contextlib.contextmanager
def _env(**kv):
    saved = {k: os.environ.get(k) for k in kv}
    for k, v in kv.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# ---------------------------------------------------------------------------
# stage ledger: ordering + wall reconciliation
# ---------------------------------------------------------------------------


def test_span_stages_ordered_and_reconcile_with_wall():
    _chain()
    span = diagnostics.last_flushes(1)[0]
    st = span["stages"]
    assert st, span
    # only canonical stages, in canonical (monotonic critical-path) order
    assert set(st) <= set(attrib.STAGES)
    order = [k for k in attrib.STAGES if k in st]
    assert list(st) == order or sorted(st, key=attrib.STAGES.index) == order
    # identity: stages + residual == wall (finalize_span construction)
    total = sum(st.values()) + span["unattributed_s"]
    assert abs(total - span["wall_s"]) <= 2e-5 * (len(st) + 2), span


def test_benched_kernel_stage_sum_within_5pct_of_wall():
    _big_chain()  # compile outside the measurement
    for _ in range(5):
        _big_chain()
    spans = diagnostics.last_flushes(5)
    label = spans[-1]["label"]
    fracs = sorted(
        s["unattributed_s"] / s["wall_s"]
        for s in spans if s["label"] == label and s["wall_s"] > 0
    )
    assert fracs, spans
    # acceptance: stage durations explain >= 95 % of span wall for a
    # benched (ms-scale) kernel; median shields one scheduler hiccup
    assert fracs[len(fracs) // 2] <= 0.05, fracs


def test_attribution_report_aggregates():
    _chain()
    rep = attrib.attribution_report()
    assert rep["flushes"] >= 1
    assert rep["stage_seconds"].get("prepare", 0.0) > 0.0
    assert rep["unattributed_s"] >= 0.0
    assert 0.0 <= rep["unattributed_frac"] <= 1.0
    assert rep["peaks"]["peak_gbps"] > 0
    assert rep == diagnostics.perf_report()["attribution"]


def test_attrib_off_disables_fence_but_keeps_stages():
    with _env(RAMBA_ATTRIB="off"):
        attrib.reconfigure()
        try:
            assert not attrib.fence_enabled()
            _chain(2713)
            st = diagnostics.last_flushes(1)[0]["stages"]
            assert "device_execute" not in st
            assert "dispatch" in st or "compile" in st
        finally:
            pass
    attrib.reconfigure()
    assert attrib.fence_enabled()


# ---------------------------------------------------------------------------
# roofline math + peak tables (pure units)
# ---------------------------------------------------------------------------


def test_classify_bandwidth_vs_compute_bound():
    peaks = {"peak_gbps": 100.0, "peak_tflops": 1.0}  # ridge = 10 fl/B
    r = attrib.classify(flops=1e6, bytes_accessed=1e8, device_s=1e-3,
                        peaks=peaks)
    assert r["bound"] == "bandwidth"
    assert r["achieved_gb_per_s"] == 100.0       # at peak bandwidth
    assert r["bandwidth_frac"] == 1.0
    assert r["frac_of_peak"] == 1.0
    assert r["intensity"] == 0.01 and r["ridge"] == 10.0
    c = attrib.classify(flops=1e10, bytes_accessed=1e6, device_s=1e-2,
                        peaks=peaks)
    assert c["bound"] == "compute"
    assert c["achieved_tflops"] == 1.0
    assert c["compute_frac"] == 1.0
    # degenerate inputs refuse to classify rather than divide by zero
    assert attrib.classify(0, 0, 1e-3, peaks) is None
    assert attrib.classify(1e6, 1e6, 0.0, peaks) is None


def test_peak_table_override_inline_and_file(tmp_path):
    table = {"zz99": {"peak_gbps": 123.0, "peak_tflops": 4.5},
             "default": {"peak_gbps": 7.0, "peak_tflops": 0.5}}
    with _env(RAMBA_PEAKS_JSON=json.dumps(table)):
        attrib.reconfigure()
        hit = attrib.peak_table("Super ZZ99 Chip")
        assert hit["peak_gbps"] == 123.0 and hit["peak_tflops"] == 4.5
        assert hit["source"] == "RAMBA_PEAKS_JSON"
        miss = attrib.peak_table("unknown-part")
        assert miss["peak_gbps"] == 7.0
        assert miss["source"].endswith(":default")
    p = tmp_path / "peaks.json"
    p.write_text(json.dumps(table))
    with _env(RAMBA_PEAKS_JSON=str(p)):
        attrib.reconfigure()
        assert attrib.peak_table("zz99 rev2")["peak_tflops"] == 4.5
    attrib.reconfigure()
    # builtin table survives a bogus override
    assert attrib.peak_table("TPU v4")["peak_gbps"] == 1228.0


def test_live_roofline_rows_from_fenced_windows():
    ledger.reconfigure(mode="on")  # arm cost_analysis capture
    try:
        for _ in range(4):
            _chain(3217)  # unique shape => fresh kernel => cost captured
        rep = attrib.attribution_report()
        rows = [r for r in rep["rooflines"].values()
                if r["device_time_source"] == "fence"]
        assert rows, rep["rooflines"]
        r = rows[0]
        assert r["bound"] in ("bandwidth", "compute")
        assert r["frac_of_peak"] >= 0.0
        assert r["device_p50_s"] > 0.0
        assert r["achieved_gb_per_s"] >= 0.0
    finally:
        ledger.reconfigure()


# ---------------------------------------------------------------------------
# perf-regression sentinel
# ---------------------------------------------------------------------------


def test_sentinel_fires_exactly_once_with_flight_incident(tmp_path):
    fdir = tmp_path / "flight"
    with _env(RAMBA_FLIGHT_DIR=str(fdir)):
        telemetry.flight_reset()
        attrib.reset()
        attrib.reconfigure(baseline_dir=str(tmp_path / "base"),
                           drift_min_samples=3)
        try:
            for _ in range(5):
                _chain(4099)
            assert attrib.save_baselines()
            # simulate a fresh run against the saved baseline
            attrib.reset()
            attrib.reconfigure(baseline_dir=str(tmp_path / "base"),
                               drift_min_samples=3)
            base = len(events.last(0, type="perf_regression"))
            with faults.active("execute:delay:ms=150"):
                for _ in range(4):
                    _chain(4099)
            evs = events.last(0, type="perf_regression")
            assert len(evs) == base + 1, evs
            ev = evs[-1]
            for k in ("fingerprint", "label", "p50_s", "baseline_p50_s",
                      "drift", "factor", "samples"):
                assert k in ev, f"perf_regression missing {k!r}"
            assert ev["p50_s"] > ev["baseline_p50_s"] * 2.0
            assert ev["drift"] > 2.0
            # exactly one flight-recorder incident for the regression
            recs = [json.load(open(p))
                    for p in glob.glob(str(fdir / "flight_*.json"))]
            perf_recs = [r for r in recs
                         if r["incident"]["type"] == "perf_regression"]
            assert len(perf_recs) == 1, [r["incident"]["type"] for r in recs]
            # further offending flushes do NOT re-fire for the same kernel
            with faults.active("execute:delay:ms=150"):
                _chain(4099)
            assert len(events.last(0, type="perf_regression")) == base + 1
            sen = diagnostics.perf_report()["attribution"]["sentinel"]
            assert sen["regressions"] == 1
            assert ev["fingerprint"] in sen["regressed"]
        finally:
            telemetry.flight_reset()
            attrib.reset()
            attrib.reconfigure()


def test_sentinel_silent_on_clean_soak(tmp_path):
    attrib.reset()
    attrib.reconfigure(baseline_dir=str(tmp_path), drift_min_samples=3)
    try:
        for _ in range(5):
            _chain(4111)
        assert attrib.save_baselines()
        attrib.reset()
        attrib.reconfigure(baseline_dir=str(tmp_path), drift_min_samples=3)
        base = len(events.last(0, type="perf_regression"))
        for _ in range(8):
            _chain(4111)
        assert len(events.last(0, type="perf_regression")) == base
        # drift_factor <= 0 disables the sentinel even for glacial calls
        attrib.reset()
        attrib.reconfigure(baseline_dir=str(tmp_path), drift_factor=0.0,
                           drift_min_samples=3)
        with faults.active("execute:delay:ms=150"):
            for _ in range(4):
                _chain(4111)
        assert len(events.last(0, type="perf_regression")) == base
    finally:
        attrib.reset()
        attrib.reconfigure()


def test_baseline_only_ratchets_down(tmp_path):
    attrib.reset()
    attrib.reconfigure(baseline_dir=str(tmp_path), drift_min_samples=1)
    try:
        attrib.record_device("aa" * 6, "prog_x", 0.010)
        attrib.save_baselines()
        first = attrib.load_baselines()["aa" * 6]["p50_s"]
        assert first == 0.010
        # a slower run must not raise the bar...
        attrib.reset()
        attrib.reconfigure(baseline_dir=str(tmp_path), drift_min_samples=1)
        attrib.record_device("aa" * 6, "prog_x", 0.500)
        attrib.save_baselines()
        assert attrib.load_baselines()["aa" * 6]["p50_s"] == first
        # ...while a faster run lowers it
        attrib.reset()
        attrib.reconfigure(baseline_dir=str(tmp_path), drift_min_samples=1)
        attrib.record_device("aa" * 6, "prog_x", 0.002)
        attrib.save_baselines()
        assert attrib.load_baselines()["aa" * 6]["p50_s"] == 0.002
    finally:
        attrib.reset()
        attrib.reconfigure()


def test_baseline_persist_restore_across_processes(tmp_path):
    """Process 1 records baselines; process 2 restores them from
    RAMBA_BASELINE_DIR and its seeded delay trips the sentinel exactly
    once — fingerprints are process-stable, so the baseline file is the
    only state shared."""
    record = (
        "import ramba_tpu as rt\n"
        "from ramba_tpu.observe import attrib\n"
        "for _ in range(5):\n"
        "    a = rt.arange(2711) * 2.0 + 1.0\n"
        "    float(rt.sum(a))\n"
        "p = attrib.save_baselines()\n"
        "assert p, 'no baseline written'\n"
        "print('SAVED', len(attrib.load_baselines()))\n"
    )
    check = (
        "import ramba_tpu as rt\n"
        "from ramba_tpu.observe import attrib, events\n"
        "assert attrib.load_baselines(), 'baseline file not restored'\n"
        "for _ in range(5):\n"
        "    a = rt.arange(2711) * 2.0 + 1.0\n"
        "    float(rt.sum(a))\n"
        "print('REGRESSIONS', len(events.last(0, type='perf_regression')))\n"
    )
    env = dict(os.environ)
    env.pop("RAMBA_FAULTS", None)
    env.pop("RAMBA_TRACE", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["RAMBA_BASELINE_DIR"] = str(tmp_path)
    env["RAMBA_PERF_DRIFT_MIN_SAMPLES"] = "3"
    r1 = subprocess.run([sys.executable, "-c", record], env=env,
                        capture_output=True, text=True, cwd=REPO)
    assert r1.returncode == 0, r1.stdout + r1.stderr
    assert "SAVED" in r1.stdout
    assert os.path.exists(tmp_path / "perf_baseline.json")
    env2 = dict(env)
    env2["RAMBA_FAULTS"] = "execute:delay:ms=150"
    r2 = subprocess.run([sys.executable, "-c", check], env=env2,
                        capture_output=True, text=True, cwd=REPO)
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert "REGRESSIONS 1" in r2.stdout, r2.stdout


# ---------------------------------------------------------------------------
# deep-mode profiler annotation
# ---------------------------------------------------------------------------


def test_deep_profile_annotation_smoke():
    with _env(RAMBA_PROFILE="deep"):
        profile.reconfigure()
        assert profile.deep()
        import jax.profiler as _prof

        ctx = profile.flush_annotation("ramba_flush:test",
                                       trace_id="tr-0042")
        assert isinstance(ctx, _prof.TraceAnnotation)
        with ctx:
            pass
        _chain()  # a real flush dispatches under the annotation
    profile.reconfigure()
    assert not profile.deep()
    if not os.environ.get("RAMBA_PROFILE_DIR"):
        from ramba_tpu import common

        if common.timing_level <= 1:
            assert isinstance(profile.flush_annotation("x"),
                              type(contextlib.nullcontext()))


# ---------------------------------------------------------------------------
# Prometheus exposition
# ---------------------------------------------------------------------------


def test_prometheus_attrib_series():
    _chain()
    text = telemetry.render()
    assert "ramba_flushes_attributed_total" in text
    assert 'ramba_stage_seconds_total{' in text
    assert 'stage="prepare"' in text
    assert "ramba_stage_unattributed_seconds_total" in text
    assert "ramba_perf_regressions_total" in text
    # satellite: jit-cache hit rate reaches the exporter
    assert "ramba_compile_hit_rate" in text


def test_prometheus_compile_class_satellite_counters(monkeypatch):
    from ramba_tpu.compile import classes, persist

    monkeypatch.setattr(classes, "snapshot", lambda: {
        "mode": "pow2", "planned": 3, "padded": 2, "bailouts": 0,
        "pad_bytes": 4096, "pad_waste_frac": 0.25,
    })
    monkeypatch.setattr(persist, "snapshot", lambda: {
        "armed": True, "hits": 1, "misses": 2, "corrupt": 0, "stores": 1,
        "bytes_read": 10, "bytes_written": 20, "call_fallbacks": 7,
    })
    fams = telemetry._Families({"rank": 0})
    telemetry._compile_series(fams)
    text = fams.render()
    fallback = [l for l in text.splitlines()
                if l.startswith("ramba_compile_call_fallbacks_total")]
    assert fallback and fallback[0].endswith(" 7"), text
    waste = [l for l in text.splitlines()
             if l.startswith("ramba_compile_bucket_pad_waste_bytes")]
    assert waste and waste[0].endswith(" 4096"), text


# ---------------------------------------------------------------------------
# offline CLIs
# ---------------------------------------------------------------------------


def _write_jsonl(path, events_):
    with open(path, "w") as f:
        for e in events_:
            f.write(json.dumps(e) + "\n")


def test_trace_report_attrib_waterfall_cli(tmp_path):
    path = tmp_path / "t.jsonl"
    _write_jsonl(path, [
        {"type": "flush", "label": "prog_a", "ts": 1.0, "seq": 1,
         "wall_s": 0.1, "unattributed_s": 0.01,
         "stages": {"prepare": 0.01, "compile": 0.07, "dispatch": 0.005,
                    "device_execute": 0.004, "write_back": 0.001}},
        {"type": "flush", "label": "prog_b", "ts": 1.1, "seq": 2,
         "wall_s": 0.05, "unattributed_s": 0.03,
         "stages": {"prepare": 0.005, "dispatch": 0.01,
                    "device_execute": 0.005}},
    ])
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "trace_report.py"),
         str(path), "--attrib"],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "stage waterfall" in r.stdout
    assert "prog_a" in r.stdout and "prog_b" in r.stdout
    assert "unattributed gap" in r.stdout
    # prog_b carries the bigger unexplained gap => listed first
    gap_block = r.stdout.split("unattributed gap")[1]
    assert gap_block.index("prog_b") < gap_block.index("prog_a")
    # a trace with no stage ledgers reports rather than crashes
    bare = tmp_path / "bare.jsonl"
    _write_jsonl(bare, [{"type": "flush", "label": "prog_c", "ts": 1.0,
                         "seq": 1, "wall_s": 0.1}])
    r2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "trace_report.py"),
         str(bare), "--attrib"],
        capture_output=True, text=True,
    )
    assert r2.returncode == 1
    assert "no stage-attributed" in r2.stdout


def test_trace_report_merge_ranks_stage_columns(tmp_path):
    base = tmp_path / "m.jsonl"
    for rank in range(2):
        _write_jsonl(f"{base}.rank{rank}", [
            {"type": "health", "source": "distributed_init", "outcome": "ok",
             "ts": 10.0, "seq": 1, "rank": rank},
            {"type": "flush", "label": "prog_a", "ts": 10.1, "seq": 2,
             "rank": rank, "wall_s": 0.01, "cache": "miss",
             "unattributed_s": 0.001,
             "stages": {"prepare": 0.002, "compile": 0.007}},
        ])
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "trace_report.py"),
         str(base), "--merge-ranks"],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "rank divergence: none" in r.stdout
    assert "stage seconds per rank:" in r.stdout
    assert "prepare" in r.stdout and "unattributed" in r.stdout
    # a rank stamping a different stage signature at the same flush
    # index is flagged as divergence
    _write_jsonl(f"{base}.rank1", [
        {"type": "health", "source": "distributed_init", "outcome": "ok",
         "ts": 10.0, "seq": 1, "rank": 1},
        {"type": "flush", "label": "prog_a", "ts": 10.1, "seq": 2,
         "rank": 1, "wall_s": 0.01, "cache": "miss",
         "unattributed_s": 0.001,
         "stages": {"prepare": 0.002, "compile": 0.005,
                    "device_execute": 0.002}},
    ])
    r2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "trace_report.py"),
         str(base), "--merge-ranks"],
        capture_output=True, text=True,
    )
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert "rank divergence at flush #0" in r2.stdout
    assert "stages" in r2.stdout


def test_roofline_report_cli(tmp_path):
    cap = tmp_path / "cap.json"
    cap.write_text(json.dumps({
        "device_kind": "FakeChip",
        "kernels": {
            "aabbccdd0011": {
                "label": "prog_bw",
                "exec": {"count": 5, "p50_s": 0.001, "total_s": 0.005},
                "sync": {"count": 5, "p50_s": 0.001},
                "flops": 1e6, "bytes_accessed": 1e8,
            },
            "ddccbbaa1100": {
                "label": "prog_fl",
                "exec": {"count": 5, "p50_s": 0.01, "total_s": 0.05},
                "flops": 1e10, "bytes_accessed": 1e6,
            },
            "deadbeef0000": {  # no cost model => skipped
                "label": "prog_na",
                "exec": {"count": 5, "p50_s": 0.01, "total_s": 0.05},
            },
        },
    }))
    peaks = json.dumps({"peak_gbps": 100.0, "peak_tflops": 1.0})
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "roofline_report.py"),
         str(cap), "--peaks", peaks],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "prog_bw" in r.stdout and "bandwidth" in r.stdout
    assert "prog_fl" in r.stdout and "compute" in r.stdout
    assert "1 skipped" in r.stdout
    assert "RAMBA_PERF=sync" in r.stdout  # dispatch-window caveat
    rj = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "roofline_report.py"),
         str(cap), "--peaks", peaks, "--json"],
        capture_output=True, text=True,
    )
    assert rj.returncode == 0, rj.stdout + rj.stderr
    obj = json.loads(rj.stdout)
    assert obj["device_kind"] == "FakeChip"
    by_label = {k["label"]: k for k in obj["kernels"]}
    assert by_label["prog_bw"]["bound"] == "bandwidth"
    assert by_label["prog_bw"]["frac_of_peak"] == 1.0
    assert by_label["prog_bw"]["device_time_source"] == "sync"
    assert by_label["prog_fl"]["device_time_source"] == "dispatch"
    # no usable kernels => usage error
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"kernels": {}}))
    r3 = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "roofline_report.py"),
         str(empty)],
        capture_output=True, text=True,
    )
    assert r3.returncode == 2


def test_perf_diff_warns_on_device_kind_mismatch(tmp_path):
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    kernels = {"aa00": {"label": "prog_a",
                        "exec": {"count": 5, "p50_s": 0.01,
                                 "total_s": 0.05}}}
    old.write_text(json.dumps({"device_kind": "TPU v4",
                               "kernels": kernels, "hbm_gb_per_s": 100.0}))
    new.write_text(json.dumps({"device_kind": "TPU v5e",
                               "kernels": kernels, "hbm_gb_per_s": 101.0}))
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "perf_diff.py"),
         str(old), str(new)],
        capture_output=True, text=True,
    )
    # warns (stderr) but does NOT gate: identical kernels => exit 0
    assert r.returncode == 0, r.stdout + r.stderr
    assert "device_kind mismatch" in r.stderr
    # same kind => no warning
    new.write_text(json.dumps({"device_kind": "TPU v4",
                               "kernels": kernels, "hbm_gb_per_s": 101.0}))
    r2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "perf_diff.py"),
         str(old), str(new)],
        capture_output=True, text=True,
    )
    assert r2.returncode == 0
    assert "device_kind mismatch" not in r2.stderr
