"""Fleet serving plane (PR 17): shared artifact tier, migration, router.

Covers the library seams of ``ramba_tpu/fleet/``:

* the shared artifact tier's race discipline — atomic tmp+rename
  publish, cross-PROCESS two-writer race with a concurrent reader that
  must never observe a torn blob, dead-writer temp GC, corruption on
  read evicted and recomputed (never raised), the content-addressed
  memo key, and the size cap,
* session migration: ``export_session`` / ``adopt_session`` round-trip
  through the PR-7 checkpoint format, manifest validation, discard,
* the ``redirect`` rung in ``retry.classify`` — fleet errors are
  retryable *elsewhere*, while in-process sheds stay fatal,
* ``observe.fleet.poll()`` — the single load/classify/rollup pass the
  collector and the router both consume, endpoint signal included,
* ``overload.admission_verdict`` — the read-only router probe that
  must not perturb breaker state, and
* the Router against REAL in-process replica servers: placement,
  refusal redirect (which must NOT feed the fleet breaker), and
  kill-mid-session heal-by-replay with byte-identical digests.

The full multi-process soak (router process + replica subprocesses +
SIGKILL + stitched traces) is scripts/two_process_suite.py --router-leg;
these tests pin the library logic in-process.
"""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import ramba_tpu as rt
from ramba_tpu.fleet import artifacts, migrate
from ramba_tpu.fleet.router import (NoHealthyReplica, ReplicaRefusal,
                                    ReplicaUnavailable, Router)
from ramba_tpu.observe import fleet, registry
from ramba_tpu.resilience import retry
from ramba_tpu.serve import overload

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    for k in ("RAMBA_ARTIFACTS", "RAMBA_MEMO_SHARED",
              "RAMBA_MEMO_SHARED_MAX", "RAMBA_HANDOFF_DIR",
              "RAMBA_FLEET_DIR", "RAMBA_FLEET_ENDPOINT",
              "RAMBA_ROUTER_HEDGE", "RAMBA_BREAKER_THRESHOLD"):
        monkeypatch.delenv(k, raising=False)
    artifacts.reset()
    overload.reset()
    yield
    artifacts.reset()
    overload.reset()


# ---------------------------------------------------------------------------
# shared artifact tier
# ---------------------------------------------------------------------------


def test_store_blob_atomic_roundtrip(tmp_path):
    p = str(tmp_path / "blob.bin")
    assert artifacts.store_blob(p, b"one")
    assert artifacts.load_blob(p) == b"one"
    assert artifacts.store_blob(p, b"two")  # replace, not append
    assert artifacts.load_blob(p) == b"two"
    assert artifacts.load_blob(str(tmp_path / "missing")) is None
    # no staging debris after successful publishes
    assert not [n for n in os.listdir(tmp_path) if n.startswith(".tmp-")]


def test_memo_roundtrip_and_stats(tmp_path):
    artifacts.configure(str(tmp_path))
    outs = [np.arange(8, dtype=np.float32), np.ones((2, 3))]
    assert artifacts.memo_store("k" * 32, outs)
    got = artifacts.memo_load("k" * 32)
    assert len(got) == 2
    np.testing.assert_array_equal(got[0], outs[0])
    np.testing.assert_array_equal(got[1], outs[1])
    assert artifacts.memo_load("m" * 32) is None  # miss
    snap = artifacts.snapshot()
    assert snap["memo_stores"] == 1
    assert snap["memo_hits"] == 1
    assert snap["memo_misses"] == 1


def test_memo_corruption_evicted_never_raised(tmp_path):
    artifacts.configure(str(tmp_path))
    artifacts.memo_store("c" * 32, [np.arange(4)])
    path = os.path.join(str(tmp_path), "memo", "c" * 32 + ".npz")
    with open(path, "wb") as f:
        f.write(b"not an npz at all")
    assert artifacts.memo_load("c" * 32) is None  # evict + recompute
    assert not os.path.exists(path)
    assert artifacts.snapshot()["memo_corrupt"] == 1


def test_memo_size_cap(tmp_path, monkeypatch):
    monkeypatch.setenv("RAMBA_MEMO_SHARED_MAX", "64")
    artifacts.configure(str(tmp_path))
    assert not artifacts.memo_store("b" * 32, [np.zeros(1024)])
    assert artifacts.snapshot()["memo_skipped_large"] == 1
    # content_key refuses over-cap inputs too (hashing them is the cost)
    assert artifacts.content_key("ch", [np.zeros(1024)], "fp") is None


def test_content_key_binds_bytes_not_identity(tmp_path):
    artifacts.configure(str(tmp_path))
    a = np.arange(16, dtype=np.float64)
    k1 = artifacts.content_key("chash", [a, ("scalar", 2.0)], "fp")
    k2 = artifacts.content_key("chash", [a.copy(), ("scalar", 2.0)], "fp")
    assert k1 == k2  # same bytes, different buffers
    b = a.copy()
    b[3] += 1.0
    assert artifacts.content_key("chash", [b, ("scalar", 2.0)], "fp") != k1
    assert artifacts.content_key("other", [a, ("scalar", 2.0)], "fp") != k1
    assert artifacts.content_key("chash", [a, ("scalar", 2.0)], "fp2") != k1


def test_gc_stale_tmp_sweeps_dead_writers(tmp_path):
    artifacts.configure(str(tmp_path))
    memo_dir = os.path.join(str(tmp_path), "memo")
    dead = os.path.join(memo_dir, ".tmp-deadwriter")
    with open(dead, "w") as f:
        f.write("partial")
    old = time.time() - 3600
    os.utime(dead, (old, old))
    fresh = os.path.join(memo_dir, ".tmp-livewriter")
    with open(fresh, "w") as f:
        f.write("partial")
    assert artifacts.gc_stale_tmp(max_age_s=300.0) == 1
    assert not os.path.exists(dead)
    assert os.path.exists(fresh)  # a live writer's staging file survives
    assert artifacts.snapshot()["tmp_gcd"] == 1


def test_disarmed_tier_is_inert(tmp_path):
    # no RAMBA_ARTIFACTS: every call degrades to a no-op, never raises
    assert not artifacts.armed()
    assert not artifacts.memo_store("k" * 32, [np.arange(4)])
    assert artifacts.memo_load("k" * 32) is None
    assert artifacts.handoff_dir() is None
    assert artifacts.gc_stale_tmp() == 0


_RACE_WRITER = """
import os, sys, time
import numpy as np
from ramba_tpu.fleet import artifacts
d, val, n, go = sys.argv[1], float(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
artifacts.configure(d)
while not os.path.exists(go):
    time.sleep(0.01)
for i in range(n):
    assert artifacts.memo_store("racekey" + "0" * 25,
                                [np.full(2048, val)])
print("WRITER_DONE", flush=True)
"""


def test_cross_process_write_race(tmp_path):
    """Two subprocess writers hammer the SAME memo key while this
    process reads it concurrently: every read must be a complete blob
    from one writer or the other (or a miss) — never torn, never a
    corruption eviction — and no staging temp survives the race."""
    artifacts.configure(str(tmp_path))
    go = str(tmp_path / "go")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env.setdefault("JAX_PLATFORMS", "cpu")
    writers = [
        subprocess.Popen([sys.executable, "-c", _RACE_WRITER,
                          str(tmp_path), val, "40", go],
                         env=env, cwd=REPO, stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True)
        for val in ("1.0", "2.0")
    ]
    with open(go, "w") as f:
        f.write("go")
    key = "racekey" + "0" * 25
    reads = complete = 0
    while any(w.poll() is None for w in writers):
        got = artifacts.memo_load(key)
        reads += 1
        if got is not None:
            (arr,) = got
            assert arr.shape == (2048,)
            v = arr[0]
            assert v in (1.0, 2.0)
            assert np.all(arr == v)  # one writer's payload, whole
            complete += 1
    outs = [w.communicate()[0] for w in writers]
    assert all(w.returncode == 0 for w in writers), outs
    assert all("WRITER_DONE" in o for o in outs), outs
    # exactly one winner file, complete, and no torn read was ever seen
    memo_dir = os.path.join(str(tmp_path), "memo")
    blobs = [n for n in os.listdir(memo_dir) if n.endswith(".npz")]
    assert blobs == [key + ".npz"]
    assert complete > 0 and reads > 0
    assert artifacts.snapshot()["memo_corrupt"] == 0
    final = artifacts.memo_load(key)[0]
    assert np.all(final == final[0]) and final[0] in (1.0, 2.0)
    # any staging debris is dead-writer debris; the sweep clears it
    artifacts.gc_stale_tmp(max_age_s=0.0)
    assert not [n for n in os.listdir(memo_dir)
                if n.startswith(".tmp-")]


# ---------------------------------------------------------------------------
# session migration
# ---------------------------------------------------------------------------


def test_migrate_export_adopt_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setenv("RAMBA_ARTIFACTS", str(tmp_path))
    artifacts.configure(str(tmp_path))
    state = {"x": rt.full([32], 3.5), "y": rt.arange(8),
             "_keep": rt.full([4], 1.0)}  # underscore = not durable
    meta = {"tenant": "acme", "trace_id": "t1", "seq": 7}
    path = migrate.export_session("sid-1", meta, state)
    assert os.path.exists(path)
    assert "sid-1" in migrate.list_handoffs()
    manifest, adopted = migrate.adopt_session("sid-1")
    assert manifest["tenant"] == "acme"
    assert manifest["seq"] == 7
    assert manifest["names"] == ["x", "y"]
    assert set(adopted) == {"x", "y"}
    np.testing.assert_array_equal(np.asarray(adopted["x"].asarray()),
                                  np.full(32, 3.5))
    np.testing.assert_array_equal(np.asarray(adopted["y"].asarray()),
                                  np.arange(8))
    migrate.discard("sid-1")
    assert "sid-1" not in migrate.list_handoffs()
    with pytest.raises(migrate.MigrateError):
        migrate.adopt_session("sid-1")


def test_migrate_manifest_validation(tmp_path, monkeypatch):
    monkeypatch.setenv("RAMBA_ARTIFACTS", str(tmp_path))
    artifacts.configure(str(tmp_path))
    with pytest.raises(migrate.MigrateError):
        migrate.load_manifest("never-exported")
    migrate.export_session("sid-2", {"seq": 1}, {"x": rt.full([4], 1.0)})
    # a manifest claiming another sid is a placement bug, not adoptable
    src = os.path.join(artifacts.handoff_dir(), "sid-2.manifest.json")
    dst = os.path.join(artifacts.handoff_dir(), "sid-3.manifest.json")
    os.rename(src, dst)
    with pytest.raises(migrate.MigrateError):
        migrate.load_manifest("sid-3")


# ---------------------------------------------------------------------------
# the redirect rung
# ---------------------------------------------------------------------------


def test_classify_redirect_rung():
    assert retry.classify(
        ReplicaRefusal("h:1", {"error": "CircuitOpenError",
                               "classification": "breaker"})) == "redirect"
    assert retry.classify(
        ReplicaUnavailable("h:1", "EOFError")) == "redirect"
    # redirect wins over shed: the replica's breaker said no, but
    # another replica can serve the identical request
    wrapped = ReplicaRefusal("h:1", {"error": "QueueFullError",
                                     "classification": "queue_full"})
    assert retry.classify(wrapped) == "redirect"
    # in-process sheds stay fatal (never re-attempt a shed in place)...
    assert retry.classify(
        overload.CircuitOpenError("t", "open")) == "fatal"
    # ...and a fully exhausted fleet has nowhere left to redirect to
    assert retry.classify(NoHealthyReplica("all dead")) == "fatal"


# ---------------------------------------------------------------------------
# fleet.poll — one load/classify pass for collector AND router
# ---------------------------------------------------------------------------


def test_poll_is_health_plus_rollup(tmp_path, monkeypatch):
    monkeypatch.setenv("RAMBA_FLEET_ENDPOINT", "127.0.0.1:4242")
    d = str(tmp_path / "spool")
    fleet.publish(d)
    # pin the classification clock: age_s is rounded wall-clock age, so
    # two free-running reads milliseconds apart would differ
    now = time.time()
    polled = fleet.poll(d, now=now)
    assert polled["dir"] == d
    assert polled["health"]["counts"]["healthy"] == 1
    ((rid, row),) = polled["health"]["replicas"].items()
    assert row["state"] == "healthy"
    # the router's discovery key rides the signals block
    assert row["signals"]["endpoint"] == "127.0.0.1:4242"
    # one classify pass: poll's health is exactly health()'s verdict
    assert polled["health"] == fleet.health(d, now=now)
    assert "goodput" in polled["rollup"]


# ---------------------------------------------------------------------------
# admission_verdict — the router's read-only probe
# ---------------------------------------------------------------------------


def test_admission_verdict_read_only(monkeypatch):
    v = overload.admission_verdict("acme")
    assert v["accepting"] and v["reasons"] == []
    assert v["breaker"] == "closed"
    monkeypatch.setenv("RAMBA_BREAKER_THRESHOLD", "1")
    overload.record_outcome("acme", False)  # trips at threshold 1
    v = overload.admission_verdict("acme")
    assert not v["accepting"]
    assert "breaker_open" in v["reasons"]
    assert v["open_breakers"] == ["acme"]
    # the probe must NOT have advanced the breaker to half-open: a
    # routing decision is not an admission attempt
    assert overload.breaker_for("acme").snapshot()["state"] == "open"


# ---------------------------------------------------------------------------
# router against real in-process replica servers
# ---------------------------------------------------------------------------


SEQ = [("init", {"name": "x", "shape": [64], "fill": 2.0}),
       ("affine", {"name": "x", "a": 1.01, "b": 1.0}),
       ("affine", {"name": "x", "a": 1.01, "b": 2.0})]


@pytest.fixture()
def two_servers(monkeypatch):
    from ramba_tpu.fleet.replica import ReplicaServer

    monkeypatch.setenv("RAMBA_BREAKER_THRESHOLD", "1")
    servers, threads = [], []
    for _ in range(2):
        s = ReplicaServer()
        t = threading.Thread(target=s.serve_forever, daemon=True)
        t.start()
        servers.append(s)
        threads.append(t)
    yield servers
    for s in servers:
        s.stop()
    for t in threads:
        t.join(timeout=10)


def _run_session(router, tenant):
    sid = router.open_session(tenant=tenant)
    for w, p in SEQ:
        router.step(sid, w, p)
    digest = router.step(sid, "digest")["result"]
    router.close_session(sid)
    return digest


def test_router_failover_heals_by_replay(two_servers):
    a, b = two_servers
    router = Router(endpoints=[a.endpoint, b.endpoint])
    reference = _run_session(router, "acme")  # no-fault answer

    redirects0 = registry.get("router.redirects")
    heals0 = registry.get("router.heals")
    sid = router.open_session(tenant="acme")
    for w, p in SEQ[:2]:
        router.step(sid, w, p)
    victim_ep = router.stats()["sessions"][sid]["endpoint"]
    victim = a if a.endpoint == victim_ep else b
    victim.stop()  # in-process SIGKILL stand-in: transport goes dark
    router.step(sid, *SEQ[2])  # redirect -> heal by replay -> serve
    digest = router.step(sid, "digest")["result"]
    assert digest == reference  # deterministic replay: byte-identical
    survivor_ep = b.endpoint if victim is a else a.endpoint
    assert router.stats()["sessions"][sid]["endpoint"] == survivor_ep
    assert registry.get("router.redirects") > redirects0
    assert registry.get("router.heals") > heals0
    # the transport failure fed the fleet breaker for the dead replica
    assert router.stats()["replicas"][victim_ep]["breaker"]["trips"] >= 1
    router.close_session(sid)


def test_router_refusal_redirects_without_feeding_breaker(
        two_servers, monkeypatch):
    a, b = two_servers
    router = Router(endpoints=[a.endpoint, b.endpoint])
    sid = router.open_session(tenant="globex")
    router.step(sid, *SEQ[0])
    first_ep = router.stats()["sessions"][sid]["endpoint"]

    real = overload.admit_submit
    refusals = {"n": 0}

    def refuse_once(*, tenant=None, priority=False, **kw):
        if refusals["n"] == 0:
            refusals["n"] += 1
            raise overload.ShedError("test-refusal", tenant=tenant)
        return real(tenant=tenant, priority=priority, **kw)

    monkeypatch.setattr(overload, "admit_submit", refuse_once)
    reply = router.step(sid, *SEQ[1])  # refused on A -> healed elsewhere
    assert reply["ok"]
    assert refusals["n"] == 1
    moved_ep = router.stats()["sessions"][sid]["endpoint"]
    assert moved_ep != first_ep
    # sheds never feed back: the refusing replica's FLEET breaker stayed
    # closed with zero trips even at threshold 1
    snap = router.stats()["replicas"][first_ep]["breaker"]
    assert snap == {"state": "closed", "trips": 0, "recent_failures": 0}
    router.close_session(sid)


def test_router_no_healthy_replica_is_terminal(two_servers):
    a, b = two_servers
    router = Router(endpoints=[a.endpoint, b.endpoint])
    sid = router.open_session(tenant="acme")
    router.step(sid, *SEQ[0])
    a.stop()
    b.stop()
    with pytest.raises(NoHealthyReplica):
        router.step(sid, *SEQ[1])
