"""Live telemetry plane: exporter, SLO histograms, tracing, flight recorder.

Covers ``ramba_tpu/observe/telemetry.py`` + ``observe/slo.py`` and their
integration seams:

* Prometheus text-format correctness — TYPE lines, rank/tenant labels,
  escaped label values, cumulative histogram buckets that are monotone
  non-decreasing and end at the +Inf total,
* fixed-bucket histogram math (quantile interpolation, saturation at the
  last finite bucket) and the slo_breach latch (one event per episode,
  re-armed on recovery),
* causal trace propagation: serve.Session mints trace_id/root_span, the
  flush span chains to it, the ticket carries it, degrade-rung and
  slow-flush events inside the dispatch scope inherit it — including
  coalesced tickets where N traces share one dispatch batch,
* the HTTP exporter end-to-end on an ephemeral port (scrape, 404, and a
  consistent scrape while flushes run),
* atomic textfile export (no partial file visible),
* flight recorder: exactly-once dump per incident under a seeded
  RAMBA_FAULTS stall, dump contents (incident + identity + ring +
  diagnostics with one capture stamp), RAMBA_FLIGHT_MAX oldest-first
  retention GC,
* the ``ramba_process_info`` identity series and multi-rank textfile
  ``.rank<i>`` suffixing,
* monotonic ``mono`` stamps on events, ``snapshot_ring`` consistency,
  and trace_report.py: ``--trace`` chain reconstruction and merge-ranks
  tolerance of an anchorless rank file.
"""

import glob
import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

import jax as _jax
import ramba_tpu as rt
from ramba_tpu import diagnostics, serve
from ramba_tpu.core import fuser
from ramba_tpu.observe import events, registry, slo, telemetry
from ramba_tpu.resilience import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_MULTIPROC = _jax.process_count() > 1

spmd_skip = pytest.mark.skipif(
    _MULTIPROC,
    reason="threaded serving is single-controller; SPMD uses --telemetry-leg",
)


@pytest.fixture(autouse=True)
def _clean_telemetry(monkeypatch):
    """No leaked exporter threads, faults, breach latches, or flight
    budget between tests."""
    monkeypatch.setenv("RAMBA_RETRY_BASE_S", "0.001")
    faults.configure(None)
    slo.reconfigure(objective_ms=-1)
    yield
    telemetry.reset()
    serve.shutdown()
    faults.reset()
    fuser.sync()
    slo.reset()
    slo.reconfigure(objective_ms=-1)


# -- histogram math ----------------------------------------------------------


def test_histogram_buckets_cumulative_monotone():
    h = slo.Histogram()
    for v in (0.0005, 0.003, 0.003, 0.07, 0.2, 42.0):
        h.observe(v)
    cum = h.cumulative()
    counts = [c for _, c in cum]
    assert counts == sorted(counts), "cumulative counts must be monotone"
    assert cum[-1][0] == float("inf")
    assert cum[-1][1] == h.count == 6
    # the 42 s outlier lands in +Inf only
    assert cum[-2][1] == 5


def test_histogram_quantile_interpolation_and_saturation():
    h = slo.Histogram()
    for _ in range(100):
        h.observe(0.004)  # lands in (0.0025, 0.005]
    q = h.quantile(0.5)
    assert 0.0025 <= q <= 0.005
    h2 = slo.Histogram()
    h2.observe(99.0)  # beyond the last finite bucket
    assert h2.quantile(0.99) == slo.BUCKETS_S[-1]
    assert slo.Histogram().quantile(0.5) is None


def test_observe_span_routes_prepare_and_dispatch():
    slo.reset()
    slo.observe_span({"tenant": "t1", "linearize_s": 0.002, "wall_s": 0.03})
    snap = slo.snapshot()["histograms"]
    assert snap["prepare"]["t1"]["count"] == 1
    assert snap["dispatch"]["t1"]["count"] == 1
    assert snap["e2e"] == {}


def test_slo_breach_latch_fires_once_then_rearms():
    slo.reset()
    slo.reconfigure(objective_ms=10.0, min_samples=5)
    breaches = []
    for _ in range(10):  # p95 ~ 50ms >> 10ms objective
        ev = slo.observe_e2e(0.05, tenant="hot", trace_id="tr1")
        if ev is not None:
            breaches.append(ev)
    assert len(breaches) == 1, "latched: one event per episode"
    ev = breaches[0]
    assert ev["type"] == "slo_breach" and ev["tenant"] == "hot"
    assert ev["trace_id"] == "tr1"
    assert ev["p95_ms"] > ev["objective_ms"]
    assert registry.get("serve.tenant.hot.slo_breach") == 1
    assert "hot" in slo.breached_tenants()
    # recovery: flood with fast samples until p95 drops below 0.8x, then
    # breach again -> second event
    for _ in range(2000):
        slo.observe_e2e(0.0001, tenant="hot")
    assert "hot" not in slo.breached_tenants()
    for _ in range(3000):
        ev = slo.observe_e2e(5.0, tenant="hot")
        if ev is not None:
            break
    assert ev is not None, "re-armed latch fires on the second episode"


# -- exporter text format ----------------------------------------------------


def test_render_counter_and_gauge_typing():
    registry.inc("probe.typing_hits", 3)
    registry.gauge("probe.typing_level", 1234)
    body = telemetry.render()
    assert "# TYPE ramba_probe_typing_hits_total counter" in body
    assert 'ramba_probe_typing_hits_total{rank="0"} 3' in body
    # gauge() names are typed gauge, no _total suffix
    assert "# TYPE ramba_probe_typing_level gauge" in body
    assert 'ramba_probe_typing_level{rank="0"} 1234' in body


def test_render_tenant_counters_get_labels():
    registry.inc("serve.tenant.acme.flushes", 7)
    body = telemetry.render()
    assert 'ramba_serve_tenant_flushes_total{rank="0",tenant="acme"} 7' \
        in body


def test_render_histogram_bucket_monotonicity_and_inf():
    slo.reset()
    for v in (0.0004, 0.002, 0.03, 0.4, 20.0):
        slo.observe("e2e", v, tenant="t")
    body = telemetry.render()
    buckets = []
    for line in body.splitlines():
        if line.startswith("ramba_flush_e2e_seconds_bucket") \
                and 'tenant="t"' in line:
            le = line.split('le="')[1].split('"')[0]
            buckets.append((le, float(line.rsplit(" ", 1)[1])))
    assert buckets, "histogram series must render"
    assert buckets[-1][0] == "+Inf"
    counts = [c for _, c in buckets]
    assert counts == sorted(counts)
    assert counts[-1] == 5
    # _sum/_count close the family
    assert "ramba_flush_e2e_seconds_count" in body
    assert "ramba_flush_e2e_seconds_sum" in body


def test_render_every_sample_has_rank_label():
    registry.inc("fuser.flushes")
    for line in telemetry.render().splitlines():
        if line.startswith("#") or not line.strip():
            continue
        assert 'rank="' in line, f"unlabeled sample: {line}"


def test_render_label_escaping():
    registry.inc('serve.tenant.we"ird.flushes')
    body = telemetry.render()
    assert 'tenant="we\\"ird"' in body


# -- http + textfile exporters ----------------------------------------------


def test_http_exporter_serves_metrics_on_ephemeral_port():
    registry.inc("fuser.flushes", 2)
    port = telemetry.start(port=0)
    assert port and port > 0
    assert telemetry.port() == port
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
    assert "ramba_fuser_flushes_total" in body
    with pytest.raises(urllib.error.HTTPError):
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/nope", timeout=5)
    telemetry.stop()
    assert not telemetry.started()


@spmd_skip
def test_http_scrape_consistent_during_flushes():
    """A scrape taken while flushes are running parses clean: histogram
    families complete, buckets monotone — the atomic-snapshot guarantee
    the exporter exists to provide."""
    port = telemetry.start(port=0)
    stop = threading.Event()
    errs = []

    def hammer():
        try:
            with serve.Session(tenant="soak") as s:
                i = 0
                while not stop.is_set() and i < 50:
                    a = rt.ones((64,)) + float(i)
                    s.flush(wait=True)
                    a.asarray()
                    i += 1
        except Exception as e:  # noqa: BLE001
            errs.append(repr(e))

    t = threading.Thread(target=hammer)
    t.start()
    try:
        for _ in range(5):
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5
            ).read().decode()
            per_series: dict = {}
            for line in body.splitlines():
                if "_bucket{" in line:
                    fam = line.split("{")[0]
                    key = (fam, line.split('tenant="')[1].split('"')[0]
                           if 'tenant="' in line else "")
                    per_series.setdefault(key, []).append(
                        float(line.rsplit(" ", 1)[1]))
            for key, counts in per_series.items():
                assert counts == sorted(counts), (key, counts)
    finally:
        stop.set()
        t.join()
    assert not errs, errs


def test_textfile_export_atomic(tmp_path):
    registry.inc("textfile.probe")
    path = tmp_path / "metrics.prom"
    telemetry.write_textfile(str(path))
    body = path.read_text()
    assert 'ramba_textfile_probe_total{rank="0"} 1' in body
    assert not list(tmp_path.glob("*.tmp")), "no torn temp files left"
    # periodic writer refreshes the file
    registry.inc("textfile.probe", 41)
    telemetry.start(path=str(path), interval_s=0.05)
    want = 'ramba_textfile_probe_total{rank="0"} 42'
    deadline = time.time() + 5
    while time.time() < deadline:
        if want in path.read_text():
            break
        time.sleep(0.02)
    assert want in path.read_text()


def test_process_info_identity_series():
    """The ``*_info`` convention: value 1, identity in the labels — the
    series federated fleet scrapes join/dedup replicas on."""
    body = telemetry.render()
    lines = [ln for ln in body.splitlines()
             if ln.startswith("ramba_process_info{")]
    assert len(lines) == 1, body[:400]
    line = lines[0]
    assert f'pid="{os.getpid()}"' in line
    assert f'schema_version="{diagnostics.SCHEMA_VERSION}"' in line
    assert 'host="' in line and 'start_time="' in line
    assert line.endswith(" 1")


@spmd_skip
def test_textfile_path_multirank_suffix(tmp_path, monkeypatch):
    """Two ranks handed the same textfile path must not clobber each
    other's atomic rewrites: nprocs>1 auto-suffixes ``.rank<i>``."""
    p = str(tmp_path / "m.prom")
    assert telemetry.textfile_path(p) == p  # single process: unchanged
    monkeypatch.setattr(events, "_rank", (1, 2))
    try:
        assert telemetry.textfile_path(p) == f"{p}.rank1"
        telemetry.write_textfile(p)
        assert os.path.exists(f"{p}.rank1") and not os.path.exists(p)
        assert 'rank="1"' in open(f"{p}.rank1").read()
    finally:
        events.invalidate_rank()


# -- trace propagation -------------------------------------------------------


@spmd_skip
def test_session_mints_trace_and_span_chains_to_root():
    with serve.Session(tenant="acme") as s:
        assert s.trace_id and s.root_span
        assert s.stream.trace_id == s.trace_id
        a = rt.ones((32,)) * 2.0
        t = s.flush(wait=True)
        a.asarray()
    assert t.trace_id == s.trace_id
    spans = [e for e in events.ring if e.get("type") == "flush"
             and e.get("trace_id") == s.trace_id]
    assert spans, "flush span carries the session's trace_id"
    span = spans[-1]
    assert span["parent_span"] == s.root_span
    assert span["span_id"] != s.root_span
    sess_evs = [e for e in events.ring if e.get("type") == "serve_session"
                and e.get("trace_id") == s.trace_id]
    assert sess_evs and sess_evs[0]["span_id"] == s.root_span


@spmd_skip
def test_explicit_trace_id_joins_existing_trace():
    with serve.Session(tenant="acme", trace_id="cafe000000000001") as s:
        assert s.trace_id == "cafe000000000001"
        rt.ones((16,)).asarray()


@spmd_skip
def test_child_events_inherit_trace_via_dispatch_scope():
    """Events emitted inside the dispatch (slow_flush here, same
    mechanism as degrade/stall/memory) are auto-stamped with the flush
    span's trace context — no per-site wiring."""
    os.environ["RAMBA_SLOW_FLUSH_FACTOR"] = "2"
    os.environ["RAMBA_SLOW_FLUSH_MIN_SAMPLES"] = "2"
    from ramba_tpu.observe import ledger as _ledger
    _ledger.reconfigure()
    try:
        faults.configure("dispatch:delay:ms=150:after=3")
        with serve.Session(tenant="acme") as s:
            for i in range(5):
                a = rt.ones((32,)) + float(i)
                s.flush(wait=True)
                a.asarray()
        slow = [e for e in events.ring if e.get("type") == "slow_flush"]
        assert slow, "seeded delay must trip the sentinel"
        assert slow[-1].get("trace_id") == s.trace_id
        # parent is the flush span, not the session root
        spans = {e.get("span_id") for e in events.ring
                 if e.get("type") == "flush"}
        assert slow[-1].get("parent_span") in spans
    finally:
        del os.environ["RAMBA_SLOW_FLUSH_FACTOR"]
        del os.environ["RAMBA_SLOW_FLUSH_MIN_SAMPLES"]
        _ledger.reconfigure()


@spmd_skip
def test_coalesced_tickets_keep_distinct_traces():
    """N same-fingerprint flushes coalesce into one dispatch batch; each
    ticket still resolves its own trace_id and the serve_coalesce event
    lists all of them."""
    fuser.flush()
    pipe = serve.CompilePipeline(coalesce=8)
    pipe._ensure_worker = lambda: None  # hold dispatch: force coalescing
    sessions, tickets, arrs = [], [], []
    try:
        for i in range(3):
            s = serve.Session(tenant=f"t{i}", pipeline=pipe)
            tok = fuser.activate_stream(s.stream)
            try:
                arrs.append(rt.arange(64) * 2.0)  # same fingerprint each
                tickets.append(s.flush())
            finally:
                fuser.deactivate_stream(tok)
            sessions.append(s)
        group = pipe.queue.pop_group(
            8, fingerprint_of=lambda t: t.work.fingerprint, timeout=0)
        assert len(group) >= 2, "same-fingerprint tickets must coalesce"
        pipe._dispatch_group(group)
        ids = {t.trace_id for t in group}
        assert len(ids) == len(group), "each ticket keeps its own trace"
        ce = [e for e in events.ring if e.get("type") == "serve_coalesce"]
        assert ce and set(ce[-1]["trace_ids"]) == ids
        for t in group:
            span = t.work.span
            assert span.get("trace_id") == t.trace_id
    finally:
        for s in sessions:
            s.close(drain=False)
        pipe.stop()


@spmd_skip
def test_e2e_slo_observed_per_ticket():
    slo.reset()
    with serve.Session(tenant="lat") as s:
        arrs = []
        for i in range(3):
            arrs.append(rt.ones((16,)) + float(i))
            s.flush(wait=True)
    rep = serve.tenant_report()
    assert rep["lat"]["e2e_samples"] >= 3
    assert rep["lat"]["e2e_p95_ms"] is not None
    assert rep["lat"]["e2e_p50_ms"] <= rep["lat"]["e2e_p99_ms"]


# -- flight recorder ---------------------------------------------------------


@spmd_skip
def test_flight_recorder_exactly_once_per_incident(tmp_path, monkeypatch):
    """A seeded one-shot stall-class fault produces exactly ONE incident
    event and exactly ONE dump — the sentinel fires once and the
    recorder maps incidents 1:1 to files."""
    fd = tmp_path / "flight"
    monkeypatch.setenv("RAMBA_FLIGHT_DIR", str(fd))
    monkeypatch.setenv("RAMBA_SLOW_FLUSH_FACTOR", "2")
    monkeypatch.setenv("RAMBA_SLOW_FLUSH_MIN_SAMPLES", "2")
    from ramba_tpu.observe import ledger as _ledger
    _ledger.reconfigure()
    telemetry.flight_reset()
    try:
        faults.configure("dispatch:delay:ms=200:after=3")
        for i in range(6):
            a = rt.ones((32,)) + float(i)
            a.asarray()
        dumps = sorted(glob.glob(str(fd / "flight_*.json")))
        assert len(dumps) == 1, dumps
        rec = json.loads(open(dumps[0]).read())
        assert rec["incident"]["type"] == "slow_flush"
        assert rec["events"], "ring included"
        assert "captured_at" in rec["diagnostics"]
        assert rec["identity"]["pid"] == os.getpid()
        assert rec["identity"]["schema_version"] == diagnostics.SCHEMA_VERSION
        assert os.path.basename(dumps[0]).startswith(
            f"flight_{rec['incident']['seq']:06d}_")
        assert registry.get("telemetry.flight_dumps") == 1
    finally:
        _ledger.reconfigure()


@spmd_skip
def test_flight_recorder_cap_is_retention_gc(tmp_path, monkeypatch):
    """RAMBA_FLIGHT_MAX is disk retention, not an incident budget: every
    incident dumps, then the OLDEST of this process's files are evicted
    past the cap — a week-long soak keeps the freshest incidents instead
    of going blind after the first N."""
    monkeypatch.setenv("RAMBA_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("RAMBA_FLIGHT_MAX", "2")
    telemetry.flight_reset()
    gc0 = registry.get("telemetry.flight_gc")
    dumps0 = registry.get("telemetry.flight_dumps")
    for i in range(5):
        events.emit({"type": "slo_breach", "tenant": "x", "n": i})
    dumps = sorted(glob.glob(str(tmp_path / "flight_*.json")))
    assert len(dumps) == 2
    assert registry.get("telemetry.flight_dumps") - dumps0 == 5
    assert registry.get("telemetry.flight_gc") - gc0 == 3
    # the two survivors are the NEWEST incidents (oldest-first eviction)
    ns = sorted(json.loads(open(p).read())["incident"]["n"] for p in dumps)
    assert ns == [3, 4]


def test_flight_recorder_off_without_dir(tmp_path):
    assert "RAMBA_FLIGHT_DIR" not in os.environ
    events.emit({"type": "slo_breach", "tenant": "x"})
    assert telemetry.dump_flight({"type": "stall", "seq": 1}) is None


def test_stall_event_is_incident():
    assert telemetry.is_incident({"type": "stall", "site": "dispatch"})
    assert telemetry.is_incident({"type": "flush_error"})
    assert telemetry.is_incident({"type": "memory", "action": "oom_evict"})
    assert not telemetry.is_incident({"type": "memory", "action": "admit"})
    assert not telemetry.is_incident({"type": "flush"})


# -- events: mono stamps, ring snapshot --------------------------------------


def test_events_carry_monotonic_stamp():
    e = events.emit({"type": "bench_tick"})
    assert isinstance(e["mono"], float) and isinstance(e["ts"], float)
    e2 = events.emit({"type": "bench_tick"})
    assert e2["mono"] >= e["mono"]


def test_snapshot_ring_is_a_copy():
    events.emit({"type": "bench_tick"})
    snap = events.snapshot_ring()
    n = len(snap)
    events.emit({"type": "bench_tick"})
    assert len(snap) == n


def test_diagnostics_snapshot_stamped_once():
    snap = diagnostics.snapshot()
    assert isinstance(snap["captured_at"], float)
    assert isinstance(snap["captured_mono"], float)
    json.dumps(snap, default=str)  # serializable whole


# -- trace_report integration ------------------------------------------------


def _run_report(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "trace_report.py"),
         *args],
        capture_output=True, text=True,
    )


def test_trace_report_trace_chain(tmp_path):
    path = tmp_path / "t.jsonl"
    evs = [
        {"type": "serve_session", "trace_id": "T1", "span_id": "R",
         "stream": "session:acme", "tenant": "acme", "ts": 1.0, "seq": 1},
        {"type": "flush", "label": "prog_a", "trace_id": "T1",
         "span_id": "S1", "parent_span": "R", "ts": 1.1, "seq": 2,
         "wall_s": 0.01, "cache": "miss", "queue_s": 0.002},
        {"type": "degrade", "site": "flush", "action": "rung",
         "from": "fused", "to": "split", "trace_id": "T1",
         "parent_span": "S1", "ts": 1.15, "seq": 3},
        {"type": "slo_breach", "tenant": "acme", "p95_ms": 50.0,
         "objective_ms": 10.0, "samples": 20, "trace_id": "T1",
         "parent_span": "R", "ts": 1.2, "seq": 4},
        # unrelated noise that must NOT appear
        {"type": "flush", "label": "prog_zzz", "trace_id": "T2",
         "span_id": "S9", "ts": 1.3, "seq": 5, "wall_s": 0.01},
    ]
    with open(path, "w") as f:
        for e in evs:
            f.write(json.dumps(e) + "\n")
    r = _run_report(str(path), "--trace", "T1")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "trace T1: 4 events" in r.stdout
    assert "session" in r.stdout and "tenant=acme" in r.stdout
    assert "flush #0" in r.stdout and "prog_a" in r.stdout
    assert "fused->split" in r.stdout
    assert "SLO-BREACH" in r.stdout
    assert "prog_zzz" not in r.stdout
    # unknown id: nonzero exit
    assert _run_report(str(path), "--trace", "NOPE").returncode == 1


def test_merge_ranks_tolerates_anchorless_rank(tmp_path):
    """A rank file with no health anchor (crashed pre-init) must get
    skew 0 and a visible warning — NOT be aligned off its first event."""
    base = tmp_path / "t.jsonl"
    r0 = [
        {"type": "health", "source": "distributed_init", "outcome": "ok",
         "ts": 100.0, "seq": 1, "rank": 0},
        {"type": "flush", "label": "prog_a", "ts": 100.1, "seq": 2,
         "rank": 0, "wall_s": 0.01, "cache": "miss"},
    ]
    r1 = [  # no health event at all
        {"type": "flush", "label": "prog_a", "ts": 500.0, "seq": 1,
         "rank": 1, "wall_s": 0.01, "cache": "miss", "degraded": "chunked"},
    ]
    for i, evs in enumerate((r0, r1)):
        with open(f"{base}.rank{i}", "w") as f:
            for e in evs:
                f.write(json.dumps(e) + "\n")
    r = _run_report(str(base), "--merge-ranks")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "UNANCHORED" in r.stdout
    assert "r1=+0.0000s" in r.stdout


def test_merge_ranks_uses_mono_for_alignment(tmp_path):
    """When anchor and events carry ``mono``, a wall-clock step between
    bring-up and later events cannot warp the merged ordering."""
    base = tmp_path / "m.jsonl"
    r0 = [
        {"type": "health", "source": "distributed_init", "outcome": "ok",
         "ts": 100.0, "mono": 10.0, "seq": 1, "rank": 0},
        # wall clock stepped +1000s mid-run; mono says +0.5s after anchor
        {"type": "flush", "label": "prog_a", "ts": 1100.5, "mono": 10.5,
         "seq": 2, "rank": 0, "wall_s": 0.01, "degraded": "eager"},
    ]
    with open(f"{base}.rank0", "w") as f:
        for e in r0:
            f.write(json.dumps(e) + "\n")
    r = _run_report(str(base), "--merge-ranks")
    assert r.returncode == 0, r.stdout + r.stderr
    # adjusted offset is mono-derived (+0.5s), not the wall-clock +1000s
    assert "+   0.500s" in r.stdout


def test_heartbeat_gap_math_uses_mono(tmp_path):
    """An NTP step between beats must not fabricate a gap when mono
    stamps are present."""
    path = tmp_path / "hb.jsonl"
    evs = [
        {"type": "heartbeat", "n": 1, "interval_s": 1.0,
         "ts": 100.0, "mono": 50.0, "seq": 1},
        # wall clock jumped 500 s; mono shows a healthy 1 s beat
        {"type": "heartbeat", "n": 2, "interval_s": 1.0,
         "ts": 600.0, "mono": 51.0, "seq": 2},
    ]
    with open(path, "w") as f:
        for e in evs:
            f.write(json.dumps(e) + "\n")
    r = _run_report(str(path))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "GAP" not in r.stdout
    assert "no gaps over 2x interval" in r.stdout


# -- registry atomicity ------------------------------------------------------


def test_gauge_names_tracked_and_reset():
    registry.gauge("memory.live_bytes", 5)
    assert "memory.live_bytes" in registry.gauge_names()
    registry.inc("fuser.flushes")
    assert "fuser.flushes" not in registry.gauge_names()
    registry.reset_counters()
    assert "memory.live_bytes" not in registry.gauge_names()
