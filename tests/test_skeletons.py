"""Skeleton + groupby tests.

Reference models: TestStencil and the skeleton examples in docs/index.md
(/root/reference/ramba/tests/test_distributed_array.py,
/root/reference/ramba/tests/test_groupby.py).
"""

import jax
import numpy as np
import pytest

import ramba_tpu as rt
from tests.helpers import default_atol, default_rtol


class TestSmap:
    def test_smap_docs_example(self):
        # docs/index.md smap example (f1 with numpy closure arg + scalar)
        def f1(a, b, c, d):
            return a * d + b - c[5]

        a = rt.ones(100)
        b = rt.zeros(100)
        c = np.arange(20)
        e = rt.smap(f1, a, b, c, 7)
        np.testing.assert_allclose(e.asarray(), np.full(100, 2.0))

    def test_smap_index_docs_example(self):
        def f2(index, a, b):
            return (a + b + index[0]) * index[0]

        a = rt.ones(100)
        b = rt.zeros(100)
        f = rt.smap_index(f2, a, b)
        i = np.arange(100)
        np.testing.assert_allclose(f.asarray(), (1 + i) * i)

    def test_smap_2d_index(self):
        def f(index, a):
            return a + index[0] * 10 + index[1]

        a = rt.zeros((4, 5))
        out = rt.smap_index(f, a).asarray()
        i, j = np.mgrid[0:4, 0:5]
        np.testing.assert_allclose(out, i * 10 + j)

    def test_smap_fuses(self):
        rt.sync()
        before = dict(rt.fuser_stats)
        a = rt.arange(100).astype(float)
        b = rt.smap(lambda x: x * 2 + 1, a) + 5
        rt.sync()
        assert rt.fuser_stats["flushes"] == before["flushes"] + 1
        np.testing.assert_allclose(b.asarray(), np.arange(100.0) * 2 + 6)


class TestSreduce:
    def test_sreduce_docs_example(self):
        a = rt.init_array(100, lambda i: i * 11.0)
        a -= 7
        a = abs(a)
        b = rt.sreduce(lambda x: x / 100, lambda x, y: x + y, 0, a)
        expected = np.abs(np.arange(100) * 11.0 - 7).sum() / 100
        assert float(b) == pytest.approx(expected)

    def test_sreduce_index(self):
        a = rt.ones(50)
        r = rt.sreduce_index(
            lambda idx, x: x * idx[0], lambda x, y: x + y, 0.0, a
        )
        assert float(r) == pytest.approx(sum(range(50)))

    def test_sreduce_reducer_split(self):
        a = rt.ones(64)
        r = rt.sreduce(
            lambda x: x,
            rt.SreduceReducer(lambda x, y: x + y, lambda x, y: x + y),
            0.0,
            a,
        )
        assert float(r) == pytest.approx(64.0)

    def test_sreduce_max(self):
        a = rt.arange(100).astype(float)
        r = rt.sreduce(lambda x: x, lambda x, y: np.maximum(x, y), -np.inf, a)
        assert float(r) == 99.0


class TestStencil:
    def test_literal_steered_offsets_not_cached(self):
        # regression: the probed neighborhood must not be cached across calls
        # whose literal args change which offsets the kernel reads
        @rt.stencil
        def spread(a, offs):
            s = a[0] * 0.0
            for o in offs:
                s = s + a[o]
            return s

        x = np.arange(8.0)
        wide = rt.sstencil(spread, rt.fromarray(x), (-2, 2)).asarray()
        narrow = rt.sstencil(spread, rt.fromarray(x), (-1, 1)).asarray()
        e_wide = np.zeros(8)
        e_wide[2:-2] = x[:-4] + x[4:]
        e_narrow = np.zeros(8)
        e_narrow[1:-1] = x[:-2] + x[2:]
        np.testing.assert_allclose(wide, e_wide)
        np.testing.assert_allclose(narrow, e_narrow)

    def test_star_1d(self):
        @rt.stencil
        def avg3(a):
            return (a[-1] + a[0] + a[1]) / 3.0

        x = rt.arange(10).astype(float)
        out = rt.sstencil(avg3, x).asarray()
        e = np.zeros(10)
        v = np.arange(10.0)
        e[1:-1] = (v[:-2] + v[1:-1] + v[2:]) / 3.0
        np.testing.assert_allclose(out, e)

    def test_star_2d_5point(self):
        @rt.stencil
        def five(a):
            return a[0, 0] + 0.25 * (a[-1, 0] + a[1, 0] + a[0, -1] + a[0, 1])

        x = rt.fromarray(np.arange(64, dtype=float).reshape(8, 8))
        out = rt.sstencil(five, x).asarray()
        v = np.arange(64, dtype=float).reshape(8, 8)
        e = np.zeros((8, 8))
        e[1:-1, 1:-1] = v[1:-1, 1:-1] + 0.25 * (
            v[:-2, 1:-1] + v[2:, 1:-1] + v[1:-1, :-2] + v[1:-1, 2:]
        )
        np.testing.assert_allclose(out, e)

    def test_radius2_asymmetric(self):
        @rt.stencil
        def st(a):
            return a[-2] + a[1]

        x = rt.arange(12).astype(float)
        out = rt.sstencil(st, x).asarray()
        v = np.arange(12.0)
        e = np.zeros(12)
        e[2:-1] = v[0:-3] + v[3:]
        np.testing.assert_allclose(out, e)

    def test_two_array_stencil(self):
        @rt.stencil
        def st(a, b):
            return a[1] - b[-1]

        x = rt.arange(10).astype(float)
        y = rt.ones(10)
        out = rt.sstencil(st, x, y).asarray()
        v = np.arange(10.0)
        e = np.zeros(10)
        e[1:-1] = v[2:] - 1.0
        np.testing.assert_allclose(out, e)

    def test_direct_numpy_call(self):
        # reference: "using a Ramba stencil directly only NumPy arrays"
        @rt.stencil
        def st(a):
            return a[-1] + a[1]

        v = np.arange(8.0)
        out = st(v)
        e = np.zeros(8)
        e[1:-1] = v[:-2] + v[2:]
        np.testing.assert_allclose(out, e)

    def test_dim_mismatch_raises(self):
        @rt.stencil
        def st(a):
            return a[0, 0]

        with pytest.raises(ValueError):
            rt.sstencil(st, rt.arange(10))


class TestScumulative:
    def test_cumsum_equiv(self):
        x = rt.arange(1, 101).astype(float)
        out = rt.scumulative(
            lambda xi, prev: xi + prev,
            lambda carry, block: block + carry,
            x,
        )
        np.testing.assert_allclose(out.asarray(), np.cumsum(np.arange(1, 101.0)))

    def test_running_max(self):
        v = np.array([3.0, 1.0, 4.0, 1.0, 5.0, 9.0, 2.0, 6.0, 5.0, 3.0] * 5)
        x = rt.fromarray(v)
        out = rt.scumulative(
            lambda xi, prev: np.maximum(xi, prev),
            lambda carry, block: np.maximum(block, carry),
            x,
        )
        np.testing.assert_allclose(out.asarray(), np.maximum.accumulate(v))

    def test_associative_detection(self):
        from ramba_tpu.skeletons import _probe_associative

        # cumsum / cummax: associative, carry applied with the same op
        assert _probe_associative(lambda x, c: x + c, lambda c, b: b + c)
        assert _probe_associative(
            lambda x, c: np.maximum(x, c), lambda c, b: np.maximum(b, c)
        )
        # EMA-style update: not associative
        assert not _probe_associative(
            lambda x, c: 0.5 * x + 0.5 * c, lambda c, b: b + 0 * c
        )

    def test_forced_sequential_matches(self):
        v = np.random.RandomState(0).rand(1000)
        fast = rt.scumulative(
            lambda x, c: x + c, lambda c, b: b + c,
            rt.fromarray(v), associative=True,
        ).asarray()
        slow = rt.scumulative(
            lambda x, c: x + c, lambda c, b: b + c,
            rt.fromarray(v), associative=False,
        ).asarray()
        np.testing.assert_allclose(fast, np.cumsum(v), rtol=default_rtol(1e-9), atol=default_atol())
        np.testing.assert_allclose(slow, np.cumsum(v), rtol=default_rtol(1e-9), atol=default_atol())

    def test_nonassociative_ema(self):
        # y_i = 0.5*x_i + 0.5*y_{i-1}: carries must chain sequentially;
        # final_func rebases a block given the previous block's last value
        v = np.random.RandomState(1).rand(64)
        alpha = 0.5
        want = [v[0]]
        for xi in v[1:]:
            want.append(alpha * xi + (1 - alpha) * want[-1])

        # carry application: y_local computed with carry 0 for the first
        # element; rebasing adds c*(1-alpha)^(k+1) per in-block position k,
        # which is not expressible as an elementwise final_func — so apply
        # the EXACT recurrence by running on one shard (small n keeps the
        # array below the distribution threshold => pure local scan).
        got = rt.scumulative(
            lambda x, c: alpha * x + (1 - alpha) * c,
            lambda c, b: b,  # unused on the single-shard path
            rt.fromarray(v),
        ).asarray()
        np.testing.assert_allclose(got, np.array(want), rtol=default_rtol(1e-9), atol=default_atol())

    @pytest.mark.skipif(
        jax.device_count() < 2,
        reason="warning requires a scan axis actually sharded over >1 device",
    )
    def test_nonassociative_sharded_warns_once(self):
        # round-4 verdict #8: documented per-block carry semantics deserve
        # a runtime warning when the scan is ALSO sharded
        import warnings

        from ramba_tpu import skeletons

        clamp = lambda x, c: np.maximum(0.0, x + c)  # noqa: E731
        v = np.random.RandomState(9).rand(4096)
        old = skeletons._warned_nonassoc
        skeletons._warned_nonassoc = False
        try:
            with pytest.warns(RuntimeWarning, match="per-block carry"):
                rt.scumulative(clamp, clamp, rt.fromarray(v),
                               associative=False).asarray()
            with warnings.catch_warnings():
                warnings.simplefilter("error")
                # second call: silent (one-time)
                rt.scumulative(clamp, clamp, rt.fromarray(v),
                               associative=False).asarray()
            skeletons._warned_nonassoc = False
            with warnings.catch_warnings():
                warnings.simplefilter("error")
                # small array stays on one shard: exact path, no warning
                rt.scumulative(clamp, clamp, rt.fromarray(v[:32]),
                               associative=False).asarray()
        finally:
            skeletons._warned_nonassoc = old

    def test_large_distributed_cumsum(self):
        n = 10_000
        v = np.random.RandomState(2).rand(n)
        got = rt.scumulative(
            lambda x, c: x + c, lambda c, b: b + c, rt.fromarray(v)
        ).asarray()
        np.testing.assert_allclose(got, np.cumsum(v), rtol=default_rtol(1e-7), atol=default_atol())

    def test_odd_length_padding(self):
        n = 1003  # not divisible by the 8-shard mesh
        v = np.random.RandomState(3).rand(n)
        got = rt.scumulative(
            lambda x, c: x + c, lambda c, b: b + c, rt.fromarray(v)
        ).asarray()
        np.testing.assert_allclose(got, np.cumsum(v), rtol=default_rtol(1e-8), atol=default_atol())

    def test_2d_both_axes(self):
        # reference signature: scumulative(local, final, arr, axis, ...)
        # (ramba.py:10057) — N-D with an axis argument
        x = np.random.RandomState(4).randn(6, 10)
        for ax in (0, 1, -1):
            got = rt.scumulative(
                lambda v, c: v + c, lambda c, b: b + c, rt.fromarray(x), ax
            ).asarray()
            np.testing.assert_allclose(got, np.cumsum(x, axis=ax), rtol=default_rtol(1e-12), atol=default_atol())

    def test_2d_distributed_both_axes(self):
        x = np.random.RandomState(5).randn(4096, 4)
        got = rt.scumulative(
            lambda v, c: v + c, lambda c, b: b + c, rt.fromarray(x), 0
        ).asarray()
        np.testing.assert_allclose(got, np.cumsum(x, axis=0), rtol=default_rtol(1e-9), atol=default_atol())
        xt = np.ascontiguousarray(x.T)
        got = rt.scumulative(
            lambda v, c: v + c, lambda c, b: b + c, rt.fromarray(xt), 1
        ).asarray()
        np.testing.assert_allclose(got, np.cumsum(xt, axis=1), rtol=default_rtol(1e-9), atol=default_atol())

    def test_dtype_and_out(self):
        xi = np.random.RandomState(6).randint(0, 5, size=20)
        g = rt.scumulative(
            lambda v, c: v + c, lambda c, b: b + c, rt.fromarray(xi), 0,
            np.float64,
        )
        from tests.helpers import map_dtype

        assert g.dtype == map_dtype(np.float64)
        np.testing.assert_allclose(g.asarray(), np.cumsum(xi).astype(float))
        out = rt.zeros(20)
        ret = rt.scumulative(
            lambda v, c: v + c, lambda c, b: b + c,
            rt.fromarray(xi.astype(float)), 0, out=out,
        )
        assert ret is out
        np.testing.assert_allclose(out.asarray(), np.cumsum(xi).astype(float))

    def test_clamp_probe_rejected_and_sequential_exact(self):
        # advisor r3 (medium): max(0, x+c) passed the positive-only probe
        # yet is non-associative on mixed signs; the probe must reject it
        # and the (single-shard) sequential path must match the loop
        from ramba_tpu.skeletons import _probe_associative

        lf = lambda v, c: np.maximum(0.0, v + c)  # noqa: E731
        assert not _probe_associative(lf, lambda c, b: np.maximum(0.0, b + c))

        v = np.random.RandomState(7).randn(64)  # below dist threshold
        want = [v[0]]
        for xi in v[1:]:
            want.append(max(0.0, xi + want[-1]))
        got = rt.scumulative(lf, lambda c, b: b, rt.fromarray(v)).asarray()
        np.testing.assert_allclose(got, np.array(want), rtol=default_rtol(1e-12), atol=default_atol())

    def test_axis_out_of_range(self):
        with pytest.raises(ValueError, match="axis"):
            rt.scumulative(
                lambda v, c: v + c, lambda c, b: b + c, rt.ones(8), 1
            )


class TestSpmd:
    def test_spmd_set_local(self):
        a = rt.zeros(800)
        rt.sync()

        def worker(local):
            blk = local.get_local()
            local.set_local(blk + 1.0)

        rt.spmd(worker, a)
        np.testing.assert_allclose(a.asarray(), np.ones(800))

    def test_spmd_worker_id(self):
        nw = rt.num_workers()
        n = 100 * nw
        a = rt.zeros(n)
        rt.sync()

        def worker(local):
            wid = rt.worker_id()
            local.set_local(local.get_local() + wid.astype(local.dtype))

        rt.spmd(worker, a)
        # n elements over nw workers -> block i filled with worker id i
        expected = np.repeat(np.arange(float(nw)), 100)
        np.testing.assert_allclose(np.sort(a.asarray()), expected)

    def test_spmd_respects_user_sharding(self):
        # a user-installed layout must reach the kernel as-is, not be
        # re-sharded to default_spec (r2 verdict weak #6)
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ramba_tpu.parallel import mesh as _mesh
        from ramba_tpu.core.expr import Const

        mesh = _mesh.get_mesh()
        axes = tuple(mesh.axis_names)
        n_all = int(np.prod([mesh.shape[a] for a in axes]))
        # shard dim 1 over ALL axes; default_spec for a square 2-D array
        # would split both dims instead
        custom = NamedSharding(mesh, P(None, axes))
        v = jax.device_put(np.zeros((16, 8 * n_all)), custom)
        a = rt.fromarray(np.zeros((16, 8 * n_all)))
        a.write_expr(Const(v))
        rt.sync()

        shapes = []

        def worker(local):
            shapes.append(local.shape)
            local.set_local(local.get_local() + 1.0)

        rt.spmd(worker, a)
        assert shapes[0] == (16, 8), shapes  # full rows, 1/n_all of cols
        np.testing.assert_allclose(a.asarray(), np.ones((16, 8 * n_all)))

    def test_spmd_uneven_shards(self):
        # r3 verdict missing #3: 1001 elements on the 8-way mesh must work
        # (pad-and-unpad internally), reference: ramba.py:3477-3491
        a = rt.fromarray(np.zeros(1001))
        rt.sync()

        def worker(lv):
            lv.set_local(lv.get_local() + rt.worker_id().astype(lv.dtype) + 1.0)

        rt.spmd(worker, a)
        nw = rt.num_workers()
        bs = -(-1001 // nw)
        exp = np.repeat(np.arange(nw) + 1.0, bs)[:1001]
        np.testing.assert_array_equal(a.asarray(), exp)

    def test_spmd_replicated_array(self):
        # small (below dist threshold) arrays arrive whole per device
        b = rt.fromarray(np.arange(10.0))
        rt.sync()

        def w(lv):
            assert lv.shape == (10,)
            lv.set_local(lv.get_local() * 2.0)

        rt.spmd(w, b)
        np.testing.assert_array_equal(b.asarray(), np.arange(10.0) * 2)

    def test_spmd_replicated_divergent_write_deterministic(self):
        # review r4: divergent per-device writes to a replicated array must
        # resolve deterministically (worker 0 wins, reference semantics)
        # and warn — never keep an arbitrary device's copy silently
        import warnings as _w

        from ramba_tpu import skeletons

        skeletons._replicated_write_warned = False
        a = rt.fromarray(np.zeros(10))
        rt.sync()
        with _w.catch_warnings(record=True) as rec:
            _w.simplefilter("always")
            rt.spmd(
                lambda lv: lv.set_local(
                    lv.get_local() + rt.worker_id().astype(lv.dtype)
                ),
                a,
            )
        np.testing.assert_array_equal(a.asarray(), np.zeros(10))
        assert any("coordinate-0" in str(w.message) for w in rec)

    def test_spmd_partial_sharding_divergent_write_deterministic(self):
        # review r4 finding 1: an array sharded along a SUBSET of mesh axes
        # is replicated along the rest; divergent writes across those
        # copies must also resolve to the coordinate-0 copy, with the same
        # warning — not silently keep an arbitrary device's copy
        import warnings as _w

        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ramba_tpu import skeletons
        from ramba_tpu.core.expr import Const
        from ramba_tpu.parallel import mesh as _mesh

        mesh = _mesh.get_mesh()
        axes = tuple(mesh.axis_names)
        if len(axes) < 2:
            pytest.skip("needs a multi-axis mesh")
        d0 = mesh.shape[axes[0]]
        rest = int(np.prod([mesh.shape[a] for a in axes[1:]]))
        n = d0 * 16
        v = jax.device_put(
            np.zeros(n), NamedSharding(mesh, P(axes[0]))
        )
        a = rt.fromarray(np.zeros(n))
        a.write_expr(Const(v))
        rt.sync()

        skeletons._replicated_write_warned = False
        with _w.catch_warnings(record=True) as rec:
            _w.simplefilter("always")
            rt.spmd(
                lambda lv: lv.set_local(
                    lv.get_local() + rt.worker_id().astype(lv.dtype)
                ),
                a,
            )
        # copy kept for block i is from mesh coordinate (i, 0, ..., 0),
        # whose worker_id is i * prod(other axis sizes)
        exp = np.repeat(np.arange(d0) * rest, 16).astype(float)
        np.testing.assert_array_equal(a.asarray(), exp)
        assert any("coordinate-0" in str(w.message) for w in rec)

    def test_spmd_uneven_pad_warns_and_valid_mask(self):
        # review r4 finding 2: padding must announce itself (block-coupled
        # computations like min silently skew otherwise), and valid_mask
        # must make bounding them easy
        import warnings as _w

        import jax.numpy as jnp

        from ramba_tpu import skeletons

        skeletons._uneven_pad_warned = False
        c = rt.fromarray(np.full(1001, 5.0))
        rt.sync()

        def w(lv):
            blk = lv.get_local()
            masked_min = jnp.min(jnp.where(lv.valid_mask, blk, jnp.inf))
            lv.set_local(blk - masked_min)

        with _w.catch_warnings(record=True) as rec:
            _w.simplefilter("always")
            rt.spmd(w, c)
        assert any("zero-padded" in str(w_.message) for w_ in rec)
        np.testing.assert_array_equal(c.asarray(), np.zeros(1001))

    def test_spmd_local_valid_bound(self):
        # kernels can bound block-coupled computations by the valid extent
        import jax.numpy as jnp

        c = rt.fromarray(np.ones(1001))
        rt.sync()

        def w(lv):
            n_valid = lv.local_valid[0]
            assert lv.global_shape == (1001,)
            block = lv.get_local()
            idx = jnp.arange(block.shape[0])
            lv.set_local(
                jnp.where(idx < n_valid, block + n_valid.astype(block.dtype),
                          block)
            )

        rt.spmd(w, c)
        nw = rt.num_workers()
        bs = -(-1001 // nw)
        per_block = [bs] * (nw - 1) + [1001 - bs * (nw - 1)]
        counts = np.repeat(per_block, bs)[:1001]
        np.testing.assert_array_equal(c.asarray(), 1.0 + counts)

    def test_spmd_2d_uneven(self):
        d = rt.fromarray(np.zeros((13, 9)))
        rt.sync()

        def w(lv):
            lv.set_local(lv.get_local() + 1.0)

        rt.spmd(w, d)
        np.testing.assert_array_equal(d.asarray(), np.ones((13, 9)))

    def test_spmd_halo_1d(self):
        # LocalView.halo: neighbor edge cells via ppermute (reference
        # LocalNdarray.getborder, ramba.py:1260-1322)
        import jax.numpy as jnp

        n = 800
        v = np.arange(n, dtype=float)
        a = rt.fromarray(v.copy())
        out = rt.zeros(n)
        rt.sync()

        def w(src, dst):
            h = src.halo(1)
            dst.set_local(h[:-2] + h[1:-1] + h[2:])

        rt.spmd(w, a, out)
        exp = np.zeros(n)
        exp[1:-1] = v[:-2] + v[1:-1] + v[2:]
        exp[0] = v[0] + v[1]
        exp[-1] = v[-2] + v[-1]
        np.testing.assert_array_equal(out.asarray(), exp)

    def test_spmd_halo_2d_corners_sharded(self):
        # corners must arrive (sequential per-dim exchange ships the
        # already-extended slab)
        n = 256
        m = np.random.RandomState(3).rand(n, n)
        b = rt.fromarray(m.copy())
        o = rt.zeros((n, n))
        rt.sync()

        def w(src, dst):
            h = src.halo(1)
            s = sum(
                h[1 + di:h.shape[0] - 1 + di, 1 + dj:h.shape[1] - 1 + dj]
                for di in (-1, 0, 1) for dj in (-1, 0, 1)
            )
            dst.set_local(s)

        rt.spmd(w, b, o)
        mp = np.pad(m, 1)
        exp = sum(
            mp[1 + di:n + 1 + di, 1 + dj:n + 1 + dj]
            for di in (-1, 0, 1) for dj in (-1, 0, 1)
        )
        np.testing.assert_allclose(
            o.asarray(), exp, rtol=default_rtol(1e-12))

    def test_spmd_halo_reflects_set_local(self):
        # halo() must read the current get_local() state, not the
        # original block
        n = 800
        a = rt.fromarray(np.zeros(n))
        out = rt.zeros(n)
        rt.sync()

        def w(src, dst):
            src.set_local(src.get_local() + 1.0)
            dst.set_local(src.halo(1)[2:])  # right-neighbor edge included

        rt.spmd(w, a, out)
        exp = np.ones(n)
        exp[-1] = 0.0  # beyond global edge: zero
        np.testing.assert_array_equal(out.asarray(), exp)

    def test_spmd_halo_unsharded_dim_any_depth_pads(self):
        # review r4: the one-hop limit only applies to sharded dims; an
        # unsharded/replicated dim pads zeros at any depth
        small = rt.fromarray(np.arange(6.0))  # below dist threshold
        got = {}
        rt.sync()

        def w(lv):
            got["h"] = lv.halo(10).shape  # depth > extent: fine, zeros
            lv.set_local(lv.get_local())

        rt.spmd(w, small)
        assert got["h"] == (26,)

    def test_spmd_halo_validation(self):
        b = rt.fromarray(np.random.RandomState(4).rand(256, 256))
        rt.sync()
        with pytest.raises(Exception, match="exceeds the local block"):
            rt.spmd(lambda lv: lv.set_local(
                lv.halo(10 ** 6)[:lv.shape[0], :lv.shape[1]]), b)
        from ramba_tpu.skeletons import LocalView

        with pytest.raises(ValueError, match="inside spmd"):
            LocalView(np.ones(4)).halo(1)

    def test_barrier(self):
        rt.barrier()


class TestGroupby:
    """Reference: test_groupby.py — verified against pandas-style manual
    computation."""

    def _data(self):
        np.random.seed(0)
        v = np.random.rand(12, 5)
        labels = np.array([0, 1, 2, 0, 1, 2, 0, 1, 2, 0, 1, 2])
        return v, labels

    @pytest.mark.parametrize("red", ["sum", "mean", "min", "max", "prod",
                                     "var", "std"])
    def test_reductions(self, red):
        v, labels = self._data()
        g = rt.fromarray(v).groupby(0, labels, 3)
        got = getattr(g, red)().asarray()
        expected = np.stack(
            [getattr(np, red)(v[labels == k], axis=0) for k in range(3)]
        )
        np.testing.assert_allclose(got, expected, rtol=default_rtol(1e-10), atol=default_atol())

    def test_count(self):
        v, labels = self._data()
        g = rt.fromarray(v).groupby(0, labels, 3)
        got = g.count().asarray()
        assert (got == 4).all()

    def test_nanmean(self):
        v, labels = self._data()
        v = v.copy()
        v[0, 0] = np.nan
        g = rt.fromarray(v).groupby(0, labels, 3)
        got = g.nanmean().asarray()
        expected = np.stack(
            [np.nanmean(v[labels == k], axis=0) for k in range(3)]
        )
        np.testing.assert_allclose(got, expected, rtol=default_rtol(1e-10), atol=default_atol())

    def test_anomaly_pattern(self):
        # the xarray climatology/anomaly idiom the reference's rewrite
        # rules recognize (ramba.py:4680-4789)
        v, labels = self._data()
        a = rt.fromarray(v)
        g = a.groupby(0, labels, 3)
        clim = g.mean()
        anom = (g - clim).asarray()
        expected = v - np.stack(
            [np.mean(v[labels == k], axis=0) for k in range(3)]
        )[labels]
        np.testing.assert_allclose(anom, expected, rtol=default_rtol(1e-10), atol=default_atol())

    def test_groupby_axis1(self):
        v = np.arange(24, dtype=float).reshape(4, 6)
        labels = np.array([0, 0, 1, 1, 1, 0])
        g = rt.fromarray(v).groupby(1, labels, 2)
        got = g.sum().asarray()
        expected = np.stack(
            [v[:, labels == k].sum(axis=1) for k in range(2)], axis=1
        )
        np.testing.assert_allclose(got, expected)

    def test_bad_labels_raises(self):
        with pytest.raises(ValueError):
            rt.fromarray(np.zeros((4, 4))).groupby(0, np.array([0, 1]))


class TestFileIO:
    def test_npy_roundtrip(self, tmp_path):
        p = str(tmp_path / "x.npy")
        v = np.arange(100.0).reshape(10, 10)
        rt.save(p, rt.fromarray(v))
        back = rt.load(p)
        np.testing.assert_allclose(back.asarray(), v)

    def test_dataset_lazy(self, tmp_path):
        from tests.helpers import driver_write

        p = str(tmp_path / "y.npy")
        driver_write(lambda: np.save(p, np.ones(5)))
        ds = rt.Dataset(p)
        assert ds.shape == (5,)
        np.testing.assert_allclose((ds[2:] + 1).asarray(), np.full(3, 2.0))

    def test_unknown_extension(self):
        with pytest.raises(ValueError):
            rt.load("file.xyz")

    def test_custom_loader(self, tmp_path):
        def my_loader(path, key):
            return rt.fromarray(np.full(3, 7.0))

        rt.register_loader("myext", my_loader)
        np.testing.assert_allclose(
            rt.load(str(tmp_path / "a.myext")).asarray(), np.full(3, 7.0)
        )

    def test_hdf5_chunked_roundtrip(self, tmp_path):
        """Per-shard chunked reads/writes: the largest host chunk must be a
        shard, never the whole array (reference contract: worker-side
        read_direct, /root/reference/ramba/fileio.py:40-120)."""
        h5py = pytest.importorskip("h5py")
        from ramba_tpu import fileio

        from tests.helpers import driver_write, local_shard_count

        n = 256
        v = np.random.RandomState(0).rand(n, n)
        p = str(tmp_path / "c.h5")

        def prep():
            with h5py.File(p, "w") as f:
                f.create_dataset("data", data=v)

        driver_write(prep)  # h5 file locking: exactly one writer

        fileio.io_stats.update(chunks=0, max_chunk_bytes=0,
                               whole_array_reads=0)
        back = rt.load(p)
        assert fileio.io_stats["whole_array_reads"] == 0
        # each process reads one chunk per LOCAL shard
        assert fileio.io_stats["chunks"] >= local_shard_count()
        # bounded host window: each chunk is at most one shard
        assert (fileio.io_stats["max_chunk_bytes"]
                <= v.nbytes // rt.num_workers() + 8)
        np.testing.assert_allclose(back.asarray(), v)
        # sharded on arrival (no full-array host staging then reshard)
        assert len(back._value().addressable_shards) == local_shard_count()

        # chunked save: written shard-by-shard, reread matches
        fileio.io_stats.update(chunks=0, max_chunk_bytes=0)
        p2 = str(tmp_path / "c2.h5")
        rt.save(p2, back)
        assert (fileio.io_stats["max_chunk_bytes"]
                <= v.nbytes // rt.num_workers() + 8)
        with h5py.File(p2, "r") as f:
            np.testing.assert_allclose(f["data"][...], v)

    def test_npy_chunked_roundtrip(self, tmp_path):
        from ramba_tpu import fileio

        n = 128
        v = np.random.RandomState(1).rand(n, n).astype(np.float32)
        p = str(tmp_path / "m.npy")
        rt.save(p, rt.fromarray(v))
        np.testing.assert_allclose(np.load(p), v)
        fileio.io_stats.update(chunks=0, max_chunk_bytes=0,
                               whole_array_reads=0)
        back = rt.load(p)
        assert fileio.io_stats["whole_array_reads"] == 0
        assert (fileio.io_stats["max_chunk_bytes"]
                <= v.nbytes // rt.num_workers() + 8)
        np.testing.assert_allclose(back.asarray(), v)

    def test_small_array_single_read(self, tmp_path):
        from ramba_tpu import fileio

        from tests.helpers import driver_write

        p = str(tmp_path / "s.npy")
        driver_write(lambda: np.save(p, np.ones(5)))
        fileio.io_stats.update(chunks=0, max_chunk_bytes=0,
                               whole_array_reads=0)
        back = rt.load(p)
        assert fileio.io_stats["whole_array_reads"] == 1
        assert fileio.io_stats["chunks"] == 0
        np.testing.assert_allclose(back.asarray(), np.ones(5))


class TestReviewRegressions2:
    """Regressions for the round-1 second code-review pass."""

    def test_sstencil_scalar_extra_arg(self):
        @rt.stencil
        def st(a, c):
            return a[-1] + a[1] + c

        x = rt.arange(10).astype(float)
        out = rt.sstencil(st, x, 5.0).asarray()
        v = np.arange(10.0)
        e = np.zeros(10)
        e[1:-1] = v[:-2] + v[2:] + 5.0
        np.testing.assert_allclose(out, e)

    def test_spmd_replicated_runs_per_device(self):
        # r4: replicated arrays run per-device (reference parity) instead
        # of raising; a no-op kernel leaves the array unchanged
        a = rt.fromarray(np.arange(50.0))
        rt.sync()
        rt.spmd(lambda l: None, a)
        np.testing.assert_array_equal(a.asarray(), np.arange(50.0))

    def test_spmd_indivisible_pads_and_unpads(self):
        # r4: 801 on the 8-way mesh pads internally; writes stick, shape kept
        a = rt.fromarray(np.zeros(801))
        rt.sync()
        rt.spmd(lambda l: l.set_local(l.get_local() + 1.0), a)
        np.testing.assert_array_equal(a.asarray(), np.ones(801))

    def test_groupby_scalar_binop(self):
        v = np.arange(12, dtype=float).reshape(6, 2)
        labels = np.array([0, 1, 0, 1, 0, 1])
        g = rt.fromarray(v).groupby(0, labels, 2)
        np.testing.assert_allclose((g * 2.0).asarray(), v * 2.0)
        np.testing.assert_allclose((1.0 + g).asarray(), 1.0 + v)

    def test_save_load_h5_extension_safe(self, tmp_path):
        with pytest.raises(ValueError):
            rt.save(str(tmp_path / "x.xyz"), rt.ones(3))
        p = str(tmp_path / "x.npy")
        rt.save(p, rt.ones(3))
        import os

        assert os.path.exists(p) and not os.path.exists(p + ".npy")


class TestGroupbyVariations:
    """Reference: TestGroupbyVariations (test_groupby.py) — groupby applied
    to sliced / transposed views must still reduce and broadcast correctly.
    The reference drives these through xarray; the group-label pattern
    (day-of-year climatology + anomaly) is expressed directly here."""

    def _labels(self, n, period=7):
        return np.arange(n) % period

    def test_mean_groupby_slice(self):
        offset, slice_size = 25, 365
        x = np.arange(400.0 * 2).reshape(2, 400)
        labels = self._labels(slice_size, 365)

        r = rt.fromarray(x)[:, offset:offset + slice_size]
        gb = r.groupby(1, labels, num_groups=365)
        final = (gb - gb.mean()).asarray()

        xs = x[:, offset:offset + slice_size]
        means = np.zeros((2, 365))
        for g in range(365):
            sel = xs[:, labels == g]
            means[:, g] = sel.mean(axis=1) if sel.size else 0
        expected = xs - means[:, labels]
        np.testing.assert_allclose(final, expected)

    def test_mean_groupby_transpose(self):
        x = np.arange(35.0).reshape(7, 5)
        labels = self._labels(7, 3)

        r = rt.fromarray(x).T  # shape (5, 7); group along dim 1
        gb = r.groupby(1, labels, num_groups=3)
        final = (gb - gb.mean()).asarray()

        xt = x.T
        means = np.stack(
            [xt[:, labels == g].mean(axis=1) for g in range(3)], axis=1
        )
        expected = xt - means[:, labels]
        np.testing.assert_allclose(final, expected)

    def test_mean_groupby_slice_transpose(self):
        x = np.arange(120.0).reshape(10, 12)
        r = rt.fromarray(x)[2:9, 1:11].T       # shape (10, 7)
        xs = x[2:9, 1:11].T
        labels = self._labels(7, 4)

        gb = r.groupby(1, labels, num_groups=4)
        got_mean = gb.mean().asarray()
        means = np.stack(
            [xs[:, labels == g].mean(axis=1) for g in range(4)], axis=1
        )
        np.testing.assert_allclose(got_mean, means)

        final = (gb - gb.mean()).asarray()
        np.testing.assert_allclose(final, xs - means[:, labels])

    def test_groupby_labels_as_ramba_array(self):
        # Reference passes ramba arrays as value_to_group (test_groupby.py:
        # coord_days = ramba.array([...])).
        x = np.arange(24.0).reshape(4, 6)
        labels = rt.fromarray(np.array([0, 1, 0, 1, 2, 2]))
        gb = rt.fromarray(x).groupby(1, labels, num_groups=3)
        got = gb.sum().asarray()
        expected = np.stack(
            [x[:, [0, 2]].sum(axis=1), x[:, [1, 3]].sum(axis=1),
             x[:, [4, 5]].sum(axis=1)], axis=1
        )
        np.testing.assert_allclose(got, expected)


class TestShardview:
    """Shard-metadata queries (reference: shardview_array.py encoding,
    find_owning_worker common.py:653-680)."""

    def test_shard_slices_and_divisions(self):
        from ramba_tpu.parallel import shardview

        nw = rt.num_workers()
        a = rt.zeros((1024, 8), distribution=(nw, 1))
        sl = shardview.shard_slices(a)
        assert len(sl) == nw
        div = shardview.divisions(a)
        assert div.shape == (nw, 2, 2)
        # blocks tile the row space exactly
        starts = sorted(int(d[0][0]) for d in div)
        assert starts == [i * (1024 // nw) for i in range(nw)]
        assert all(int(d[1][1]) == 8 for d in div)

    def test_find_owning_worker(self):
        from ramba_tpu.parallel import shardview

        a = rt.zeros((1024,), distribution=(rt.num_workers(),))
        w0 = shardview.find_owning_worker(a, 0)
        w_last = shardview.find_owning_worker(a, 1023)
        assert w0 != w_last
        with pytest.raises(IndexError):
            shardview.find_owning_worker(a, 5000)

    def test_default_distribution(self):
        from ramba_tpu.parallel import shardview

        div = shardview.default_distribution((4096,))
        assert div.shape[0] == rt.num_workers()

    def test_spmd_global_start(self):
        # each worker writes its global row offset into its block
        x = rt.zeros((1024,))

        def kern(v):
            import jax.numpy as jnp

            start = v.global_start[0]
            blk = v.get_local()
            v.set_local(jnp.full(blk.shape, start, blk.dtype))

        rt.spmd(kern, x)
        got = x.asarray()
        # every element equals its block's global start
        bs = 1024 // rt.num_workers()
        expect = (np.arange(1024) // bs) * bs
        np.testing.assert_allclose(got, expect)


class TestCheckpoint:
    """Orbax-backed checkpoint/restore (exceeds the reference, which has
    no checkpointing - SURVEY §5)."""

    def test_roundtrip_tree(self, tmp_path):
        pytest.importorskip("orbax.checkpoint")
        w = rt.fromarray(np.random.RandomState(0).rand(64, 32))
        b = rt.arange(200).astype(float) * 2.0
        rt.checkpoint.save(str(tmp_path / "ck"), {"w": w, "b": b})
        back = rt.checkpoint.restore(str(tmp_path / "ck"))
        np.testing.assert_allclose(back["w"].asarray(), w.asarray())
        np.testing.assert_allclose(back["b"].asarray(), b.asarray())
        # sharded on arrival
        from tests.helpers import local_shard_count

        assert (len(back["w"]._value().addressable_shards)
                == local_shard_count())

    def test_restore_into_target_sharding(self, tmp_path):
        pytest.importorskip("orbax.checkpoint")
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ramba_tpu.parallel import mesh as _mesh
        from ramba_tpu.core.expr import Const

        w = rt.fromarray(np.random.RandomState(1).rand(64, 64))
        rt.checkpoint.save(str(tmp_path / "ck2"), {"w": w})
        mesh = _mesh.get_mesh()
        axes = tuple(mesh.axis_names)
        tgt = jax.ShapeDtypeStruct(
            (64, 64), np.float64,
            sharding=NamedSharding(mesh, P(None, axes)),
        )
        back = rt.checkpoint.restore(str(tmp_path / "ck2"), {"w": tgt})
        np.testing.assert_allclose(back["w"].asarray(), w.asarray())
        got_spec = tuple(back["w"]._value().sharding.spec)
        got_spec += (None,) * (2 - len(got_spec))

        # normalize: a 1-axis mesh may report the bare name, not a tuple
        def _names(e):
            return (e,) if isinstance(e, str) else tuple(e or ())

        assert _names(got_spec[0]) == ()          # dim 0 stays unsharded
        assert _names(got_spec[1]) == tuple(axes)


class TestRtdShardedFormat:
    """Sharded directory format (.rtd): per-shard files + manifests,
    reloadable on a different mesh (reference analog: per-worker shard
    I/O, ramba.py:3929-3956)."""

    def test_roundtrip_same_mesh(self, tmp_path):
        from ramba_tpu import fileio

        v = np.random.RandomState(0).rand(96, 64)
        p = str(tmp_path / "a.rtd")
        rt.save(p, rt.fromarray(v))
        fileio.io_stats.update(chunks=0, max_chunk_bytes=0,
                               whole_array_reads=0)
        back = rt.load(p)
        np.testing.assert_allclose(back.asarray(), v)
        # chunked both ways: host window stays at shard size
        from tests.helpers import local_shard_count

        assert (fileio.io_stats["max_chunk_bytes"]
                <= v.nbytes // rt.num_workers() + 8)
        assert len(back._value().addressable_shards) == local_shard_count()

    def test_reload_region_assembly_across_layouts(self, tmp_path):
        """Saved boxes need not align with the reading layout: force a
        mismatch by saving a column-split array and reloading (the
        default solver layout differs)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ramba_tpu.core.expr import Const
        from ramba_tpu.parallel import mesh as _mesh

        mesh = _mesh.get_mesh()
        axes = tuple(mesh.axis_names)
        from ramba_tpu.core.ndarray import put_sharded

        v = np.random.RandomState(1).rand(64, 64)
        a = rt.fromarray(v)
        a.write_expr(Const(put_sharded(
            v, NamedSharding(mesh, P(None, axes))
        )))
        p = str(tmp_path / "b.rtd")
        rt.save(p, a)
        back = rt.load(p)
        np.testing.assert_allclose(back.asarray(), v)

    def test_incomplete_save_detected(self, tmp_path):
        import glob
        import json
        import os

        from tests.helpers import driver_write

        v = np.ones((64, 64))
        p = str(tmp_path / "c.rtd")
        rt.save(p, rt.fromarray(v))

        # drop one shard from the manifest: load must refuse the
        # uncovered region, not return zeros (corruption is done once, by
        # the driver rank, behind a barrier)
        def corrupt_manifest():
            mpath = sorted(glob.glob(p + "/manifest.p*.json"))[0]
            m = json.load(open(mpath))
            m["shards"] = m["shards"][1:]
            json.dump(m, open(mpath, "w"))

        driver_write(corrupt_manifest)
        with pytest.raises(ValueError, match="does not cover"):
            rt.load(p).asarray()
        # a missing shard FILE also refuses (loudly, at read time)
        rt.save(str(tmp_path / "c2.rtd"), rt.fromarray(v))
        driver_write(lambda: os.remove(
            sorted(glob.glob(str(tmp_path / "c2.rtd") + "/shard_*.npy"))[0]
        ))
        with pytest.raises((FileNotFoundError, OSError)):
            rt.load(str(tmp_path / "c2.rtd")).asarray()

    def test_1d_and_odd_shapes(self, tmp_path):
        for shape in ((1000,), (17, 33)):
            v = np.random.RandomState(2).rand(*shape)
            p = str(tmp_path / f"d{len(shape)}.rtd")
            rt.save(p, rt.fromarray(v))
            np.testing.assert_allclose(rt.load(p).asarray(), v)

    def test_resave_replaces_cleanly(self, tmp_path):
        # a second save to the same path must not merge with stale shards
        p = str(tmp_path / "e.rtd")
        rt.save(p, rt.fromarray(np.ones((64, 64))))
        v2 = np.random.RandomState(3).rand(128, 32)
        rt.save(p, rt.fromarray(v2))
        back = rt.load(p)
        assert back.shape == (128, 32)
        np.testing.assert_allclose(back.asarray(), v2)

    def test_stale_foreign_manifest_detected(self, tmp_path):
        # a manifest part from a save with a different process count must
        # refuse at load (the stale-merge hazard of partial overwrites)
        import json

        from tests.helpers import driver_write

        p = str(tmp_path / "f.rtd")
        a = rt.fromarray(np.ones((64, 64)))
        rt.save(p, a)

        def fake_part():
            with open(p + "/manifest.p7.json", "w") as f:
                json.dump({"shape": [64, 64],
                           "dtype": str(np.dtype(a.dtype)),
                           "nproc": 1, "shards": []}, f)

        driver_write(fake_part)
        # single-process: part-count mismatch; cross-process leg: the
        # foreign part's nproc clashes first — both are the refusal
        with pytest.raises(ValueError,
                           match="manifest parts|inconsistent .rtd"):
            rt.load(p).asarray()
