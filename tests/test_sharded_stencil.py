"""Multi-device stencil path: shard_map + explicit ppermute halo exchange.

Reference behavior being matched: per-worker stencils over halo-padded
shards with point-to-point border exchange (/root/reference/ramba/ramba.py:
1260-1322, 3315-3376).  Assertions cover numerics vs the single-device
shifted-slice path AND the communication structure: the lowered HLO must
use collective-permute (nearest-neighbor halos), never a full all-gather
of the operand.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import ramba_tpu as rt
from tests.helpers import default_atol, default_rtol
from ramba_tpu.ops import stencil_pallas, stencil_sharded
from ramba_tpu.parallel import mesh as _mesh


def _star2():
    @rt.stencil
    def star2(a):
        return (
            0.25 * (a[0, 1] + a[0, -1] + a[1, 0] + a[-1, 0])
            + 0.125 * (a[0, 2] + a[0, -2] + a[2, 0] + a[-2, 0])
        )

    return star2


def _star2_numpy(x):
    out = np.zeros_like(x)
    out[2:-2, 2:-2] = (
        0.25 * (x[2:-2, 3:-1] + x[2:-2, 1:-3] + x[3:-1, 2:-2] + x[1:-3, 2:-2])
        + 0.125 * (x[2:-2, 4:] + x[2:-2, :-4] + x[4:, 2:-2] + x[:-4, 2:-2])
    )
    return out


@pytest.fixture
def sharded_only(monkeypatch):
    """Fail loudly if dispatch does NOT take the sharded path."""
    calls = {"n": 0}
    real = stencil_sharded.run

    def spy(*a, **k):
        calls["n"] += 1
        return real(*a, **k)

    monkeypatch.setattr(stencil_sharded, "run", spy)
    return calls


class TestShardedStencil:
    def test_eligible_on_multichip_mesh(self):
        x = jnp.zeros((64, 64), jnp.float32)
        assert stencil_sharded.eligible((-2, -2), (2, 2), [x])
        # 1-D: handled when large enough to distribute
        assert stencil_sharded.eligible((-1,), (1,), [jnp.zeros(4096)])
        assert not stencil_sharded.eligible((-1,), (1,), [jnp.zeros(64)])
        # tiny array below dist threshold: replicated, local compute
        assert not stencil_sharded.eligible(
            (-1, -1), (1, 1), [jnp.zeros((4, 4), jnp.float32)]
        )

    def test_star2_matches_numpy(self, sharded_only):
        x = np.random.RandomState(0).rand(64, 48).astype(np.float32)
        out = rt.sstencil(_star2(), rt.fromarray(x)).asarray()
        np.testing.assert_allclose(out, _star2_numpy(x), rtol=1e-5, atol=1e-6)
        assert sharded_only["n"] >= 1

    def test_odd_shape_padding(self, sharded_only):
        # shapes not divisible by the mesh factors exercise the pad+slice
        x = np.random.RandomState(1).rand(37, 53).astype(np.float32)
        out = rt.sstencil(_star2(), rt.fromarray(x)).asarray()
        np.testing.assert_allclose(out, _star2_numpy(x), rtol=1e-5, atol=1e-6)
        assert sharded_only["n"] >= 1

    def test_asymmetric_offsets(self, sharded_only):
        @rt.stencil
        def shifted(a):
            return a[-3, 0] + a[0, 2]

        x = np.random.RandomState(2).rand(40, 24).astype(np.float32)
        out = rt.sstencil(shifted, rt.fromarray(x)).asarray()
        e = np.zeros_like(x)
        e[3:, :-2] = x[:-3, :-2] + x[3:, 2:]
        np.testing.assert_allclose(out, e, rtol=default_rtol(1e-6))

    def test_corner_offsets(self, sharded_only):
        # diagonal reads require corner halos (col-then-row exchange)
        @rt.stencil
        def diag(a):
            return a[-1, -1] + a[1, 1]

        x = np.random.RandomState(3).rand(32, 32).astype(np.float32)
        out = rt.sstencil(diag, rt.fromarray(x)).asarray()
        e = np.zeros_like(x)
        e[1:-1, 1:-1] = x[:-2, :-2] + x[2:, 2:]
        np.testing.assert_allclose(out, e, rtol=default_rtol(1e-6))

    def test_two_input_arrays(self, sharded_only):
        @rt.stencil
        def mix(a, b):
            return a[0, 0] + 0.5 * (b[-1, 0] + b[1, 0])

        x = np.random.RandomState(4).rand(24, 40).astype(np.float32)
        y = np.random.RandomState(5).rand(24, 40).astype(np.float32)
        out = rt.sstencil(mix, rt.fromarray(x), rt.fromarray(y)).asarray()
        e = np.zeros_like(x)
        e[1:-1, :] = x[1:-1, :] + 0.5 * (y[:-2, :] + y[2:, :])
        np.testing.assert_allclose(out, e, rtol=default_rtol(1e-6))

    def test_literal_arg(self, sharded_only):
        @rt.stencil
        def scaled(a, w):
            return w * (a[0, -1] + a[0, 1])

        x = np.random.RandomState(6).rand(16, 32).astype(np.float32)
        out = rt.sstencil(scaled, rt.fromarray(x), 0.5).asarray()
        e = np.zeros_like(x)
        e[:, 1:-1] = 0.5 * (x[:, :-2] + x[:, 2:])
        np.testing.assert_allclose(out, e, rtol=default_rtol(1e-6))

    def test_hlo_uses_ppermute_not_allgather(self):
        """The halo exchange must be nearest-neighbor collective-permutes;
        an all-gather of the full operand would defeat the design."""
        mesh = _mesh.get_mesh()
        H = W = 64

        def step(x):
            return stencil_sharded.run(
                _star2().func, (-2, -2), (2, 2), (("arr", 0),), [x], 8
            )

        x = jnp.zeros((H, W), jnp.float32)
        hlo = jax.jit(step).lower(x).compile().as_text()
        assert "collective-permute" in hlo
        # no all-gather reconstructing the full (H, W) operand
        import re

        for m in re.finditer(r"all-gather[^\n]*f32\[(\d+),(\d+)\]", hlo):
            assert (int(m.group(1)), int(m.group(2))) != (H, W), m.group(0)

    def test_overlap_on_off_equivalent(self, monkeypatch):
        """The overlapped schedule (interior from local data concurrent
        with halo ppermutes, border strips after) must tile the block
        exactly — same numerics as the single full-block evaluation."""
        from ramba_tpu.core import fuser

        x = np.random.RandomState(8).rand(64, 48).astype(np.float32)
        outs = {}
        for flag in (True, False):
            monkeypatch.setattr(stencil_sharded, "_OVERLAP", flag)
            # fresh kernel objects per iteration already force a retrace
            # (the kernel function is part of the program key); clear the
            # cache anyway so the flag is provably consulted
            fuser._compile_cache.clear()
            outs[flag] = rt.sstencil(_star2(), rt.fromarray(x)).asarray()
        np.testing.assert_allclose(outs[True], outs[False], rtol=1e-6)
        np.testing.assert_allclose(outs[True], _star2_numpy(x), rtol=1e-5,
                                   atol=1e-6)

    def test_overlap_used(self, monkeypatch):
        calls = {"n": 0}
        real = stencil_sharded._overlapped_val

        def spy(*a, **k):
            calls["n"] += 1
            return real(*a, **k)

        monkeypatch.setattr(stencil_sharded, "_overlapped_val", spy)
        x = np.random.RandomState(9).rand(64, 64).astype(np.float32)
        rt.sstencil(_star2(), rt.fromarray(x)).asarray()
        assert calls["n"] >= 1

    def test_composed_with_pallas_interpret(self, monkeypatch):
        """shard_map + ppermute halos feeding the Pallas kernel (interpret
        mode on CPU; on TPU the same composition runs the Mosaic kernel)."""
        monkeypatch.setattr(stencil_pallas, "_INTERPRET", True)
        monkeypatch.setattr(stencil_pallas, "_ENABLED", True)
        x = np.random.RandomState(7).rand(48, 64).astype(np.float32)
        out = rt.sstencil(_star2(), rt.fromarray(x)).asarray()
        np.testing.assert_allclose(out, _star2_numpy(x), rtol=1e-5, atol=1e-6)


class TestShardedStencilND:
    """Explicit ppermute halos generalize to 1-D and 3-D stencils."""

    def test_1d_stencil(self):
        @rt.stencil
        def avg3(a):
            return (a[-1] + a[0] + a[1]) / 3.0

        v = np.random.RandomState(10).rand(4096)
        got = rt.sstencil(avg3, rt.fromarray(v)).asarray()
        e = np.zeros_like(v)
        e[1:-1] = (v[:-2] + v[1:-1] + v[2:]) / 3.0
        np.testing.assert_allclose(got, e, rtol=default_rtol(1e-9))

    def test_1d_dispatches_sharded(self, monkeypatch):
        calls = {"n": 0}
        real = stencil_sharded.run

        def spy(*a, **k):
            calls["n"] += 1
            return real(*a, **k)

        monkeypatch.setattr(stencil_sharded, "run", spy)

        @rt.stencil
        def diff(a):
            return a[1] - a[-1]

        v = np.random.RandomState(11).rand(2048)
        got = rt.sstencil(diff, rt.fromarray(v)).asarray()
        assert calls["n"] >= 1
        e = np.zeros_like(v)
        e[1:-1] = v[2:] - v[:-2]
        np.testing.assert_allclose(got, e, rtol=default_rtol(1e-9))

    def test_3d_stencil(self):
        @rt.stencil
        def seven(a):
            return a[0, 0, 0] + (
                a[-1, 0, 0] + a[1, 0, 0] + a[0, -1, 0]
                + a[0, 1, 0] + a[0, 0, -1] + a[0, 0, 1]
            ) / 6.0

        v = np.random.RandomState(12).rand(16, 24, 12)
        got = rt.sstencil(seven, rt.fromarray(v)).asarray()
        e = np.zeros_like(v)
        c = v[1:-1, 1:-1, 1:-1]
        e[1:-1, 1:-1, 1:-1] = c + (
            v[:-2, 1:-1, 1:-1] + v[2:, 1:-1, 1:-1]
            + v[1:-1, :-2, 1:-1] + v[1:-1, 2:, 1:-1]
            + v[1:-1, 1:-1, :-2] + v[1:-1, 1:-1, 2:]
        ) / 6.0
        np.testing.assert_allclose(got, e, rtol=default_rtol(1e-9))

    def test_3d_odd_shapes(self):
        @rt.stencil
        def st(a):
            return a[-1, 0, 1] + a[1, -1, 0]

        v = np.random.RandomState(13).rand(9, 13, 7)
        got = rt.sstencil(st, rt.fromarray(v)).asarray()
        # lo=(-1,-1,0), hi=(1,0,1): valid i in [1,n0-1), j in [1,n1),
        # k in [0,n2-1)
        e = np.zeros_like(v)
        e[1:-1, 1:, :-1] = v[:-2, 1:, 1:] + v[2:, :-1, :-1]
        np.testing.assert_allclose(got, e, rtol=default_rtol(1e-9))


class TestStencilIterate:
    """sstencil_iterate: all sweeps in one lax.fori_loop program — the
    TPU-native replacement for the reference's persistent local_border
    buffers (ramba.py:1947-2071; round-3 verdict missing #4)."""

    def test_matches_chained_sstencil_2d(self):
        @rt.stencil
        def five(a):
            return a[0, 0] + 0.25 * (
                a[-1, 0] + a[1, 0] + a[0, -1] + a[0, 1]
            )

        x = np.random.RandomState(20).rand(64, 64)
        y = rt.fromarray(x)
        for _ in range(5):
            y = rt.sstencil(five, y)
        it = rt.sstencil_iterate(five, rt.fromarray(x), 5)
        np.testing.assert_allclose(
            np.asarray(it), np.asarray(y), rtol=default_rtol(1e-12))

    def test_zero_iters_is_identity(self):
        @rt.stencil
        def five(a):
            return a[0, 0] + a[1, 0]

        from tests.helpers import map_dtype

        x = np.random.RandomState(21).rand(16, 16)
        np.testing.assert_array_equal(
            np.asarray(rt.sstencil_iterate(five, rt.fromarray(x), 0)),
            x.astype(map_dtype(x.dtype)))

    def test_negative_iters_raises(self):
        @rt.stencil
        def five(a):
            return a[0, 0]

        with pytest.raises(ValueError, match=">= 0"):
            rt.sstencil_iterate(five, rt.fromarray(np.ones((8, 8))), -1)

    def test_1d_sharded_with_literal_arg(self):
        @rt.stencil
        def avg(a, w):
            return (a[-1] + a[0] + a[1]) * w

        v = np.random.RandomState(22).rand(4096)
        y = rt.fromarray(v)
        for _ in range(3):
            y = rt.sstencil(avg, y, 1 / 3.0)
        it = rt.sstencil_iterate(avg, rt.fromarray(v), 3, 1 / 3.0)
        np.testing.assert_allclose(
            np.asarray(it), np.asarray(y), rtol=default_rtol(1e-12),
            atol=default_atol())

    def test_program_size_constant_in_iters(self):
        # the loop body must be a real lax.fori_loop, not an unrolled
        # chain: the traced program for 300 sweeps is the same size as
        # for 3 (review r4: a compile-count check could not see this)
        import jax
        import jax.numpy as jnp

        from ramba_tpu import skeletons

        @rt.stencil
        def five(a):
            return a[0, 0] + 0.25 * (
                a[-1, 0] + a[1, 0] + a[0, -1] + a[0, 1]
            )

        st, lo, hi, slots, taps, _ = skeletons._stencil_node(
            five, rt.fromarray(np.ones((32, 32))), ())

        def eqns(k):
            jp = jax.make_jaxpr(
                lambda a: skeletons._eval_stencil_iter(
                    (st.func, lo, hi, tuple(slots), taps, k), a
                )
            )(jnp.ones((32, 32)))
            return len(jp.jaxpr.eqns)

        assert eqns(300) == eqns(3)

    def test_iterate_promoting_kernel_matches_chain(self):
        # review r4: int input + float-literal kernel must promote like
        # chained sstencil, not crash fori_loop on a carry dtype mismatch
        @rt.stencil
        def five(a):
            return a[0, 0] + 0.25 * (
                a[-1, 0] + a[1, 0] + a[0, -1] + a[0, 1]
            )

        x = np.arange(64, dtype=np.int32).reshape(8, 8)
        y = rt.fromarray(x)
        for _ in range(2):
            y = rt.sstencil(five, y)
        it = rt.sstencil_iterate(five, rt.fromarray(x), 2)
        assert np.asarray(it).dtype == np.asarray(y).dtype
        np.testing.assert_allclose(
            np.asarray(it), np.asarray(y), rtol=default_rtol(1e-12))
