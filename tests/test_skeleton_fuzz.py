"""Seeded differential fuzz over the skeleton surface.

Extends the op-pipeline fuzzer (test_fuzz.py) to the structured-parallelism
APIs: random traceable kernels drive smap/smap_index, random-offset stencil
kernels drive sstencil/sstencil_iterate, and random reducers drive
sreduce/scumulative — each op descriptor carries BOTH the framework
application and a numpy reference built from the same parameters, so the
comparison can never drift from the generator.  Seeds are fixed so
failures reproduce.
"""

import os

import numpy as np
import pytest

import ramba_tpu as rt
from tests.helpers import default_atol, default_rtol


def _mk_smap(rng):
    c = float(rng.uniform(0.5, 2.0))
    kind = rng.randint(4)
    if kind == 0:
        return (lambda x: x * c + 1.0), (lambda v: v * c + 1.0)
    if kind == 1:
        return (lambda x: np.maximum(x, c)), (lambda v: np.maximum(v, c))
    if kind == 2:
        return (
            lambda x: np.where(x > c, x * 2.0, -x),
            lambda v: np.where(v > c, v * 2.0, -v),
        )
    return (lambda x: np.tanh(x)), (lambda v: np.tanh(v))


def _mk_stencil_1d(rng):
    offs = sorted(set(int(o) for o in rng.randint(-2, 3, size=3)))
    ws = [float(rng.uniform(-1, 1)) for _ in offs]

    def kern(a, _offs=tuple(offs), _ws=tuple(ws)):
        s = a[0] * 0.0
        for o, w in zip(_offs, _ws):
            s = s + a[o] * w
        return s

    lo, hi = -min(min(offs), 0), max(max(offs), 0)

    def ref(v):
        out = np.zeros_like(v)
        n = v.size
        core = slice(lo, n - hi if hi else None)
        acc = np.zeros(n - lo - hi)
        for o, w in zip(offs, ws):
            acc = acc + v[lo + o: n - hi + o] * w
        out[core] = acc
        return out

    return kern, ref


def _mk_cumul(rng):
    kind = rng.randint(2)
    if kind == 0:
        return (
            lambda x, c: x + c,
            lambda c, b: b + c,
            np.cumsum,
            None,
        )
    return (
        lambda x, c: np.maximum(x, c),
        lambda c, b: np.maximum(b, c),
        np.maximum.accumulate,
        None,
    )


def _check(seed):
    rng = np.random.RandomState(seed)
    n = int(rng.randint(64, 4097))
    v = rng.rand(n)
    got = rt.fromarray(v.copy())
    want = v.copy()

    for _ in range(rng.randint(2, 5)):
        # smap gets double weight (cheapest op; keeps pipelines varied)
        c = rng.randint(4)
        if c in (0, 3):
            k, ref = _mk_smap(rng)
            got = rt.smap(k, got)
            want = ref(want)
        elif c == 1:
            kern, ref = _mk_stencil_1d(rng)
            st = rt.stencil(kern)
            iters = int(rng.randint(1, 4))
            if rng.randint(2):
                got = rt.sstencil_iterate(st, got, iters)
            else:
                for _ in range(iters):
                    got = rt.sstencil(st, got)
            for _ in range(iters):
                want = ref(want)
        else:  # c == 2
            local, fin, ref, _ = _mk_cumul(rng)
            got = rt.scumulative(local, fin, got)
            want = ref(want)

    np.testing.assert_allclose(
        np.asarray(got), want,
        rtol=default_rtol(1e-8), atol=default_atol(),
        err_msg=f"seed {seed}",
    )

    # one reduction at the end (sreduce over the final state)
    total = float(
        rt.sreduce(lambda x: x, lambda a, b: a + b, 0.0, rt.fromarray(want))
    )
    assert abs(total - want.sum()) <= max(
        default_atol(), default_rtol(1e-8) * abs(want.sum())
    ), (seed, total, want.sum())


@pytest.mark.parametrize("seed", range(25))
def test_skeleton_program(seed):
    _check(seed)


@pytest.mark.skipif(
    not os.environ.get("RAMBA_TPU_FUZZ_WIDE"),
    reason="set RAMBA_TPU_FUZZ_WIDE=1 for the wide sweep",
)
@pytest.mark.parametrize("block", range(5))
def test_skeleton_program_wide(block):
    for seed in range(25 + block * 35, 25 + (block + 1) * 35):
        _check(seed)
