"""Elastic job lifecycle: watchdog, heartbeat, checkpoint manager, resume.

Covers ``ramba_tpu.resilience.elastic`` plus its integrations:

* the ``hang:ms=<n>`` / ``after=<k>`` RAMBA_FAULTS grammar that seeds
  deterministic stalls,
* the watchdog deadline around flush dispatch: a seeded dispatch hang
  raises a classified ``RankStallError`` within 2x ``RAMBA_WATCHDOG_S``
  and the degradation ladder recovers on the next rung (or propagates,
  when the classification override says fatal),
* heartbeat beacons on the event stream + deterministic miss detection,
* ``CheckpointManager``: step-numbered saves with manifests, retention-K
  GC that never deletes the newest valid checkpoint, strict ``load``,
* ``CheckpointCorruptError`` paths: truncated/absent manifest,
  mesh-shape mismatch without a target, x64-flag mismatch,
* mesh-reshape ``resume`` (manifest-validated, current-mesh targets,
  HBM-governor admission) and ``drain_to_checkpoint`` quiescing serve
  sessions,
* the ``checkpoint.save`` stale-tmp-debris purge regression.
"""

import json
import os
import time

import numpy as np
import pytest

import jax as _jax
import ramba_tpu as rt
from ramba_tpu.observe import events, registry
from ramba_tpu.resilience import elastic, faults, retry

_MULTIPROC = _jax.process_count() > 1


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    """No leaked fault plans, watchdog arming, or beacons between tests;
    near-zero retry backoff so retry-path tests stay fast."""
    monkeypatch.setenv("RAMBA_RETRY_BASE_S", "0.001")
    monkeypatch.delenv("RAMBA_WATCHDOG_S", raising=False)
    faults.configure(None)
    yield
    elastic.stop_heartbeat()
    faults.reset()


def _ck(tmp_path, name):
    return str(tmp_path / name)


# -- hang:ms / after= fault grammar -----------------------------------------


def test_hang_spec_parses():
    sp = faults._parse_one("dispatch:hang:ms=250:after=2")
    assert (sp.mode, sp.kind, sp.delay_ms, sp.after_n) == \
        ("hang", "hang", 250.0, 2)
    sp = faults._parse_one("x:hang:ms=5")
    assert sp.after_n is None


@pytest.mark.parametrize("bad", [
    "x:hang",                    # hang needs ms=
    "x:hang:ms=5:oom",           # hang takes no kind
    "x:hang:ms=5:after=-1",      # negative trigger
    "x:hang:ms=5:after=1:after=2",   # duplicate
    "x:once:after=1",            # after= payload only for delay/hang
])
def test_hang_spec_rejects(bad):
    with pytest.raises(ValueError):
        faults._parse_one(bad)


def test_hang_after_fires_exactly_once():
    faults.configure("s:hang:ms=60:after=1")
    durations = []
    for _ in range(4):
        t0 = time.monotonic()
        faults.check("s")  # never raises
        durations.append(time.monotonic() - t0)
    # checks 1, 3, 4 pass untouched; check 2 sleeps
    assert durations[1] > 0.05
    assert all(d < 0.03 for i, d in enumerate(durations) if i != 1)
    assert faults.stats()["s"]["fired"] == 1


def test_hang_without_after_fires_every_check():
    faults.configure("s:hang:ms=15")
    t0 = time.monotonic()
    faults.check("s")
    faults.check("s")
    assert time.monotonic() - t0 > 0.025
    ev = events.last(2, type="fault")
    assert ev and ev[-1]["kind"] == "hang" and ev[-1]["ms"] == 15.0


# -- watchdog / RankStallError ----------------------------------------------


def test_stall_error_classification_routing():
    for cls in ("retryable", "degrade", "fatal"):
        assert retry.classify(elastic.RankStallError("s", 0.1, cls)) == cls


def test_stall_class_defaults_and_override(monkeypatch):
    assert elastic.stall_class_for("dispatch") == "degrade"
    assert elastic.stall_class_for("barrier") == "fatal"
    assert elastic.stall_class_for("heartbeat") == "retryable"
    assert elastic.stall_class_for("unknown_site") == "degrade"
    monkeypatch.setenv("RAMBA_WATCHDOG_CLASS_DISPATCH", "fatal")
    assert elastic.stall_class_for("dispatch") == "fatal"
    monkeypatch.setenv("RAMBA_WATCHDOG_CLASS_DISPATCH", "bogus")
    assert elastic.stall_class_for("dispatch") == "degrade"


def test_with_deadline_unarmed_is_plain_call():
    assert elastic.watchdog_seconds() is None
    assert elastic.with_deadline("dispatch", lambda: 41 + 1) == 42


def test_with_deadline_raises_within_two_deadlines():
    wd = 0.15
    t0 = time.monotonic()
    with pytest.raises(elastic.RankStallError) as ei:
        elastic.with_deadline("dispatch", lambda: time.sleep(1.0),
                              timeout_s=wd)
    elapsed = time.monotonic() - t0
    assert elapsed < 2 * wd  # the acceptance bound
    assert ei.value.stall_classification == "degrade"
    st = events.last(1, type="stall")[-1]
    assert st["site"] == "dispatch" and st["deadline_s"] == wd


def test_with_deadline_propagates_errors_and_results():
    assert elastic.with_deadline("s", lambda: "ok", timeout_s=5.0) == "ok"
    with pytest.raises(ZeroDivisionError):
        elastic.with_deadline("s", lambda: 1 / 0, timeout_s=5.0)


@pytest.mark.skipif(_MULTIPROC, reason="single-process timing test")
def test_seeded_dispatch_hang_degrades_and_recovers(monkeypatch):
    """The acceptance path: a seeded dispatch hang trips the watchdog
    (classified degrade), the ladder drops a rung, and the flush still
    produces the right answer."""
    wd = 0.25
    monkeypatch.setenv("RAMBA_WATCHDOG_S", str(wd))
    faults.configure("dispatch:hang:ms=800:after=0")
    stalls0 = registry.get("elastic.stalls")
    a = rt.arange(600) * 2.0 + 1.0
    got = float(a.sum())
    assert got == float((np.arange(600) * 2.0 + 1.0).sum())
    st = events.last(3, type="stall")
    assert st and st[-1]["classification"] == "degrade"
    assert st[-1]["waited_s"] <= 2 * wd
    # >= 1: a cold-cache split compile can legitimately blow the same
    # deadline and push the ladder one more rung — still a recovery
    assert registry.get("elastic.stalls") >= stalls0 + 1
    sp = events.last(1, type="flush")[-1]
    assert sp.get("degraded") in ("split", "chunked", "eager", "host")


@pytest.mark.skipif(_MULTIPROC, reason="single-process timing test")
def test_seeded_hang_fatal_class_propagates(monkeypatch):
    monkeypatch.setenv("RAMBA_WATCHDOG_S", "0.3")
    monkeypatch.setenv("RAMBA_WATCHDOG_CLASS_DISPATCH", "fatal")
    faults.configure("dispatch:hang:ms=900:after=0")
    a = rt.arange(100) * 3.0
    with pytest.raises(elastic.RankStallError):
        float(a.sum())
    # the hang was one-shot: the quarantined graph self-heals on re-touch
    assert float(a.sum()) == float((np.arange(100) * 3.0).sum())


@pytest.mark.skipif(_MULTIPROC, reason="single-process timing test")
def test_abandoned_rung_does_not_consume_buffers(monkeypatch):
    """A rung the watchdog gave up on must not wake later and donate the
    leaf buffers the recovery path still owns."""
    monkeypatch.setenv("RAMBA_WATCHDOG_S", "0.3")
    faults.configure("dispatch:hang:ms=900:after=0")
    a = rt.arange(4096) * 1.5  # big enough to be donation-eligible
    first = float(a.sum())
    time.sleep(1.2)  # let the abandoned thread wake and (not) run
    assert float(a.sum()) == first


# -- heartbeat ---------------------------------------------------------------


def test_heartbeat_beacons_on_event_stream():
    elastic.start_heartbeat(0.04)
    time.sleep(0.15)
    elastic.stop_heartbeat()
    beats = events.last(20, type="heartbeat")
    assert len(beats) >= 2
    assert beats[-1]["n"] > beats[-2]["n"]
    assert beats[-1]["interval_s"] == 0.04


@pytest.mark.skipif(_MULTIPROC, reason="single-process timing test")
def test_heartbeat_miss_detection_under_seeded_hang():
    elastic.start_heartbeat(0.04)
    time.sleep(0.06)  # at least one clean beat
    assert elastic.check_heartbeat() is True
    # the NEXT heartbeat check stalls long past 2x the interval
    faults.configure("heartbeat:hang:ms=600:after=0")
    time.sleep(0.3)
    assert elastic.check_heartbeat() is False
    missed = events.last(5, type="lifecycle")
    assert any(ev["phase"] == "heartbeat_missed" for ev in missed)
    assert registry.get("elastic.heartbeat_missed") >= 1


def test_check_heartbeat_without_beacon_is_healthy():
    elastic.stop_heartbeat()
    assert elastic.check_heartbeat() is True
    assert elastic.last_beat_age() is None


# -- CheckpointManager -------------------------------------------------------


def test_manager_save_restore_roundtrip(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    mgr = elastic.CheckpointManager(_ck(tmp_path, "mgr"), keep=3)
    w = rt.arange(64).reshape(8, 8) * 1.5
    b = rt.arange(8) * 0.25
    mgr.register("model", {"w": w, "b": b})
    d = mgr.save(7)
    assert os.path.isdir(d) and mgr.latest() == 7
    man = mgr.manifest(7)
    assert man["process_count"] == _jax.process_count()
    assert man["x64"] == bool(_jax.config.jax_enable_x64)
    assert len(man["leaves"]) == 2
    shapes = sorted(tuple(lf["shape"]) for lf in man["leaves"])
    assert shapes == [(8,), (8, 8)]
    back = mgr.load(7)
    np.testing.assert_allclose(np.asarray(back["model"]["w"]),
                               np.arange(64).reshape(8, 8) * 1.5)


def test_manager_save_requires_something(tmp_path):
    mgr = elastic.CheckpointManager(_ck(tmp_path, "mgr0"))
    with pytest.raises(ValueError, match="nothing to checkpoint"):
        mgr.save(1)


def test_manager_maybe_save_cadence(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    mgr = elastic.CheckpointManager(_ck(tmp_path, "mgrc"), every_steps=3)
    mgr.register("s", {"x": rt.arange(10) * 1.0})
    assert mgr.maybe_save(1) is None
    assert mgr.maybe_save(2) is None
    assert mgr.maybe_save(3) is not None
    assert mgr.valid_steps() == [3]


def test_manager_retention_gc(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    mgr = elastic.CheckpointManager(_ck(tmp_path, "mgrgc"), keep=2)
    mgr.register("s", {"x": rt.arange(12) * 1.0})
    for s in (1, 2, 3, 4):
        mgr.save(s)
    assert mgr.all_steps() == [3, 4]
    assert mgr.latest() == 4


def test_manager_gc_never_deletes_newest_valid(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    mgr = elastic.CheckpointManager(_ck(tmp_path, "mgrnv"), keep=5)
    mgr.register("s", {"x": rt.arange(12) * 1.0})
    mgr.save(1)
    mgr.save(2)
    # tear step 2's manifest: step 1 becomes the newest VALID checkpoint
    with open(mgr.manifest_path(2), "w") as f:
        f.write('{"step": 2, "process_')  # truncated mid-key
    assert mgr.latest() == 1
    # even the tightest retention must keep the newest valid step
    tight = elastic.CheckpointManager(mgr.root, keep=1)
    deleted = tight.gc()
    assert 1 not in deleted
    assert os.path.isdir(mgr.step_dir(1)) and mgr.latest() == 1
    # torn debris NEWER than the newest valid is left for a possible
    # concurrent writer, not reaped
    assert os.path.isdir(mgr.step_dir(2))


# -- CheckpointCorruptError paths -------------------------------------------


def test_manifest_absent_raises(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    from ramba_tpu.checkpoint import CheckpointCorruptError

    mgr = elastic.CheckpointManager(_ck(tmp_path, "mgra"))
    mgr.register("s", {"x": rt.arange(6) * 1.0})
    mgr.save(1)
    os.remove(mgr.manifest_path(1))
    assert mgr.latest() is None
    with pytest.raises(CheckpointCorruptError, match="no manifest"):
        mgr.manifest(1)
    with pytest.raises(CheckpointCorruptError, match="no valid checkpoint"):
        elastic.resume(mgr)


def test_manifest_truncated_raises(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    from ramba_tpu.checkpoint import CheckpointCorruptError

    mgr = elastic.CheckpointManager(_ck(tmp_path, "mgrt"))
    mgr.register("s", {"x": rt.arange(6) * 1.0})
    mgr.save(1)
    with open(mgr.manifest_path(1), "w") as f:
        f.write('{"step": 1, "proc')
    with pytest.raises(CheckpointCorruptError, match="unreadable"):
        mgr.manifest(1)
    with pytest.raises(CheckpointCorruptError):
        mgr.load(1)


def test_mesh_shape_mismatch_without_target_raises(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    from ramba_tpu.checkpoint import CheckpointCorruptError

    mgr = elastic.CheckpointManager(_ck(tmp_path, "mgrm"))
    mgr.register("s", {"x": rt.arange(32) * 1.0})
    mgr.save(1)
    man = mgr.manifest(1)
    man["process_count"] = int(man["process_count"]) + 1
    man["mesh_devices"] = int(man["mesh_devices"]) * 2
    # re-stamp: this models a DIFFERENT environment writing a valid
    # manifest, not at-rest corruption (that path is test_integrity.py)
    man["digest"] = elastic._manifest_digest(man)
    with open(mgr.manifest_path(1), "w") as f:
        json.dump(man, f)
    with pytest.raises(CheckpointCorruptError, match="elastic.resume"):
        mgr.load(1)
    # resume() is exactly the escape hatch: rebuilds the target for the
    # CURRENT mesh and re-shards
    res = elastic.resume(mgr)
    np.testing.assert_allclose(np.asarray(res.state["s"]["x"]),
                               np.arange(32) * 1.0)
    assert res.manifest["mesh_devices"] == man["mesh_devices"]


def test_x64_flag_mismatch_raises(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    from ramba_tpu.checkpoint import CheckpointCorruptError

    mgr = elastic.CheckpointManager(_ck(tmp_path, "mgrx"))
    mgr.register("s", {"x": rt.arange(6) * 1.0})
    mgr.save(1)
    man = mgr.manifest(1)
    man["x64"] = not man["x64"]
    # re-stamp: this models a DIFFERENT environment writing a valid
    # manifest, not at-rest corruption (that path is test_integrity.py)
    man["digest"] = elastic._manifest_digest(man)
    with open(mgr.manifest_path(1), "w") as f:
        json.dump(man, f)
    with pytest.raises(CheckpointCorruptError, match="jax_enable_x64"):
        mgr.load(1)
    with pytest.raises(CheckpointCorruptError, match="jax_enable_x64"):
        elastic.resume(mgr)


def test_manifest_leaf_count_mismatch_raises(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    from ramba_tpu.checkpoint import CheckpointCorruptError

    mgr = elastic.CheckpointManager(_ck(tmp_path, "mgrl"))
    mgr.register("s", {"x": rt.arange(6) * 1.0, "y": rt.arange(4) * 1.0})
    mgr.save(1)
    man = mgr.manifest(1)
    man["leaves"] = man["leaves"][:1]
    # re-stamp: this models a DIFFERENT environment writing a valid
    # manifest, not at-rest corruption (that path is test_integrity.py)
    man["digest"] = elastic._manifest_digest(man)
    with open(mgr.manifest_path(1), "w") as f:
        json.dump(man, f)
    with pytest.raises(CheckpointCorruptError, match="leaves"):
        elastic.resume(mgr)


# -- resume ------------------------------------------------------------------


def test_resume_picks_newest_valid_step(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    mgr = elastic.CheckpointManager(_ck(tmp_path, "mgrn"), keep=5)
    x = {"x": rt.arange(16) * 1.0}
    mgr.register("s", x)
    mgr.save(3)
    mgr.register("s", {"x": rt.arange(16) * 2.0})
    mgr.save(9)
    res = elastic.resume(mgr)
    assert res.step == 9
    np.testing.assert_allclose(np.asarray(res.state["s"]["x"]),
                               np.arange(16) * 2.0)
    lc = [ev["phase"] for ev in events.last(10, type="lifecycle")]
    assert "resume_begin" in lc and "resume_complete" in lc


def test_resume_under_hbm_admission_spills(tmp_path, monkeypatch):
    pytest.importorskip("orbax.checkpoint")
    from ramba_tpu.resilience import memory

    mgr = elastic.CheckpointManager(_ck(tmp_path, "mgrb"))
    big = rt.arange(50_000) * 1.0
    mgr.register("s", {"x": big})
    mgr.save(1)
    float(big.sum())  # ensure materialized + in the ledger
    live = memory.ledger.live_bytes
    assert live > 0
    incoming = 50_000 * np.dtype(np.asarray(big).dtype).itemsize
    # budget so tight the incoming restore must evict resident arrays
    monkeypatch.setenv("RAMBA_HBM_BUDGET", str(live + incoming // 2))
    evictions0 = registry.get("memory.evictions")
    res = elastic.resume(mgr)
    np.testing.assert_allclose(np.asarray(res.state["s"]["x"]),
                               np.arange(50_000) * 1.0)
    assert registry.get("memory.evictions") > evictions0
    admits = [ev for ev in events.last(10, type="lifecycle")
              if ev["phase"] == "restore_admit"]
    assert admits and admits[-1]["freed_bytes"] > 0


# -- drain-to-checkpoint -----------------------------------------------------


@pytest.mark.skipif(_MULTIPROC, reason="serve sessions are single-process")
def test_drain_to_checkpoint_quiesces_sessions(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    from ramba_tpu import serve

    root = _ck(tmp_path, "mgrd")
    with serve.Session(tenant="t0") as s:
        x = rt.arange(256) * 3.0
        y = x + 1.0
        s.flush()  # pending work in flight through the async pipeline
        d = elastic.drain_to_checkpoint(root, 5, {"y": y})
    mgr = elastic.CheckpointManager(root)
    assert mgr.latest() == 5 and os.path.isdir(d)
    res = elastic.resume(mgr)
    np.testing.assert_allclose(np.asarray(res.state["y"]),
                               np.arange(256) * 3.0 + 1.0)
    phases = [ev["phase"] for ev in events.last(50, type="lifecycle")
              if ev.get("step") == 5]
    assert phases[:3] == ["drain_begin", "drain_complete",
                          "checkpoint_saved"]
    serve.shutdown()


@pytest.mark.skipif(_MULTIPROC, reason="single-process timing test")
def test_drain_hang_is_fatal_stall(tmp_path, monkeypatch):
    monkeypatch.setenv("RAMBA_DRAIN_S", "0.1")

    def wedged():
        time.sleep(1.0)

    monkeypatch.setattr(elastic, "quiesce", wedged)
    with pytest.raises(elastic.RankStallError) as ei:
        elastic.drain_to_checkpoint(_ck(tmp_path, "mgrw"), 1, {"x": 1})
    assert ei.value.stall_classification == "fatal"


# -- checkpoint.save stale tmp debris (satellite regression) -----------------


def test_save_purges_stale_tmp_siblings(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    from ramba_tpu import checkpoint

    p = _ck(tmp_path, "debris")
    w = rt.arange(32) * 1.0
    # a crashed writer's debris, in both shapes: the staged tmp itself
    # and Orbax's in-progress temp dirs
    for junk in (p + ".ramba-tmp",
                 p + ".ramba-tmp.orbax-checkpoint-tmp-123",
                 p + ".orbax-checkpoint-tmp-456"):
        os.makedirs(junk, exist_ok=True)
        with open(os.path.join(junk, "partial"), "w") as f:
            f.write("torn")
    purged0 = registry.get("checkpoint.tmp_purged")
    checkpoint.save(p, {"w": w})
    for junk in (p + ".ramba-tmp",
                 p + ".ramba-tmp.orbax-checkpoint-tmp-123",
                 p + ".orbax-checkpoint-tmp-456"):
        assert not os.path.exists(junk), junk
    assert registry.get("checkpoint.tmp_purged") == purged0 + 3
    back = checkpoint.restore(p)
    np.testing.assert_allclose(np.asarray(back["w"]), np.arange(32) * 1.0)


def test_save_does_not_purge_unrelated_siblings(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    from ramba_tpu import checkpoint

    p = _ck(tmp_path, "ckpt")
    other = _ck(tmp_path, "ckpt2.ramba-tmp")  # different base: not debris
    os.makedirs(other)
    checkpoint.save(p, {"w": rt.arange(8) * 1.0})
    assert os.path.isdir(other)


# -- diagnostics surface -----------------------------------------------------


def test_elastic_report_shape():
    from ramba_tpu import diagnostics

    rep = diagnostics.elastic_report()
    for key in ("watchdog_s", "heartbeat_running", "heartbeats", "stalls",
                "checkpoints", "resumes", "drains"):
        assert key in rep
    snap = diagnostics.snapshot()
    assert "elastic" in snap
