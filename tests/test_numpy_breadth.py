"""Differential tests for the round-4 NumPy-breadth batch (ops/extras.py):
the remaining common numpy names a drop-in user reaches for — lazily
lowered, host index helpers, window generators, host-boundary fallbacks,
and numpy's in-place mutators expressed through the write-back machinery.
"""

import numpy as np
import pytest

import ramba_tpu as rt
from tests.helpers import default_atol, default_rtol


def _cmp(got, want, rtol=1e-9):
    got = np.asarray(got) if not isinstance(got, (list, tuple)) else got
    if isinstance(want, (list, tuple)):
        assert len(got) == len(want)
        for g, w in zip(got, want):
            _cmp(g, w, rtol)
        return
    np.testing.assert_allclose(
        np.asarray(got), want, rtol=default_rtol(rtol), atol=default_atol()
    )


class TestLazyLowered:
    def test_rot_flip(self):
        m = np.arange(12.0).reshape(3, 4)
        _cmp(rt.rot90(rt.fromarray(m)), np.rot90(m))
        _cmp(rt.rot90(rt.fromarray(m), 2), np.rot90(m, 2))
        _cmp(rt.fliplr(rt.fromarray(m)), np.fliplr(m))
        _cmp(rt.flipud(rt.fromarray(m)), np.flipud(m))

    def test_atleast_3d(self):
        v = np.arange(5.0)
        assert np.asarray(rt.atleast_3d(rt.fromarray(v))).shape == \
            np.atleast_3d(v).shape

    def test_fix_around(self):
        v = np.array([-1.7, -0.2, 0.2, 1.7])
        _cmp(rt.fix(rt.fromarray(v)), np.fix(v))
        _cmp(rt.around(rt.fromarray(v), 0), np.around(v, 0))

    def test_nancum(self):
        v = np.array([1.0, np.nan, 2.0, np.nan, 3.0])
        _cmp(rt.nancumsum(rt.fromarray(v)), np.nancumsum(v))
        _cmp(rt.nancumprod(rt.fromarray(v)), np.nancumprod(v))

    def test_quantiles(self):
        v = np.random.RandomState(0).rand(101)
        a = rt.fromarray(v)
        _cmp(rt.quantile(a, 0.5), np.quantile(v, 0.5), rtol=1e-6)
        _cmp(rt.percentile(a, [25, 75]), np.percentile(v, [25, 75]),
             rtol=1e-6)
        _cmp(rt.median(a), np.median(v), rtol=1e-6)
        w = v.copy()
        w[::7] = np.nan
        b = rt.fromarray(w)
        _cmp(rt.nanquantile(b, 0.5), np.nanquantile(w, 0.5), rtol=1e-6)
        _cmp(rt.nanpercentile(b, 30), np.nanpercentile(w, 30), rtol=1e-6)
        _cmp(rt.nanmedian(b), np.nanmedian(w), rtol=1e-6)

    def test_quantile_axis(self):
        v = np.random.RandomState(1).rand(8, 16)
        _cmp(rt.quantile(rt.fromarray(v), 0.25, axis=1),
             np.quantile(v, 0.25, axis=1), rtol=1e-6)

    def test_take_along_axis(self):
        v = np.random.RandomState(2).rand(6, 5)
        idx = np.argsort(v, axis=1)
        got = rt.take_along_axis(rt.fromarray(v), rt.fromarray(idx), 1)
        _cmp(got, np.take_along_axis(v, idx, 1))

    def test_diagonal(self):
        m = np.arange(24.0).reshape(4, 6)
        _cmp(rt.diagonal(rt.fromarray(m)), np.diagonal(m))
        _cmp(rt.diagonal(rt.fromarray(m), 1), np.diagonal(m, 1))

    def test_trapezoid(self):
        y = np.random.RandomState(3).rand(64)
        x = np.sort(np.random.RandomState(4).rand(64))
        _cmp(rt.trapezoid(rt.fromarray(y)), np.trapezoid(y), rtol=1e-6)
        _cmp(rt.trapz(rt.fromarray(y), rt.fromarray(x)),
             np.trapezoid(y, x), rtol=1e-6)
        _cmp(rt.trapezoid(rt.fromarray(y), dx=0.5),
             np.trapezoid(y, dx=0.5), rtol=1e-6)

    def test_vander_polyval(self):
        x = np.array([1.0, 2.0, 3.0])
        _cmp(rt.vander(rt.fromarray(x)), np.vander(x))
        _cmp(rt.vander(rt.fromarray(x), 2, increasing=True),
             np.vander(x, 2, increasing=True))
        p = np.array([2.0, 0.0, 1.0])
        _cmp(rt.polyval(rt.fromarray(p), rt.fromarray(x)), np.polyval(p, x))

    def test_frexp(self):
        v = np.array([0.5, 3.0, -6.25, 0.0])
        gm, ge = rt.frexp(rt.fromarray(v))
        wm, we = np.frexp(v)
        _cmp(gm, wm)
        np.testing.assert_array_equal(np.asarray(ge), we)

    def test_broadcast_arrays(self):
        a = np.arange(3.0)
        b = np.arange(4.0)[:, None]
        ga, gb = rt.broadcast_arrays(rt.fromarray(a), rt.fromarray(b))
        wa, wb = np.broadcast_arrays(a, b)
        _cmp(ga, wa)
        _cmp(gb, wb)


class TestSplitsStacks:
    def test_vsplit_hsplit_dsplit(self):
        m = np.arange(48.0).reshape(4, 4, 3)
        for g, w in zip(rt.vsplit(rt.fromarray(m), 2), np.vsplit(m, 2)):
            _cmp(g, w)
        for g, w in zip(rt.hsplit(rt.fromarray(m), 2), np.hsplit(m, 2)):
            _cmp(g, w)
        for g, w in zip(rt.dsplit(rt.fromarray(m), 3), np.dsplit(m, 3)):
            _cmp(g, w)

    def test_row_stack(self):
        a = np.arange(4.0)
        _cmp(rt.row_stack([rt.fromarray(a), rt.fromarray(a * 2)]),
             np.vstack([a, a * 2]))


class TestIndexHelpers:
    def test_tri_diag_indices(self):
        assert all(
            (np.asarray(g) == w).all()
            for g, w in zip(rt.tril_indices(4), np.tril_indices(4))
        )
        assert all(
            (np.asarray(g) == w).all()
            for g, w in zip(rt.diag_indices(3), np.diag_indices(3))
        )

    def test_unravel_ravel(self):
        idx = rt.unravel_index(np.array([5, 11]), (3, 4))
        widx = np.unravel_index(np.array([5, 11]), (3, 4))
        for g, w in zip(idx, widx):
            np.testing.assert_array_equal(g, w)
        back = rt.ravel_multi_index(idx, (3, 4))
        np.testing.assert_array_equal(back, [5, 11])

    def test_ix_(self):
        grids = rt.ix_(np.array([0, 2]), np.array([1, 3]))
        wgrids = np.ix_(np.array([0, 2]), np.array([1, 3]))
        for g, w in zip(grids, wgrids):
            np.testing.assert_array_equal(g, w)


class TestWindows:
    @pytest.mark.parametrize("name", ["bartlett", "blackman", "hamming",
                                      "hanning"])
    def test_windows(self, name):
        _cmp(getattr(rt, name)(16), getattr(np, name)(16), rtol=1e-6)

    def test_kaiser(self):
        _cmp(rt.kaiser(16, 8.6), np.kaiser(16, 8.6), rtol=1e-6)


class TestHostBoundary:
    def test_partition(self):
        v = np.random.RandomState(5).rand(32)
        got = rt.partition(rt.fromarray(v), 10)
        assert (got[:10] <= got[10]).all() and (got[11:] >= got[10]).all()
        gi = rt.argpartition(rt.fromarray(v), 10)
        assert (v[gi[:10]] <= v[gi[10]]).all()

    def test_set_ops_equiv(self):
        a = np.array([1, 2, 3, 4])
        b = np.array([3, 4, 5])
        np.testing.assert_array_equal(
            rt.setxor1d(rt.fromarray(a), rt.fromarray(b)), np.setxor1d(a, b))
        assert rt.array_equiv(rt.fromarray(a), rt.fromarray(a.copy()))
        assert not rt.array_equiv(rt.fromarray(a), rt.fromarray(b))

    def test_trim_resize(self):
        v = np.array([0.0, 0.0, 1.0, 2.0, 0.0])
        np.testing.assert_array_equal(rt.trim_zeros(rt.fromarray(v)),
                                      np.trim_zeros(v))
        _cmp(rt.resize(rt.fromarray(np.arange(4.0)), (3, 3)),
             np.resize(np.arange(4.0), (3, 3)))

    def test_poly_roots_fit(self):
        z = np.array([1.0, 2.0])
        np.testing.assert_allclose(rt.poly(rt.fromarray(z)), np.poly(z))
        r = rt.roots(rt.fromarray(np.array([1.0, -3.0, 2.0])))
        np.testing.assert_allclose(sorted(r.real), [1.0, 2.0], atol=1e-8)
        x = np.arange(8.0)
        y = 3 * x + 1
        c = rt.polyfit(rt.fromarray(x), rt.fromarray(y), 1)
        np.testing.assert_allclose(c, [3.0, 1.0], atol=1e-6)

    def test_real_if_close_piecewise_apply(self):
        c = np.array([1 + 1e-15j, 2 + 1e-16j])
        assert np.asarray(rt.real_if_close(rt.fromarray(c))).dtype.kind == "f"
        x = np.linspace(-2, 2, 9)
        got = rt.piecewise(rt.fromarray(x), [x < 0, x >= 0],
                           [lambda v: -v, lambda v: v * 2])
        np.testing.assert_allclose(got, np.piecewise(
            x, [x < 0, x >= 0], [lambda v: -v, lambda v: v * 2]))
        m = np.arange(12.0).reshape(3, 4)
        np.testing.assert_allclose(
            rt.apply_along_axis(np.sum, 1, rt.fromarray(m)),
            np.apply_along_axis(np.sum, 1, m))
        np.testing.assert_allclose(
            rt.apply_over_axes(np.sum, rt.fromarray(m), [0]),
            np.apply_over_axes(np.sum, m, [0]))


class TestMutators:
    def test_fill_diagonal(self):
        a = rt.fromarray(np.zeros((4, 4)))
        rt.fill_diagonal(a, 7.0)
        w = np.zeros((4, 4))
        np.fill_diagonal(w, 7.0)
        np.testing.assert_array_equal(np.asarray(a), w)

    def test_putmask_place(self):
        v = np.arange(8.0)
        a = rt.fromarray(v.copy())
        rt.putmask(a, np.asarray(v) > 4, np.array([-1.0, -2.0]))
        w = v.copy()
        np.putmask(w, v > 4, np.array([-1.0, -2.0]))
        np.testing.assert_array_equal(np.asarray(a), w)

        b = rt.fromarray(v.copy())
        rt.place(b, v % 2 == 0, np.array([9.0]))
        w2 = v.copy()
        np.place(w2, v % 2 == 0, np.array([9.0]))
        np.testing.assert_array_equal(np.asarray(b), w2)

    def test_put_along_axis(self):
        v = np.random.RandomState(6).rand(4, 5)
        a = rt.fromarray(v.copy())
        idx = np.argmax(v, axis=1, keepdims=True)
        rt.put_along_axis(a, idx, 0.0, 1)
        w = v.copy()
        np.put_along_axis(w, idx, 0.0, 1)
        _cmp(np.asarray(a), w)

    def test_axis_none_paths(self):
        # numpy treats axis=None as flatten-first for these three
        v = np.random.RandomState(12).rand(4, 6)
        p = np.asarray(rt.partition(rt.fromarray(v), 5, axis=None))
        assert (p[:5] <= p[5]).all() and (p[6:] >= p[5]).all()
        gi = np.asarray(rt.argpartition(rt.fromarray(v), 5, axis=None))
        fv = v.ravel()
        assert (fv[gi[:5]] <= fv[gi[5]]).all()
        a = rt.fromarray(v.copy())
        w = v.copy()
        idx = np.array([3, 7])
        rt.put_along_axis(a, idx, 9.0, None)
        np.put_along_axis(w, idx, 9.0, None)
        _cmp(np.asarray(a), w)

    def test_fill_diagonal_wrap_and_array_val(self):
        v = np.zeros((7, 3))
        a = rt.fromarray(v.copy())
        rt.fill_diagonal(a, np.array([1.0, 2.0, 3.0]), wrap=True)
        w = v.copy()
        np.fill_diagonal(w, np.array([1.0, 2.0, 3.0]), wrap=True)
        np.testing.assert_array_equal(np.asarray(a), w)

    def test_mutators_stay_on_device(self):
        # round-4 verdict #5: no _host() round-trip for distributed inputs
        # — the whole-array device->host gather (2 copies of a big array)
        # is the thing being regression-tested, via the comm counter
        from ramba_tpu.utils.timing import comm_stats

        n = 256  # (256, 256) = 65k elements, well over the 20k bar
        v = np.random.RandomState(8).rand(n, n).astype(np.float32)
        w = v.copy()
        a = rt.fromarray(v)
        rt.sync()
        before = comm_stats["device_to_host_bytes"]

        rt.fill_diagonal(a, 7.0)
        np.fill_diagonal(w, 7.0)
        rt.putmask(a, w > 0.5, np.array([-1.0, -2.0], np.float32))
        np.putmask(w, w > 0.5, np.array([-1.0, -2.0], np.float32))
        rt.place(a, w < 0.25, np.array([9.0], np.float32))
        np.place(w, w < 0.25, np.array([9.0], np.float32))
        idx = np.argmin(w, axis=1, keepdims=True)
        rt.put_along_axis(a, idx, 5.0, 1)
        np.put_along_axis(w, idx, 5.0, 1)
        p = rt.partition(a.reshape(-1), 1000)
        gi = rt.argpartition(a.reshape(-1), 1000)
        rt.sync()
        assert comm_stats["device_to_host_bytes"] == before, (
            "mutators transferred distributed data to the host"
        )
        _cmp(np.asarray(a), w)
        pf = np.asarray(p)
        assert (pf[:1000] <= pf[1000]).all() and (pf[1001:] >= pf[1000]).all()
        wf = np.asarray(a).ravel()
        gif = np.asarray(gi)
        assert (wf[gif[:1000]] <= wf[gif[1000]]).all()


class TestReductionWhereInitial:
    """where=/initial= accepted as fused lazy lowerings (round-4 verdict
    #10; the reference's module-level wrappers reject them,
    ramba.py:7996-8031)."""

    def setup_method(self):
        rng = np.random.RandomState(11)
        self.v = rng.randn(6, 7)
        self.m = rng.rand(6, 7) > 0.4

    def _both(self, fn, np_fn, **kw):
        from tests.helpers import default_rtol

        a = rt.fromarray(self.v)
        for axis in (None, 0, 1):
            got = fn(a, axis=axis, **kw)
            want = np_fn(self.v, axis=axis, **kw)
            np.testing.assert_allclose(np.asarray(got), want,
                                       rtol=default_rtol(1e-12))

    def test_sum_where_initial(self):
        self._both(rt.sum, np.sum, where=self.m)
        self._both(rt.sum, np.sum, where=self.m, initial=5.0)
        self._both(rt.sum, np.sum, initial=-2.0)

    def test_prod_where_initial(self):
        self._both(rt.prod, np.prod, where=self.m)
        self._both(rt.prod, np.prod, where=self.m, initial=0.5)

    def test_min_max_where_requires_initial(self):
        self._both(rt.min, np.min, where=self.m, initial=10.0)
        self._both(rt.max, np.max, where=self.m, initial=-10.0)
        self._both(rt.min, np.min, initial=-100.0)
        with pytest.raises(ValueError, match="identity"):
            rt.min(rt.fromarray(self.v), where=self.m)

    def test_min_max_where_integer(self):
        vi = (self.v * 10).astype(np.int64)
        a = rt.fromarray(vi)
        got = rt.min(a, where=self.m, initial=np.int64(99))
        want = np.min(vi, where=self.m, initial=np.int64(99))
        assert int(got) == int(want)

    def test_min_max_where_bool(self):
        b = self.v > 0
        m = self.m
        a = rt.fromarray(b)
        assert bool(rt.min(a, where=m, initial=True)) == bool(
            np.min(b, where=m, initial=True))
        assert bool(rt.max(a, where=m, initial=False)) == bool(
            np.max(b, where=m, initial=False))

    def test_mean_where_dtype(self):
        a = rt.fromarray(self.v)
        got = rt.mean(a, dtype=np.int32, where=self.m)
        want = np.mean(self.v, dtype=np.int32, where=self.m)
        assert np.asarray(got).dtype == want.dtype

    def test_any_all_where(self):
        b = self.v > 0
        a = rt.fromarray(b)
        for axis in (None, 0, 1):
            np.testing.assert_array_equal(
                np.asarray(rt.any(a, axis=axis, where=self.m)),
                np.any(b, axis=axis, where=self.m))
            np.testing.assert_array_equal(
                np.asarray(rt.all(a, axis=axis, where=self.m)),
                np.all(b, axis=axis, where=self.m))

    def test_mean_where(self):
        self._both(rt.mean, np.mean, where=self.m)

    def test_nan_reductions_where_initial(self):
        from tests.helpers import default_rtol

        v = self.v.copy()
        v[0, 0] = v[3, 4] = np.nan
        a = rt.fromarray(v)
        for rt_fn, np_fn, kw in (
            (rt.nansum, np.nansum, {"where": self.m}),
            (rt.nansum, np.nansum, {"where": self.m, "initial": 2.5}),
            (rt.nanprod, np.nanprod, {"where": self.m}),
            (rt.nanmin, np.nanmin, {"where": self.m, "initial": 50.0}),
            (rt.nanmax, np.nanmax, {"where": self.m, "initial": -50.0}),
        ):
            got = rt_fn(a, **kw)
            # the masked-out NaNs sit at where=False positions; numpy
            # still warns/ignores consistently — compare values
            want = np_fn(v, **kw)
            np.testing.assert_allclose(np.asarray(got), want,
                                       rtol=default_rtol(1e-12))
        # all-NaN slice with initial=: numpy returns the initial, not NaN
        nan_all = rt.fromarray(np.full(8, np.nan))
        assert float(rt.nanmin(nan_all, initial=5.0)) == 5.0
        assert float(rt.nanmax(nan_all, initial=-5.0)) == -5.0

    def test_where_stays_lazy_and_fused(self):
        from ramba_tpu.core import fuser

        a = rt.fromarray(self.v)
        rt.sync()
        before = dict(fuser.stats)
        s = rt.sum(a * 2.0 + 1.0, where=self.m)
        float(s)
        assert fuser.stats["flushes"] - before["flushes"] == 1


class TestRound5GapClosure:
    """histogram2d / lexsort / sort_complex / block / copyto / require /
    packbits round out the drop-in surface (round-5 audit)."""

    def test_histogram2d(self):
        rng = np.random.RandomState(13)
        x, y = rng.rand(500), rng.rand(500)
        got_h, got_xe, got_ye = rt.histogram2d(rt.fromarray(x),
                                               rt.fromarray(y), bins=5)
        want_h, want_xe, want_ye = np.histogram2d(x, y, bins=5)
        np.testing.assert_array_equal(got_h, want_h)
        np.testing.assert_allclose(got_xe, want_xe)
        np.testing.assert_allclose(got_ye, want_ye)

    def test_lexsort(self):
        a = np.array([1, 5, 1, 4, 3, 4, 4])
        b = np.array([9, 4, 0, 4, 0, 2, 1])
        got = np.asarray(rt.lexsort((rt.fromarray(b), rt.fromarray(a))))
        np.testing.assert_array_equal(got, np.lexsort((b, a)))
        # single 2-D key array: numpy treats the ROWS as separate keys
        m2 = np.array([[3, 1, 2], [1, 5, 1]])
        np.testing.assert_array_equal(
            np.asarray(rt.lexsort(rt.fromarray(m2))), np.lexsort(m2))

    def test_copyto_weak_python_scalars(self):
        # NEP 50: python int into f32 is fine under casting='safe'; a
        # python float into int32 is rejected like numpy
        a = rt.fromarray(np.zeros(3, np.float32))
        rt.copyto(a, 1, casting="safe")
        assert np.asarray(a).tolist() == [1.0, 1.0, 1.0]
        with pytest.raises(TypeError):
            rt.copyto(rt.fromarray(np.zeros(3, np.int32)), 1.5,
                      casting="same_kind")

    def test_sort_complex(self):
        v = np.array([3 + 2j, 1 - 1j, 1 + 3j, 2.0])
        np.testing.assert_allclose(
            np.asarray(rt.sort_complex(rt.fromarray(v))),
            np.sort_complex(v))

    def test_block(self):
        a = rt.fromarray(np.ones((2, 2)))
        b = rt.fromarray(np.zeros((2, 2)))
        got = np.asarray(rt.block([[a, b], [b, a]]))
        want = np.block([[np.ones((2, 2)), np.zeros((2, 2))],
                         [np.zeros((2, 2)), np.ones((2, 2))]])
        np.testing.assert_array_equal(got, want)

    def test_copyto_where_stays_on_device(self):
        from ramba_tpu.utils.timing import comm_stats

        v = np.random.RandomState(14).rand(256, 256).astype(np.float32)
        w = v.copy()
        a = rt.fromarray(v)
        rt.sync()
        before = comm_stats["device_to_host_bytes"]
        mask = w > 0.5
        rt.copyto(a, np.float32(7.0), where=mask)
        np.copyto(w, np.float32(7.0), where=mask)
        rt.sync()
        assert comm_stats["device_to_host_bytes"] == before
        np.testing.assert_array_equal(np.asarray(a), w)
        with pytest.raises(TypeError, match="Cannot cast"):
            # complex -> float is unsafe in BOTH numerics regimes (the x32
            # leg truncates f64 to f32, which would equal dst's dtype)
            rt.copyto(a, np.array([1 + 2j]), casting="safe")

    def test_grid_complex_step_and_positional_hist(self):
        # numpy's linspace form (complex step) and positional density=
        np.testing.assert_allclose(np.asarray(rt.ogrid[0:1:5j]),
                                   np.ogrid[0:1:5j])
        np.testing.assert_allclose(np.asarray(rt.mgrid[0:1:3j, 0:4]),
                                   np.mgrid[0:1:3j, 0:4])
        with pytest.raises(ValueError, match="zero"):
            rt.ogrid[0:5:0]
        from tests.helpers import default_rtol

        x = np.random.RandomState(0).rand(200)
        y = np.random.RandomState(1).rand(200)
        np.testing.assert_allclose(
            np.histogram2d(rt.fromarray(x), rt.fromarray(y), 5, None,
                           True)[0],
            np.histogram2d(x, y, 5, None, True)[0],
            rtol=default_rtol())
        np.testing.assert_allclose(
            np.histogram(rt.fromarray(x), 5, None, True)[0],
            np.histogram(x, 5, None, True)[0],
            rtol=default_rtol())

    def test_ogrid_r_c(self):
        o = rt.ogrid[0:4, 0:3]
        for a, b in zip(o, np.ogrid[0:4, 0:3]):
            np.testing.assert_array_equal(np.asarray(a), b)
        np.testing.assert_array_equal(np.asarray(rt.ogrid[1:9:2]),
                                      np.ogrid[1:9:2])
        np.testing.assert_array_equal(
            np.asarray(rt.r_[np.array([1, 2]), 3, 4:7]),
            np.r_[np.array([1, 2]), 3, 4:7])
        a = rt.fromarray(np.arange(3.0))
        np.testing.assert_array_equal(
            np.asarray(rt.c_[a, a]),
            np.c_[np.arange(3.0), np.arange(3.0)])

    def test_sort_percentile_kwargs_and_nanarg(self):
        from tests.helpers import default_rtol

        v = np.random.RandomState(15).rand(6, 8)
        a = rt.fromarray(v)
        np.testing.assert_allclose(
            np.asarray(rt.sort(a, axis=1, kind="stable")), np.sort(v, 1))
        np.testing.assert_array_equal(
            np.asarray(rt.argsort(a, axis=0, kind="mergesort")),
            np.argsort(v, 0, kind="stable"))
        with pytest.raises(ValueError, match="structured"):
            rt.sort(a, order="f0")
        for method in ("linear", "lower", "higher", "nearest", "midpoint"):
            np.testing.assert_allclose(
                np.asarray(rt.percentile(a, 30, method=method)),
                np.percentile(v, 30, method=method),
                rtol=default_rtol(1e-12))
        vn = v.copy()
        vn[0, 0] = np.nan
        an = rt.fromarray(vn)
        assert int(rt.nanargmin(an)) == np.nanargmin(vn)
        np.testing.assert_array_equal(
            np.asarray(rt.nanargmax(an, axis=1)), np.nanargmax(vn, axis=1))
        # np.* dispatch
        assert int(np.nanargmin(an)) == np.nanargmin(vn)
        # all-NaN slice raises like numpy (jnp would return -1 silently)
        vn2 = v.copy()
        vn2[2, :] = np.nan
        with pytest.raises(ValueError, match="All-NaN"):
            rt.nanargmin(rt.fromarray(vn2), axis=1)
        with pytest.raises(ValueError, match="All-NaN"):
            rt.nanargmax(rt.fromarray(np.full(4, np.nan)))

    def test_require_and_packbits(self):
        a = rt.fromarray(np.arange(6.0))
        r = rt.require(a, dtype=np.float32)
        assert np.asarray(r).dtype == np.float32
        bits = np.array([1, 0, 1, 1, 0, 0, 1, 0], np.uint8)
        np.testing.assert_array_equal(
            rt.packbits(rt.fromarray(bits)), np.packbits(bits))
        packed = np.packbits(bits)
        np.testing.assert_array_equal(
            rt.unpackbits(rt.fromarray(packed)), np.unpackbits(packed))


class TestUfuncInteropEdges:
    """numpy-left operands and numpy out= targets (round-5 probes)."""

    def test_numpy_inplace_and_out_targets(self):
        v = np.random.RandomState(16).rand(16)
        a = rt.fromarray(v.copy())
        w = v.copy()
        w += a  # numpy-left in-place: host copy-back
        np.testing.assert_allclose(w, v * 2)
        out = np.zeros(16)
        r = np.add(a, a, out=out)
        assert r is out
        np.testing.assert_allclose(out, v * 2)

    def test_matmul_ufunc_numpy_left(self):
        m = np.random.RandomState(17).rand(4, 4)
        am = rt.fromarray(m)
        np.testing.assert_allclose(np.asarray(m @ am), m @ m,
                                   rtol=default_rtol(1e-10))
        np.testing.assert_allclose(np.asarray(am @ m), m @ m,
                                   rtol=default_rtol(1e-10))


class TestNumpyDispatch:
    def test_np_namespace_routes_to_framework(self):
        # np.<fn>(rt_array) must dispatch through __array_function__ for the
        # breadth batch, not fall back to host numpy conversion
        v = np.random.RandomState(7).rand(64)
        a = rt.fromarray(v)
        _cmp(np.median(a), np.median(v), rtol=1e-6)
        _cmp(np.percentile(a, 25), np.percentile(v, 25), rtol=1e-6)
        m = np.arange(12.0).reshape(3, 4)
        rm = rt.fromarray(m)
        got = np.rot90(rm)
        assert isinstance(got, type(rm))  # stayed a framework array
        _cmp(got, np.rot90(m))
        _cmp(np.diagonal(rm), np.diagonal(m))
        _cmp(np.take_along_axis(rm, rt.fromarray(np.argsort(m, axis=1)), 1),
             np.take_along_axis(m, np.argsort(m, axis=1), 1))


class TestReviewRegressions:
    def test_median_keeps_out_support(self):
        # review r4: the breadth batch must not shadow reductions.median
        v = np.random.RandomState(8).rand(32)
        buf = rt.zeros(())
        r = rt.median(rt.fromarray(v), out=buf)
        np.testing.assert_allclose(float(buf), np.median(v),
                                   rtol=default_rtol(1e-9))
        assert r is buf

    def test_split_dim_guards(self):
        with pytest.raises(ValueError, match="2 or more"):
            rt.vsplit(rt.fromarray(np.arange(4.0)), 2)
        with pytest.raises(ValueError, match="3 or more"):
            rt.dsplit(rt.fromarray(np.arange(4.0).reshape(2, 2)), 2)

    def test_take_along_axis_none_flattens(self):
        v = np.random.RandomState(9).rand(3, 4)
        idx = np.array([5, 0, 11])
        _cmp(rt.take_along_axis(rt.fromarray(v), rt.fromarray(idx), None),
             np.take_along_axis(v, idx, None))

    def test_frexp_single_eval_edge_cases(self):
        v = np.array([0.0, np.inf, -np.inf, 0.5, 1024.0, -3.75])
        gm, ge = rt.frexp(rt.fromarray(v))
        wm, we = np.frexp(v)
        _cmp(gm, wm)
        np.testing.assert_array_equal(np.asarray(ge), we)


class TestRandomBreadth:
    """numpy.random surface beyond the reference's module (choice,
    permutation/shuffle, and the common distributions) — statistical
    checks plus structural invariants; all device-count-invariant."""

    def setup_method(self, method):
        rt.random.seed(1234)

    def test_distribution_moments(self):
        e = np.asarray(rt.random.exponential(2.0, size=20000))
        assert abs(e.mean() - 2.0) < 0.1 and (e >= 0).all()
        po = np.asarray(rt.random.poisson(3.0, size=20000))
        assert abs(po.mean() - 3.0) < 0.1
        b = np.asarray(rt.random.beta(2.0, 5.0, size=20000))
        assert abs(b.mean() - 2 / 7) < 0.02 and (0 <= b).all() and (b <= 1).all()
        g = np.asarray(rt.random.gamma(3.0, 2.0, size=20000))
        assert abs(g.mean() - 6.0) < 0.25
        bi = np.asarray(rt.random.binomial(10, 0.3, size=20000))
        assert abs(bi.mean() - 3.0) < 0.1
        sn = np.asarray(rt.random.standard_normal(20000))
        assert abs(sn.mean()) < 0.05 and abs(sn.std() - 1.0) < 0.05

    def test_scale_accepts_arrays(self):
        # ADVICE r4: `scale != 1.0` raised "truth value is ambiguous"
        scales = np.array([1.0, 2.0, 4.0, 8.0])
        e = np.asarray(rt.random.exponential(scales, size=4))
        assert e.shape == (4,) and (e >= 0).all()
        g = np.asarray(rt.random.gamma(3.0, scales, size=4))
        assert g.shape == (4,) and (g > 0).all()

    def test_permutation_and_shuffle(self):
        perm = np.asarray(rt.random.permutation(257))
        assert sorted(perm) == list(range(257))
        # dtype parity (ADVICE r4): int64 under x64, int32 in x32 regime
        import jax as _jax

        want = np.int64 if _jax.config.jax_enable_x64 else np.int32
        assert perm.dtype == want, perm.dtype
        arr = rt.fromarray(np.arange(100.0))
        pa = np.asarray(rt.random.permutation(arr))
        assert sorted(pa) == list(range(100))
        x = rt.fromarray(np.arange(64.0))
        rt.random.shuffle(x)
        got = np.asarray(x)
        assert sorted(got) == list(range(64))
        assert not (got == np.arange(64.0)).all()  # actually shuffled

    def test_choice(self):
        c = np.asarray(rt.random.choice(5, size=1000))
        assert set(np.unique(c)) <= set(range(5))
        cn = np.asarray(rt.random.choice(16, size=16, replace=False))
        assert sorted(cn) == list(range(16))
        cp = np.asarray(rt.random.choice(3, size=5000, p=[0.1, 0.1, 0.8]))
        assert (cp == 2).mean() > 0.7
        vals = np.array([10.0, 20.0, 30.0])
        cv = np.asarray(rt.random.choice(rt.fromarray(vals), size=100))
        assert set(np.unique(cv)) <= {10.0, 20.0, 30.0}

    def test_int_distributions_use_wide_dtype(self):
        # review r4: poisson/binomial follow randint's dtype=int convention
        # (int64 under the x64 leg, int32 under x32) — not hardcoded int32
        from tests.helpers import map_dtype

        want = map_dtype(np.int64)
        assert np.asarray(rt.random.poisson(3.0, size=8)).dtype == want
        assert np.asarray(rt.random.binomial(5, 0.5, size=8)).dtype == want


class TestNamespaceUtilities:
    def test_index_and_metadata_helpers(self):
        assert rt.s_[1:5] == np.s_[1:5]
        assert rt.index_exp[2] == np.index_exp[2]
        assert list(rt.ndindex(2, 2)) == list(np.ndindex(2, 2))
        assert rt.broadcast_shapes((3, 1), (4,)) == (3, 4)
        assert rt.promote_types(np.int32, np.float32) == np.float64
        assert rt.can_cast(np.int32, np.int64)
        assert rt.issubdtype(np.float32, np.floating)

    def test_shape_ndim_size(self):
        a = rt.fromarray(np.zeros((3, 4)))
        assert rt.shape(a) == (3, 4)
        assert rt.ndim(a) == 2
        assert rt.size(a) == 12 and rt.size(a, 1) == 4

    def test_printing_and_iteration(self):
        a = rt.fromarray(np.arange(4.0))
        s = rt.array2string(a)
        assert "0." in s and "3." in s
        assert "array" in rt.array_repr(a)
        items = list(rt.ndenumerate(a))
        assert items[0] == ((0,), 0.0) and items[-1] == ((3,), 3.0)
        with rt.printoptions(precision=2):
            assert len(rt.array_str(rt.fromarray(np.array([1.23456])))) < 12
        with rt.errstate(divide="ignore"):
            np.float64(1.0) / np.float64(0.0)

    def test_np_metadata_dispatch_and_host_inputs(self):
        # review r4: np.shape/np.size on ramba arrays must dispatch (not
        # TypeError), and host inputs must not round-trip through device
        a = rt.fromarray(np.zeros((3, 4)))
        assert np.shape(a) == (3, 4)
        assert np.ndim(a) == 2
        assert np.size(a) == 12
        assert "0." in np.array2string(a)
        # plain host inputs stay host-side (free metadata reads)
        assert rt.shape([[1, 2], [3, 4]]) == (2, 2)
        assert rt.ndim(5) == 0
        assert rt.size(np.zeros((2, 5)), 1) == 5


class TestCreationIOBreadth:
    def test_logspace_geomspace(self):
        _cmp(rt.logspace(0, 3, 10), np.logspace(0, 3, 10), rtol=1e-6)
        _cmp(rt.logspace(0, 4, 8, base=2.0), np.logspace(0, 4, 8, base=2.0),
             rtol=1e-6)
        _cmp(rt.geomspace(1, 1000, 4), np.geomspace(1, 1000, 4), rtol=1e-6)
        _cmp(rt.geomspace(-1, -1000, 4), np.geomspace(-1, -1000, 4),
             rtol=1e-6)
        with pytest.raises(ValueError):
            rt.geomspace(0, 10, 5)
        # mixed signs: clear ValueError, not an opaque log10 domain error
        with pytest.raises(ValueError, match="sign"):
            rt.geomspace(-1, 10, 5)

    def test_from_variants(self):
        np.testing.assert_array_equal(
            np.asarray(rt.fromiter(range(5), int)), np.arange(5))
        buf = np.arange(4.0).tobytes()
        _cmp(rt.frombuffer(buf), np.frombuffer(buf))
        _cmp(rt.fromstring("1 2 3", sep=" "), np.array([1.0, 2.0, 3.0]))

    def test_contiguous_chkfinite_rollaxis(self):
        a = rt.fromarray(np.arange(6.0))
        assert rt.ascontiguousarray(a) is not None
        with pytest.raises(ValueError, match="infs or NaNs"):
            rt.asarray_chkfinite(np.array([1.0, np.nan]))
        m = rt.fromarray(np.zeros((2, 3, 4)))
        assert np.asarray(rt.rollaxis(m, 2)).shape == np.rollaxis(
            np.zeros((2, 3, 4)), 2).shape
        assert np.asarray(rt.rollaxis(m, 0, 3)).shape == np.rollaxis(
            np.zeros((2, 3, 4)), 0, 3).shape

    def test_loadtxt_savetxt(self, tmp_path):
        p = str(tmp_path / "t.txt")
        data = np.arange(6.0).reshape(2, 3)
        rt.savetxt(p, rt.fromarray(data))
        _cmp(rt.loadtxt(p), data)
        from tests.helpers import driver_write

        p2 = str(tmp_path / "t2.txt")
        # raw numpy write: one writer + barrier on the cross-process leg
        driver_write(lambda: np.savetxt(p2, data, delimiter=","))
        _cmp(rt.loadtxt(p2, delimiter=","), data)
        _cmp(rt.genfromtxt(p2, delimiter=","), data)

    def test_rollaxis_negative_and_errors(self):
        # review r4: negative start must add n (not modulo), out-of-range
        # axis must raise like numpy
        base = np.zeros((2, 3, 4))
        m = rt.fromarray(base)
        for axis in range(-3, 3):
            for start in range(-3, 4):
                got = np.asarray(rt.rollaxis(m, axis, start)).shape
                want = np.rollaxis(base, axis, start).shape
                assert got == want, (axis, start, got, want)
        with pytest.raises(Exception, match="out of bounds"):
            rt.rollaxis(m, 5)

    def test_geomspace_complex_raises_clearly(self):
        with pytest.raises(NotImplementedError, match="complex"):
            rt.geomspace(1j, 1000j, 4)
