"""End-to-end data integrity plane (``resilience/integrity.py``).

The contract under test, in order of importance:

* **Never serve suspect bytes** — a flipped blob at any stamped seam
  (shared memo, AOT executable, plan certificate, checkpoint leaf,
  migration payload) is detected by digest, evicted, and recomputed /
  recompiled / rejected; the caller observes the *correct* answer or a
  classified error, never silence and never a crash.
* **Byte identity under audit** — ``RAMBA_AUDIT`` shadow re-execution
  must not perturb primary results: audit-on and audit-off runs of the
  same seeded chain are byte-identical.
* **Visibility** — every detection is an ``integrity`` event, a
  counter, and (past ``RAMBA_INTEGRITY_THRESHOLD`` in the window) a
  ``suspect`` health signal the fleet plane classifies as degraded.
* **Offline scrub** — ``ramba-fsck`` finds at-rest corruption with the
  runtime not even loaded, and ``--repair`` quarantines rather than
  deletes.

The SPMD analog (rank-skewed shadow flips agreed via coherence, plus
the wrong-answer repro with the plane disabled) is
``scripts/two_process_suite.py --integrity-leg``.
"""

import json
import os
import sys

import numpy as np
import pytest

import ramba_tpu as rt
from ramba_tpu import diagnostics
from ramba_tpu.core import fuser, memo, plancache
from ramba_tpu.fleet import artifacts, migrate
from ramba_tpu.observe import events, fleet, registry, telemetry
from ramba_tpu.resilience import faults, integrity

_SCRIPTS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts")
if _SCRIPTS not in sys.path:
    sys.path.insert(0, _SCRIPTS)
import ramba_fsck  # noqa: E402


@pytest.fixture(autouse=True)
def _isolate(monkeypatch):
    """Empty pending set, integrity plane at defaults (stamping on,
    audits off), zeroed counters, no faults, no shared tier."""
    fuser.flush()
    faults.configure(None)
    for k in ("RAMBA_INTEGRITY", "RAMBA_AUDIT", "RAMBA_INTEGRITY_THRESHOLD",
              "RAMBA_INTEGRITY_WINDOW_S", "RAMBA_MEMO", "RAMBA_ARTIFACTS",
              "RAMBA_FAULTS", "RAMBA_VERIFY", "RAMBA_PLANCERT"):
        monkeypatch.delenv(k, raising=False)
    integrity.reset()
    memo.reset()
    artifacts.reset()
    yield
    faults.reset()
    fuser.flush()
    integrity.reset()
    memo.reset()
    for k in ("RAMBA_ARTIFACTS", "RAMBA_MEMO", "RAMBA_AUDIT",
              "RAMBA_INTEGRITY"):
        os.environ.pop(k, None)
    artifacts.reset()


# ---------------------------------------------------------------------------
# RAMBA_FAULTS flip mode (the corruption driver itself)
# ---------------------------------------------------------------------------


class TestFlipMode:
    def test_unarmed_is_identity(self):
        data = b"x" * 64
        assert faults.corrupt("memo:blob", data) is data

    def test_flip_is_deterministic_and_bounded(self):
        data = bytes(range(256))
        faults.configure("memo:blob:flip:bytes=2", seed=7)
        first = faults.corrupt("memo:blob", data)
        faults.configure("memo:blob:flip:bytes=2", seed=7)
        again = faults.corrupt("memo:blob", data)
        assert first == again and first != data
        assert len(first) == len(data)
        diff = [i for i in range(len(data)) if first[i] != data[i]]
        assert 1 <= len(diff) <= 2
        # XOR 0xFF self-inverts: re-flipping restores the original
        assert all(first[i] ^ 0xFF == data[i] for i in diff)

    def test_after_is_one_shot(self):
        data = b"payload-bytes" * 4
        faults.configure("memo:blob:flip:bytes=1:after=1", seed=3)
        assert faults.corrupt("memo:blob", data) == data       # call 1
        assert faults.corrupt("memo:blob", data) != data       # call 2 fires
        assert faults.corrupt("memo:blob", data) == data       # call 3

    def test_corrupt_file_flips_in_place(self, tmp_path):
        p = str(tmp_path / "blob.bin")
        data = os.urandom(128)
        with open(p, "wb") as f:
            f.write(data)
        faults.configure("checkpoint:leaf:flip:bytes=3", seed=1)
        assert faults.corrupt_file("checkpoint:leaf", p)
        with open(p, "rb") as f:
            now = f.read()
        assert now != data and len(now) == len(data)

    def test_flip_emits_fault_event(self):
        faults.configure("memo:blob:flip:bytes=1")
        faults.corrupt("memo:blob", b"0123456789")
        ev = events.last(4, type="fault")
        assert any(e.get("site") == "memo:blob" and e.get("mode") == "flip"
                   for e in ev), ev


# ---------------------------------------------------------------------------
# the envelope
# ---------------------------------------------------------------------------


class TestEnvelope:
    def test_roundtrip(self):
        payload = b"the quick brown fox"
        blob = integrity.wrap(payload, "memo.npz")
        assert blob != payload
        assert integrity.unwrap(blob, "memo.npz", site="test") == payload
        assert integrity.stats["stamped"] >= 1
        assert integrity.stats["verified"] >= 1

    def test_every_single_byte_flip_is_detected(self):
        blob = bytearray(integrity.wrap(b"ramba", "memo.npz"))
        for i in range(len(blob)):
            bad = bytes(blob[:i]) + bytes([blob[i] ^ 0xFF]) \
                + bytes(blob[i + 1:])
            with pytest.raises(integrity.IntegrityError):
                integrity.unwrap(bad, "memo.npz", site="test",
                                 record=False)

    def test_unstamped_is_strict(self):
        with pytest.raises(integrity.IntegrityError) as ei:
            integrity.unwrap(b"no envelope here", "memo.npz",
                             site="memo:blob")
        assert ei.value.reason == "unstamped"
        assert integrity.stats["failures"] >= 1
        ev = events.last(4, type="integrity")
        assert ev and ev[-1]["site"] == "memo:blob", ev

    def test_schema_confusion_is_detected(self):
        blob = integrity.wrap(b"payload", "aot.pkl")
        with pytest.raises(integrity.IntegrityError) as ei:
            integrity.unwrap(blob, "memo.npz", site="test", record=False)
        assert ei.value.reason == "schema"

    def test_disabled_plane_strips_without_verifying(self, monkeypatch):
        blob = integrity.wrap(b"payload", "memo.npz")
        monkeypatch.setenv("RAMBA_INTEGRITY", "0")
        assert not integrity.enabled()
        # stamped blobs still load (envelope stripped), raw blobs pass
        # through, and even a flipped digest no longer raises
        assert integrity.unwrap(blob, "memo.npz", site="t") == b"payload"
        assert integrity.unwrap(b"raw", "memo.npz", site="t") == b"raw"
        bad = blob[:10] + bytes([blob[10] ^ 0xFF]) + blob[11:]
        integrity.unwrap(bad, "memo.npz", site="t")  # must not raise
        # and new writes are unstamped (identity)
        assert integrity.wrap(b"new", "memo.npz") == b"new"

    def test_verify_blob_classifies_offline(self):
        blob = integrity.wrap(b"payload", "memo.npz")
        assert integrity.verify_blob(blob, "memo.npz") is None
        assert integrity.verify_blob(None, "memo.npz") == "missing"
        assert integrity.verify_blob(b"raw", "memo.npz") == "unstamped"
        bad = blob[:-2] + bytes([blob[-2] ^ 0xFF]) + blob[-1:]
        assert integrity.verify_blob(bad, "memo.npz") == "digest"
        other = integrity.wrap(b"payload", "aot.pkl")
        assert str(integrity.verify_blob(other, "memo.npz")) \
            .startswith("schema")
        assert integrity.stats["failures"] == 0  # offline: no strikes


# ---------------------------------------------------------------------------
# seam: shared memo blobs (memo:blob)
# ---------------------------------------------------------------------------


class TestMemoBlobSeam:
    def _tier(self, tmp_path, monkeypatch):
        monkeypatch.setenv("RAMBA_ARTIFACTS", str(tmp_path))
        artifacts.configure(str(tmp_path))

    def test_flip_detected_evicted_recomputed(self, tmp_path, monkeypatch):
        self._tier(tmp_path, monkeypatch)
        key = "deadbeef" * 5
        ref = np.arange(64.0)
        assert artifacts.memo_store(key, [ref])
        got = artifacts.memo_load(key)
        np.testing.assert_array_equal(got[0], ref)
        faults.configure("memo:blob:flip:bytes=1")
        c0 = integrity.stats["failures"]
        assert artifacts.memo_load(key) is None      # never served
        assert artifacts.snapshot()["memo_corrupt"] >= 1
        assert integrity.stats["failures"] == c0 + 1
        assert not os.path.exists(artifacts._memo_path(key))  # evicted
        ev = events.last(6, type="integrity")
        assert any(e["site"] == "memo:blob" for e in ev), ev
        # recompute + republish heals the lane
        faults.configure(None)
        assert artifacts.memo_store(key, [ref])
        np.testing.assert_array_equal(artifacts.memo_load(key)[0], ref)

    def test_unstamped_preplane_blob_evicted_once(self, tmp_path,
                                                  monkeypatch):
        import io

        self._tier(tmp_path, monkeypatch)
        key = "cafebabe" * 5
        buf = io.BytesIO()
        np.savez(buf, out0=np.ones(8))
        path = artifacts._memo_path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as f:
            f.write(buf.getvalue())               # raw, pre-plane entry
        assert artifacts.memo_load(key) is None
        assert not os.path.exists(path)
        assert integrity.stats["unstamped_evictions"] >= 1

    def test_valid_envelope_bad_payload_is_deserialize(self, tmp_path,
                                                       monkeypatch):
        # a stamped-but-unparseable blob (schema drift / pre-stamp torn
        # write) still classifies as an integrity incident  (satellite:
        # existing corrupt paths emit integrity events too)
        self._tier(tmp_path, monkeypatch)
        key = "0badf00d" * 5
        path = artifacts._memo_path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as f:
            f.write(integrity.wrap(b"not an npz archive", "memo.npz"))
        assert artifacts.memo_load(key) is None
        assert not os.path.exists(path)
        ev = events.last(6, type="integrity")
        assert any(e["site"] == "memo:blob" and e["reason"] == "deserialize"
                   for e in ev), ev


# ---------------------------------------------------------------------------
# seam: persistent AOT executables (aot:blob)
# ---------------------------------------------------------------------------


class TestAotBlobSeam:
    def test_flip_evicts_recompiles_correct_answer(self, tmp_path):
        from ramba_tpu.compile import persist

        saved = {k: os.environ.get(k) for k in ("RAMBA_CACHE", "RAMBA_AOT")}
        os.environ["RAMBA_CACHE"] = str(tmp_path / "cache")
        os.environ.pop("RAMBA_AOT", None)
        try:
            persist.reconfigure()
            assert persist.armed(), persist.snapshot()
            with fuser._cache_lock:
                fuser._compile_cache.clear()
            base = np.arange(40, dtype=np.float32).reshape(5, 8)
            np.asarray(rt.array(base) * 5.0 - 2.0)
            assert persist.save_topk(4)["stored"] >= 1
            with fuser._cache_lock:
                fuser._compile_cache.clear()
            c0 = persist.snapshot()["corrupt"]
            i0 = integrity.stats["failures"]
            faults.configure("aot:blob:flip:bytes=2")
            out = np.asarray(rt.array(base) * 5.0 - 2.0)  # must NOT raise
            np.testing.assert_array_equal(out, base * 5.0 - 2.0)
            snap = persist.snapshot()
            assert snap["corrupt"] >= c0 + 1, snap
            assert integrity.stats["failures"] >= i0 + 1
            assert registry.get("compile.persist_corrupt") >= 1
        finally:
            faults.configure(None)
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            persist.reset()


# ---------------------------------------------------------------------------
# seam: shared plan certificates (plancert:blob)
# ---------------------------------------------------------------------------


class TestPlancertBlobSeam:
    def test_flipped_cert_evicted_rederived(self, tmp_path, monkeypatch):
        from ramba_tpu.analyze import plancert

        monkeypatch.setenv("RAMBA_PLANCERT", "1")
        monkeypatch.setenv("RAMBA_VERIFY", "strict")
        monkeypatch.setenv("RAMBA_ARTIFACTS", str(tmp_path))
        artifacts.configure(str(tmp_path))
        plancache.reset()
        plancert.reset_caches()
        try:
            def _workload():
                a = rt.fromarray(np.arange(256.0).reshape(16, 16))
                b = rt.fromarray(np.ones((16, 16)))
                return np.asarray((a + b) * 2.0 - 0.5)

            first = _workload()
            certs = [e.cert for e in plancache._store.values()]
            assert certs and all(c.chash for c in certs)
            for c in certs:
                assert plancache.publish(c)
            cert_dir = os.path.join(str(tmp_path), "plancert")
            blobs = sorted(os.listdir(cert_dir))
            assert blobs
            # at-rest bit rot: flip one byte of every published cert
            for name in blobs:
                p = os.path.join(cert_dir, name)
                raw = bytearray(open(p, "rb").read())
                raw[len(raw) // 2] ^= 0xFF
                open(p, "wb").write(bytes(raw))
            plancache.reset()          # model a fresh process
            i0 = integrity.stats["failures"]
            second = _workload()       # adoption must fail silently
            assert first.tobytes() == second.tobytes()
            assert plancache.snapshot().get("adopted", 0) == 0
            assert integrity.stats["failures"] >= i0 + 1
            # the poisoned blobs were evicted, not left to re-trip
            left = [n for n in blobs
                    if os.path.exists(os.path.join(cert_dir, n))]
            assert len(left) < len(blobs)
        finally:
            plancache.reset()
            plancert.reset_caches()


# ---------------------------------------------------------------------------
# seam: checkpoint leaves + sidecar (checkpoint:leaf)  [satellite: leaf
# clobber must raise CheckpointCorruptError]
# ---------------------------------------------------------------------------


def _ckpt_tree():
    return {"w": rt.arange(64).reshape(8, 8) * 1.5, "b": rt.arange(8) * 0.25}


def _sidecar_doc(path):
    from ramba_tpu import checkpoint

    with open(checkpoint.digests_path(path), "rb") as f:
        raw = f.read()
    payload = integrity.unwrap(raw, checkpoint._DIGESTS_SCHEMA,
                               site="test", record=False)
    return json.loads(payload.decode())


class TestCheckpointIntegrity:
    def test_save_writes_sidecar_restore_verifies(self, tmp_path):
        pytest.importorskip("orbax.checkpoint")
        from ramba_tpu import checkpoint

        p = str(tmp_path / "ck")
        checkpoint.save(p, _ckpt_tree())
        assert os.path.exists(checkpoint.digests_path(p))
        doc = _sidecar_doc(p)
        assert doc["files"], doc
        v0 = integrity.stats["verified"]
        back = checkpoint.restore(p)
        np.testing.assert_allclose(np.asarray(back["w"]),
                                   np.arange(64).reshape(8, 8) * 1.5)
        assert integrity.stats["verified"] > v0

    def test_clobbered_leaf_file_raises(self, tmp_path):
        # satellite: physical same-length corruption of a leaf data file
        pytest.importorskip("orbax.checkpoint")
        from ramba_tpu import checkpoint

        p = str(tmp_path / "ck")
        checkpoint.save(p, _ckpt_tree())
        doc = _sidecar_doc(p)
        rel = max(doc["files"], key=lambda r: doc["files"][r]["size"])
        full = os.path.join(os.path.abspath(p), rel)
        size = os.path.getsize(full)
        with open(full, "wb") as f:
            f.write(b"\x5a" * size)              # same length, wrong bytes
        i0 = integrity.stats["failures"]
        with pytest.raises(checkpoint.CheckpointCorruptError):
            checkpoint.restore(p)
        assert integrity.stats["failures"] > i0
        ev = events.last(6, type="integrity")
        assert any(e["site"] == "checkpoint:leaf" for e in ev), ev

    def test_flip_seam_detected_then_found_by_fsck(self, tmp_path):
        pytest.importorskip("orbax.checkpoint")
        from ramba_tpu import checkpoint

        p = str(tmp_path / "ck")
        checkpoint.save(p, _ckpt_tree())
        faults.configure("checkpoint:leaf:flip:bytes=2")
        with pytest.raises(checkpoint.CheckpointCorruptError):
            checkpoint.restore(p)
        # the flip persisted on disk: the offline scrubber finds it with
        # no faults armed and no runtime state
        faults.configure(None)
        r = ramba_fsck.scan(checkpoints=[p])
        assert r["status"] == ramba_fsck.EXIT_CORRUPT, r
        assert r["corrupt"] >= 1

    def test_legacy_checkpoint_without_sidecar_restores(self, tmp_path):
        pytest.importorskip("orbax.checkpoint")
        from ramba_tpu import checkpoint

        p = str(tmp_path / "ck")
        checkpoint.save(p, _ckpt_tree())
        os.remove(checkpoint.digests_path(p))
        back = checkpoint.restore(p)          # unverified but served
        np.testing.assert_allclose(np.asarray(back["b"]),
                                   np.arange(8) * 0.25)

    def test_corrupt_sidecar_raises(self, tmp_path):
        pytest.importorskip("orbax.checkpoint")
        from ramba_tpu import checkpoint

        p = str(tmp_path / "ck")
        checkpoint.save(p, _ckpt_tree())
        sp = checkpoint.digests_path(p)
        raw = bytearray(open(sp, "rb").read())
        raw[len(raw) // 2] ^= 0xFF
        open(sp, "wb").write(bytes(raw))
        with pytest.raises(checkpoint.CheckpointCorruptError):
            checkpoint.restore(p)

    def test_disabled_plane_writes_no_sidecar(self, tmp_path, monkeypatch):
        pytest.importorskip("orbax.checkpoint")
        from ramba_tpu import checkpoint

        monkeypatch.setenv("RAMBA_INTEGRITY", "0")
        p = str(tmp_path / "ck")
        checkpoint.save(p, _ckpt_tree())
        assert not os.path.exists(checkpoint.digests_path(p))
        back = checkpoint.restore(p)
        np.testing.assert_allclose(np.asarray(back["b"]),
                                   np.arange(8) * 0.25)


class TestElasticManifestDigest:
    def test_tampered_manifest_raises(self, tmp_path):
        pytest.importorskip("orbax.checkpoint")
        from ramba_tpu.checkpoint import CheckpointCorruptError
        from ramba_tpu.resilience import elastic

        mgr = elastic.CheckpointManager(str(tmp_path / "mgr"))
        mgr.register("s", {"x": rt.arange(6) * 1.0})
        mgr.save(1)
        man = mgr.manifest(1)
        assert man.get("digest")              # stamped at publish
        with open(mgr.manifest_path(1)) as f:
            doc = json.load(f)
        doc["x64"] = not doc["x64"]           # field tamper, digest kept
        with open(mgr.manifest_path(1), "w") as f:
            json.dump(doc, f)
        with pytest.raises(CheckpointCorruptError, match="digest"):
            mgr.manifest(1)


# ---------------------------------------------------------------------------
# seam: migration payloads (migrate:payload)
# ---------------------------------------------------------------------------


class TestMigratePayloadSeam:
    def _tier(self, tmp_path, monkeypatch):
        monkeypatch.setenv("RAMBA_ARTIFACTS", str(tmp_path))
        artifacts.configure(str(tmp_path))

    def test_manifest_records_payload_bytes(self, tmp_path, monkeypatch):
        self._tier(tmp_path, monkeypatch)
        path = migrate.export_session("sid-a", {"seq": 1},
                                      {"x": rt.full([64], 2.0)})
        man = migrate.load_manifest("sid-a")
        assert man["payload_bytes"] == migrate._payload_bytes(path)
        assert man["payload_bytes"] > 0

    def test_truncated_payload_rejected(self, tmp_path, monkeypatch):
        # satellite: handoff whose on-disk byte-length disagrees with the
        # manifest must be rejected before restore is even attempted
        self._tier(tmp_path, monkeypatch)
        path = migrate.export_session("sid-b", {"seq": 1},
                                      {"x": rt.full([64], 2.0)})
        files = migrate._payload_files(path)
        victim = max(files, key=os.path.getsize)
        with open(victim, "rb+") as f:
            f.truncate(max(0, os.path.getsize(victim) - 7))
        i0 = integrity.stats["failures"]
        with pytest.raises(migrate.MigrateError):
            migrate.adopt_session("sid-b")
        assert integrity.stats["failures"] > i0
        ev = events.last(6, type="integrity")
        assert any(e["site"] == "migrate:payload" for e in ev), ev

    def test_flip_seam_rejected(self, tmp_path, monkeypatch):
        self._tier(tmp_path, monkeypatch)
        migrate.export_session("sid-c", {"seq": 1},
                               {"x": rt.full([64], 2.0)})
        faults.configure("migrate:payload:flip:bytes=2")
        with pytest.raises(migrate.MigrateError):
            migrate.adopt_session("sid-c")

    def test_legacy_manifest_without_census_adopts(self, tmp_path,
                                                   monkeypatch):
        self._tier(tmp_path, monkeypatch)
        migrate.export_session("sid-d", {"seq": 1},
                               {"x": rt.full([16], 3.0)})
        mp = migrate._manifest_path("sid-d", None)
        man = json.loads(open(mp, "rb").read())
        man.pop("payload_bytes")
        with open(mp, "w") as f:
            json.dump(man, f)
        manifest, adopted = migrate.adopt_session("sid-d")
        np.testing.assert_array_equal(np.asarray(adopted["x"].asarray()),
                                      np.full(16, 3.0))

    def test_discard_removes_sidecar(self, tmp_path, monkeypatch):
        from ramba_tpu import checkpoint

        self._tier(tmp_path, monkeypatch)
        path = migrate.export_session("sid-e", {"seq": 1},
                                      {"x": rt.full([8], 1.0)})
        side = checkpoint.digests_path(path)
        if os.path.exists(side):              # stamped export
            migrate.discard("sid-e")
            assert not os.path.exists(side)


# ---------------------------------------------------------------------------
# shadow recompute audits (audit:shadow)
# ---------------------------------------------------------------------------


def _audited_flush(scale):
    a = rt.fromarray(np.arange(512.0) / 100.0)
    b = rt.fromarray(np.arange(512.0) * 0.5 + 1.0)
    return float(rt.sum((a + b) * scale))


class TestShadowAudit:
    def test_clean_flush_audits_without_mismatch(self, monkeypatch):
        monkeypatch.setenv("RAMBA_MEMO", "1")
        monkeypatch.setenv("RAMBA_AUDIT", "1")
        memo.reset()
        expect = float(np.sum((np.arange(512.0) / 100.0
                               + np.arange(512.0) * 0.5 + 1.0) * 2.0))
        got = _audited_flush(2.0)
        assert got == pytest.approx(expect, rel=1e-12)
        snap = integrity.snapshot()
        assert snap["audits"] >= 1, snap
        assert snap["audit_mismatches"] == 0, snap
        assert snap["audit_errors"] == 0, snap

    def test_flipped_shadow_flags_mismatch_serves_primary(self,
                                                          monkeypatch):
        monkeypatch.setenv("RAMBA_MEMO", "1")
        monkeypatch.setenv("RAMBA_AUDIT", "1")
        memo.reset()
        faults.configure("audit:shadow:flip:bytes=4")
        expect = float(np.sum((np.arange(512.0) / 100.0
                               + np.arange(512.0) * 0.5 + 1.0) * 3.0))
        got = _audited_flush(3.0)          # primary result must be served
        assert got == pytest.approx(expect, rel=1e-12)
        snap = integrity.snapshot()
        assert snap["audits"] >= 1, snap
        assert snap["audit_mismatches"] >= 1, snap
        assert snap["audit_errors"] == 0, snap
        # a flush whose audit disagreed must not seed the memo cache
        assert memo.cache.snapshot()["entries"] == 0
        ev = events.last(8, type="integrity")
        assert any(e["site"] == "audit:shadow" for e in ev), ev

    def test_audit_on_off_byte_identity(self, monkeypatch):
        """Fuzz leg: a seeded op chain produces byte-identical results
        with audits off and with every eligible flush audited."""
        monkeypatch.setenv("RAMBA_MEMO", "1")

        def _chain():
            rng = np.random.default_rng(1234)
            outs = []
            a = rt.fromarray(rng.standard_normal(256))
            b = rt.fromarray(rng.standard_normal(256))
            for _ in range(4):
                k = float(rng.uniform(0.5, 2.0))
                c = (a * k + b) - 0.25
                outs.append(np.asarray(c).tobytes())
                outs.append(np.asarray(rt.sum(c * c)).tobytes())
            return outs

        monkeypatch.delenv("RAMBA_AUDIT", raising=False)
        memo.reset()
        baseline = _chain()
        fuser.flush()
        memo.reset()
        integrity.reset()
        monkeypatch.setenv("RAMBA_AUDIT", "1")
        audited = _chain()
        assert baseline == audited
        snap = integrity.snapshot()
        assert snap["audits"] >= 1, snap
        assert snap["audit_mismatches"] == 0, snap


# ---------------------------------------------------------------------------
# suspect quarantine + fleet visibility
# ---------------------------------------------------------------------------


class TestSuspectQuarantine:
    def test_threshold_trips_suspect_and_fleet_signal(self, monkeypatch):
        monkeypatch.setenv("RAMBA_INTEGRITY_THRESHOLD", "2")
        assert not integrity.suspect()
        integrity.failure("memo:blob", "digest", detail="t1")
        assert integrity.failure_count() == 1
        assert not integrity.suspect()
        integrity.failure("aot:blob", "digest", detail="t2")
        assert integrity.suspect()
        sig = fleet._signals()
        assert sig["integrity_suspect"] is True
        assert sig["integrity_failures"] >= 2

    def test_window_expires_strikes(self, monkeypatch):
        monkeypatch.setenv("RAMBA_INTEGRITY_THRESHOLD", "1")
        integrity.failure("memo:blob", "digest", detail="old")
        now = __import__("time").time()
        assert integrity.suspect(now)
        assert not integrity.suspect(now + integrity.suspect_window_s() + 1)

    def test_fleet_classifies_suspect_replica_degraded(self):
        doc = {"schema_version": diagnostics.SCHEMA_VERSION,
               "interval_s": 30.0, "published_at": 1000.0,
               "signals": {"integrity_suspect": True,
                           "integrity_failures": 3}}
        state, reason = fleet.classify({"doc": doc}, now=1010.0)
        assert state == fleet.DEGRADED
        assert "integrity suspect" in reason and "3" in reason

    def test_integrity_is_a_flight_trigger(self):
        assert "integrity" in telemetry.FLIGHT_TRIGGERS

    def test_diagnostics_surface(self):
        integrity.failure("memo:blob", "digest", detail="probe")
        rep = diagnostics.integrity_report()
        assert rep["failures"] >= 1
        snap = diagnostics.snapshot()
        assert snap.get("integrity", {}).get("failures", 0) >= 1


# ---------------------------------------------------------------------------
# ramba-fsck (offline scrub)
# ---------------------------------------------------------------------------


class TestFsck:
    def test_empty_tier_is_exit_empty(self, tmp_path):
        r = ramba_fsck.scan(artifacts=str(tmp_path))
        assert r["status"] == ramba_fsck.EXIT_EMPTY and r["scanned"] == 0

    def test_detect_repair_quarantine_rescan(self, tmp_path, monkeypatch):
        monkeypatch.setenv("RAMBA_ARTIFACTS", str(tmp_path))
        artifacts.configure(str(tmp_path))
        assert artifacts.memo_store("fsck0" * 8, [np.arange(16.0)])
        assert artifacts.memo_store("fsck1" * 8, [np.ones(4)])
        root = str(tmp_path)
        r = ramba_fsck.scan(artifacts=root)
        assert r["status"] == ramba_fsck.EXIT_CLEAN and r["scanned"] >= 2
        memo_dir = os.path.join(root, "memo")
        victim = os.path.join(memo_dir, sorted(os.listdir(memo_dir))[0])
        raw = bytearray(open(victim, "rb").read())
        raw[len(raw) // 2] ^= 0xFF
        open(victim, "wb").write(bytes(raw))
        r = ramba_fsck.scan(artifacts=root)
        assert r["status"] == ramba_fsck.EXIT_CORRUPT and r["corrupt"] == 1
        r = ramba_fsck.scan(artifacts=root, repair=True)
        assert r["status"] == ramba_fsck.EXIT_CORRUPT
        qdir = os.path.join(root, "quarantine")
        assert os.path.isdir(qdir)
        assert not os.path.exists(victim)     # moved, not deleted
        r = ramba_fsck.scan(artifacts=root)
        assert r["status"] == ramba_fsck.EXIT_CLEAN, r

    def test_cli_exit_codes(self, tmp_path, monkeypatch):
        monkeypatch.setenv("RAMBA_ARTIFACTS", str(tmp_path))
        artifacts.configure(str(tmp_path))
        assert artifacts.memo_store("fsck2" * 8, [np.arange(8.0)])
        assert ramba_fsck.main(["--artifacts", str(tmp_path)]) \
            == ramba_fsck.EXIT_CLEAN
        assert ramba_fsck.main(["--artifacts", str(tmp_path / "nope")]) \
            == ramba_fsck.EXIT_EMPTY
