"""One-shot TPU perf experiments: chain decomposition + stencil variants.

Run directly on the real chip. Each measurement uses a scalar fetch as the
completion barrier (block_until_ready does not synchronize through the
remote-dispatch tunnel). Results guide kernel optimization; this script is
not part of the test suite.
"""

import time

import numpy as np


def timeit(fn, reps=3):
    fn()  # compile
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    import jax
    import jax.numpy as jnp

    n = 1_000_000_000

    @jax.jit
    def write_only():
        a = jax.lax.iota(jnp.float32, n)
        d = a * 2.0
        return d, jnp.sum(d)

    @jax.jit
    def chain():
        a = jax.lax.iota(jnp.float32, n) / 1000.0
        b = jnp.sin(a)
        c = jnp.cos(a)
        d = b * b + c ** 2
        return d, jnp.sum(d)

    @jax.jit
    def sum_only():
        a = jax.lax.iota(jnp.float32, n) / 1000.0
        b = jnp.sin(a)
        c = jnp.cos(a)
        return jnp.sum(b * b + c ** 2)

    for name, f in [("write+sum", write_only), ("chain", chain),
                    ("sum_only", sum_only)]:
        t = timeit(lambda f=f: float(jax.tree.leaves(f())[-1]))
        print(f"{name}: {t*1e3:.1f} ms")

    # ---- stencil variants on 8192^2 f32, PRK star r=2 ----
    sn, sk = 8192, 30
    x0 = np.random.RandomState(0).rand(sn, sn).astype(np.float32)
    flops = 13 * (sn - 4) * (sn - 4) * sk

    def report(name, t):
        print(f"{name}: {t/sk*1e3:.2f} ms/iter, {flops/t/1e6:.0f} PRK-MFlops")

    def star_xla(a):
        # shifted-slice path over the interior, zero borders
        H, W = a.shape
        i = a[2:-2, 2:-2]
        val = (0.25 * (a[2:-2, 3:-1] + a[2:-2, 1:-3]
                       + a[3:-1, 2:-2] + a[1:-3, 2:-2])
               + 0.125 * (a[2:-2, 4:] + a[2:-2, :-4]
                          + a[4:, 2:-2] + a[:-4, 2:-2]))
        return jnp.zeros_like(a).at[2:-2, 2:-2].set(val)

    @jax.jit
    def xla_chain(a):
        for _ in range(sk):
            a = star_xla(a)
        return a, jnp.sum(a)

    xj = jnp.asarray(x0)
    t = timeit(lambda: float(xla_chain(xj)[1]))
    report("stencil XLA shifted-slice", t)

    # conv formulation (linear stencils only; ceiling probe)
    kern = np.zeros((5, 5), np.float32)
    kern[2, 3] = kern[2, 1] = kern[3, 2] = kern[1, 2] = 0.25
    kern[2, 4] = kern[2, 0] = kern[4, 2] = kern[0, 2] = 0.125
    kj = jnp.asarray(kern)[None, None]

    @jax.jit
    def conv_chain(a):
        v = a[None, None]
        for _ in range(sk):
            out = jax.lax.conv_general_dilated(
                v, kj, (1, 1), [(2, 2), (2, 2)])
            # zero borders to match sstencil semantics
            v = jnp.zeros_like(out).at[:, :, 2:-2, 2:-2].set(
                out[:, :, 2:-2, 2:-2])
        return v, jnp.sum(v)

    t = timeit(lambda: float(conv_chain(xj)[1]))
    report("stencil lax.conv", t)

    # current pallas path through the framework
    import ramba_tpu as rt

    @rt.stencil
    def star2(a):
        return (0.25 * (a[0, 1] + a[0, -1] + a[1, 0] + a[-1, 0])
                + 0.125 * (a[0, 2] + a[0, -2] + a[2, 0] + a[-2, 0]))

    xr = rt.fromarray(x0)
    rt.sync()

    def pallas_chain():
        y = xr
        for _ in range(sk):
            y = rt.sstencil(star2, y)
        return float(rt.sum(y))

    t = timeit(pallas_chain)
    report("stencil pallas (framework)", t)


if __name__ == "__main__":
    main()
